"""Replication log: dirty-slot deltas coalesced into epoch-stamped frames.

The primary's engine marks every slot a dispatch touches into a journal —
off the decision path.  Two journal backends exist (engine/state.py):

- ``DeviceSlotJournal`` (preferred): the touched-slot bitmap lives on the
  device and is updated by a tiny async scatter over the dispatch's own
  uploaded lane arrays — the delta extraction rides the dispatch that
  already runs, and the decision path pays one attribute check plus one
  enqueue.  ``drain`` fetches the bitmap off the decision path.
- ``SlotJournal`` (fallback): the original host-side boolean scatter.

Which serves is a measured election (ops/pallas/election.py, path name
``device_journal``): both journals are timed marking a representative
batch, and the device pass serves only where it wins — a host where the
dispatch-call overhead exceeds the numpy scatter keeps the host journal.
``RATELIMITER_DEVICE_JOURNAL=on|off|auto`` overrides.

``ReplicationLog.cut()`` turns the journal's accumulated delta into wire
frames:

1. flush the micro-batcher (queued requests dispatch, marking their slots);
2. drain the journal (atomic swap — marks racing the drain land in the
   NEXT epoch, and a row read here that a concurrent dispatch then
   overwrites is simply re-shipped next cut: row writes are idempotent);
3. read the dirty rows from the device (one gather per algo);
4. dump the key->slot index journal + limiter table (the addressing a
   standby needs to serve the rows after promotion);
5. stamp everything with the next epoch and chunk to the wire budget
   (replication/wire.py).

Consistency model: a frame captures every mutation that completed before
its cut began; mutations concurrent with the cut land in this epoch, the
next, or both (both is harmless).  Slot REUSE concurrent with a cut (an
eviction remapping a slot between the row read and the index dump) can
pair a new key with its predecessor's row for one epoch — the next cut
repairs it, and keys whose last mutation precedes the cut are exact,
which is precisely the "at or before the replicated epoch" guarantee the
failover drill checks (storage/chaos.py).

The sharded engine is NOT served here: per-shard epochs and standby-mesh
streams live in replication/sharded.py (``ShardedReplicationLog``).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List

import numpy as np

from ratelimiter_tpu.engine.state import DeviceSlotJournal, SlotJournal
from ratelimiter_tpu.replication.wire import DEFAULT_FRAME_BUDGET, chunk_frames


def _wall_ms() -> int:
    return time.time_ns() // 1_000_000


# ---------------------------------------------------------------------------
# Journal election (device bitmap vs host scatter)
# ---------------------------------------------------------------------------

_JOURNAL_ENV = "RATELIMITER_DEVICE_JOURNAL"


def _measure_journal_ab(num_slots: int = 1 << 16, lanes: int = 1 << 15,
                        reps: int = 6) -> Dict:
    """Time both journals marking the same representative batch.

    The device side is timed through a full mark+sync cycle (reps marks,
    one drain-equivalent fetch) so its async dispatch can't hide compute
    the host would eventually pay; the host side is the plain numpy
    scatter.  Keys follow the election module's A/B naming: ``pallas_s``
    is the device journal, ``xla_s`` the host journal.
    """
    import jax.numpy as jnp

    slots = ((np.arange(lanes, dtype=np.int64) * 2654435761)
             % num_slots).astype(np.int32)
    dev_arr = jnp.asarray(slots)

    host = SlotJournal(num_slots)
    host.mark("tb", slots)  # warm
    t0 = time.perf_counter()
    for _ in range(reps):
        host.mark("tb", slots)
    host_s = (time.perf_counter() - t0) / reps

    dev = DeviceSlotJournal(num_slots)
    dev.mark("tb", dev_arr)  # warm (compiles the scatter)
    dev.drain()
    t0 = time.perf_counter()
    for _ in range(reps):
        dev.mark("tb", dev_arr)
    np.asarray(dev._bits["tb"])  # settle the async chain
    dev_s = (time.perf_counter() - t0) / reps

    return {"pallas_s": dev_s, "xla_s": host_s,
            "lanes": lanes, "num_slots": num_slots}


def device_journal_elected() -> bool:
    """Whether the device journal serves on this host/device pair.

    ``RATELIMITER_DEVICE_JOURNAL=on|off`` forces; ``auto`` (default)
    runs the shared measured election, cached per (platform, device
    kind) like every Pallas path."""
    policy = os.environ.get(_JOURNAL_ENV, "auto").lower()
    if policy in ("on", "always", "1"):
        return True
    if policy in ("off", "never", "0"):
        return False
    from ratelimiter_tpu.ops.pallas import election

    return election.measured_election("device_journal", _measure_journal_ab)


def make_journal(num_slots: int, kind: str = "auto"):
    """Build the journal a replication log attaches: ``device``,
    ``host``, or ``auto`` (elected)."""
    if kind == "device" or (kind == "auto" and device_journal_elected()):
        return DeviceSlotJournal(num_slots)
    return SlotJournal(num_slots)


def read_rows_padded(engine, algo: str, ids: np.ndarray) -> np.ndarray:
    """``engine.read_rows`` with the id lane padded to a power of two so
    cut-to-cut dirty-count jitter reuses a handful of gather shapes
    instead of compiling one per epoch."""
    n = len(ids)
    size = 1 << max(int(n - 1).bit_length(), 8) if n else 0
    if size <= n:
        return engine.read_rows(algo, ids)
    padded = np.concatenate(
        [ids, np.full(size - n, ids[0] if n else 0, dtype=np.int64)])
    return engine.read_rows(algo, padded)[:n]


# ---------------------------------------------------------------------------
# Flat (single-device) log
# ---------------------------------------------------------------------------


class ReplicationLog:
    """Owns the primary's journal and cuts epoch-stamped frame batches."""

    def __init__(self, storage, max_frame_bytes: int = DEFAULT_FRAME_BUDGET,
                 journal_kind: str = "auto"):
        engine = storage.engine
        if not getattr(engine, "supports_replication", False):
            raise ValueError(
                "replication requires a journaled engine "
                "(this backend has none)")
        if hasattr(engine, "n_shards"):
            raise ValueError(
                "the sharded engine replicates per shard — use "
                "replication.sharded.ShardedReplicationLog so one shard "
                "can be promoted without the world")
        self.storage = storage
        self.engine = engine
        self.max_frame_bytes = int(max_frame_bytes)
        self.journal = make_journal(engine.num_slots, journal_kind)
        self.journal_kind = ("device" if getattr(self.journal, "device",
                                                 False) else "host")
        engine.journal = self.journal
        self.epoch = 0
        self._full_pending = True  # first cut bootstraps the standby
        self._lock = threading.Lock()
        # Lag of the newest cut: age of the oldest mutation it shipped.
        self.last_cut_lag_ms = 0.0

    def request_full(self) -> None:
        """Make the next cut ship the complete state (standby bootstrap,
        or recovery after a ship failure left the stream gapped)."""
        with self._lock:
            self._full_pending = True
            self.journal.mark_all("sw")
            self.journal.mark_all("tb")

    def cut(self) -> List[Dict]:
        """Cut one epoch: returns the frame dicts to ship (empty when
        nothing changed since the last cut — the epoch is not consumed)."""
        with self._lock:
            self.storage.flush()
            if self._full_pending:
                self.journal.mark_all("sw")
                self.journal.mark_all("tb")
            deltas_ids, oldest_ns, was_all = self.journal.drain()
            full = self._full_pending or was_all
            if not deltas_ids and not full:
                self.last_cut_lag_ms = 0.0
                return []
            deltas = {}
            for algo, ids in deltas_ids.items():
                deltas[algo] = {
                    "slots": ids,
                    "rows": read_rows_padded(self.engine, algo, ids),
                }
            from ratelimiter_tpu.engine.checkpoint import (
                _limiter_table_dump,
                dump_slot_indexes,
            )

            index_dump = dump_slot_indexes(self.storage)
            limiters = _limiter_table_dump(self.storage)
            self.epoch += 1
            self._full_pending = False
            now = time.time_ns()
            self.last_cut_lag_ms = ((now - oldest_ns) / 1e6
                                    if oldest_ns is not None else 0.0)
            return chunk_frames(self.epoch, _wall_ms(),
                                self.engine.num_slots, deltas, index_dump,
                                limiters, full=full,
                                max_bytes=self.max_frame_bytes)

    def remark(self, frames: List[Dict]) -> None:
        """Put a failed ship's slots back in the journal so the delta is
        re-sent (the replicator also requests a full frame, since the
        standby's epoch stream now has a gap)."""
        for frame in frames:
            for algo, payload in frame.get("algos", {}).items():
                self.journal.mark(algo, payload["slots"])

    def pending(self) -> int:
        return self.journal.pending()

    def detach(self) -> None:
        """Stop journaling (the engine reverts to zero-overhead marks)."""
        self.engine.journal = None


def engine_state_fingerprint(engine) -> Dict[str, np.ndarray]:
    """Host copies of both packed state arrays (test/drill equality
    checks between a primary and a caught-up standby)."""
    engine.block_until_ready()
    return {"sw": np.asarray(engine.sw_packed).copy(),
            "tb": np.asarray(engine.tb_packed).copy()}
