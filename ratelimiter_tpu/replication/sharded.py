"""Shard-aware replication: per-shard epochs, standby mesh, single-shard
failover.

The flat pipeline (log.py / replicator.py / standby.py) replicates a
single-device engine as one stream; a sharded deployment must not — a
whole-world standby forces whole-world promotion, exactly the "when two
is worse than one" failure mode at datacenter scale.  Here each shard of
a ``ShardedDeviceEngine`` ships its OWN delta stream:

- ``ShardedReplicationLog`` owns one journal over the global slot space
  (device bitmap preferred, like the flat log) and cuts per-shard
  epochs: the drained dirty set is bucketed by ``slot //
  slots_per_shard``, and shard q's frames carry LOCAL slot ids, shard
  q's key->slot sub-index journal, and ``num_slots = slots_per_shard``
  — so a per-shard standby is an ORDINARY flat standby of
  ``slots_per_shard`` geometry running the ordinary
  ``StandbyReceiver``.  Nothing standby-side is shard-special, which is
  what keeps promotion the already-proven flat path.
- ``ShardedReplicator`` ships every shard's stream on one cadence with
  per-shard failure isolation: a dead link to standby q re-marks only
  q's delta and full-requests only q — the other shards' streams never
  stall.
- ``ShardStandbySet`` is the standby mesh: N flat storages + receivers,
  one per shard.
- ``ShardFailoverRouter`` is the serving façade after a shard failure:
  requests route by the SAME key->shard hash the engine uses; a failed
  shard's keys are denied (bounded under-admission, counted) until its
  standby is promoted, then served by the promoted flat storage while
  the surviving shards keep serving from the primary — the
  DEGRADED-shard state the health machinery reports instead of DOWN.

``storage/chaos.py:shard_failover_drill`` proves the contract: kill one
shard of N mid-Zipf-stream, promote only it, decisions bit-identical to
``semantics/oracle.py`` after promotion while survivors never stop.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from ratelimiter_tpu.replication.log import make_journal, read_rows_padded
from ratelimiter_tpu.replication.wire import (
    DEFAULT_FRAME_BUDGET,
    chunk_frames,
    encode_frame,
)
from ratelimiter_tpu.utils.logging import get_logger

_log = get_logger("replication.sharded")


def _wall_ms() -> int:
    return time.time_ns() // 1_000_000


class ShardedReplicationLog:
    """Per-shard epoch cuts over one global dirty-slot journal."""

    def __init__(self, storage, max_frame_bytes: int = DEFAULT_FRAME_BUDGET,
                 journal_kind: str = "auto"):
        engine = storage.engine
        if not hasattr(engine, "n_shards"):
            raise ValueError(
                "ShardedReplicationLog requires the sharded engine; use "
                "ReplicationLog for a single-device one")
        self.storage = storage
        self.engine = engine
        self.n_shards = int(engine.n_shards)
        self.slots_per_shard = int(engine.slots_per_shard)
        self.max_frame_bytes = int(max_frame_bytes)
        self.journal = make_journal(engine.num_slots, journal_kind)
        self.journal_kind = ("device" if getattr(self.journal, "device",
                                                 False) else "host")
        engine.journal = self.journal
        self.epochs = [0] * self.n_shards
        self._full_pending = [True] * self.n_shards  # bootstrap each shard
        # Drained-but-not-yet-cut dirty ids per shard per algo (global).
        self._pending: List[Dict[str, List[np.ndarray]]] = [
            {"sw": [], "tb": []} for _ in range(self.n_shards)]
        self._lock = threading.Lock()
        self.last_cut_lag_ms = 0.0

    # -- journal plumbing ------------------------------------------------------
    def _drain_into_pending(self) -> None:
        """Drain the global journal and bucket the dirty ids by shard
        (caller holds the lock)."""
        deltas, oldest_ns, was_all = self.journal.drain()
        if was_all:
            # A whole-state mark (bulk restore/import) dirties every
            # shard completely: their next cuts must ship as FULL frames
            # so the receivers re-baseline instead of seeing a partial
            # overlay.
            for q in range(self.n_shards):
                self._full_pending[q] = True
        for algo, ids in deltas.items():
            shard = ids // self.slots_per_shard
            for q in np.unique(shard):
                self._pending[int(q)][algo].append(ids[shard == q])
        if oldest_ns is not None:
            self.last_cut_lag_ms = (time.time_ns() - oldest_ns) / 1e6
        else:
            self.last_cut_lag_ms = 0.0

    def request_full(self, shard: Optional[int] = None) -> None:
        """Re-baseline one shard's stream (or all of them)."""
        with self._lock:
            shards = range(self.n_shards) if shard is None else [int(shard)]
            for q in shards:
                self._full_pending[q] = True

    def cut_shard(self, shard: int) -> List[Dict]:
        """Cut one epoch for one shard; frames carry LOCAL slot ids and
        the shard's sub-index journal (empty when nothing changed)."""
        q = int(shard)
        sps = self.slots_per_shard
        with self._lock:
            self.storage.flush()
            self._drain_into_pending()
            full = self._full_pending[q]
            if full:
                # A full frame must carry the complete shard state.
                base = np.arange(q * sps, (q + 1) * sps, dtype=np.int64)
                for algo in ("sw", "tb"):
                    self._pending[q][algo] = [base]
            deltas = {}
            for algo in ("sw", "tb"):
                chunks = self._pending[q][algo]
                if not chunks:
                    continue
                self._pending[q][algo] = []
                ids = (chunks[0] if len(chunks) == 1
                       else np.unique(np.concatenate(chunks)))
                deltas[algo] = {
                    "slots": ids - q * sps,  # LOCAL: standby geometry
                    "rows": read_rows_padded(self.engine, algo, ids),
                }
            if not deltas and not full:
                return []
            from ratelimiter_tpu.engine.checkpoint import (
                _limiter_table_dump,
                dump_shard_slot_indexes,
            )

            index_dump = dump_shard_slot_indexes(self.storage, q)
            limiters = _limiter_table_dump(self.storage)
            self.epochs[q] += 1
            self._full_pending[q] = False
            frames = chunk_frames(self.epochs[q], _wall_ms(), sps, deltas,
                                  index_dump, limiters, full=full,
                                  max_bytes=self.max_frame_bytes)
            for f in frames:
                f["shard"] = q
                f["n_shards"] = self.n_shards
            return frames

    def cut_all(self) -> Dict[int, List[Dict]]:
        return {q: self.cut_shard(q) for q in range(self.n_shards)}

    def remark(self, shard: int, frames: List[Dict]) -> None:
        """Re-journal a failed ship's slots (frames carry LOCAL ids)."""
        base = int(shard) * self.slots_per_shard
        for frame in frames:
            for algo, payload in frame.get("algos", {}).items():
                self.journal.mark(algo, np.asarray(payload["slots"],
                                                   dtype=np.int64) + base)

    def pending(self) -> int:
        with self._lock:
            queued = sum(len(a) for p in self._pending
                         for algo_chunks in p.values()
                         for a in algo_chunks)
            return queued + self.journal.pending()

    def detach(self) -> None:
        self.engine.journal = None


class ShardedReplicator:
    """Ships every shard's epoch stream; failures isolate per shard.

    ``sinks`` maps shard -> sink (one standby link per shard — the
    standby mesh).  One cadence thread cuts and ships all shards; a
    shard whose sink fails gets its delta re-marked and its next frame
    full, while the other shards' streams continue unharmed this cycle.
    """

    def __init__(self, log: ShardedReplicationLog, sinks: Dict[int, object],
                 interval_ms: float = 200.0, registry=None):
        self.log = log
        self.sinks = dict(sinks)
        missing = set(range(log.n_shards)) - set(self.sinks)
        if missing:
            raise ValueError(f"no sink for shard(s) {sorted(missing)}")
        self.interval_ms = float(interval_ms)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._ship_lock = threading.Lock()
        self.frames_shipped = 0
        self.bytes_shipped = 0
        self.errors = 0
        self.shard_errors = [0] * log.n_shards
        self._shard_last_error: List[Optional[str]] = [None] * log.n_shards
        # Shards handed off to a promoted replacement: their standby is
        # now SERVING — shipping more frames into it would corrupt it,
        # so the orchestrator drops the shard from the stream.
        self._dropped: set = set()
        self._shard_link_last: List[Optional[str]] = [None] * log.n_shards
        if registry is not None:
            self._m_lag = registry.gauge(
                "ratelimiter.replication.lag_ms",
                "Age (ms) of the oldest unreplicated mutation at the "
                "last epoch cut")
            self._m_frames = registry.counter(
                "ratelimiter.replication.frames",
                "Replication frames shipped to the standby")
            self._m_bytes = registry.counter(
                "ratelimiter.replication.bytes",
                "Encoded replication bytes shipped")
            self._m_errors = registry.counter(
                "ratelimiter.replication.errors",
                "Replication ship failures (frames re-marked, next "
                "frame full)")
            self._m_links_dead = registry.gauge(
                "ratelimiter.replication.links_dead",
                "Standby-mesh links currently marked DEAD (standby "
                "gone, its replica going stale)")
        else:
            self._m_lag = self._m_frames = None
            self._m_bytes = self._m_errors = None
            self._m_links_dead = None

    def ship_now(self) -> int:
        """One synchronous cycle over every shard; returns frames
        shipped.  Per-shard failures are isolated (counted, re-marked,
        full-requested) — the cycle always completes."""
        shipped = 0
        with self._ship_lock:
            for q in range(self.log.n_shards):
                if q in self._dropped:
                    continue
                shipped += self._ship_shard(q)
                self._observe_link(q)
            if self._m_lag is not None:
                self._m_lag.set(self.log.last_cut_lag_ms)
            if self._m_links_dead is not None:
                self._m_links_dead.set(float(sum(
                    1 for s in self._shard_link_last if s == "dead")))
        return shipped

    def drop_shard(self, q: int) -> None:
        """Stop shipping one shard's stream (its standby was promoted
        and is now SERVING — more frames would corrupt it).  The shard's
        pending delta stays in the journal; it is simply never cut."""
        with self._ship_lock:
            self._dropped.add(int(q))

    def restore_shard(self, q: int, sink=None) -> None:
        """Resume a dropped shard's stream (the operator unfence path):
        optionally swap in a fresh sink (a replaced standby's receiver)
        and re-baseline with a FULL frame on the next cut."""
        with self._ship_lock:
            self._dropped.discard(int(q))
            if sink is not None:
                self.sinks[int(q)] = sink
        self.log.request_full(int(q))

    def dropped_shards(self) -> set:
        with self._ship_lock:
            return set(self._dropped)

    def shard_link_state(self, q: int) -> str:
        fn = getattr(self.sinks[int(q)], "link_state", None)
        return fn() if fn is not None else "unknown"

    def _observe_link(self, q: int) -> None:
        state = self.shard_link_state(q)
        if state == self._shard_link_last[q] or state == "unknown":
            return
        from ratelimiter_tpu.observability import flight_recorder

        if state == "dead":
            flight_recorder().record("replication.link_dead", shard=q)
            _log.warning("shard %d standby link marked DEAD (standby "
                         "gone, not merely slow); its replica is going "
                         "stale", q)
        elif state == "up" and self._shard_link_last[q] == "dead":
            flight_recorder().record("replication.link_restored", shard=q)
        self._shard_link_last[q] = state

    def _ship_shard(self, q: int) -> int:
        sink = self.sinks[q]
        consume = getattr(sink, "consume_reconnected", None)
        if consume is not None and consume():
            _log.warning("shard %d replication link reconnected; "
                         "re-baselining with a full frame", q)
            self.log.request_full(q)
        frames = self.log.cut_shard(q)
        if not frames:
            # Idle cycle for this shard: heartbeat so a silently-dead
            # standby is detected with no deltas flowing.
            hb = getattr(sink, "heartbeat", None)
            if hb is not None:
                hb()
            return 0
        shipped = 0
        try:
            for frame in frames:
                data = encode_frame(frame)
                sink.send(data)
                shipped += 1
                self.frames_shipped += 1
                self.bytes_shipped += len(data)
                if self._m_frames is not None:
                    self._m_frames.increment()
                    self._m_bytes.add(len(data))
            self._shard_last_error[q] = None
        except Exception as exc:  # noqa: BLE001 — isolate to this shard
            self.errors += 1
            self.shard_errors[q] += 1
            self._shard_last_error[q] = str(exc)[:200]
            if self._m_errors is not None:
                self._m_errors.increment()
            self.log.remark(q, frames[shipped:])
            self.log.request_full(q)
            _log.warning("shard %d replication ship failed: %s (delta "
                         "re-marked; next frame full)", q, exc)
        return shipped

    def start(self) -> "ShardedReplicator":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="sharded-replicator", daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_ms / 1000.0):
            try:
                self.ship_now()
            except Exception as exc:  # noqa: BLE001 — loop survives
                _log.warning("sharded replication cycle failed: %s", exc)

    def stop(self, final_ship: bool = False) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if final_ship:
            try:
                self.ship_now()
            except Exception as exc:  # noqa: BLE001 — best effort
                _log.warning("final sharded ship failed: %s", exc)
        self._stop.clear()

    def close(self) -> None:
        self.stop()
        self.log.detach()
        for sink in self.sinks.values():
            if hasattr(sink, "close"):
                sink.close()

    def lag_ms(self) -> float:
        return self.log.last_cut_lag_ms

    def shard_status(self) -> Dict[int, Dict]:
        return {q: {"epoch": self.log.epochs[q],
                    "errors": self.shard_errors[q],
                    "last_error": self._shard_last_error[q],
                    "link": self.shard_link_state(q),
                    "dropped": q in self._dropped}
                for q in range(self.log.n_shards)}


class ShardStandbySet:
    """The standby mesh: one flat same-geometry storage + receiver per
    shard.  ``storage_factory()`` builds one ``slots_per_shard`` flat
    storage (the caller owns clocks/config)."""

    def __init__(self, n_shards: int, storage_factory: Callable[[], object],
                 registry=None):
        self.n_shards = int(n_shards)
        from ratelimiter_tpu.replication.standby import StandbyReceiver

        self.storages = [storage_factory() for _ in range(self.n_shards)]
        self.receivers = [StandbyReceiver(s, registry=registry)
                          for s in self.storages]

    def in_process_sinks(self) -> Dict[int, object]:
        from ratelimiter_tpu.replication.transport import InProcessSink

        return {q: InProcessSink(rx) for q, rx in enumerate(self.receivers)}

    def promote(self, shard: int, force: bool = False):
        """Promote ONE shard's standby; returns its (flat) storage."""
        return self.receivers[int(shard)].promote(force=force)

    def replace(self, shard: int, storage, receiver) -> None:
        """Swap in a freshly re-seeded standby for one shard (the
        orchestrator's RESTORED step: the old standby was promoted to
        serving, this one returns the system to N+1)."""
        q = int(shard)
        self.storages[q] = storage
        self.receivers[q] = receiver

    def close(self, except_shards: tuple = ()) -> None:
        for q, storage in enumerate(self.storages):
            if q not in except_shards:
                storage.close()


class ShardFailoverRouter:
    """Serving façade over a sharded primary plus promoted replacements.

    Routes by the engine's own key->shard hash.  A shard marked failed
    is DENIED (fail-closed, counted — bounded under-admission during the
    promotion window) until ``install_replacement`` hands its keys to a
    promoted flat storage; every other shard keeps serving from the
    primary throughout.  ``shard_health()`` feeds the health state
    machine's DEGRADED-shard reporting (service/app.py)."""

    def __init__(self, primary):
        engine = primary.engine
        if not hasattr(engine, "n_shards"):
            raise ValueError("ShardFailoverRouter wraps a sharded storage")
        self.primary = primary
        self.n_shards = int(engine.n_shards)
        self.replacements: Dict[int, object] = {}
        self.failed: set = set()
        self.unavailable_denies = 0
        self._lock = threading.Lock()
        # Per-shard state bookkeeping for the health surface: when the
        # current state was entered (wall ms for operators, monotonic
        # for durations) — the DEGRADED-shard payload reports both.
        now_w, now_m = _wall_ms(), time.monotonic()
        self._state_since_wall = [now_w] * self.n_shards
        self._state_since_mono = [now_m] * self.n_shards

    def _mark_transition(self, shard: int) -> None:
        """Caller holds the lock."""
        self._state_since_wall[shard] = _wall_ms()
        self._state_since_mono[shard] = time.monotonic()

    # -- failover control ------------------------------------------------------
    def fail_shard(self, shard: int) -> None:
        with self._lock:
            self.failed.add(int(shard))
            self._mark_transition(int(shard))
        from ratelimiter_tpu.observability import flight_recorder

        flight_recorder().record("shard.failed", shard=int(shard))

    def install_replacement(self, shard: int, storage) -> None:
        """Hand a failed shard's keyspace to a promoted flat storage."""
        with self._lock:
            self.replacements[int(shard)] = storage
            self.failed.discard(int(shard))
            self._mark_transition(int(shard))
        from ratelimiter_tpu.observability import flight_recorder

        flight_recorder().record("shard.promoted", shard=int(shard))

    def repair_shard(self, shard: int) -> None:
        """Operator repair: route ``shard``'s keys back to the PRIMARY.

        The exit from a terminal FAILED shard (orchestrator.unfence):
        the operator has verified the primary's shard is actually
        healthy (false-dead) and its fence lifted — clear both the
        failed mark and any installed replacement so routing falls
        through to the primary again."""
        with self._lock:
            self.failed.discard(int(shard))
            self.replacements.pop(int(shard), None)
            self._mark_transition(int(shard))
        from ratelimiter_tpu.observability import flight_recorder

        flight_recorder().record("shard.repaired", shard=int(shard))

    def shard_health(self) -> Dict[int, str]:
        with self._lock:
            return {q: ("failed" if q in self.failed
                        else "promoted" if q in self.replacements
                        else "active")
                    for q in range(self.n_shards)}

    def shard_status(self) -> Dict[int, Dict]:
        """Per-shard state WITH transition timestamps: the health
        payload's DEGRADED-shard detail (operators and the orchestrator
        drill assert promotion-window bounds from ``in_state_ms``)."""
        now = time.monotonic()
        with self._lock:
            out = {}
            for q in range(self.n_shards):
                state = ("failed" if q in self.failed
                         else "promoted" if q in self.replacements
                         else "active")
                out[q] = {
                    "state": state,
                    "since_ms": self._state_since_wall[q],
                    "in_state_ms": round(
                        (now - self._state_since_mono[q]) * 1000.0, 3),
                }
            return out

    def degraded_shards(self) -> List[int]:
        with self._lock:
            return sorted(self.failed | set(self.replacements))

    # -- routed decision surface ----------------------------------------------
    def _shard_of_keys(self, lids, keys) -> np.ndarray:
        from ratelimiter_tpu.parallel.sharded import shard_of_key

        return np.asarray([shard_of_key((int(l), k), self.n_shards)
                           for l, k in zip(lids, keys)], dtype=np.int64)

    def __getattr__(self, name):
        # Everything that is not a per-key decision surface (limiter
        # registration, flush plumbing, the legacy host-side contract,
        # engine/batcher attributes the health payload reads) passes
        # through to the sharded primary.  Decision surfaces are routed
        # explicitly below so a failed shard fails CLOSED.
        if name.startswith("__"):
            raise AttributeError(name)
        return getattr(self.__dict__["primary"], name)

    def acquire(self, algo, lid, key, permits, **kw):
        from ratelimiter_tpu.parallel.sharded import shard_of_key

        q = int(shard_of_key((int(lid), key), self.n_shards))
        backend = self._backend(q)
        if backend is None:
            with self._lock:
                self.unavailable_denies += 1
            # Fail-closed deny; cache_value is pinned at the ceiling so
            # a local TTL cache can never convert this deny into allows.
            return {"allowed": False, "observed": np.iinfo(np.int64).max,
                    "remaining": 0, "cache_value": np.iinfo(np.int32).max}
        return backend.acquire(algo, lid, key, permits, **kw)

    def acquire_many_ids(self, algo, lid, key_ids, permits):
        from ratelimiter_tpu.parallel.sharded import shard_of_int_keys

        key_ids = np.ascontiguousarray(key_ids, dtype=np.int64)
        permits = np.asarray(permits)
        shard = shard_of_int_keys(key_ids, self.n_shards)
        with self._lock:
            routed = bool(self.failed or self.replacements)
        if not routed:
            return self.primary.acquire_many_ids(algo, lid, key_ids,
                                                 permits)
        out: Dict[str, np.ndarray] = {}
        n = len(key_ids)
        for q in np.unique(shard):
            idx = np.nonzero(shard == q)[0]
            backend = self._backend(int(q))
            if backend is None:
                with self._lock:
                    self.unavailable_denies += len(idx)
                res = {"allowed": np.zeros(len(idx), dtype=bool)}
            else:
                res = backend.acquire_many_ids(algo, lid, key_ids[idx],
                                               permits[idx])
            for name, vals in res.items():
                if name not in out:
                    out[name] = np.zeros(n, dtype=np.asarray(vals).dtype)
                out[name][idx] = vals
        return out

    def acquire_stream_strs(self, algo, lid, keys, permits=None, **kw):
        from ratelimiter_tpu.parallel.sharded import shard_of_key

        with self._lock:
            routed = bool(self.failed or self.replacements)
        if not routed:
            return self.primary.acquire_stream_strs(algo, lid, keys,
                                                    permits=permits, **kw)
        keys = list(keys)
        shard = np.asarray([shard_of_key((int(lid), k), self.n_shards)
                            for k in keys], dtype=np.int64)
        out = np.zeros(len(keys), dtype=bool)
        for q in np.unique(shard):
            idx = np.nonzero(shard == q)[0]
            backend = self._backend(int(q))
            if backend is None:
                with self._lock:
                    self.unavailable_denies += len(idx)
                continue  # denied: out already False
            out[idx] = backend.acquire_stream_strs(
                algo, lid, [keys[i] for i in idx],
                permits=None if permits is None else permits[idx], **kw)
        return out

    def available_many(self, algo, lid, keys):
        from ratelimiter_tpu.parallel.sharded import shard_of_key

        keys = list(keys)
        out = np.zeros(len(keys), dtype=np.int64)
        shard = np.asarray([shard_of_key((int(lid), k), self.n_shards)
                            for k in keys], dtype=np.int64)
        for q in np.unique(shard):
            idx = np.nonzero(shard == q)[0]
            backend = self._backend(int(q))
            if backend is None:
                out[idx] = 0  # failed shard: report no availability
                continue
            out[idx] = backend.available_many(algo, lid,
                                              [keys[i] for i in idx])
        return out

    def reset_key(self, algo, lid, key) -> None:
        from ratelimiter_tpu.parallel.sharded import shard_of_key

        q = int(shard_of_key((int(lid), key), self.n_shards))
        backend = self._backend(q)
        if backend is not None:
            backend.reset_key(algo, lid, key)

    # -- lease routing (leases/manager.py) -------------------------------------
    # Lease reserve/credit must route per key like every other decision
    # surface — the __getattr__ passthrough would silently hand them to
    # the primary, bypassing a promoted replacement, and a failed shard
    # must refuse grants (fail-closed: no budget, no local admission).

    def lease_reserve(self, algo, lid, key, requested):
        from ratelimiter_tpu.parallel.sharded import shard_of_key

        q = int(shard_of_key((int(lid), key), self.n_shards))
        backend = self._backend(q)
        if backend is None:
            with self._lock:
                self.unavailable_denies += 1
            return {"granted": 0, "ws": 0, "stamp": 0}
        return backend.lease_reserve(algo, lid, key, requested)

    def lease_credit(self, algo, lid, key, credit, grant_ws):
        from ratelimiter_tpu.parallel.sharded import shard_of_key

        q = int(shard_of_key((int(lid), key), self.n_shards))
        backend = self._backend(q)
        if backend is None:
            return {"credited": 0, "stamp": 0}
        return backend.lease_credit(algo, lid, key, credit, grant_ws)

    def _backend(self, q: int):
        with self._lock:
            if q in self.failed:
                return None
            return self.replacements.get(q, self.primary)

    # -- policy actuation (control/, ARCHITECTURE §15) -------------------------
    def set_policy(self, lid, config, generation=None):
        """Broadcast a live policy update to EVERY serving backend: the
        primary assigns the generation, promoted replacements install
        the SAME stamp — so decisions keep one generation order across
        a failover boundary (the replication stream already carries
        updates that happened BEFORE a promotion; this covers the ones
        that happen after)."""
        gen = self.primary.set_policy(lid, config, generation=generation)
        with self._lock:
            replacements = list(self.replacements.values())
        for backend in replacements:
            if backend is self.primary:
                continue
            try:
                backend.set_policy(lid, config, generation=gen)
            except KeyError:
                # A replacement that never saw the lid registered cannot
                # serve it either (registration replicates first) — skip.
                pass
        return gen

    def acquire_many(self, algo, lid_per_req, keys, permits):
        shard = self._shard_of_keys(lid_per_req, keys)
        lids = np.asarray(lid_per_req)
        perms = np.asarray(permits)
        keys = list(keys)
        out: Dict[str, np.ndarray] = {}
        for q in np.unique(shard):
            idx = np.nonzero(shard == q)[0]
            backend = self._backend(int(q))
            if backend is None:
                # Promotion window: fail closed (deny) — bounded
                # under-admission, never unbounded over-admission.
                with self._lock:
                    self.unavailable_denies += len(idx)
                res = {"allowed": np.zeros(len(idx), dtype=bool)}
            else:
                res = backend.acquire_many(
                    algo, [int(lids[i]) for i in idx],
                    [keys[i] for i in idx], [int(perms[i]) for i in idx])
            for name, vals in res.items():
                if name not in out:
                    out[name] = np.zeros(len(keys),
                                         dtype=np.asarray(vals).dtype)
                out[name][idx] = vals
        return out

    def acquire_stream_ids(self, algo, lid, key_ids, permits=None, **kw):
        from ratelimiter_tpu.parallel.sharded import shard_of_int_keys

        key_ids = np.ascontiguousarray(key_ids, dtype=np.int64)
        shard = shard_of_int_keys(key_ids, self.n_shards)
        out = np.zeros(len(key_ids), dtype=bool)
        with self._lock:
            routed = bool(self.failed or self.replacements)
        if not routed:
            return self.primary.acquire_stream_ids(algo, lid, key_ids,
                                                   permits=permits, **kw)
        special = sorted(self.failed | set(self.replacements))
        mask_special = np.isin(shard, special)
        live_idx = np.nonzero(~mask_special)[0]
        if len(live_idx):
            out[live_idx] = self.primary.acquire_stream_ids(
                algo, lid, key_ids[live_idx],
                permits=None if permits is None else permits[live_idx],
                **kw)
        for q in special:
            idx = np.nonzero(shard == q)[0]
            if not len(idx):
                continue
            backend = self._backend(q)
            if backend is None:
                with self._lock:
                    self.unavailable_denies += len(idx)
                continue  # denied: out already False
            out[idx] = backend.acquire_stream_ids(
                algo, lid, key_ids[idx],
                permits=None if permits is None else permits[idx], **kw)
        return out

    # -- passthrough plumbing --------------------------------------------------
    def is_available(self) -> bool:
        """Health probe: the primary must answer (a single failed shard
        is DEGRADED via :meth:`shard_health`, not unavailable)."""
        try:
            return bool(self.primary.is_available())
        except Exception:  # noqa: BLE001 — erroring probe = unavailable
            return False

    def flush(self) -> None:
        self.primary.flush()
        with self._lock:
            reps = list(self.replacements.values())
        for r in reps:
            r.flush()

    def close(self) -> None:
        self.primary.close()
        with self._lock:
            reps = list(self.replacements.values())
        for r in reps:
            r.close()
