"""Sequential reference semantics ("the oracle").

This module is the *specification* of what every execution backend in the
framework — the in-memory storage path, the single-device JAX engine, the
sharded multi-chip engine, and the Pallas kernels — must decide, bit for bit.
It is a direct, pure-Python, integer-arithmetic restatement of the reference
implementation's behavior:

- Sliding-window counter: ``algorithms/SlidingWindowRateLimiter.java:86-188``
  including its two documented quirks (SURVEY.md §7):
  Q1 — ``tryAcquire(key, permits)`` checks ``count + permits > max`` but
  increments by **1**, not ``permits`` (lines 104-116);
  Q2 — a request can be counted-then-rejected by the post-increment check
  ``newCount <= maxPermits`` (lines 114-123), inflating the window.
  Window-bucket expiry follows Redis PEXPIRE semantics: each increment sets
  the bucket's TTL to exactly ``window`` (RedisRateLimitStorage.java:38-49),
  so the *previous* bucket disappears ``window`` ms after its last increment,
  not at the 2x-window boundary.

- Token bucket: the Redis Lua script ``TokenBucketRateLimiter.java:38-68``:
  lazy init to full capacity, refill ``min(cap, tokens + elapsed*rate)``,
  consume-if-enough, write-back (with TTL = 2x window,
  TokenBucketRateLimiter.java:121-128) **only on allow** — a denied request
  leaves the stored state untouched, which is observationally equivalent for
  tokens (refill is idempotent) but does *not* refresh the TTL.

Arithmetic model
----------------
The reference mixes Java doubles (the sliding-window weight,
SlidingWindowRateLimiter.java:170-174) and Lua floats (token refill).  This
framework instead defines **exact integer semantics**:

- Sliding window estimate: ``curr + (prev * (window - now % window)) // window``
  — the exact rational floor.  The Java double expression
  ``(long)(prev * (1 - (now % win)/win) + curr)`` equals this except when the
  exact weighted product ``prev*(window-rem)/window`` is an integer and double
  rounding falls below it; since the rational has denominator ``window``
  (<= 3.6e6), any non-integer value is at least ``1/window`` (~2.8e-7) from an
  integer while double error is a few ulps (~1e-12 at realistic counts), so the
  two agree everywhere except that measure-zero boundary.  Property tests in
  ``tests/test_oracle.py`` compare against a float emulation.

- Token bucket: integer fixed point, 1 token == 2**20 fp units
  (``core/config.py:TOKEN_FP_SHIFT``); the refill rate is rounded once at
  config time (relative error <= 0.5/rate_fp, i.e. ~5e-5 for 10 tokens/sec).

Both choices make decisions deterministic and device-friendly (pure int64
ops, no data-dependent float rounding), at the cost of a documented,
quantified deviation on exact ties.

``getAvailablePermits`` for the token bucket is implemented *correctly*
(refill-then-floor) rather than reproducing the reference's WRONGTYPE crash
(quirk Q3: TokenBucketRateLimiter.java:146-151 string-GETs a Redis hash).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

from ratelimiter_tpu.core.config import RateLimitConfig, TOKEN_FP_ONE


@dataclasses.dataclass(frozen=True)
class Decision:
    """Outcome of one try_acquire."""

    allowed: bool
    # Whether the current-window counter was incremented (sliding window) or
    # the bucket was written (token bucket). Due to quirk Q2 a sliding-window
    # request can increment yet be denied.
    mutated: bool
    # Sliding window: the weighted estimate read before the increment check.
    # Token bucket: whole tokens available after refill (pre-consume), floored.
    observed: int
    # Sliding window: raw current-bucket counter after the operation.
    # Token bucket: whole tokens remaining after the operation, floored.
    remaining_hint: int


class SlidingWindowOracle:
    """Exact sequential semantics of the sliding-window-counter limiter.

    Storage model: dict (key, window_start) -> (count, expiry_deadline_ms),
    mirroring one Redis string counter per window bucket with PEXPIRE.
    """

    def __init__(self, config: RateLimitConfig):
        config.validate()
        self.config = config
        self._buckets: Dict[Tuple[str, int], Tuple[int, int]] = {}

    def reconfigure(self, config: RateLimitConfig) -> None:
        """Adopt a live policy update (control/, ARCHITECTURE §15): only
        the rates move — the window is part of the state shape (bucket
        keys, PEXPIRE deadlines) and is immutable, exactly the
        ``LimiterTable.set_policy`` contract.  Stored bucket state is
        untouched: the device keeps every counter across a policy
        update, so the oracle must too — a generation-schedule replay
        feeds the same updates at the same boundaries and stays
        bit-identical."""
        config.validate()
        if config.window_ms != self.config.window_ms:
            raise ValueError("reconfigure cannot change the window")
        self.config = config

    # -- storage model --------------------------------------------------------
    def _get_bucket(self, key: str, window_start: int, now_ms: int) -> int:
        entry = self._buckets.get((key, window_start))
        if entry is None:
            return 0
        count, deadline = entry
        if now_ms >= deadline:  # Redis PEXPIRE: gone at/after the deadline
            del self._buckets[(key, window_start)]
            return 0
        return count

    def _increment_bucket(self, key: str, window_start: int, now_ms: int) -> int:
        """INCR + PEXPIRE(window) pipelined (RedisRateLimitStorage.java:38-49)."""
        count = self._get_bucket(key, window_start, now_ms)
        count += 1
        self._buckets[(key, window_start)] = (count, now_ms + self.config.window_ms)
        return count

    # -- estimate (SlidingWindowRateLimiter.java:158-180) ---------------------
    def current_count(self, key: str, now_ms: int) -> int:
        win = self.config.window_ms
        curr_ws = (now_ms // win) * win
        prev_ws = curr_ws - win
        curr = self._get_bucket(key, curr_ws, now_ms)
        prev = self._get_bucket(key, prev_ws, now_ms)
        rem = now_ms % win
        # Exact-integer form of: (long)(prev * (1 - rem/win) + curr)
        return curr + (prev * (win - rem)) // win

    # -- RateLimiter surface --------------------------------------------------
    def try_acquire(self, key: str, permits: int, now_ms: int) -> Decision:
        if permits <= 0:
            raise ValueError("permits must be positive")
        cfg = self.config
        win = cfg.window_ms
        estimated = self.current_count(key, now_ms)

        if estimated + permits > cfg.max_permits:
            # Rejected pre-increment (SlidingWindowRateLimiter.java:104-111).
            return Decision(allowed=False, mutated=False, observed=estimated,
                            remaining_hint=self._get_bucket(key, (now_ms // win) * win, now_ms))

        curr_ws = (now_ms // win) * win
        new_count = self._increment_bucket(key, curr_ws, now_ms)
        # Post-increment check on the RAW bucket counter, not the weighted
        # estimate (SlidingWindowRateLimiter.java:114-123) — quirks Q1/Q2.
        allowed = new_count <= cfg.max_permits
        return Decision(allowed=allowed, mutated=True, observed=estimated,
                        remaining_hint=new_count)

    def get_available_permits(self, key: str, now_ms: int) -> int:
        return max(0, self.config.max_permits - self.current_count(key, now_ms))

    # -- lease reserve/credit (spec for ops/lease.py) -------------------------
    def reserve(self, key: str, requested: int, now_ms: int) -> Tuple[int, int]:
        """Bulk-reserve up to ``requested`` permits in one atomic step:
        grant ``min(requested, max_permits - estimate)`` (clamped >= 0) and
        charge the current-window bucket by the granted count, with the
        same PEXPIRE refresh an increment applies.  This is the host
        specification of the device RESERVE kernel (ops/lease.py) that
        backs token leases (leases/): the grant is bounded by the
        remaining-window budget, which is what bounds lease
        over-admission by construction.  Returns ``(granted,
        window_start)`` — the window the charge landed in, which a later
        :meth:`credit` must present."""
        if requested <= 0:
            return 0, (now_ms // self.config.window_ms) * self.config.window_ms
        win = self.config.window_ms
        estimated = self.current_count(key, now_ms)
        granted = max(0, min(int(requested),
                             self.config.max_permits - estimated))
        curr_ws = (now_ms // win) * win
        if granted > 0:
            count = self._get_bucket(key, curr_ws, now_ms) + granted
            self._buckets[(key, curr_ws)] = (count, now_ms + win)
        return granted, curr_ws

    def credit(self, key: str, unused: int, grant_ws: int,
               now_ms: int) -> int:
        """Return ``unused`` reserved permits (lease release/renewal).
        Credits apply only while the window the charge landed in is still
        the CURRENT window (``grant_ws``): once the window rolled, the
        charge already ages out as previous-window weight, and crediting
        a later window would under-count live traffic.  The decrement
        never refreshes the bucket TTL (a credit is not an increment).
        Returns the permits actually credited."""
        if unused <= 0:
            return 0
        win = self.config.window_ms
        curr_ws = (now_ms // win) * win
        if curr_ws != int(grant_ws):
            return 0
        count = self._get_bucket(key, curr_ws, now_ms)
        if count <= 0:
            return 0
        credited = min(int(unused), count)
        _, deadline = self._buckets[(key, curr_ws)]
        self._buckets[(key, curr_ws)] = (count - credited, deadline)
        return credited

    def seed_count(self, key: str, count: int, now_ms: int) -> None:
        """Install ``count`` as the current-window bucket as of ``now_ms``
        (TTL = one window, as a real increment would set).  Used by the
        degraded-mode host limiter (storage/degraded.py) to start its
        approximation from the last counter value the device reported."""
        win = self.config.window_ms
        self._buckets[(key, (now_ms // win) * win)] = (
            max(int(count), 0), now_ms + win)

    def reset(self, key: str, now_ms: int) -> None:
        win = self.config.window_ms
        curr_ws = (now_ms // win) * win
        self._buckets.pop((key, curr_ws), None)
        self._buckets.pop((key, curr_ws - win), None)


class TokenBucketOracle:
    """Exact sequential semantics of the token-bucket limiter (fixed point).

    Storage model: dict key -> (tokens_fp, last_refill_ms, ttl_deadline_ms),
    mirroring the Redis hash {tokens, last_refill} with PEXPIRE(2*window)
    refreshed only by the Lua script's allow branch
    (TokenBucketRateLimiter.java:60-64).
    """

    def __init__(self, config: RateLimitConfig):
        config.validate()
        if config.refill_rate <= 0:
            raise ValueError(
                "Token bucket requires positive refillRate. "
                "Use RateLimitConfig(refill_rate=...)"
            )
        self.config = config
        self._buckets: Dict[str, Tuple[int, int, int]] = {}

    def reconfigure(self, config: RateLimitConfig) -> None:
        """Adopt a live policy update (see SlidingWindowOracle
        .reconfigure): capacity and refill rate move, window (the TTL
        shape) does not; stored fixed-point state is untouched — a
        bucket holding more than the NEW capacity reads as exactly the
        new capacity (the ``min(cap, ...)`` in :meth:`_refilled`),
        which is the device kernel's own refill arithmetic."""
        config.validate()
        if config.window_ms != self.config.window_ms:
            raise ValueError("reconfigure cannot change the window")
        if config.refill_rate <= 0:
            raise ValueError("Token bucket requires positive refillRate")
        self.config = config

    def _load(self, key: str, now_ms: int) -> Tuple[int, int]:
        """Returns (tokens_fp, last_refill) applying lazy init on absent or
        expired state (Lua lines: `if tokens == nil then tokens = capacity`)."""
        entry = self._buckets.get(key)
        if entry is None:
            return self.config.max_permits_fp, now_ms
        tokens_fp, last_refill, deadline = entry
        if now_ms >= deadline:
            del self._buckets[key]
            return self.config.max_permits_fp, now_ms
        return tokens_fp, last_refill

    def _refilled(self, key: str, now_ms: int) -> int:
        """Refill = min(cap, tokens + elapsed_ms * rate_fp) — a pure integer
        multiply (rate_fp is fp-units/ms), exact w.r.t. the rational
        semantics.  Elapsed is clamped once the refill is guaranteed to cap
        the bucket, bounding the product within int64 on device."""
        tokens_fp, last_refill = self._load(key, now_ms)
        elapsed = now_ms - last_refill
        cap_fp = self.config.max_permits_fp
        rate_fp = self.config.refill_rate_fp
        elapsed = min(elapsed, cap_fp // max(rate_fp, 1) + 1)
        return min(cap_fp, tokens_fp + elapsed * rate_fp)

    def try_acquire(self, key: str, permits: int, now_ms: int) -> Decision:
        if permits <= 0:
            raise ValueError("permits must be positive")
        cfg = self.config
        if permits > cfg.max_permits:
            # Can never be fulfilled (TokenBucketRateLimiter.java:110-116);
            # rejected client-side without touching storage.
            whole = self._refilled(key, now_ms) // TOKEN_FP_ONE
            return Decision(allowed=False, mutated=False,
                            observed=whole, remaining_hint=whole)

        tokens_fp = self._refilled(key, now_ms)
        observed = tokens_fp // TOKEN_FP_ONE
        requested_fp = permits * TOKEN_FP_ONE

        if tokens_fp >= requested_fp:
            tokens_fp -= requested_fp
            # HMSET + PEXPIRE(2*window) — only on the allow branch.
            self._buckets[key] = (tokens_fp, now_ms, now_ms + 2 * cfg.window_ms)
            return Decision(allowed=True, mutated=True, observed=observed,
                            remaining_hint=tokens_fp // TOKEN_FP_ONE)
        # Deny: no write-back (state, including TTL, untouched).
        return Decision(allowed=False, mutated=False, observed=observed,
                        remaining_hint=tokens_fp // TOKEN_FP_ONE)

    def get_available_permits(self, key: str, now_ms: int) -> int:
        """Refill-then-floor, replacing the reference's broken string-GET of a
        hash (quirk Q3)."""
        return self._refilled(key, now_ms) // TOKEN_FP_ONE

    # -- lease reserve/credit (spec for ops/lease.py) -------------------------
    def reserve(self, key: str, requested: int, now_ms: int) -> Tuple[int, int]:
        """Bulk-reserve up to ``requested`` whole tokens atomically:
        grant ``min(requested, refilled // ONE)``, consume the granted
        tokens, and write back with the allow-branch TTL.  Host
        specification of the device RESERVE kernel backing token leases.
        Returns ``(granted, 0)`` — the token bucket has no window start;
        the second element keeps the surface uniform with the sliding
        window."""
        if requested <= 0:
            return 0, 0
        tokens_fp = self._refilled(key, now_ms)
        granted = min(int(requested), tokens_fp // TOKEN_FP_ONE)
        if granted > 0:
            tokens_fp -= granted * TOKEN_FP_ONE
            self._buckets[key] = (tokens_fp, now_ms,
                                  now_ms + 2 * self.config.window_ms)
        return granted, 0

    def credit(self, key: str, unused: int, grant_ws: int,
               now_ms: int) -> int:
        """Return ``unused`` reserved tokens (lease release/renewal):
        refill, then add back up to capacity.  State is written only
        when something was actually absorbed (a bucket already at
        capacity stays bit-untouched, like the deny branch).
        ``grant_ws`` is ignored (uniform surface).  Returns whole tokens
        absorbed."""
        if unused <= 0:
            return 0
        cfg = self.config
        tokens_fp = self._refilled(key, now_ms)
        absorbed = min(int(unused) * TOKEN_FP_ONE,
                       cfg.max_permits_fp - tokens_fp)
        if absorbed <= 0:
            return 0
        self._buckets[key] = (tokens_fp + absorbed, now_ms,
                              now_ms + 2 * cfg.window_ms)
        return absorbed // TOKEN_FP_ONE

    def seed_tokens(self, key: str, whole_tokens: int, now_ms: int) -> None:
        """Install a bucket holding ``whole_tokens`` as of ``now_ms`` (TTL =
        2x window, as the allow branch would set).  Degraded-mode seeding:
        the device's last reported remaining-token count becomes the
        approximation's starting state (storage/degraded.py)."""
        cfg = self.config
        fp = max(0, min(cfg.max_permits_fp, int(whole_tokens) * TOKEN_FP_ONE))
        self._buckets[key] = (fp, now_ms, now_ms + 2 * cfg.window_ms)

    def reset(self, key: str, now_ms: int) -> None:
        self._buckets.pop(key, None)
