from ratelimiter_tpu.semantics.oracle import (
    Decision,
    SlidingWindowOracle,
    TokenBucketOracle,
)

__all__ = ["Decision", "SlidingWindowOracle", "TokenBucketOracle"]
