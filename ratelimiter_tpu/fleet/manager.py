"""NodeManager: node lifecycle for a cross-host cell.

One NodeManager owns the ``hostproc`` node processes of a cell.  It
SPAWNS them through the executor boundary (fleet/executor.py), ADOPTS
ones something else launched (a drill, an init system), probes every
node over the PR 14 control RPC — one ``probe_all`` round trip per
NODE per tick, not per shard — and walks each through the lifecycle::

    SPAWNING -> READY -> SERVING -> DRAINING -> RETIRED
                  \\________________/     |
                          v               v
                        FAILED <----------+

- SPAWNING: exec'd, ready line not yet seen (transient inside
  ``spawn`` — a node that never leaves it raises ``SpawnError``).
- READY: booted and probing OK; a standby, or a primary not yet
  carrying traffic.
- SERVING: owns live keyspace (at least one shard routes here).
- DRAINING: scheduled for retirement; the drain-aware witness reads
  its shards "dead" so the orchestrator promotes away gracefully.
- RETIRED: terminal, clean exit (stdin EOF honored).
- FAILED: terminal, declared dead — probe-failure streak over the
  threshold or the process exited on its own.

The manager is the fleet actuator's data source (``GET /actuator/
fleet``) and the FleetAutopilot's substrate: attached autopilots are
driven from the same tick, so re-seed jobs advance on the probe
cadence with no extra threads.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, List, Optional

from ratelimiter_tpu.fleet.executor import LocalExecutor
from ratelimiter_tpu.utils.logging import get_logger

_log = get_logger("fleet.manager")

SPAWNING = "SPAWNING"
READY = "READY"
SERVING = "SERVING"
DRAINING = "DRAINING"
RETIRED = "RETIRED"
FAILED = "FAILED"

# States a node can be probed in (terminal ones are left alone).
_LIVE = (READY, SERVING, DRAINING)


class Node:
    """One managed node: identity, lifecycle state, control handle."""

    __slots__ = ("name", "role", "version", "shards", "state", "handle",
                 "ctl", "host", "control_port", "ready", "lid_base",
                 "since", "since_wall_ms", "last_probe", "last_probe_at",
                 "probe_fail_streak", "last_error")

    def __init__(self, name: str, role: str, ready: dict, host: str,
                 ctl, handle=None, now: float = 0.0):
        self.name = name
        self.role = role
        self.version = str(ready.get("version", "v0"))
        self.shards = int(ready.get("shards", 1))
        self.state = READY
        self.handle = handle
        self.ctl = ctl
        self.host = host
        self.control_port = int(ready["control_port"])
        self.ready = dict(ready)
        self.lid_base = ready.get("lid_base")
        self.since = now
        self.since_wall_ms = time.time_ns() // 1_000_000
        self.last_probe: Dict[str, dict] = {}
        self.last_probe_at: Optional[float] = None
        self.probe_fail_streak = 0
        self.last_error: Optional[str] = None

    def repl_ports(self) -> List[int]:
        if "repl_ports" in self.ready:
            return list(self.ready["repl_ports"])
        if "repl_port" in self.ready:
            return [int(self.ready["repl_port"])]
        return []

    def sidecar_ports(self) -> List[int]:
        if "sidecar_ports" in self.ready:
            return list(self.ready["sidecar_ports"])
        if "sidecar_port" in self.ready:
            return [int(self.ready["sidecar_port"])]
        return []


class NodeManager:
    """Spawn/adopt/probe/retire nodes; drive attached autopilots.

    ``clock`` and the control-client factory are injectable for
    deterministic tests; metrics land in the ``ratelimiter.fleet.*``
    family (ARCHITECTURE §13).
    """

    def __init__(self, executor=None, probe_interval_ms: float = 500.0,
                 probe_fail_threshold: int = 3,
                 probe_timeout_s: float = 1.0,
                 registry=None, recorder=None,
                 clock: Callable[[], float] = time.monotonic,
                 control_client_factory: Optional[Callable] = None):
        self.executor = executor if executor is not None else LocalExecutor()
        self.probe_interval_ms = float(probe_interval_ms)
        self.probe_fail_threshold = int(probe_fail_threshold)
        self.probe_timeout_s = float(probe_timeout_s)
        self._clock = clock
        if control_client_factory is None:
            from ratelimiter_tpu.replication.control import ControlClient

            control_client_factory = ControlClient
        self._ctl_factory = control_client_factory
        self.nodes: Dict[str, Node] = {}
        self.respawns = 0
        self.reseeds = 0
        self.upgrade_steps = 0
        self._autopilots: List[object] = []
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if recorder is not None:
            self._recorder = recorder
        else:
            from ratelimiter_tpu.observability import flight_recorder

            self._recorder = flight_recorder()
        if registry is not None:
            self._m_nodes = registry.gauge(
                "ratelimiter.fleet.nodes",
                "Live managed nodes (READY/SERVING/DRAINING)")
            self._m_respawns = registry.counter(
                "ratelimiter.fleet.respawns",
                "Replacement nodes spawned by the fleet autopilot "
                "(after a promotion consumed a standby, or a rolling-"
                "upgrade step)")
            self._m_reseeds = registry.counter(
                "ratelimiter.fleet.reseeds",
                "Automated cross-host re-seeds completed (fresh "
                "standby baselined and handed back — cell at N+1)")
            self._m_upgrades = registry.counter(
                "ratelimiter.fleet.upgrade_steps",
                "Rolling-upgrade node replacements completed")
        else:
            self._m_nodes = self._m_respawns = None
            self._m_reseeds = self._m_upgrades = None

    # -- membership ------------------------------------------------------------
    def spawn(self, name: str, role: str, *, version: str = "v0",
              shards: int = 1, host: str = "127.0.0.1",
              limiters: Optional[list] = None,
              repl_targets: Optional[List[str]] = None,
              standby_control: str = "", lease: bool = False,
              num_slots: int = 512, repl_interval_ms: float = 100.0,
              ack_timeout_ms: Optional[float] = None,
              boot_timeout_s: Optional[float] = None,
              extra_args: tuple = (), respawn: bool = False) -> Node:
        """Exec a hostproc node, wait out its boot, adopt it READY.

        ``respawn=True`` marks this spawn as a replacement (autopilot
        re-seed, upgrade step) for the ``fleet.respawns`` counter."""
        argv = ["--role", role, "--host", host,
                "--num-slots", str(int(num_slots)),
                "--shards", str(int(shards)), "--version", str(version),
                "--repl-interval-ms", str(float(repl_interval_ms))]
        if limiters:
            argv += ["--limiters", json.dumps(limiters)]
        if repl_targets:
            argv += ["--repl-target", ",".join(repl_targets)]
        if standby_control:
            argv += ["--standby-control", standby_control]
        if ack_timeout_ms is not None:
            argv += ["--ack-timeout-ms", str(float(ack_timeout_ms))]
        if lease:
            argv += ["--lease"]
        argv += list(extra_args)
        with self._lock:
            if name in self.nodes:
                raise ValueError(f"node {name!r} already managed")
        handle, ready = self.executor.spawn(argv,
                                            boot_timeout_s=boot_timeout_s)
        try:
            node = self.adopt(name, ready, handle=handle, host=host)
        except Exception:
            self.executor.terminate(handle, grace_s=2.0)
            raise
        if respawn:
            self.respawns += 1
            if self._m_respawns is not None:
                self._m_respawns.increment()
        self._recorder.record("fleet.spawned", node=name, role=role,
                              version=str(version), respawn=bool(respawn))
        return node

    def adopt(self, name: str, ready: dict, handle=None,
              host: str = "127.0.0.1", ctl=None) -> Node:
        """Take ownership of an already-running node from its ready
        line.  Refuses a duplicate NAME and a duplicate control
        endpoint — adopting the same process twice would double-probe
        it and let two retire() calls race over one lifetime handle."""
        from ratelimiter_tpu.replication.remote import parse_ready

        info = parse_ready(dict(ready))
        with self._lock:
            if name in self.nodes:
                raise ValueError(f"node {name!r} already managed")
            port = int(info["control_port"])
            for other in self.nodes.values():
                if other.state in _LIVE and other.host == host \
                        and other.control_port == port:
                    raise ValueError(
                        f"control endpoint {host}:{port} already "
                        f"managed as node {other.name!r} — refusing "
                        f"double-adopt")
            if ctl is None:
                ctl = self._ctl_factory(host, port,
                                        timeout=self.probe_timeout_s)
            node = Node(name, info["role"], info, host, ctl,
                        handle=handle, now=self._clock())
            self.nodes[name] = node
        self._export()
        return node

    def node(self, name: str) -> Node:
        with self._lock:
            return self.nodes[name]

    # -- lifecycle transitions -------------------------------------------------
    def _transition(self, node: Node, to: str, **fields) -> None:
        if node.state == to:
            return
        self._recorder.record("fleet.transition", node=node.name,
                              **{"from": node.state, "to": to}, **fields)
        _log.info("fleet node %s: %s -> %s %s", node.name, node.state,
                  to, fields or "")
        node.state = to
        node.since = self._clock()
        node.since_wall_ms = time.time_ns() // 1_000_000

    def mark_serving(self, name: str) -> None:
        with self._lock:
            node = self.nodes[name]
            if node.state not in (READY, SERVING):
                raise ValueError(
                    f"node {name!r} is {node.state}, cannot serve")
            self._transition(node, SERVING)

    def mark_draining(self, name: str) -> None:
        with self._lock:
            node = self.nodes[name]
            if node.state not in (READY, SERVING, DRAINING):
                raise ValueError(
                    f"node {name!r} is {node.state}, cannot drain")
            self._transition(node, DRAINING)

    def retire(self, name: str, grace_s: float = 10.0) -> None:
        """Graceful exit: DRAIN (if not already), stdin-EOF terminate
        through the executor, then RETIRED."""
        with self._lock:
            node = self.nodes[name]
            if node.state in (RETIRED, FAILED):
                return
            self._transition(node, DRAINING)
        if node.handle is not None:
            self.executor.terminate(node.handle, grace_s=grace_s)
        with self._lock:
            self._transition(node, RETIRED)
            self._close_ctl(node)
        self._export()

    def fail(self, name: str, error: str = "declared failed") -> None:
        with self._lock:
            node = self.nodes[name]
            self._fail(node, error)

    def kill(self, name: str) -> None:
        """Hard-kill a node we hold the handle for (chaos drills' mid-
        upgrade primary kill) and mark it FAILED immediately."""
        with self._lock:
            node = self.nodes[name]
        if node.handle is not None:
            self.executor.kill(node.handle)
        with self._lock:
            if node.state not in (RETIRED, FAILED):
                self._fail(node, "killed")

    def _fail(self, node: Node, error: str) -> None:
        node.last_error = error
        self._transition(node, FAILED, error=error)
        self._close_ctl(node)
        self._export()

    def _close_ctl(self, node: Node) -> None:
        try:
            node.ctl.close()
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass

    # -- probe loop ------------------------------------------------------------
    def tick(self) -> None:
        """One probe round over every live node (one ``probe_all`` RPC
        per node — mux_handlers answers every shard in a single round
        trip; a pre-fleet single-shard node falls back to bare
        ``probe``), then drive attached autopilots."""
        with self._lock:
            nodes = list(self.nodes.values())
        for node in nodes:
            if node.state not in _LIVE:
                continue
            if node.handle is not None \
                    and not self.executor.alive(node.handle):
                with self._lock:
                    if node.state in _LIVE:
                        self._fail(node, "process exited")
                continue
            shards = self._probe(node)
            if shards is None:
                node.probe_fail_streak += 1
                if node.probe_fail_streak >= self.probe_fail_threshold:
                    with self._lock:
                        if node.state in _LIVE:
                            self._fail(node,
                                       f"{node.probe_fail_streak} "
                                       f"consecutive probe failures")
            else:
                node.probe_fail_streak = 0
                node.last_probe = shards
                node.last_probe_at = self._clock()
        self._export()
        for autopilot in list(self._autopilots):
            try:
                autopilot.tick()
            except Exception as exc:  # noqa: BLE001 — the probe loop
                # outlives a wedged re-seed job
                _log.warning("fleet autopilot tick failed: %s", exc)

    def _probe(self, node: Node) -> Optional[Dict[str, dict]]:
        resp = node.ctl.try_call("probe_all",
                                 timeout=self.probe_timeout_s)
        if resp is not None and resp.get("ok"):
            return dict(resp.get("shards", {}))
        resp = node.ctl.try_call("probe", timeout=self.probe_timeout_s)
        if resp is not None and resp.get("ok"):
            return {"0": dict(resp, ok=True)}
        return None

    # -- autopilot + counters --------------------------------------------------
    def attach(self, autopilot) -> None:
        """Drive ``autopilot.tick()`` from this manager's probe tick.
        Anything with ``tick()`` (+ optional ``status()``) rides the
        cadence: FleetAutopilot re-seed jobs, NodeLifecycle plans, and
        the ControllerElection (control/fleet.py) — so controller
        leader death is detected and repaired on the SAME tick that
        notices the node died, with no extra threads."""
        self._autopilots.append(autopilot)

    def note_reseed(self) -> None:
        self.reseeds += 1
        if self._m_reseeds is not None:
            self._m_reseeds.increment()

    def note_upgrade_step(self) -> None:
        self.upgrade_steps += 1
        if self._m_upgrades is not None:
            self._m_upgrades.increment()

    # -- observability ---------------------------------------------------------
    def live_nodes(self) -> List[str]:
        with self._lock:
            return sorted(n.name for n in self.nodes.values()
                          if n.state in _LIVE)

    def degraded_nodes(self) -> List[str]:
        """Nodes the health state machine folds to DEGRADED: FAILED
        (declared dead, keyspace moved or moving) and DRAINING
        (scheduled out — capacity leaving)."""
        with self._lock:
            return sorted(n.name for n in self.nodes.values()
                          if n.state in (FAILED, DRAINING))

    def status(self) -> dict:
        now = self._clock()
        with self._lock:
            nodes = {
                n.name: {
                    "role": n.role,
                    "state": n.state,
                    "version": n.version,
                    "shards": n.shards,
                    "host": n.host,
                    "control_port": n.control_port,
                    "lid_base": n.lid_base,
                    "pid": (n.handle.pid if n.handle is not None
                            and hasattr(n.handle, "pid") else None),
                    "since_ms": n.since_wall_ms,
                    "in_state_ms": round((now - n.since) * 1000.0, 3),
                    "probe_age_ms": (
                        None if n.last_probe_at is None
                        else round((now - n.last_probe_at) * 1000.0, 3)),
                    "probe_fail_streak": n.probe_fail_streak,
                    "last_error": n.last_error,
                }
                for n in self.nodes.values()
            }
        out = {"nodes": nodes, "respawns": self.respawns,
               "reseeds": self.reseeds,
               "upgrade_steps": self.upgrade_steps}
        jobs = []
        for autopilot in self._autopilots:
            status = getattr(autopilot, "status", None)
            if status is not None:
                jobs.append(status())
        if jobs:
            out["autopilot"] = jobs
        return out

    def _export(self) -> None:
        if self._m_nodes is not None:
            with self._lock:
                live = sum(1 for n in self.nodes.values()
                           if n.state in _LIVE)
            self._m_nodes.set(float(live))

    # -- cadence ---------------------------------------------------------------
    def start(self) -> "NodeManager":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="fleet-manager", daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.probe_interval_ms / 1000.0):
            try:
                self.tick()
            except Exception as exc:  # noqa: BLE001 — loop survives
                _log.warning("fleet tick failed: %s", exc)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self._stop.clear()

    def close(self, terminate: bool = True) -> None:
        """Stop the cadence and (by default) retire every node this
        manager spawned — their stdin pipes die with us anyway; an
        explicit EOF beats an orphan hunting for a closed pipe."""
        self.stop()
        with self._lock:
            nodes = list(self.nodes.values())
        for node in nodes:
            if terminate and node.handle is not None \
                    and node.state in _LIVE:
                try:
                    self.executor.terminate(node.handle, grace_s=5.0)
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
                with self._lock:
                    self._transition(node, RETIRED)
            self._close_ctl(node)
        self._export()
