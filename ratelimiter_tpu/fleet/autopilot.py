"""FleetAutopilot: automated cross-host re-seed (back to N+1).

PR 14's cross-host topology fails over once, then the cell is N+0
until an operator hand-builds a standby.  The autopilot closes that
loop: it watches the orchestrator's standby set and, the moment a
promotion CONSUMES shard q's standby (``receivers[q].promoted``), runs
the re-seed job the operator used to:

1. spawn a fresh single-shard ``hostproc --role standby`` at the
   configured deploy version (NodeManager -> executor boundary);
2. RETARGET the now-serving backend's replication stream at the new
   node's listener — the control op stops the pipeline, swaps the
   sink, forces a full re-baseline frame, and ships it synchronously
   (replication/hostproc.py);
3. poll the new replica to ``consistent`` and hand it back: swap the
   orchestrator's StandbySet entry, re-point the shard's witness at
   the new vantage (the witness dict is read at call time, so an
   in-place mutation is the whole rewire), and re-aim the serving-
   lease relay leg at the new node's mailbox.

Every job is bounded by ``reseed_deadline_s`` — a job past it is
FAILED loudly (flight event) instead of silently wedging the cell at
N+0.  Jobs advance from the NodeManager's tick; no extra threads.

``witness_wrap`` adds the rolling-upgrade leg: a shard whose SERVING
node is DRAINING answers "dead" regardless of the standby's vantage —
without it, the still-heartbeating draining primary's "alive" verdict
would veto its own graceful promote-away forever.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

from ratelimiter_tpu.fleet import manager as _mgr
from ratelimiter_tpu.utils.logging import get_logger

_log = get_logger("fleet.autopilot")


class FleetAutopilot:
    """Per-cell re-seed driver.

    Parameters
    ----------
    manager : NodeManager spawning replacement nodes.
    orchestrator : FailoverOrchestrator (its router resolves the
        serving backend; ``set_lease_channel`` re-aims renewals).
    standby_set : the orchestrator's RemoteStandbySet (watched for
        consumption; ``replace`` hands the fresh replica back).
    witness_ctls : the LIVE dict behind ``standby_witness`` — entries
        are mutated in place to swap a shard's witness vantage.
    node_defaults : spawn kwargs for replacement standbys (num_slots,
        lease, host, repl_interval_ms, ack_timeout_ms,
        boot_timeout_s).  Geometry must match the serving nodes.
    version : deploy version tag for replacements (a rolling upgrade
        bumps this, then drains nodes — every respawn lands new).
    """

    def __init__(self, manager, orchestrator, standby_set,
                 witness_ctls: Dict[int, object],
                 node_defaults: Optional[dict] = None,
                 version: str = "v0",
                 reseed_deadline_s: float = 120.0,
                 recorder=None,
                 clock: Callable[[], float] = time.monotonic):
        self.manager = manager
        self.orch = orchestrator
        self.standby_set = standby_set
        self.witness_ctls = witness_ctls
        self.node_defaults = dict(node_defaults or {})
        self.version = str(version)
        self.reseed_deadline_s = float(reseed_deadline_s)
        self._clock = clock
        # Optional ControllerElection (control/fleet.py): a node handed
        # back into the cell is announced so the controller leader
        # anti-entropies it to the fleet's policy generation before it
        # can serve a stale one.
        self.election = None
        # q -> (node_name, shard_on_node): who serves / shadows shard q.
        self._serving: Dict[int, tuple] = {}
        self._standby: Dict[int, tuple] = {}
        self._jobs: Dict[int, dict] = {}
        self.completed: list = []
        self.failed_jobs: list = []
        self._seq = 0
        if recorder is not None:
            self._recorder = recorder
        else:
            from ratelimiter_tpu.observability import flight_recorder

            self._recorder = flight_recorder()

    # -- topology bookkeeping --------------------------------------------------
    def bind(self, q: int, serving: tuple, standby: tuple) -> None:
        """Register shard q's placement: ``(node_name, shard_on_node)``
        for the serving and standby side."""
        self._serving[int(q)] = (str(serving[0]), int(serving[1]))
        self._standby[int(q)] = (str(standby[0]), int(standby[1]))

    def serving_node(self, q: int) -> Optional[str]:
        entry = self._serving.get(int(q))
        return entry[0] if entry is not None else None

    def standby_node(self, q: int) -> Optional[str]:
        entry = self._standby.get(int(q))
        return entry[0] if entry is not None else None

    def serving_placement(self, q: int) -> Optional[tuple]:
        return self._serving.get(int(q))

    def standby_placement(self, q: int) -> Optional[tuple]:
        return self._standby.get(int(q))

    def witness_wrap(self, inner: Callable[[int], str]
                     ) -> Callable[[int], str]:
        """Drain-aware witness: a shard whose serving node is DRAINING
        reads "dead" so the orchestrator promotes away from it — the
        graceful leg of a rolling upgrade.  Every other shard defers
        to ``inner`` (the standby-vantage witness)."""

        def witness(q: int) -> str:
            entry = self._serving.get(int(q))
            if entry is not None:
                node = self.manager.nodes.get(entry[0])
                if node is not None and node.state == _mgr.DRAINING:
                    return "dead"
            return inner(q)

        return witness

    # -- the re-seed state machine ---------------------------------------------
    def tick(self) -> None:
        # Two passes: FIRST swap the serving bindings of every newly
        # consumed shard (cheap, keeps the drain-aware probe/witness
        # truthful), THEN advance jobs — _advance can block for seconds
        # on a replacement node's boot, and shard 1's stale binding
        # must not wait out shard 0's spawn.
        for q in range(self.standby_set.n_shards):
            if q in self._jobs:
                continue
            rx = self.standby_set.receivers[q]
            if getattr(rx, "promoted", False):
                self._begin(q)
        for q, job in list(self._jobs.items()):
            self._advance(q, job)

    def _begin(self, q: int) -> None:
        """Shard q's standby was consumed by a promotion: the old
        standby node now serves q; open a re-seed job."""
        consumed = self._standby.pop(q, None)
        if consumed is not None:
            self._serving[q] = consumed
            node = self.manager.nodes.get(consumed[0])
            if node is not None and node.state in (_mgr.READY,
                                                   _mgr.SERVING):
                self.manager.mark_serving(consumed[0])
        job = {"q": q, "state": "spawn", "started_at": self._clock(),
               "node": None, "rx": None, "backend": None, "error": None}
        self._jobs[q] = job
        self._recorder.record("fleet.reseed_started", shard=q,
                              serving=self.serving_node(q))

    def _advance(self, q: int, job: dict) -> None:
        if job["state"] in ("done", "failed"):
            return
        elapsed = self._clock() - job["started_at"]
        if elapsed > self.reseed_deadline_s:
            job["state"] = "failed"
            job["elapsed_s"] = round(elapsed, 3)
            self.failed_jobs.append(
                {k: job[k] for k in ("q", "state", "node", "error",
                                     "elapsed_s")})
            self._jobs.pop(q, None)
            _log.warning("re-seed job for shard %d missed its %.1fs "
                         "deadline (last error: %s) — cell stays N+0",
                         q, self.reseed_deadline_s, job["error"])
            self._recorder.record("fleet.reseed_deadline", shard=q,
                                  deadline_s=self.reseed_deadline_s,
                                  error=job["error"])
            return
        try:
            if job["state"] == "spawn":
                backend = self.orch.router.serving(q)
                if backend is None:
                    return  # promotion not installed yet; next tick
                job["backend"] = backend
                name = f"reseed-q{q}-{self._seq}"
                self._seq += 1
                self.manager.spawn(name, "standby", shards=1,
                                   version=self.version, respawn=True,
                                   **self.node_defaults)
                job["node"] = name
                job["state"] = "retarget"
            if job["state"] == "retarget":
                from ratelimiter_tpu.replication.remote import (
                    RemoteReceiver,
                )

                node = self.manager.node(job["node"])
                job["backend"].retarget(node.host, node.repl_ports()[0])
                job["rx"] = RemoteReceiver(node.ctl, shard=0)
                job["state"] = "wait_consistent"
            if job["state"] == "wait_consistent":
                rx = job["rx"]
                if rx.consistent and not rx.promoted:
                    self._finalize(q, job)
        except Exception as exc:  # noqa: BLE001 — retried every tick
            # until the deadline; the error rides along for the
            # deadline event and /actuator/fleet.
            job["error"] = f"{type(exc).__name__}: {exc}"[:200]

    def install_standby(self, q: int, node_name: str, shard: int, rx,
                        serving_backend=None) -> None:
        """Hand a consistent replica back to the orchestrator: swap the
        StandbySet entry, re-point shard q's witness vantage (the
        witness dict is read at call time, so the in-place mutation IS
        the rewire — replication/remote.py:standby_witness), and re-aim
        the serving-lease relay leg at the new node's mailbox.  Also
        the planned-replacement path: a rolling upgrade's graceful
        standby swap calls this directly."""
        node = self.manager.node(node_name)
        self.standby_set.replace(q, None, rx)
        self.witness_ctls[q] = (node.ctl, int(shard))
        if serving_backend is not None and \
                float(getattr(self.orch.cfg,
                              "fence_lease_ttl_ms", 0.0)) > 0:
            from ratelimiter_tpu.replication.remote import (
                FanoutLeaseChannel,
            )

            self.orch.set_lease_channel(
                q, FanoutLeaseChannel(serving_backend, node.ctl,
                                      shard=int(shard)))
        self._standby[int(q)] = (node.name, int(shard))
        if self.election is not None:
            from ratelimiter_tpu.replication.remote import RemoteBackend

            # The join-side half of the generation-convergence
            # invariant (ARCHITECTURE §15): the fresh node is converged
            # to the leader's generation before anything can read a
            # stale policy from it.
            self.election.note_join(
                node.name, RemoteBackend(node.ctl, label=node.name,
                                         shard=int(shard)))

    def _finalize(self, q: int, job: dict) -> None:
        node = self.manager.node(job["node"])
        self.install_standby(q, job["node"], 0, job["rx"],
                             serving_backend=job["backend"])
        elapsed = self._clock() - job["started_at"]
        job["state"] = "done"
        job["elapsed_s"] = round(elapsed, 3)
        self.completed.append(
            {k: job[k] for k in ("q", "node", "elapsed_s")})
        self._jobs.pop(q, None)
        self.manager.note_reseed()
        _log.info("re-seed for shard %d complete in %.2fs (standby %s, "
                  "version %s) — cell back at N+1", q, elapsed,
                  node.name, node.version)
        self._recorder.record("fleet.reseeded", shard=q, node=node.name,
                              elapsed_s=job["elapsed_s"],
                              version=node.version)

    # -- observability ---------------------------------------------------------
    def status(self) -> dict:
        now = self._clock()
        return {
            "version": self.version,
            "serving": {str(q): e[0] for q, e in self._serving.items()},
            "standby": {str(q): e[0] for q, e in self._standby.items()},
            "jobs": {
                str(q): {
                    "state": j["state"], "node": j["node"],
                    "elapsed_s": round(now - j["started_at"], 3),
                    "error": j["error"],
                }
                for q, j in self._jobs.items()
            },
            "completed": len(self.completed),
            "failed": len(self.failed_jobs),
        }
