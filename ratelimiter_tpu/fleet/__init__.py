"""Fleet autopilot (ARCHITECTURE §16): the deployment layer above the
failover orchestrator.

- :mod:`executor` — the process-execution boundary (``LocalExecutor``
  runs ``hostproc`` as local subprocesses; anything with the same
  duck-typed surface — a container runtime, a remote agent — slots in
  unchanged).
- :mod:`manager` — the :class:`~manager.NodeManager`: spawns, adopts,
  probes, and retires nodes, tracking per-node lifecycle state
  (SPAWNING → READY → SERVING → DRAINING → RETIRED/FAILED).
- :mod:`autopilot` — the :class:`~autopilot.FleetAutopilot`: watches
  the orchestrator's standby set and, when a promotion consumes a
  standby, spawns a fresh one, drives the control-RPC re-seed, and
  hands the consistent replica back — the cell returns to N+1 with
  zero operator calls.
"""

from ratelimiter_tpu.fleet.autopilot import FleetAutopilot
from ratelimiter_tpu.fleet.executor import LocalExecutor, SpawnError
from ratelimiter_tpu.fleet.manager import (
    DRAINING,
    FAILED,
    READY,
    RETIRED,
    SERVING,
    SPAWNING,
    Node,
    NodeManager,
)

__all__ = [
    "DRAINING",
    "FAILED",
    "FleetAutopilot",
    "LocalExecutor",
    "Node",
    "NodeManager",
    "READY",
    "RETIRED",
    "SERVING",
    "SPAWNING",
    "SpawnError",
]
