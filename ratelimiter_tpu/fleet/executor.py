"""Process-execution boundary for the fleet layer.

The :class:`~ratelimiter_tpu.fleet.manager.NodeManager` never touches
``subprocess`` directly — it talks to an EXECUTOR duck type::

    spawn(args, boot_timeout_s=None) -> (handle, ready: dict)
    alive(handle) -> bool
    terminate(handle, grace_s=...)   # graceful: stdin EOF first
    kill(handle)                     # hard kill (drills' primary kill)

so "where a node runs" (local subprocess today; a container runtime or
a remote exec agent later) is swappable without touching lifecycle
logic.  :class:`LocalExecutor` is the subprocess implementation: it
launches ``python -m ratelimiter_tpu.replication.hostproc`` with a
stdin pipe (the node's lifetime handle — hostproc exits on stdin EOF),
reads the ONE ready-JSON line off stdout under a boot deadline, and
surfaces every boot pathology as :class:`SpawnError` (timeout, early
exit, malformed line) instead of a hang.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
from typing import List, Optional, Tuple

from ratelimiter_tpu.utils.logging import get_logger

_log = get_logger("fleet.executor")

_HOSTPROC_ARGV = [sys.executable, "-m",
                  "ratelimiter_tpu.replication.hostproc"]


class SpawnError(RuntimeError):
    """A node failed to boot: no ready line within the deadline, the
    process exited first, or the line was not valid JSON."""


class ProcessHandle:
    """The LocalExecutor's opaque handle: one hostproc subprocess."""

    def __init__(self, proc: subprocess.Popen):
        self.proc = proc

    @property
    def pid(self) -> int:
        return self.proc.pid

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return f"ProcessHandle(pid={self.proc.pid})"


class LocalExecutor:
    """Run nodes as local OS subprocesses.

    ``argv_prefix`` defaults to the hostproc module runner; tests
    override it (e.g. ``[sys.executable, "-c", ...]``) to exercise the
    boot-pathology paths without a real node.  ``JAX_PLATFORMS=cpu`` is
    forced unless the caller's env already pins a platform — fleet
    nodes on one dev host must not fight over an accelerator.
    """

    def __init__(self, argv_prefix: Optional[List[str]] = None,
                 env: Optional[dict] = None,
                 boot_timeout_s: float = 180.0):
        self.argv_prefix = list(argv_prefix if argv_prefix is not None
                                else _HOSTPROC_ARGV)
        self.env = dict(env or {})
        self.boot_timeout_s = float(boot_timeout_s)

    def spawn(self, args: List[str],
              boot_timeout_s: Optional[float] = None,
              ) -> Tuple[ProcessHandle, dict]:
        """Launch a node and block for its ready line; returns the
        lifetime handle plus the parsed ready JSON.  Raises
        :class:`SpawnError` on any boot pathology (the half-started
        process is torn down first — no orphans)."""
        timeout = float(boot_timeout_s if boot_timeout_s is not None
                        else self.boot_timeout_s)
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.update(self.env)
        proc = subprocess.Popen(
            self.argv_prefix + list(args),
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            env=env, text=True)
        handle = ProcessHandle(proc)
        box: dict = {}

        def _read() -> None:
            try:
                box["line"] = proc.stdout.readline()
            except Exception:  # noqa: BLE001 — reported as empty below
                box["line"] = ""

        reader = threading.Thread(target=_read, name="node-boot-reader",
                                  daemon=True)
        reader.start()
        reader.join(timeout)
        if "line" not in box:
            self.kill(handle)
            raise SpawnError(
                f"node {self.argv_prefix + list(args)!r} printed no "
                f"ready line within {timeout:.1f}s")
        line = (box["line"] or "").strip()
        if not line:
            rc = proc.poll()
            self.kill(handle)
            raise SpawnError(
                f"node exited (rc={rc}) before printing a ready line")
        try:
            ready = json.loads(line)
        except json.JSONDecodeError as exc:
            self.kill(handle)
            raise SpawnError(
                f"malformed ready line {line!r}: {exc}") from exc
        if not isinstance(ready, dict):
            self.kill(handle)
            raise SpawnError(f"ready line is not a JSON object: {line!r}")
        return handle, ready

    def alive(self, handle: ProcessHandle) -> bool:
        return handle.proc.poll() is None

    def terminate(self, handle: ProcessHandle,
                  grace_s: float = 10.0) -> None:
        """Graceful retirement: close stdin (hostproc's exit signal),
        wait out the grace period, then escalate terminate -> kill."""
        proc = handle.proc
        try:
            if proc.stdin is not None:
                proc.stdin.close()
        except OSError:
            pass
        try:
            proc.wait(timeout=grace_s)
            return
        except subprocess.TimeoutExpired:
            _log.warning("node pid=%d ignored stdin EOF for %.1fs; "
                         "terminating", proc.pid, grace_s)
        proc.terminate()
        try:
            proc.wait(timeout=5.0)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=5.0)

    def kill(self, handle: ProcessHandle) -> None:
        """Hard kill (no stdin courtesy): SIGKILL and reap."""
        proc = handle.proc
        try:
            if proc.stdin is not None:
                proc.stdin.close()
        except OSError:
            pass
        if proc.poll() is None:
            proc.kill()
        try:
            proc.wait(timeout=5.0)
        except subprocess.TimeoutExpired:  # pragma: no cover — kernel owes
            pass                           # us a reaped SIGKILL
