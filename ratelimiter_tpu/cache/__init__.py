from ratelimiter_tpu.cache.ttl_cache import TTLCache

__all__ = ["TTLCache"]
