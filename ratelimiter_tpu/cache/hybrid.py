"""Hybrid host-side serving tier (r11) — the Apt-Serve shape.

The reference's TTL cache (``ttl_cache.py``) is a *negative* cache: it
short-circuits repeat rejections from a possibly-stale counter, trading
accuracy for round trips.  This tier is the grown-up version the
Apt-Serve paper sketches (PAPERS.md: adaptive request scheduling over a
hybrid cache that keeps the fast path off the expensive resource): it
answers **hot repeat-reject and safely-under-limit keys host-side from
EXACT per-key state**, with bounded staleness, and every host-side
mutation is **device-confirmed asynchronously**.

How exactness works
-------------------
The tier never guesses.  A key is *adopted* only when a device result
fully determines its semantic state:

- sliding window: a ``mutated`` decision whose weighted estimate carried
  zero previous-window contribution (``observed + 1 == cache_value``).
  Then the current bucket is exactly ``cache_value`` with deadline
  ``stamp + window`` (the increment's PEXPIRE), the previous bucket
  contributes zero for the remainder of this window (the floored weight
  is monotone non-increasing in-window), and across the boundary the
  tracked current bucket *becomes* the previous one — so the oracle
  snapshot is exact from adoption onward.
- token bucket: an allowed decision from a **full** bucket
  (``observed == max_permits`` — the floor equals the cap only when the
  fixed-point level is exactly the cap), leaving exactly
  ``(max_permits - permits) * TOKEN_FP_ONE`` with ``last_refill = stamp``.

From adoption on, the tier replays the key's traffic through the same
``semantics/oracle.py`` arithmetic every backend is proven against, so a
host-served decision is bit-identical to what the device would answer —
as long as every mutation of the key flows through this tier.  Paths
that can mutate state behind it (streams, direct batches, eviction,
reset, promotion) *invalidate* the entry at remap/clear time
(storage/tpu.py hooks), and every host-served **mutating** decision is
forwarded through the normal micro-batch path; its drain result is
compared field-for-field against the prediction.  Any mismatch counts
``ratelimiter.cache.hybrid.divergence`` and drops the entry — the tier
re-adopts from fresh device results.

Bounded over-admission
----------------------
Same bound ``storage/degraded.py`` proves for the breaker's open state:
the tier's oracle arithmetic admits at most ``max_permits`` per key per
window on its own, and the device independently admits at most
``max_permits`` — so even under worst-case divergence (a stale snapshot
racing hidden device traffic) the combined admission is bounded by **one
extra ``max_permits`` per key per window**, not unbounded fail-open.
Three additional brakes keep the divergence window small: entries serve
only within ``ttl_ms`` of their last device confirmation, at most
``unconfirmed_cap`` forwarded mutations may be awaiting confirmation
(past that the caller falls through to the device path, which refreshes
the entry), and sliding-window serves refuse the last ``guard_ms`` of a
window (a forwarded increment landing across the boundary would split
buckets between host and device).

Locking: ``lock`` is exposed and **held by the storage across
serve + confirmation submit**, so the device applies a key's forwarded
mutations in exactly the order the host decided them.
"""

from __future__ import annotations

import collections
import threading
from typing import Dict, Optional, Tuple

from ratelimiter_tpu.core.config import RateLimitConfig
from ratelimiter_tpu.semantics.oracle import (
    Decision,
    SlidingWindowOracle,
    TokenBucketOracle,
)
from ratelimiter_tpu.utils.logging import get_logger

log = get_logger("cache.hybrid")


class _Entry:
    __slots__ = ("slot", "unconfirmed", "last_sync_ms", "gen")

    def __init__(self, slot: int, stamp_ms: int, gen: int):
        self.slot = int(slot)
        self.unconfirmed = 0
        self.last_sync_ms = int(stamp_ms)
        self.gen = gen


class HybridServingCache:
    """Exact host-side serving tier over adopted oracle snapshots."""

    def __init__(self, clock_ms, ttl_ms: float = 50.0,
                 max_keys: int = 65536, unconfirmed_cap: int = 64,
                 guard_ms: float = 5.0, registry=None):
        self._clock_ms = clock_ms
        self.ttl_ms = float(ttl_ms)
        self.max_keys = int(max_keys)
        self.unconfirmed_cap = int(unconfirmed_cap)
        self.guard_ms = float(guard_ms)
        self.lock = threading.RLock()
        self._configs: Dict[int, Tuple[str, RateLimitConfig]] = {}
        self._oracles: Dict[Tuple[str, int], object] = {}
        # (algo, lid, key) -> _Entry; LRU-bounded by max_keys.
        self._entries: "collections.OrderedDict" = collections.OrderedDict()
        self._by_slot: Dict[Tuple[str, int], Tuple[str, int, str]] = {}
        self._gen = 0
        self.served = 0       # decisions answered host-side
        self.rejects_served = 0  # of those: pure rejects (zero device work)
        self.adopted = 0
        self.invalidated = 0
        self.divergence = 0

        def _counter(name, desc):
            return (registry.counter(name, desc)
                    if registry is not None else None)

        self._served_c = _counter(
            "ratelimiter.cache.hybrid.served",
            "Decisions answered host-side by the hybrid serving tier")
        self._adopted_c = _counter(
            "ratelimiter.cache.hybrid.adopted",
            "Keys adopted into exact host-side tracking")
        self._invalidated_c = _counter(
            "ratelimiter.cache.hybrid.invalidated",
            "Hybrid-tier entries dropped (evict/reset/TTL/divergence)")
        self._divergence_c = _counter(
            "ratelimiter.cache.hybrid.divergence",
            "Device confirmations that mismatched the host prediction")

    # -- policy registry ------------------------------------------------------
    def register(self, lid: int, algo: str, config: RateLimitConfig) -> None:
        with self.lock:
            self._configs[int(lid)] = (algo, config)

    def update_policy(self, lid: int, algo: str,
                      config: RateLimitConfig) -> None:
        """Live policy update (storage.set_policy calls this BEFORE the
        device row moves): every entry tracking the lid is dropped — a
        host serve racing the update must not answer under the old rate
        — and the lid's oracle is rebuilt so re-adoption replays the
        NEW policy's arithmetic."""
        with self.lock:
            self._configs[int(lid)] = (algo, config)
            self._oracles.pop((algo, int(lid)), None)
            stale = [ek for ek in self._entries
                     if ek[0] == algo and ek[1] == int(lid)]
            for ek in stale:
                self._drop(ek)

    def _oracle(self, algo: str, lid: int):
        k = (algo, int(lid))
        oracle = self._oracles.get(k)
        if oracle is None:
            cfg = self._configs[int(lid)][1]
            oracle = (SlidingWindowOracle(cfg) if algo == "sw"
                      else TokenBucketOracle(cfg))
            self._oracles[k] = oracle
        return oracle

    # -- serve (storage.acquire_async fast path; lock held by caller) --------
    def serve(self, algo: str, lid: int, key: str, permits: int):
        """Host-side decision for a tracked key, or None (device path).

        Returns ``(out_dict, predicted)``; ``predicted`` is the oracle
        :class:`Decision` when the serve mutated host state (the caller
        forwards the identical request and registers it via
        :meth:`watch_confirm`), or None for a pure reject."""
        ek = (algo, int(lid), key)
        entry = self._entries.get(ek)
        if entry is None:
            return None
        now = self._clock_ms()
        cfg = self._configs[int(lid)][1]
        # Every decline DROPS the entry rather than bypassing it: a
        # bypassed request would mutate device state the snapshot never
        # sees until its drain callback, and a serve racing that replay
        # could answer from pre-op state.  Dropping keeps the invariant
        # "tracked => every mutation flowed through the tier"; the key
        # re-adopts from the next determining device result.
        if now - entry.last_sync_ms > self.ttl_ms:
            self._drop(ek)  # bounded staleness: re-adopt from the device
            return None
        if entry.unconfirmed >= self.unconfirmed_cap:
            self._drop(ek)  # backpressure: let the device path refresh it
            return None
        if algo == "sw":
            win = cfg.window_ms
            if win - (now % win) <= self.guard_ms:
                # Window edge: a forwarded increment could land in the
                # next bucket on the device.
                self._drop(ek)
                return None
        oracle = self._oracle(algo, int(lid))
        d: Decision = oracle.try_acquire(key, int(permits), now)
        self._entries.move_to_end(ek)
        self.served += 1
        if self._served_c is not None:
            self._served_c.increment()
        if algo == "sw":
            out = {"allowed": d.allowed, "mutated": d.mutated,
                   "observed": d.observed, "cache_value": d.remaining_hint,
                   "host_served": True}
        else:
            out = {"allowed": d.allowed, "observed": d.observed,
                   "remaining": d.remaining_hint, "host_served": True}
        if d.mutated:
            entry.unconfirmed += 1
            return out, d
        self.rejects_served += 1
        return out, None

    # -- device feedback ------------------------------------------------------
    def watch_confirm(self, algo: str, lid: int, key: str,
                      predicted: Decision, slot: int, fut) -> None:
        """Register a forwarded mutation's future (lock held): its drain
        result must match the host prediction field-for-field."""
        ek = (algo, int(lid), key)
        entry = self._entries.get(ek)
        if entry is None:
            return
        entry.slot = int(slot)
        self._by_slot[(algo, int(slot))] = ek
        gen = entry.gen
        fut.add_done_callback(
            lambda f: self._confirm(ek, gen, predicted, f))

    def _confirm(self, ek, gen: int, predicted: Decision, fut) -> None:
        try:
            out = fut.result()
        except Exception:  # noqa: BLE001 — device path failed; drop entry
            with self.lock:
                entry = self._entries.get(ek)
                if entry is not None and entry.gen == gen:
                    self._drop(ek)
            return
        algo = ek[0]
        ok = bool(out["allowed"]) == predicted.allowed and int(
            out["observed"]) == predicted.observed
        if algo == "sw":
            ok = ok and bool(out["mutated"]) == predicted.mutated and int(
                out["cache_value"]) == predicted.remaining_hint
        else:
            ok = ok and int(out["remaining"]) == predicted.remaining_hint
        with self.lock:
            entry = self._entries.get(ek)
            if entry is None or entry.gen != gen:
                return
            if not ok:
                self.divergence += 1
                if self._divergence_c is not None:
                    self._divergence_c.increment()
                log.warning(
                    "hybrid tier divergence on %s (predicted %s); "
                    "entry dropped", ek, predicted)
                self._drop(ek)
                return
            entry.unconfirmed -= 1
            stamp = out.get("stamp")
            if stamp is not None:
                entry.last_sync_ms = max(entry.last_sync_ms, int(stamp))

    def watch_miss(self, algo: str, lid: int, key: str, permits: int,
                   slot: int, fut) -> None:
        """Register a device-path miss (no lock held): its result either
        refreshes the tracked entry or — when it pins the key's full
        semantic state — adopts the key into host-side tracking."""
        fut.add_done_callback(
            lambda f: self._absorb(algo, int(lid), key, int(permits),
                                   int(slot), f))

    def _absorb(self, algo: str, lid: int, key: str, permits: int,
                slot: int, fut) -> None:
        try:
            out = fut.result()
        except Exception:  # noqa: BLE001 — failed dispatch teaches nothing
            return
        stamp = out.get("stamp")
        if stamp is None:
            return
        stamp = int(stamp)
        with self.lock:
            ek = (algo, lid, key)
            entry = self._entries.get(ek)
            if entry is not None:
                # A tracked key took the device path (unconfirmed cap,
                # window guard): the device mutated state the snapshot
                # didn't see — replay the same op through the oracle and
                # verify; mismatch means hidden divergence.
                oracle = self._oracle(algo, lid)
                d = oracle.try_acquire(key, permits, stamp)
                if (d.allowed != bool(out["allowed"])
                        or d.observed != int(out["observed"])):
                    self.divergence += 1
                    if self._divergence_c is not None:
                        self._divergence_c.increment()
                    self._drop(ek)
                else:
                    entry.last_sync_ms = max(entry.last_sync_ms, stamp)
                return
            cfg_entry = self._configs.get(lid)
            if cfg_entry is None or cfg_entry[0] != algo:
                return
            cfg = cfg_entry[1]
            if algo == "sw":
                if not (bool(out["mutated"])
                        and int(out["observed"]) + 1
                        == int(out["cache_value"])):
                    return  # previous-window contribution unknown
                self._adopt(ek, slot, stamp)
                self._oracle(algo, lid).seed_count(
                    key, int(out["cache_value"]), stamp)
            else:
                if not (bool(out["allowed"])
                        and int(out["observed"]) == cfg.max_permits):
                    return  # fractional fixed-point level unknown
                self._adopt(ek, slot, stamp)
                self._oracle(algo, lid).seed_tokens(
                    key, cfg.max_permits - permits, stamp)

    def _adopt(self, ek, slot: int, stamp: int) -> None:
        self._gen += 1
        self._entries[ek] = _Entry(slot, stamp, self._gen)
        self._by_slot[(ek[0], int(slot))] = ek
        self.adopted += 1
        if self._adopted_c is not None:
            self._adopted_c.increment()
        while len(self._entries) > self.max_keys:
            old_ek, old = self._entries.popitem(last=False)
            self._forget_state(old_ek, old)

    # -- invalidation (storage hooks) -----------------------------------------
    def _forget_state(self, ek, entry: Optional[_Entry]) -> None:
        algo, lid, key = ek
        if entry is not None:
            self._by_slot.pop((algo, entry.slot), None)
        oracle = self._oracles.get((algo, int(lid)))
        if oracle is not None:
            # Purge the key's semantic state so a later re-adoption
            # starts clean (the oracle dicts would otherwise leak).
            oracle.reset(key, self._clock_ms())

    def _drop(self, ek) -> None:
        entry = self._entries.pop(ek, None)
        if entry is None:
            return
        self._forget_state(ek, entry)
        self.invalidated += 1
        if self._invalidated_c is not None:
            self._invalidated_c.increment()

    def invalidate(self, algo: str, lid: int, key: str) -> None:
        with self.lock:
            self._drop((algo, int(lid), key))

    def invalidate_slots(self, algo: str, slots) -> None:
        """Slots being cleared/evicted: drop any entry tracking them."""
        with self.lock:
            for slot in slots:
                ek = self._by_slot.get((algo, int(slot)))
                if ek is not None:
                    self._drop(ek)

    def invalidate_all(self) -> None:
        with self.lock:
            n = len(self._entries)
            self._entries.clear()
            self._by_slot.clear()
            self._oracles.clear()
            self.invalidated += n
            if self._invalidated_c is not None and n:
                self._invalidated_c.add(n)

    # -- introspection --------------------------------------------------------
    def pending_confirms(self) -> int:
        """Forwarded mutations not yet device-confirmed, across tracked
        entries.  A host-served mutation is stamped at serve time but
        applied at dispatch time; callers that control the clock (tests,
        drills) quiesce this to zero before advancing it, so serve stamp
        == dispatch stamp and decisions stay bit-exact.  Under a live
        wall clock the skew is bounded by the flush deadline (sub-ms vs
        multi-second windows); a skewed op that does change a window or
        estimate is caught by its confirmation and the entry dropped."""
        with self.lock:
            return sum(e.unconfirmed for e in self._entries.values())

    def stats(self) -> Dict:
        with self.lock:
            return {
                "tracked": len(self._entries),
                "served": self.served,
                "rejects_served": self.rejects_served,
                "adopted": self.adopted,
                "invalidated": self.invalidated,
                "divergence": self.divergence,
            }

    def __len__(self) -> int:
        with self.lock:
            return len(self._entries)
