"""Host-side TTL cache — the Caffeine analog (C7 in SURVEY.md).

The reference builds a Caffeine cache with ``expireAfterWrite(localCacheTtl)``
and ``maximumSize(10000)`` (SlidingWindowRateLimiter.java:57-64) and uses it
as a *negative* cache: the last-seen count per key short-circuits repeat
rejections without touching Redis (SlidingWindowRateLimiter.java:93-100).

This implementation keeps the same contract — ``get_if_present`` /
``put`` / ``invalidate`` with expire-after-write semantics and a bounded
size (oldest-write eviction) — with an injectable millisecond clock so tests
control time deterministically.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable, Optional


def _wall_clock_ms() -> int:
    return time.time_ns() // 1_000_000


class TTLCache:
    """Bounded expire-after-write cache keyed by string."""

    def __init__(
        self,
        ttl_ms: int,
        max_size: int = 10_000,
        clock_ms: Callable[[], int] = _wall_clock_ms,
    ):
        if ttl_ms <= 0:
            raise ValueError("ttl_ms must be positive")
        if max_size <= 0:
            raise ValueError("max_size must be positive")
        self._ttl_ms = int(ttl_ms)
        self._max_size = int(max_size)
        self._clock_ms = clock_ms
        # key -> (value, write_deadline_ms); insertion order == write order.
        self._data: "OrderedDict[str, tuple]" = OrderedDict()
        self._lock = threading.Lock()

    def get_if_present(self, key: str):
        now = self._clock_ms()
        with self._lock:
            entry = self._data.get(key)
            if entry is None:
                return None
            value, deadline = entry
            if now >= deadline:
                del self._data[key]
                return None
            return value

    def put(self, key: str, value) -> None:
        now = self._clock_ms()
        with self._lock:
            if key in self._data:
                del self._data[key]
            self._data[key] = (value, now + self._ttl_ms)
            while len(self._data) > self._max_size:
                self._data.popitem(last=False)

    def invalidate(self, key: str) -> None:
        with self._lock:
            self._data.pop(key, None)

    def invalidate_all(self) -> None:
        with self._lock:
            self._data.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)
