import time, numpy as np
from ratelimiter_tpu import RateLimitConfig
from ratelimiter_tpu.algorithms import SlidingWindowRateLimiter, TokenBucketRateLimiter
from ratelimiter_tpu.metrics import MeterRegistry
from ratelimiter_tpu.storage import TpuBatchedStorage

rng = np.random.default_rng(7)
storage = TpuBatchedStorage(num_slots=1 << 21)
tb = TokenBucketRateLimiter(storage, RateLimitConfig(max_permits=100, window_ms=60_000, refill_rate=50.0), MeterRegistry())
sw = SlidingWindowRateLimiter(storage, RateLimitConfig(max_permits=100, window_ms=60_000, enable_local_cache=False), MeterRegistry())

B, K = 1 << 19, 8
n = B * K * 2
for name, lim in (("tb", tb), ("sw", sw)):
    key_ids = rng.integers(0, 1_000_000, n)
    lim.try_acquire_stream_ids(key_ids[:B * K], batch=B, subbatches=K)  # compile
    for rep in range(4):
        t0 = time.perf_counter()
        lim.try_acquire_stream_ids(key_ids, batch=B, subbatches=K)
        dt = time.perf_counter() - t0
        print(f"{name} rep{rep}: {n/dt/1e6:.2f}M/s", flush=True)
storage.close()
