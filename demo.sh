#!/usr/bin/env bash
# End-to-end demo driver for ratelimiter_tpu (C17 parity: the reference ships
# a 6-scenario curl walkthrough; this is the same idea against our service).
#
# Usage: ./demo.sh [BASE_URL]     (default http://localhost:8080)
# Start the server first:  python -m ratelimiter_tpu.service.app

set -euo pipefail
BASE="${1:-http://localhost:8080}"

say()  { printf '\n\033[1;36m== %s ==\033[0m\n' "$*"; }
call() { curl -s -w '\n  -> HTTP %{http_code}\n' "$@"; }

say "0. Health"
call "$BASE/api/health"

say "1. Standard API traffic (sliding window, 100/min) as user demo-1"
for i in 1 2 3; do
  call -H 'X-User-ID: demo-1' "$BASE/api/data"
done

say "2. Anonymous traffic shares one key"
call "$BASE/api/data"

say "3. Brute-force protection (auth, 10/min): 11th login attempt is 429"
for i in $(seq 1 11); do
  code=$(curl -s -o /dev/null -w '%{http_code}' -X POST \
    -H 'Content-Type: application/json' \
    -d '{"username":"attacker"}' "$BASE/api/login")
  printf '  attempt %2d -> %s\n' "$i" "$code"
done

say "4. Burst batch (token bucket, cap 50, 10/sec refill)"
call -X POST -H 'X-User-ID: batch-user' -H 'Content-Type: application/json' \
  -d '{"size":40}' "$BASE/api/batch"
echo "  ...second burst of 40 should be rejected (only ~10 tokens left):"
call -X POST -H 'X-User-ID: batch-user' -H 'Content-Type: application/json' \
  -d '{"size":40}' "$BASE/api/batch"

say "5. Admin reset clears all limiters for a user"
call -X DELETE "$BASE/api/admin/reset/attacker"
echo "  ...attacker can log in again:"
call -X POST -H 'Content-Type: application/json' \
  -d '{"username":"attacker"}' "$BASE/api/login"

say "6. Observability"
call "$BASE/actuator/health"
call "$BASE/actuator/metrics"

say "demo complete"
