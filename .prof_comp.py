import time, numpy as np, jax, jax.numpy as jnp
from functools import partial

B = 1 << 20
N = 1 << 21
rng = np.random.default_rng(0)
slots = jnp.asarray(rng.integers(0, N, B).astype(np.int32))
vals64 = jnp.asarray(rng.integers(0, 1 << 40, B).astype(np.int64))
state = jnp.zeros((N,), jnp.int64)
R = 20

def timed(name, fn, *args):
    out = fn(*args)
    s = np.asarray(jax.tree_util.tree_leaves(out)[0])  # force
    t0 = time.perf_counter()
    out = fn(*args)
    s = np.asarray(jax.tree_util.tree_leaves(out)[0])
    dt = time.perf_counter() - t0
    print(f"{name:42s} {(dt - 0.11)/R*1e3:8.1f} ms/iter (total {dt:.2f}s)", flush=True)

@jax.jit
def loop_sort(x):
    def body(i, x):
        return jnp.argsort(x, stable=True).astype(jnp.int32)
    return jnp.sum(jax.lax.fori_loop(0, R, body, x))

@jax.jit
def loop_sort_unstable(x):
    def body(i, x):
        return jnp.argsort(x).astype(jnp.int32)
    return jnp.sum(jax.lax.fori_loop(0, R, body, x))

@jax.jit
def loop_scan64(x):
    def body(i, x):
        return jax.lax.associative_scan(jnp.add, x)
    return jnp.sum(jax.lax.fori_loop(0, R, body, x))

@jax.jit
def loop_scan32(x):
    x = x.astype(jnp.int32)
    def body(i, x):
        return jax.lax.associative_scan(jnp.add, x)
    return jnp.sum(jax.lax.fori_loop(0, R, body, x))

@jax.jit
def loop_cumsum64(x):
    def body(i, x):
        return jnp.cumsum(x)
    return jnp.sum(jax.lax.fori_loop(0, R, body, x))

@jax.jit
def loop_gather_scatter(st, idx):
    def body(i, st):
        v = st[idx] + 1
        return st.at[idx].set(v)
    return jnp.sum(jax.lax.fori_loop(0, R, body, st))

@jax.jit
def loop_take64(x, idx):
    def body(i, x):
        return x[idx]
    return jnp.sum(jax.lax.fori_loop(0, R, body, x))

timed("argsort stable i32[1M]", loop_sort, slots)
timed("argsort unstable i32[1M]", loop_sort_unstable, slots)
timed("assoc_scan add i64[1M]", loop_scan64, vals64)
timed("assoc_scan add i32[1M]", loop_scan32, vals64)
timed("cumsum i64[1M]", loop_cumsum64, vals64)
timed("gather+scatter i64[2M] by i32[1M]", loop_gather_scatter, state, slots)
timed("take i64[1M] by perm", loop_take64, vals64, slots % B)
