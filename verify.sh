#!/usr/bin/env bash
# Repo-structure verifier (C17 parity: required/forbidden file lint).
set -uo pipefail
cd "$(dirname "$0")"

required=(
  ratelimiter_tpu/__init__.py
  ratelimiter_tpu/core/config.py
  ratelimiter_tpu/core/limiter.py
  ratelimiter_tpu/semantics/oracle.py
  ratelimiter_tpu/ops/segments.py
  ratelimiter_tpu/ops/sliding_window.py
  ratelimiter_tpu/ops/token_bucket.py
  ratelimiter_tpu/engine/state.py
  ratelimiter_tpu/engine/engine.py
  ratelimiter_tpu/engine/slots.py
  ratelimiter_tpu/engine/batcher.py
  ratelimiter_tpu/parallel/sharded.py
  ratelimiter_tpu/storage/base.py
  ratelimiter_tpu/storage/memory.py
  ratelimiter_tpu/storage/tpu.py
  ratelimiter_tpu/algorithms/sliding_window.py
  ratelimiter_tpu/algorithms/token_bucket.py
  ratelimiter_tpu/cache/ttl_cache.py
  ratelimiter_tpu/metrics/registry.py
  ratelimiter_tpu/service/app.py
  ratelimiter_tpu/service/wiring.py
  ratelimiter_tpu/service/props.py
  tests/conftest.py
  bench.py
  __graft_entry__.py
  demo.sh
  Dockerfile
  docker-compose.yml
  SURVEY.md
  README.md
)

forbidden=(
  "*.pyc.orig"
  "*.java"
  ".ipynb_checkpoints"
)

fail=0
echo "checking required files..."
for f in "${required[@]}"; do
  if [[ -e "$f" ]]; then
    echo "  ok  $f"
  else
    echo "  MISSING  $f"
    fail=1
  fi
done

echo "checking forbidden patterns..."
for pat in "${forbidden[@]}"; do
  hits=$(find . -path ./.git -prune -o -name "$pat" -print | head -5)
  if [[ -n "$hits" ]]; then
    echo "  FORBIDDEN  $pat:"
    echo "$hits" | sed 's/^/    /'
    fail=1
  else
    echo "  ok  no $pat"
  fi
done

echo "running fast failover drill (replication)..."
if timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_replication.py::test_failover_drill_fast \
    -q -p no:cacheprovider; then
  echo "  ok  failover drill"
else
  echo "  FAILED  failover drill"
  fail=1
fi

echo "running fast one-shard-of-N failover drill (shard-aware replication)..."
if timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_shard_replication.py::test_shard_failover_drill_fast \
    -q -p no:cacheprovider; then
  echo "  ok  shard failover drill"
else
  echo "  FAILED  shard failover drill"
  fail=1
fi

echo "running replication overhead gate (elected journal <= 2% of hot path)..."
if timeout -k 10 600 env JAX_PLATFORMS=cpu python \
    bench/replication_overhead.py --n 2097152 --rounds 5 \
    --assert-budget 0.02 > /dev/null; then
  echo "  ok  replication overhead budget"
else
  echo "  FAILED  replication overhead budget (journal marks cost more"
  echo "          than 2% of the headline decision path)"
  fail=1
fi

echo "running observability overhead gate (full layer incl. telemetry plane + usage ring <= 2% of hot path)..."
if timeout -k 10 600 env JAX_PLATFORMS=cpu python \
    bench/observability_overhead.py --n 2097152 --rounds 5 \
    --assert-budget 0.02 --assert-leased-ratio 0.4 > /dev/null; then
  echo "  ok  observability overhead budget + leased telemetry ratio"
else
  echo "  FAILED  observability overhead budget (stage timers + trace +"
  echo "          flight recorder + fleet telemetry/usage ring cost more"
  echo "          than 2% of the headline stream, the leased client's"
  echo "          telemetry-on throughput fell below 0.4x the off"
  echo "          baseline, or sampled latency stamping stopped beating"
  echo "          the per-burn perf_counter pair)"
  fail=1
fi

echo "running local latency SLO gate (p99 <= 1 ms on CPU, assembly not dominant)..."
if timeout -k 10 600 env JAX_PLATFORMS=cpu python \
    bench/local_latency_slo.py --assert-meets > /dev/null; then
  echo "  ok  local latency SLO (sub-ms p99, assembly stage demoted)"
else
  echo "  FAILED  local latency SLO (p99 over 1 ms, or assembly is"
  echo "          again the dominant lifecycle stage — see the bench's"
  echo "          stderr decomposition)"
  fail=1
fi

echo "running orchestrated failover + flap drills (self-healing, zero manual promotes)..."
if timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_orchestrator.py::test_orchestrated_failover_drill_fast \
    tests/test_orchestrator.py::test_orchestrator_flap_drill_fast \
    -q -p no:cacheprovider; then
  echo "  ok  orchestrated failover + flap drills"
else
  echo "  FAILED  orchestrated failover + flap drills"
  fail=1
fi

echo "running cross-host failover drill (real subprocesses, partitions, fence lease)..."
if timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_cross_host.py::test_cross_host_failover_drill_fast \
    -q -p no:cacheprovider; then
  echo "  ok  cross-host failover drill"
else
  echo "  FAILED  cross-host failover drill (a partitioned primary out-"
  echo "          lived its serving lease, the witness failed to veto a"
  echo "          false fencing, the remote promotion broke bit-identity,"
  echo "          or a zombie-era token lease was honored across the"
  echo "          promotion boundary)"
  fail=1
fi

echo "running rolling-upgrade drill (fleet autopilot, zero-loss node replacement)..."
if timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_fleet.py::test_rolling_upgrade_drill_fast \
    -q -p no:cacheprovider; then
  echo "  ok  rolling-upgrade drill"
else
  echo "  FAILED  rolling-upgrade drill (a node replacement lost a"
  echo "          decision, the autopilot failed to re-seed the cell"
  echo "          back to N+1 inside its deadline, or the mid-upgrade"
  echo "          kill's promotion raced the dead node's serving lease)"
  fail=1
fi

echo "running fast lease failover drill (leases honored-or-revoked, bounded over-admission)..."
if timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_leases.py::test_lease_failover_drill_fast \
    -q -p no:cacheprovider; then
  echo "  ok  lease failover drill"
else
  echo "  FAILED  lease failover drill (a leased client or a promoted"
  echo "          standby broke the over-admission bound, or the"
  echo "          reserve/credit replay diverged from the oracle)"
  fail=1
fi

echo "running lease loopback gate (>= 10x wire-frame reduction + telemetry reconciliation)..."
if timeout -k 10 600 env JAX_PLATFORMS=cpu python bench/lease_loopback.py \
    --assert-ratio > /dev/null; then
  echo "  ok  lease wire-frame reduction + fleet-counter reconciliation"
else
  echo "  FAILED  lease loopback (fewer than 10x frames saved per decision"
  echo "          vs the per-decision v2 path, leased throughput below the"
  echo "          v2 baseline, fleet decision counters not reconciling with"
  echo "          client ground truth, or a leased trace missing its"
  echo "          client->sidecar->batcher->shard lineage)"
  fail=1
fi

echo "running fast aggregator failover drill (bulk leases, scoped revocation, nested bound)..."
if timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_edge.py::test_aggregator_failover_drill_fast \
    -q -p no:cacheprovider; then
  echo "  ok  aggregator failover drill"
else
  echo "  FAILED  aggregator failover drill (burns after an aggregator death"
  echo "          escaped the dropped bulk budgets, a shard promotion revoked"
  echo "          a survivor-shard pool, the aggregator/core over-admission"
  echo "          folds diverged, or the replay diverged from the oracle)"
  fail=1
fi

echo "running aggregator loopback gate (>= 5x frame collapse vs direct leases on shared keys)..."
if timeout -k 10 600 env JAX_PLATFORMS=cpu python bench/lease_loopback.py \
    --assert-ratio --aggregator > /dev/null; then
  echo "  ok  aggregator frame collapse (zero admission mismatches both arms)"
else
  echo "  FAILED  aggregator loopback (fewer than 5x frames saved per decision"
  echo "          vs the direct-lease arm on the same shared hot keys, or an"
  echo "          admission mismatch in either arm)"
  fail=1
fi

echo "running tenant-storm gate (adaptive limits hold goodput where static collapse)..."
if timeout -k 10 600 env JAX_PLATFORMS=cpu python bench/tenant_storm.py \
    --assert-adaptive > /dev/null; then
  echo "  ok  tenant storm (adaptive >= 0.8x pre-storm goodput, static below,"
  echo "      decisions bit-identical to the generation-aware oracle)"
else
  echo "  FAILED  tenant storm (adaptive limits failed to hold well-behaved"
  echo "          goodput in the 0.8x band through the storm, the static arm"
  echo "          did not collapse, no recovery was observed, or a decision"
  echo "          diverged from the generation-aware oracle)"
  fail=1
fi

echo "running control-plane overhead gate (controller tick + generation checks <= 2%)..."
if timeout -k 10 600 env JAX_PLATFORMS=cpu python bench/control_overhead.py \
    --assert-budget 0.02 > /dev/null; then
  echo "  ok  control-plane overhead budget"
else
  echo "  FAILED  control-plane overhead budget (a converged controller's"
  echo "          tick sweep + per-grant generation checks cost more than"
  echo "          2% of steady-state CPU at the configured cadence)"
  fail=1
fi

echo "running fleet control-plane overhead gate (elected leader over control RPC <= 2%)..."
if timeout -k 10 600 env JAX_PLATFORMS=cpu python bench/control_overhead.py \
    --fleet --assert-budget 0.02 > /dev/null; then
  echo "  ok  fleet control-plane overhead budget"
else
  echo "  FAILED  fleet control-plane overhead budget (the fleet cadence —"
  echo "          majority seat renewal + fleet-summed signals sweep +"
  echo "          the AIMD pass over real control-RPC members — costs"
  echo "          more than 2% of steady-state CPU)"
  fail=1
fi

echo "running partitioned-controller drill (epoch-fenced leadership, zero zombie writes)..."
if timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_fleet_control.py::test_partitioned_controller_drill_fast \
    -q -p no:cacheprovider; then
  echo "  ok  partitioned-controller drill"
else
  echo "  FAILED  partitioned-controller drill (a partitioned leader's"
  echo "          policy write landed after its epoch was superseded, the"
  echo "          standby failed to take over inside the detection budget,"
  echo "          the fleet did not converge to one policy generation, a"
  echo "          decision diverged from the generation-aware oracle, or"
  echo "          storm goodput fell below 0.8x pre-storm)"
  fail=1
fi

echo "running orchestrator idle overhead gate (RPC probe path <= 2% steady-state)..."
if timeout -k 10 600 env JAX_PLATFORMS=cpu python \
    bench/orchestrator_overhead.py --n 1048576 --rounds 3 --probe-rpc \
    --assert-budget 0.02 > /dev/null; then
  echo "  ok  orchestrator idle overhead budget (control-RPC probes)"
else
  echo "  FAILED  orchestrator idle overhead budget (the probe loop —"
  echo "          one control-RPC round trip per node per tick — costs"
  echo "          more than 2% steady-state CPU at its cadence)"
  fail=1
fi

echo "running fleet manager idle overhead gate (probe loop <= 2% steady-state)..."
if timeout -k 10 600 env JAX_PLATFORMS=cpu python \
    bench/fleet_overhead.py --assert-budget 0.02 > /dev/null; then
  echo "  ok  fleet manager idle overhead budget"
else
  echo "  FAILED  fleet manager idle overhead budget (the NodeManager's"
  echo "          probe loop — one muxed probe_all RPC per node per tick"
  echo "          — costs more than 2% steady-state CPU at its cadence)"
  fail=1
fi

echo "running fast overload + breaker chaos drills..."
if timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_overload.py::test_overload_drill_fast \
    tests/test_breaker.py::test_outage_drill_fast \
    -q -p no:cacheprovider; then
  echo "  ok  overload + outage drills"
else
  echo "  FAILED  overload + outage drills"
  fail=1
fi

echo "running fast ingress drill (sidecar chaos)..."
if timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_sidecar_chaos.py::test_ingress_drill_fast \
    -q -p no:cacheprovider; then
  echo "  ok  ingress drill"
else
  echo "  FAILED  ingress drill"
  fail=1
fi

echo "running hardened sidecar loopback ratio (>= 0.9x unhardened; v5 columnar >= 0.9x v4)..."
if timeout -k 10 600 env JAX_PLATFORMS=cpu python bench/sidecar_loopback.py \
    --assert-ratio > /dev/null; then
  echo "  ok  hardened loopback throughput + v5 columnar floor"
else
  echo "  FAILED  hardened loopback throughput (ingress hardening costs"
  echo "          more than 10% of the unhardened baseline, or the v5"
  echo "          columnar batch path fell below 0.9x of the v4"
  echo "          per-request frame path on the same server shape)"
  fail=1
fi

echo "running coalesce smoke gate (coalesced >= 1.0x uncoalesced on Zipf, 0 oracle mismatches)..."
if timeout -k 10 600 env JAX_PLATFORMS=cpu python bench/coalesce_smoke.py \
    --assert-ratio > /dev/null; then
  echo "  ok  Zipf key coalescing (faster than the scan it replaces, bit-identical)"
else
  echo "  FAILED  coalesce smoke (the coalesced digest lost to the"
  echo "          rank-major scan on repeat-heavy Zipf traffic, or a"
  echo "          coalesced decision diverged from the sequential"
  echo "          oracle replay)"
  fail=1
fi

echo "running chaos conductor gate (seeded multi-fault schedules, zero invariant violations)..."
if timeout -k 10 600 env JAX_PLATFORMS=cpu python bench/chaos_soak.py \
    --seeds 3 --assert-invariants > /tmp/_chaos_soak.log 2>&1; then
  echo "  ok  chaos conductor (oracle bit-identity, lease/pool conservation,"
  echo "      admission bound, epoch monotonicity, liveness — all held)"
else
  echo "  FAILED  chaos conductor (an invariant broke under a seeded fault"
  echo "          schedule; the minimized replayable artifact path is below —"
  echo "          re-run it with: python -m ratelimiter_tpu.chaos.replay"
  echo "          --artifact <path>)"
  tail -20 /tmp/_chaos_soak.log | sed 's/^/    /'
  fail=1
fi

echo "regenerating CAPABILITIES.md test/LoC counts..."
if python bench/gen_capabilities.py; then
  echo "  ok  capability counts"
else
  echo "  FAILED  capability count generation"
  fail=1
fi

echo "running perf smokes (sharded 1/2/4/8 monotonicity + relay election)..."
if timeout -k 10 1800 python bench/perf_smoke.py; then
  echo "  ok  perf smokes"
else
  echo "  FAILED  perf smokes (sharded scaling inversion on the 1/2/4/8"
  echo "          curve, or an election picked a measured-slower backend)"
  fail=1
fi

if [[ "${RUN_SLOW:-0}" == "1" ]]; then
  echo "running full tenant-storm soak (RUN_SLOW=1)..."
  if timeout -k 10 900 env JAX_PLATFORMS=cpu python bench/tenant_storm.py \
      --assert-adaptive --soak > /dev/null; then
    echo "  ok  tenant-storm soak"
  else
    echo "  FAILED  tenant-storm soak"
    fail=1
  fi
  echo "running slow failover + overload + outage + ingress soaks (RUN_SLOW=1)..."
  if timeout -k 10 900 env JAX_PLATFORMS=cpu python -m pytest \
      tests/test_replication.py::test_failover_soak_slow \
      tests/test_shard_replication.py::test_shard_failover_soak_slow \
      tests/test_orchestrator.py::test_orchestrator_soak_slow \
      tests/test_cross_host.py::test_cross_host_soak_slow \
      tests/test_fleet.py::test_rolling_upgrade_soak_slow \
      tests/test_overload.py::test_overload_soak_slow \
      tests/test_breaker.py::test_outage_soak_slow \
      tests/test_sidecar_chaos.py::test_ingress_soak_slow \
      tests/test_edge.py::test_edgeproc_subprocess_ready_and_eof_shutdown \
      -q -m slow -p no:cacheprovider; then
    echo "  ok  slow soaks"
  else
    echo "  FAILED  slow soaks"
    fail=1
  fi
  echo "running long chaos soak (RUN_SLOW=1: 6 seeds x 48 steps, both edge topologies)..."
  if timeout -k 10 1800 env JAX_PLATFORMS=cpu python bench/chaos_soak.py \
      --seeds 6 --soak --assert-invariants > /tmp/_chaos_soak_slow.log 2>&1; then
    echo "  ok  chaos soak"
  else
    echo "  FAILED  chaos soak (minimized replayable artifact path below)"
    tail -20 /tmp/_chaos_soak_slow.log | sed 's/^/    /'
    fail=1
  fi
else
  echo "skipping slow soaks (set RUN_SLOW=1 to run them)"
fi

if [[ $fail -eq 0 ]]; then
  echo "structure OK"
else
  echo "structure FAILED"
fi
exit $fail
