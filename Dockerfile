# ratelimiter_tpu service image (C16 parity: two-stage like the reference's
# maven -> JRE build — here a g++ stage compiles the native slot index and a
# slim runtime serves; jit "compilation" happens at boot warmup and persists
# via the compilation cache).
#
# For TPU hosts, swap the base image for one with libtpu and run with
# --privileged (or the TPU device plugin under Kubernetes).

FROM python:3.12-slim AS native-build
RUN apt-get update && apt-get install -y --no-install-recommends g++ make \
    && rm -rf /var/lib/apt/lists/*
WORKDIR /build
COPY native/ native/
RUN make -B -C native ARCH=x86-64-v2

FROM python:3.12-slim

RUN useradd --create-home ratelimiter
WORKDIR /app

# jax[cpu] serves the CPU fallback; on TPU VMs the host-provided jax/libtpu
# is mounted instead.
RUN pip install --no-cache-dir "jax[cpu]" numpy

COPY ratelimiter_tpu/ ratelimiter_tpu/
COPY --from=native-build /build/native/libslotindex.so native/
COPY application.properties .

USER ratelimiter
EXPOSE 8080

HEALTHCHECK --interval=10s --timeout=3s --retries=3 \
  CMD python -c "import urllib.request,sys; \
    sys.exit(0 if b'UP' in urllib.request.urlopen('http://localhost:8080/api/health', timeout=2).read() else 1)"

CMD ["python", "-m", "ratelimiter_tpu.service.app", "application.properties"]
