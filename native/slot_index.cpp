// Native slot index: key -> slot assignment with LRU eviction.
//
// The host-side hot path of the TPU rate limiter: every decision needs a
// key -> slot lookup before it can join a device batch.  The pure-Python
// index (ratelimiter_tpu/engine/slots.py — the semantic reference for this
// file) tops out around 1-2M ops/s; this open-addressing table with an
// intrusive LRU list sustains tens of millions, keeping the host from
// starving the device.
//
// Design:
//  - 128-bit key fingerprints (two independent FNV-1a streams) instead of
//    stored keys: collision odds ~n^2/2^129 (~1e-25 at 10M keys).  Both
//    string keys and int64 ids are supported; a per-limiter `lid` seed is
//    mixed in so tenants are isolated.
//  - Open addressing, linear probing, power-of-two capacity, tombstone-free
//    deletion (backward-shift), load factor <= 0.5.
//  - Intrusive doubly-linked LRU over the entries; eviction returns the
//    victim's slot so the caller can zero its device state before reuse.
//    Recency is BATCH-GRANULAR by design: all hits of a key within one
//    batch-assign call count as one touch (at its first occurrence), so
//    repeat hits skip the 3-cache-line LRU re-link — the dominant host
//    cost on Zipf traffic.  Keys touched in the same batch are equally
//    "recent" for eviction purposes (the same resolution trade Redis
//    makes with its sampled LRU); the Python index documents the same
//    contract for its scalar path, where every call is its own batch.
//  - Pinning: (a) an explicit pin refcount per slot for queued async
//    requests, (b) a generation stamp so entries touched by the current
//    batch call are never evicted by later keys of the same batch.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in the image).

#include <algorithm>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <vector>

#if defined(__linux__)
#include <sys/mman.h>
#endif

namespace {

// Exactly 32 bytes, 32-aligned: two entries per cache line with no
// straddle, so the home-bucket probe touches ONE line.  gen is u32 (a
// per-batch counter compared only for equality with the current batch;
// a wrap after 2^32 batches can at worst skip one LRU re-link or
// eviction candidate once — recency noise, not a correctness hazard).
struct alignas(32) Entry {
  uint64_t h1 = 0, h2 = 0;  // 128-bit fingerprint; h1==0 && h2==0 => empty
  int32_t slot = -1;
  int32_t lru_prev = -1, lru_next = -1;
  uint32_t gen = 0;
};

struct Index {
  int64_t num_slots;
  uint64_t mask;              // table size - 1
  std::vector<Entry> table;
  std::vector<int32_t> entry_of_slot;  // slot -> table position (-1 if free)
  std::vector<int32_t> free_slots;
  std::vector<uint32_t> pins;          // slot -> pin refcount
  // Slots removed (admin reset) while their pin refcount was nonzero:
  // freeing them immediately would let a new key take the slot before the
  // pinned dispatch enqueues, receiving its stale write.  They are flagged
  // here and surface on the dirty list at last unpin; reassignment reports
  // them as their own eviction so the caller re-clears device state first.
  std::vector<uint8_t> deferred;       // slot -> removed-while-pinned flag
  std::vector<int32_t> dirty_free;     // unpinned deferred slots (need clear)
  int64_t size = 0;
  int32_t lru_head = -1, lru_tail = -1;  // head = most recent
  uint64_t gen = 0;
  // Scratch for the relay path (assign_batch_uniques): per-slot duplicate
  // counters for the current batch, epoch-tagged so no per-batch reset is
  // needed.  One 16-byte struct per slot (not parallel arrays) so the
  // rank loop costs a single cache-line touch per request, which pass 2
  // prefetches ahead from the already-resolved slot ids.  Allocated
  // lazily on the first uniques call.
  struct BatchScratch {
    uint64_t epoch = 0;   // last batch generation seen
    int32_t cnt = 0;      // occurrences so far this batch
    int32_t uidx = -1;    // dense unique index this batch
  };
  std::vector<BatchScratch> batch;
  std::vector<int32_t> ucnt;           // dense per-unique occurrence counts
  // Within-batch front cache: repeat hits of a key inside one batch call
  // (most of Zipf traffic) resolve from this cache-resident direct-mapped
  // table instead of re-probing the DRAM hash table.  Safe because a hit
  // is only honored when the line was verified under the CURRENT batch
  // generation — and current-generation entries are eviction-protected,
  // so the cached slot cannot have been reassigned mid-batch.  One
  // 32-byte struct per line (not parallel arrays): a hit touches ONE
  // cache line, and the line carries the batch-dense unique index so the
  // fused uniques walk never touches the slot-indexed scratch on hits.
  struct FcLine {
    uint64_t h1 = 0, h2 = 0;
    uint64_t gen = 0;
    int32_t slot = -1;
    int32_t uidx = -1;
  };
  std::vector<FcLine> fc;
};

const uint64_t kFrontCacheSize = 1 << 17;  // 128K lines, 4 MB

static void advise_huge(void* p, size_t bytes) {
  // The probe is one random DRAM access per request; at 10M+ slots the
  // table spans hundreds of MB and 4K-page TLB misses double its cost.
  // Transparent huge pages are advisory — failure is fine.  madvise
  // rejects non-page-aligned starts with EINVAL, and heap pointers are
  // rarely page-aligned, so round the range inward first.
#if defined(__linux__) && defined(MADV_HUGEPAGE)
  const uintptr_t kPage = 4096;
  uintptr_t start = (reinterpret_cast<uintptr_t>(p) + kPage - 1) & ~(kPage - 1);
  uintptr_t end = (reinterpret_cast<uintptr_t>(p) + bytes) & ~(kPage - 1);
  if (end > start && end - start >= (2u << 20))
    madvise(reinterpret_cast<void*>(start), end - start, MADV_HUGEPAGE);
#else
  (void)p;
  (void)bytes;
#endif
}

inline void fnv_mix(uint64_t& h, uint64_t x) {
  h ^= x;
  h *= 0x100000001b3ULL;
}

inline void hash_bytes(const uint8_t* p, int64_t n, uint64_t seed,
                       uint64_t& h1, uint64_t& h2) {
  h1 = 0xcbf29ce484222325ULL ^ seed;
  h2 = 0x84222325cbf29ce4ULL ^ (seed * 0x9e3779b97f4a7c15ULL);
  for (int64_t i = 0; i < n; i++) {
    fnv_mix(h1, p[i]);
    h2 = (h2 ^ (p[i] + 0x9e3779b97f4a7c15ULL + (h2 << 6) + (h2 >> 2)));
  }
  h2 = h2 * 0xff51afd7ed558ccdULL + n;
  if (h1 == 0 && h2 == 0) h2 = 1;  // reserve (0,0) for "empty"
}

inline void hash_int(int64_t key, uint64_t seed, uint64_t& h1, uint64_t& h2) {
  uint64_t x = static_cast<uint64_t>(key) + seed * 0x9e3779b97f4a7c15ULL;
  // splitmix64 twice for two independent streams
  uint64_t z = (x += 0x9e3779b97f4a7c15ULL);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  h1 = z ^ (z >> 31);
  z = (x += 0x9e3779b97f4a7c15ULL);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  h2 = z ^ (z >> 31);
  if (h1 == 0 && h2 == 0) h2 = 1;
}

// -- LRU helpers -------------------------------------------------------------

inline uint32_t gen32(const Index* ix) {
  return static_cast<uint32_t>(ix->gen);
}

inline void lru_unlink(Index* ix, int32_t pos) {
  Entry& e = ix->table[pos];
  if (e.lru_prev >= 0) ix->table[e.lru_prev].lru_next = e.lru_next;
  else ix->lru_head = e.lru_next;
  if (e.lru_next >= 0) ix->table[e.lru_next].lru_prev = e.lru_prev;
  else ix->lru_tail = e.lru_prev;
  e.lru_prev = e.lru_next = -1;
}

inline void lru_push_front(Index* ix, int32_t pos) {
  Entry& e = ix->table[pos];
  e.lru_prev = -1;
  e.lru_next = ix->lru_head;
  if (ix->lru_head >= 0) ix->table[ix->lru_head].lru_prev = pos;
  ix->lru_head = pos;
  if (ix->lru_tail < 0) ix->lru_tail = pos;
}

inline void lru_touch(Index* ix, int32_t pos) {
  if (ix->lru_head == pos) return;
  lru_unlink(ix, pos);
  lru_push_front(ix, pos);
}

// -- table ops ---------------------------------------------------------------

inline int32_t find(Index* ix, uint64_t h1, uint64_t h2) {
  uint64_t pos = h1 & ix->mask;
  while (true) {
    Entry& e = ix->table[pos];
    if (e.h1 == 0 && e.h2 == 0) return -1;
    if (e.h1 == h1 && e.h2 == h2) return static_cast<int32_t>(pos);
    pos = (pos + 1) & ix->mask;
  }
}

// Backward-shift deletion keeps probe chains intact without tombstones.
inline void erase_at(Index* ix, uint64_t pos) {
  uint64_t hole = pos;
  uint64_t next = (hole + 1) & ix->mask;
  while (true) {
    Entry& e = ix->table[next];
    if (e.h1 == 0 && e.h2 == 0) break;
    uint64_t home = e.h1 & ix->mask;
    // Can e move into the hole? Yes iff hole lies within [home, next).
    bool movable = ((next - home) & ix->mask) >= ((next - hole) & ix->mask);
    if (movable) {
      // Fix LRU links & slot back-pointer to the new position.
      int32_t np = static_cast<int32_t>(next), hp = static_cast<int32_t>(hole);
      if (e.lru_prev >= 0) ix->table[e.lru_prev].lru_next = hp;
      else ix->lru_head = hp;
      if (e.lru_next >= 0) ix->table[e.lru_next].lru_prev = hp;
      else ix->lru_tail = hp;
      ix->entry_of_slot[e.slot] = hp;
      ix->table[hole] = e;
      e = Entry{};
      hole = next;
      (void)np;
    }
    next = (next + 1) & ix->mask;
  }
  ix->table[hole] = Entry{};
}

inline int32_t insert(Index* ix, uint64_t h1, uint64_t h2, int32_t slot) {
  uint64_t pos = h1 & ix->mask;
  while (true) {
    Entry& e = ix->table[pos];
    if (e.h1 == 0 && e.h2 == 0) {
      e.h1 = h1; e.h2 = h2; e.slot = slot;
      e.gen = gen32(ix);
      ix->entry_of_slot[slot] = static_cast<int32_t>(pos);
      lru_push_front(ix, static_cast<int32_t>(pos));
      ix->size++;
      return static_cast<int32_t>(pos);
    }
    pos = (pos + 1) & ix->mask;
  }
}

// Returns evicted slot (>= 0) or -1 if a free slot was available, -2 if
// eviction failed (everything pinned).
inline int64_t take_slot(Index* ix, int32_t* out_slot) {
  if (!ix->free_slots.empty()) {
    *out_slot = ix->free_slots.back();
    ix->free_slots.pop_back();
    return -1;
  }
  // Dirty free slots (removed while pinned, since unpinned) may carry a
  // stale write from the formerly-pinned dispatch: hand them out as their
  // own "eviction" so the caller zeroes the device state before reuse.
  // A dirty slot can have been RE-pinned since it was listed (a queued
  // micro-batch request pinned via the per-call set) — skip those, exactly
  // as the LRU eviction scan below does.  The list is tiny (admin resets
  // racing streams), so the scan is O(few).
  for (size_t i = ix->dirty_free.size(); i-- > 0;) {
    int32_t slot = ix->dirty_free[i];
    if (ix->pins[slot] == 0) {
      ix->dirty_free.erase(ix->dirty_free.begin() + i);
      *out_slot = slot;
      return slot;
    }
  }
  // Evict from LRU tail, skipping pinned and current-generation entries.
  int32_t pos = ix->lru_tail;
  while (pos >= 0) {
    Entry& e = ix->table[pos];
    if (ix->pins[e.slot] == 0 && e.gen != gen32(ix)) {
      int32_t victim_slot = e.slot;
      lru_unlink(ix, pos);
      ix->entry_of_slot[victim_slot] = -1;
      erase_at(ix, static_cast<uint64_t>(pos));
      ix->size--;
      *out_slot = victim_slot;
      return victim_slot;
    }
    pos = e.lru_prev;
  }
  return -2;
}

// Probe-or-insert WITHOUT front-cache handling (callers manage the fc
// line themselves; the fused uniques walk writes it with the unique id).
inline int64_t probe_or_insert(Index* ix, uint64_t h1, uint64_t h2,
                               int32_t* out_slot) {
  int32_t pos = find(ix, h1, h2);
  if (pos >= 0) {
    Entry& e = ix->table[pos];
    // Repeat hit within the same batch generation: the entry is already
    // recency-stamped and eviction-protected; skip the LRU re-link (3
    // random cache lines).  Zipf batches repeat hot keys constantly, so
    // this removes most of the pointer chasing on the host hot path.
    if (e.gen != gen32(ix)) {
      e.gen = gen32(ix);
      lru_touch(ix, pos);
    }
    *out_slot = e.slot;
    return -1;
  }
  int32_t slot;
  int64_t evicted = take_slot(ix, &slot);
  if (evicted == -2) { *out_slot = -1; return -2; }
  insert(ix, h1, h2, slot);
  *out_slot = slot;
  return evicted;
}

inline int64_t assign_hashed(Index* ix, uint64_t h1, uint64_t h2,
                             int32_t* out_slot) {
  const uint64_t fci = h1 & (kFrontCacheSize - 1);
  if (!ix->fc.empty()) {
    Index::FcLine& L = ix->fc[fci];
    if (L.gen == ix->gen && L.h1 == h1 && L.h2 == h2) {
      // Repeat hit within this batch: already gen-stamped + LRU-touched.
      *out_slot = L.slot;
      return -1;
    }
  }
  int64_t evicted = probe_or_insert(ix, h1, h2, out_slot);
  if (evicted != -2 && !ix->fc.empty()) {
    Index::FcLine& L = ix->fc[fci];
    L.h1 = h1; L.h2 = h2; L.gen = ix->gen;
    L.slot = *out_slot; L.uidx = -1;
  }
  return evicted;
}

// One batch-assign loop for every key flavor (the hash functor is the
// only difference).  Chunked hash-then-prefetch-then-probe: the probe is
// DRAM-latency-bound, so home buckets are prefetched a chunk ahead.
const int kChunk = 32;

inline void ensure_fc(Index* ix) {
  if (ix->fc.empty()) {  // batch paths only; scalar calls skip the fc
    ix->fc.assign(kFrontCacheSize, Index::FcLine{});
    advise_huge(ix->fc.data(), ix->fc.size() * sizeof(Index::FcLine));
  }
}

template <typename HashAt>
inline void assign_batch(Index* ix, int64_t n, int32_t* out_slots,
                         int32_t* out_evicted, HashAt&& hash_at) {
  ensure_fc(ix);
  ix->gen++;
  uint64_t h1s[kChunk], h2s[kChunk];
  for (int64_t base = 0; base < n; base += kChunk) {
    int64_t m = n - base < kChunk ? n - base : kChunk;
    for (int64_t j = 0; j < m; j++) {
      hash_at(base + j, h1s[j], h2s[j]);
      __builtin_prefetch(&ix->fc[h1s[j] & (kFrontCacheSize - 1)], 1, 3);
      __builtin_prefetch(&ix->table[h1s[j] & ix->mask], 1, 1);
    }
    for (int64_t j = 0; j < m; j++) {
      int64_t ev = assign_hashed(ix, h1s[j], h2s[j], &out_slots[base + j]);
      out_evicted[base + j] = static_cast<int32_t>(ev);
    }
  }
}

// Unique-compaction variant (the segment-digest path): one uint32 word
// per UNIQUE slot of the batch — (slot << (rank_bits+1)) | (count << 1)
// with count clamped like the rank — plus per-request (unique-index,
// rank) scratch the caller keeps host-side to reconstruct per-request
// decisions from the device's per-unique allowed counts.  On skewed
// traffic this cuts host->device bytes by the duplicate factor.
// Returns the number of uniques (first-appearance order).
// FUSED probe + duplicate-structure walk: one pass over the requests.
// Front-cache hits (the bulk of skewed traffic) touch ONE fc cache line
// and one dense-ucnt cell — the slot-indexed scratch (tens of MB, a DRAM
// touch per request in the old two-pass layout) is consulted only on fc
// misses.  Within a chunk, requests are staged hits-then-misses; a key's
// requests always land in the SAME stage (the fc line is stable across a
// chunk's check loop), so per-segment rank order stays arrival order.
template <typename HashAt>
inline int64_t assign_batch_uniques(Index* ix, int64_t n, int32_t rank_bits,
                                    uint32_t* out_uwords, int32_t* out_uidx,
                                    int32_t* out_rank, int32_t* out_evicted,
                                    HashAt&& hash_at) {
  if (ix->batch.empty()) {
    ix->batch.assign(ix->num_slots, {});
    advise_huge(ix->batch.data(),
                ix->batch.size() * sizeof(Index::BatchScratch));
  }
  if (static_cast<int64_t>(ix->ucnt.size()) < n) ix->ucnt.resize(n);
  ensure_fc(ix);
  ix->gen++;
  const uint64_t epoch = ix->gen;
  const uint32_t rank_max = (1u << rank_bits) - 1;
  Index::BatchScratch* scratch = ix->batch.data();
  Index::FcLine* fc = ix->fc.data();
  int32_t* ucnt = ix->ucnt.data();
  int64_t u = 0;
  uint64_t h1s[kChunk], h2s[kChunk];
  int64_t misses[kChunk];
  for (int64_t base = 0; base < n; base += kChunk) {
    int64_t m = n - base < kChunk ? n - base : kChunk;
    for (int64_t j = 0; j < m; j++) {
      hash_at(base + j, h1s[j], h2s[j]);
      __builtin_prefetch(&fc[h1s[j] & (kFrontCacheSize - 1)], 1, 3);
    }
    // Stage 1: fc hits resolve immediately; misses queue with their
    // table bucket prefetched (the DRAM latency overlaps the rest of
    // the chunk instead of stalling per request).
    int64_t nm = 0;
    for (int64_t j = 0; j < m; j++) {
      const int64_t i = base + j;
      Index::FcLine& L = fc[h1s[j] & (kFrontCacheSize - 1)];
      if (L.gen == epoch && L.h1 == h1s[j] && L.h2 == h2s[j]) {
        out_evicted[i] = -1;
        out_uidx[i] = L.uidx;
        out_rank[i] = ucnt[L.uidx]++;
        continue;
      }
      __builtin_prefetch(&ix->table[h1s[j] & ix->mask], 1, 1);
      misses[nm++] = j;
    }
    // Stage 2: misses probe/insert the main table in arrival order.
    // 2a resolves every miss's table position (home bucket prefetched
    // in stage 1) while issuing prefetches for the strict-LRU relink
    // neighbors and the slot scratch that 2b will touch — the relink
    // is up to 3 random DRAM accesses that a serial loop pays at full
    // latency per request (the 10M-key uniform walk measured
    // ~198 ns/request, VERDICT r3 #3); overlapping them across the
    // chunk is the fix.  Recorded positions stay valid across pure
    // INSERTS (linear-probe insert fills an empty bucket and never
    // relocates existing entries) — only an EVICTION's backward-shift
    // erase can move entries, so 2b keeps using the staged positions
    // until the first eviction of the chunk and re-probes after (the
    // r5 code fell back to fully serial probe_or_insert for the WHOLE
    // chunk on any insert, which made first-touch churn passes lose
    // every prefetch the staged path buys — the scenario-4
    // churn-vs-steady gap).
    int32_t hitpos[kChunk];
    bool has_insert = false;
    const uint32_t g32 = gen32(ix);
    for (int64_t k = 0; k < nm; k++) {
      const int64_t j = misses[k];
      int32_t pos = find(ix, h1s[j], h2s[j]);
      hitpos[k] = pos;
      if (pos < 0) {
        has_insert = true;
        continue;
      }
      const Entry& e = ix->table[pos];
      if (e.gen != g32) {
        if (e.lru_prev >= 0)
          __builtin_prefetch(&ix->table[e.lru_prev], 1, 1);
        if (e.lru_next >= 0)
          __builtin_prefetch(&ix->table[e.lru_next], 1, 1);
      }
      __builtin_prefetch(&scratch[e.slot], 1, 1);
    }
    if (ix->lru_head >= 0)
      __builtin_prefetch(&ix->table[ix->lru_head], 1, 1);
    if (has_insert) {
      // First-touch staging: the inserts of this chunk will pop the
      // free-list tail in order (as long as no eviction interleaves),
      // so prefetch those slots' batch scratch + back-pointer lines
      // now; a wrong guess (eviction path taken instead) is harmless.
      const int64_t fs = static_cast<int64_t>(ix->free_slots.size());
      int64_t taken = 0;
      for (int64_t k = 0; k < nm && taken < fs; k++) {
        if (hitpos[k] >= 0) continue;
        int32_t s = ix->free_slots[fs - 1 - taken++];
        __builtin_prefetch(&scratch[s], 1, 1);
        __builtin_prefetch(&ix->entry_of_slot[s], 1, 1);
      }
    }
    bool positions_valid = true;
    for (int64_t k = 0; k < nm; k++) {
      const int64_t j = misses[k];
      const int64_t i = base + j;
      int32_t slot;
      int64_t ev;
      if (hitpos[k] >= 0 && positions_valid) {
        Entry& e = ix->table[hitpos[k]];
        if (e.gen != g32) {
          e.gen = g32;
          lru_touch(ix, hitpos[k]);
        }
        slot = e.slot;
        ev = -1;
      } else {
        ev = probe_or_insert(ix, h1s[j], h2s[j], &slot);
        // An eviction ran erase_at (backward shift relocates entries):
        // staged positions recorded in 2a may now be stale.
        if (ev >= 0) positions_valid = false;
      }
      out_evicted[i] = static_cast<int32_t>(ev);
      if (ev == -2) {  // assignment failed: deny lane, not a unique
        out_uidx[i] = -1;
        out_rank[i] = 0;
        continue;
      }
      Index::BatchScratch& b = scratch[slot];
      int32_t ui;
      if (b.epoch != epoch) {
        b.epoch = epoch;
        ui = b.uidx = static_cast<int32_t>(u);
        out_uwords[u] = static_cast<uint32_t>(slot) << (rank_bits + 1);
        ucnt[u] = 0;
        u++;
      } else {
        ui = b.uidx;
      }
      Index::FcLine& L = fc[h1s[j] & (kFrontCacheSize - 1)];
      L.h1 = h1s[j]; L.h2 = h2s[j]; L.gen = epoch;
      L.slot = slot; L.uidx = ui;
      out_uidx[i] = ui;
      out_rank[i] = ucnt[ui]++;
    }
  }
  for (int64_t j = 0; j < u; j++) {
    uint32_t cnt = static_cast<uint32_t>(ucnt[j]);
    if (cnt > rank_max) cnt = rank_max;
    out_uwords[j] |= cnt << 1;
  }
  return u;
}

}  // namespace

extern "C" {

void* rl_index_new(int64_t num_slots) {
  Index* ix = new Index();
  ix->num_slots = num_slots;
  uint64_t cap = 16;
  while (cap < static_cast<uint64_t>(num_slots) * 2) cap <<= 1;
  ix->mask = cap - 1;
  ix->table.assign(cap, Entry{});
  advise_huge(ix->table.data(), cap * sizeof(Entry));
  ix->entry_of_slot.assign(num_slots, -1);
  ix->pins.assign(num_slots, 0);
  ix->deferred.assign(num_slots, 0);
  ix->free_slots.reserve(num_slots);
  for (int64_t s = num_slots - 1; s >= 0; s--)
    ix->free_slots.push_back(static_cast<int32_t>(s));
  return ix;
}

void rl_index_free(void* h) { delete static_cast<Index*>(h); }

int64_t rl_index_len(void* h) { return static_cast<Index*>(h)->size; }

// Batch assign for int64 keys. out_evicted[i] = slot to clear before reuse
// (-1 none, -2 assignment failed: all pinned).
//
void rl_index_assign_ints(void* h, const int64_t* keys, int64_t n,
                          uint64_t lid_seed, int32_t* out_slots,
                          int32_t* out_evicted) {
  assign_batch(static_cast<Index*>(h), n, out_slots, out_evicted,
               [&](int64_t i, uint64_t& h1, uint64_t& h2) {
                 hash_int(keys[i], lid_seed, h1, h2);
               });
}

// Batch assign for int64 keys with PER-REQUEST seeds (multi-tenant batches:
// seed = limiter id, so the namespace is identical to per-lid scalar calls).
void rl_index_assign_ints_multi(void* h, const int64_t* keys,
                                const uint64_t* seeds, int64_t n,
                                int32_t* out_slots, int32_t* out_evicted) {
  assign_batch(static_cast<Index*>(h), n, out_slots, out_evicted,
               [&](int64_t i, uint64_t& h1, uint64_t& h2) {
                 hash_int(keys[i], seeds[i], h1, h2);
               });
}

// Batch assign for string keys packed as bytes + offsets (offsets[n] entries
// of start positions, key i = data[offsets[i]..offsets[i+1])).
void rl_index_assign_bytes(void* h, const uint8_t* data, const int64_t* offsets,
                           int64_t n, uint64_t lid_seed, int32_t* out_slots,
                           int32_t* out_evicted) {
  assign_batch(static_cast<Index*>(h), n, out_slots, out_evicted,
               [&](int64_t i, uint64_t& h1, uint64_t& h2) {
                 hash_bytes(data + offsets[i], offsets[i + 1] - offsets[i],
                            lid_seed, h1, h2);
               });
}

// Unique-compaction variants (see assign_batch_uniques above).
int64_t rl_index_assign_ints_uniques(void* h, const int64_t* keys, int64_t n,
                                     uint64_t lid_seed, int32_t rank_bits,
                                     uint32_t* out_uwords, int32_t* out_uidx,
                                     int32_t* out_rank, int32_t* out_evicted) {
  return assign_batch_uniques(static_cast<Index*>(h), n, rank_bits,
                              out_uwords, out_uidx, out_rank, out_evicted,
                              [&](int64_t i, uint64_t& h1, uint64_t& h2) {
                                hash_int(keys[i], lid_seed, h1, h2);
                              });
}

int64_t rl_index_assign_ints_multi_uniques(
    void* h, const int64_t* keys, const uint64_t* seeds, int64_t n,
    int32_t rank_bits, uint32_t* out_uwords, int32_t* out_uidx,
    int32_t* out_rank, int32_t* out_evicted) {
  return assign_batch_uniques(static_cast<Index*>(h), n, rank_bits,
                              out_uwords, out_uidx, out_rank, out_evicted,
                              [&](int64_t i, uint64_t& h1, uint64_t& h2) {
                                hash_int(keys[i], seeds[i], h1, h2);
                              });
}

int64_t rl_index_assign_bytes_uniques(
    void* h, const uint8_t* data, const int64_t* offsets, int64_t n,
    uint64_t lid_seed, int32_t rank_bits, uint32_t* out_uwords,
    int32_t* out_uidx, int32_t* out_rank, int32_t* out_evicted) {
  return assign_batch_uniques(
      static_cast<Index*>(h), n, rank_bits, out_uwords, out_uidx, out_rank,
      out_evicted, [&](int64_t i, uint64_t& h1, uint64_t& h2) {
        hash_bytes(data + offsets[i], offsets[i + 1] - offsets[i], lid_seed,
                   h1, h2);
      });
}

// Unique-compaction assign for PRECOMPUTED fingerprints — the native
// string fast path: the CPython-API hasher (str_pack.cpp:
// rl_strlist_hash_fp) emits (h1, h2) straight from the interned UTF-8
// buffers, and this walk consumes them with zero byte copies.  The
// fingerprints are bit-identical to hash_bytes over the same UTF-8, so
// this path interoperates with every bytes/scalar entry point.  The
// (0,0) reservation guard is applied here too so raw callers can't
// alias the empty sentinel.
int64_t rl_index_assign_fps_uniques(
    void* h, const uint64_t* h1s, const uint64_t* h2s, int64_t n,
    int32_t rank_bits, uint32_t* out_uwords, int32_t* out_uidx,
    int32_t* out_rank, int32_t* out_evicted) {
  return assign_batch_uniques(static_cast<Index*>(h), n, rank_bits,
                              out_uwords, out_uidx, out_rank, out_evicted,
                              [&](int64_t i, uint64_t& h1, uint64_t& h2) {
                                h1 = h1s[i];
                                h2 = h2s[i] |
                                     (h1 == 0 && h2s[i] == 0 ? 1 : 0);
                              });
}

// Batch fingerprint hashing for packed byte keys (no table access): the
// fallback producer for the fingerprint paths when the CPython hasher
// is unavailable, and the router's input for sharded string streams.
// Bit-identical to the hash the assign walks compute internally.
void rl_hash_bytes_batch(const uint8_t* data, const int64_t* offsets,
                         int64_t n, uint64_t seed, uint64_t* out_h1,
                         uint64_t* out_h2) {
  for (int64_t i = 0; i < n; i++) {
    hash_bytes(data + offsets[i], offsets[i + 1] - offsets[i], seed,
               out_h1[i], out_h2[i]);
  }
}

// Shard routing from precomputed fingerprints (string streams): shard =
// h1 % n_shards plus the same stable counting sort as rl_shard_route,
// so each shard's requests become one contiguous slice in arrival
// order.  Must agree with parallel/sharded.py:shard_of_key's string
// branch (which computes the same h1 scalar-side).
void rl_route_hashes(const uint64_t* h1s, int64_t n, int32_t n_shards,
                     int32_t* out_shard, int64_t* out_order,
                     int64_t* out_counts) {
  for (int32_t s = 0; s < n_shards; s++) out_counts[s] = 0;
  const uint64_t ns = static_cast<uint64_t>(n_shards);
  for (int64_t i = 0; i < n; i++) {
    int32_t s = static_cast<int32_t>(h1s[i] % ns);
    out_shard[i] = s;
    out_counts[s]++;
  }
  std::vector<int64_t> off(n_shards);
  int64_t acc = 0;
  for (int32_t s = 0; s < n_shards; s++) {
    off[s] = acc;
    acc += out_counts[s];
  }
  for (int64_t i = 0; i < n; i++) out_order[off[out_shard[i]]++] = i;
}

// Fused route + gather (r6): same as rl_shard_route but the second
// pass also emits the keys in shard-sorted order — on the 1-core bench
// host the separate numpy fancy-gather was a whole extra memory pass
// per chunk.
void rl_shard_route2(const int64_t* keys, int64_t n, int32_t n_shards,
                     int32_t* out_shard, int64_t* out_order,
                     int64_t* out_counts, int64_t* out_keys_sorted) {
  for (int32_t s = 0; s < n_shards; s++) out_counts[s] = 0;
  const uint64_t ns = static_cast<uint64_t>(n_shards);
  for (int64_t i = 0; i < n; i++) {
    uint64_t x = static_cast<uint64_t>(keys[i]) + 0x9E3779B97F4A7C15ULL;
    x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
    x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
    x = x ^ (x >> 31);
    int32_t s = static_cast<int32_t>(x % ns);
    out_shard[i] = s;
    out_counts[s]++;
  }
  std::vector<int64_t> off(n_shards);
  int64_t acc = 0;
  for (int32_t s = 0; s < n_shards; s++) {
    off[s] = acc;
    acc += out_counts[s];
  }
  for (int64_t i = 0; i < n; i++) {
    int64_t p = off[out_shard[i]]++;
    out_order[p] = i;
    out_keys_sorted[p] = keys[i];
  }
}

// Fused fingerprint route + gather (string streams): shard = h1 %
// n_shards, emitting both fingerprint streams shard-sorted alongside
// the stable order.
void rl_route_hashes2(const uint64_t* h1s, const uint64_t* h2s,
                      int64_t n, int32_t n_shards, int32_t* out_shard,
                      int64_t* out_order, int64_t* out_counts,
                      uint64_t* out_h1_sorted, uint64_t* out_h2_sorted) {
  for (int32_t s = 0; s < n_shards; s++) out_counts[s] = 0;
  const uint64_t ns = static_cast<uint64_t>(n_shards);
  for (int64_t i = 0; i < n; i++) {
    int32_t s = static_cast<int32_t>(h1s[i] % ns);
    out_shard[i] = s;
    out_counts[s]++;
  }
  std::vector<int64_t> off(n_shards);
  int64_t acc = 0;
  for (int32_t s = 0; s < n_shards; s++) {
    off[s] = acc;
    acc += out_counts[s];
  }
  for (int64_t i = 0; i < n; i++) {
    int64_t p = off[out_shard[i]]++;
    out_order[p] = i;
    out_h1_sorted[p] = h1s[i];
    out_h2_sorted[p] = h2s[i];
  }
}

// Relay decision reconstruction SCATTERED to caller positions (r6):
// out[pos[i]] = rank[i] < counts[uidx[i]].  The sharded drain used to
// materialize the decisions densely and then numpy-fancy-scatter them
// into the output — two memory passes fused into one here.
void rl_relay_decide_pos(const uint8_t* counts, int32_t counts_width,
                         const int32_t* uidx, const int32_t* rank,
                         const int64_t* pos, int64_t n,
                         uint8_t* out, int64_t* out_allowed) {
  int64_t allowed = 0;
  if (counts_width == 1) {
    for (int64_t i = 0; i < n; i++) {
      uint8_t a = rank[i] < static_cast<int32_t>(counts[uidx[i]]);
      out[pos[i]] = a;
      allowed += a;
    }
  } else {
    const uint16_t* c16 = reinterpret_cast<const uint16_t*>(counts);
    for (int64_t i = 0; i < n; i++) {
      uint8_t a = rank[i] < static_cast<int32_t>(c16[uidx[i]]);
      out[pos[i]] = a;
      allowed += a;
    }
  }
  *out_allowed = allowed;
}

// Scalar lookups (no assignment). Return slot or -1.
int32_t rl_index_get_int(void* h, int64_t key, uint64_t lid_seed) {
  Index* ix = static_cast<Index*>(h);
  uint64_t h1, h2;
  hash_int(key, lid_seed, h1, h2);
  int32_t pos = find(ix, h1, h2);
  if (pos < 0) return -1;
  lru_touch(ix, pos);
  return ix->table[pos].slot;
}

int32_t rl_index_get_bytes(void* h, const uint8_t* data, int64_t len,
                           uint64_t lid_seed) {
  Index* ix = static_cast<Index*>(h);
  uint64_t h1, h2;
  hash_bytes(data, len, lid_seed, h1, h2);
  int32_t pos = find(ix, h1, h2);
  if (pos < 0) return -1;
  lru_touch(ix, pos);
  return ix->table[pos].slot;
}

// Remove a key; returns its slot (caller must clear device state BEFORE the
// slot can be reused) or -1.  A slot with a live pin refcount (a stream's
// assign->dispatch window) is NOT freed here — that would let a new key take
// it before the pinned dispatch enqueues its write.  It is deferred and
// surfaces on the dirty list at last unpin (see take_slot).
static int32_t remove_at(Index* ix, int32_t pos) {
  int32_t slot = ix->table[pos].slot;
  lru_unlink(ix, pos);
  ix->entry_of_slot[slot] = -1;
  erase_at(ix, static_cast<uint64_t>(pos));
  ix->size--;
  if (ix->pins[slot] > 0)
    ix->deferred[slot] = 1;
  else
    ix->free_slots.push_back(slot);
  return slot;
}

int32_t rl_index_remove_bytes(void* h, const uint8_t* data, int64_t len,
                              uint64_t lid_seed) {
  Index* ix = static_cast<Index*>(h);
  uint64_t h1, h2;
  hash_bytes(data, len, lid_seed, h1, h2);
  int32_t pos = find(ix, h1, h2);
  if (pos < 0) return -1;
  return remove_at(ix, pos);
}

int32_t rl_index_remove_int(void* h, int64_t key, uint64_t lid_seed) {
  Index* ix = static_cast<Index*>(h);
  uint64_t h1, h2;
  hash_int(key, lid_seed, h1, h2);
  int32_t pos = find(ix, h1, h2);
  if (pos < 0) return -1;
  return remove_at(ix, pos);
}

// -- enumeration / restore (checkpointing at native speed) -------------------
// The table stores fingerprints, not keys, so enumeration yields
// (h1, h2, slot) triples.  Dump order is LRU order, most-recent first;
// restore rebuilds the exact same recency order, so eviction behavior
// continues unchanged across a snapshot/restore cycle.

int64_t rl_index_dump(void* h, uint64_t* out_h1, uint64_t* out_h2,
                      int32_t* out_slots) {
  Index* ix = static_cast<Index*>(h);
  int64_t i = 0;
  for (int32_t pos = ix->lru_head; pos >= 0; pos = ix->table[pos].lru_next) {
    const Entry& e = ix->table[pos];
    out_h1[i] = e.h1;
    out_h2[i] = e.h2;
    out_slots[i] = e.slot;
    i++;
  }
  return i;
}

// Rebuild from a dump (MRU-first order, as produced by rl_index_dump).
// Returns 0 on success, -1 on invalid input (bad slot, duplicate slot or
// fingerprint, zero fingerprint, n > num_slots).  The index is cleared
// first; on failure it is left cleared.
static void reset_empty(Index* ix) {
  std::fill(ix->table.begin(), ix->table.end(), Entry{});
  std::fill(ix->entry_of_slot.begin(), ix->entry_of_slot.end(), -1);
  std::fill(ix->deferred.begin(), ix->deferred.end(), 0);
  ix->dirty_free.clear();
  ix->size = 0;
  ix->lru_head = ix->lru_tail = -1;
  ix->free_slots.clear();
  // Pin refcounts survive a clear/restore (they belong to in-flight
  // dispatch windows, not to the mapping): a still-pinned slot must not
  // reach the clean free list — defer it so it surfaces on the dirty
  // list (=> cleared before reuse) at last unpin.
  for (int64_t s = ix->num_slots - 1; s >= 0; s--) {
    if (ix->pins[s] > 0)
      ix->deferred[s] = 1;
    else
      ix->free_slots.push_back(static_cast<int32_t>(s));
  }
}

int32_t rl_index_restore(void* h, const uint64_t* h1s, const uint64_t* h2s,
                         const int32_t* slots, int64_t n) {
  Index* ix = static_cast<Index*>(h);
  reset_empty(ix);
  if (n > ix->num_slots) return -1;  // index left empty-but-usable
  ix->free_slots.clear();
  // Insert tail-first so entry 0 ends at the LRU head (most recent).
  for (int64_t i = n - 1; i >= 0; i--) {
    uint64_t h1 = h1s[i], h2 = h2s[i];
    int32_t slot = slots[i];
    if (slot < 0 || slot >= ix->num_slots || (h1 == 0 && h2 == 0) ||
        ix->entry_of_slot[slot] >= 0 || find(ix, h1, h2) >= 0) {
      reset_empty(ix);
      return -1;
    }
    insert(ix, h1, h2, slot);
  }
  for (int64_t s = ix->num_slots - 1; s >= 0; s--) {
    if (ix->entry_of_slot[s] >= 0) {
      // Slot re-mapped by the restore: it must NOT surface on the dirty
      // free list at last unpin (two keys would share it).
      ix->deferred[s] = 0;
      continue;
    }
    if (ix->pins[s] > 0)  // in-flight dispatch window: see reset_empty
      ix->deferred[s] = 1;
    else
      ix->free_slots.push_back(static_cast<int32_t>(s));
  }
  return 0;
}

// Fingerprint-level lookup/assign (flat-to-flat rebalance: fingerprints are
// geometry-independent for LRU-assigned tables, so a dump from a smaller
// index can be imported into a larger one without knowing the keys).
void rl_index_lookup_fps(void* h, const uint64_t* h1s, const uint64_t* h2s,
                         int64_t n, int32_t* out_slots) {
  Index* ix = static_cast<Index*>(h);
  for (int64_t i = 0; i < n; i++) {
    int32_t pos = find(ix, h1s[i], h2s[i]);
    out_slots[i] = pos < 0 ? -1 : ix->table[pos].slot;
  }
}

void rl_index_assign_fps(void* h, const uint64_t* h1s, const uint64_t* h2s,
                         int64_t n, int32_t* out_slots, int32_t* out_evicted) {
  assign_batch(static_cast<Index*>(h), n, out_slots, out_evicted,
               [&](int64_t i, uint64_t& h1, uint64_t& h2) {
                 h1 = h1s[i];
                 h2 = h2s[i] | (h1 == 0 && h2s[i] == 0 ? 1 : 0);
               });
}

// Relay decision reconstruction: allowed[i] = rank[i] < counts[uidx[i]].
// One fused pass instead of numpy's gather + astype + compare temporaries;
// counts element width is 1 or 2 bytes (the device's u8/u16 output).
void rl_relay_decide(const uint8_t* counts, int32_t counts_width,
                     const int32_t* uidx, const int32_t* rank, int64_t n,
                     uint8_t* out_allowed) {
  if (counts_width == 1) {
    for (int64_t i = 0; i < n; i++)
      out_allowed[i] = rank[i] < static_cast<int32_t>(counts[uidx[i]]);
  } else {
    const uint16_t* c16 = reinterpret_cast<const uint16_t*>(counts);
    for (int64_t i = 0; i < n; i++)
      out_allowed[i] = rank[i] < static_cast<int32_t>(c16[uidx[i]]);
  }
}

// Shard routing for the sharded stream paths: one pass hashes every key
// with the splitmix64 finalizer (bit-identical to
// parallel/sharded.py:shard_of_int_keys) and counts per shard; a second
// pass emits the STABLE counting-sort order, so each shard's requests
// become one contiguous slice in arrival order.  Replaces a numpy
// hash (6 vector passes) + O(n log n) argsort on the chunk hot path.
void rl_shard_route(const int64_t* keys, int64_t n, int32_t n_shards,
                    int32_t* out_shard, int64_t* out_order,
                    int64_t* out_counts) {
  for (int32_t s = 0; s < n_shards; s++) out_counts[s] = 0;
  const uint64_t ns = static_cast<uint64_t>(n_shards);
  for (int64_t i = 0; i < n; i++) {
    uint64_t x = static_cast<uint64_t>(keys[i]) + 0x9E3779B97F4A7C15ULL;
    x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
    x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
    x = x ^ (x >> 31);
    int32_t s = static_cast<int32_t>(x % ns);
    out_shard[i] = s;
    out_counts[s]++;
  }
  std::vector<int64_t> off(n_shards);
  int64_t acc = 0;
  for (int32_t s = 0; s < n_shards; s++) {
    off[s] = acc;
    acc += out_counts[s];
  }
  for (int64_t i = 0; i < n; i++) out_order[off[out_shard[i]]++] = i;
}

void rl_index_pin(void* h, int32_t slot) {
  Index* ix = static_cast<Index*>(h);
  if (slot >= 0 && slot < ix->num_slots) ix->pins[slot]++;
}

// Last unpin of a removed-while-pinned slot frees it onto the dirty list
// (take_slot reports dirty slots as their own eviction => cleared on reuse).
static inline void unpin_one(Index* ix, int32_t slot) {
  if (slot < 0 || slot >= ix->num_slots || ix->pins[slot] == 0) return;
  if (--ix->pins[slot] == 0 && ix->deferred[slot]) {
    ix->deferred[slot] = 0;
    ix->dirty_free.push_back(slot);
  }
}

void rl_index_unpin(void* h, int32_t slot) {
  unpin_one(static_cast<Index*>(h), slot);
}

// Batch pin/unpin (refcounted, duplicates fine): streams hold these from
// slot assignment until their device dispatch is enqueued, so concurrent
// scalar traffic can never evict-and-clear a slot that an in-preparation
// batch is about to write (the reverse direction — queued micro-batcher
// slots vs stream assigns — is covered by the per-call pinned set).
void rl_index_pin_batch(void* h, const int32_t* slots, int64_t n) {
  Index* ix = static_cast<Index*>(h);
  for (int64_t i = 0; i < n; i++) {
    int32_t s = slots[i];
    if (s >= 0 && s < ix->num_slots) ix->pins[s]++;
  }
}

void rl_index_unpin_batch(void* h, const int32_t* slots, int64_t n) {
  Index* ix = static_cast<Index*>(h);
  for (int64_t i = 0; i < n; i++) unpin_one(ix, slots[i]);
}

// ---------------------------------------------------------------------------
// Weighted-relay rank-major layout (storage/tpu.py:_stream_weighted).
//
// The device's weighted scan step wants segments sorted by occurrence
// count DESCENDING so each rank step's active set is a prefix, with the
// per-request permits laid out rank-major compacted (all rank-0 permits,
// then rank-1, ...).  The probe walk already produced per-unique counts
// (in the uwords' count field) and per-request (uidx, rank) — this pass
// turns them into the device layout in O(u + n), replacing a numpy
// argsort + bincount/cumsum + fancy-index scatter that cost ~1.4 s on a
// 16M-request chunk (VERDICT r3 #2).
//
// Inputs: uwords[u] with the segment count in bits 1..rank_bits (true,
// unclamped — the caller verified r_max <= r_cap < r_b), per-request
// uidx/rank, permits as int64 (values already bounded to the engine's
// <=255 weighted cap), and r_b = pow2 >= r_max.
// Outputs (all caller-allocated): uw_sorted (first u entries written;
// caller pre-fills the padding), spos[u] (unique -> sorted position),
// roff[r_b] (rank-major block offsets), perms_rank (caller-zeroed;
// exactly n positions scattered).  Returns 0, or -1 if a count exceeds
// r_b (caller's r_cap check violated — layout would be out of bounds).
int32_t rl_weighted_layout(const uint32_t* uwords, int64_t u,
                           int32_t rank_bits, const int32_t* uidx,
                           const int32_t* rank, int64_t n,
                           const int64_t* perms, int64_t r_b,
                           uint32_t* uw_sorted, int32_t* spos,
                           int64_t* roff, uint8_t* perms_rank) {
  if (r_b <= 0 || r_b > 4096) return -1;
  const uint32_t cmask = (1u << rank_bits) - 1u;
  std::vector<int64_t> hist(r_b + 1, 0);
  for (int64_t i = 0; i < u; i++) {
    uint32_t c = (uwords[i] >> 1) & cmask;
    if (static_cast<int64_t>(c) > r_b) return -1;
    hist[c]++;
  }
  // start[v] = #segments with count > v — the descending-stable bucket
  // start, and also k_r (active segments at rank step v).
  std::vector<int64_t> start(r_b + 1, 0);
  int64_t acc = 0;
  for (int64_t v = r_b; v >= 0; v--) {
    start[v] = acc;
    acc += hist[v];
  }
  // roff[r] = sum_{q<r} k_r[q] — BEFORE start is consumed by placement.
  int64_t racc = 0;
  for (int64_t r = 0; r < r_b; r++) {
    roff[r] = racc;
    racc += start[r];
  }
  for (int64_t i = 0; i < u; i++) {
    uint32_t c = (uwords[i] >> 1) & cmask;
    int64_t p = start[c]++;
    uw_sorted[p] = uwords[i];
    spos[i] = static_cast<int32_t>(p);
  }
  for (int64_t i = 0; i < n; i++) {
    int64_t p = roff[rank[i]] + spos[uidx[i]];
    perms_rank[p] = static_cast<uint8_t>(perms[i]);
  }
  return 0;
}

// Sort a uniques batch by SLOT (radix on the word's slot field) and
// remap uidx accordingly — in place.  Slot-sorted digests let the
// device scatter run as a dense block sweep (ops/pallas/block_scatter
// presorted path) instead of XLA's ~45 ns/index generic scatter, and
// the gather ride ascending addresses.  Slots are unique within a
// batch, so stability is irrelevant; 2x11-bit LSD radix passes cover
// the <= 2^22 slot ids every engine geometry produces (wider slot
// fields fall back to more passes).  O(u) per pass + O(n) remap.
int32_t rl_sort_uniques(uint32_t* uwords, int64_t u, int32_t rank_bits,
                        int32_t* uidx, int64_t n) {
  if (u <= 1) return 0;
  const int shift = rank_bits + 1;
  std::vector<uint32_t> tmp_w(u);
  std::vector<int32_t> ord(u), ord_tmp(u);
  for (int64_t i = 0; i < u; i++) ord[i] = static_cast<int32_t>(i);
  uint32_t max_slot = 0;
  for (int64_t i = 0; i < u; i++) {
    uint32_t s = uwords[i] >> shift;
    if (s > max_slot) max_slot = s;
  }
  const int kBits = 11;
  const uint32_t kMask = (1u << kBits) - 1u;
  int passes = 1;
  while (passes * kBits < 32 && (max_slot >> (passes * kBits)) != 0)
    passes++;
  std::vector<int64_t> cnt(1u << kBits);
  for (int p = 0; p < passes; p++) {
    const int sh = shift + p * kBits;
    std::fill(cnt.begin(), cnt.end(), 0);
    for (int64_t i = 0; i < u; i++) cnt[(uwords[ord[i]] >> sh) & kMask]++;
    int64_t acc = 0;
    for (uint32_t b = 0; b <= kMask; b++) {
      int64_t c = cnt[b];
      cnt[b] = acc;
      acc += c;
    }
    for (int64_t i = 0; i < u; i++)
      ord_tmp[cnt[(uwords[ord[i]] >> sh) & kMask]++] = ord[i];
    ord.swap(ord_tmp);
  }
  // inv[old] = new position; gather words into sorted order.
  std::vector<int32_t> inv(u);
  for (int64_t j = 0; j < u; j++) {
    inv[ord[j]] = static_cast<int32_t>(j);
    tmp_w[j] = uwords[ord[j]];
  }
  std::memcpy(uwords, tmp_w.data(), u * sizeof(uint32_t));
  for (int64_t i = 0; i < n; i++) {
    int32_t ui = uidx[i];
    if (ui >= 0) uidx[i] = inv[ui];
  }
  return 0;
}

// Per-request words-mode reconstruction (ops/relay.py:rebuild_words in
// one pass): word = (slot | clamped rank | last-of-segment), written
// straight into the caller's padded dispatch buffer — the numpy version
// materialized ~6 full-stream temporaries plus a pad copy, ~1s of the
// 10M-key uniform pass's host time.  For an over-clamp segment the
// flagged lane is the one at rank clamp-1, matching the numpy fallback
// bit for bit.
void rl_rebuild_words(const uint32_t* uwords, const int32_t* uidx,
                      const int32_t* rank, int64_t n, int32_t rank_bits,
                      uint32_t* out) {
  const uint32_t rmask = (1u << rank_bits) - 1u;
  for (int64_t i = 0; i < n; i++) {
    uint32_t w = uwords[uidx[i]];
    uint32_t cnt = (w >> 1) & rmask;
    uint32_t r = static_cast<uint32_t>(rank[i]);
    uint32_t rcl = r > rmask ? rmask : r;
    out[i] = (w & ~((rmask << 1) | 1u)) | (rcl << 1)
             | ((r + 1 == cnt) ? 1u : 0u);
  }
}

// Decision reconstruction for the layout above: request i's decision is
// bit (roff[rank[i]] + spos[uidx[i]]) of the fetched bitmask (MSB-first
// within each byte, matching numpy packbits).  One pass replaces
// unpackbits + a fancy-index gather.
void rl_weighted_decide(const uint8_t* bits, const int64_t* roff,
                        const int32_t* spos, const int32_t* uidx,
                        const int32_t* rank, int64_t n, uint8_t* out) {
  for (int64_t i = 0; i < n; i++) {
    int64_t p = roff[rank[i]] + spos[uidx[i]];
    out[i] = (bits[p >> 3] >> (7 - (p & 7))) & 1;
  }
}

// Split-digest layout (r5, ops/relay.py:_relay_counts_split): partition
// uniques into singletons (count field == 1 — exact: rank_bits >= 2 so
// the clamp sentinel is >= 3) and multi-count segments; singletons'
// slots go out as a 3-byte little-endian plane, multis keep their
// uwords, and uidx is remapped to singles-then-multis positions.  Two
// passes (O(u) classify+emit, O(n) remap) replacing four numpy passes;
// `scratch` is caller-provided int32[u] for the position map.  Returns
// the singleton count.
int64_t rl_split_layout(const uint32_t* uwords, int64_t u,
                        int32_t rank_bits, const int32_t* uidx, int64_t n,
                        uint8_t* s3, uint32_t* mwords, int32_t* uidx2,
                        int32_t* scratch) {
  const uint32_t rmask = (1u << rank_bits) - 1u;
  const int shift = rank_bits + 1;
  int64_t n_s = 0;
  for (int64_t i = 0; i < u; i++) {
    if (((uwords[i] >> 1) & rmask) == 1u) n_s++;
  }
  int64_t si = 0, mi = n_s;
  for (int64_t i = 0; i < u; i++) {
    uint32_t w = uwords[i];
    if (((w >> 1) & rmask) == 1u) {
      uint32_t s = w >> shift;
      s3[si * 3] = static_cast<uint8_t>(s & 0xFF);
      s3[si * 3 + 1] = static_cast<uint8_t>((s >> 8) & 0xFF);
      s3[si * 3 + 2] = static_cast<uint8_t>((s >> 16) & 0xFF);
      scratch[i] = static_cast<int32_t>(si++);
    } else {
      mwords[mi - n_s] = w;
      scratch[i] = static_cast<int32_t>(mi++);
    }
  }
  for (int64_t i = 0; i < n; i++) uidx2[i] = scratch[uidx[i]];
  return n_s;
}

}  // extern "C"
