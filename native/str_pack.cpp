// String-key batch packing via the CPython API.
//
// The string stream paths hand the C slot index (packed bytes, offsets)
// for a whole batch of Python str keys.  The pure-Python packer costs
// ~85 ns/key ("\x00".join + encode + separator scan + compaction);
// walking the list with PyList_GET_ITEM + PyUnicode_AsUTF8AndSize does
// the same work in one pass at C speed, with no separator restrictions
// (keys containing NUL take this path too, where the join fallback
// couldn't).
//
// Built as its OWN shared library (linked against libpython) so the
// Python-free libslotindex.so stays loadable anywhere; loaded lazily
// via ctypes with py_object arguments.  Callers hold the GIL (plain
// ctypes call) — these functions touch Python objects and must not be
// invoked from GIL-released contexts.

#include <Python.h>

#include <cstdint>
#include <cstring>

extern "C" {

// Pass 1: total UTF-8 byte length of a LIST of str.  Also caches each
// object's UTF-8 representation (PyUnicode_AsUTF8AndSize memoizes on
// the unicode object), so pass 2's lookups are pointer reads.
// Returns -1 if seq is not a list or any element is not str.
int64_t rl_strlist_total(PyObject* seq) {
  if (!PyList_Check(seq)) return -1;
  Py_ssize_t n = PyList_GET_SIZE(seq);
  int64_t total = 0;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* it = PyList_GET_ITEM(seq, i);
    if (!PyUnicode_Check(it)) return -1;
    Py_ssize_t len;
    const char* p = PyUnicode_AsUTF8AndSize(it, &len);
    if (p == nullptr) {
      PyErr_Clear();
      return -1;
    }
    total += len;
  }
  return total;
}

// Pass 2: copy the UTF-8 bytes into buf and write n+1 offsets.
// Caller allocated buf (expect_total bytes, from rl_strlist_total) and
// offs (expect_n + 1).  Named _pack2: the arity changed when the
// bounds re-checks landed, and a stale prebuilt .so binding the old
// 3-arg symbol would silently drop the guard — a new name makes a
// stale library fail to bind and fall back to the numpy packer.
// The two passes are separated by Python code
// (np.empty) where the GIL can drop, so a caller thread mutating the
// list in between (growing it, or swapping in longer strings) must turn
// into an error return, not a heap overflow: every length is re-checked
// against what the buffers were sized for.  Returns 0, or -1 on type
// errors / size drift (buffer untouched beyond progress).
int32_t rl_strlist_pack2(PyObject* seq, uint8_t* buf, int64_t* offs,
                        int64_t expect_n, int64_t expect_total) {
  if (!PyList_Check(seq)) return -1;
  Py_ssize_t n = PyList_GET_SIZE(seq);
  if (n != expect_n) return -1;
  int64_t pos = 0;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* it = PyList_GET_ITEM(seq, i);
    if (!PyUnicode_Check(it)) return -1;
    Py_ssize_t len;
    const char* p = PyUnicode_AsUTF8AndSize(it, &len);
    if (p == nullptr) {
      PyErr_Clear();
      return -1;
    }
    if (pos + len > expect_total) return -1;
    offs[i] = pos;
    std::memcpy(buf + pos, p, static_cast<size_t>(len));
    pos += len;
  }
  offs[n] = pos;
  return 0;
}

// Fingerprint hashing straight off the list: one pass computes the
// 128-bit FNV fingerprints the slot index keys on, reading each str's
// interned UTF-8 buffer in place — no join, no byte copy, no offsets
// array.  MUST stay bit-identical to slot_index.cpp:hash_bytes (the
// fingerprints interoperate with every bytes/scalar entry point and
// with checkpoints); the mixing below is a verbatim copy, covered by
// tests/test_native_index.py parity tests.
//
// ``start``/``n`` window the list so stream chunking never slices the
// (multi-million-entry) Python list: the storage passes the whole list
// plus a window and zero per-key Python objects are created.
// Returns 0, or -1 on type errors / out-of-range windows (the list can
// shrink between calls — bounds are re-checked here).
static inline void fp_mix(uint64_t& h, uint64_t x) {
  h ^= x;
  h *= 0x100000001b3ULL;
}

int32_t rl_strlist_hash_fp(PyObject* seq, int64_t start, int64_t n,
                           uint64_t seed, uint64_t* out_h1,
                           uint64_t* out_h2) {
  if (!PyList_Check(seq) || start < 0 || n < 0) return -1;
  if (start + n > static_cast<int64_t>(PyList_GET_SIZE(seq))) return -1;
  for (int64_t i = 0; i < n; i++) {
    PyObject* it = PyList_GET_ITEM(seq, start + i);
    if (!PyUnicode_Check(it)) return -1;
    Py_ssize_t len;
    const char* p = PyUnicode_AsUTF8AndSize(it, &len);
    if (p == nullptr) {
      PyErr_Clear();
      return -1;
    }
    uint64_t h1 = 0xcbf29ce484222325ULL ^ seed;
    uint64_t h2 = 0x84222325cbf29ce4ULL ^ (seed * 0x9e3779b97f4a7c15ULL);
    const uint8_t* b = reinterpret_cast<const uint8_t*>(p);
    for (Py_ssize_t j = 0; j < len; j++) {
      fp_mix(h1, b[j]);
      h2 = (h2 ^ (b[j] + 0x9e3779b97f4a7c15ULL + (h2 << 6) + (h2 >> 2)));
    }
    h2 = h2 * 0xff51afd7ed558ccdULL + static_cast<uint64_t>(len);
    if (h1 == 0 && h2 == 0) h2 = 1;  // reserve (0,0) for "empty"
    out_h1[i] = h1;
    out_h2[i] = h2;
  }
  return 0;
}

}  // extern "C"
