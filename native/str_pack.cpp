// String-key batch packing via the CPython API.
//
// The string stream paths hand the C slot index (packed bytes, offsets)
// for a whole batch of Python str keys.  The pure-Python packer costs
// ~85 ns/key ("\x00".join + encode + separator scan + compaction);
// walking the list with PyList_GET_ITEM + PyUnicode_AsUTF8AndSize does
// the same work in one pass at C speed, with no separator restrictions
// (keys containing NUL take this path too, where the join fallback
// couldn't).
//
// Built as its OWN shared library (linked against libpython) so the
// Python-free libslotindex.so stays loadable anywhere; loaded lazily
// via ctypes with py_object arguments.  Callers hold the GIL (plain
// ctypes call) — these functions touch Python objects and must not be
// invoked from GIL-released contexts.

#include <Python.h>

#include <cstdint>
#include <cstring>

extern "C" {

// Pass 1: total UTF-8 byte length of a LIST of str.  Also caches each
// object's UTF-8 representation (PyUnicode_AsUTF8AndSize memoizes on
// the unicode object), so pass 2's lookups are pointer reads.
// Returns -1 if seq is not a list or any element is not str.
int64_t rl_strlist_total(PyObject* seq) {
  if (!PyList_Check(seq)) return -1;
  Py_ssize_t n = PyList_GET_SIZE(seq);
  int64_t total = 0;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* it = PyList_GET_ITEM(seq, i);
    if (!PyUnicode_Check(it)) return -1;
    Py_ssize_t len;
    const char* p = PyUnicode_AsUTF8AndSize(it, &len);
    if (p == nullptr) {
      PyErr_Clear();
      return -1;
    }
    total += len;
  }
  return total;
}

// Pass 2: copy the UTF-8 bytes into buf and write n+1 offsets.
// Caller allocated buf (expect_total bytes, from rl_strlist_total) and
// offs (expect_n + 1).  Named _pack2: the arity changed when the
// bounds re-checks landed, and a stale prebuilt .so binding the old
// 3-arg symbol would silently drop the guard — a new name makes a
// stale library fail to bind and fall back to the numpy packer.
// The two passes are separated by Python code
// (np.empty) where the GIL can drop, so a caller thread mutating the
// list in between (growing it, or swapping in longer strings) must turn
// into an error return, not a heap overflow: every length is re-checked
// against what the buffers were sized for.  Returns 0, or -1 on type
// errors / size drift (buffer untouched beyond progress).
int32_t rl_strlist_pack2(PyObject* seq, uint8_t* buf, int64_t* offs,
                        int64_t expect_n, int64_t expect_total) {
  if (!PyList_Check(seq)) return -1;
  Py_ssize_t n = PyList_GET_SIZE(seq);
  if (n != expect_n) return -1;
  int64_t pos = 0;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* it = PyList_GET_ITEM(seq, i);
    if (!PyUnicode_Check(it)) return -1;
    Py_ssize_t len;
    const char* p = PyUnicode_AsUTF8AndSize(it, &len);
    if (p == nullptr) {
      PyErr_Clear();
      return -1;
    }
    if (pos + len > expect_total) return -1;
    offs[i] = pos;
    std::memcpy(buf + pos, p, static_cast<size_t>(len));
    pos += len;
  }
  offs[n] = pos;
  return 0;
}

}  // extern "C"
