// String-key batch packing via the CPython API.
//
// The string stream paths hand the C slot index (packed bytes, offsets)
// for a whole batch of Python str keys.  The pure-Python packer costs
// ~85 ns/key ("\x00".join + encode + separator scan + compaction);
// walking the list with PyList_GET_ITEM + PyUnicode_AsUTF8AndSize does
// the same work in one pass at C speed, with no separator restrictions
// (keys containing NUL take this path too, where the join fallback
// couldn't).
//
// Built as its OWN shared library (linked against libpython) so the
// Python-free libslotindex.so stays loadable anywhere; loaded lazily
// via ctypes with py_object arguments.  Callers hold the GIL (plain
// ctypes call) — these functions touch Python objects and must not be
// invoked from GIL-released contexts.

#include <Python.h>

#include <cstdint>
#include <cstring>

extern "C" {

// Pass 1: total UTF-8 byte length of a LIST of str.  Also caches each
// object's UTF-8 representation (PyUnicode_AsUTF8AndSize memoizes on
// the unicode object), so pass 2's lookups are pointer reads.
// Returns -1 if seq is not a list or any element is not str.
int64_t rl_strlist_total(PyObject* seq) {
  if (!PyList_Check(seq)) return -1;
  Py_ssize_t n = PyList_GET_SIZE(seq);
  int64_t total = 0;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* it = PyList_GET_ITEM(seq, i);
    if (!PyUnicode_Check(it)) return -1;
    Py_ssize_t len;
    const char* p = PyUnicode_AsUTF8AndSize(it, &len);
    if (p == nullptr) {
      PyErr_Clear();
      return -1;
    }
    total += len;
  }
  return total;
}

// Pass 2: copy the UTF-8 bytes into buf and write n+1 offsets.
// Caller allocated buf (>= rl_strlist_total bytes) and offs (n+1).
// Returns 0, or -1 on type errors (buffer untouched beyond progress).
int32_t rl_strlist_pack(PyObject* seq, uint8_t* buf, int64_t* offs) {
  if (!PyList_Check(seq)) return -1;
  Py_ssize_t n = PyList_GET_SIZE(seq);
  int64_t pos = 0;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* it = PyList_GET_ITEM(seq, i);
    if (!PyUnicode_Check(it)) return -1;
    Py_ssize_t len;
    const char* p = PyUnicode_AsUTF8AndSize(it, &len);
    if (p == nullptr) {
      PyErr_Clear();
      return -1;
    }
    offs[i] = pos;
    std::memcpy(buf + pos, p, static_cast<size_t>(len));
    pos += len;
  }
  offs[n] = pos;
  return 0;
}

}  // extern "C"
