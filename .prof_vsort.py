import jax
jax.config.update("jax_enable_x64", True)
import time, numpy as np, jax.numpy as jnp

R = 10
rng = np.random.default_rng(0)

def timed(name, fn, *args):
    out = fn(*args); np.asarray(jax.tree_util.tree_leaves(out)[0])
    t0 = time.perf_counter()
    out = fn(*args); np.asarray(jax.tree_util.tree_leaves(out)[0])
    dt = time.perf_counter() - t0
    print(f"{name:54s} {(dt-0.11)/R*1e3:8.1f} ms/iter", flush=True)

def mk(M, lanes, key_dtype):
    keys = jnp.asarray(rng.integers(0, M, M).astype(key_dtype))
    payloads = tuple(jnp.zeros((M,), jnp.int32) for _ in range(lanes))
    @jax.jit
    def f(keys, payloads):
        def body(i, carry):
            k, ps = carry
            out = jax.lax.sort((k,) + ps, num_keys=1, is_stable=True)
            return (out[0], out[1:])
        return jax.lax.fori_loop(0, R, body, (keys, payloads))
    return f, keys, payloads

for M in (1 << 21, 3 << 20, 1 << 22):
    for lanes in (2, 4, 6):
        f, k, p = mk(M, lanes, np.int32)
        timed(f"lax.sort stable {M>>20}M el, 1 key + {lanes} i32 lanes", f, k, p)

# i64 payload lanes (for full i64 state without bitcast plumbing)
keys = jnp.asarray(rng.integers(0, 1 << 21, 3 << 20).astype(np.int32))
p64 = tuple(jnp.zeros((3 << 20,), jnp.int64) for _ in range(3))
@jax.jit
def f64(keys, ps):
    def body(i, carry):
        k, ps = carry
        out = jax.lax.sort((k,) + ps, num_keys=1, is_stable=True)
        return (out[0], out[1:])
    return jax.lax.fori_loop(0, R, body, (keys, ps))
timed("lax.sort stable 3M el, 1 key + 3 i64 lanes", f64, keys, p64)
