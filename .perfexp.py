import os, sys, time, numpy as np
from ratelimiter_tpu.utils.compile_cache import enable_compile_cache
enable_compile_cache("/root/repo/.jax_cache")
from ratelimiter_tpu import RateLimitConfig
from ratelimiter_tpu.algorithms import TokenBucketRateLimiter
from ratelimiter_tpu.metrics import MeterRegistry
from ratelimiter_tpu.storage import TpuBatchedStorage
from ratelimiter_tpu.bench.harness import zipf_stream

rng = np.random.default_rng(42)
num_keys = 1_000_000
for B, K in [(1 << 19, 8), (1 << 20, 8), (1 << 19, 16)]:
    storage = TpuBatchedStorage(num_slots=2_000_000)
    tb = TokenBucketRateLimiter(storage, RateLimitConfig(max_permits=100, window_ms=60_000, refill_rate=50.0), MeterRegistry())
    n = B * K * 2
    ids = zipf_stream(rng, num_keys, n)
    t0 = time.perf_counter()
    tb.try_acquire_stream_ids(ids[:B * K], batch=B, subbatches=K)
    c = time.perf_counter() - t0
    best = 0
    for _ in range(3):
        t0 = time.perf_counter()
        tb.try_acquire_stream_ids(ids, batch=B, subbatches=K)
        best = max(best, n / (time.perf_counter() - t0))
    print(f"B={B} K={K} pallas={os.environ.get('RATELIMITER_PALLAS','0')}: "
          f"compile {c:.0f}s, best {best/1e6:.2f}M/s", flush=True)
    storage.close()
