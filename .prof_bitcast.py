import jax
jax.config.update("jax_enable_x64", True)
import time, numpy as np, jax.numpy as jnp

B = 1 << 20
N = 1 << 21
R = 20
rng = np.random.default_rng(0)
slots = jnp.asarray(rng.integers(0, N, B).astype(np.int32))
staterow32 = jnp.zeros((N, 8), jnp.int32)
staterow64 = jnp.zeros((N, 4), jnp.int64)

def timed(name, fn, *args):
    out = fn(*args); np.asarray(jax.tree_util.tree_leaves(out)[0])
    t0 = time.perf_counter()
    out = fn(*args); np.asarray(jax.tree_util.tree_leaves(out)[0])
    dt = time.perf_counter() - t0
    print(f"{name:52s} {(dt-0.11)/R*1e3:8.1f} ms/iter", flush=True)

@jax.jit
def rows32(st, idx):
    def body(i, st):
        rows = st[idx] + 1
        return st.at[idx].set(rows)
    return jax.lax.fori_loop(0, R, body, st)

@jax.jit
def rows64_via_bitcast(st, idx):
    def body(i, st):
        st32 = jax.lax.bitcast_convert_type(st, jnp.int32)  # [N,4,2]
        st32 = st32.reshape(N, 8)
        rows32 = st32[idx]                                   # i32 row gather
        rows64 = jax.lax.bitcast_convert_type(
            rows32.reshape(B, 4, 2), jnp.int64)              # [B,4]
        rows64 = rows64 + 1
        up32 = jax.lax.bitcast_convert_type(rows64, jnp.int32).reshape(B, 8)
        st32 = st32.at[idx].set(up32)
        return jax.lax.bitcast_convert_type(st32.reshape(N, 4, 2), jnp.int64)
    return jax.lax.fori_loop(0, R, body, st)

timed("i32[2M,8] row gather+scatter @1M", rows32, staterow32, slots)
timed("i64[2M,4] rows via i32 bitcast @1M", rows64_via_bitcast, staterow64, slots)
