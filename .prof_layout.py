import jax
jax.config.update("jax_enable_x64", True)
import time, numpy as np, jax.numpy as jnp

B = 1 << 20
N = 1 << 21
R = 10
rng = np.random.default_rng(0)
idx = jnp.asarray(np.sort(rng.integers(0, N, B)).astype(np.int32))

def timed(name, fn, *args):
    out = fn(*args); np.asarray(jax.tree_util.tree_leaves(out)[0])
    t0 = time.perf_counter()
    out = fn(*args); np.asarray(jax.tree_util.tree_leaves(out)[0])
    dt = time.perf_counter() - t0
    print(f"{name:50s} {(dt-0.11)/R*1e3:8.1f} ms/iter", flush=True)

def soa(k, dtype):
    arrs = tuple(jnp.zeros((N,), dtype) for _ in range(k))
    @jax.jit
    def f(arrs):
        def body(i, arrs):
            vals = tuple(a[idx] + 1 for a in arrs)
            return tuple(a.at[idx].set(v) for a, v in zip(arrs, vals))
        return jax.lax.fori_loop(0, R, body, arrs)
    return f, arrs

def row(k, dtype):
    arr = jnp.zeros((N, k), dtype)
    @jax.jit
    def f(arr):
        def body(i, arr):
            return arr.at[idx].set(arr[idx] + 1)
        return jax.lax.fori_loop(0, R, body, arr)
    return f, arr

for k in (1, 2, 4):
    f, a = soa(k, jnp.int32); timed(f"SoA {k}x flat i32 g+s", f, a)
for k in (2, 4, 8):
    f, a = row(k, jnp.int32); timed(f"row i32[N,{k}] g+s", f, a)
f, a = soa(1, jnp.int64); timed("SoA 1x flat i64 g+s", f, a)
f, a = soa(2, jnp.int64); timed("SoA 2x flat i64 g+s", f, a)

# scatter-only (values independent of gathered data, dependency via first elem)
arr2 = jnp.zeros((N,), jnp.int32)
vals = jnp.ones((B,), jnp.int32)
@jax.jit
def scat_only(st):
    def body(i, st):
        return st.at[idx].set(vals + st[0])
    return jax.lax.fori_loop(0, R, body, st)
timed("scatter-only flat i32", scat_only, arr2)

@jax.jit
def gath_only(x):
    def body(i, x):
        g = x[idx][:N // 2] if False else x[idx]
        return x.at[0].add(g[0] + g[-1])
    return jax.lax.fori_loop(0, R, body, x)
timed("gather-only flat i32 (approx)", gath_only, arr2)
