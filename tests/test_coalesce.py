"""Zipf key coalescing (ops/relay.py:*_relay_weighted_counts).

Within a chunk whose repeats carry segment-uniform weights, the stream
path folds every repeat of a key into ONE weighted decision per unique
(device work scales with uniques, not requests) and reconstructs the
per-request allow/deny bits host-side via the prefix-allow rule
``rank < n_allowed[uidx]``.  These tests pin the bit-identity contract:
coalesced decisions must equal the sequential per-request semantics of
``semantics/oracle.py`` — and of the uncoalesced device path — exactly,
including deny/allow interleavings and eviction pressure.
"""

import numpy as np
import pytest

import ratelimiter_tpu.storage.tpu as tpu_mod
from ratelimiter_tpu.core.config import RateLimitConfig
from ratelimiter_tpu.semantics import SlidingWindowOracle, TokenBucketOracle
from ratelimiter_tpu.storage.tpu import TpuBatchedStorage


def _cfg_oracle(algo):
    if algo == "sw":
        cfg = RateLimitConfig(max_permits=6, window_ms=1000,
                              enable_local_cache=False)
        return cfg, SlidingWindowOracle(cfg)
    cfg = RateLimitConfig(max_permits=9, window_ms=1200, refill_rate=4.0)
    return cfg, TokenBucketOracle(cfg)


def _spy_coalesce(monkeypatch, st, algo):
    """Count engagements of the coalesced dispatch on this storage."""
    name = f"{algo}_weighted_counts_dispatch"
    orig = getattr(st.engine, name)
    calls = {"n": 0}

    def spy(*a, **k):
        calls["n"] += 1
        return orig(*a, **k)

    monkeypatch.setattr(st.engine, name, spy)
    return calls


@pytest.mark.parametrize("algo", ["sw", "tb"])
def test_coalesced_zipf_stream_vs_oracle(monkeypatch, algo):
    """Zipf traffic with per-key-uniform weights: the coalesced digest
    must ENGAGE and every request decision must match the sequential
    oracle replay exactly — allows, denies, and their interleavings."""
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK", 256)
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK_MAX", 256)
    now = [4_000_000]
    st = TpuBatchedStorage(num_slots=1 << 12, clock_ms=lambda: now[0])
    cfg, oracle = _cfg_oracle(algo)
    lid = st.register_limiter(algo, cfg)
    calls = _spy_coalesce(monkeypatch, st, algo)
    rng = np.random.default_rng(7)
    for step in range(6):
        now[0] += int(rng.integers(0, 900))
        ids = (rng.zipf(1.2, 600) % 40).astype(np.int64)
        # Per-key-deterministic weight: every repeat of a key carries
        # the same permits, so every chunk coalesces.
        perms = (ids % 4 + 1).astype(np.int64)
        got = st.acquire_stream_ids(algo, lid, ids, perms)
        for j, k in enumerate(ids):
            want = oracle.try_acquire(f"id:{k}", int(perms[j]),
                                      now[0]).allowed
            assert got[j] == want, (algo, step, j)
    assert calls["n"] > 0, "coalesced dispatch never engaged"
    st.close()


@pytest.mark.parametrize("algo", ["sw", "tb"])
def test_coalesced_matches_uncoalesced_device_path(monkeypatch, algo):
    """RATELIMITER_COALESCE on/off must be bit-identical on the same
    stream — the digest is an encoding of the scan, not a new policy."""
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK", 256)
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK_MAX", 256)
    cfg, _ = _cfg_oracle(algo)
    rng = np.random.default_rng(13)
    ids = (rng.zipf(1.3, 2000) % 64).astype(np.int64)
    perms = (ids % 5 + 1).astype(np.int64)
    outs = []
    for coalesce in (True, False):
        monkeypatch.setattr(tpu_mod, "_COALESCE", coalesce)
        now = [8_000_000]
        st = TpuBatchedStorage(num_slots=1 << 12, clock_ms=lambda: now[0])
        lid = st.register_limiter(algo, cfg)
        rows = []
        for start in range(0, len(ids), 500):
            rows.append(st.acquire_stream_ids(
                algo, lid, ids[start:start + 500],
                perms[start:start + 500]))
            now[0] += 377
        outs.append(np.concatenate(rows))
        st.close()
    np.testing.assert_array_equal(outs[0], outs[1])


def test_coalesce_deny_allow_interleave_across_keys(monkeypatch):
    """Interleaved hot keys with different budgets produce alternating
    allow/deny in ARRIVAL order; the host-side ``rank < n_allowed``
    reconstruction must reproduce that ordering exactly."""
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK", 64)
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK_MAX", 64)
    now = [2_000_000]
    st = TpuBatchedStorage(num_slots=1 << 10, clock_ms=lambda: now[0])
    cfg = RateLimitConfig(max_permits=10, window_ms=60_000, refill_rate=0.0)
    lid = st.register_limiter("tb", cfg)
    calls = _spy_coalesce(monkeypatch, st, "tb")
    # Key 1 @ weight 4 -> allows 2 of 6; key 2 @ weight 3 -> allows 3 of 6.
    ids = np.asarray([1, 2] * 6, dtype=np.int64)
    perms = np.where(ids == 1, 4, 3).astype(np.int64)
    got = st.acquire_stream_ids("tb", lid, ids, perms)
    want = [True, True, True, True, False, True,   # k1:4,8 k2:3,6,9
            False, False, False, False, False, False]
    np.testing.assert_array_equal(got, want)
    assert calls["n"] == 1
    st.close()


@pytest.mark.parametrize("algo", ["sw", "tb"])
def test_mixed_weights_fall_back_exactly(monkeypatch, algo):
    """A chunk whose repeats carry DIFFERENT weights for one key cannot
    coalesce — the path must fall back (no digest dispatch) and still
    match the oracle, skip recurrence included."""
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK", 128)
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK_MAX", 128)
    now = [6_000_000]
    st = TpuBatchedStorage(num_slots=1 << 12, clock_ms=lambda: now[0])
    cfg, oracle = _cfg_oracle(algo)
    lid = st.register_limiter(algo, cfg)
    calls = _spy_coalesce(monkeypatch, st, algo)
    rng = np.random.default_rng(29)
    ids = rng.integers(0, 20, 384).astype(np.int64)
    perms = rng.integers(1, 7, 384).astype(np.int64)  # mixed per key
    got = st.acquire_stream_ids(algo, lid, ids, perms)
    for j, k in enumerate(ids):
        want = oracle.try_acquire(f"id:{k}", int(perms[j]),
                                  now[0]).allowed
        assert got[j] == want, (algo, j)
    assert calls["n"] == 0, "mixed-weight chunk must not coalesce"
    st.close()


@pytest.mark.parametrize("algo", ["sw", "tb"])
def test_coalesce_eviction_pressure_matches_uncoalesced(monkeypatch, algo):
    """Keys evicted between chunks (slot churn far above capacity) must
    not change a single decision coalesced-vs-uncoalesced: both paths
    see the same assigns, the same clears, the same state."""
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK", 64)
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK_MAX", 64)
    cfg, _ = _cfg_oracle(algo)
    rng = np.random.default_rng(43)
    # 300 distinct keys through 128 slots: heavy eviction churn.
    ids = (rng.zipf(1.1, 1500) % 300).astype(np.int64)
    perms = (ids % 3 + 1).astype(np.int64)
    outs = []
    for coalesce in (True, False):
        monkeypatch.setattr(tpu_mod, "_COALESCE", coalesce)
        now = [1_000_000]
        st = TpuBatchedStorage(num_slots=128, clock_ms=lambda: now[0])
        lid = st.register_limiter(algo, cfg)
        rows = []
        for start in range(0, len(ids), 250):
            rows.append(st.acquire_stream_ids(
                algo, lid, ids[start:start + 250],
                perms[start:start + 250]))
            now[0] += 211
        outs.append(np.concatenate(rows))
        st.close()
    np.testing.assert_array_equal(outs[0], outs[1])


def test_sharded_weighted_stream_vs_oracle():
    """The sharded weighted stream (flat sharded dispatch — coalescing
    is a single-device digest) stays bit-identical to the oracle on the
    same Zipf traffic, so the v5 ingest contract holds on the mesh."""
    from ratelimiter_tpu.engine.engine import LimiterTable
    from ratelimiter_tpu.parallel import ShardedDeviceEngine

    now = [3_000_000]
    eng = ShardedDeviceEngine(slots_per_shard=256, table=LimiterTable())
    st = TpuBatchedStorage(engine=eng, clock_ms=lambda: now[0])
    cfg = RateLimitConfig(max_permits=8, window_ms=1500, refill_rate=5.0)
    oracle = TokenBucketOracle(cfg)
    lid = st.register_limiter("tb", cfg)
    rng = np.random.default_rng(59)
    for step in range(4):
        now[0] += int(rng.integers(0, 1200))
        ids = (rng.zipf(1.2, 500) % 60).astype(np.int64)
        perms = (ids % 4 + 1).astype(np.int64)
        got = st.acquire_stream_ids("tb", lid, ids, perms)
        for j, k in enumerate(ids):
            want = oracle.try_acquire(f"id:{k}", int(perms[j]),
                                      now[0]).allowed
            assert got[j] == want, (step, j)
    st.close()


@pytest.mark.parametrize("algo", ["sw", "tb"])
def test_coalesced_string_stream_vs_oracle(monkeypatch, algo):
    """String keys ride the same weighted loop (hash once -> assign ->
    coalesce): the v5 sidecar feeds this path straight off the wire."""
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK", 256)
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK_MAX", 256)
    now = [7_000_000]
    st = TpuBatchedStorage(num_slots=1 << 12, clock_ms=lambda: now[0])
    cfg, oracle = _cfg_oracle(algo)
    lid = st.register_limiter(algo, cfg)
    calls = _spy_coalesce(monkeypatch, st, algo)
    rng = np.random.default_rng(71)
    ids = (rng.zipf(1.2, 600) % 50).astype(np.int64)
    keys = [f"user-{k}" for k in ids]
    perms = (ids % 4 + 1).astype(np.int64)
    got = st.acquire_stream_strs(algo, lid, keys, perms)
    for j, k in enumerate(keys):
        want = oracle.try_acquire(k, int(perms[j]), now[0]).allowed
        assert got[j] == want, (algo, j)
    assert calls["n"] > 0, "string stream never coalesced"
    st.close()
