"""Multi-host routing: two sidecar "hosts", keys pinned by hash, decisions
exact across the fleet."""

import numpy as np

from ratelimiter_tpu import RateLimitConfig
from ratelimiter_tpu.parallel.multihost import HostRouter, host_of_key
from ratelimiter_tpu.semantics import SlidingWindowOracle
from ratelimiter_tpu.service.sidecar import SidecarServer
from ratelimiter_tpu.storage import TpuBatchedStorage

T0 = 1_753_000_000_000


class FakeClock:
    def __init__(self, t=T0):
        self.t = t

    def __call__(self):
        return self.t


def test_router_splits_and_reassembles():
    clock = FakeClock((T0 // 60_000) * 60_000)
    cfg = RateLimitConfig(max_permits=4, window_ms=60_000, enable_local_cache=False)

    # Two independent "hosts", each with its own device state — registered
    # with the same config so limiter ids line up fleet-wide.
    hosts = []
    for _ in range(2):
        storage = TpuBatchedStorage(num_slots=256, max_delay_ms=0.2, clock_ms=clock)
        server = SidecarServer(storage, host="127.0.0.1").start()
        lid = server.register("sw", cfg)
        hosts.append((server, storage, lid))
    lid = hosts[0][2]
    assert all(h[2] == lid for h in hosts)

    router = HostRouter([("127.0.0.1", h[0].port) for h in hosts])
    oracle = SlidingWindowOracle(cfg)

    keys = [f"user{i}" for i in range(12)]
    # Sanity: both hosts own some keys.
    owners = {host_of_key(k, 2) for k in keys}
    assert owners == {0, 1}

    rng = np.random.default_rng(3)
    for step in range(8):
        n = int(rng.integers(1, 20))
        batch = [keys[int(rng.integers(0, len(keys)))] for _ in range(n)]
        got = router.acquire_batch(lid, batch)
        for j in range(n):
            want = oracle.try_acquire(batch[j], 1, clock.t).allowed
            assert got[j] == want, (step, j)

    # Reset routes to the owner and takes effect.
    victim = keys[0]
    while router.try_acquire(lid, victim):
        oracle.try_acquire(victim, 1, clock.t)
    router.reset(lid, victim)
    oracle.reset(victim, clock.t)
    assert router.try_acquire(lid, victim)

    router.close()
    for server, storage, _ in hosts:
        server.stop()
        storage.close()
