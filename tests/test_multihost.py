"""Multi-host routing: two sidecar "hosts", keys pinned by hash, decisions
exact across the fleet."""

import numpy as np

from ratelimiter_tpu import RateLimitConfig
from ratelimiter_tpu.parallel.multihost import HostRouter, host_of_key
from ratelimiter_tpu.semantics import SlidingWindowOracle
from ratelimiter_tpu.service.sidecar import SidecarServer
from ratelimiter_tpu.storage import TpuBatchedStorage

T0 = 1_753_000_000_000


class FakeClock:
    def __init__(self, t=T0):
        self.t = t

    def __call__(self):
        return self.t


def test_router_splits_and_reassembles():
    clock = FakeClock((T0 // 60_000) * 60_000)
    cfg = RateLimitConfig(max_permits=4, window_ms=60_000, enable_local_cache=False)

    # Two independent "hosts", each with its own device state — registered
    # with the same config so limiter ids line up fleet-wide.
    hosts = []
    for _ in range(2):
        storage = TpuBatchedStorage(num_slots=256, max_delay_ms=0.2, clock_ms=clock)
        server = SidecarServer(storage, host="127.0.0.1").start()
        lid = server.register("sw", cfg)
        hosts.append((server, storage, lid))
    lid = hosts[0][2]
    assert all(h[2] == lid for h in hosts)

    router = HostRouter([("127.0.0.1", h[0].port) for h in hosts])
    oracle = SlidingWindowOracle(cfg)

    keys = [f"user{i}" for i in range(12)]
    # Sanity: both hosts own some keys.
    owners = {host_of_key(k, 2) for k in keys}
    assert owners == {0, 1}

    rng = np.random.default_rng(3)
    for step in range(8):
        n = int(rng.integers(1, 20))
        batch = [keys[int(rng.integers(0, len(keys)))] for _ in range(n)]
        got = router.acquire_batch(lid, batch)
        for j in range(n):
            want = oracle.try_acquire(batch[j], 1, clock.t).allowed
            assert got[j] == want, (step, j)

    # Reset routes to the owner and takes effect.
    victim = keys[0]
    while router.try_acquire(lid, victim):
        oracle.try_acquire(victim, 1, clock.t)
    router.reset(lid, victim)
    oracle.reset(victim, clock.t)
    assert router.try_acquire(lid, victim)

    router.close()
    for server, storage, _ in hosts:
        server.stop()
        storage.close()


def _one_host(clock, cfg):
    storage = TpuBatchedStorage(num_slots=128, max_delay_ms=0.2,
                                clock_ms=clock)
    server = SidecarServer(storage, host="127.0.0.1").start()
    lid = server.register("sw", cfg)
    return server, storage, lid


def test_router_surfaces_down_endpoint():
    """A dead owner surfaces a connection error to the caller — no silent
    cross-host failover (a different host would hand the key fresh quota)."""
    import socket

    import pytest

    clock = FakeClock()
    cfg = RateLimitConfig(max_permits=4, window_ms=60_000,
                          enable_local_cache=False)
    server, storage, lid = _one_host(clock, cfg)
    # Reserve a port and close it: a definitely-down second endpoint.
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()

    router = HostRouter([("127.0.0.1", server.port),
                         ("127.0.0.1", dead_port)])
    keys = [f"user{i}" for i in range(20)]
    up = [k for k in keys if host_of_key(k, 2) == 0]
    down = [k for k in keys if host_of_key(k, 2) == 1]
    assert up and down

    assert router.try_acquire(lid, up[0])  # live host unaffected
    with pytest.raises(OSError):
        router.try_acquire(lid, down[0])
    # Batches touching the dead owner error too; live-only batches work.
    assert router.acquire_batch(lid, up[:3]) == [True] * 3
    with pytest.raises(OSError):
        router.acquire_batch(lid, keys)

    router.close()
    server.stop()
    storage.close()


def test_router_reconnects_after_host_restart():
    """An owner restart (same endpoint, new process/socket) is absorbed by
    the router's drop-and-retry — callers never see the stale connection."""
    clock = FakeClock()
    cfg = RateLimitConfig(max_permits=10, window_ms=60_000,
                          enable_local_cache=False)
    server, storage, lid = _one_host(clock, cfg)
    port = server.port
    router = HostRouter([("127.0.0.1", port)])
    assert router.try_acquire(lid, "alice")

    # "Restart": stop the sidecar, bring a fresh one up on the SAME port.
    server.stop()
    storage.close()
    storage2 = TpuBatchedStorage(num_slots=128, max_delay_ms=0.2,
                                 clock_ms=clock)
    server2 = SidecarServer(storage2, host="127.0.0.1", port=port).start()
    lid2 = server2.register("sw", cfg)
    assert lid2 == lid

    # The cached connection is stale; the router must reconnect and decide.
    assert router.try_acquire(lid, "alice")
    # State belongs to the (restarted) host: fresh quota there is expected;
    # subsequent calls keep working on the new connection.
    assert router.available(lid, "alice") == cfg.max_permits - 1

    router.close()
    server2.stop()
    storage2.close()


def test_router_down_endpoint_recovers_without_restart():
    """A previously-down endpoint that comes up is usable on the next call
    (failed connections are never cached)."""
    import socket

    import pytest

    clock = FakeClock()
    cfg = RateLimitConfig(max_permits=4, window_ms=60_000,
                          enable_local_cache=False)
    # Pick the port first so the router can point at it while it's down.
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    router = HostRouter([("127.0.0.1", port)])
    with pytest.raises(OSError):
        router.try_acquire(1, "bob")

    storage = TpuBatchedStorage(num_slots=128, max_delay_ms=0.2,
                                clock_ms=clock)
    server = SidecarServer(storage, host="127.0.0.1", port=port).start()
    lid = server.register("sw", cfg)
    assert router.try_acquire(lid, "bob")  # same router object recovered

    router.close()
    server.stop()
    storage.close()
