"""TTL cache (Caffeine analog, C7) and metrics registry (C12)."""

from ratelimiter_tpu.cache import TTLCache
from ratelimiter_tpu.metrics import MeterRegistry


class FakeClock:
    def __init__(self, t=0):
        self.t = t

    def __call__(self):
        return self.t


def test_cache_expire_after_write():
    clock = FakeClock()
    c = TTLCache(ttl_ms=100, clock_ms=clock)
    c.put("a", 5)
    assert c.get_if_present("a") == 5
    clock.t = 99
    assert c.get_if_present("a") == 5
    clock.t = 100
    assert c.get_if_present("a") is None


def test_cache_put_refreshes_ttl():
    clock = FakeClock()
    c = TTLCache(ttl_ms=100, clock_ms=clock)
    c.put("a", 1)
    clock.t = 80
    c.put("a", 2)  # expireAfterWrite: deadline moves to 180
    clock.t = 150
    assert c.get_if_present("a") == 2
    clock.t = 180
    assert c.get_if_present("a") is None


def test_cache_invalidate_and_bound():
    clock = FakeClock()
    c = TTLCache(ttl_ms=1000, max_size=3, clock_ms=clock)
    for i in range(5):
        c.put(f"k{i}", i)
    # Oldest writes evicted first; size bounded at 3.
    assert len(c) == 3
    assert c.get_if_present("k0") is None
    assert c.get_if_present("k4") == 4
    c.invalidate("k4")
    assert c.get_if_present("k4") is None


def test_counter_and_registry():
    reg = MeterRegistry()
    a = reg.counter("ratelimiter.requests.allowed", "allowed")
    a.increment()
    a.add(41)
    # Same name returns the same meter (Micrometer registry semantics).
    assert reg.counter("ratelimiter.requests.allowed").count() == 42
    scrape = reg.scrape()
    assert scrape["ratelimiter.requests.allowed"] == 42


def test_timer_percentiles():
    reg = MeterRegistry()
    t = reg.timer("ratelimiter.storage.latency")
    for v in range(1, 101):
        t.record_us(float(v))
    snap = t.snapshot()
    assert snap["count"] == 100
    # Bucket-interpolated: rank 50 falls in the (32, 64] bucket, which
    # holds samples 33..64 — 32 + 32 * (50 - 32) / 32 = 50 exactly.
    assert snap["p50_us"] == 50.0
    # p95/p99 land in the (64, 128] bucket (36 samples, 65..100): the
    # interpolation overshoots the true value but stays in-bucket.
    assert 64.0 < snap["p95_us"] <= 128.0
    assert snap["p95_us"] <= snap["p99_us"] <= 128.0
    assert abs(snap["mean_us"] - 50.5) < 1e-9


def test_timer_quantile_small_sample_bias():
    """The old reservoir snapshot indexed ``samples[int(p * len)]``,
    which returns the element *after* the p-quantile on small sets:
    p50 of four samples read samples[2].  The bucket interpolation at
    rank ``p * n`` must not inherit that bias — exact values below are
    hand-computed from the bucket bounds."""
    t = MeterRegistry().timer("t")
    # Four samples in four distinct buckets: 1 -> [0,1], 2 -> (1,2],
    # 4 -> (2,4], 8 -> (4,8].
    for v in (1.0, 2.0, 4.0, 8.0):
        t.record_us(v)
    # rank = 0.5 * 4 = 2: cum hits 2 inside the (1,2] bucket ->
    # 1 + (2-1) * (2-1)/1 = 2.0 (the old code would have answered 4.0,
    # the element after the median).
    assert t.snapshot()["p50_us"] == 2.0

    t2 = MeterRegistry().timer("t2")
    for _ in range(100):
        t2.record_us(100.0)  # all in (64, 128]
    snap = t2.snapshot()
    # rank 50 of 100 identical samples: 64 + 64 * 50/100 = 96 exactly.
    assert snap["p50_us"] == 96.0
    assert snap["mean_us"] == 100.0


def test_timer_bucket_surfaces():
    t = MeterRegistry().timer("t")
    for v in (0.5, 1.0, 3.0, 100.0, 1e19):
        t.record_us(v)
    counts = t.bucket_counts()
    bounds = t.bucket_bounds_us()
    assert len(counts) == len(bounds) == t.N_BUCKETS
    assert bounds[-1] == float("inf")
    assert sum(counts) == t.count() == 5
    assert counts[0] == 2          # 0.5 and 1.0 in [0, 1]
    assert counts[2] == 1          # 3.0 in (2, 4]
    assert counts[7] == 1          # 100.0 in (64, 128]
    assert counts[-1] == 1         # 1e19 > 2^63 clamps into the +Inf bucket
    assert abs(t.total_us() - (0.5 + 1 + 3 + 100 + 1e19)) < 1e4
