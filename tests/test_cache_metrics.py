"""TTL cache (Caffeine analog, C7) and metrics registry (C12)."""

from ratelimiter_tpu.cache import TTLCache
from ratelimiter_tpu.metrics import MeterRegistry


class FakeClock:
    def __init__(self, t=0):
        self.t = t

    def __call__(self):
        return self.t


def test_cache_expire_after_write():
    clock = FakeClock()
    c = TTLCache(ttl_ms=100, clock_ms=clock)
    c.put("a", 5)
    assert c.get_if_present("a") == 5
    clock.t = 99
    assert c.get_if_present("a") == 5
    clock.t = 100
    assert c.get_if_present("a") is None


def test_cache_put_refreshes_ttl():
    clock = FakeClock()
    c = TTLCache(ttl_ms=100, clock_ms=clock)
    c.put("a", 1)
    clock.t = 80
    c.put("a", 2)  # expireAfterWrite: deadline moves to 180
    clock.t = 150
    assert c.get_if_present("a") == 2
    clock.t = 180
    assert c.get_if_present("a") is None


def test_cache_invalidate_and_bound():
    clock = FakeClock()
    c = TTLCache(ttl_ms=1000, max_size=3, clock_ms=clock)
    for i in range(5):
        c.put(f"k{i}", i)
    # Oldest writes evicted first; size bounded at 3.
    assert len(c) == 3
    assert c.get_if_present("k0") is None
    assert c.get_if_present("k4") == 4
    c.invalidate("k4")
    assert c.get_if_present("k4") is None


def test_counter_and_registry():
    reg = MeterRegistry()
    a = reg.counter("ratelimiter.requests.allowed", "allowed")
    a.increment()
    a.add(41)
    # Same name returns the same meter (Micrometer registry semantics).
    assert reg.counter("ratelimiter.requests.allowed").count() == 42
    scrape = reg.scrape()
    assert scrape["ratelimiter.requests.allowed"] == 42


def test_timer_percentiles():
    reg = MeterRegistry()
    t = reg.timer("ratelimiter.storage.latency")
    for v in range(1, 101):
        t.record_us(float(v))
    snap = t.snapshot()
    assert snap["count"] == 100
    assert 45 <= snap["p50_us"] <= 55
    assert 94 <= snap["p95_us"] <= 100
    assert abs(snap["mean_us"] - 50.5) < 1e-9
