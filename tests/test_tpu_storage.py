"""TpuBatchedStorage end-to-end: limiter classes over the device backend.

The same SlidingWindowRateLimiter / TokenBucketRateLimiter classes that run
per-op over InMemoryStorage here route whole decisions through the batched
device path — and must still match the oracle exactly.  Also covers the
slot index (LRU eviction, pinning, reuse-after-clear) and the micro-batcher
under real thread concurrency (the reference's 20-thread smoke test,
SlidingWindowRateLimiterTest.java:135-176, done for real).
"""

import random
import threading

import numpy as np
import pytest

from ratelimiter_tpu import RateLimitConfig
from ratelimiter_tpu.algorithms import SlidingWindowRateLimiter, TokenBucketRateLimiter
from ratelimiter_tpu.engine.slots import SlotIndex
from ratelimiter_tpu.metrics import MeterRegistry
from ratelimiter_tpu.semantics import SlidingWindowOracle, TokenBucketOracle
from ratelimiter_tpu.storage import TpuBatchedStorage

T0 = 1_753_000_000_000


class FakeClock:
    def __init__(self, t=T0):
        self.t = t

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# SlotIndex
# ---------------------------------------------------------------------------

def test_slot_index_assign_and_lru_eviction():
    idx = SlotIndex(num_slots=2)
    s_a, ev = idx.assign("a")
    assert ev is None
    s_b, ev = idx.assign("b")
    assert ev is None and s_a != s_b
    idx.get("a")  # touch: b becomes LRU
    s_c, ev = idx.assign("c")
    assert ev == s_b and s_c == s_b
    assert idx.get("b") is None
    assert idx.get("a") == s_a


def test_slot_index_pinning():
    idx = SlotIndex(num_slots=2)
    s_a, _ = idx.assign("a")
    s_b, _ = idx.assign("b")
    s_c, ev = idx.assign("c", pinned={s_a})
    assert ev == s_b  # LRU would be a, but it's pinned
    with pytest.raises(RuntimeError):
        idx.assign("d", pinned={s_a, s_c})


def test_slot_index_remove():
    idx = SlotIndex(num_slots=2)
    s_a, _ = idx.assign("a")
    assert idx.remove("a") == s_a
    assert idx.remove("a") is None
    s_b, ev = idx.assign("b")
    assert ev is None  # freed slot reused without eviction


# ---------------------------------------------------------------------------
# Differential: limiter classes over the TPU backend vs oracle
# ---------------------------------------------------------------------------

def test_sw_tpu_backend_differential():
    clock = FakeClock()
    storage = TpuBatchedStorage(num_slots=512, max_delay_ms=0.2, clock_ms=clock)
    cfg = RateLimitConfig(max_permits=20, window_ms=1000, enable_local_cache=False)
    limiter = SlidingWindowRateLimiter(storage, cfg, MeterRegistry(), clock_ms=clock)
    oracle = SlidingWindowOracle(cfg)
    rng = random.Random(5)
    keys = [f"u{i}" for i in range(6)]
    for step in range(50):
        clock.t += rng.randrange(0, 400)
        n = rng.randrange(1, 32)
        batch = [rng.choice(keys) for _ in range(n)]
        permits = [rng.randrange(1, 3) for _ in range(n)]
        got = limiter.try_acquire_many(batch, permits)
        for j in range(n):
            want = oracle.try_acquire(batch[j], permits[j], clock.t).allowed
            assert got[j] == want, (step, j)
        if rng.random() < 0.2:
            k = rng.choice(keys)
            limiter.reset(k)
            oracle.reset(k, clock.t)
        k = rng.choice(keys)
        assert limiter.get_available_permits(k) == oracle.get_available_permits(k, clock.t)
    storage.close()


def test_tb_tpu_backend_differential():
    clock = FakeClock()
    storage = TpuBatchedStorage(num_slots=512, max_delay_ms=0.2, clock_ms=clock)
    cfg = RateLimitConfig(max_permits=15, window_ms=2000, refill_rate=10.0)
    limiter = TokenBucketRateLimiter(storage, cfg, MeterRegistry(), clock_ms=clock)
    oracle = TokenBucketOracle(cfg)
    rng = random.Random(6)
    keys = [f"u{i}" for i in range(6)]
    for step in range(50):
        clock.t += rng.randrange(0, 600)
        n = rng.randrange(1, 32)
        batch = [rng.choice(keys) for _ in range(n)]
        permits = [rng.randrange(1, 18) for _ in range(n)]
        got = limiter.try_acquire_many(batch, permits)
        for j in range(n):
            want = oracle.try_acquire(batch[j], permits[j], clock.t).allowed
            assert got[j] == want, (step, j)
        k = rng.choice(keys)
        assert limiter.get_available_permits(k) == oracle.get_available_permits(k, clock.t)
    storage.close()


def test_single_acquire_through_batcher():
    clock = FakeClock()
    storage = TpuBatchedStorage(num_slots=64, max_delay_ms=0.1, clock_ms=clock)
    cfg = RateLimitConfig(max_permits=3, window_ms=60_000, enable_local_cache=False)
    limiter = SlidingWindowRateLimiter(storage, cfg, MeterRegistry(), clock_ms=clock)
    clock.t = (T0 // 60_000) * 60_000
    results = [limiter.try_acquire("u") for _ in range(5)]
    assert results == [True, True, True, False, False]
    storage.close()


def test_negative_cache_on_tpu_backend():
    clock = FakeClock((T0 // 60_000) * 60_000)
    storage = TpuBatchedStorage(num_slots=64, max_delay_ms=0.1, clock_ms=clock)
    cfg = RateLimitConfig(max_permits=2, window_ms=60_000,
                          enable_local_cache=True, local_cache_ttl_ms=10_000)
    registry = MeterRegistry()
    limiter = SlidingWindowRateLimiter(storage, cfg, registry, clock_ms=clock)
    assert limiter.try_acquire("u")
    assert limiter.try_acquire("u")
    assert not limiter.try_acquire("u")  # device-backed rejection, caches count
    hits0 = registry.counter("ratelimiter.cache.hits").count()
    assert not limiter.try_acquire("u")  # short-circuited host-side
    assert registry.counter("ratelimiter.cache.hits").count() == hits0 + 1
    storage.close()


# ---------------------------------------------------------------------------
# Concurrency (the reference's disabled 20-thread test, for real)
# ---------------------------------------------------------------------------

def test_concurrent_threads_never_exceed_limit():
    storage = TpuBatchedStorage(num_slots=64, max_delay_ms=0.3)
    cfg = RateLimitConfig(max_permits=10, window_ms=60_000, enable_local_cache=False)
    limiter = SlidingWindowRateLimiter(storage, cfg, MeterRegistry())
    n_threads, per_thread = 20, 10
    allowed = np.zeros(n_threads, dtype=np.int64)
    barrier = threading.Barrier(n_threads)

    def worker(i):
        barrier.wait()
        for _ in range(per_thread):
            if limiter.try_acquire("shared"):
                allowed[i] += 1

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # 200 requests against a 10/window limit: exactly 10 allowed.
    assert allowed.sum() == 10
    storage.close()


# ---------------------------------------------------------------------------
# Eviction under slot pressure
# ---------------------------------------------------------------------------

def test_eviction_reuses_slots_cleanly():
    clock = FakeClock()
    storage = TpuBatchedStorage(num_slots=8, max_delay_ms=0.1, clock_ms=clock)
    cfg = RateLimitConfig(max_permits=2, window_ms=60_000, enable_local_cache=False)
    limiter = SlidingWindowRateLimiter(storage, cfg, MeterRegistry(), clock_ms=clock)
    clock.t = (T0 // 60_000) * 60_000
    # Drain key k0's budget, then push enough distinct keys to evict it.
    assert limiter.try_acquire("k0")
    assert limiter.try_acquire("k0")
    assert not limiter.try_acquire("k0")
    for i in range(1, 9):
        assert limiter.try_acquire(f"k{i}")
    # k0 was evicted (LRU): it starts fresh — a documented consequence of
    # finite slot capacity; operators size num_slots >= active keys.
    assert limiter.try_acquire("k0")
    storage.close()


def test_legacy_contract_still_works_on_tpu_storage():
    clock = FakeClock()
    storage = TpuBatchedStorage(num_slots=16, clock_ms=clock)
    assert storage.increment_and_expire("c", 1000) == 1
    assert storage.get("c") == 1
    storage.set("c", 7, 1000)
    assert storage.compare_and_set("c", 7, 9)
    storage.z_add("z", 1.0, "m")
    assert storage.z_count("z", 0, 2) == 1
    assert storage.is_available()
    storage.close()


def test_stream_permits_over_i32_denied_not_wrapped():
    """The stream path carries permits as i32 lanes; a value past 2^31-1
    would wrap negative and turn a reject into an allow-with-credit.  It
    must be DENIED (identical to the i64 batch path, where any permits
    above int32 exceeds every limiter's max_permits) — and must not
    consume or credit tokens for neighbouring requests."""
    import numpy as np

    storage = TpuBatchedStorage(num_slots=64, clock_ms=lambda: 10_000)
    lid = storage.register_limiter(
        "tb", RateLimitConfig(max_permits=5, window_ms=1000, refill_rate=1.0))
    got = storage.acquire_stream_ids(
        "tb", lid, np.asarray([1, 1, 1], dtype=np.int64),
        np.asarray([1, 1 << 31, 4], dtype=np.int64), batch=16, subbatches=1)
    # 1 allowed; oversized denied; 4 still allowed (bucket untouched by #2).
    assert got.tolist() == [True, False, True]
    # Batch-path agreement on a fresh key.
    batch = storage.acquire_many_ids(
        "tb", lid, np.asarray([2], dtype=np.int64),
        np.asarray([1 << 31], dtype=np.int64))
    assert not batch["allowed"][0]
    storage.close()


@pytest.mark.parametrize("weighted", [False, True])
def test_chunk_plan_pipelined_preserves_decisions(monkeypatch, weighted):
    """Link-adaptive chunk plans (VERDICT r3 #1): a pipelined plan (the
    fast-link election outcome, forced here for determinism) runs fixed
    chunks with eager drains — decisions must match a plan-less storage
    pass-for-pass."""
    import ratelimiter_tpu.storage.tpu as tpu_mod

    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK", 256)
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK_MAX", 1 << 14)
    now = [1_000_000]
    rng = np.random.default_rng(3)
    n = 4096
    ids = rng.integers(0, 1500, n).astype(np.int64)
    perms = (rng.integers(1, 8, n).astype(np.int64) if weighted
             else None)

    def make(planned):
        st = TpuBatchedStorage(num_slots=4096, clock_ms=lambda: now[0])
        lid = st.register_limiter("tb", RateLimitConfig(
            max_permits=20, window_ms=60_000, refill_rate=1.0))
        if planned:  # what a fast-link election produces
            key = (("weighted", "ints", "tb", n) if weighted
                   else ("relay", "ints", "tb", False, n))
            st._chunk_plans[key] = {"kind": "pipelined", "chunk": 600,
                                    "ref": 1e9, "passes": 0, "best": None}
        return st, lid

    st_a, lid_a = make(True)
    st_b, lid_b = make(False)
    for _ in range(3):
        got_a = st_a.acquire_stream_ids("tb", lid_a, ids, perms)
        got_b = st_b.acquire_stream_ids("tb", lid_b, ids, perms)
        np.testing.assert_array_equal(got_a, got_b)
    # The huge ref wall keeps the plan from reverting mid-test.
    kinds = {k[0]: v["kind"] for k, v in st_a._chunk_plans.items()}
    want = "weighted" if weighted else "relay"
    assert kinds.get(want) == "pipelined", st_a._chunk_plans
    st_a.close()
    st_b.close()


def test_chunk_plan_election_logic():
    """Synthetic election inputs: a CPU-bound words pass elects a
    pipelined schedule (its wire is linear in requests — splitting is
    free and overlaps the fetch cycles); a wire-bound DIGEST pass with
    strong dedup keeps giant chunks on a slow link (splitting inflates
    the per-unique wire); a pipelined pass measuring clearly worse
    reverts (sticky)."""
    st = TpuBatchedStorage(num_slots=1 << 12)
    n = 1 << 24
    # Uniform words traffic: u ~ 0.9 n, wire 4.125 B/request.
    giant_tot = {"walk_s": 1.6, "host_s": 0.4, "wire": 4.125 * n,
                 "giant": n - (1 << 19), "fetch_s": 1.5, "chunks": 2,
                 "digest_chunks": 0, "bpr": 4.125, "device_s": 1.0,
                 "cu": [(1 << 19, 480_000), (n - (1 << 19), 14_800_000)]}
    # The FIRST measurement only records a provisional giant (fresh
    # shapes' first passes are insert- and compile-heavy); the second
    # elects for real.
    st.set_link_profile(85e6, 0.107, 85e6)
    st._elect_chunk_plan(("relay", "ints", "tb", False, n), n, giant_tot, 3.5)
    assert st._chunk_plans[("relay", "ints", "tb", False, n)]["kind"] == "giant"
    st._elect_chunk_plan(("relay", "ints", "tb", False, n), n, giant_tot, 3.5)
    plan = st._chunk_plans[("relay", "ints", "tb", False, n)]
    assert plan["kind"] == "pipelined" and plan["chunk"] >= 1 << 19, plan
    assert sum(plan["schedule"]) >= n, plan  # schedule covers the stream
    # Wire-bound slow-link DIGEST pass with strong dedup (u ~ c^0.6):
    # splitting multiplies the per-unique upload — giant stays.
    st.set_link_profile(5e6, 0.107, 5e6)
    slow_tot = {"walk_s": 0.05, "host_s": 0.02, "wire": 8.1e6,
                "giant": n - (1 << 19), "fetch_s": 3.0, "chunks": 2,
                "digest_chunks": 2, "bpu": 6.0, "device_s": 0.07,
                "cu": [(1 << 19, 150_000), (n - (1 << 19), 1_200_000)]}
    st._elect_chunk_plan(("relay", "ints", "tb", False, n), n, slow_tot, 3.2)
    st._elect_chunk_plan(("relay", "ints", "tb", False, n), n, slow_tot, 3.2)
    assert st._chunk_plans[("relay", "ints", "tb", False, n)]["kind"] == "giant"
    # Revert: pipelined passes clearly worse than the serial baseline
    # (first pass alone is NOT enough — it pays the new shapes' compiles).
    st.set_link_profile(85e6, 0.107)
    st._chunk_plans.clear()
    st._elect_chunk_plan(("relay", "ints", "tb", False, n), n, giant_tot, 0.95)
    st._elect_chunk_plan(("relay", "ints", "tb", False, n), n, giant_tot, 0.95)
    ref = st._chunk_plans[("relay", "ints", "tb", False, n)]["ref"]
    st._maybe_revert_plan(("relay", "ints", "tb", False, n), 10.0)
    assert st._chunk_plans[("relay", "ints", "tb", False, n)]["kind"] == "pipelined"
    st._maybe_revert_plan(("relay", "ints", "tb", False, n), 2.0 * ref)
    assert st._chunk_plans[("relay", "ints", "tb", False, n)]["kind"] == "giant"
    # A reverted plan is LOCKED: a later clean giant pass must not
    # re-elect it back to pipelined (shape oscillation).
    st._elect_chunk_plan(("relay", "ints", "tb", False, n), n, giant_tot, 0.95)
    assert st._chunk_plans[("relay", "ints", "tb", False, n)]["kind"] == "giant"
    # Whereas a PROVISIONAL giant (compile-contaminated first pass:
    # huge measured fetch) is re-elected once clean measurements arrive.
    st._chunk_plans.clear()
    dirty = dict(giant_tot, fetch_s=12.0)  # compiles inside the fetches
    st._elect_chunk_plan(("relay", "ints", "tb", False, n), n, dirty, 13.0)
    assert st._chunk_plans[("relay", "ints", "tb", False, n)]["kind"] == "giant"
    st._elect_chunk_plan(("relay", "ints", "tb", False, n), n, giant_tot, 0.95)
    assert st._chunk_plans[("relay", "ints", "tb", False, n)]["kind"] == "pipelined"
    st.close()


def test_link_probe_and_profile_reset():
    """probe_link measures once and feeds the storage profile with a
    bandwidth that cannot be the broken-probe floor clamp, and setting
    a new profile clears cached chunk plans (they were elected for the
    old link)."""
    from ratelimiter_tpu.utils.link import PROBE_BYTES

    st = TpuBatchedStorage(num_slots=256)
    prof = st.probe_link()
    # The probe clamps up_s to >= 1e-6 s; a measurement AT the clamp
    # (PROBE_BYTES / 1e-6) means the timing collapsed — treat as broken.
    assert st._link_profile == prof
    assert 0 < prof[0] < PROBE_BYTES / 1e-6
    assert 0 < prof[1] < 60.0  # a round trip measured, under a minute
    st._chunk_plans[("relay", "ints", "tb", False, 4096)] = {
        "kind": "pipelined", "chunk": 512, "ref": 1.0,
        "giant_wall": 1.2, "passes": 0, "best": None}
    st.set_link_profile(1e9, 0.001)
    assert st._link_profile == (1e9, 0.001, 1e9)  # down defaults to up
    assert st._chunk_plans == {}
    st.close()


def test_rate_aware_mode_election():
    """_elect_digest_mode: on fast links the sorted digest's cheaper
    device step wins even where its wire cost loses; on slow links wire
    dominates and the verdict matches the bytes-only fallback."""
    from ratelimiter_tpu.storage.tpu import _elect_digest_mode

    dig_bpu, words_bpr = 6.0, 4.125
    u, cn = 900_000, 1_000_000  # u/n = 0.9: wire alone says words
    assert not _elect_digest_mode(None, u, cn, 0, dig_bpu, words_bpr,
                                  True)  # bytes-only fallback: words
    # 85 MB/s, sorted sweep engaged: device savings flip it to digest.
    assert _elect_digest_mode((85e6, 0.1), u, cn, 0, dig_bpu, words_bpr,
                              True)
    # Same link but the sweep can't engage (unsorted 52 ns): words.
    assert not _elect_digest_mode((85e6, 0.1), u, cn, 0, dig_bpu,
                                  words_bpr, False)
    # 5 MB/s: wire dominates; digest only wins with real dedup.
    assert not _elect_digest_mode((5e6, 0.1), u, cn, 0, dig_bpu,
                                  words_bpr, True)
    assert _elect_digest_mode((5e6, 0.1), cn // 3, cn, 0, dig_bpu,
                              words_bpr, True)
    # Multi-lid costs (10 B/unique vs 8.125 B/request) with the delta
    # charge: dedup-poor chunks stay words, dedup-rich ones go digest.
    assert not _elect_digest_mode((5e6, 0.1), 950_000, cn, 950_000, 10.0,
                                  8.125, True)
    assert _elect_digest_mode((5e6, 0.1), cn // 3, cn, cn // 3, 10.0,
                              8.125, True)


def test_digest_mode_election_flips_with_device_rates():
    """VERDICT r4 #5: the words-vs-digest election consumes the PROBED
    device rates — on a device with a cheap per-lane words step the
    same chunk elects words, on one with an expensive step it elects
    digest (wire identical in both cases)."""
    from ratelimiter_tpu.storage.tpu import _elect_digest_mode

    link = (50e6, 0.1, 50e6)
    base = {"s_per_unique_sorted": 25e-9, "s_per_unique_unsorted": 52e-9}
    fast_lane = dict(base, s_per_lane=5e-9)
    slow_lane = dict(base, s_per_lane=300e-9)
    kw = dict(u=900, cn=1000, n_delta=0, digest_bpu=6.0, words_bpr=4.125,
              srt_ok=False, cdt_size=1)
    assert _elect_digest_mode(link, rates=slow_lane, **kw) is True
    assert _elect_digest_mode(link, rates=fast_lane, **kw) is False


def test_device_rates_fallback_and_cache(monkeypatch, tmp_path):
    """RATELIMITER_RATE_PROBE=0 yields the v5e fallback constants; a
    pre-seeded disk cache is honored without probing; both are
    memoized per (platform, kind)."""
    import json as _json

    import jax

    from ratelimiter_tpu.engine import device_rates as dr

    monkeypatch.setattr(dr, "_mem_cache", {})
    monkeypatch.setenv("RATELIMITER_RATE_PROBE", "0")
    old = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", str(tmp_path))
    got = dr.get_device_rates()
    assert got["source"] == "fallback"
    assert got["s_per_lane"] == dr.FALLBACK_RATES["s_per_lane"]
    # Seed the disk cache as a probe artifact would; a fresh mem cache
    # must read it instead of falling back (or probing).
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", dev.platform)
    path = dr._cache_path(dev.platform, kind)
    assert str(tmp_path) in path
    rates = {"s_per_lane": 1e-9, "s_per_unique_sorted": 2e-9,
             "s_per_unique_unsorted": 3e-9, "source": "probe"}
    import os as _os

    _os.makedirs(_os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        _json.dump(rates, fh)
    monkeypatch.setattr(dr, "_mem_cache", {})
    try:
        # The opt-out beats the disk artifact (determinism pin) ...
        assert dr.get_device_rates()["source"] == "fallback"
        # ... and with probing allowed, the artifact is honored without
        # re-probing.
        monkeypatch.setenv("RATELIMITER_RATE_PROBE", "1")
        monkeypatch.setattr(dr, "_probe", lambda: (_ for _ in ()).throw(
            AssertionError("disk cache must prevent probing")))
        monkeypatch.setattr(dr, "_mem_cache", {})
        got2 = dr.get_device_rates()
    finally:
        jax.config.update("jax_compilation_cache_dir", old)
    assert got2["s_per_lane"] == 1e-9 and got2["source"] == "probe"


def test_schedule_candidates_invariants():
    """Every candidate schedule covers n exactly, never emits a chunk
    above _RELAY_CHUNK_MAX, and never ends in a sub-floor crumb (the
    last entry sizes OVERFLOW chunks when a longer stream reuses a
    banded plan — an RTT-sized tail entry would drain the overflow in
    crumbs)."""
    from ratelimiter_tpu.storage.tpu import (
        _RELAY_CHUNK,
        _RELAY_CHUNK_MAX,
        _schedule_candidates,
    )

    for n in (1 << 24, (1 << 24) + 1234, 12_582_912,
              _RELAY_CHUNK + _RELAY_CHUNK_MAX + 300_000, 1 << 26):
        for words_pow2 in (False, True):
            for sched in _schedule_candidates(n, _RELAY_CHUNK, words_pow2):
                assert sum(sched) == n, (n, words_pow2, sched)
                assert max(sched) <= _RELAY_CHUNK_MAX, sched
                assert sched[-1] >= _RELAY_CHUNK, (n, words_pow2, sched)
    assert _schedule_candidates(2 * _RELAY_CHUNK, _RELAY_CHUNK,
                                False) == []  # short streams: no plan


def test_chunk_cursor_overflow_uses_last_entry():
    """A stream longer than its banded plan's schedule drains the
    overflow at the LAST entry's size (never crumbs), and peek() sizes
    the prefetch identically to the next next_size()."""
    from ratelimiter_tpu.storage.tpu import _ChunkCursor

    plan = {"kind": "pipelined", "schedule": (100, 500, 200),
            "chunk": 500}
    cur = _ChunkCursor(plan, True)
    n = 1600  # 800 scheduled + 800 overflow
    sizes, start = [], 0
    while start < n:
        peek = cur.peek(n - start) if sizes else None
        c = cur.next_size(n - start)
        if peek is not None:
            assert peek == c
        sizes.append(c)
        start += c
    assert sizes == [100, 500, 200, 200, 200, 200, 200]
    # Legacy int-chunk plans still honor growth.
    cur2 = _ChunkCursor({"kind": "pipelined", "chunk": 300}, True)
    assert cur2.next_size(10_000) == 300
    cur2.grow(700)
    assert cur2.next_size(10_000) == 700


def test_drain_set_error_propagation_and_backpressure():
    """_DrainSet: finish() re-raises the first drain error once all
    drains land; finish(swallow=True) waits but never raises (the
    primary-exception path); submit() bounds in-flight drains."""
    import concurrent.futures as cf
    import threading
    import time as _time

    from ratelimiter_tpu.storage.tpu import _DrainSet

    pool = cf.ThreadPoolExecutor(4)
    try:
        ds = _DrainSet(pool, inflight=2)
        done = []

        def ok(i):
            _time.sleep(0.01)
            done.append(i)

        def boom(i):
            raise RuntimeError(f"drain {i} failed")

        ds.submit(ok, 1)
        ds.submit(boom, 2)
        ds.submit(ok, 3)
        with pytest.raises(RuntimeError, match="drain 2 failed"):
            ds.finish()
        assert sorted(done) == [1, 3]  # every drain ran to completion
        ds.finish()  # cleared: a second finish is a no-op
        # swallow=True: waits, never raises.
        ds.submit(boom, 4)
        ds.finish(swallow=True)
        # Backpressure: with inflight=2, the third submit must WAIT on
        # the oldest live drain (released by a timer thread) instead of
        # queueing unboundedly — measured by the submit's block time.
        gate = threading.Event()
        slow_done = []

        def slow(i):
            gate.wait(5.0)
            slow_done.append(i)

        ds.submit(slow, 1)
        ds.submit(slow, 2)
        threading.Timer(0.2, gate.set).start()
        t0 = _time.perf_counter()
        ds.submit(slow, 3)  # blocks on live[0] until the gate opens
        blocked = _time.perf_counter() - t0
        ds.finish()
        assert sorted(slow_done) == [1, 2, 3]
        assert blocked >= 0.15, blocked  # the cap actually held
    finally:
        pool.shutdown(wait=False)


# ---------------------------------------------------------------------------
# Clock-regression clamp observability
# ---------------------------------------------------------------------------

def test_backward_clock_clamped_and_counted():
    """A wall clock stepping backwards (NTP) is absorbed by the monotonic
    stamp clamp — and now COUNTED in ratelimiter.time.backward_clamp so
    the event is observable instead of silent."""
    clock = FakeClock()
    registry = MeterRegistry()
    storage = TpuBatchedStorage(num_slots=64, max_delay_ms=0.1,
                                clock_ms=clock, meter_registry=registry)
    try:
        meter = registry.counter("ratelimiter.time.backward_clamp")
        assert storage._monotonic_now() == T0
        clock.t = T0 - 5_000  # NTP step backwards
        assert storage._monotonic_now() == T0  # clamped, not regressed
        assert storage.backward_clamps == 1
        assert meter.count() == 1
        clock.t = T0 - 1  # still behind: every regressed read counts
        assert storage._monotonic_now() == T0
        assert storage.backward_clamps == 2
        assert meter.count() == 2
        clock.t = T0 + 7
        assert storage._monotonic_now() == T0 + 7  # clock caught up
        assert storage.backward_clamps == 2

        # Decisions keep flowing at the clamped stamp: a regressed batch
        # must not roll windows backwards or zero live counts.
        lid = storage.register_limiter("sw", RateLimitConfig(
            max_permits=3, window_ms=60_000, enable_local_cache=False))
        clock.t = ((T0 + 7) // 60_000) * 60_000 + 120_000  # fresh window
        allowed = [storage.acquire("sw", lid, "ntp", 1)["allowed"]
                   for _ in range(3)]
        clock.t -= 90_000  # regress past a window boundary
        denied = storage.acquire("sw", lid, "ntp", 1)["allowed"]
        assert allowed == [True, True, True] and not denied
        assert storage.backward_clamps >= 3
    finally:
        storage.close()
