"""Fleet autopilot (ISSUE 16): node lifecycle, the executor boundary's
boot pathologies, the strict ready-line contract, automated re-seed
bookkeeping, and the service-plane fold.

Layers under test, bottom-up:

- parse_ready (replication/remote.py): the explicit ``lid_base``
  contract — registered lids without a base (or a disagreeing one)
  fail loudly instead of silently assuming the lids-start-at-1
  convention; pre-fleet lines normalize to one v0 shard;
- mux_handlers (replication/control.py): shard-addressed dispatch and
  the one-RPC-per-node ``probe_all``;
- LocalExecutor (fleet/executor.py): every boot pathology —
  spawn timeout, early exit, malformed/non-object ready line — is a
  typed SpawnError, and a REAL hostproc node honors stdin EOF (clean
  rc=0 through retire());
- NodeManager (fleet/manager.py): lifecycle transitions and their
  refusals, double-adopt refusal (name and control endpoint), the
  probe-fail streak and process-exit paths to FAILED;
- FleetAutopilot (fleet/autopilot.py): the drain-aware witness wrap
  and the re-seed deadline (a wedged job FAILS loudly, never wedges
  the tick);
- FailoverOrchestrator._validate_timing: the misconfiguration warnings
  (flight events, never raises);
- service plane: GET /actuator/fleet and the FAILED/DRAINING ->
  DEGRADED health fold;
- the full thing: rolling_upgrade_drill — every node of a live 2-shard
  cell replaced under Zipf traffic with a mid-upgrade primary kill,
  bit-identical to the oracle, N+1 at the end.
"""

import sys
import threading
import types

import pytest

from ratelimiter_tpu.fleet import (
    DRAINING,
    FAILED,
    LocalExecutor,
    NodeManager,
    READY,
    RETIRED,
    SERVING,
    SpawnError,
)
from ratelimiter_tpu.fleet.autopilot import FleetAutopilot
from ratelimiter_tpu.replication.control import mux_handlers
from ratelimiter_tpu.replication.remote import parse_ready


class _Recorder:
    """Flight-recorder stub: captures (kind, fields) tuples."""

    def __init__(self):
        self.events = []

    def record(self, kind, **fields):
        self.events.append((kind, fields))

    def kinds(self):
        return [k for k, _ in self.events]


class _Ctl:
    """ControlClient stub with a scripted probe answer."""

    def __init__(self, answer="ok", shards=1):
        self.answer = answer
        self.shards = shards
        self.closed = False
        self.calls = []

    def try_call(self, op, timeout=None, **kw):
        self.calls.append(op)
        if self.answer == "dead":
            return None
        if op == "probe_all":
            if self.answer == "bare":
                return None  # pre-fleet node: no mux, fall back
            return {"ok": True, "shards": {
                str(q): {"ok": True, "available": True}
                for q in range(self.shards)}}
        if op == "probe":
            return {"ok": True, "available": True}
        return None

    def close(self):
        self.closed = True


class _DeadExecutor:
    """Executor whose processes are never alive (exit-detection path)."""

    def alive(self, handle):
        return False

    def terminate(self, handle, grace_s=10.0):
        pass

    def kill(self, handle):
        pass


_READY = {"ready": True, "role": "primary", "control_port": 7001}


def _manager(**kw):
    kw.setdefault("recorder", _Recorder())
    return NodeManager(executor=kw.pop("executor", _DeadExecutor()), **kw)


# ---------------------------------------------------------------------------
# parse_ready: the explicit lid_base contract
# ---------------------------------------------------------------------------

def test_parse_ready_requires_lid_base_with_lids():
    with pytest.raises(ValueError, match="no lid_base"):
        parse_ready({"ready": True, "role": "primary",
                     "control_port": 1, "lids": [3, 4]})


def test_parse_ready_rejects_disagreeing_lid_base():
    with pytest.raises(ValueError, match="disagrees with min"):
        parse_ready({"ready": True, "role": "primary", "control_port": 1,
                     "lids": [3, 4], "lid_base": 1})


def test_parse_ready_flattens_multi_shard_lid_lists():
    info = parse_ready({"ready": True, "role": "standby",
                        "control_port": 1, "shards": 2,
                        "lids": [[5, 6], [5, 6]], "lid_base": 5})
    assert info["shards"] == 2


def test_parse_ready_normalizes_pre_fleet_lines():
    # A pre-fleet node's line (no shards/version) is one v0 shard;
    # scalar lids back-compat rides the same path.
    info = parse_ready({"ready": True, "role": "primary",
                        "control_port": 1, "lids": [1, 2], "lid_base": 1})
    assert info["shards"] == 1 and info["version"] == "v0"


@pytest.mark.parametrize("line, match", [
    ({"role": "primary", "control_port": 1}, "not a hostproc ready"),
    ({"ready": True, "role": "primary"}, "missing control_port"),
    ({"ready": True, "role": "witness", "control_port": 1},
     "unknown role"),
    ("ready", "not a hostproc ready"),
])
def test_parse_ready_rejects_malformed_lines(line, match):
    with pytest.raises(ValueError, match=match):
        parse_ready(line)


# ---------------------------------------------------------------------------
# mux_handlers: shard addressing + probe_all
# ---------------------------------------------------------------------------

def test_mux_dispatch_and_probe_all():
    handlers = mux_handlers({
        0: {"probe": lambda: {"available": True},
            "poke": lambda x: {"shard": 0, "x": x}},
        1: {"probe": lambda: {"available": False}},
    }, extra={"version": lambda: {"v": "v1"}})
    # Default shard is 0 (single-shard callers keep working verbatim).
    assert handlers["poke"](x=9) == {"shard": 0, "x": 9}
    out = handlers["probe_all"]()["shards"]
    assert out["0"] == {"ok": True, "available": True}
    assert out["1"] == {"ok": True, "available": False}
    assert handlers["version"]() == {"v": "v1"}
    with pytest.raises(ValueError, match="unknown shard"):
        handlers["probe"](shard=7)
    with pytest.raises(ValueError, match="not served by shard"):
        handlers["poke"](shard=1, x=1)


def test_probe_all_isolates_a_raising_shard():
    handlers = mux_handlers({
        0: {"probe": lambda: {"available": True}},
        1: {"probe": lambda: (_ for _ in ()).throw(RuntimeError("boom"))},
    })
    out = handlers["probe_all"]()["shards"]
    assert out["0"]["ok"] is True
    assert out["1"]["ok"] is False and "boom" in out["1"]["error"]


# ---------------------------------------------------------------------------
# LocalExecutor: boot pathologies through argv_prefix overrides
# ---------------------------------------------------------------------------

def _pathological(script, timeout):
    return LocalExecutor(argv_prefix=[sys.executable, "-c", script],
                         boot_timeout_s=timeout)


def test_spawn_timeout_is_a_spawn_error():
    ex = _pathological("import time; time.sleep(60)", 0.5)
    with pytest.raises(SpawnError, match="no ready line within"):
        ex.spawn([])


def test_early_exit_is_a_spawn_error():
    ex = _pathological("raise SystemExit(3)", 10.0)
    # rc may lag the EOF (the child is not reaped yet when readline
    # returns), so only the pathology class is asserted, not the code.
    with pytest.raises(SpawnError, match="before printing a ready line"):
        ex.spawn([])


def test_malformed_ready_line_is_a_spawn_error():
    ex = _pathological("print('not json'); import time; time.sleep(60)",
                       10.0)
    with pytest.raises(SpawnError, match="malformed ready line"):
        ex.spawn([])


def test_non_object_ready_line_is_a_spawn_error():
    ex = _pathological("print('[1, 2]'); import time; time.sleep(60)",
                       10.0)
    with pytest.raises(SpawnError, match="not a JSON object"):
        ex.spawn([])


def test_hostproc_honors_stdin_eof():
    """A REAL standby node spawned through the manager retires with a
    clean rc=0 on stdin EOF — the graceful half of every rolling-
    upgrade step."""
    mgr = NodeManager(probe_interval_ms=60_000.0, recorder=_Recorder())
    try:
        node = mgr.spawn("n", "standby", shards=1, num_slots=128,
                         boot_timeout_s=180.0)
        assert node.state == READY and node.role == "standby"
        mgr.retire("n", grace_s=20.0)
        assert node.state == RETIRED
        assert node.handle.proc.returncode == 0, (
            "hostproc ignored stdin EOF (escalated to terminate/kill)")
    finally:
        mgr.close()


# ---------------------------------------------------------------------------
# NodeManager lifecycle
# ---------------------------------------------------------------------------

def test_lifecycle_transitions_and_refusals():
    rec = _Recorder()
    mgr = _manager(recorder=rec)
    node = mgr.adopt("a", dict(_READY), ctl=_Ctl())
    assert node.state == READY
    mgr.mark_serving("a")
    assert node.state == SERVING
    mgr.mark_draining("a")
    assert node.state == DRAINING
    assert mgr.degraded_nodes() == ["a"]
    with pytest.raises(ValueError, match="cannot serve"):
        mgr.mark_serving("a")  # DRAINING never un-drains back to SERVING
    mgr.retire("a")
    assert node.state == RETIRED and node.ctl.closed
    with pytest.raises(ValueError, match="cannot drain"):
        mgr.mark_draining("a")
    mgr.retire("a")  # terminal retire is idempotent
    assert [k for k in rec.kinds() if k == "fleet.transition"], rec.events


def test_adopt_refuses_duplicate_name_and_endpoint():
    mgr = _manager()
    mgr.adopt("a", dict(_READY), ctl=_Ctl())
    with pytest.raises(ValueError, match="already managed"):
        mgr.adopt("a", {"ready": True, "role": "primary",
                        "control_port": 7002}, ctl=_Ctl())
    with pytest.raises(ValueError, match="double-adopt"):
        mgr.adopt("b", dict(_READY), ctl=_Ctl())
    # A FAILED node releases its endpoint: the replacement can re-bind.
    mgr.fail("a")
    mgr.adopt("b", dict(_READY), ctl=_Ctl())
    assert mgr.live_nodes() == ["b"]


def test_probe_fail_streak_declares_failed():
    mgr = _manager(probe_fail_threshold=3)
    ctl = _Ctl(answer="dead")
    node = mgr.adopt("a", dict(_READY), ctl=ctl)
    mgr.tick()
    mgr.tick()
    assert node.state == READY and node.probe_fail_streak == 2
    mgr.tick()
    assert node.state == FAILED and ctl.closed
    assert "3 consecutive probe failures" in node.last_error
    assert mgr.degraded_nodes() == ["a"]
    streak = node.probe_fail_streak
    mgr.tick()  # terminal nodes are left alone
    assert node.probe_fail_streak == streak


def test_process_exit_declares_failed():
    mgr = _manager()
    node = mgr.adopt("a", dict(_READY), ctl=_Ctl(), handle=object())
    mgr.tick()
    assert node.state == FAILED and node.last_error == "process exited"


def test_probe_all_and_bare_probe_fallback():
    mgr = _manager()
    muxed = mgr.adopt("m", {"ready": True, "role": "primary",
                            "control_port": 7001, "shards": 2},
                      ctl=_Ctl(shards=2))
    bare = mgr.adopt("b", {"ready": True, "role": "primary",
                           "control_port": 7002}, ctl=_Ctl(answer="bare"))
    mgr.tick()
    assert sorted(muxed.last_probe) == ["0", "1"]
    assert list(bare.last_probe) == ["0"]  # pre-fleet single-shard shape
    assert bare.ctl.calls == ["probe_all", "probe"]
    st = mgr.status()["nodes"]
    assert st["m"]["state"] == READY and st["b"]["state"] == READY


# ---------------------------------------------------------------------------
# FleetAutopilot: drain-aware witness + the re-seed deadline
# ---------------------------------------------------------------------------

def _autopilot(mgr, standby_set, clock, **kw):
    orch = kw.pop("orch", types.SimpleNamespace(
        router=types.SimpleNamespace(serving=lambda q: object()),
        cfg=types.SimpleNamespace(fence_lease_ttl_ms=0.0)))
    return FleetAutopilot(mgr, orch, standby_set, witness_ctls={},
                          recorder=kw.pop("recorder", _Recorder()),
                          clock=lambda: clock["t"], **kw)


def test_witness_wrap_folds_draining_to_dead():
    mgr = types.SimpleNamespace(
        nodes={"P": types.SimpleNamespace(state=DRAINING)})
    standby_set = types.SimpleNamespace(n_shards=2, receivers=[])
    pilot = _autopilot(mgr, standby_set, {"t": 0.0})
    pilot.bind(0, ("P", 0), ("S", 0))
    witness = pilot.witness_wrap(lambda q: "alive")
    assert witness(0) == "dead"  # serving node is scheduled out
    assert witness(1) == "alive"  # unbound shard defers to the inner
    mgr.nodes["P"].state = SERVING
    assert witness(0) == "alive"


def test_reseed_deadline_fails_loudly_without_wedging():
    class _Mgr:
        nodes = {}

        def mark_serving(self, name):
            pass

        def spawn(self, *a, **kw):
            raise RuntimeError("no capacity")

    rx = types.SimpleNamespace(promoted=True, consistent=False)
    standby_set = types.SimpleNamespace(n_shards=1, receivers=[rx])
    clock = {"t": 0.0}
    rec = _Recorder()
    pilot = _autopilot(_Mgr(), standby_set, clock, recorder=rec,
                       reseed_deadline_s=5.0)
    pilot.bind(0, ("P", 0), ("S", 0))
    pilot.tick()
    assert pilot.status()["jobs"]["0"]["state"] == "spawn"
    assert "RuntimeError: no capacity" in pilot._jobs[0]["error"]
    # The consumed standby became the serving binding.
    assert pilot.serving_placement(0) == ("S", 0)
    clock["t"] = 6.0
    pilot.tick()  # past the deadline: loud failure, job slot released
    assert pilot._jobs == {}
    assert len(pilot.failed_jobs) == 1
    assert pilot.failed_jobs[0]["q"] == 0
    assert "no capacity" in pilot.failed_jobs[0]["error"]
    assert "fleet.reseed_deadline" in rec.kinds()
    pilot.tick()  # the standby is still consumed: a fresh job reopens
    assert pilot.status()["jobs"]["0"]["state"] == "spawn"


# ---------------------------------------------------------------------------
# Orchestrator timing validation (warn, never raise)
# ---------------------------------------------------------------------------

def _orch(rec, cfg_kw=None, **kw):
    from ratelimiter_tpu.replication.orchestrator import (
        FailoverOrchestrator,
        OrchestratorConfig,
    )

    router = types.SimpleNamespace(n_shards=1)
    return FailoverOrchestrator(
        router, None, None,
        config=OrchestratorConfig(probe_interval_ms=100.0,
                                  suspect_threshold=3,
                                  hysteresis_ms=500.0,
                                  **(cfg_kw or {})),
        recorder=rec, **kw)


def _problems(rec):
    return [f["problem"] for k, f in rec.events
            if k == "orchestrator.misconfigured"]


def test_misconfiguration_warnings_fire_at_construction():
    rec = _Recorder()
    # Budget = 4 probes * 100ms + 500ms hysteresis = 900ms.
    _orch(rec, witness_fresh_ms=100.0, repl_heartbeat_ms=100.0)
    assert any("under the replication" in p for p in _problems(rec))

    rec = _Recorder()
    _orch(rec, witness_fresh_ms=900.0, repl_heartbeat_ms=100.0)
    assert any("at or past the detection" in p for p in _problems(rec))

    rec = _Recorder()
    _orch(rec, cfg_kw={"fence_lease_ttl_ms": 800.0})
    assert any("fence_lease_ttl_ms" in p for p in _problems(rec))


def test_well_configured_orchestrator_records_nothing():
    rec = _Recorder()
    _orch(rec, cfg_kw={"fence_lease_ttl_ms": 2000.0},
          witness_fresh_ms=400.0, repl_heartbeat_ms=100.0)
    assert _problems(rec) == []


# ---------------------------------------------------------------------------
# Service plane: GET /actuator/fleet + the health fold
# ---------------------------------------------------------------------------

def test_fleet_actuator_and_health_fold():
    import http.client
    import json as _json

    from ratelimiter_tpu.service.app import health_payload, make_server
    from ratelimiter_tpu.service.props import AppProperties
    from ratelimiter_tpu.service.wiring import build_app

    # OFF by default: no manager wired, no fleet section in health.
    ctx0 = build_app(AppProperties({"storage.backend": "memory"}))
    try:
        assert ctx0.fleet is None
        assert "fleet" not in health_payload(ctx0)
    finally:
        ctx0.close()

    ctx = build_app(AppProperties({
        "storage.backend": "memory",
        "ratelimiter.fleet.enabled": "true",
        "ratelimiter.fleet.probe_interval_ms": "60000",
    }))
    srv = make_server(ctx, port=0)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    try:
        ctx.fleet.adopt("n1", dict(_READY), ctl=_Ctl())
        payload = health_payload(ctx)
        assert payload["status"] == "UP"
        assert payload["fleet"]["live_nodes"] == ["n1"]
        assert payload["fleet"]["degraded_nodes"] == []

        conn = http.client.HTTPConnection(
            "127.0.0.1", srv.server_address[1], timeout=10)
        conn.request("GET", "/actuator/fleet")
        body = _json.loads(conn.getresponse().read())
        conn.close()
        assert body["enabled"] is True
        assert body["nodes"]["n1"]["state"] == READY

        # FAILED folds the cell to DEGRADED — capacity moved or moving,
        # never DOWN (the orchestrator's terminal-FAILED covers that).
        ctx.fleet.fail("n1", "declared dead by test")
        payload = health_payload(ctx)
        assert payload["status"] == "DEGRADED"
        assert payload["fleet"]["degraded_nodes"] == ["n1"]
    finally:
        srv.shutdown()
        ctx.close()


# ---------------------------------------------------------------------------
# The multi-process drill
# ---------------------------------------------------------------------------

def test_rolling_upgrade_drill_fast():
    from ratelimiter_tpu.storage.chaos import rolling_upgrade_drill

    report = rolling_upgrade_drill()
    assert report["mismatches"] == 0 and report["decisions"] > 0
    assert report["promotions"] == 4
    assert report["respawns"] == 4 and report["reseeds"] == 4
    assert report["upgrade_steps"] == 2
    # The mid-upgrade kill's fence was undeliverable: promotion waited
    # out the dead node's serving lease.
    assert report["kill_promote_s"] >= 0.6


@pytest.mark.slow
def test_rolling_upgrade_soak_slow():
    """The 3-node cell (single-shard primaries P0/P1 + standby S):
    three drain steps instead of two, every other invariant identical."""
    from ratelimiter_tpu.storage.chaos import rolling_upgrade_drill

    report = rolling_upgrade_drill(full=True)
    assert report["mismatches"] == 0
    assert report["upgrade_steps"] == 3
    assert report["promotions"] == 4 and report["reseeds"] == 4
