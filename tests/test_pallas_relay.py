"""Differential tests for the fused Pallas relay-step kernel
(ops/pallas/relay_step.py), driven in interpret mode on CPU.

The kernel must be BIT-identical to the composed-XLA digest step (and
therefore to semantics/oracle.py, which the composed step is already
differentially tested against) for both algorithms, across rank_bits
and counts dtypes, through clear interleavings, and at the engine
dispatch layer where the per-path election selects it.
"""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ratelimiter_tpu.core.config import RateLimitConfig
from ratelimiter_tpu.engine.state import LimiterTable
from ratelimiter_tpu.ops import relay
from ratelimiter_tpu.ops.pallas import election
from ratelimiter_tpu.ops.pallas import relay_step as rs
from ratelimiter_tpu.ops.sliding_window import make_sw_packed
from ratelimiter_tpu.ops.token_bucket import make_tb_packed


@pytest.fixture()
def fused_interpret(monkeypatch):
    """Force the fused path live on CPU: interpret-mode kernel, fresh
    probe, fresh election (interpret elects unconditionally)."""
    monkeypatch.setattr(rs, "_INTERPRET", True)
    monkeypatch.setattr(rs, "_probe_ok", None)
    election.reset_for_tests()
    yield
    election.reset_for_tests()


def _sorted_uwords(rng, s_rows, u, n_real, rank_bits, max_count=8,
                   clamp_some=False):
    slots = np.sort(rng.choice(s_rows, size=n_real,
                               replace=False)).astype(np.uint32)
    cmax = (1 << rank_bits) - 1
    counts = rng.integers(1, min(max_count, cmax) + 1,
                          n_real).astype(np.uint32)
    if clamp_some and n_real > 2:
        counts[rng.integers(0, n_real, 2)] = cmax
    uw = np.full(u, 0xFFFFFFFF, dtype=np.uint32)
    uw[:n_real] = (slots << np.uint32(rank_bits + 1)) | (
        counts << np.uint32(1))
    return uw, slots, counts


@pytest.mark.parametrize("algo", ["tb", "sw"])
@pytest.mark.parametrize("s_rows,out_np", [
    (512, np.uint8),      # rank_bits 21 — the supported ceiling
    (1024, np.uint16),    # uint16 counts wire format
    (4096, np.uint8),     # rank_bits 18, multi-block windows
])
def test_fused_matches_xla_digest(algo, s_rows, out_np):
    """Multi-step randomized differential: identical counts AND state
    vs the composed-XLA step, across geometries and counts dtypes,
    including clamp-sentinel counts and padding tails."""
    rng = np.random.default_rng(19 + s_rows)
    rb = 31 - int(s_rows).bit_length()
    table = LimiterTable()
    lid = jnp.int32(table.register(RateLimitConfig(
        max_permits=min(9, (1 << rb) - 2), window_ms=900,
        refill_rate=4.0)))
    tarr = table.device_arrays
    jdt = jnp.uint8 if out_np == np.uint8 else jnp.uint16
    ref_fn = jax.jit(functools.partial(
        relay.tb_relay_counts if algo == "tb" else relay.sw_relay_counts,
        rank_bits=rb, out_dtype=jdt))
    fused_fn = jax.jit(functools.partial(
        rs.tb_relay_counts_fused if algo == "tb"
        else rs.sw_relay_counts_fused,
        rank_bits=rb, out_dtype=jdt, interpret=True))
    make = make_tb_packed if algo == "tb" else make_sw_packed
    st_r, st_f = make(s_rows), make(s_rows)
    now = 1
    for step in range(6):
        now += int(rng.integers(0, 1300))
        u = 512 if s_rows == 512 else int(rng.choice([512, 1024]))
        uw, _, _ = _sorted_uwords(rng, s_rows, u,
                                  int(rng.integers(1, u)), rb,
                                  clamp_some=step % 2 == 0)
        uw_j = jnp.asarray(uw)
        st_r, want = ref_fn(st_r, tarr, uw_j, lid, jnp.int64(now))
        st_f, got = fused_fn(st_f, tarr, uw_j, lid, jnp.int64(now))
        np.testing.assert_array_equal(np.asarray(want), np.asarray(got),
                                      err_msg=f"{algo} step {step}")
        np.testing.assert_array_equal(np.asarray(st_r), np.asarray(st_f),
                                      err_msg=f"{algo} state {step}")


@pytest.mark.parametrize("algo", ["tb", "sw"])
def test_fused_matches_oracle_with_clears(algo, fused_interpret):
    """Engine-dispatch soak against the executable oracle with clear
    interleavings: keys map 1:1 to slots, duplicate-heavy batches, and
    slots cleared mid-stream (reset semantics) — every decision must
    match semantics/oracle.py exactly, through the ELECTED fused path."""
    import random

    from ratelimiter_tpu.engine.engine import DeviceEngine
    from ratelimiter_tpu.semantics import (
        SlidingWindowOracle,
        TokenBucketOracle,
    )

    s_rows = 1 << 12
    table = LimiterTable()
    if algo == "sw":
        cfg = RateLimitConfig(max_permits=6, window_ms=1000,
                              enable_local_cache=False)
        oracle = SlidingWindowOracle(cfg)
    else:
        cfg = RateLimitConfig(max_permits=8, window_ms=1500,
                              refill_rate=5.0)
        oracle = TokenBucketOracle(cfg)
    lid = table.register(cfg)
    eng = DeviceEngine(num_slots=s_rows, table=table)
    assert eng._relay_fused_ok(algo, 4096), "fused path not elected"
    rb = eng.rank_bits
    dispatch = (eng.sw_relay_counts_dispatch if algo == "sw"
                else eng.tb_relay_counts_dispatch)
    clear = eng.sw_clear if algo == "sw" else eng.tb_clear
    rng = np.random.default_rng(29)
    pyrng = random.Random(29)
    now = 3_000_000
    for step in range(10):
        now += pyrng.randrange(0, 900)
        keys = rng.integers(0, 40, 500)  # key == slot (identity index)
        order, uidx0, rank = {}, np.empty(500, np.int32), np.empty(
            500, np.int32)
        counts: dict = {}
        for i, k in enumerate(keys):
            if k not in order:
                order[k] = len(order)
            r = counts.get(k, 0)
            counts[k] = r + 1
            uidx0[i] = order[k]
            rank[i] = r
        uslots = np.asarray(sorted(order), dtype=np.uint32)
        ucnt = np.asarray([counts[s] for s in uslots], dtype=np.uint32)
        # uidx into the SORTED unique lane (the wire order).
        pos_of = {s: j for j, s in enumerate(uslots)}
        uidx = np.asarray([pos_of[k] for k in keys], dtype=np.int32)
        uw = np.full(4096, 0xFFFFFFFF, dtype=np.uint32)
        uw[:len(uslots)] = ((uslots << np.uint32(rb + 1))
                            | (ucnt << np.uint32(1)))
        got_counts = np.asarray(dispatch(uw, np.int32(lid), now,
                                         np.uint8, slots_sorted=True))
        got = rank < got_counts[:len(uslots)].astype(np.int32)[uidx]
        for j, k in enumerate(keys):
            want = oracle.try_acquire(f"k{k}", 1, now).allowed
            assert got[j] == want, (algo, step, j, int(k))
        if pyrng.random() < 0.5:
            victims = [int(pyrng.choice(list(keys))) for _ in range(3)]
            clear(victims)
            for v in victims:
                oracle.reset(f"k{v}", now)


def test_fused_election_gates_dispatch(monkeypatch):
    """Election env overrides must flip the engine's backend choice:
    _ELECT off => composed XLA even when the kernel is live; on CPU
    without interpret the fused path must never be live at all."""
    from ratelimiter_tpu.engine.engine import DeviceEngine

    table = LimiterTable()
    table.register(RateLimitConfig(max_permits=9, window_ms=1000,
                                   refill_rate=4.0))
    eng = DeviceEngine(num_slots=1 << 12, table=table)
    # Plain CPU: not live (platform gate, before any probe/election).
    assert not eng._relay_fused_ok("tb", 4096)
    # Interpret forced but election forced OFF: still not live.
    monkeypatch.setattr(rs, "_INTERPRET", True)
    monkeypatch.setattr(rs, "_probe_ok", None)
    monkeypatch.setenv("RATELIMITER_PALLAS_ELECT_RELAY_FUSED", "off")
    election.reset_for_tests()
    try:
        assert not eng._relay_fused_ok("tb", 4096)
    finally:
        election.reset_for_tests()
    # Geometry gates regardless of election: unpadded/odd lanes, tiny
    # tables, oversized rank_bits.
    assert not rs.supported((1 << 12, 4), 1000, 10)    # batch % T != 0
    assert not rs.supported((1 << 12, 4), 256, 10)     # batch < 2T
    assert not rs.supported((100, 4), 4096, 10)        # rows % T != 0
    assert not rs.supported((1 << 12, 4), 4096, 22)    # rank_bits > 21


def test_election_record_consistency(monkeypatch, tmp_path):
    """A measured election must persist a record whose verdict matches
    its own A/B times, and the disk cache must round-trip."""
    calls = {"n": 0}

    def fake_measure():
        calls["n"] += 1
        return {"pallas_s": 2.0, "xla_s": 1.0}   # XLA clearly wins

    monkeypatch.setattr(election, "_cache_path",
                        lambda name: str(tmp_path / f"{name}.json"))
    election.reset_for_tests()
    try:
        assert election.measured_election("t_path", fake_measure) is False
        rec = election.report()["t_path"]
        assert rec["elected"] == (
            rec["pallas_s"] <= rec["margin"] * rec["xla_s"])
        # Second resolve: in-process cache, no re-measure.
        assert election.measured_election("t_path", fake_measure) is False
        assert calls["n"] == 1
        # Fresh process simulation: disk cache serves the verdict.
        election.reset_for_tests()
        assert election.measured_election("t_path", fake_measure) is False
        assert calls["n"] == 1
        assert election.report()["t_path"]["source"] == "disk_cache"
    finally:
        election.reset_for_tests()


@pytest.mark.parametrize("algo", ["tb", "sw"])
def test_storage_stream_fused_matches_unfused(monkeypatch, algo,
                                              fused_interpret):
    """Storage-level parity: the relay stream with the fused kernel
    elected must decide exactly like a storage running the composed
    path on the same stream (sorted digest chunks, pins + evictions +
    clears exercised by the real index)."""
    import ratelimiter_tpu.storage.tpu as tpu_mod
    from ratelimiter_tpu.engine.native_index import native_available
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    if not native_available():
        pytest.skip("needs the native index (sort_uniques)")
    monkeypatch.setattr(tpu_mod, "_SORT_UNIQUES_MIN", 1 << 9)
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK", 1 << 12)
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK_MAX", 1 << 12)
    now = [4_000_000]
    rng = np.random.default_rng(31)
    st_f = TpuBatchedStorage(num_slots=1 << 12, clock_ms=lambda: now[0])
    if algo == "sw":
        cfg = RateLimitConfig(max_permits=6, window_ms=1000,
                              enable_local_cache=False)
    else:
        cfg = RateLimitConfig(max_permits=9, window_ms=1200,
                              refill_rate=4.0)
    lid_f = st_f.register_limiter(algo, cfg)
    assert st_f.engine._relay_fused_ok(algo, 1 << 12)
    # The reference storage: fused disabled at its engine (instance
    # shadow — both engines share the module-level interpret override).
    st_r = TpuBatchedStorage(num_slots=1 << 12, clock_ms=lambda: now[0])
    lid_r = st_r.register_limiter(algo, cfg)
    st_r.engine._relay_fused_ok = lambda algo, u: False
    try:
        for rep in range(3):
            # Duplicate-heavy so the digest mode is elected; > 512
            # uniques so the sorted path engages.
            ids = rng.integers(0, 1500, 1 << 12)
            a = st_f.acquire_stream_ids(algo, lid_f, ids, None)
            b = st_r.acquire_stream_ids(algo, lid_r, ids, None)
            np.testing.assert_array_equal(a, b, err_msg=f"rep {rep}")
            if rep == 1:
                k = int(ids[0])
                st_f.reset_key(algo, lid_f, k)
                st_r.reset_key(algo, lid_r, k)
            now[0] += 533
        # The fused jit must actually have served (not a vacuous pass).
        assert any(len(k) > 2 and k[2] == "fused"
                   for k in st_f.engine._relay_counts), (
            "fused path never engaged in the stream")
    finally:
        st_f.close()
        st_r.close()
