"""Pallas solver kernel (interpret mode on CPU) vs the XLA solver."""

import numpy as np
import pytest

import jax.numpy as jnp

from ratelimiter_tpu.ops.pallas.solver import (
    SAT,
    pallas_solve,
    seg_first_index,
)
from ratelimiter_tpu.ops.segments import first_occurrence, solve_threshold_recurrence


def run_both(slots, u, w):
    slots = jnp.asarray(slots, dtype=jnp.int32)
    first = first_occurrence(slots)
    xla = solve_threshold_recurrence(
        jnp.asarray(u, dtype=jnp.int64), jnp.asarray(w, dtype=jnp.int64), first)
    pal = pallas_solve(
        jnp.asarray(u, dtype=jnp.int32), jnp.asarray(w, dtype=jnp.int32),
        seg_first_index(first), interpret=True)
    return np.asarray(xla), np.asarray(pal)


def test_seg_first_index():
    slots = jnp.asarray([0, 0, 2, 2, 2, 7], dtype=jnp.int32)
    sf = seg_first_index(first_occurrence(slots))
    assert list(np.asarray(sf)) == [0, 0, 2, 2, 2, 5]


@pytest.mark.parametrize("seed", range(4))
def test_pallas_matches_xla_random(seed):
    rng = np.random.default_rng(seed)
    n = 256
    slots = np.sort(rng.integers(0, 30, size=n)).astype(np.int32)
    u = rng.integers(-5, 40, size=n)
    w = rng.integers(1, 9, size=n)
    xla, pal = run_both(slots, u, w)
    np.testing.assert_array_equal(xla, pal)


def test_pallas_hot_segment():
    n = 512
    slots = np.zeros(n, dtype=np.int32)
    u = np.full(n, 100)
    w = np.ones(n, dtype=np.int64)
    xla, pal = run_both(slots, u, w)
    np.testing.assert_array_equal(xla, pal)
    assert pal.sum() == 101


def test_pallas_saturation_correct():
    # Weights big enough to overflow a non-saturating i32 prefix within one
    # segment; saturated sums must still reject exactly like the (unbounded)
    # XLA int64 path.
    n = 64
    slots = np.zeros(n, dtype=np.int32)
    w = np.full(n, 100_000_000)  # 100M per element
    u = np.full(n, 250_000_000)  # prefix sums 0/100M/200M pass; then reject
    xla, pal = run_both(slots, u, w)
    np.testing.assert_array_equal(xla, pal)
    assert pal[:3].sum() == 3 and pal[3:].sum() == 0


def test_pallas_saturated_exclusive_prefix_rejects():
    # Regression: when the INCLUSIVE prefix clamps at SAT, deriving the
    # exclusive prefix as inclusive-minus-own would underestimate it by
    # the element's own (large) weight and wrongly admit.  The exclusive
    # scan must saturate directly.
    slots = np.zeros(3, dtype=np.int32)
    w = np.array([2 ** 29, 6 * 10 ** 8, 1], dtype=np.int64)
    u = np.array([2 ** 29, 5 * 10 ** 8, 0], dtype=np.int64)
    xla, pal = run_both(slots, u, w)
    np.testing.assert_array_equal(xla, pal)
    np.testing.assert_array_equal(pal, [1, 0, 0])
