"""Observability + failure paths: dispatch latency histogram, batcher
exception propagation, and the HTTP service over the real TPU-batched stack."""

import http.client
import json
import threading

import pytest

from ratelimiter_tpu import RateLimitConfig
from ratelimiter_tpu.algorithms import SlidingWindowRateLimiter
from ratelimiter_tpu.metrics import MeterRegistry
from ratelimiter_tpu.storage import TpuBatchedStorage


def test_storage_latency_histogram_populated():
    registry = MeterRegistry()
    storage = TpuBatchedStorage(num_slots=64, max_delay_ms=0.1,
                                meter_registry=registry)
    limiter = SlidingWindowRateLimiter(
        storage, RateLimitConfig.per_minute(10), registry)
    for _ in range(5):
        limiter.try_acquire("u")
    storage.flush()
    snap = registry.scrape()["ratelimiter.storage.latency"]
    assert snap["count"] >= 1
    assert snap["p99_us"] > 0
    storage.close()


def test_batcher_dispatch_failure_fails_waiters():
    from ratelimiter_tpu.engine.batcher import MicroBatcher

    def boom(slots, lids, permits):
        raise RuntimeError("device fell over")

    batcher = MicroBatcher(
        dispatch={"sw": boom}, clear={"sw": lambda s: None}, max_delay_ms=0.05)
    fut = batcher.submit("sw", 0, 0, 1)
    with pytest.raises(RuntimeError, match="device fell over"):
        fut.result(timeout=5)
    batcher.close()


def test_service_over_tpu_backend_end_to_end():
    from ratelimiter_tpu.service.app import make_server
    from ratelimiter_tpu.service.props import AppProperties
    from ratelimiter_tpu.service.wiring import build_app

    props = AppProperties({
        "storage.backend": "tpu",
        "storage.num_slots": "4096",
        "batcher.max_delay_ms": "0.2",
        "parallel.shard": "off",
    })
    ctx = build_app(props)
    srv = make_server(ctx, port=0)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    try:
        port = srv.server_address[1]

        def req(method, path, body=None, headers=None):
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
            conn.request(method, path,
                         body=json.dumps(body) if body else None,
                         headers=headers or {})
            resp = conn.getresponse()
            data = json.loads(resp.read() or b"{}")
            conn.close()
            return resp.status, data

        # Sliding window through the device engine.
        for i in range(10):
            status, data = req("POST", "/api/login", {"username": "tpu-user"})
            assert status == 200, data
        status, _ = req("POST", "/api/login", {"username": "tpu-user"})
        assert status == 429
        # Token bucket burst through the device engine. (Real wall clock:
        # first-dispatch jit compile time refills a few tokens between the
        # consume and the availability peek, so only bound the remainder.)
        status, data = req("POST", "/api/batch", {"size": 50},
                           {"X-User-ID": "tpu-burst", "Content-Type": "application/json"})
        assert status == 200 and data["tokens_remaining"] < 50
        status, _ = req("POST", "/api/batch", {"size": 50},
                        {"X-User-ID": "tpu-burst", "Content-Type": "application/json"})
        assert status == 429
        # Reset restores both.
        status, _ = req("DELETE", "/api/admin/reset/tpu-user")
        assert status == 200
        status, _ = req("POST", "/api/login", {"username": "tpu-user"})
        assert status == 200
        # Latency histogram exposed over the actuator.
        status, data = req("GET", "/actuator/metrics")
        assert status == 200
        assert data["meters"]["ratelimiter.storage.latency"]["count"] >= 1
        # Decision trace ring exposed too.
        status, data = req("GET", "/actuator/trace")
        assert status == 200
        assert data["total_dispatches"] >= 1
        rec = data["recent"][-1]
        assert {"t_ms", "algo", "batch", "allowed", "latency_us"} <= set(rec)
    finally:
        srv.shutdown()
        thread.join(timeout=5)
        ctx.close()


def test_batcher_pipelines_drains():
    """Fetch latency must overlap across batches: with a 50 ms drain and
    four consecutive batches, the pipelined batcher finishes in well under
    the 200 ms a serialized drain chain would take."""
    import time as _time

    from ratelimiter_tpu.engine.batcher import MicroBatcher

    def dispatch(slots, lids, permits):
        return {"allowed": [True] * len(slots)}  # handle = precomputed

    def drain(handle, n):
        _time.sleep(0.05)  # the "device fetch"
        return handle

    batcher = MicroBatcher(
        dispatch={"tb": dispatch}, drain={"tb": drain},
        clear={"tb": lambda s: None},
        max_delay_ms=2.0, max_inflight=4)
    t0 = _time.perf_counter()
    futs = []
    for _ in range(4):
        futs.append(batcher.submit("tb", 1, 1, 1))
        _time.sleep(0.004)  # let the flush deadline cut a fresh batch
    for f in futs:
        assert f.result(timeout=5)["allowed"] is True
    elapsed = _time.perf_counter() - t0
    batcher.close()
    assert elapsed < 0.15, f"drains serialized: {elapsed:.3f}s"


def test_logging_configured_from_props_and_emits_decisions(caplog):
    """Logging parity (SURVEY §5.5): level/pattern come from props; the
    decision and dispatch layers emit debug records."""
    import logging

    from ratelimiter_tpu.algorithms import TokenBucketRateLimiter
    from ratelimiter_tpu.service.props import AppProperties
    from ratelimiter_tpu.storage import InMemoryStorage
    from ratelimiter_tpu.utils.logging import setup_logging

    logger = setup_logging(AppProperties({"logging.level": "DEBUG"}))
    assert logger.level == logging.DEBUG
    # Idempotent: re-setup must not stack handlers.
    n_handlers = len(logger.handlers)
    setup_logging(AppProperties({"logging.level": "DEBUG"}))
    assert len(logger.handlers) == n_handlers

    limiter = TokenBucketRateLimiter(
        InMemoryStorage(clock_ms=lambda: 50_000),
        RateLimitConfig(max_permits=3, window_ms=1000, refill_rate=1.0),
        MeterRegistry(), clock_ms=lambda: 50_000)
    with caplog.at_level(logging.DEBUG, logger="ratelimiter_tpu"):
        # caplog attaches its own handler; propagate briefly for capture.
        logging.getLogger("ratelimiter_tpu").propagate = True
        limiter.try_acquire("carol")
        logging.getLogger("ratelimiter_tpu").propagate = False
    assert any("tb decision key=carol" in r.message for r in caplog.records)
    logger.setLevel(logging.INFO)
