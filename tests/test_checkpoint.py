"""Checkpoint/resume: device state + key->slot index survive a 'restart'.

The reference leans on Redis AOF for durability; here HBM state is
explicitly snapshotted and restored (SURVEY.md §5.4).  A restored process
must continue making the exact decisions the uninterrupted one would.
"""

import random

import pytest

from ratelimiter_tpu import RateLimitConfig
from ratelimiter_tpu.algorithms import SlidingWindowRateLimiter, TokenBucketRateLimiter
from ratelimiter_tpu.metrics import MeterRegistry
from ratelimiter_tpu.semantics import SlidingWindowOracle, TokenBucketOracle
from ratelimiter_tpu.storage import TpuBatchedStorage

T0 = 1_753_000_000_000


class FakeClock:
    def __init__(self, t=T0):
        self.t = t

    def __call__(self):
        return self.t


def drive(limiter, oracle, clock, rng, keys, steps):
    for _ in range(steps):
        clock.t += rng.randrange(0, 300)
        n = rng.randrange(1, 16)
        ks = [rng.choice(keys) for _ in range(n)]
        perms = [rng.randrange(1, 4) for _ in range(n)]
        got = limiter.try_acquire_many(ks, perms)
        for j in range(n):
            want = oracle.try_acquire(ks[j], perms[j], clock.t).allowed
            assert got[j] == want


def test_checkpoint_restore_continues_identically(tmp_path):
    clock = FakeClock()
    rng = random.Random(21)
    keys = [f"u{i}" for i in range(10)]
    cfg_sw = RateLimitConfig(max_permits=15, window_ms=2000, enable_local_cache=False)
    cfg_tb = RateLimitConfig(max_permits=25, window_ms=3000, refill_rate=12.0)

    storage = TpuBatchedStorage(num_slots=256, max_delay_ms=0.1,
                                clock_ms=clock, checkpointable=True)
    sw = SlidingWindowRateLimiter(storage, cfg_sw, MeterRegistry(), clock_ms=clock)
    tb = TokenBucketRateLimiter(storage, cfg_tb, MeterRegistry(), clock_ms=clock)
    osw, otb = SlidingWindowOracle(cfg_sw), TokenBucketOracle(cfg_tb)

    drive(sw, osw, clock, rng, keys, 20)
    drive(tb, otb, clock, rng, keys, 20)

    ckpt = str(tmp_path / "ckpt")
    storage.save_checkpoint(ckpt)
    storage.close()

    # "Restart": a fresh storage + fresh limiter objects, same configs in the
    # same registration order, restored from disk.
    clock2 = FakeClock(clock.t)
    storage2 = TpuBatchedStorage(num_slots=256, max_delay_ms=0.1,
                                 clock_ms=clock2, checkpointable=True)
    sw2 = SlidingWindowRateLimiter(storage2, cfg_sw, MeterRegistry(), clock_ms=clock2)
    tb2 = TokenBucketRateLimiter(storage2, cfg_tb, MeterRegistry(), clock_ms=clock2)
    storage2.restore_checkpoint(ckpt)

    # The oracles carry on from their (never-interrupted) state; the restored
    # stack must agree with them decision-for-decision.
    drive(sw2, osw, clock2, rng, keys, 20)
    drive(tb2, otb, clock2, rng, keys, 20)
    storage2.close()


def test_checkpoint_geometry_mismatch_rejected(tmp_path):
    storage = TpuBatchedStorage(num_slots=128, checkpointable=True)
    ckpt = str(tmp_path / "ckpt")
    storage.save_checkpoint(ckpt)
    storage.close()

    storage2 = TpuBatchedStorage(num_slots=256, checkpointable=True)
    with pytest.raises(ValueError, match="geometry"):
        storage2.restore_checkpoint(ckpt)
    storage2.close()


def test_checkpoint_atomic_overwrite(tmp_path):
    storage = TpuBatchedStorage(num_slots=64, checkpointable=True)
    ckpt = str(tmp_path / "ckpt")
    storage.save_checkpoint(ckpt)
    storage.save_checkpoint(ckpt)  # overwrite in place must not corrupt
    storage2 = TpuBatchedStorage(num_slots=64, checkpointable=True)
    storage2.restore_checkpoint(ckpt)
    storage.close()
    storage2.close()


def test_native_index_checkpoint_refused(tmp_path):
    from ratelimiter_tpu.engine.native_index import native_available

    if not native_available():
        pytest.skip("no native index")
    storage = TpuBatchedStorage(num_slots=64)  # native index by default
    with pytest.raises(ValueError, match="enumerable"):
        storage.save_checkpoint(str(tmp_path / "ckpt"))
    storage.close()


def test_legacy_sharded_dump_int_keys_refused():
    """A sharded dump with NO shard_hash predates the splitmix64 int-key
    routing: restoring its int-key entries under current routing would
    silently orphan them (lookups hit a different shard), so it is refused.
    String-key-only legacy dumps routed identically then and now — those
    restore fine."""
    import jax

    if jax.device_count() < 2:
        pytest.skip("needs a multi-device mesh")
    from ratelimiter_tpu.engine import checkpoint as ck
    from ratelimiter_tpu.engine.state import LimiterTable
    from ratelimiter_tpu.parallel import ShardedDeviceEngine, make_mesh
    from ratelimiter_tpu.parallel.sharded import shard_of_key

    cfg = RateLimitConfig(max_permits=5, window_ms=60_000, refill_rate=1.0)

    def fresh():
        engine = ShardedDeviceEngine(slots_per_shard=16, table=LimiterTable(),
                                     mesh=make_mesh())
        st = TpuBatchedStorage(engine=engine, checkpointable=True)
        st.register_limiter("tb", cfg)
        return st

    st = fresh()
    n_shards = st.engine.n_shards
    sps = st.engine.slots_per_shard

    def misplaced(key):
        """A placement that current routing would NOT pick (what a legacy
        crc32 binary can produce for int/bool keys)."""
        return ((shard_of_key(key, n_shards) + 1) % n_shards) * sps

    for user in (42, False):  # int and bool route via splitmix64 today
        for key, entry_key in (((1, user), [1, user]), (user, user)):
            dump = {"algos": {"tb": {
                "kind": "sharded",  # no shard_hash field — a legacy dump
                "entries": [[entry_key, misplaced(key)]],
            }}}
            with pytest.raises(ValueError, match="shard hash"):
                ck.restore_slot_indexes(st, dump)
    st.close()

    st = fresh()
    n_shards = st.engine.n_shards
    shard = shard_of_key((1, "alice"), n_shards)  # crc32 then == crc32 now
    legacy_str = {"algos": {"tb": {
        "kind": "sharded",
        "entries": [[[1, "alice"], shard * st.engine.slots_per_shard + 3]],
    }}}
    ck.restore_slot_indexes(st, legacy_str)
    assert st._index["tb"].get((1, "alice")) is not None
    st.close()
