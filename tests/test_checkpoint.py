"""Checkpoint/resume: device state + key->slot index survive a 'restart'.

The reference leans on Redis AOF for durability; here HBM state is
explicitly snapshotted and restored (SURVEY.md §5.4).  A restored process
must continue making the exact decisions the uninterrupted one would.
"""

import random

import pytest

from ratelimiter_tpu import RateLimitConfig
from ratelimiter_tpu.algorithms import SlidingWindowRateLimiter, TokenBucketRateLimiter
from ratelimiter_tpu.metrics import MeterRegistry
from ratelimiter_tpu.semantics import SlidingWindowOracle, TokenBucketOracle
from ratelimiter_tpu.storage import TpuBatchedStorage

T0 = 1_753_000_000_000


class FakeClock:
    def __init__(self, t=T0):
        self.t = t

    def __call__(self):
        return self.t


def drive(limiter, oracle, clock, rng, keys, steps):
    for _ in range(steps):
        clock.t += rng.randrange(0, 300)
        n = rng.randrange(1, 16)
        ks = [rng.choice(keys) for _ in range(n)]
        perms = [rng.randrange(1, 4) for _ in range(n)]
        got = limiter.try_acquire_many(ks, perms)
        for j in range(n):
            want = oracle.try_acquire(ks[j], perms[j], clock.t).allowed
            assert got[j] == want


def test_checkpoint_restore_continues_identically(tmp_path):
    clock = FakeClock()
    rng = random.Random(21)
    keys = [f"u{i}" for i in range(10)]
    cfg_sw = RateLimitConfig(max_permits=15, window_ms=2000, enable_local_cache=False)
    cfg_tb = RateLimitConfig(max_permits=25, window_ms=3000, refill_rate=12.0)

    storage = TpuBatchedStorage(num_slots=256, max_delay_ms=0.1,
                                clock_ms=clock, checkpointable=True)
    sw = SlidingWindowRateLimiter(storage, cfg_sw, MeterRegistry(), clock_ms=clock)
    tb = TokenBucketRateLimiter(storage, cfg_tb, MeterRegistry(), clock_ms=clock)
    osw, otb = SlidingWindowOracle(cfg_sw), TokenBucketOracle(cfg_tb)

    drive(sw, osw, clock, rng, keys, 20)
    drive(tb, otb, clock, rng, keys, 20)

    ckpt = str(tmp_path / "ckpt")
    storage.save_checkpoint(ckpt)
    storage.close()

    # "Restart": a fresh storage + fresh limiter objects, same configs in the
    # same registration order, restored from disk.
    clock2 = FakeClock(clock.t)
    storage2 = TpuBatchedStorage(num_slots=256, max_delay_ms=0.1,
                                 clock_ms=clock2, checkpointable=True)
    sw2 = SlidingWindowRateLimiter(storage2, cfg_sw, MeterRegistry(), clock_ms=clock2)
    tb2 = TokenBucketRateLimiter(storage2, cfg_tb, MeterRegistry(), clock_ms=clock2)
    storage2.restore_checkpoint(ckpt)

    # The oracles carry on from their (never-interrupted) state; the restored
    # stack must agree with them decision-for-decision.
    drive(sw2, osw, clock2, rng, keys, 20)
    drive(tb2, otb, clock2, rng, keys, 20)
    storage2.close()


def test_checkpoint_geometry_mismatch_rejected(tmp_path):
    storage = TpuBatchedStorage(num_slots=128, checkpointable=True)
    ckpt = str(tmp_path / "ckpt")
    storage.save_checkpoint(ckpt)
    storage.close()

    storage2 = TpuBatchedStorage(num_slots=256, checkpointable=True)
    with pytest.raises(ValueError, match="geometry"):
        storage2.restore_checkpoint(ckpt)
    storage2.close()


def test_checkpoint_atomic_overwrite(tmp_path):
    storage = TpuBatchedStorage(num_slots=64, checkpointable=True)
    ckpt = str(tmp_path / "ckpt")
    storage.save_checkpoint(ckpt)
    storage.save_checkpoint(ckpt)  # overwrite in place must not corrupt
    storage2 = TpuBatchedStorage(num_slots=64, checkpointable=True)
    storage2.restore_checkpoint(ckpt)
    storage.close()
    storage2.close()


def test_native_index_checkpoint_round_trips(tmp_path):
    """The DEFAULT (native-index) storage checkpoints and restores: the
    index dumps fingerprint triples at native speed, and the restored
    process continues the exact decisions — durability and hyperscale
    indexing are no longer mutually exclusive."""
    from ratelimiter_tpu.engine.native_index import native_available

    if not native_available():
        pytest.skip("no native index")
    clock = FakeClock()
    rng = random.Random(33)
    keys = [f"n{i}" for i in range(12)]
    cfg_sw = RateLimitConfig(max_permits=9, window_ms=2500,
                             enable_local_cache=False)
    cfg_tb = RateLimitConfig(max_permits=14, window_ms=2000, refill_rate=6.0)

    storage = TpuBatchedStorage(num_slots=64, max_delay_ms=0.1,
                                clock_ms=clock)  # native index by default
    sw = SlidingWindowRateLimiter(storage, cfg_sw, MeterRegistry(),
                                  clock_ms=clock)
    tb = TokenBucketRateLimiter(storage, cfg_tb, MeterRegistry(),
                                clock_ms=clock)
    osw, otb = SlidingWindowOracle(cfg_sw), TokenBucketOracle(cfg_tb)
    drive(sw, osw, clock, rng, keys, 15)
    drive(tb, otb, clock, rng, keys, 15)
    ckpt = str(tmp_path / "ckpt")
    storage.save_checkpoint(ckpt)
    storage.close()

    clock2 = FakeClock(clock.t)
    storage2 = TpuBatchedStorage(num_slots=64, max_delay_ms=0.1,
                                 clock_ms=clock2)
    sw2 = SlidingWindowRateLimiter(storage2, cfg_sw, MeterRegistry(),
                                   clock_ms=clock2)
    tb2 = TokenBucketRateLimiter(storage2, cfg_tb, MeterRegistry(),
                                 clock_ms=clock2)
    storage2.restore_checkpoint(ckpt)
    drive(sw2, osw, clock2, rng, keys, 15)
    drive(tb2, otb, clock2, rng, keys, 15)
    storage2.close()


def test_native_fp_rebalance_flat_to_larger_flat(tmp_path):
    """Fingerprint export from the default native index imports into a
    LARGER flat native target (geometry-free for LRU tables), carrying
    consumed state."""
    from ratelimiter_tpu.engine import checkpoint as ck
    from ratelimiter_tpu.engine.native_index import native_available

    if not native_available():
        pytest.skip("no native index")
    import numpy as np

    clock = lambda: 91_000  # noqa: E731
    cfg = RateLimitConfig(max_permits=4, window_ms=60_000, refill_rate=0.001)
    src = TpuBatchedStorage(num_slots=64, clock_ms=clock)
    lid = src.register_limiter("tb", cfg)
    drained = src.acquire_stream_ids(
        "tb", lid, np.asarray([5] * 4 + [6], dtype=np.int64),
        np.ones(5, dtype=np.int64), batch=16, subbatches=1)
    assert drained.tolist() == [True] * 5
    dump = ck.export_keys(src)
    src.close()
    assert dump["algos"]["tb"]["kind"] == "fp"

    dst = TpuBatchedStorage(num_slots=1024, clock_ms=clock)
    lid2 = dst.register_limiter("tb", cfg)
    assert lid2 == lid
    ck.import_keys(dst, dump)
    got = dst.acquire_stream_ids(
        "tb", lid2, np.asarray([5, 6, 6, 6, 6], dtype=np.int64),
        np.ones(5, dtype=np.int64), batch=16, subbatches=1)
    dst.close()
    # key 5 was fully drained; key 6 had 3 of 4 left.
    assert got.tolist() == [False, True, True, True, False]


def test_legacy_sharded_dump_int_keys_refused():
    """A sharded dump with NO shard_hash predates the splitmix64 int-key
    routing: restoring its int-key entries under current routing would
    silently orphan them (lookups hit a different shard), so it is refused.
    Legacy entries that happen to sit where the CURRENT hash routes them
    pass the placement check and restore fine (exercised below with a
    string entry placed at today's routing)."""
    import jax

    if jax.device_count() < 2:
        pytest.skip("needs a multi-device mesh")
    from ratelimiter_tpu.engine import checkpoint as ck
    from ratelimiter_tpu.engine.state import LimiterTable
    from ratelimiter_tpu.parallel import ShardedDeviceEngine, make_mesh
    from ratelimiter_tpu.parallel.sharded import shard_of_key

    cfg = RateLimitConfig(max_permits=5, window_ms=60_000, refill_rate=1.0)

    def fresh():
        engine = ShardedDeviceEngine(slots_per_shard=16, table=LimiterTable(),
                                     mesh=make_mesh())
        st = TpuBatchedStorage(engine=engine, checkpointable=True)
        st.register_limiter("tb", cfg)
        return st

    st = fresh()
    n_shards = st.engine.n_shards
    sps = st.engine.slots_per_shard

    def misplaced(key):
        """A placement that current routing would NOT pick (what a legacy
        crc32 binary can produce for int/bool keys)."""
        return ((shard_of_key(key, n_shards) + 1) % n_shards) * sps

    for user in (42, False):  # int and bool route via splitmix64 today
        for key, entry_key in (((1, user), [1, user]), (user, user)):
            dump = {"algos": {"tb": {
                "kind": "sharded",  # no shard_hash field — a legacy dump
                "entries": [[entry_key, misplaced(key)]],
            }}}
            with pytest.raises(ValueError, match="shard hash"):
                ck.restore_slot_indexes(st, dump)
    st.close()

    st = fresh()
    n_shards = st.engine.n_shards
    # Built at CURRENT routing: the placement check accepts any legacy
    # entry that already sits where today's hash routes it (and refuses
    # the rest loudly) — no model of the old hash needed.
    shard = shard_of_key((1, "alice"), n_shards)
    legacy_str = {"algos": {"tb": {
        "kind": "sharded",
        "entries": [[[1, "alice"], shard * st.engine.slots_per_shard + 3]],
    }}}
    ck.restore_slot_indexes(st, legacy_str)
    assert st._index["tb"].get((1, "alice")) is not None
    st.close()


def test_sharded_native_checkpoint_round_trips(tmp_path):
    """Sharded DEFAULT storage (native sub-indexes): checkpoint carries
    per-shard fingerprints and restores into the same shard geometry."""
    import jax

    if jax.device_count() < 2:
        pytest.skip("needs a multi-device mesh")
    from ratelimiter_tpu.engine.native_index import native_available

    if not native_available():
        pytest.skip("no native index")
    import numpy as np

    from ratelimiter_tpu.engine.state import LimiterTable
    from ratelimiter_tpu.parallel import ShardedDeviceEngine, make_mesh

    clock = lambda: 71_000  # noqa: E731
    cfg = RateLimitConfig(max_permits=3, window_ms=60_000, refill_rate=0.001)

    def fresh():
        eng = ShardedDeviceEngine(slots_per_shard=16, table=LimiterTable(),
                                  mesh=make_mesh())
        return TpuBatchedStorage(engine=eng, clock_ms=clock)

    src = fresh()
    lid = src.register_limiter("tb", cfg)
    ids = np.asarray([11] * 3 + [12], dtype=np.int64)
    assert src.acquire_stream_ids("tb", lid, ids, None,
                                  batch=16, subbatches=1).all()
    ckpt = str(tmp_path / "ckpt")
    src.save_checkpoint(ckpt)
    src.close()

    dst = fresh()
    dst.register_limiter("tb", cfg)
    dst.restore_checkpoint(ckpt)
    got = dst.acquire_stream_ids(
        "tb", lid, np.asarray([11, 12, 12, 12], dtype=np.int64), None,
        batch=16, subbatches=1)
    dst.close()
    assert got.tolist() == [False, True, True, False]


# ---------------------------------------------------------------------------
# Integrity (format v3): per-array CRC32s + manifest checksum
# ---------------------------------------------------------------------------

def _small_checkpoint(tmp_path, tag="ckpt"):
    clock = FakeClock()
    cfg = RateLimitConfig(max_permits=15, window_ms=2000,
                          enable_local_cache=False)
    storage = TpuBatchedStorage(num_slots=128, max_delay_ms=0.1,
                                clock_ms=clock, checkpointable=True)
    sw = SlidingWindowRateLimiter(storage, cfg, MeterRegistry(),
                                  clock_ms=clock)
    for i in range(20):
        sw.try_acquire(f"u{i % 6}")
    path = str(tmp_path / tag)
    storage.save_checkpoint(path)
    storage.close()
    return path, cfg


def test_checkpoint_bit_flip_refused(tmp_path):
    """A single flipped byte in state.npz fails the per-array CRC32 (or
    the zip layer) with the typed corruption error."""
    import os

    from ratelimiter_tpu.engine.checkpoint import (
        CheckpointCorruptError,
        load_checkpoint,
    )

    path, _ = _small_checkpoint(tmp_path)
    npz = os.path.join(path, "state.npz")
    blob = bytearray(open(npz, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    with open(npz, "wb") as fh:
        fh.write(bytes(blob))
    with pytest.raises(CheckpointCorruptError):
        load_checkpoint(path)


def test_checkpoint_truncated_npz_refused(tmp_path):
    """A torn write (truncated state.npz) is refused with the typed
    error, not a random zip/numpy traceback mid-restore."""
    import os

    from ratelimiter_tpu.engine.checkpoint import (
        CheckpointCorruptError,
        load_checkpoint,
    )

    path, _ = _small_checkpoint(tmp_path)
    npz = os.path.join(path, "state.npz")
    blob = open(npz, "rb").read()
    with open(npz, "wb") as fh:
        fh.write(blob[: len(blob) // 3])
    with pytest.raises(CheckpointCorruptError):
        load_checkpoint(path)


def test_checkpoint_manifest_tamper_refused(tmp_path):
    """Editing index.json (even a metadata field) breaks the manifest
    checksum."""
    import json
    import os

    from ratelimiter_tpu.engine.checkpoint import (
        CheckpointCorruptError,
        load_checkpoint,
    )

    path, _ = _small_checkpoint(tmp_path)
    idx = os.path.join(path, "index.json")
    meta = json.load(open(idx))
    meta["num_slots"] = 999  # a geometry lie the checksum must catch
    with open(idx, "w") as fh:
        json.dump(meta, fh)
    with pytest.raises(CheckpointCorruptError, match="manifest"):
        load_checkpoint(path)


def test_checkpoint_older_format_still_restores(tmp_path):
    """A v2 dump (no checksums) predates integrity and must still load —
    and restore into a live storage."""
    import json
    import os

    from ratelimiter_tpu.engine.checkpoint import load_checkpoint

    path, cfg = _small_checkpoint(tmp_path)
    idx = os.path.join(path, "index.json")
    meta = json.load(open(idx))
    meta["format"] = 2
    meta.pop("checksums", None)
    meta.pop("manifest_crc", None)
    with open(idx, "w") as fh:
        json.dump(meta, fh)
    assert load_checkpoint(path)["meta"]["format"] == 2

    clock = FakeClock()
    storage = TpuBatchedStorage(num_slots=128, max_delay_ms=0.1,
                                clock_ms=clock, checkpointable=True)
    SlidingWindowRateLimiter(storage, cfg, MeterRegistry(), clock_ms=clock)
    storage.restore_checkpoint(path)
    storage.close()
