"""Engine differential with the Pallas solver enabled (interpret mode).

The flags latch at import, so the pallas-enabled engine runs in a
subprocess; decisions must match the oracle exactly, proving the kernel
composes correctly with both device steps (incl. the token bucket's exact
fixed-point shift)."""

import os
import subprocess
import sys

SCRIPT = r"""
import os, random
import jax
jax.config.update("jax_platforms", "cpu")
from ratelimiter_tpu import RateLimitConfig
from ratelimiter_tpu.engine.engine import DeviceEngine
from ratelimiter_tpu.engine.state import LimiterTable
from ratelimiter_tpu.semantics import SlidingWindowOracle, TokenBucketOracle
from ratelimiter_tpu.ops.pallas.solver import _pallas_supported

assert _pallas_supported(), "pallas interpret probe failed"

T0 = 1_753_000_000_000
rng = random.Random(5)
table = LimiterTable()
cfg_sw = RateLimitConfig(max_permits=12, window_ms=1500, enable_local_cache=False)
cfg_tb = RateLimitConfig(max_permits=20, window_ms=2500, refill_rate=15.0)
lid_sw, lid_tb = table.register(cfg_sw), table.register(cfg_tb)
osw, otb = SlidingWindowOracle(cfg_sw), TokenBucketOracle(cfg_tb)
engine = DeviceEngine(num_slots=256, table=table)
slots = {}
def slot(lid, k):
    return slots.setdefault((lid, k), len(slots))
now = T0
for step in range(20):
    now += rng.randrange(0, 700)
    n = rng.randrange(1, 24)
    ks = [f"u{rng.randrange(6)}" for _ in range(n)]
    perms = [rng.randrange(1, 23) for _ in range(n)]
    out = engine.sw_acquire([slot(lid_sw, k) for k in ks], [lid_sw]*n,
                            [min(p, 3) for p in perms], now)
    for j in range(n):
        d = osw.try_acquire(ks[j], min(perms[j], 3), now)
        assert out["allowed"][j] == d.allowed, ("sw", step, j)
    out = engine.tb_acquire([slot(lid_tb, k) for k in ks], [lid_tb]*n, perms, now)
    for j in range(n):
        d = otb.try_acquire(ks[j], perms[j], now)
        assert out["allowed"][j] == d.allowed, ("tb", step, j)
print("PALLAS_DIFFERENTIAL_OK")
"""


def test_pallas_enabled_engine_matches_oracle():
    env = dict(os.environ)
    env.update({
        "RATELIMITER_PALLAS": "1",
        "RATELIMITER_PALLAS_INTERPRET": "1",
        "JAX_PLATFORMS": "cpu",
    })
    proc = subprocess.run(
        [sys.executable, "-c", SCRIPT], env=env, capture_output=True,
        text=True, timeout=600,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert "PALLAS_DIFFERENTIAL_OK" in proc.stdout, proc.stderr[-3000:]
