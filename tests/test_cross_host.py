"""Cross-host failover (ISSUE 14): control-plane RPC, the distributed
fence lease, the standby witness, asymmetric partitions, and the
multi-process drill.

Layers under test, bottom-up:

- the control wire (replication/control.py): framed-JSON dispatch,
  in-protocol refusals, the lease-relay mailbox's skew-free age
  accounting;
- the serving lease on TpuBatchedStorage: monotonic epoch grants, the
  self-fence on expiry (every dispatch surface funnels through it), no
  resurrection of a fenced storage, operator lift re-arms;
- the orchestrator's cross-host behaviors on a simulated clock with
  fake backends: the standby witness VETOES fencing while the primary's
  replication heartbeats still land, and FENCING waits out an
  unreachable zombie's lease before PROMOTING;
- asymmetric partitions: FaultInjectingProxy.partition(direction=) cuts
  one pump only; a half-open link (sends land, acks vanish) reads DEAD
  on SocketSink.link_state() while the receiving side proves the bytes
  arrived, and the orchestrator's default probe counts the resulting
  ship-error growth as a probe failure;
- satellites: SidecarClient.reconnect re-arms the telemetry latch and
  LeaseClient counts it (telemetry_rearmed); terminal-FAILED shards
  turn /actuator/health DOWN with the failed ids listed;
- the full thing: cross_host_failover_drill with shard, standby, and
  orchestrator in separate OS processes.
"""

import threading
import time
import types

import pytest

from ratelimiter_tpu import RateLimitConfig
from ratelimiter_tpu.replication import (
    ControlClient,
    ControlServer,
    FailoverOrchestrator,
    LeaseMailbox,
    OrchestratorConfig,
    ReplicationServer,
    SocketSink,
    StandbyReceiver,
)
from ratelimiter_tpu.replication.remote import RemoteShardDirectory
from ratelimiter_tpu.storage import TpuBatchedStorage
from ratelimiter_tpu.storage.chaos import FaultInjectingProxy
from ratelimiter_tpu.storage.errors import FencedError

T0 = 1_753_000_000_000


# ---------------------------------------------------------------------------
# Control wire
# ---------------------------------------------------------------------------

def test_control_wire_roundtrip_and_refusals():
    calls = []

    def echo(**kw):
        calls.append(kw)
        return {"echo": kw}

    def boom():
        raise RuntimeError("handler exploded")

    server = ControlServer({"echo": echo, "boom": boom}).start()
    client = ControlClient("127.0.0.1", server.port, timeout=2.0)
    try:
        resp = client.call("echo", a=1, b="x")
        assert resp["ok"] and resp["echo"] == {"a": 1, "b": "x"}
        # Unknown op and a raising handler both answer IN-PROTOCOL —
        # the port never wedges or drops the connection for them.
        assert client.call("nope")["ok"] is False
        boomed = client.call("boom")
        assert boomed["ok"] is False and "handler exploded" in boomed["error"]
        assert client.call("echo", c=2)["ok"]  # same conn still serves
        with pytest.raises(RuntimeError, match="refused"):
            client.call_ok("boom")
        assert server.requests_served >= 4
    finally:
        client.close()
        server.stop()


def test_lease_mailbox_age_is_relative():
    box = LeaseMailbox()
    assert box.fetch() == {"deposited": False}
    box.deposit(epoch=3, ttl_ms=500.0)
    time.sleep(0.03)
    got = box.fetch()
    assert got["deposited"] and got["epoch"] == 3
    # Age is measured on the MAILBOX's clock between deposit and fetch:
    # the relay needs no synchronized wall clocks anywhere.
    assert 25.0 <= got["age_ms"] < 5000.0
    box.deposit(epoch=4, ttl_ms=500.0)
    assert box.fetch()["epoch"] == 4  # newest deposit wins


# ---------------------------------------------------------------------------
# Serving lease (storage layer)
# ---------------------------------------------------------------------------

def test_serving_lease_grants_are_monotonic_and_expiry_self_fences():
    clock = {"t": T0}
    storage = TpuBatchedStorage(num_slots=128, clock_ms=lambda: clock["t"])
    lid = storage.register_limiter("tb", RateLimitConfig(
        max_permits=10, window_ms=1000, refill_rate=5.0))
    assert storage.serving_lease_info()["installed"] is False
    storage.grant_serving_lease(2, 500.0)
    # fence_info's epoch covers the lease epoch: token leases granted
    # now are stamped with the serving generation.
    assert storage.fence_info()["epoch"] == 2
    assert bool(storage.acquire("tb", lid, "a", 1)["allowed"]) is True
    with pytest.raises(ValueError, match="monotonic"):
        storage.grant_serving_lease(1, 500.0)
    # A renewal at the SAME epoch extends the deadline.
    clock["t"] += 400
    storage.grant_serving_lease(2, 500.0)
    clock["t"] += 400  # past the first deadline, inside the renewed one
    assert storage.acquire("tb", lid, "a", 1)["allowed"] in (True, False)
    # Expiry: the first decision past the deadline self-fences, and
    # every surface after it refuses.
    clock["t"] += 600
    with pytest.raises(FencedError):
        storage.acquire("tb", lid, "a", 1)
    info = storage.serving_lease_info()
    assert info["self_fenced"] is True
    with pytest.raises(FencedError):
        storage.acquire_many("tb", [lid], ["a"], [1])
    # No resurrection: a late grant cannot un-fence.
    with pytest.raises(ValueError, match="resurrect"):
        storage.grant_serving_lease(9, 500.0)
    # The operator exit: lift_fence re-arms, then a fresh generation
    # serves again.
    storage.lift_fence(9)
    storage.grant_serving_lease(9, 500.0)
    assert storage.serving_lease_info()["self_fenced"] is False
    assert len(storage.acquire_many("tb", [lid], ["a"], [1])["allowed"]) == 1
    storage.close()


def test_explicit_fence_supersedes_serving_lease():
    clock = {"t": T0}
    storage = TpuBatchedStorage(num_slots=128, clock_ms=lambda: clock["t"])
    storage.grant_serving_lease(1, 500.0)
    storage.fence(5)
    # The fence voided the lease (no double accounting) and a grant
    # cannot resurrect the fenced storage.
    assert storage.serving_lease_info()["installed"] is False
    with pytest.raises(ValueError, match="resurrect"):
        storage.grant_serving_lease(6, 500.0)
    storage.close()


# ---------------------------------------------------------------------------
# Orchestrator: witness veto + fence-wait (fakes, simulated clock)
# ---------------------------------------------------------------------------

class _FakeBackend:
    def __init__(self, fence_reachable=True):
        self.fence_reachable = fence_reachable
        self.fences = []
        self.grants = []

    def fence(self, epoch, shards=None):
        if not self.fence_reachable:
            raise ConnectionError("partitioned: fence undeliverable")
        self.fences.append((int(epoch), shards))
        return int(epoch)

    def grant_serving_lease(self, epoch, ttl_ms):
        self.grants.append((int(epoch), float(ttl_ms)))


class _FakeRouter:
    def __init__(self, backend):
        self.n_shards = 1
        self.primary = backend
        self.replacements = {}
        self.failed = set()

    def shard_primary(self, q):
        return self.primary

    def shard_health(self):
        return {0: "failed" if 0 in self.failed
                else "promoted" if 0 in self.replacements else "active"}

    def fail_shard(self, q):
        self.failed.add(int(q))

    def install_replacement(self, q, backend):
        self.replacements[int(q)] = backend
        self.failed.discard(int(q))

    def _backend(self, q):
        if q in self.failed:
            return None
        return self.replacements.get(int(q), self.primary)


class _FakeReceiver:
    def __init__(self):
        self.consistent = True
        self.promoted = False
        self.last_epoch = 7
        self.backend = _FakeBackend()

    def promote(self, force=False):
        self.promoted = True
        return self.backend


def _fake_orch(backend, witness=None, **cfg_kw):
    rx = _FakeReceiver()
    router = _FakeRouter(backend)
    standby_set = types.SimpleNamespace(receivers=[rx],
                                        replace=lambda *a: None)
    sim = {"s": 0.0}
    cfg = OrchestratorConfig(probe_interval_ms=50.0, suspect_threshold=2,
                             hysteresis_ms=100.0, promote_backoff_ms=1.0,
                             reseed=False, **cfg_kw)
    probe_ok = {"v": True}
    # An installed replacement answers probes (else the machine would
    # immediately re-suspect what it just promoted).
    orch = FailoverOrchestrator(
        router, standby_set, None, config=cfg,
        probe=lambda q: probe_ok["v"] or bool(router.replacements),
        witness=witness,
        lease_channels={0: types.SimpleNamespace(
            grant=backend.grant_serving_lease)},
        clock=lambda: sim["s"], sleep=lambda s: None)

    def tick(n=1):
        for _ in range(n):
            sim["s"] += cfg.probe_interval_ms / 1000.0
            orch.tick()

    return orch, router, rx, probe_ok, tick, sim


def test_witness_veto_holds_fencing_while_primary_heartbeats_land():
    backend = _FakeBackend()
    verdict = {"v": "alive"}
    orch, router, rx, probe_ok, tick, _ = _fake_orch(
        backend, witness=lambda q: verdict["v"], fence_lease_ttl_ms=400.0)
    tick(2)
    assert backend.grants, "healthy ticks granted no serving lease"
    probe_ok["v"] = False
    # Probe says dead; the standby still hears the primary -> every
    # hysteresis expiry is VETOED, nothing fences, nothing promotes.
    tick(12)
    st = orch.status()
    assert st["witness_vetoes"] >= 1
    assert orch.fence_epoch == 0 and orch.promotions == 0
    assert not backend.fences and not router.failed
    assert st["shards"][0]["state"] in ("MONITORING", "SUSPECT")
    # The witness flips to dead (heartbeats stopped landing): the same
    # probe verdict now fences and promotes.
    verdict["v"] = "dead"
    tick(12)
    assert orch.fence_epoch == 1 and orch.promotions == 1
    assert rx.promoted and router.replacements[0] is rx.backend
    # The replacement was handed a lease at a STRICTLY higher epoch
    # than anything the zombie ever held.
    assert rx.backend.grants and rx.backend.grants[0][0] == 2
    assert all(ep < 2 for ep, _ in backend.grants)


def test_fencing_waits_out_an_unreachable_zombies_lease():
    backend = _FakeBackend(fence_reachable=False)
    orch, router, rx, probe_ok, tick, sim = _fake_orch(
        backend, witness=lambda q: "dead",
        fence_lease_ttl_ms=1000.0, fence_wait_slack_ms=100.0)
    tick(2)  # healthy: leases granted
    granted_at = orch._watch[0].lease_granted_at
    probe_ok["v"] = False
    tick(6)  # SUSPECT -> hysteresis -> FENCING (fence RPC fails)
    st = orch.status()["shards"][0]["state"]
    assert st == "FENCING", st
    assert orch.fence_epoch == 1  # epoch bumped even though undeliverable
    assert router.failed == {0}   # routed traffic fails closed meanwhile
    assert orch.promotions == 0, (
        "promoted before the zombie's lease could have expired")
    # FENCING holds until granted_at + ttl + slack ON THE ORCHESTRATOR'S
    # CLOCK, then promotion proceeds.
    wait_until = granted_at + 1.1
    while sim["s"] < wait_until - 0.05:
        tick(1)
        assert orch.promotions == 0, f"promoted early at {sim['s']}"
    tick(3)
    assert orch.promotions == 1 and rx.promoted


def test_witness_without_verdict_never_vetoes():
    backend = _FakeBackend()
    orch, router, rx, probe_ok, tick, _ = _fake_orch(
        backend, witness=lambda q: "unknown")
    probe_ok["v"] = False
    tick(12)
    # "unknown" proves nothing: the probe verdict drives the machine
    # exactly as without a witness.
    assert orch.promotions == 1 and orch.status()["witness_vetoes"] == 0


# ---------------------------------------------------------------------------
# Asymmetric partitions (half-open links)
# ---------------------------------------------------------------------------

def test_half_open_link_reads_dead_while_bytes_still_land():
    storage = TpuBatchedStorage(num_slots=128)
    receiver = StandbyReceiver(storage)
    server = ReplicationServer(receiver, host="127.0.0.1").start()
    proxy = FaultInjectingProxy(server.port).start()
    sink = SocketSink("127.0.0.1", proxy.port, timeout=2.0,
                      max_retries=0, ack_timeout=0.3, dead_after=2)
    try:
        assert sink.heartbeat() is True
        assert sink.link_state() == "up"
        rx_before = server.rx_age_ms()
        assert rx_before is not None
        # Cut ONLY the server->client direction: sends still LAND at
        # the standby, acks vanish — the half-open link shape.
        proxy.partition(direction="down")
        assert sink.heartbeat() is False
        assert sink.heartbeat() is False
        assert sink.link_state() == "dead", (
            "ack loss on a half-open link must read DEAD")
        # Proof the bytes arrived: the standby's rx stamp kept fresh
        # through the 'dead' verdict (the witness-side distinction
        # between 'standby cannot answer' and 'primary stopped talking').
        assert server.rx_age_ms() < 2000.0
        proxy.heal()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not sink.heartbeat():
            time.sleep(0.05)
        assert sink.link_state() == "up"
    finally:
        sink.close()
        proxy.stop()
        server.stop()
        storage.close()


def test_partition_direction_validation():
    proxy = FaultInjectingProxy(1)  # never started; control surface only
    with pytest.raises(ValueError, match="direction"):
        proxy.partition(direction="sideways")


def test_default_probe_counts_ship_error_growth_as_failure():
    backend = _FakeBackend()
    router = _FakeRouter(backend)
    replicator = types.SimpleNamespace(
        shard_errors=[0], shard_link_state=lambda q: "up")
    orch = FailoverOrchestrator(
        router, types.SimpleNamespace(receivers=[_FakeReceiver()],
                                      replace=lambda *a: None),
        replicator, clock=lambda: 0.0, sleep=lambda s: None)
    assert orch._default_probe(0) is True
    # A half-open replication link fails ships; the error-streak growth
    # IS the probe signal for the primary (non-blocking by design).
    replicator.shard_errors[0] += 1
    assert orch._default_probe(0) is False
    assert orch._default_probe(0) is True  # no growth since last look


# ---------------------------------------------------------------------------
# Remote directory bookkeeping
# ---------------------------------------------------------------------------

def test_remote_directory_tracks_serving_backend():
    class _B:
        def is_available(self):
            return True

        def close(self):
            pass

    primary, replacement = _B(), _B()
    d = RemoteShardDirectory({0: primary})
    assert d.serving(0) is primary
    assert d.shard_health() == {0: "active"}
    d.fail_shard(0)
    assert d.serving(0) is None  # fail-closed window
    assert d.shard_health() == {0: "failed"}
    assert d.shard_status()[0]["state"] == "failed"
    d.install_replacement(0, replacement)
    assert d.serving(0) is replacement
    assert d.degraded_shards() == [0]
    d.repair_shard(0)
    assert d.serving(0) is primary
    assert d.shard_health() == {0: "active"}


# ---------------------------------------------------------------------------
# Satellite: telemetry re-arm after reconnect
# ---------------------------------------------------------------------------

def test_telemetry_latch_rearms_after_reconnect():
    from ratelimiter_tpu.leases.client import LeaseClient
    from ratelimiter_tpu.service.sidecar import SidecarClient, SidecarServer

    storage = TpuBatchedStorage(num_slots=128, max_delay_ms=0.2)
    server = SidecarServer(storage, host="127.0.0.1",
                           drain_timeout_ms=200.0).start()
    lid = server.register("tb", RateLimitConfig(
        max_permits=100, window_ms=60_000, refill_rate=50.0))
    client = SidecarClient("127.0.0.1", server.port)
    burner = LeaseClient(client, lid, telemetry=True,
                         telemetry_flush_ms=0.0, telemetry_rearm_ms=0.0)
    try:
        assert client.telemetry_supported()
        # Kill the socket under the client: the next telemetry write
        # fails and LATCHES the connection's telemetry down.
        client._sock.close()
        burner._telem.record_burn(lid, "k", 1, 1.0)
        burner._flush_telemetry(T0)
        assert burner.telemetry_dropped == 1
        assert client._telemetry_down is True
        assert not client.telemetry_supported()
        # The next flush re-arms: reconnect + re-HELLO succeeds against
        # the live server, the latch clears, the report ships.
        burner._telem.record_burn(lid, "k", 1, 1.0)
        burner._flush_telemetry(T0 + 1)
        assert burner.telemetry_rearmed == 1
        assert client._telemetry_down is False
        assert client.server_version >= 4
        assert burner.telemetry_flushes == 1
        # The decision path works on the fresh connection too.
        assert client.try_acquire(lid, "k2") is True
    finally:
        client.close()
        server.stop()
        storage.close()


# ---------------------------------------------------------------------------
# Satellite: terminal FAILED shards are DOWN
# ---------------------------------------------------------------------------

def _fake_ctx(shard_states):
    from ratelimiter_tpu.service.props import AppProperties

    status = {
        "fence_epoch": 1, "promotions": 0, "false_alarms": 0,
        "shards": {q: {"state": s} for q, s in shard_states.items()},
    }
    storage = types.SimpleNamespace(is_available=lambda: True)
    return types.SimpleNamespace(
        storage=storage, registry=None, props=AppProperties(),
        breaker=None, sidecar=None, recorder=None, fail_open=True,
        orchestrator=types.SimpleNamespace(
            orchestrator=types.SimpleNamespace(status=lambda: status)))


def test_health_terminal_failed_shard_is_down():
    from ratelimiter_tpu.service.app import health_payload

    payload = health_payload(_fake_ctx({0: "FAILED", 1: "MONITORING"}))
    assert payload["status"] == "DOWN"
    assert payload["orchestrator"]["failed_shards"] == [0]
    # A shard mid-promotion (recovery in flight) is NOT an outage.
    payload = health_payload(_fake_ctx({0: "PROMOTING", 1: "MONITORING"}))
    assert payload["status"] != "DOWN"
    assert payload["orchestrator"]["failed_shards"] == []


# ---------------------------------------------------------------------------
# Wiring: the per-node control port
# ---------------------------------------------------------------------------

def test_wiring_control_port_serves_fence_authority():
    from ratelimiter_tpu.service.props import AppProperties
    from ratelimiter_tpu.service.wiring import build_app

    ctx = build_app(AppProperties({
        "storage.num_slots": "256",
        "parallel.shard": "off",
        "warmup.enabled": "false",
        "ratelimiter.control.port": "0",
    }))
    try:
        assert ctx.control is None  # port 0 = off (the default)
    finally:
        ctx.close()
    import socket as socket_mod

    with socket_mod.socket() as s:  # grab a free port for the config
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    ctx = build_app(AppProperties({
        "storage.num_slots": "256",
        "parallel.shard": "off",
        "warmup.enabled": "false",
        "ratelimiter.control.port": str(port),
    }))
    try:
        assert ctx.control is not None and ctx.control.port == port
        client = ControlClient("127.0.0.1", port, timeout=2.0)
        probe = client.call_ok("probe")
        assert probe["role"] == "primary" and probe["available"]
        client.call_ok("lease", epoch=1, ttl_ms=60_000.0)
        assert client.call_ok("probe")["fence"]["epoch"] == 1
        client.close()
    finally:
        ctx.close()


# ---------------------------------------------------------------------------
# The multi-process drill
# ---------------------------------------------------------------------------

def test_cross_host_failover_drill_fast():
    from ratelimiter_tpu.storage.chaos import cross_host_failover_drill

    report = cross_host_failover_drill()
    assert report["mismatches"] == 0
    assert report["scenario_a"]["witness_vetoes"] >= 1
    b = report["scenario_b"]
    assert b["self_fence_after_s"] <= b["lease_ttl_s"] + 0.75
    assert b["promotion_after_s"] >= b["self_fence_after_s"]
    assert b["new_epoch"] > b["old_epoch"]
    assert report["status"]["promotions"] == 1


@pytest.mark.slow
def test_cross_host_soak_slow():
    """Three full kill/partition cycles, fresh processes each — proves
    the drill's topology builds and tears down cleanly under repetition
    (each cycle is one partition-A + partition-B sequence)."""
    from ratelimiter_tpu.storage.chaos import cross_host_failover_drill

    for cycle in range(3):
        report = cross_host_failover_drill(seed=cycle)
        assert report["mismatches"] == 0, (cycle, report)
        assert report["status"]["promotions"] == 1, (cycle, report)
