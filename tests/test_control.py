"""Adaptive policy control plane (control/, ARCHITECTURE §15).

- Live set_policy actuation: generation metadata, bit-identity across
  an update boundary on the micro / stream / lease paths vs an oracle
  fed the same generation schedule, hybrid-tier invalidation.
- AIMD convergence on a simulated clock: storm -> multiplicative cut ->
  additive recovery; pinned-lid immunity; hierarchical global cap.
- Concurrency slots: lease budgets bounded by max_concurrent.
- The LimiterTable._grow hazard regression: a capacity grow under
  concurrent dispatch stays decision-safe (and warns).
- Policy replication: a mid-stream update crosses a PR 9 failover —
  the promoted standby serves the post-update generation.
"""

import threading

import numpy as np
import pytest

from ratelimiter_tpu.control import AdaptivePolicyController, ControlConfig
from ratelimiter_tpu.core.config import RateLimitConfig
from ratelimiter_tpu.engine.state import LimiterTable
from ratelimiter_tpu.metrics import MeterRegistry
from ratelimiter_tpu.observability.flightrecorder import FlightRecorder
from ratelimiter_tpu.semantics.oracle import (
    SlidingWindowOracle,
    TokenBucketOracle,
)
from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

T0 = 1_700_000_000_000


def make_storage(clock, **kw):
    kw.setdefault("num_slots", 512)
    kw.setdefault("max_delay_ms", 0.2)
    return TpuBatchedStorage(clock_ms=lambda: clock["t"], **kw)


# ---------------------------------------------------------------------------
# Actuation path: set_policy + generations
# ---------------------------------------------------------------------------

def test_set_policy_generation_metadata():
    clock = {"t": T0}
    st = make_storage(clock)
    lid = st.register_limiter("sw", RateLimitConfig(max_permits=10,
                                                    window_ms=1000))
    assert st.policy_info()["generation"] == 0
    assert st.policy_info()["lids"][lid]["generation"] == 0
    gen = st.set_policy(lid, RateLimitConfig(max_permits=5,
                                             window_ms=1000))
    info = st.policy_info()
    assert gen == 1 and info["generation"] == 1
    assert info["lids"][lid] == {
        "algo": "sw", "generation": 1, "max_permits": 5,
        "window_ms": 1000, "refill_rate": 0.0}
    # Window is shape: immutable.
    with pytest.raises(ValueError):
        st.set_policy(lid, RateLimitConfig(max_permits=5, window_ms=2000))
    with pytest.raises(KeyError):
        st.set_policy(99, RateLimitConfig(max_permits=5, window_ms=1000))
    st.close()


def test_bit_identity_across_policy_boundary_micro_and_stream():
    """Micro batches and the string-stream path must stay bit-identical
    to an oracle fed the SAME generation schedule (raise AND cut, both
    algos), with per-key state carried across the boundary."""
    clock = {"t": T0}
    st = make_storage(clock)
    sw0 = RateLimitConfig(max_permits=8, window_ms=1000)
    tb0 = RateLimitConfig(max_permits=20, window_ms=1000, refill_rate=10.0)
    lid_sw = st.register_limiter("sw", sw0)
    lid_tb = st.register_limiter("tb", tb0)
    osw, otb = SlidingWindowOracle(sw0), TokenBucketOracle(tb0)
    st.add_policy_listener(
        lambda lid, algo, cfg, gen:
            (osw if lid == lid_sw else otb).reconfigure(cfg))

    rng = np.random.default_rng(42)
    schedule = [None, (3, 5.0), None, (30, 2.0), (8, 10.0), None]
    keys = [f"u{i}" for i in range(6)]
    for step, update in enumerate(schedule):
        if update is not None:
            mp, rate = update
            st.set_policy(lid_sw, RateLimitConfig(max_permits=mp,
                                                  window_ms=1000))
            st.set_policy(lid_tb, RateLimitConfig(
                max_permits=mp, window_ms=1000, refill_rate=rate))
        clock["t"] += int(rng.choice([1, 250, 400, 999, 1500]))
        now = clock["t"]
        ks = [keys[i] for i in rng.integers(0, len(keys), 24)]
        out = st.acquire_many("sw", [lid_sw] * 24, ks, [1] * 24)
        expect = [osw.try_acquire(k, 1, now) for k in ks]
        assert out["allowed"].tolist() == [d.allowed for d in expect], step
        assert out["observed"].tolist() == [d.observed for d in expect]
        out = st.acquire_many("tb", [lid_tb] * 24, ks, [1] * 24)
        expect = [otb.try_acquire(k, 1, now) for k in ks]
        assert out["allowed"].tolist() == [d.allowed for d in expect], step
        # String-stream path (relay/digest machinery) across the same
        # generation schedule.
        sk = [keys[i] for i in rng.integers(0, len(keys), 64)]
        allowed = st.acquire_stream_strs("sw", lid_sw, sk)
        expect = [osw.try_acquire(k, 1, now).allowed for k in sk]
        assert np.asarray(allowed).tolist() == expect, step
    st.close()


def test_bit_identity_across_policy_boundary_lease_path():
    """lease_reserve / lease_credit against the oracle reserve/credit
    spec across a rate cut: a renewal at an older generation
    re-reserves under the NEW rate."""
    clock = {"t": T0 + 100}
    st = make_storage(clock)
    cfg0 = RateLimitConfig(max_permits=20, window_ms=1000)
    lid = st.register_limiter("sw", cfg0)
    oracle = SlidingWindowOracle(cfg0)
    st.add_policy_listener(
        lambda l, algo, cfg, gen: oracle.reconfigure(cfg))

    out = st.lease_reserve("sw", lid, "k", 16)
    got, ws = oracle.reserve("k", 16, clock["t"])
    assert (out["granted"], out["ws"]) == (got, ws) == (16, out["ws"])

    st.set_policy(lid, RateLimitConfig(max_permits=6, window_ms=1000))
    # Credit back 10 unused, re-reserve: the new rate clamps the grant.
    cr = st.lease_credit("sw", lid, "k", 10, out["ws"])
    assert cr["credited"] == oracle.credit("k", 10, ws, clock["t"])
    out2 = st.lease_reserve("sw", lid, "k", 16)
    got2, _ = oracle.reserve("k", 16, clock["t"])
    assert out2["granted"] == got2
    assert out2["granted"] == 0  # 6 charged > new max 6: nothing left
    st.close()


def test_lease_manager_rebases_budget_after_policy_cut():
    from ratelimiter_tpu.leases import LeaseManager

    clock = {"t": T0}
    st = make_storage(clock)
    lid = st.register_limiter("sw", RateLimitConfig(max_permits=100,
                                                    window_ms=1000))
    mgr = LeaseManager(st, default_budget=64, ttl_ms=10_000.0)
    g = mgr.grant(lid, "k")
    assert g.granted == 64
    st.set_policy(lid, RateLimitConfig(max_permits=10, window_ms=1000))
    # Renewal at the older generation: unused budget credited, fresh
    # budget clamped by the NEW rate.
    g2 = mgr.renew(lid, "k", used=4)
    assert g2 is not None and 0 < g2.granted <= 10
    assert mgr.policy_rebased_total == 1
    st.close()


def test_set_policy_invalidates_hybrid_serving_entries():
    clock = {"t": T0}
    st = make_storage(clock, serving_cache=True,
                      serving_cache_ttl_ms=10_000.0)
    lid = st.register_limiter("tb", RateLimitConfig(
        max_permits=10, window_ms=60_000, refill_rate=5.0))
    # Adopt: an allowed decision from a full bucket.
    st.acquire("tb", lid, "h", 1)
    st.flush()
    assert len(st._serving) == 1
    st.set_policy(lid, RateLimitConfig(max_permits=4, window_ms=60_000,
                                       refill_rate=5.0))
    assert len(st._serving) == 0  # entry dropped with the old policy
    # Decisions after the update still match the oracle under the new
    # config with the pre-update consumption intact.
    oracle = TokenBucketOracle(RateLimitConfig(
        max_permits=10, window_ms=60_000, refill_rate=5.0))
    oracle.try_acquire("h", 1, T0)
    oracle.reconfigure(RateLimitConfig(max_permits=4, window_ms=60_000,
                                       refill_rate=5.0))
    clock["t"] += 10
    out = st.acquire("tb", lid, "h", 1)
    d = oracle.try_acquire("h", 1, clock["t"])
    assert bool(out["allowed"]) == d.allowed
    assert int(out["observed"]) == d.observed
    st.close()


# ---------------------------------------------------------------------------
# AIMD controller
# ---------------------------------------------------------------------------

def _drive(st, lid, key, demand, now):
    out = st.acquire_many("sw", [lid] * demand, [key] * demand,
                          [1] * demand)
    return int(out["allowed"].sum())


def make_controller(st, clock, registry=None, recorder=None, **cfg):
    cfg.setdefault("interval_ms", 1000.0)
    cfg.setdefault("window_ms", 2000)
    cfg.setdefault("min_load_per_s", 1.0)
    return AdaptivePolicyController(
        st, ControlConfig(**cfg), registry=registry, recorder=recorder,
        clock_ms=lambda: clock["t"])


def test_aimd_storm_cut_and_recovery_simulated_clock():
    """Storm -> multiplicative cut toward the floor -> post-storm
    additive recovery back to the ceiling, all on a simulated clock."""
    clock = {"t": T0}
    st = make_storage(clock)
    registry = MeterRegistry()
    recorder = FlightRecorder(256)
    lid = st.register_limiter("sw", RateLimitConfig(max_permits=100,
                                                    window_ms=1000))
    ctl = AdaptivePolicyController(
        st, ControlConfig(interval_ms=1000.0, window_ms=2000,
                          floor_fraction=0.1, decrease_factor=0.5,
                          increase_fraction=0.1, min_load_per_s=1.0),
        registry=registry, recorder=recorder,
        clock_ms=lambda: clock["t"])

    fractions = []
    for sec in range(24):
        clock["t"] += 1000
        demand = 1000 if sec < 8 else 20   # storm, then normal load
        _drive(st, lid, "t", demand, clock["t"])
        ctl.tick()
        fractions.append(ctl.status()["lids"][str(lid)]["fraction"])
    # Cut phase: reaches the floor within a few ticks.
    assert min(fractions[:8]) == pytest.approx(0.1)
    # Recovery: additive raise back to the ceiling.
    assert fractions[-1] == pytest.approx(1.0)
    assert fractions[10] < fractions[14] < fractions[-1]
    status = ctl.status()
    assert status["adjustments"] > 0
    assert status["generation"] == st.policy_info()["generation"] > 0
    # Effective policy is back at the registered ceiling.
    assert status["lids"][str(lid)]["effective_max_permits"] == 100
    # Coalesced flight events: the whole convergence is a handful of
    # tallied policy.adjusted entries, not one per tick.
    kinds = [e["kind"] for e in recorder.snapshot(last=256)["events"]]
    n_adjust_events = kinds.count("policy.adjusted")
    assert 0 < n_adjust_events < status["adjustments"]
    meters = registry.scrape()
    assert meters["ratelimiter.control.adjustments"] == \
        status["adjustments"]
    assert meters["ratelimiter.control.generation"] == \
        status["generation"]
    ctl.close()
    st.close()


def test_pinned_lid_is_immune_to_the_loop():
    clock = {"t": T0}
    st = make_storage(clock)
    lid_a = st.register_limiter("sw", RateLimitConfig(max_permits=50,
                                                      window_ms=1000))
    lid_b = st.register_limiter("sw", RateLimitConfig(max_permits=50,
                                                      window_ms=1000))
    ctl = make_controller(st, clock)
    ctl.pin(lid_b)
    for _ in range(4):
        clock["t"] += 1000
        _drive(st, lid_a, "a", 500, clock["t"])   # both storm equally
        _drive(st, lid_b, "b", 500, clock["t"])
        ctl.tick()
    s = ctl.status()
    assert s["lids"][str(lid_a)]["fraction"] < 1.0
    assert s["lids"][str(lid_b)]["fraction"] == 1.0
    assert s["lids"][str(lid_b)]["state"] == "PINNED"
    assert s["pinned"] == [lid_b]
    assert st.policy_info()["lids"][lid_b]["generation"] == 0
    assert st.policy_info()["lids"][lid_b]["max_permits"] == 50
    # Unpin: the lid rejoins the loop and gets cut like its peer.
    ctl.pin(lid_b, pinned=False)
    clock["t"] += 1000
    _drive(st, lid_b, "b", 500, clock["t"])
    ctl.tick()
    assert ctl.status()["lids"][str(lid_b)]["fraction"] < 1.0
    ctl.close()
    st.close()


def test_global_cap_scales_every_tenant():
    """Fleet admitted over the hierarchical cap: every unpinned
    tenant's effective rate scales by cap/admitted (floor-protected),
    and the engagement is a flight event + gauge."""
    clock = {"t": T0}
    st = make_storage(clock)
    registry = MeterRegistry()
    recorder = FlightRecorder(64)
    lids = [st.register_limiter("sw", RateLimitConfig(
        max_permits=100, window_ms=1000)) for _ in range(3)]
    ctl = make_controller(st, clock, registry=registry,
                          recorder=recorder, global_cap_per_s=120.0,
                          target_excess=0.99)
    for _ in range(3):
        clock["t"] += 1000
        for i, lid in enumerate(lids):
            _drive(st, lid, f"k{i}", 80, clock["t"])  # 240/s aggregate
        ctl.tick()
    s = ctl.status()
    assert s["global_scale"] < 1.0
    assert s["global_cap_engagements"] > 0
    for lid in lids:
        eff = s["lids"][str(lid)]["effective_max_permits"]
        assert eff < 100
    assert registry.scrape()["ratelimiter.control.global_scale"] < 1.0
    kinds = [e["kind"] for e in recorder.snapshot(last=64)["events"]]
    assert "control.global_cap_engaged" in kinds
    # Load back under the cap: the scale releases to 1.0.
    for _ in range(6):
        clock["t"] += 1000
        _drive(st, lids[0], "k0", 30, clock["t"])
        ctl.tick()
    assert ctl.status()["global_scale"] == 1.0
    ctl.close()
    st.close()


def test_global_cap_engages_on_raw_observed_load_not_admitted():
    """The shed-heavy storm regression: per-tenant limits deny most of
    the storm, so the ADMITTED rate stays far under the cap while raw
    arrivals are far above it.  Admitted-rate scaling would never
    engage here; the cap must trigger and size on OBSERVED load."""
    clock = {"t": T0}
    st = make_storage(clock)
    recorder = FlightRecorder(64)
    lid = st.register_limiter("sw", RateLimitConfig(max_permits=30,
                                                    window_ms=1000))
    ctl = make_controller(st, clock, recorder=recorder,
                          global_cap_per_s=120.0, target_excess=0.99)
    admitted = 0
    for _ in range(3):
        clock["t"] += 1000
        admitted = _drive(st, lid, "hot", 200, clock["t"])  # 200/s raw
        ctl.tick()
    assert admitted <= 30  # the per-tenant limit sheds the storm...
    s = ctl.status()
    assert s["global_cap_engagements"] > 0
    assert s["global_scale"] == pytest.approx(120.0 / 200.0, rel=0.2)
    events = [e for e in recorder.snapshot(last=64)["events"]
              if e["kind"] == "control.global_cap_engaged"]
    assert events and events[-1]["observed_per_s"] > 120.0
    assert events[-1]["admitted_per_s"] < 120.0  # the old rule's blind spot
    ctl.close()
    st.close()


# ---------------------------------------------------------------------------
# Concurrency slots (leases as slots)
# ---------------------------------------------------------------------------

def test_concurrency_slots_bound_outstanding_lease_budget():
    from ratelimiter_tpu.leases import LeaseManager

    clock = {"t": T0}
    st = make_storage(clock)
    lid = st.register_limiter("tb", RateLimitConfig(
        max_permits=1000, window_ms=60_000, refill_rate=100.0))
    mgr = LeaseManager(st, default_budget=8, max_budget=64,
                       ttl_ms=60_000.0)
    mgr.set_concurrency_cap(lid, 16)
    g1 = mgr.grant(lid, "worker-a", requested=8)
    g2 = mgr.grant(lid, "worker-b", requested=8)
    assert g1.granted == 8 and g2.granted == 8
    # Slots exhausted: a third worker is refused (stays per-decision).
    g3 = mgr.grant(lid, "worker-c", requested=8)
    assert g3.granted == 0
    assert mgr.concurrency_refused_total == 1
    assert mgr.table.outstanding_budget_for("tb", lid) == 16
    # Release frees slots.
    mgr.release(lid, "worker-a", used=8)
    g4 = mgr.grant(lid, "worker-c", requested=8)
    assert g4.granted == 8
    # A renewal only competes with OTHER leases, not its own budget.
    g5 = mgr.renew(lid, "worker-b", used=8, requested=8)
    assert g5 is not None and g5.granted == 8
    # Cap cut below outstanding: the next renewal revokes to the
    # per-decision path (lazy convergence) and credits the remainder.
    mgr.set_concurrency_cap(lid, 8)
    g6 = mgr.renew(lid, "worker-c", used=0, requested=8)
    assert g6 is not None and g6.granted == 0
    assert mgr.table.get("tb", lid, "worker-c") is None
    assert mgr.status()["concurrency_caps"] == {lid: 8}
    st.close()


# ---------------------------------------------------------------------------
# LimiterTable._grow hazard regression
# ---------------------------------------------------------------------------

def test_grow_under_concurrent_dispatch_is_decision_safe():
    """Registering past the table capacity under live traffic must warn
    (the recompile stall is real) but never corrupt decisions."""
    import logging

    clock = {"t": T0}
    st = make_storage(clock, table_capacity=4)
    cfg = RateLimitConfig(max_permits=50, window_ms=1000)
    lid = st.register_limiter("sw", cfg)
    oracle = SlidingWindowOracle(cfg)

    stop = threading.Event()
    errors = []

    def traffic():
        i = 0
        while not stop.is_set():
            try:
                st.acquire_many("sw", [lid] * 8,
                                [f"g{i % 4}"] * 8, [1] * 8)
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)
                return
            i += 1

    thread = threading.Thread(target=traffic)
    thread.start()
    grew = []
    # Capture the grow warning directly off the module logger (the
    # ratelimiter_tpu hierarchy does not propagate to root once
    # setup_logging has run in-session, so caplog would miss it).
    records = []
    handler = logging.Handler()
    handler.emit = records.append
    grow_log = logging.getLogger("ratelimiter_tpu.engine.state")
    grow_log.addHandler(handler)
    try:
        for _ in range(12):  # capacity 4 -> forces >= 2 implicit grows
            grew.append(st.register_limiter("sw", cfg))
    finally:
        grow_log.removeHandler(handler)
    stop.set()
    thread.join()
    assert not errors
    assert st.table.implicit_grows >= 1
    assert any("recompiles" in r.getMessage() for r in records)
    # Decisions on the ORIGINAL lid remained well-formed through the
    # grows; replay a deterministic wave now and require bit-identity.
    st.flush()
    clock["t"] += 5000   # fresh windows: oracle state re-synchronizes
    for lid_new in grew:
        out = st.acquire_many("sw", [lid_new] * 4, ["x"] * 4, [1] * 4)
        assert out["allowed"].tolist() == [True] * 4
    out = st.acquire_many("sw", [lid] * 60, ["fresh"] * 60, [1] * 60)
    expect = [oracle.try_acquire("fresh", 1, clock["t"]).allowed
              for _ in range(60)]
    assert out["allowed"].tolist() == expect
    # Pre-sizing avoids the hazard entirely.
    st2 = make_storage({"t": T0}, table_capacity=64)
    for _ in range(40):
        st2.register_limiter("sw", cfg)
    assert st2.table.implicit_grows == 0
    st2.close()
    st.close()


# ---------------------------------------------------------------------------
# Policy replication across failover (the chaos drill)
# ---------------------------------------------------------------------------

def test_policy_update_replicates_across_failover():
    """A mid-stream set_policy crosses the PR 9 replication stream: the
    promoted standby serves the POST-update generation, decisions
    bit-identical to the generation-aware oracle."""
    from ratelimiter_tpu.replication import (
        InProcessSink,
        ReplicationLog,
        Replicator,
        StandbyReceiver,
    )

    clock = {"t": T0}
    primary = make_storage(clock, num_slots=512)
    standby = make_storage(clock, num_slots=512)
    cfg0 = RateLimitConfig(max_permits=12, window_ms=1000)
    lid = primary.register_limiter("sw", cfg0)
    oracle = SlidingWindowOracle(cfg0)
    log = ReplicationLog(primary)
    receiver = StandbyReceiver(standby)
    repl = Replicator(log, InProcessSink(receiver))

    def wave(storage, n=24):
        keys = [f"w{i % 8}" for i in range(n)]
        out = storage.acquire_many("sw", [lid] * n, keys, [1] * n)
        expect = [oracle.try_acquire(k, 1, clock["t"]).allowed
                  for k in keys]
        assert out["allowed"].tolist() == expect

    wave(primary)
    repl.ship_now()
    # Mid-stream policy update, then more traffic under the new rate.
    new_cfg = RateLimitConfig(max_permits=4, window_ms=1000)
    gen = primary.set_policy(lid, new_cfg)
    oracle.reconfigure(new_cfg)
    clock["t"] += 400
    wave(primary)
    repl.ship_now()

    # Failover: the promoted standby must carry the post-update
    # generation and decide under the NEW policy.
    promoted = receiver.promote()
    assert promoted.policy_info()["generation"] == gen == 1
    assert promoted.policy_info()["lids"][lid]["max_permits"] == 4
    clock["t"] += 2000   # fresh window: continuation is exact
    wave(promoted)
    repl.close()
    primary.close()
    standby.close()


def test_policy_update_after_bootstrap_frame_applies_on_standby():
    """A standby that registered the ORIGINAL config from an early
    frame must apply a later frame's rate change (newer generation)
    instead of refusing it as drift — while true drift still raises."""
    from ratelimiter_tpu.engine.checkpoint import apply_limiter_policies

    clock = {"t": T0}
    st = make_storage(clock)
    lid = st.register_limiter("sw", RateLimitConfig(max_permits=12,
                                                    window_ms=1000))
    # Newer generation: applied.
    apply_limiter_policies(st, {str(lid): {
        "algo": "sw", "max_permits": 5, "window_ms": 1000,
        "refill_rate": 0.0, "gen": 3}})
    assert st.policy_info()["lids"][lid]["max_permits"] == 5
    assert st.policy_info()["lids"][lid]["generation"] == 3
    # Same values, same gen: idempotent no-op.
    apply_limiter_policies(st, {str(lid): {
        "algo": "sw", "max_permits": 5, "window_ms": 1000,
        "refill_rate": 0.0, "gen": 3}})
    # Rate drift with NO newer generation: refused.
    with pytest.raises(ValueError, match="no newer policy generation"):
        apply_limiter_policies(st, {str(lid): {
            "algo": "sw", "max_permits": 7, "window_ms": 1000,
            "refill_rate": 0.0, "gen": 3}})
    # Window drift: always refused.
    with pytest.raises(ValueError, match="algo/window shape"):
        apply_limiter_policies(st, {str(lid): {
            "algo": "sw", "max_permits": 5, "window_ms": 2000,
            "refill_rate": 0.0, "gen": 9}})
    st.close()


# ---------------------------------------------------------------------------
# Operator surface: /actuator/policies + pin + health mirror
# ---------------------------------------------------------------------------

def test_actuator_policies_endpoint_and_pin():
    import http.client
    import json

    from ratelimiter_tpu.service.app import make_server
    from ratelimiter_tpu.service.props import AppProperties
    from ratelimiter_tpu.service.wiring import build_app

    props = AppProperties({
        "storage.backend": "tpu",
        "storage.num_slots": "4096",
        "parallel.shard": "off",
        "warmup.enabled": "false",
        "link.probe.enabled": "false",
        "ratelimiter.control.enabled": "true",
        "ratelimiter.control.interval_ms": "60000",  # tick manually
    })
    ctx = build_app(props)
    srv = make_server(ctx, port=0)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    try:
        conn = http.client.HTTPConnection(
            "127.0.0.1", srv.server_address[1], timeout=10)

        def req(method, path, body=None):
            conn.request(method, path,
                         body=json.dumps(body) if body else None)
            resp = conn.getresponse()
            return resp.status, json.loads(resp.read())

        # Drive one request so lids exist + the controller adopts them.
        conn.request("GET", "/api/data", headers={"X-User-ID": "ctl"})
        conn.getresponse().read()
        ctx.controller.tick()

        status, payload = req("GET", "/actuator/policies")
        assert status == 200 and payload["enabled"]
        assert payload["generation"] == 0
        lid = next(iter(payload["controller"]["lids"]))
        row = payload["controller"]["lids"][lid]
        assert row["state"] in ("IDLE", "STEADY")
        assert not row["pinned"]

        status, out = req("POST", f"/actuator/policies/{lid}/pin")
        assert status == 200 and out["pinned"]
        status, payload = req("GET", "/actuator/policies")
        assert payload["controller"]["lids"][lid]["pinned"]
        assert int(lid) in payload["controller"]["pinned"]

        # Health payload mirrors the control plane.
        status, health = req("GET", "/actuator/health")
        assert health["control"]["pinned"] == [int(lid)]
        assert health["control"]["generation"] == 0

        status, out = req("POST", f"/actuator/policies/{lid}/pin",
                          {"pinned": False})
        assert status == 200 and not out["pinned"]
        status, _ = req("POST", "/actuator/policies/12345/pin")
        assert status == 404
        conn.close()
    finally:
        srv.shutdown()
        ctx.close()
