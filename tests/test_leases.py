"""Token leases (leases/ + ops/lease.py + protocol v3).

Layers under test, bottom-up:

- the RESERVE/CREDIT device kernels against their oracle specification
  (``semantics/oracle.py:reserve/credit``) — bit-identical over random
  interleavings, including duplicate-slot batches (greedy segmented
  grants) and the sharded engine's host round-trip path;
- the storage surface: fence checks, eviction-safe credits, stamps;
- the LeaseManager: one lease per key, TTL clamping to the sliding
  window, fence-epoch revocation, table bounds;
- the LeaseClient: local burn, wire-frame collapse, renewal, fallback;
- the chaos drill (the fast variant verify.sh runs).
"""

import random

import numpy as np
import pytest

from ratelimiter_tpu import RateLimitConfig
from ratelimiter_tpu.leases import DirectTransport, LeaseClient, LeaseManager
from ratelimiter_tpu.metrics import MeterRegistry
from ratelimiter_tpu.semantics import SlidingWindowOracle, TokenBucketOracle
from ratelimiter_tpu.storage import TpuBatchedStorage
from ratelimiter_tpu.storage.errors import FencedError

T0 = 1_753_000_000_000


def make_storage(clock, **kw):
    return TpuBatchedStorage(num_slots=256, clock_ms=lambda: clock["t"],
                             **kw)


# ---------------------------------------------------------------------------
# Kernels vs oracle (the bit-identity contract)
# ---------------------------------------------------------------------------

def test_reserve_credit_matches_oracle_random_stream():
    clock = {"t": T0}
    st = make_storage(clock)
    cfg_sw = RateLimitConfig(max_permits=20, window_ms=2000,
                             enable_local_cache=False)
    cfg_tb = RateLimitConfig(max_permits=50, window_ms=2000,
                             refill_rate=10.0)
    lsw = st.register_limiter("sw", cfg_sw)
    ltb = st.register_limiter("tb", cfg_tb)
    osw = SlidingWindowOracle(cfg_sw)
    otb = TokenBucketOracle(cfg_tb)
    rng = random.Random(0)
    ws_store = {}
    try:
        for step in range(250):
            clock["t"] += rng.choice([1, 7, 250, 999, 2000, 2501])
            now = clock["t"]
            key = f"k{rng.randrange(4)}"
            kind = rng.choice(["res_sw", "res_tb", "cred_sw", "cred_tb"])
            if kind == "res_sw":
                req = rng.randrange(1, 30)
                out = st.lease_reserve("sw", lsw, key, req)
                g, ws = osw.reserve(key, req, now)
                assert (out["granted"], out["ws"]) == (g, ws), (step, kind)
                ws_store[key] = out["ws"]
            elif kind == "res_tb":
                req = rng.randrange(1, 60)
                out = st.lease_reserve("tb", ltb, key, req)
                assert out["granted"] == otb.reserve(key, req, now)[0], (
                    step, kind)
            elif kind == "cred_sw":
                ws = ws_store.get(key, 0)
                c = rng.randrange(0, 10)
                out = st.lease_credit("sw", lsw, key, c, ws)
                assert out["credited"] == osw.credit(key, c, ws, now), (
                    step, kind)
            else:
                c = rng.randrange(0, 20)
                out = st.lease_credit("tb", ltb, key, c, 0)
                assert out["credited"] == otb.credit(key, c, 0, now), (
                    step, kind)
            # Availability must stay bit-identical after every op.
            assert int(st.available_many("sw", lsw, [key])[0]) == \
                osw.get_available_permits(key, now), step
            assert int(st.available_many("tb", ltb, [key])[0]) == \
                otb.get_available_permits(key, now), step
    finally:
        st.close()


def test_reserve_duplicate_slots_grant_greedily():
    """A batch reserving the SAME slot twice grants sequentially —
    exactly two back-to-back oracle reserves at one timestamp."""
    clock = {"t": T0}
    st = make_storage(clock)
    cfg = RateLimitConfig(max_permits=25, window_ms=2000, refill_rate=8.0)
    lid = st.register_limiter("tb", cfg)
    oracle = TokenBucketOracle(cfg)
    try:
        slot = st._assign_slot("tb", lid, "dup", hold_pin=False)
        now = clock["t"]
        granted, _ = st.engine.lease_reserve(
            "tb", [slot, slot], [lid, lid], [20, 20], now)
        want = [oracle.reserve("dup", 20, now)[0],
                oracle.reserve("dup", 20, now)[0]]
        assert list(granted) == want == [20, 5]
    finally:
        st.close()


def test_reserve_on_sharded_engine_matches_oracle():
    from ratelimiter_tpu.engine.state import LimiterTable
    from ratelimiter_tpu.parallel import ShardedDeviceEngine, make_mesh

    clock = {"t": T0}
    engine = ShardedDeviceEngine(slots_per_shard=64, table=LimiterTable(),
                                 mesh=make_mesh(n_devices=4))
    st = TpuBatchedStorage(engine=engine, clock_ms=lambda: clock["t"])
    cfg = RateLimitConfig(max_permits=30, window_ms=2000,
                          enable_local_cache=False)
    lid = st.register_limiter("sw", cfg)
    oracle = SlidingWindowOracle(cfg)
    rng = random.Random(3)
    ws_store = {}
    try:
        for step in range(60):
            clock["t"] += rng.choice([1, 250, 999, 2000])
            now = clock["t"]
            key = f"shk{rng.randrange(6)}"
            if rng.random() < 0.6:
                req = rng.randrange(1, 20)
                out = st.lease_reserve("sw", lid, key, req)
                g, ws = oracle.reserve(key, req, now)
                assert (out["granted"], out["ws"]) == (g, ws), step
                ws_store[key] = out["ws"]
            else:
                c = rng.randrange(0, 8)
                out = st.lease_credit("sw", lid, key, c,
                                      ws_store.get(key, 0))
                assert out["credited"] == oracle.credit(
                    key, c, ws_store.get(key, 0), now), step
            assert int(st.available_many("sw", lid, [key])[0]) == \
                oracle.get_available_permits(key, now), step
    finally:
        st.close()


def test_fenced_storage_refuses_lease_ops():
    clock = {"t": T0}
    st = make_storage(clock)
    lid = st.register_limiter("tb", RateLimitConfig(
        max_permits=10, window_ms=1000, refill_rate=5.0))
    try:
        out = st.lease_reserve("tb", lid, "a", 4)
        assert out["granted"] == 4
        st.fence(7)
        with pytest.raises(FencedError):
            st.lease_reserve("tb", lid, "a", 4)
        with pytest.raises(FencedError):
            st.lease_credit("tb", lid, "a", 2, 0)
    finally:
        st.close()


# ---------------------------------------------------------------------------
# LeaseManager policy
# ---------------------------------------------------------------------------

def test_manager_one_lease_per_key_and_release():
    clock = {"t": T0}
    st = make_storage(clock)
    cfg = RateLimitConfig(max_permits=100, window_ms=60_000,
                          refill_rate=50.0)
    lid = st.register_limiter("tb", cfg)
    mgr = LeaseManager(st, default_budget=16, ttl_ms=1000.0,
                       clock_ms=lambda: clock["t"])
    try:
        g = mgr.grant(lid, "k", 16)
        assert g.granted == 16
        # Second grant on a live lease is refused (one burner per key).
        assert mgr.grant(lid, "k", 16).granted == 0
        # Renew credits the unused remainder and re-charges.
        g2 = mgr.renew(lid, "k", used=10)
        assert g2 is not None and g2.granted == 16
        assert int(st.available_many("tb", lid, ["k"])[0]) == 100 - 10 - 16
        mgr.release(lid, "k", used=4)
        assert mgr.table.outstanding() == 0
        assert int(st.available_many("tb", lid, ["k"])[0]) == 100 - 14
    finally:
        st.close()


def test_manager_sw_ttl_clamps_to_remaining_window():
    clock = {"t": (T0 // 2000) * 2000 + 1500}  # 500 ms left in the window
    st = make_storage(clock)
    cfg = RateLimitConfig(max_permits=100, window_ms=2000,
                          enable_local_cache=False)
    lid = st.register_limiter("sw", cfg)
    mgr = LeaseManager(st, default_budget=8, ttl_ms=60_000.0,
                       clock_ms=lambda: clock["t"])
    try:
        g = mgr.grant(lid, "k", 8)
        assert g.granted == 8
        # The lease must not outlive the charged window.
        assert g.ttl_ms <= 500
    finally:
        st.close()


def test_manager_fence_epoch_revokes_on_renew():
    clock = {"t": T0}
    st = make_storage(clock)
    cfg = RateLimitConfig(max_permits=100, window_ms=60_000,
                          refill_rate=50.0)
    lid = st.register_limiter("tb", cfg)
    registry = MeterRegistry()
    mgr = LeaseManager(st, default_budget=16, ttl_ms=10_000.0,
                       clock_ms=lambda: clock["t"], registry=registry)
    try:
        g = mgr.grant(lid, "k", 16)
        assert g.granted == 16 and g.epoch == 0
        st.fence(3)
        st.lift_fence(3)  # epoch stays 3; storage serves again
        assert mgr.renew(lid, "k", used=5) is None  # REVOKED
        assert mgr.revoked_total == 1
        assert mgr.over_admission_total == 5
        # Re-grant carries the new epoch.
        g2 = mgr.grant(lid, "k", 16)
        assert g2.granted == 16 and g2.epoch == 3
        meters = registry.scrape()
        assert meters["ratelimiter.lease.revoked"] == 1.0
        assert meters["ratelimiter.lease.over_admission"] == 5.0
    finally:
        st.close()


def test_manager_table_bound_refuses_and_uncharges():
    clock = {"t": T0}
    st = make_storage(clock)
    cfg = RateLimitConfig(max_permits=100, window_ms=60_000,
                          refill_rate=50.0)
    lid = st.register_limiter("tb", cfg)
    mgr = LeaseManager(st, default_budget=8, ttl_ms=10_000.0,
                       max_leases=2, clock_ms=lambda: clock["t"])
    try:
        assert mgr.grant(lid, "a", 8).granted == 8
        assert mgr.grant(lid, "b", 8).granted == 8
        assert mgr.grant(lid, "c", 8).granted == 0  # table full
        # The refused grant's charge was credited back.
        assert int(st.available_many("tb", lid, ["c"])[0]) == 100
    finally:
        st.close()


# ---------------------------------------------------------------------------
# LeaseClient burn semantics
# ---------------------------------------------------------------------------

def test_client_wire_collapse_and_reconcile():
    clock = {"t": T0}
    st = make_storage(clock)
    cfg = RateLimitConfig(max_permits=500, window_ms=2000,
                          refill_rate=100.0)
    lid = st.register_limiter("tb", cfg)
    mgr = LeaseManager(st, default_budget=32, ttl_ms=5000.0,
                       record_ops=True, clock_ms=lambda: clock["t"])
    cli = LeaseClient(DirectTransport(mgr), lid, budget=32,
                      clock_ms=lambda: clock["t"], direct_fallback=False)
    try:
        allowed = 0
        for _ in range(300):
            clock["t"] += 1
            allowed += bool(cli.try_acquire("hot"))
        assert allowed == 300
        assert cli.wire_ops * 10 <= 300
        cli.release_all()
        st.flush()
        oracle = TokenBucketOracle(cfg)
        for op in mgr.ops:
            if op[0] == "reserve":
                _, _a, _l, key, req, granted, _ws, stamp = op
                assert oracle.reserve(key, req, stamp)[0] == granted
            else:
                _, _a, _l, key, unused, ws, stamp = op
                oracle.credit(key, unused, ws, stamp)
        assert int(st.available_many("tb", lid, ["hot"])[0]) == \
            oracle.get_available_permits("hot", clock["t"])
    finally:
        st.close()


def test_client_falls_back_per_decision_on_contended_key():
    """granted == 0 (key leased elsewhere) -> the client forwards each
    decision to the ordinary acquire path: the device arbitrates."""
    clock = {"t": T0}
    st = make_storage(clock)
    cfg = RateLimitConfig(max_permits=100, window_ms=60_000,
                          refill_rate=50.0)
    lid = st.register_limiter("tb", cfg)
    mgr = LeaseManager(st, default_budget=16, ttl_ms=10_000.0,
                       clock_ms=lambda: clock["t"])
    holder = LeaseClient(DirectTransport(mgr), lid, budget=16,
                         clock_ms=lambda: clock["t"])
    contender = LeaseClient(DirectTransport(mgr), lid, budget=16,
                            clock_ms=lambda: clock["t"],
                            direct_fallback=True)
    try:
        assert holder.try_acquire("shared")   # holder owns the lease
        assert contender.try_acquire("shared")  # served per-decision
        assert contender.wire_ops >= 2        # grant attempt + fallback
        assert contender.local_decisions == 0
    finally:
        st.close()


# ---------------------------------------------------------------------------
# The drill (fast variant; verify.sh runs this)
# ---------------------------------------------------------------------------

def test_lease_failover_drill_fast():
    from ratelimiter_tpu.storage.chaos import lease_failover_drill

    registry = MeterRegistry()
    report = lease_failover_drill(registry=registry)
    assert report["promotions"] == 1
    assert report["decisions"] > 1000
    assert report["wire_ops_healthy"] * 10 <= report["decisions"]
    assert report["burned_after_fence"] <= \
        report["status"]["outstanding_budget"] + 16 * 16  # bounded
    meters = registry.scrape()
    assert meters["ratelimiter.lease.granted"] >= 1.0
    assert meters["ratelimiter.lease.revoked"] >= 1.0
    assert meters["ratelimiter.lease.local_decisions"] > 1000.0
    assert meters["ratelimiter.lease.outstanding"] == 0.0
