"""Replication & hot-standby failover (ratelimiter_tpu/replication/).

Layers under test, bottom-up:

- the engine's dirty-slot journal marks every dispatch path;
- frame encode/decode round-trips and budget chunking;
- continuous replication converges the standby's packed state to the
  primary's, bit for bit;
- failover (the chaos drill) serves decisions bit-identical to
  ``semantics/oracle.py`` for keys at or before the promoted epoch;
- checkpoint restore + catch-up-from-log equals continuous replication;
- epoch gaps are detected, refuse promotion, and heal via a full frame;
- the sidecar-style TCP transport carries the same guarantee.
"""

import copy
import random

import numpy as np
import pytest

from ratelimiter_tpu import RateLimitConfig
from ratelimiter_tpu.engine.state import SlotJournal
from ratelimiter_tpu.metrics import MeterRegistry
from ratelimiter_tpu.replication import (
    FrameArchive,
    InProcessSink,
    ReplicationLog,
    ReplicationServer,
    ReplicationStateError,
    Replicator,
    SocketSink,
    StandbyReceiver,
    TeeSink,
    chunk_frames,
    decode_frame,
    encode_frame,
    engine_state_fingerprint,
)
from ratelimiter_tpu.semantics import SlidingWindowOracle, TokenBucketOracle
from ratelimiter_tpu.storage import TpuBatchedStorage

T0 = 1_753_000_000_000


def make_pair(num_slots=512, clock=None):
    clock = clock if clock is not None else {"t": T0}
    primary = TpuBatchedStorage(num_slots=num_slots,
                                clock_ms=lambda: clock["t"])
    standby = TpuBatchedStorage(num_slots=num_slots,
                                clock_ms=lambda: clock["t"])
    return clock, primary, standby


# ---------------------------------------------------------------------------
# Journal
# ---------------------------------------------------------------------------

def test_journal_marks_and_drains():
    j = SlotJournal(64)
    j.mark("sw", [3, 5, 5, -1, 999])   # padding/out-of-range filtered
    j.mark("tb", np.array([7], dtype=np.int32))
    assert j.pending() == 3
    deltas, oldest, was_all = j.drain()
    assert sorted(deltas["sw"].tolist()) == [3, 5]
    assert deltas["tb"].tolist() == [7]
    assert oldest is not None and not was_all
    # drained: empty until new marks
    deltas, oldest, _ = j.drain()
    assert deltas == {} and oldest is None
    j.mark_all("sw")
    deltas, _, was_all = j.drain()
    assert was_all and len(deltas["sw"]) == 64 and "tb" not in deltas


def test_engine_dispatch_paths_mark_journal():
    """Every storage decision path must leave its touched slots dirty."""
    clock = {"t": T0}
    storage = TpuBatchedStorage(num_slots=256, clock_ms=lambda: clock["t"])
    log = ReplicationLog(storage)
    j = log.journal
    lid = storage.register_limiter("tb", RateLimitConfig(
        max_permits=50, window_ms=2000, refill_rate=10.0))
    lid_sw = storage.register_limiter("sw", RateLimitConfig(
        max_permits=20, window_ms=2000, enable_local_cache=False))

    # batch path (acquire_many) + scalar path (acquire)
    storage.acquire_many("tb", [lid] * 4, ["a", "b", "c", "d"], [1] * 4)
    storage.acquire("sw", lid_sw, "z", 1)
    storage.flush()
    assert j.pending() >= 5

    deltas, _, _ = j.drain()
    assert len(deltas["tb"]) >= 4 and len(deltas["sw"]) >= 1

    # stream paths (relay/digest/flat elections all mark via the engine)
    keys = np.asarray([1, 2, 3, 1, 2, 9, 9, 9], dtype=np.int64)
    storage.acquire_stream_ids("tb", lid, keys)                      # relay
    storage.acquire_stream_ids("tb", lid, keys,
                               permits=np.full(8, 2))                # weighted
    storage.flush()
    deltas, _, _ = j.drain()
    assert len(deltas["tb"]) >= 4  # 4 distinct keys touched

    # reset path
    storage.reset_key("tb", lid, "a")
    deltas, _, _ = j.drain()
    assert len(deltas["tb"]) >= 1
    storage.close()


# ---------------------------------------------------------------------------
# Wire format
# ---------------------------------------------------------------------------

def test_frame_roundtrip_and_chunking():
    deltas = {
        "sw": {"slots": np.arange(10, dtype=np.int64),
               "rows": np.arange(60, dtype=np.int32).reshape(10, 6)},
        "tb": {"slots": np.array([3, 9], dtype=np.int64),
               "rows": np.arange(8, dtype=np.int32).reshape(2, 4)},
    }
    index_dump = {"algos": {"sw": {"kind": "flat",
                                   "entries": [[[1, "k"], 4]]}}}
    limiters = {"1": {"algo": "sw", "max_permits": 5, "window_ms": 1000,
                      "refill_rate": 0.0}}
    # Tiny budget: every row lands in its own sub-frame.
    frames = chunk_frames(7, 123456, 512, deltas, index_dump, limiters,
                          max_bytes=40)
    assert len(frames) > 3
    assert all(f["epoch"] == 7 for f in frames)
    assert [f["seq"] for f in frames] == list(range(len(frames)))
    assert sum(1 for f in frames if f["last"]) == 1
    assert frames[-1]["last"] and "index" in frames[-1]
    assert all("index" not in f for f in frames[:-1])
    # Every delta row survives the chunking exactly once.
    got = {"sw": [], "tb": []}
    for f in frames:
        rt = decode_frame(encode_frame(f))
        assert rt["epoch"] == 7 and rt["num_slots"] == 512
        for algo, p in rt["algos"].items():
            got[algo].append((p["slots"], p["rows"]))
        if rt["last"]:
            assert rt["index"]["algos"]["sw"]["entries"] == [[[1, "k"], 4]]
            assert rt["limiters"] == limiters
    for algo in ("sw", "tb"):
        slots = np.concatenate([s for s, _ in got[algo]])
        rows = np.concatenate([r for _, r in got[algo]])
        np.testing.assert_array_equal(slots, deltas[algo]["slots"])
        np.testing.assert_array_equal(rows, deltas[algo]["rows"])


def test_frame_rejects_bad_magic():
    with pytest.raises(ValueError):
        decode_frame(b"XXXX" + b"\0" * 16)


# ---------------------------------------------------------------------------
# Continuous replication -> state convergence
# ---------------------------------------------------------------------------

def test_continuous_replication_converges_state():
    clock, primary, standby = make_pair()
    lid = primary.register_limiter("sw", RateLimitConfig(
        max_permits=10, window_ms=1000, enable_local_cache=False))
    lid_tb = primary.register_limiter("tb", RateLimitConfig(
        max_permits=30, window_ms=1000, refill_rate=5.0))
    log = ReplicationLog(primary)
    receiver = StandbyReceiver(standby)
    repl = Replicator(log, InProcessSink(receiver))

    rng = random.Random(1)
    for _ in range(5):
        clock["t"] += rng.choice([1, 500, 1000, 2500])
        keys = [f"k{rng.randrange(24)}" for _ in range(32)]
        primary.acquire_many("sw", [lid] * 32, keys, [1] * 32)
        primary.acquire_many("tb", [lid_tb] * 32, keys,
                             [rng.choice([1, 2]) for _ in range(32)])
        repl.ship_now()

    fp_p = engine_state_fingerprint(primary.engine)
    fp_s = engine_state_fingerprint(standby.engine)
    np.testing.assert_array_equal(fp_p["sw"], fp_s["sw"])
    np.testing.assert_array_equal(fp_p["tb"], fp_s["tb"])
    assert receiver.last_epoch == log.epoch > 0
    primary.close()
    standby.close()


def test_stream_paths_replicate():
    """Relay/digest/flat stream traffic (uwords marking) converges too."""
    clock, primary, standby = make_pair(num_slots=1024)
    lid = primary.register_limiter("tb", RateLimitConfig(
        max_permits=100, window_ms=1000, refill_rate=50.0))
    log = ReplicationLog(primary)
    receiver = StandbyReceiver(standby)
    repl = Replicator(log, InProcessSink(receiver))

    rng = np.random.default_rng(3)
    for _ in range(3):
        clock["t"] += 137
        keys = rng.integers(0, 500, size=4096)
        primary.acquire_stream_ids("tb", lid, keys)
        repl.ship_now()
    fp_p = engine_state_fingerprint(primary.engine)
    fp_s = engine_state_fingerprint(standby.engine)
    np.testing.assert_array_equal(fp_p["tb"], fp_s["tb"])
    primary.close()
    standby.close()


# ---------------------------------------------------------------------------
# Failover drill (fast deterministic; verify.sh runs this one)
# ---------------------------------------------------------------------------

def test_failover_drill_fast():
    from ratelimiter_tpu.storage.chaos import failover_drill

    registry = MeterRegistry()
    report = failover_drill(num_slots=1024, n_keys=32, batch=24,
                            registry=registry)
    assert report["mismatches"] == 0
    assert report["decisions"] > 200
    assert report["loss_wave_decisions"] > 0     # the kill WAS mid-stream
    assert max(report["lag_ms_samples"]) > 0     # lag observed during soak
    meters = registry.scrape()
    assert meters["ratelimiter.replication.failovers"] == 1.0
    assert meters["ratelimiter.replication.epoch_gap"] == 0.0
    assert meters["ratelimiter.replication.frames"] >= report["frames"]


@pytest.mark.slow
def test_failover_soak_slow():
    """Bigger drill with the ASYNC replicator thread running mid-soak
    (the production shape) — the kill still lands between the last
    replicated epoch and unshipped traffic."""
    registry = MeterRegistry()
    from ratelimiter_tpu.storage.chaos import failover_drill

    report = failover_drill(num_slots=4096, n_keys=256, waves=12,
                            kill_after_wave=10, post_waves=6, batch=128,
                            registry=registry, background_interval_ms=20.0)
    assert report["mismatches"] == 0
    assert report["decisions"] > 4000
    meters = registry.scrape()
    assert meters["ratelimiter.replication.failovers"] == 1.0
    assert meters["ratelimiter.replication.epoch_gap"] == 0.0


# ---------------------------------------------------------------------------
# Checkpoint x replication interplay
# ---------------------------------------------------------------------------

def test_checkpoint_then_catchup_equals_continuous(tmp_path):
    """Restore-from-checkpoint + catch-up-from-log must equal continuous
    replication — and both must serve decisions bit-identical to the
    oracle after promotion."""
    clock = {"t": T0}
    primary = TpuBatchedStorage(num_slots=512, clock_ms=lambda: clock["t"])
    cont = TpuBatchedStorage(num_slots=512, clock_ms=lambda: clock["t"])
    cfg = RateLimitConfig(max_permits=15, window_ms=2000,
                          enable_local_cache=False)
    lid = primary.register_limiter("sw", cfg)
    log = ReplicationLog(primary)
    receiver = StandbyReceiver(cont)
    archive = FrameArchive()
    repl = Replicator(log, TeeSink(InProcessSink(receiver), archive))
    oracle = SlidingWindowOracle(cfg)

    rng = random.Random(7)

    def wave():
        clock["t"] += rng.choice([1, 999, 2000])
        keys = [f"u{rng.randrange(20)}" for _ in range(24)]
        out = primary.acquire_many("sw", [lid] * 24, keys, [1] * 24)
        for j, k in enumerate(keys):
            d = oracle.try_acquire(k, 1, clock["t"])
            assert bool(out["allowed"][j]) == d.allowed

    for _ in range(3):
        wave()
        repl.ship_now()
    ckpt_epoch = log.epoch
    primary.save_checkpoint(str(tmp_path / "ckpt"))

    for _ in range(3):
        wave()
        repl.ship_now()

    # Late joiner: checkpoint restore, then replay the log's frames
    # cut after the checkpoint epoch.
    late = TpuBatchedStorage(num_slots=512, clock_ms=lambda: clock["t"])
    late.register_limiter("sw", cfg)  # same registration order as primary
    late.restore_checkpoint(str(tmp_path / "ckpt"))
    late_rx = StandbyReceiver(late, start_epoch=ckpt_epoch)
    for data in archive.frames:
        if decode_frame(data)["epoch"] > ckpt_epoch:
            late_rx.apply_bytes(data)
    assert late_rx.consistent and late_rx.last_epoch == log.epoch

    fp_cont = engine_state_fingerprint(cont.engine)
    fp_late = engine_state_fingerprint(late.engine)
    np.testing.assert_array_equal(fp_cont["sw"], fp_late["sw"])
    np.testing.assert_array_equal(fp_cont["tb"], fp_late["tb"])

    # Promote the late joiner and keep matching the oracle exactly.
    primary.close()
    promoted = late_rx.promote()
    for _ in range(3):
        clock["t"] += rng.choice([1, 999, 2000])
        keys = [f"u{rng.randrange(20)}" for _ in range(24)]
        out = promoted.acquire_many("sw", [lid] * 24, keys, [1] * 24)
        for j, k in enumerate(keys):
            d = oracle.try_acquire(k, 1, clock["t"])
            assert bool(out["allowed"][j]) == d.allowed
            assert int(out["observed"][j]) == d.observed
    promoted.close()
    cont.close()


# ---------------------------------------------------------------------------
# Gap detection & recovery
# ---------------------------------------------------------------------------

def test_epoch_gap_refuses_promotion_until_full_frame():
    registry = MeterRegistry()
    clock, primary, standby = make_pair()
    lid = primary.register_limiter("tb", RateLimitConfig(
        max_permits=40, window_ms=1000, refill_rate=10.0))
    log = ReplicationLog(primary)
    receiver = StandbyReceiver(standby, registry=registry)

    def traffic():
        clock["t"] += 77
        primary.acquire_many("tb", [lid] * 8,
                             [f"g{i}" for i in range(8)], [1] * 8)

    traffic()
    for f in log.cut():                       # epoch 1 (full bootstrap)
        receiver.apply(f)
    assert receiver.consistent
    traffic()
    dropped = log.cut()                       # epoch 2: lost in transit
    assert dropped
    traffic()
    for f in log.cut():                       # epoch 3 arrives -> gap
        receiver.apply(f)
    assert not receiver.consistent
    assert registry.scrape()["ratelimiter.replication.epoch_gap"] == 1.0
    with pytest.raises(ReplicationStateError):
        receiver.promote()

    # Recovery: a full frame re-baselines the stream.
    log.request_full()
    for f in log.cut():
        receiver.apply(f)
    assert receiver.consistent
    fp_p = engine_state_fingerprint(primary.engine)
    fp_s = engine_state_fingerprint(standby.engine)
    np.testing.assert_array_equal(fp_p["tb"], fp_s["tb"])
    receiver.promote()
    primary.close()
    standby.close()


def test_ship_failure_remarks_and_requests_full():
    class FlakySink:
        def __init__(self):
            self.fail = False
            self.delivered = []

        def send(self, data):
            if self.fail:
                raise ConnectionError("standby unreachable")
            self.delivered.append(data)

    clock, primary, standby = make_pair()
    lid = primary.register_limiter("sw", RateLimitConfig(
        max_permits=9, window_ms=1000, enable_local_cache=False))
    log = ReplicationLog(primary)
    sink = FlakySink()
    repl = Replicator(log, sink)

    clock["t"] += 5
    primary.acquire_many("sw", [lid] * 4, list("abcd"), [1] * 4)
    repl.ship_now()
    n_ok = len(sink.delivered)

    clock["t"] += 5
    primary.acquire_many("sw", [lid] * 4, list("efgh"), [1] * 4)
    sink.fail = True
    with pytest.raises(ConnectionError):
        repl.ship_now()
    assert repl.errors == 1
    assert log.pending() > 0  # failed delta re-marked

    sink.fail = False
    repl.ship_now()  # full recovery frame
    assert len(sink.delivered) > n_ok
    receiver = StandbyReceiver(standby)
    for data in sink.delivered:
        receiver.apply_bytes(data)
    # The post-failure full frame re-baselines despite the gap.
    assert receiver.consistent
    fp_p = engine_state_fingerprint(primary.engine)
    fp_s = engine_state_fingerprint(standby.engine)
    np.testing.assert_array_equal(fp_p["sw"], fp_s["sw"])
    primary.close()
    standby.close()


def test_geometry_mismatch_rejected():
    clock, primary, _ = make_pair(num_slots=512)
    other = TpuBatchedStorage(num_slots=256, clock_ms=lambda: clock["t"])
    lid = primary.register_limiter("sw", RateLimitConfig(
        max_permits=5, window_ms=1000, enable_local_cache=False))
    clock["t"] += 1
    primary.acquire("sw", lid, "x", 1)
    log = ReplicationLog(primary)
    receiver = StandbyReceiver(other)
    with pytest.raises(ValueError, match="geometry"):
        for f in log.cut():
            receiver.apply(f)
    primary.close()
    other.close()


# ---------------------------------------------------------------------------
# TCP transport (sidecar-style framing)
# ---------------------------------------------------------------------------

def test_tcp_transport_failover_vs_oracle():
    clock, primary, standby = make_pair()
    cfg = RateLimitConfig(max_permits=12, window_ms=1500,
                          enable_local_cache=False)
    lid = primary.register_limiter("sw", cfg)
    log = ReplicationLog(primary)
    receiver = StandbyReceiver(standby)
    server = ReplicationServer(receiver, host="127.0.0.1").start()
    sink = SocketSink("127.0.0.1", server.port)
    repl = Replicator(log, sink)
    oracle = SlidingWindowOracle(cfg)
    rng = random.Random(11)

    try:
        for _ in range(4):
            clock["t"] += rng.choice([3, 700, 1500])
            keys = [f"t{rng.randrange(16)}" for _ in range(20)]
            out = primary.acquire_many("sw", [lid] * 20, keys, [1] * 20)
            for j, k in enumerate(keys):
                d = oracle.try_acquire(k, 1, clock["t"])
                assert bool(out["allowed"][j]) == d.allowed
            repl.ship_now()
        snap = copy.deepcopy(oracle)
        # loss wave, then crash
        clock["t"] += 3
        primary.acquire_many("sw", [lid] * 4, ["t0", "t1", "t2", "t3"],
                             [1] * 4)
    finally:
        primary.close()
        sink.close()
        server.stop()

    oracle = snap
    promoted = receiver.promote()
    for _ in range(3):
        clock["t"] += rng.choice([3, 700, 1500])
        keys = [f"t{rng.randrange(16)}" for _ in range(20)]
        out = promoted.acquire_many("sw", [lid] * 20, keys, [1] * 20)
        for j, k in enumerate(keys):
            d = oracle.try_acquire(k, 1, clock["t"])
            assert bool(out["allowed"][j]) == d.allowed
            assert int(out["observed"][j]) == d.observed
    promoted.close()


def test_socket_sink_reconnects_and_rebaselines_after_link_drop():
    """Drop-the-link chaos: the standby dies mid-stream and a NEW (empty)
    standby comes up on the same port.  The sink must reconnect with its
    capped backoff instead of erroring out of the replication thread
    (``Replicator.errors`` stays 0), and the replicator must re-baseline
    the restarted standby with a full frame on the next cycle."""
    clock, primary, standby = make_pair()
    cfg = RateLimitConfig(max_permits=12, window_ms=1500,
                          enable_local_cache=False)
    lid = primary.register_limiter("sw", cfg)
    log = ReplicationLog(primary)
    receiver1 = StandbyReceiver(standby)
    server1 = ReplicationServer(receiver1, host="127.0.0.1").start()
    sink = SocketSink("127.0.0.1", server1.port, max_retries=8,
                      backoff_ms=5.0, backoff_cap_ms=50.0)
    repl = Replicator(log, sink)
    rng = random.Random(5)
    standby2 = None
    server2 = None

    def wave():
        clock["t"] += rng.choice([3, 700, 1500])
        keys = [f"t{rng.randrange(16)}" for _ in range(20)]
        primary.acquire_many("sw", [lid] * 20, keys, [1] * 20)

    import threading
    import time as time_mod

    boot = {}
    # The restarted standby's storage is built up front (jax array init
    # can take seconds on CPU); only the port BIND is delayed, so the
    # backoff loop's worst case stays well inside max_retries.
    standby2 = TpuBatchedStorage(num_slots=512, clock_ms=lambda: clock["t"])

    def restart_standby_later(port, delay_s):
        time_mod.sleep(delay_s)
        boot["receiver"] = StandbyReceiver(standby2)
        boot["server"] = ReplicationServer(
            boot["receiver"], host="127.0.0.1", port=port).start()

    try:
        wave()
        assert repl.ship_now() > 0
        assert receiver1.consistent

        # Drop the link: cut the established connection and kill the
        # standby process (listener + storage).
        sink._drop()
        server1.stop()
        standby.close()
        # A restarted, EMPTY standby binds the same port — but only
        # AFTER the sink has started retrying, so the capped-backoff
        # loop is what carries the cycle through the outage.
        t = threading.Thread(target=restart_standby_later,
                             args=(server1.port, 0.1), daemon=True)
        t.start()

        # The next cycle hits connection-refused, backs off, reconnects
        # once the standby is back, and delivers — no error escapes the
        # ship cycle.
        wave()
        assert repl.ship_now() > 0
        t.join(timeout=5.0)
        server2 = boot["server"]
        receiver2 = boot["receiver"]
        assert sink.reconnects >= 1
        assert repl.errors == 0
        # The delta landed past the restarted standby's epoch 0: gap —
        # refuses promotion...
        assert not receiver2.consistent
        with pytest.raises(ReplicationStateError):
            receiver2.promote()

        # ...until the next cycle re-baselines with a full frame
        # (triggered by the consumed reconnect flag).
        wave()
        assert repl.ship_now() > 0
        assert receiver2.consistent
        assert repl.errors == 0
    finally:
        primary.close()
        sink.close()
        if server2 is not None:
            server2.stop()
        if standby2 is not None:
            standby2.close()


# ---------------------------------------------------------------------------
# Service wiring & metrics exposure
# ---------------------------------------------------------------------------

def test_wiring_replication_disabled_by_default():
    from ratelimiter_tpu.service.props import AppProperties
    from ratelimiter_tpu.service.wiring import _maybe_replication

    props = AppProperties({"storage.backend": "memory"})
    clock = {"t": T0}
    storage = TpuBatchedStorage(num_slots=256, clock_ms=lambda: clock["t"])
    assert _maybe_replication(storage, props, MeterRegistry()) is None
    assert storage.engine.journal is None  # zero hot-path overhead when off
    storage.close()


def test_wiring_primary_standby_roundtrip_over_tcp():
    from ratelimiter_tpu.service.props import AppProperties
    from ratelimiter_tpu.service.wiring import _maybe_replication

    clock = {"t": T0}
    registry = MeterRegistry()
    standby = TpuBatchedStorage(num_slots=256, clock_ms=lambda: clock["t"])
    h_standby = _maybe_replication(standby, AppProperties({
        "replication.enabled": "true", "replication.role": "standby",
        "replication.listen_port": "0"}), registry)
    assert h_standby is not None and h_standby.role == "standby"
    port = h_standby.server.port

    primary = TpuBatchedStorage(num_slots=256, clock_ms=lambda: clock["t"])
    h_primary = _maybe_replication(primary, AppProperties({
        "replication.enabled": "true", "replication.role": "primary",
        "replication.target": f"127.0.0.1:{port}",
        "replication.interval_ms": "10000"}), registry)
    assert h_primary is not None and h_primary.role == "primary"

    lid = primary.register_limiter("tb", RateLimitConfig(
        max_permits=25, window_ms=1000, refill_rate=10.0))
    clock["t"] += 9
    primary.acquire_many("tb", [lid] * 6, [f"w{i}" for i in range(6)],
                         [1] * 6)
    h_primary.replicator.ship_now()
    assert h_standby.receiver.last_epoch == 1
    status = h_primary.status()
    assert status["epoch"] == 1 and status["frames_shipped"] >= 1
    meters = registry.scrape()
    assert meters["ratelimiter.replication.frames"] >= 1
    assert meters["ratelimiter.replication.bytes"] > 0
    assert "ratelimiter.replication.lag_ms" in meters

    fp_p = engine_state_fingerprint(primary.engine)
    fp_s = engine_state_fingerprint(standby.engine)
    np.testing.assert_array_equal(fp_p["tb"], fp_s["tb"])

    h_primary.close()
    h_standby.close()
    primary.close()
    standby.close()


def test_gauge_meter():
    registry = MeterRegistry()
    g = registry.gauge("x.lag", "test gauge")
    g.set(12.5)
    assert registry.scrape()["x.lag"] == 12.5
    assert registry.gauge("x.lag") is g
    with pytest.raises(TypeError):
        registry.counter("x.lag")


# ---------------------------------------------------------------------------
# Link liveness: ack deadline + heartbeat (standby gone vs standby slow)
# ---------------------------------------------------------------------------

def test_heartbeat_acks_and_keeps_link_up():
    clock, primary, standby = make_pair(num_slots=256)
    receiver = StandbyReceiver(standby)
    server = ReplicationServer(receiver, host="127.0.0.1").start()
    sink = SocketSink("127.0.0.1", server.port, ack_timeout=1.0)
    try:
        assert sink.link_state() == "unknown"   # no contact yet
        assert sink.heartbeat() is True
        assert sink.link_state() == "up"
        # Heartbeats apply NOTHING to the standby.
        assert receiver.frames_applied == 0
    finally:
        sink.close()
        server.stop()
        primary.close()
        standby.close()


def test_silently_dead_standby_marks_link_dead():
    """A partition (bytes dropped, no RST) must fail the heartbeat at
    the ACK DEADLINE — not the 10 s connect timeout — and enough
    consecutive failures mark the link DEAD: the 'standby gone' verdict
    the orchestrator needs, as opposed to 'standby slow'."""
    import time as time_mod

    from ratelimiter_tpu.storage.chaos import FaultInjectingProxy

    clock, primary, standby = make_pair(num_slots=256)
    receiver = StandbyReceiver(standby)
    server = ReplicationServer(receiver, host="127.0.0.1").start()
    proxy = FaultInjectingProxy(server.port).start()
    sink = SocketSink("127.0.0.1", proxy.port, ack_timeout=0.25,
                      dead_after=2, max_retries=0)
    try:
        assert sink.heartbeat() is True
        assert sink.link_state() == "up"
        proxy.partition()                      # silence, no RST
        t0 = time_mod.monotonic()
        assert sink.heartbeat() is False       # 1st failure: not dead yet
        assert time_mod.monotonic() - t0 < 2.0  # the ACK deadline fired
        assert sink.link_state() == "up"
        assert sink.heartbeat() is False       # 2nd consecutive: DEAD
        assert sink.link_state() == "dead"
        # Healing restores UP on the next successful ack.
        proxy.heal()
        deadline = time_mod.monotonic() + 5.0
        while not sink.heartbeat() and time_mod.monotonic() < deadline:
            pass
        assert sink.link_state() == "up"
    finally:
        sink.close()
        proxy.stop()
        server.stop()
        primary.close()
        standby.close()


def test_replicator_idle_cycles_heartbeat_and_flag_dead_link():
    """With NO deltas flowing, the replicator's idle cycles must still
    detect a silently-dead standby: heartbeat -> link DEAD -> gauge 0 +
    flight event (the old behavior saw nothing until the next delta)."""
    import time as time_mod

    from ratelimiter_tpu.observability import flight_recorder
    from ratelimiter_tpu.storage.chaos import FaultInjectingProxy

    frec = flight_recorder()
    fmark = frec.mark()
    registry = MeterRegistry()
    clock, primary, standby = make_pair(num_slots=256)
    lid = primary.register_limiter("tb", RateLimitConfig(
        max_permits=20, window_ms=1000, refill_rate=10.0))
    receiver = StandbyReceiver(standby)
    server = ReplicationServer(receiver, host="127.0.0.1").start()
    proxy = FaultInjectingProxy(server.port).start()
    sink = SocketSink("127.0.0.1", proxy.port, ack_timeout=0.2,
                      dead_after=2, max_retries=0)
    repl = Replicator(ReplicationLog(primary), sink, interval_ms=30.0,
                      registry=registry).start()
    try:
        clock["t"] += 5
        primary.acquire_many("tb", [lid] * 2, ["a", "b"], [1, 1])
        deadline = time_mod.monotonic() + 10.0
        while receiver.last_epoch < 1 and time_mod.monotonic() < deadline:
            time_mod.sleep(0.02)
        assert receiver.last_epoch >= 1
        assert registry.scrape()["ratelimiter.replication.link_up"] == 1.0
        proxy.partition()                       # standby silently gone
        deadline = time_mod.monotonic() + 15.0
        while sink.link_state() != "dead" \
                and time_mod.monotonic() < deadline:
            time_mod.sleep(0.05)
        assert sink.link_state() == "dead", (
            "idle heartbeats never detected the partition")
        deadline = time_mod.monotonic() + 5.0
        while registry.scrape()["ratelimiter.replication.link_up"] != 0.0 \
                and time_mod.monotonic() < deadline:
            time_mod.sleep(0.05)
        assert registry.scrape()["ratelimiter.replication.link_up"] == 0.0
        assert any(e["kind"] == "replication.link_dead"
                   for e in frec.events(since=fmark))
    finally:
        repl.stop()
        sink.close()
        proxy.stop()
        server.stop()
        primary.close()
        standby.close()
