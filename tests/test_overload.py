"""Admission control & overload shedding (engine/batcher.py + service tier).

The overload contract: a request is answered — allowed, denied, shed with
a typed retryable error, or failed by shutdown — but NEVER stranded on
``Future.result()``.  Covers the bounded pending queue, queue-deadline
budgets, the flusher watchdog, ``close()`` stranding, the overload chaos
drill, and the service tier's 429-with-Retry-After / health-state surface.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from ratelimiter_tpu.engine.batcher import MicroBatcher
from ratelimiter_tpu.engine.errors import OverloadedError, ShutdownError


def _sync_batcher(**kw):
    """Batcher over an instant synchronous dispatch (no drain fn)."""
    def dispatch(slots, lids, permits):
        return {"allowed": [True] * len(slots)}

    kw.setdefault("max_delay_ms", 10_000.0)  # accumulate unless told not to
    return MicroBatcher(dispatch={"sw": dispatch},
                        clear={"sw": lambda slots: None}, **kw)


def test_submit_sheds_at_max_pending_with_retry_after():
    b = _sync_batcher(max_pending=2)
    try:
        b.submit("sw", 0, 0, 1)
        b.submit("sw", 1, 0, 1)
        with pytest.raises(OverloadedError) as exc_info:
            b.submit("sw", 2, 0, 1)
        assert exc_info.value.reason == "queue_full"
        assert exc_info.value.retry_after_ms > 0
        assert b.shed_total == 1
        assert b.queue_depth() == 2
    finally:
        b.close()


def test_zero_max_pending_disables_the_bound():
    b = _sync_batcher(max_pending=0)
    try:
        futs = [b.submit("sw", i, 0, 1) for i in range(64)]
        b.flush()
        assert all(f.result(timeout=5)["allowed"] for f in futs)
        assert b.shed_total == 0
    finally:
        b.close()


def test_queue_deadline_expires_undispatched_requests():
    """A request the flusher cannot dispatch in time (here: a dispatch
    wedged holding the lock) is failed by the watchdog with a typed
    deadline error — not left waiting."""
    release = threading.Event()

    def slow_dispatch(slots, lids, permits):
        release.wait(timeout=10)
        return {"allowed": [True] * len(slots)}

    b = MicroBatcher(dispatch={"sw": slow_dispatch},
                     clear={"sw": lambda slots: None},
                     max_delay_ms=0.0, deadline_ms=60.0)
    try:
        first = b.submit("sw", 0, 0, 1)   # wedges inside dispatch
        time.sleep(0.02)                   # let the flusher take it
        second = b.submit("sw", 1, 0, 1)  # queued behind the wedge
        with pytest.raises(OverloadedError) as exc_info:
            second.result(timeout=5)
        assert exc_info.value.reason == "deadline"
        assert b.deadline_total == 1
        release.set()
        assert first.result(timeout=5)["allowed"]  # dispatched: never shed
    finally:
        release.set()
        b.close()


def test_per_request_deadline_overrides_batcher_default():
    release = threading.Event()

    def slow_dispatch(slots, lids, permits):
        release.wait(timeout=10)
        return {"allowed": [True] * len(slots)}

    b = MicroBatcher(dispatch={"sw": slow_dispatch},
                     clear={"sw": lambda slots: None},
                     max_delay_ms=0.0, deadline_ms=0.0)  # no default budget
    try:
        b.submit("sw", 0, 0, 1)
        time.sleep(0.02)
        tight = b.submit("sw", 1, 0, 1, deadline_ms=50.0)
        with pytest.raises(OverloadedError):
            tight.result(timeout=5)
    finally:
        release.set()
        b.close()


def test_dead_flusher_fails_queue_and_refuses_submits():
    b = _sync_batcher()
    try:
        queued = b.submit("sw", 0, 0, 1)
        b.max_delay_s = None  # poison: the flusher loop dies on compare
        with b._cv:
            b._cv.notify_all()
        with pytest.raises(OverloadedError) as exc_info:
            queued.result(timeout=5)
        assert exc_info.value.reason == "flusher_dead"
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:  # watchdog flags the corpse
            try:
                b.submit("sw", 1, 0, 1)
            except OverloadedError as exc:
                assert exc.reason == "flusher_dead"
                break
            time.sleep(0.01)
        else:
            pytest.fail("submit kept queuing onto a dead flusher")
    finally:
        b.max_delay_s = 10.0
        b.close()


def test_close_fails_pending_futures_with_shutdown_error():
    """Satellite: close() must fail still-pending futures instead of
    leaving callers blocked on Future.result() — even when a dispatch is
    wedged and never returns."""
    stuck = threading.Event()

    def hung_dispatch(slots, lids, permits):
        stuck.wait(timeout=30)
        return {"allowed": [True] * len(slots)}

    b = MicroBatcher(dispatch={"sw": hung_dispatch},
                     clear={"sw": lambda slots: None}, max_delay_ms=0.0)
    dispatched = b.submit("sw", 0, 0, 1)  # wedges inside dispatch
    time.sleep(0.02)
    queued = b.submit("sw", 1, 0, 1)      # never dispatched
    t0 = time.monotonic()
    b.close(timeout=0.3)
    assert time.monotonic() - t0 < 5  # bounded, not hung
    for fut in (dispatched, queued):
        with pytest.raises(ShutdownError):
            fut.result(timeout=1)
    stuck.set()


def test_submit_after_close_raises_shutdown_error():
    b = _sync_batcher()
    b.close()
    with pytest.raises(ShutdownError):
        b.submit("sw", 0, 0, 1)


def test_overload_drill_fast():
    """Chaos drill: queue depth bounded, overload shed not queued, p99 of
    admitted requests within the deadline budget at 2x offered load."""
    from ratelimiter_tpu.storage.chaos import overload_drill

    # 0.8x as the under-capacity point: at exactly 1x the synthetic
    # device's sleep() overhead makes Python effectively over-subscribed.
    report = overload_drill(load_multipliers=(0.8, 2.0), bursts=25)
    assert report["runs"][0]["goodput_frac"] > 0.9     # under capacity: no shed
    two_x = report["runs"][1]
    assert two_x["shed_frac"] > 0.2                    # 2x: overload shed
    assert two_x["max_depth_seen"] <= 256              # drill default bound


@pytest.mark.slow
def test_overload_soak_slow():
    from ratelimiter_tpu.storage.chaos import overload_drill

    report = overload_drill(load_multipliers=(1.0, 2.0, 4.0), bursts=120)
    four_x = report["runs"][-1]
    assert four_x["shed_frac"] > 0.4
    assert four_x["max_depth_seen"] <= 256             # drill default bound


# ---------------------------------------------------------------------------
# Service tier: 429-with-Retry-After vs 503, health state machine
# ---------------------------------------------------------------------------

@pytest.fixture()
def ctx_server():
    from ratelimiter_tpu.service.props import AppProperties
    from ratelimiter_tpu.service.app import make_server
    from ratelimiter_tpu.service.wiring import build_app

    props = AppProperties({
        "storage.backend": "memory",
        "chaos.failure_rate": "0.000001",  # arms chaos so the stack is full
        "warmup.enabled": "false",
        "server.port": "0",
    })
    ctx = build_app(props)
    ctx.storage._inner._inner.failure_rate = 0.0  # deterministic again
    srv = make_server(ctx, port=0)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    yield ctx, srv
    srv.shutdown()
    thread.join(timeout=5)
    ctx.close()


def _get(srv, path, headers=None):
    port = srv.server_address[1]
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read()), dict(resp.getheaders())
    except urllib.error.HTTPError as err:
        return err.code, json.loads(err.read() or b"{}"), dict(err.headers)


def test_shed_request_gets_429_with_retry_after(ctx_server):
    ctx, srv = ctx_server

    def shed(key, permits=1):
        raise OverloadedError("queue full", reason="queue_full",
                              retry_after_ms=2500.0)

    ctx.limiters["api"].try_acquire = shed
    status, data, headers = _get(srv, "/api/data",
                                 headers={"X-User-ID": "alice"})
    assert status == 429
    assert data["error"] == "Overloaded"
    assert data["reason"] == "queue_full"
    assert int(headers["Retry-After"]) == 3  # ceil(2500 ms)
    meters = ctx.registry.scrape()
    assert meters["ratelimiter.overload.rejected"] == 1


def test_shutdown_gets_503(ctx_server):
    ctx, srv = ctx_server

    def closed(key, permits=1):
        raise ShutdownError("batcher closed")

    ctx.limiters["api"].try_acquire = closed
    status, data, headers = _get(srv, "/api/data")
    assert status == 503
    assert "Retry-After" in headers


def test_health_up_then_degraded_then_down(ctx_server):
    ctx, srv = ctx_server
    status, data, _ = _get(srv, "/actuator/health")
    assert (status, data["status"]) == (200, "UP")

    ctx.breaker.trip()  # breaker open + fail_open: still serving -> DEGRADED
    status, data, _ = _get(srv, "/actuator/health")
    assert (status, data["status"]) == (200, "DEGRADED")
    assert data["breaker"]["state"] == "open"

    ctx.fail_open = False  # open breaker, no fallback, no fail-open -> DOWN
    status, data, _ = _get(srv, "/actuator/health")
    assert (status, data["status"]) == (503, "DOWN")


def test_health_shedding_window(ctx_server):
    ctx, srv = ctx_server

    class _StubBatcher:
        max_pending = 8
        shed_total = 3
        deadline_total = 0
        last_shed_s = time.monotonic()

        def queue_depth(self):
            return 8

    ctx.storage._batcher = _StubBatcher()
    status, data, _ = _get(srv, "/actuator/health")
    assert (status, data["status"]) == (200, "SHEDDING")
    assert data["overload"]["queue_depth"] == 8
    # Outside the shed window the state decays back to UP.
    ctx.storage._batcher.last_shed_s = time.monotonic() - 3600.0
    status, data, _ = _get(srv, "/actuator/health")
    assert (status, data["status"]) == (200, "UP")
