"""Metrics-name drift check: every meter the full wiring registers must
appear in ARCHITECTURE.md's §13 metric catalog — new metrics without
docs fail CI."""

import os
import re
import threading

import pytest

_ARCH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "ARCHITECTURE.md")


def _documented_names() -> set:
    with open(_ARCH, encoding="utf-8") as fh:
        text = fh.read()
    names = set(re.findall(r"ratelimiter\.[a-z0-9_.]+", text))
    # Table rows compress families as `ratelimiter.stream.pack` /
    # `.index` / ... — expand the short suffixes against their prefix.
    for prefix, suffixes in re.findall(
            r"`(ratelimiter\.[a-z0-9_.]+)`((?:\s*/\s*`\.[a-z0-9_]+`)+)",
            text):
        base = prefix.rsplit(".", 1)[0]
        for suffix in re.findall(r"`\.([a-z0-9_]+)`", suffixes):
            names.add(f"{base}.{suffix}")
    return names


def test_all_registered_meters_are_documented():
    """Boot the full wiring (tpu backend, breaker, degraded, sidecar),
    drive one request through each surface so lazily-created meters
    exist, then assert every registered name is in the §13 table."""
    from ratelimiter_tpu.service.app import make_server
    from ratelimiter_tpu.service.props import AppProperties
    from ratelimiter_tpu.service.wiring import build_app

    props = AppProperties({
        "storage.backend": "tpu",
        "storage.num_slots": "4096",
        "batcher.max_delay_ms": "0.2",
        "parallel.shard": "off",
        "warmup.enabled": "false",
        "link.probe.enabled": "false",
        "ratelimiter.sidecar.enabled": "true",
        "ratelimiter.sidecar.port": "0",
        "ratelimiter.lease.enabled": "true",
        "ratelimiter.edge.enabled": "true",
        "ratelimiter.control.enabled": "true",
        "ratelimiter.control.interval_ms": "60000",
        "ratelimiter.fleet.enabled": "true",
        "ratelimiter.fleet.probe_interval_ms": "60000",
        "ratelimiter.obs.trace_sample": "4",
    })
    ctx = build_app(props)
    srv = make_server(ctx, port=0)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    try:
        import http.client

        conn = http.client.HTTPConnection(
            "127.0.0.1", srv.server_address[1], timeout=10)
        conn.request("GET", "/api/data", headers={"X-User-ID": "drift"})
        conn.getresponse().read()
        conn.request("GET", "/actuator/health")
        conn.getresponse().read()
        conn.close()

        registered = set(ctx.registry.meters())
        assert registered, "wiring registered no meters?"
        documented = _documented_names()
        undocumented = sorted(registered - documented)
        assert not undocumented, (
            "meters registered but missing from ARCHITECTURE.md §13's "
            f"catalog: {undocumented} — document them or rename")
    finally:
        srv.shutdown()
        ctx.close()


def test_catalog_regex_expands_families():
    """Guard the expansion helper itself: compressed table rows must
    yield their full names."""
    names = _documented_names()
    for expected in ("ratelimiter.stream.pack", "ratelimiter.stream.fetch",
                     "ratelimiter.sidecar.pipeline_shed",
                     "ratelimiter.replication.applied_epoch",
                     "ratelimiter.requests.allowed",
                     "ratelimiter.lease.granted",
                     "ratelimiter.lease.local_decisions",
                     "ratelimiter.lease.over_admission",
                     "ratelimiter.decisions.allowed",
                     "ratelimiter.decisions.denied",
                     "ratelimiter.decisions.shed",
                     "ratelimiter.decisions.lease_local",
                     "ratelimiter.telemetry.reports",
                     "ratelimiter.telemetry.rejected",
                     "ratelimiter.telemetry.staleness_ms",
                     "ratelimiter.telemetry.local_latency",
                     "ratelimiter.tenant.admitted",
                     "ratelimiter.fleet.nodes",
                     "ratelimiter.fleet.respawns",
                     "ratelimiter.fleet.reseeds",
                     "ratelimiter.fleet.upgrade_steps",
                     "ratelimiter.control.leader",
                     "ratelimiter.control.elections",
                     "ratelimiter.control.stale_rejected",
                     "ratelimiter.control.converge_ms"):
        assert expected in names, expected
