"""Load-time property validation (service/props.py).

Satellite contract: malformed ints/floats/bools for known keys fall back
to their defaults with a warning NAMING the key, and unknown file keys /
``RATELIMITER_*`` env overrides warn instead of passing silently.
"""

import logging

import pytest

from ratelimiter_tpu.service.props import AppProperties


@pytest.fixture(autouse=True)
def _capture_props_log(caplog):
    # setup_logging (run by any earlier service test) turns off propagation
    # on the package root; caplog's handler sits on the root logger.
    from ratelimiter_tpu.utils.logging import ROOT

    logger = logging.getLogger(ROOT)
    was = logger.propagate
    logger.propagate = True
    caplog.set_level(logging.WARNING, logger=f"{ROOT}.service.props")
    yield caplog
    logger.propagate = was


def test_malformed_int_falls_back_to_default(caplog):
    props = AppProperties({"batcher.max_batch": "81q2"})
    assert props.get_int("batcher.max_batch", -1) == 8192  # the default
    assert any("batcher.max_batch" in rec.message for rec in caplog.records)


def test_malformed_float_falls_back_to_default(caplog):
    props = AppProperties({"breaker.open_ms": "five seconds"})
    assert props.get_float("breaker.open_ms", -1.0) == 5000.0
    assert any("breaker.open_ms" in rec.message for rec in caplog.records)


def test_malformed_bool_falls_back_to_default(caplog):
    props = AppProperties({"breaker.enabled": "maybe"})
    assert props.get_bool("breaker.enabled") is True
    assert any("breaker.enabled" in rec.message for rec in caplog.records)


def test_wellformed_values_pass_silently(caplog):
    props = AppProperties({
        "batcher.max_batch": "1024",
        "breaker.open_ms": "250.5",
        "breaker.enabled": "off",
        "ratelimiter.overload.max_pending": "128",
    })
    assert props.get_int("batcher.max_batch") == 1024
    assert props.get_float("breaker.open_ms") == 250.5
    assert props.get_bool("breaker.enabled") is False
    assert props.get_int("ratelimiter.overload.max_pending") == 128
    assert not caplog.records


def test_unknown_file_key_warns_but_is_kept(caplog):
    props = AppProperties({"ratelimiter.overlod.max_pending": "10"})  # typo
    assert any("ratelimiter.overlod.max_pending" in rec.message
               for rec in caplog.records)
    assert props.get("ratelimiter.overlod.max_pending") == "10"


def test_env_override_applies_and_unknown_env_warns(
        caplog, monkeypatch, tmp_path):
    monkeypatch.setenv("RATELIMITER_BREAKER_FAILURE_THRESHOLD", "3")
    monkeypatch.setenv("RATELIMITER_BRAKER_OPEN_MS", "100")  # typo
    props = AppProperties.load(str(tmp_path / "missing.properties"))
    assert props.get_int("breaker.failure_threshold") == 3
    assert any("RATELIMITER_BRAKER_OPEN_MS" in rec.message
               for rec in caplog.records)


def test_env_direct_keys_do_not_warn(caplog, monkeypatch, tmp_path):
    # Env vars read directly by engine/ops modules are exempt from the
    # unknown-key scan (conftest sets RATELIMITER_RATE_PROBE already).
    monkeypatch.setenv("RATELIMITER_PALLAS", "1")
    AppProperties.load(str(tmp_path / "missing.properties"))
    assert not any("RATELIMITER_PALLAS" in rec.message
                   for rec in caplog.records)


def test_malformed_env_override_falls_back(caplog, monkeypatch, tmp_path):
    monkeypatch.setenv("RATELIMITER_SERVER_PORT", "eight-thousand")
    props = AppProperties.load(str(tmp_path / "missing.properties"))
    assert props.get_int("server.port") == 8080
    assert any("server.port" in rec.message for rec in caplog.records)
