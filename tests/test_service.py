"""HTTP service endpoint tests (C2/C3 parity) over a live ThreadingHTTPServer."""

import json
import threading
import http.client

import pytest

from ratelimiter_tpu.service.app import make_server
from ratelimiter_tpu.service.props import AppProperties
from ratelimiter_tpu.service.wiring import build_app
from ratelimiter_tpu.storage import InMemoryStorage


@pytest.fixture()
def server():
    # memory backend: fast, hermetic; the TPU backend is covered by
    # test_tpu_storage/test_sharded and the bench harness.
    props = AppProperties({"storage.backend": "memory", "server.port": "0"})
    storage = InMemoryStorage()
    ctx = build_app(props, storage=storage)
    srv = make_server(ctx, port=0)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    yield srv
    srv.shutdown()
    thread.join(timeout=5)
    ctx.close()


def req(srv, method, path, body=None, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", srv.server_address[1], timeout=10)
    payload = json.dumps(body) if body is not None else None
    conn.request(method, path, body=payload, headers=headers or {})
    resp = conn.getresponse()
    data = json.loads(resp.read() or b"{}")
    out_headers = dict(resp.getheaders())
    conn.close()
    return resp.status, data, out_headers


def test_data_endpoint_and_headers(server):
    status, data, headers = req(server, "GET", "/api/data",
                                headers={"X-User-ID": "alice"})
    assert status == 200
    assert data["message"] == "Success!"
    assert data["remaining"] == 99
    assert "timestamp" in data["data"]
    assert headers["X-RateLimit-Limit"] == "100"
    assert headers["X-RateLimit-Remaining"] == "99"


def test_data_anonymous_key(server):
    status, data, _ = req(server, "GET", "/api/data")
    assert status == 200
    assert data["remaining"] == 99


def test_login_and_429(server):
    for i in range(10):
        status, data, _ = req(server, "POST", "/api/login",
                              body={"username": "bob"})
        assert status == 200
        assert data["message"] == "Login successful"
    status, data, _ = req(server, "POST", "/api/login", body={"username": "bob"})
    assert status == 429
    assert data["error"] == "Rate limit exceeded"
    assert data["remaining"] == 0
    # Different user unaffected.
    status, _, _ = req(server, "POST", "/api/login", body={"username": "carol"})
    assert status == 200


def test_batch_endpoint(server):
    status, data, _ = req(server, "POST", "/api/batch", body={"size": 30},
                          headers={"X-User-ID": "dave"})
    assert status == 200
    assert data["items_processed"] == 30
    assert data["tokens_remaining"] == 20
    # 30 more exceeds the remaining 20 tokens -> 429.
    status, data, _ = req(server, "POST", "/api/batch", body={"size": 30},
                          headers={"X-User-ID": "dave"})
    assert status == 429
    # Missing header -> 400 (the reference's required header).
    status, _, _ = req(server, "POST", "/api/batch", body={"size": 1})
    assert status == 400


def test_health_and_actuator(server):
    status, data, _ = req(server, "GET", "/api/health")
    assert status == 200 and data["status"] == "UP"
    status, data, _ = req(server, "GET", "/actuator/health")
    assert status == 200 and data["status"] == "UP"
    req(server, "GET", "/api/data", headers={"X-User-ID": "m"})
    status, data, _ = req(server, "GET", "/actuator/metrics")
    assert status == 200
    assert data["meters"]["ratelimiter.requests.allowed"] >= 1


def test_admin_reset_both_paths(server):
    for _ in range(10):
        req(server, "POST", "/api/login", body={"username": "eve"})
    status, _, _ = req(server, "POST", "/api/login", body={"username": "eve"})
    assert status == 429
    # Actual mount point (/api/admin, DemoController.java:118) ...
    status, data, _ = req(server, "DELETE", "/api/admin/reset/eve")
    assert status == 200 and "eve" in data["message"]
    status, _, _ = req(server, "POST", "/api/login", body={"username": "eve"})
    assert status == 200
    # ... and the README-documented path (quirk Q4) also works.
    status, _, _ = req(server, "DELETE", "/admin/reset/eve")
    assert status == 200


def test_unknown_route_404(server):
    status, _, _ = req(server, "GET", "/nope")
    assert status == 404


def test_chaos_drill_fail_open_end_to_end():
    """chaos.failure_rate=1 via config wires the fault injector around the
    backend; every decision op fails and the service fail-opens at HTTP."""
    props = AppProperties({
        "storage.backend": "memory",
        "ratelimiter.fail_open": "true",
        "chaos.failure_rate": "1",
    })
    ctx = build_app(props)
    srv = make_server(ctx, port=0)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    try:
        status, data, _ = req(srv, "GET", "/api/data",
                              headers={"X-User-ID": "c"})
        assert status == 200
        assert ctx.registry.scrape()["ratelimiter.failopen.allowed"] >= 1
        assert ctx.storage.injected_failures >= 1
    finally:
        srv.shutdown()
        thread.join(timeout=5)
        ctx.close()


def test_fail_open_allows_on_storage_outage():
    props = AppProperties({"storage.backend": "memory", "ratelimiter.fail_open": "true"})
    storage = InMemoryStorage()
    ctx = build_app(props, storage=storage)
    srv = make_server(ctx, port=0)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    try:
        # Sabotage the storage: every op fails post-retries (StorageException,
        # what RetryPolicy raises) -> fail-open must allow.
        from ratelimiter_tpu.storage import StorageException

        def boom(*a, **k):
            raise StorageException("storage down")

        storage.increment_and_expire = boom  # type: ignore[assignment]
        storage.get = boom  # type: ignore[assignment]
        status, data, _ = req(srv, "GET", "/api/data", headers={"X-User-ID": "z"})
        assert status == 200
        assert data["remaining"] == -1  # "unable to determine"
        assert ctx.registry.scrape()["ratelimiter.failopen.allowed"] >= 1
    finally:
        srv.shutdown()
        thread.join(timeout=5)


def test_controller_actuator_disabled_by_default(server):
    status, data, _ = req(server, "GET", "/actuator/controller")
    assert status == 200 and data == {"enabled": False}


def test_fleet_control_needs_a_control_port():
    """fleet.enabled with no peers and no own control port cannot form
    a member set: wiring warns and leaves fleet control off."""
    props = AppProperties({"storage.backend": "memory",
                           "ratelimiter.control.fleet.enabled": "true"})
    ctx = build_app(props)
    try:
        assert ctx.fleet_control is None
    finally:
        ctx.close()


def test_controller_actuator_and_health_fold_fleet_mode():
    """/actuator/controller in fleet mode: leader identity, fence
    epoch, last broadcast generation, per-node applied generation —
    and a node serving BEHIND the leader's generation folds health to
    DEGRADED (the generation-convergence invariant, operator-visible)."""
    from ratelimiter_tpu.control import ControllerElection, FleetControlPlane
    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.replication.control import controller_handlers
    from ratelimiter_tpu.service.wiring import FleetControlHandle
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    class TableBackend:
        def __init__(self, table):
            self.table = table

        def controller_claim(self, node, epoch, ttl_ms=3000.0):
            return self.table["controller_claim"](node=node, epoch=epoch,
                                                  ttl_ms=ttl_ms)

        def set_policy_rows(self, rows, epoch, node=""):
            return self.table["set_policy"](rows=rows, epoch=epoch,
                                            node=node)

        def policy_info(self):
            return self.table["policy_info"]()

        def signals(self, window_ms=2000):
            return self.table["signals"](window_ms=window_ms)

    props = AppProperties({"storage.backend": "memory", "server.port": "0"})
    ctx = build_app(props, storage=InMemoryStorage())
    member = TpuBatchedStorage(num_slots=64, max_delay_ms=0.2)
    cfg = RateLimitConfig(max_permits=40, window_ms=1000)
    lid = member.register_limiter("sw", cfg)
    plane = FleetControlPlane(
        "ctrl-a", {"n0": TableBackend(controller_handlers(member))},
        limiters={lid: ("sw", cfg)})
    election = ControllerElection([plane])
    election.tick()
    ctx.fleet_control = FleetControlHandle(plane, election)
    srv = make_server(ctx, port=0)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    try:
        plane.set_policy(lid, RateLimitConfig(max_permits=10,
                                              window_ms=1000))
        status, data, _ = req(srv, "GET", "/actuator/controller")
        assert status == 200
        assert data["enabled"] and data["fleet"]
        assert data["node"] == "ctrl-a" and data["is_leader"]
        assert data["epoch"] == 1
        assert data["last_broadcast_generation"] == 1
        assert data["nodes"]["n0"]["generation"] == 1
        assert data["election"]["leader"] == "ctrl-a"
        assert data["lagging_nodes"] == []
        status, health, _ = req(srv, "GET", "/actuator/health")
        assert status == 200 and health["status"] == "UP"
        assert health["controller"]["is_leader"]
        # A node left behind the broadcast generation = DEGRADED.
        # (Simulate a broadcast the node never applied: the leader's
        # generation advances, the seat's stays — exactly what the
        # actuator's per-node refresh would find after a lost frame.)
        plane.generation = plane.last_broadcast_generation = 2
        plane.node_generations["n0"] = 1
        status, health, _ = req(srv, "GET", "/actuator/health")
        assert status == 200 and health["status"] == "DEGRADED"
        assert health["controller"]["lagging_nodes"] == ["n0"]
        status, data, _ = req(srv, "GET", "/actuator/controller")
        assert data["lagging_nodes"] == ["n0"]
        assert data["nodes"]["n0"]["generation"] == 1
    finally:
        srv.shutdown()
        thread.join(timeout=5)
        ctx.close()
        member.close()
