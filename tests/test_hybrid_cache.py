"""Hybrid host-side serving tier (cache/hybrid.py, r11).

The load-bearing claim: a host-served decision is bit-identical to what
the device would have answered — proven against ``semantics/oracle.py``
under churn (slot eviction), TTL/window expiry, and a mid-stream policy
``reset_key`` — and over-admission under adversarial divergence is
bounded exactly as ``storage/degraded.py`` bounds it (one extra
``max_permits`` per key per window).
"""

import random
import time

import pytest

from ratelimiter_tpu.core.config import RateLimitConfig
from ratelimiter_tpu.semantics.oracle import (
    SlidingWindowOracle,
    TokenBucketOracle,
)


def _wait_for(cond, timeout=10.0):
    """Adoption/confirmation land on drain-thread callbacks, which race
    the caller's Future.result() wakeup — poll briefly before asserting
    tier state."""
    deadline = time.time() + timeout
    while not cond() and time.time() < deadline:
        time.sleep(0.005)
    assert cond()


def _storage(clock, **kw):
    from ratelimiter_tpu.storage import TpuBatchedStorage

    kw.setdefault("num_slots", 1 << 10)
    kw.setdefault("max_delay_ms", 0.2)
    return TpuBatchedStorage(clock_ms=lambda: clock[0],
                             serving_cache=True, **kw)


def test_hybrid_bit_identity_sw():
    """Sliding window: interleaved repeat traffic over few keys with an
    injected clock that crosses window boundaries and PEXPIRE deadlines,
    plus a mid-stream reset — every decision (host-served or device)
    equals the sequential oracle, field for field."""
    clock = [10_000]
    st = _storage(clock, serving_cache_ttl_ms=10_000.0)
    try:
        cfg = RateLimitConfig(max_permits=4, window_ms=500)
        lid = st.register_limiter("sw", cfg)
        oracle = SlidingWindowOracle(cfg)
        st.warm_micro_shapes()
        rng = random.Random(3)
        keys = [f"h{i}" for i in range(4)]
        served_any = 0
        for step in range(700):
            delta = rng.choice([0, 0, 0, 1, 7, 80, 700])
            if delta:
                # Quiesce in-flight confirmations before moving the
                # injected clock: a forwarded op must dispatch at the
                # stamp its host serve decided at (see
                # HybridServingCache.pending_confirms).
                st.flush()
                _wait_for(lambda: st._serving.pending_confirms() == 0)
                clock[0] += delta
            key = rng.choice(keys)
            if step % 90 == 89:
                # Mid-stream policy reset: device slot cleared AND the
                # tier entry invalidated (storage.reset_key hook).
                st.reset_key("sw", lid, key)
                oracle.reset(key, clock[0])
                continue
            permits = rng.choice([1, 1, 2])
            out = st.acquire("sw", lid, key, permits)
            d = oracle.try_acquire(key, permits, clock[0])
            assert bool(out["allowed"]) == d.allowed, (step, key, out)
            assert bool(out["mutated"]) == d.mutated, (step, key, out)
            assert int(out["observed"]) == d.observed, (step, key, out)
            assert int(out["cache_value"]) == d.remaining_hint, \
                (step, key, out)
            served_any += bool(out.get("host_served"))
        assert served_any > 0, "tier never served — test proves nothing"
        assert st._serving.divergence == 0
    finally:
        st.close()


def test_hybrid_bit_identity_tb():
    clock = [10_000]
    st = _storage(clock, serving_cache_ttl_ms=10_000.0)
    try:
        cfg = RateLimitConfig(max_permits=6, window_ms=1000,
                              refill_rate=3.0)
        lid = st.register_limiter("tb", cfg)
        oracle = TokenBucketOracle(cfg)
        st.warm_micro_shapes()
        rng = random.Random(11)
        keys = [f"t{i}" for i in range(3)]
        served_any = 0
        for step in range(600):
            delta = rng.choice([0, 0, 1, 30, 400, 5000])
            if delta:
                st.flush()  # quiesce before moving the clock (see sw test)
                _wait_for(lambda: st._serving.pending_confirms() == 0)
                clock[0] += delta
            key = rng.choice(keys)
            permits = rng.choice([1, 1, 2, 3])
            out = st.acquire("tb", lid, key, permits)
            d = oracle.try_acquire(key, permits, clock[0])
            assert bool(out["allowed"]) == d.allowed, (step, key, out)
            assert int(out["observed"]) == d.observed, (step, key, out)
            assert int(out["remaining"]) == d.remaining_hint, \
                (step, key, out)
            served_any += bool(out.get("host_served"))
        assert served_any > 0
        assert st._serving.divergence == 0
    finally:
        st.close()


def test_hybrid_bit_identity_under_slot_churn():
    """num_slots barely above the working set: evictions constantly
    remap slots.  An evicted key's device state is gone, so the oracle
    models eviction as reset — the tier must invalidate at remap time or
    it would keep serving forgotten state."""
    clock = [10_000]
    st = _storage(clock, num_slots=1 << 5, serving_cache_ttl_ms=60_000.0)
    try:
        cfg = RateLimitConfig(max_permits=5, window_ms=60_000)
        lid = st.register_limiter("sw", cfg)
        oracle = SlidingWindowOracle(cfg)
        st.warm_micro_shapes()
        rng = random.Random(5)
        # Working set larger than the slot table: steady churn.
        keys = [f"c{i}" for i in range(48)]
        tracked = set()
        for step in range(800):
            clock[0] += rng.choice([0, 0, 1])
            key = rng.choice(keys)
            before = st._index["sw"].get((lid, key))
            out = st.acquire("sw", lid, key, 1)
            if before is None:
                # The key was absent (never seen or evicted): its device
                # state restarted from zero — mirror in the oracle.
                oracle.reset(key, clock[0])
            d = oracle.try_acquire(key, 1, clock[0])
            assert bool(out["allowed"]) == d.allowed, (step, key, out)
            assert int(out["observed"]) == d.observed, (step, key, out)
        assert st._serving.divergence == 0
    finally:
        st.close()


def test_hybrid_over_admission_bounded_under_adversarial_divergence():
    """Device state mutated BEHIND the tier (direct acquire_many — the
    stream/batch surface the tier doesn't intercept): the tier's serves
    may disagree with the device, but combined admission per key per
    window stays within oracle-allows + max_permits — the exact
    storage/degraded.py bound — because the tier's own arithmetic can
    admit at most max_permits per window and so can the device."""
    clock = [10_000]
    st = _storage(clock, serving_cache_ttl_ms=60_000.0,
                  serving_cache_unconfirmed_cap=1 << 20)
    try:
        cfg = RateLimitConfig(max_permits=8, window_ms=60_000)
        lid = st.register_limiter("sw", cfg)
        st.warm_micro_shapes()
        key = "victim"
        # Adopt the key into the tier.
        allowed_total = int(bool(st.acquire("sw", lid, key, 1)["allowed"]))
        _wait_for(lambda: len(st._serving) == 1)
        # Hidden device traffic: 6 direct batch decisions the tier never
        # sees as serves (acquire_many bypasses it) — but note the batch
        # path clears/evictions would invalidate; same-slot writes with
        # no eviction do not.
        out = st.acquire_many("sw", [lid] * 6, [key] * 6, [1] * 6)
        allowed_total += int(out["allowed"].sum())
        # The tier's snapshot is now stale by 6 admits.  Drain its whole
        # host-side budget.
        for _ in range(30):
            r = st.acquire("sw", lid, key, 1)
            allowed_total += int(bool(r["allowed"]))
        st.flush()
        # One window, one key: the oracle alone would admit max_permits.
        # Bound: <= 2 * max_permits (one extra window of over-admission).
        assert allowed_total <= 2 * cfg.max_permits
        # And the divergence was detected, not silently absorbed.
        _wait_for(lambda: st._serving.divergence > 0
                  or st._serving.invalidated > 0)
    finally:
        st.close()


def test_hybrid_unconfirmed_cap_forces_device_path():
    """With the flusher effectively stalled (long fixed deadline),
    forwarded confirmations can't drain; once unconfirmed hits the cap
    the tier drops the entry and the caller rides the device path."""
    clock = [10_000]
    st = _storage(clock, max_delay_ms=5_000.0, adaptive_flush=False,
                  serving_cache_unconfirmed_cap=2,
                  serving_cache_ttl_ms=60_000.0)
    try:
        cfg = RateLimitConfig(max_permits=1000, window_ms=60_000)
        lid = st.register_limiter("sw", cfg)
        st.warm_micro_shapes()
        f0 = st.acquire_async("sw", lid, "k", 1)
        st.flush()
        assert bool(f0.result(timeout=30)["allowed"])
        _wait_for(lambda: len(st._serving) == 1)  # adopted
        f1 = st.acquire_async("sw", lid, "k", 1)
        f2 = st.acquire_async("sw", lid, "k", 1)
        assert f1.done() and f2.done()  # host-served instantly
        served_before = st._serving.served
        f3 = st.acquire_async("sw", lid, "k", 1)  # cap hit -> device
        assert not f3.done()
        assert st._serving.served == served_before
        assert len(st._serving) == 0  # dropped, will re-adopt
        st.flush()
        assert bool(f3.result(timeout=30)["allowed"])
        assert st._serving.divergence == 0
    finally:
        st.close()


def test_hybrid_eviction_invalidates_entry():
    clock = [10_000]
    st = _storage(clock, serving_cache_ttl_ms=60_000.0)
    try:
        cfg = RateLimitConfig(max_permits=5, window_ms=60_000)
        lid = st.register_limiter("sw", cfg)
        st.warm_micro_shapes()
        st.acquire("sw", lid, "evictme", 1)
        _wait_for(lambda: len(st._serving) == 1)
        slot = st._index["sw"].get((lid, "evictme"))
        st._clear_slots("sw", [slot])
        assert len(st._serving) == 0
    finally:
        st.close()


def test_hybrid_reset_key_invalidates_entry():
    clock = [10_000]
    st = _storage(clock, serving_cache_ttl_ms=60_000.0)
    try:
        cfg = RateLimitConfig(max_permits=5, window_ms=60_000)
        lid = st.register_limiter("sw", cfg)
        st.warm_micro_shapes()
        st.acquire("sw", lid, "r", 1)
        _wait_for(lambda: len(st._serving) == 1)
        st.reset_key("sw", lid, "r")
        assert len(st._serving) == 0
        # Post-reset decisions restart clean (fresh window).
        out = st.acquire("sw", lid, "r", 1)
        assert bool(out["allowed"]) and int(out["observed"]) == 0
    finally:
        st.close()


def test_hybrid_repeat_reject_served_without_device_traffic():
    """The hot repeat-reject path: once a key is at its limit, rejects
    resolve host-side with zero batcher submissions."""
    clock = [10_000]
    st = _storage(clock, serving_cache_ttl_ms=60_000.0)
    try:
        cfg = RateLimitConfig(max_permits=2, window_ms=60_000)
        lid = st.register_limiter("sw", cfg)
        st.warm_micro_shapes()
        for _ in range(4):
            st.acquire("sw", lid, "hot", 1)  # 2 allowed, then rejects
        st.flush()
        _wait_for(lambda: len(st._serving) == 1)
        rejects_before = st._serving.rejects_served
        depth_before = st._batcher.max_depth_seen
        shipped = st._serving.served
        for _ in range(20):
            out = st.acquire("sw", lid, "hot", 1)
            assert not bool(out["allowed"])
        assert st._serving.rejects_served - rejects_before == 20
        assert st._serving.served - shipped == 20
        assert st._batcher.max_depth_seen == depth_before
        assert st._serving.divergence == 0
    finally:
        st.close()
