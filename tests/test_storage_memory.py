"""InMemoryStorage contract tests (the real test double, SURVEY.md §4)."""

import pytest

from ratelimiter_tpu.storage import InMemoryStorage, RetryPolicy, StorageException


class FakeClock:
    def __init__(self, t=1_753_000_000_000):
        self.t = t

    def __call__(self):
        return self.t


def test_increment_and_expire():
    clock = FakeClock()
    s = InMemoryStorage(clock_ms=clock)
    assert s.increment_and_expire("k", 1000) == 1
    assert s.increment_and_expire("k", 1000) == 2
    assert s.get("k") == 2
    clock.t += 999
    assert s.get("k") == 2  # TTL refreshed by the second increment
    clock.t += 1
    assert s.get("k") == 0  # expired exactly at the deadline
    assert s.increment_and_expire("k", 1000) == 1  # fresh counter


def test_set_get_delete():
    s = InMemoryStorage(clock_ms=FakeClock())
    s.set("k", 42, 1000)
    assert s.get("k") == 42
    s.delete("k")
    assert s.get("k") == 0


def test_compare_and_set():
    s = InMemoryStorage(clock_ms=FakeClock())
    s.set("k", 5, 10_000)
    assert s.compare_and_set("k", 5, 9)
    assert s.get("k") == 9
    assert not s.compare_and_set("k", 5, 7)
    assert s.get("k") == 9
    # CAS against an absent key treats it as 0 (RedisRateLimitStorage.java:78).
    assert s.compare_and_set("absent", 0, 1)
    assert s.get("absent") == 1


def test_zset_ops():
    s = InMemoryStorage(clock_ms=FakeClock())
    s.z_add("z", 1.0, "a")
    s.z_add("z", 2.0, "b")
    s.z_add("z", 3.0, "c")
    assert s.z_count("z", 1.5, 3.5) == 2
    assert s.z_remove_range_by_score("z", 0.0, 2.0) == 2
    assert s.z_count("z", 0.0, 10.0) == 1


def test_unknown_script_raises():
    s = InMemoryStorage(clock_ms=FakeClock())
    with pytest.raises(StorageException):
        s.eval_script("no_such_script", ["k"], [])


def test_health_and_fault_injection():
    s = InMemoryStorage(clock_ms=FakeClock())
    assert s.is_available()
    s.set_available(False)
    assert not s.is_available()


def test_retry_policy_retries_then_raises():
    calls = []

    def flaky():
        calls.append(1)
        raise ConnectionError("boom")

    slept = []
    with pytest.raises(StorageException):
        RetryPolicy().execute(flaky, sleep=slept.append)
    assert len(calls) == 3
    # Linear backoff 10/20 ms between the 3 attempts
    # (RedisRateLimitStorage.java:155-178).
    assert slept == [0.01, 0.02]


def test_retry_policy_recovers():
    calls = []

    def flaky_then_ok():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("boom")
        return "ok"

    assert RetryPolicy().execute(flaky_then_ok, sleep=lambda *_: None) == "ok"
