"""Boundary-biased soak differential.

Long randomized streams with timestamps deliberately biased onto the
decision-relevant edges — exact window boundaries, PEXPIRE deadlines,
TTL expiries, zero-dt repeats — driven through the device engine and the
oracle in lockstep.  This is the deep-fuzz layer on top of the per-feature
differentials."""

import random

import pytest

from ratelimiter_tpu import RateLimitConfig
from ratelimiter_tpu.engine.engine import DeviceEngine
from ratelimiter_tpu.engine.state import LimiterTable
from ratelimiter_tpu.semantics import SlidingWindowOracle, TokenBucketOracle

T0 = 1_753_000_000_000


def biased_dt(rng: random.Random, win: int) -> int:
    """Time steps concentrated on boundaries."""
    roll = rng.random()
    if roll < 0.25:
        return 0                      # same-ms repeat
    if roll < 0.40:
        return rng.choice([1, 2, 3])
    if roll < 0.60:
        return rng.choice([win - 1, win, win + 1])
    if roll < 0.75:
        return rng.choice([2 * win - 1, 2 * win, 2 * win + 1])
    if roll < 0.90:
        return rng.randrange(1, win)
    return rng.randrange(2 * win, 6 * win)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_soak_sliding_window(seed):
    rng = random.Random(100 + seed)
    win = rng.choice([1000, 2500, 60_000])
    cfg = RateLimitConfig(max_permits=rng.choice([1, 5, 40]), window_ms=win,
                          enable_local_cache=False)
    table = LimiterTable()
    lid = table.register(cfg)
    engine = DeviceEngine(num_slots=64, table=table)
    oracle = SlidingWindowOracle(cfg)
    smap = {}
    now = T0
    for step in range(250):
        now += biased_dt(rng, win)
        n = rng.randrange(1, 12)
        ks = [f"k{rng.randrange(6)}" for _ in range(n)]
        perms = [rng.choice([1, 1, 1, 2, cfg.max_permits,
                             cfg.max_permits + 1]) for _ in range(n)]
        slots = [smap.setdefault(k, len(smap)) for k in ks]
        out = engine.sw_acquire(slots, [lid] * n, perms, now)
        for j in range(n):
            d = oracle.try_acquire(ks[j], perms[j], now)
            assert out["allowed"][j] == d.allowed, (seed, step, j, now - T0)
            assert out["observed"][j] == d.observed, (seed, step, j)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_soak_token_bucket(seed):
    rng = random.Random(200 + seed)
    win = rng.choice([1000, 3000])
    cap = rng.choice([1, 7, 60])
    cfg = RateLimitConfig(max_permits=cap, window_ms=win,
                          refill_rate=rng.choice([0.5, 3.0, 47.0, 1000.0]))
    table = LimiterTable()
    lid = table.register(cfg)
    engine = DeviceEngine(num_slots=64, table=table)
    oracle = TokenBucketOracle(cfg)
    smap = {}
    now = T0
    for step in range(250):
        now += biased_dt(rng, win)
        n = rng.randrange(1, 12)
        ks = [f"k{rng.randrange(6)}" for _ in range(n)]
        perms = [rng.choice([1, 1, cap, cap + 1, max(1, cap // 2)])
                 for _ in range(n)]
        slots = [smap.setdefault(k, len(smap)) for k in ks]
        out = engine.tb_acquire(slots, [lid] * n, perms, now)
        for j in range(n):
            d = oracle.try_acquire(ks[j], perms[j], now)
            assert out["allowed"][j] == d.allowed, (seed, step, j, now - T0)
            assert out["remaining"][j] == d.remaining_hint, (seed, step, j)


@pytest.mark.parametrize("seed", [0, 1])
def test_soak_mixed_paths_vs_oracle(seed):
    """Interleave every storage decision path — single acquire, batched
    string keys, int-key batches, and the pipelined stream — on ONE storage
    against the oracle.  All paths must address the same buckets and agree
    with the sequential semantics."""
    import numpy as np

    from ratelimiter_tpu.storage import TpuBatchedStorage

    rng = random.Random(300 + seed)
    win = 2000
    cfg = RateLimitConfig(max_permits=20, window_ms=win, refill_rate=10.0)
    clock = {"t": T0}
    storage = TpuBatchedStorage(num_slots=128,
                                clock_ms=lambda: clock["t"])
    lid = storage.register_limiter("tb", cfg)
    oracle = TokenBucketOracle(cfg)

    n_keys = 5
    for step in range(60):
        clock["t"] += biased_dt(rng, win)
        now = clock["t"]
        mode = rng.randrange(3)
        n = rng.randrange(1, 10)
        key_ids = [rng.randrange(n_keys) for _ in range(n)]
        perms = [rng.choice([1, 2, 5, 21]) for _ in range(n)]
        if mode == 0:
            # String-key path — its own bucket family ("s:K" != int K).
            got = [storage.acquire("tb", lid, f"s:{k}", p)["allowed"]
                   for k, p in zip(key_ids, perms)]
            okeys = [f"s:{k}" for k in key_ids]
        elif mode == 1:
            # Int-key batch — same buckets as the stream path.
            got = storage.acquire_many_ids(
                "tb", lid, np.asarray(key_ids),
                np.asarray(perms))["allowed"]
            okeys = [f"int:{k}" for k in key_ids]
        else:
            got = storage.acquire_stream_ids(
                "tb", np.full(n, lid), np.asarray(key_ids),
                np.asarray(perms), batch=16, subbatches=1)
            okeys = [f"int:{k}" for k in key_ids]
        for j in range(n):
            d = oracle.try_acquire(okeys[j], perms[j], now)
            assert bool(got[j]) == d.allowed, (seed, step, j, mode)
    storage.close()


def test_monotonic_stamp_guards_clock_regression():
    """A wall clock stepping backwards must not zero live windows."""
    from ratelimiter_tpu.algorithms import SlidingWindowRateLimiter
    from ratelimiter_tpu.metrics import MeterRegistry
    from ratelimiter_tpu.storage import TpuBatchedStorage

    class JumpyClock:
        def __init__(self):
            self.t = (T0 // 60_000) * 60_000

        def __call__(self):
            return self.t

    clock = JumpyClock()
    storage = TpuBatchedStorage(num_slots=32, max_delay_ms=0.1, clock_ms=clock)
    cfg = RateLimitConfig(max_permits=2, window_ms=60_000, enable_local_cache=False)
    limiter = SlidingWindowRateLimiter(storage, cfg, MeterRegistry(), clock_ms=clock)
    assert limiter.try_acquire("u")
    assert limiter.try_acquire("u")
    assert not limiter.try_acquire("u")
    clock.t -= 120_000  # NTP-style regression of two windows
    # Without the monotonic clamp the engine would see an "old" window,
    # zero the state, and wrongly admit.
    assert not limiter.try_acquire("u")
    storage.close()


@pytest.mark.parametrize("seed", [0, 1])
def test_grand_soak_all_paths_with_reset_and_checkpoint(seed, tmp_path):
    """The widest interleave: scalar, int batch, unit stream, WEIGHTED
    stream (single-lid), string stream, admin reset, and a mid-soak
    checkpoint save/restore cycle — one storage, one oracle, decisions
    bit-identical throughout."""
    import numpy as np

    from ratelimiter_tpu.storage import TpuBatchedStorage

    rng = random.Random(900 + seed)
    nrng = np.random.default_rng(900 + seed)
    win = 1500
    cfg = RateLimitConfig(max_permits=9, window_ms=win, refill_rate=6.0)
    clock = {"t": T0}
    storage = TpuBatchedStorage(num_slots=256,
                                clock_ms=lambda: clock["t"])
    lid = storage.register_limiter("tb", cfg)
    oracle = TokenBucketOracle(cfg)
    n_keys = 8
    ckpt = str(tmp_path / f"soak{seed}.ckpt")

    for step in range(50):
        clock["t"] += biased_dt(rng, win)
        now = clock["t"]
        mode = rng.randrange(6)
        n = rng.randrange(1, 12)
        key_ids = nrng.integers(0, n_keys, n)
        perms = nrng.integers(1, 6, n).astype(np.int64)
        if mode == 0:
            # Scalar path with RAW int keys: shares the int bucket family
            # with the batch/stream paths below.
            got = [storage.acquire("tb", lid, int(k), int(p))["allowed"]
                   for k, p in zip(key_ids, perms)]
            okeys = [f"int:{k}" for k in key_ids]
        elif mode == 1:
            got = storage.acquire_many_ids(
                "tb", lid, key_ids, perms)["allowed"]
            okeys = [f"int:{k}" for k in key_ids]
        elif mode == 2:
            got = storage.acquire_stream_ids(
                "tb", lid, key_ids, None, batch=16, subbatches=1)
            perms = np.ones(n, dtype=np.int64)
            okeys = [f"int:{k}" for k in key_ids]
        elif mode == 3:  # weighted relay stream
            got = storage.acquire_stream_ids(
                "tb", lid, key_ids, perms, batch=16, subbatches=1)
            okeys = [f"int:{k}" for k in key_ids]
        elif mode == 4:  # weighted STRING stream, its own bucket family
            keys = [f"s:{int(k)}" for k in key_ids]
            got = storage.acquire_stream_strs("tb", lid, keys, perms)
            okeys = keys
        else:
            got = storage.acquire_stream_strs(
                "tb", lid, [f"s:{int(k)}" for k in key_ids], None)
            perms = np.ones(n, dtype=np.int64)
            okeys = [f"s:{k}" for k in key_ids]
        for j in range(n):
            d = oracle.try_acquire(okeys[j], int(perms[j]), now)
            assert bool(got[j]) == d.allowed, (seed, step, j, mode)
        r = rng.random()
        if r < 0.15:
            k = rng.randrange(n_keys)
            fam = rng.choice(["int", "s"])
            key = k if fam == "int" else f"s:{k}"
            storage.reset_key("tb", lid, key)
            oracle.reset(f"{fam}:{k}" if fam == "int" else key, now)
        elif r < 0.25:
            storage.save_checkpoint(ckpt)
            storage.restore_checkpoint(ckpt)
    storage.close()


@pytest.mark.parametrize("seed", [0, 1])
def test_soak_sorted_digest_stream_vs_oracle(seed, monkeypatch):
    """Unit-stream soak with the slot-sorted digest path FORCED (tiny
    sort threshold + gate patched onto the XLA fallback): radix sort +
    uidx remap + sorted dispatch + reconstruction must stay bit-exact
    against the oracle across evictions, resets, and time steps."""
    import numpy as np

    import ratelimiter_tpu.storage.tpu as tpu_mod
    from ratelimiter_tpu.engine.native_index import native_available
    from ratelimiter_tpu.storage import TpuBatchedStorage

    if not native_available():
        pytest.skip("needs the native library")
    monkeypatch.setattr(tpu_mod, "_SORT_UNIQUES_MIN", 2)
    monkeypatch.setattr(tpu_mod, "_presorted_scatter_usable",
                        lambda eng, algo, padded: True)
    # Count the sorts: the digest election needs heavy duplication
    # (6.0*u <= 4.125*n, ops/relay.py:wire_costs), so the traffic below
    # is many requests over FEW keys — and the test fails if the sorted
    # path never actually engaged.
    import ratelimiter_tpu.engine.native_index as ni

    sorts = {"n": 0}
    real_sort = ni.sort_uniques

    def counting_sort(uw, rb, ui):
        sorts["n"] += 1
        return real_sort(uw, rb, ui)

    monkeypatch.setattr(ni, "sort_uniques", counting_sort)
    rng = random.Random(1700 + seed)
    nrng = np.random.default_rng(1700 + seed)
    win = 1200
    cfg = RateLimitConfig(max_permits=7, window_ms=win, refill_rate=5.0)
    clock = {"t": T0}
    storage = TpuBatchedStorage(num_slots=128, clock_ms=lambda: clock["t"])
    lid = storage.register_limiter("tb", cfg)
    oracle = TokenBucketOracle(cfg)
    n_keys = 24
    for step in range(40):
        clock["t"] += biased_dt(rng, win)
        now = clock["t"]
        n = rng.randrange(100, 260)  # ~4-10x duplication: digest elects
        key_ids = nrng.integers(0, n_keys, n)
        got = storage.acquire_stream_ids("tb", lid, key_ids, None,
                                         batch=512, subbatches=1)
        for j in range(n):
            d = oracle.try_acquire(f"int:{key_ids[j]}", 1, now)
            assert bool(got[j]) == d.allowed, (seed, step, j)
        if rng.random() < 0.2:
            k = rng.randrange(n_keys)
            storage.reset_key("tb", lid, k)
            oracle.reset(f"int:{k}", now)
    storage.close()
    assert sorts["n"] >= 20, \
        f"sorted digest path engaged only {sorts['n']} times"
