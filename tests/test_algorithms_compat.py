"""Compat-path algorithms (storage plugin) vs the oracle.

The InMemoryStorage-backed algorithm classes must reproduce the oracle's
decisions exactly — this is the differential test SURVEY.md §4 prescribes as
the replacement for the reference's disabled Mockito tests.
"""

import random

from ratelimiter_tpu import RateLimitConfig
from ratelimiter_tpu.algorithms import SlidingWindowRateLimiter, TokenBucketRateLimiter
from ratelimiter_tpu.metrics import MeterRegistry
from ratelimiter_tpu.semantics import SlidingWindowOracle, TokenBucketOracle
from ratelimiter_tpu.storage import InMemoryStorage

T0 = 1_753_000_000_000


class FakeClock:
    def __init__(self, t=T0):
        self.t = t

    def __call__(self):
        return self.t


def make_sw(config):
    clock = FakeClock()
    storage = InMemoryStorage(clock_ms=clock)
    limiter = SlidingWindowRateLimiter(storage, config, MeterRegistry(), clock_ms=clock)
    return limiter, clock


def make_tb(config):
    clock = FakeClock()
    storage = InMemoryStorage(clock_ms=clock)
    limiter = TokenBucketRateLimiter(storage, config, MeterRegistry(), clock_ms=clock)
    return limiter, clock


# ---------------------------------------------------------------------------
# Differential: random streams, decisions must match the oracle exactly
# ---------------------------------------------------------------------------

def test_sw_differential_vs_oracle():
    cfg = RateLimitConfig(max_permits=25, window_ms=1000, enable_local_cache=False)
    limiter, clock = make_sw(cfg)
    oracle = SlidingWindowOracle(cfg)
    rng = random.Random(1)
    keys = [f"u{i}" for i in range(5)]
    for step in range(5000):
        clock.t += rng.randrange(0, 120)
        key = rng.choice(keys)
        permits = rng.randrange(1, 4)
        if rng.random() < 0.01:
            limiter.reset(key)
            oracle.reset(key, clock.t)
            continue
        got = limiter.try_acquire(key, permits)
        want = oracle.try_acquire(key, permits, clock.t).allowed
        assert got == want, f"step {step}: {key} p={permits} t={clock.t - T0}"
        assert limiter.get_available_permits(key) == oracle.get_available_permits(key, clock.t)


def test_tb_differential_vs_oracle():
    cfg = RateLimitConfig(max_permits=30, window_ms=2000, refill_rate=13.0)
    limiter, clock = make_tb(cfg)
    oracle = TokenBucketOracle(cfg)
    rng = random.Random(2)
    keys = [f"u{i}" for i in range(5)]
    for step in range(5000):
        clock.t += rng.randrange(0, 300)
        key = rng.choice(keys)
        permits = rng.randrange(1, 35)  # sometimes above capacity
        if rng.random() < 0.01:
            limiter.reset(key)
            oracle.reset(key, clock.t)
            continue
        got = limiter.try_acquire(key, permits)
        want = oracle.try_acquire(key, permits, clock.t).allowed
        assert got == want, f"step {step}: {key} p={permits} t={clock.t - T0}"
        assert limiter.get_available_permits(key) == oracle.get_available_permits(key, clock.t)


# ---------------------------------------------------------------------------
# Local negative cache (C7)
# ---------------------------------------------------------------------------

def test_cache_short_circuits_rejections():
    cfg = RateLimitConfig(max_permits=3, window_ms=60_000,
                          enable_local_cache=True, local_cache_ttl_ms=100)
    clock = FakeClock((T0 // 60_000) * 60_000)
    storage = InMemoryStorage(clock_ms=clock)
    registry = MeterRegistry()
    limiter = SlidingWindowRateLimiter(storage, cfg, registry, clock_ms=clock)

    for _ in range(3):
        assert limiter.try_acquire("u")
        clock.t += 1
    assert not limiter.try_acquire("u")  # storage-backed rejection, caches count
    hits_before = registry.counter("ratelimiter.cache.hits").count()
    assert not limiter.try_acquire("u")  # served from the negative cache
    assert registry.counter("ratelimiter.cache.hits").count() == hits_before + 1

    # After the TTL the cache entry lapses and storage is consulted again.
    clock.t += 100
    hits = registry.counter("ratelimiter.cache.hits").count()
    assert not limiter.try_acquire("u")
    assert registry.counter("ratelimiter.cache.hits").count() == hits


def test_reset_invalidates_cache():
    cfg = RateLimitConfig(max_permits=2, window_ms=60_000,
                          enable_local_cache=True, local_cache_ttl_ms=10_000)
    clock = FakeClock((T0 // 60_000) * 60_000)
    storage = InMemoryStorage(clock_ms=clock)
    limiter = SlidingWindowRateLimiter(storage, cfg, MeterRegistry(), clock_ms=clock)
    assert limiter.try_acquire("u")
    assert limiter.try_acquire("u")
    assert not limiter.try_acquire("u")
    limiter.reset("u")
    assert limiter.try_acquire("u")  # cache invalidated with storage


# ---------------------------------------------------------------------------
# Metrics (C12)
# ---------------------------------------------------------------------------

def test_metric_names_and_counts():
    cfg = RateLimitConfig(max_permits=2, window_ms=60_000, enable_local_cache=False)
    clock = FakeClock((T0 // 60_000) * 60_000)
    registry = MeterRegistry()
    limiter = SlidingWindowRateLimiter(
        InMemoryStorage(clock_ms=clock), cfg, registry, clock_ms=clock)
    limiter.try_acquire("u")
    limiter.try_acquire("u")
    limiter.try_acquire("u")
    scrape = registry.scrape()
    assert scrape["ratelimiter.requests.allowed"] == 2
    assert scrape["ratelimiter.requests.rejected"] == 1

    tb_registry = MeterRegistry()
    tb = TokenBucketRateLimiter(
        InMemoryStorage(clock_ms=clock),
        RateLimitConfig(max_permits=2, window_ms=60_000, refill_rate=1.0),
        tb_registry, clock_ms=clock)
    tb.try_acquire("u", 2)
    tb.try_acquire("u", 1)
    scrape = tb_registry.scrape()
    assert scrape["ratelimiter.tokenbucket.allowed"] == 1
    assert scrape["ratelimiter.tokenbucket.rejected"] == 1


# ---------------------------------------------------------------------------
# Batch entry points (default loop implementation)
# ---------------------------------------------------------------------------

def test_try_acquire_many_default_path():
    cfg = RateLimitConfig(max_permits=3, window_ms=60_000, enable_local_cache=False)
    limiter, clock = make_sw(cfg)
    clock.t = (T0 // 60_000) * 60_000
    out = limiter.try_acquire_many(["a", "a", "a", "a", "b"])
    assert list(out) == [True, True, True, False, True]
