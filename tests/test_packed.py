"""Differential tests for the transfer-minimal step variants (ops/packed.py).

The fused and scan-bits wrappers must produce decisions identical to the
plain steps they wrap — they exist purely to reduce device->host transfers.
"""

import numpy as np
import pytest

from ratelimiter_tpu.core.config import RateLimitConfig
from ratelimiter_tpu.engine.engine import DeviceEngine
from ratelimiter_tpu.engine.state import LimiterTable, make_sw_state, make_tb_state
from ratelimiter_tpu.metrics import MeterRegistry
from ratelimiter_tpu.storage import TpuBatchedStorage


@pytest.fixture()
def table():
    t = LimiterTable()
    t.register(RateLimitConfig(max_permits=5, window_ms=1000))          # lid 1 (sw)
    t.register(RateLimitConfig(max_permits=10, window_ms=1000,
                               refill_rate=5.0))                        # lid 2 (tb)
    return t


def _steps_outputs(algo, table, slots, lids, permits, now):
    """Run the plain step and return its output dict (ground truth)."""
    import jax
    import jax.numpy as jnp

    from ratelimiter_tpu.ops.sliding_window import sw_step
    from ratelimiter_tpu.ops.token_bucket import tb_step

    if algo == "sw":
        state = make_sw_state(64)
        _, out = jax.jit(sw_step)(state, table.device_arrays,
                                  jnp.asarray(slots, jnp.int32),
                                  jnp.asarray(lids, jnp.int32),
                                  jnp.asarray(permits, jnp.int64),
                                  jnp.int64(now))
        return {k: np.asarray(v) for k, v in out._asdict().items()}
    state = make_tb_state(64)
    _, out = jax.jit(tb_step)(state, table.device_arrays,
                              jnp.asarray(slots, jnp.int32),
                              jnp.asarray(lids, jnp.int32),
                              jnp.asarray(permits, jnp.int64),
                              jnp.int64(now))
    return {k: np.asarray(v) for k, v in out._asdict().items()}


def test_fused_sw_matches_plain(table):
    rng = np.random.default_rng(0)
    slots = rng.integers(0, 8, 32).astype(np.int32)
    permits = rng.integers(1, 3, 32).astype(np.int64)
    truth = _steps_outputs("sw", table, slots, [1] * 32, permits, 5_000)

    engine = DeviceEngine(num_slots=64, table=table)
    got = engine.sw_acquire(slots, [1] * 32, permits, 5_000)
    np.testing.assert_array_equal(got["allowed"], truth["allowed"])
    np.testing.assert_array_equal(got["mutated"], truth["mutated"])
    np.testing.assert_array_equal(got["observed"], truth["observed"])
    np.testing.assert_array_equal(got["cache_value"], truth["cache_value"])


def test_fused_tb_matches_plain(table):
    rng = np.random.default_rng(1)
    slots = rng.integers(0, 8, 32).astype(np.int32)
    permits = rng.integers(1, 4, 32).astype(np.int64)
    truth = _steps_outputs("tb", table, slots, [2] * 32, permits, 5_000)

    engine = DeviceEngine(num_slots=64, table=table)
    got = engine.tb_acquire(slots, [2] * 32, permits, 5_000)
    np.testing.assert_array_equal(got["allowed"], truth["allowed"])
    np.testing.assert_array_equal(got["observed"], truth["observed"])
    np.testing.assert_array_equal(got["remaining"], truth["remaining"])


@pytest.mark.parametrize("algo,lid", [("sw", 1), ("tb", 2)])
def test_scan_bits_matches_sequential_batches(table, algo, lid):
    """K sub-batches in one scan dispatch == K successive plain acquires."""
    rng = np.random.default_rng(2)
    k, b = 3, 16
    slots = rng.integers(0, 6, (k, b)).astype(np.int32)
    permits = rng.integers(1, 3, (k, b)).astype(np.int32)
    now = np.full(k, 7_000, dtype=np.int64)

    seq = DeviceEngine(num_slots=64, table=table)
    expect = []
    for i in range(k):
        fn = seq.sw_acquire if algo == "sw" else seq.tb_acquire
        expect.append(fn(slots[i], [lid] * b, permits[i].astype(np.int64), 7_000)["allowed"])
    expect = np.concatenate(expect)

    scan = DeviceEngine(num_slots=64, table=table)
    dispatch = scan.sw_scan_dispatch if algo == "sw" else scan.tb_scan_dispatch
    bits = np.asarray(dispatch(slots, lid, permits, now))
    got = np.unpackbits(bits, axis=1)[:, :b].reshape(-1).astype(bool)
    np.testing.assert_array_equal(got, expect)


@pytest.mark.parametrize("algo,lid", [("sw", 1), ("tb", 2)])
def test_scan_bits_unit_permits_and_uniform_lid(table, algo, lid):
    rng = np.random.default_rng(3)
    k, b = 2, 24
    slots = rng.integers(0, 5, (k, b)).astype(np.int32)
    now = np.full(k, 9_000, dtype=np.int64)

    seq = DeviceEngine(num_slots=64, table=table)
    expect = []
    for i in range(k):
        fn = seq.sw_acquire if algo == "sw" else seq.tb_acquire
        expect.append(fn(slots[i], [lid] * b, np.ones(b, np.int64), 9_000)["allowed"])
    expect = np.concatenate(expect)

    scan = DeviceEngine(num_slots=64, table=table)
    dispatch = scan.sw_scan_dispatch if algo == "sw" else scan.tb_scan_dispatch
    bits = np.asarray(dispatch(slots, lid, None, now))
    got = np.unpackbits(bits, axis=1)[:, :b].reshape(-1).astype(bool)
    np.testing.assert_array_equal(got, expect)


def test_stream_ids_matches_batched(tmp_path):
    """acquire_stream_ids == acquire_many_ids on the same stream."""
    cfg = RateLimitConfig(max_permits=20, window_ms=1000, refill_rate=10.0)
    rng = np.random.default_rng(4)
    key_ids = rng.integers(0, 50, 1000).astype(np.int64)
    permits = rng.integers(1, 3, 1000).astype(np.int64)
    clock = lambda: 42_000  # noqa: E731 — frozen clock: identical stamps

    s1 = TpuBatchedStorage(num_slots=256, clock_ms=clock)
    lid1 = s1.register_limiter("tb", cfg)
    expect = np.empty(1000, dtype=bool)
    for i in range(0, 1000, 64):
        expect[i:i + 64] = s1.acquire_many_ids(
            "tb", lid1, key_ids[i:i + 64], permits[i:i + 64])["allowed"]
    s1.close()

    s2 = TpuBatchedStorage(num_slots=256, clock_ms=clock)
    lid2 = s2.register_limiter("tb", cfg)
    got = s2.acquire_stream_ids("tb", lid2, key_ids, permits,
                                batch=64, subbatches=2)
    s2.close()
    np.testing.assert_array_equal(got, expect)


def test_stream_ids_unit_permits_sliding_window():
    cfg = RateLimitConfig(max_permits=3, window_ms=1000,
                          enable_local_cache=False)
    rng = np.random.default_rng(5)
    key_ids = rng.integers(0, 10, 300).astype(np.int64)
    clock = lambda: 10_500  # noqa: E731

    s1 = TpuBatchedStorage(num_slots=64, clock_ms=clock)
    lid1 = s1.register_limiter("sw", cfg)
    expect = np.empty(300, dtype=bool)
    for i in range(0, 300, 32):
        expect[i:i + 32] = s1.acquire_many_ids(
            "sw", lid1, key_ids[i:i + 32],
            np.ones(32, np.int64)[: len(key_ids[i:i + 32])])["allowed"]
    s1.close()

    s2 = TpuBatchedStorage(num_slots=64, clock_ms=clock)
    lid2 = s2.register_limiter("sw", cfg)
    got = s2.acquire_stream_ids("sw", lid2, key_ids, None,
                                batch=32, subbatches=2)
    s2.close()
    np.testing.assert_array_equal(got, expect)


def test_stream_ids_multi_tenant_matches_batched():
    """Per-request limiter ids through the stream path == the string-keyed
    mixed-lid acquire_many path on the same request sequence."""
    rng = np.random.default_rng(6)
    clock = lambda: 77_000  # noqa: E731
    cfg_a = RateLimitConfig(max_permits=5, window_ms=1000, refill_rate=2.0)
    cfg_b = RateLimitConfig(max_permits=10, window_ms=1000, refill_rate=5.0)

    n, b = 200, 25
    keys = rng.integers(0, 20, n).astype(np.int64)
    permits = rng.integers(1, 3, n).astype(np.int64)

    s1 = TpuBatchedStorage(num_slots=256, clock_ms=clock)
    lid_a1 = s1.register_limiter("tb", cfg_a)
    lid_b1 = s1.register_limiter("tb", cfg_b)
    lids1 = np.where(keys % 2 == 0, lid_a1, lid_b1).astype(np.int64)
    expect = np.empty(n, dtype=bool)
    for i in range(0, n, b):
        expect[i:i + b] = s1.acquire_many(
            "tb", list(lids1[i:i + b]),
            [f"u{k}" for k in keys[i:i + b]],
            list(permits[i:i + b]))["allowed"]
    s1.close()

    s2 = TpuBatchedStorage(num_slots=256, clock_ms=clock)
    lid_a2 = s2.register_limiter("tb", cfg_a)
    lid_b2 = s2.register_limiter("tb", cfg_b)
    assert (lid_a2, lid_b2) == (lid_a1, lid_b1)
    lids2 = np.where(keys % 2 == 0, lid_a2, lid_b2).astype(np.int64)
    got = s2.acquire_stream_ids("tb", lids2, keys, permits,
                                batch=b, subbatches=2)
    s2.close()
    np.testing.assert_array_equal(got, expect)


def test_stream_multi_lid_shares_namespace_with_scalar_paths():
    """The multi-lid stream and acquire_many_ids address the SAME (lid, key)
    bucket — consuming via one path is visible to the other."""
    clock = lambda: 33_000  # noqa: E731
    s = TpuBatchedStorage(num_slots=128, clock_ms=clock)
    lid = s.register_limiter("tb", RateLimitConfig(
        max_permits=10, window_ms=1000, refill_rate=1.0))
    # Drain 10 tokens of key 7 via the scalar path.
    out = s.acquire_many_ids("tb", lid, np.asarray([7]), np.asarray([10]))
    assert out["allowed"][0]
    # The multi-lid stream must see the empty bucket, not a fresh one.
    got = s.acquire_stream_ids(
        "tb", np.asarray([lid]), np.asarray([7]), np.asarray([10]),
        batch=8, subbatches=1)
    s.close()
    assert not got[0]


def test_stream_multi_lid_rejects_bad_lids():
    s = TpuBatchedStorage(num_slots=64)
    s.register_limiter("tb", RateLimitConfig(
        max_permits=5, window_ms=1000, refill_rate=1.0))
    with pytest.raises(ValueError):
        s.acquire_stream_ids("tb", np.asarray([99]), np.asarray([1]))
    with pytest.raises(ValueError):
        s.acquire_stream_ids("tb", np.asarray([-1]), np.asarray([1]))
    s.close()


def _sharded_storage(clock, slots_per_shard=64):
    from ratelimiter_tpu.engine.state import LimiterTable
    from ratelimiter_tpu.parallel import ShardedDeviceEngine, make_mesh

    engine = ShardedDeviceEngine(slots_per_shard=slots_per_shard,
                                 table=LimiterTable(), mesh=make_mesh())
    return TpuBatchedStorage(engine=engine, clock_ms=clock)


def test_stream_sharded_matches_flat():
    """The sharded stream (key->shard routing + shard_map scan) must make
    exactly the decisions of the flat stream on the same request sequence."""
    rng = np.random.default_rng(8)
    clock = lambda: 55_000  # noqa: E731
    cfg = RateLimitConfig(max_permits=7, window_ms=1000, refill_rate=3.0)
    key_ids = rng.integers(0, 40, 600).astype(np.int64)
    permits = rng.integers(1, 3, 600).astype(np.int64)

    flat = TpuBatchedStorage(num_slots=512, clock_ms=clock)
    lid_f = flat.register_limiter("tb", cfg)
    expect = flat.acquire_stream_ids("tb", lid_f, key_ids, permits,
                                     batch=50, subbatches=3)
    flat.close()

    sharded = _sharded_storage(clock)
    lid_s = sharded.register_limiter("tb", cfg)
    assert lid_s == lid_f
    index = sharded._index["tb"]
    if not getattr(index, "supports_batch_ints", False):
        pytest.skip("native index unavailable")
    got = sharded.acquire_stream_ids("tb", lid_s, key_ids, permits,
                                     batch=50, subbatches=3)
    sharded.close()
    np.testing.assert_array_equal(got, expect)


def test_stream_sharded_multi_lid_and_scalar_agree():
    """Sharded stream with per-request lids shares buckets with the scalar
    sharded paths (scalar int acquire via index.assign routes to the same
    shard/slot)."""
    clock = lambda: 66_000  # noqa: E731
    cfg = RateLimitConfig(max_permits=4, window_ms=1000, refill_rate=1.0)
    sharded = _sharded_storage(clock)
    lid = sharded.register_limiter("tb", cfg)
    index = sharded._index["tb"]
    if not getattr(index, "supports_batch_ints", False):
        sharded.close()
        pytest.skip("native index unavailable")
    # Drain key 9 fully via the stream.
    got = sharded.acquire_stream_ids(
        "tb", np.full(4, lid), np.full(4, 9, dtype=np.int64), None,
        batch=4, subbatches=1)
    assert got.tolist() == [True] * 4
    # The scalar path must observe the drained bucket.
    out = sharded.acquire("tb", lid, 9, 1)
    sharded.close()
    assert not out["allowed"]


def test_stream_concurrent_with_queued_acquires():
    """A long-running stream must not evict slots out from under requests
    concurrently queued in the micro-batcher (pin protection), and the
    total admitted across both paths must respect every bucket's cap."""
    import threading

    clock = lambda: 44_000  # noqa: E731
    cap = 10
    s = TpuBatchedStorage(num_slots=512, clock_ms=clock, max_delay_ms=0.2)
    lid = s.register_limiter("tb", RateLimitConfig(
        max_permits=cap, window_ms=60_000, refill_rate=0.001))
    rng = np.random.default_rng(9)

    hot_allowed = []
    stop = threading.Event()

    def hammer():
        # Single-key acquires through the batcher while the stream runs.
        while not stop.is_set():
            out = s.acquire("tb", lid, "hot", 1)
            hot_allowed.append(bool(out["allowed"]))

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for t in threads:
        t.start()
    stream_allowed = 0
    stream_n = 0
    for _ in range(6):
        ids = rng.integers(0, 200, 2000)
        got = s.acquire_stream_ids("tb", lid, ids, None,
                                   batch=256, subbatches=2)
        stream_allowed += int(got.sum())
        stream_n += len(ids)
    stop.set()
    for t in threads:
        t.join()
    s.close()
    # The hot key (string namespace) has its own bucket: exactly cap allowed.
    assert sum(hot_allowed) == cap, sum(hot_allowed)
    # Stream buckets: every int key admits at most cap.
    assert stream_allowed <= 200 * cap
    assert stream_n == 12_000


def test_tb_drain_at_epoch_zero_stays_drained(table):
    """A bucket drained at now=0 must NOT alias the absent-key sentinel and
    refill instantly (regression: last_refill clamps to >= 1)."""
    engine = DeviceEngine(num_slots=64, table=table)
    # lid 2: cap 10, refill 5/s -> 0.005/ms
    out = engine.tb_acquire([3], [2], [10], 0)       # drain all 10 at t=0
    assert out["allowed"][0]
    out = engine.tb_acquire([3], [2], [10], 5)       # 5 ms later: ~0 tokens
    assert not out["allowed"][0]


def test_stream_ids_tail_padding():
    """Stream length not a multiple of k*b: tail decided correctly."""
    cfg = RateLimitConfig(max_permits=2, window_ms=1000,
                          enable_local_cache=False)
    clock = lambda: 5_500  # noqa: E731
    s = TpuBatchedStorage(num_slots=32, clock_ms=clock)
    lid = s.register_limiter("sw", cfg)
    key_ids = np.zeros(7, dtype=np.int64)  # same key 7x, limit 2
    got = s.acquire_stream_ids("sw", lid, key_ids, None, batch=4, subbatches=2)
    s.close()
    assert got.tolist() == [True, True, False, False, False, False, False]
