"""Host-parallel partitioned slot index (engine/partitioned.py).

Decision equivalence vs the single-LRU native index under ample
capacity, the scalar/vector interface contract, and checkpoint
round-trips with the geometry guards.
"""

import numpy as np
import pytest

from ratelimiter_tpu.core.config import RateLimitConfig
from ratelimiter_tpu.engine.native_index import native_available
from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native index unavailable")


def test_auto_host_parallel_election(monkeypatch):
    """r7: TpuBatchedStorage auto-elects host_parallel=min(cores, 8)
    for large single-device tables; explicit kwargs always win; small
    tables, few cores, and checkpointable deployments stay single-LRU."""
    import ratelimiter_tpu.storage.tpu as tpu_mod
    from ratelimiter_tpu.engine.partitioned import PartitionedSlotIndex

    def with_cores(n):
        monkeypatch.setattr(tpu_mod.os, "sched_getaffinity",
                            lambda pid: set(range(n)), raising=False)

    with_cores(6)
    st = TpuBatchedStorage(num_slots=1 << 16)
    # 6 does not divide 2^16: the election walks down to 4 partitions.
    assert st._host_parallel == 4
    assert isinstance(st._index["tb"], PartitionedSlotIndex)
    st.close()
    # Explicit kwarg wins — both directions.
    st = TpuBatchedStorage(num_slots=1 << 16, host_parallel=0)
    assert st._host_parallel == 0
    st.close()
    st = TpuBatchedStorage(num_slots=1 << 16, host_parallel=2)
    assert st._host_parallel == 2
    assert st._index["tb"].n_parts == 2
    st.close()
    # Cores capped at 8; non-dividing counts walk down.
    with_cores(64)
    st = TpuBatchedStorage(num_slots=1 << 16)
    assert st._host_parallel == 8
    st.close()
    # Small tables and <= 2 cores stay single-LRU.
    st = TpuBatchedStorage(num_slots=1 << 12)
    assert st._host_parallel == 0
    st.close()
    with_cores(2)
    st = TpuBatchedStorage(num_slots=1 << 16)
    assert st._host_parallel == 0
    st.close()
    # Checkpointable keeps the enumerable Python index.
    with_cores(6)
    st = TpuBatchedStorage(num_slots=1 << 16, checkpointable=True)
    assert st._host_parallel == 0
    st.close()


def test_partitioned_stream_matches_plain():
    now = [9_000_000]
    st_p = TpuBatchedStorage(num_slots=1 << 12, host_parallel=4,
                             clock_ms=lambda: now[0])
    st_n = TpuBatchedStorage(num_slots=1 << 12, clock_ms=lambda: now[0])
    cfg = RateLimitConfig(max_permits=7, window_ms=1000, refill_rate=5.0)
    lid_p = st_p.register_limiter("tb", cfg)
    lid_n = st_n.register_limiter("tb", cfg)
    from ratelimiter_tpu.engine.partitioned import PartitionedSlotIndex

    assert isinstance(st_p._index["tb"], PartitionedSlotIndex)
    rng = np.random.default_rng(8)
    for rep in range(3):
        ids = rng.integers(0, 200, 900)
        a = st_p.acquire_stream_ids("tb", lid_p, ids, None)
        b = st_n.acquire_stream_ids("tb", lid_n, ids, None)
        np.testing.assert_array_equal(a, b, err_msg=f"rep {rep}")
        now[0] += 411
    st_p.close()
    st_n.close()


def test_partitioned_multi_lid_digest_matches_plain():
    """Multi-tenant digest mode with a partitioned index: the per-unique
    lid lane must be mapped through uidx (partition-major unique order),
    not positionally."""
    now = [9_500_000]
    st_p = TpuBatchedStorage(num_slots=1 << 12, host_parallel=4,
                             clock_ms=lambda: now[0])
    st_n = TpuBatchedStorage(num_slots=1 << 12, clock_ms=lambda: now[0])
    cfgs = [RateLimitConfig(max_permits=3 + i, window_ms=1000,
                            refill_rate=2.0 + i) for i in range(4)]
    lids_p = np.asarray([st_p.register_limiter("tb", c) for c in cfgs])
    lids_n = np.asarray([st_n.register_limiter("tb", c) for c in cfgs])
    rng = np.random.default_rng(17)
    for rep in range(3):
        ids = rng.integers(0, 150, 800)
        tl = rng.integers(0, 4, 800)
        a = st_p.acquire_stream_ids("tb", lids_p[tl], ids, None)
        b = st_n.acquire_stream_ids("tb", lids_n[tl], ids, None)
        np.testing.assert_array_equal(a, b, err_msg=f"rep {rep}")
        now[0] += 333
    st_p.close()
    st_n.close()


def test_partitioned_scalar_and_batch_share_namespace():
    from ratelimiter_tpu.engine.partitioned import PartitionedSlotIndex

    ix = PartitionedSlotIndex(1 << 10, 4)
    s1, _ = ix.assign((3, 42))
    slots, _ = ix.assign_batch_ints(np.asarray([42, 42, 7]), 3)
    assert slots[0] == s1 and slots[1] == s1 and slots[2] != s1
    assert ix.get((3, 7)) == slots[2]
    assert len(ix) == 2
    assert ix.remove((3, 42)) == s1
    assert ix.get((3, 42)) is None
    uw, uidx, rank, _ = ix.assign_batch_ints_uniques(
        np.asarray([7, 7, 42]), 3, 8)
    assert len(uw) == 2
    np.testing.assert_array_equal(rank, [0, 1, 0])
    # Word slot fields must be the GLOBAL slots; uniques may merge in
    # partition order, so map through uidx rather than positionally.
    got_slots = (uw >> np.uint32(9)).astype(np.int64)
    assert got_slots[uidx[0]] == ix.get((3, 7))
    assert got_slots[uidx[2]] == ix.get((3, 42))
    assert uidx[0] == uidx[1] != uidx[2]
    ix.close()


def test_partitioned_export_into_flat_native():
    """export_keys from a host-partitioned storage produces the flat 'fp'
    payload (global slots), importable into a flat native target that
    then continues with identical decisions."""
    now = [6_000_000]
    st_p = TpuBatchedStorage(num_slots=1 << 10, host_parallel=2,
                             clock_ms=lambda: now[0])
    cfg = RateLimitConfig(max_permits=4, window_ms=1000, refill_rate=3.0)
    lid = st_p.register_limiter("tb", cfg)
    rng = np.random.default_rng(12)
    ids = rng.integers(0, 120, 500)
    st_p.acquire_stream_ids("tb", lid, ids, None)
    dump = st_p.export_keys()
    assert dump["algos"]["tb"]["kind"] == "fp"

    st_f = TpuBatchedStorage(num_slots=1 << 11, clock_ms=lambda: now[0])
    lid_f = st_f.register_limiter("tb", cfg)
    assert lid_f == lid
    st_f.import_keys(dump)
    now[0] += 77
    ids2 = rng.integers(0, 120, 500)
    a = st_p.acquire_stream_ids("tb", lid, ids2, None)
    b = st_f.acquire_stream_ids("tb", lid_f, ids2, None)
    np.testing.assert_array_equal(a, b)
    st_p.close()
    st_f.close()


def test_partitioned_checkpoint_round_trip(tmp_path):
    now = [4_000_000]
    st = TpuBatchedStorage(num_slots=1 << 10, host_parallel=2,
                           clock_ms=lambda: now[0])
    cfg = RateLimitConfig(max_permits=5, window_ms=1000, refill_rate=2.0)
    lid = st.register_limiter("tb", cfg)
    rng = np.random.default_rng(3)
    ids = rng.integers(0, 100, 400)
    st.acquire_stream_ids("tb", lid, ids, None)
    path = str(tmp_path / "ckpt")
    st.save_checkpoint(path)

    # Same-geometry restore continues identically to the original.
    st2 = TpuBatchedStorage(num_slots=1 << 10, host_parallel=2,
                            table=st.table, clock_ms=lambda: now[0])
    st2.restore_checkpoint(path)
    now[0] += 100
    ids2 = rng.integers(0, 100, 400)
    a = st.acquire_stream_ids("tb", lid, ids2, None)
    b = st2.acquire_stream_ids("tb", lid, ids2, None)
    np.testing.assert_array_equal(a, b)

    # Geometry mismatches are refused, not silently orphaned.
    st3 = TpuBatchedStorage(num_slots=1 << 10, host_parallel=4,
                            table=st.table, clock_ms=lambda: now[0])
    with pytest.raises(ValueError, match="partition"):
        st3.restore_checkpoint(path)
    st4 = TpuBatchedStorage(num_slots=1 << 10, table=st.table,
                            clock_ms=lambda: now[0])
    with pytest.raises(ValueError, match="partition"):
        st4.restore_checkpoint(path)
    # ...and a flat fingerprint dump cannot enter a partitioned index.
    path2 = str(tmp_path / "ckpt_flat")
    st4.acquire_stream_ids("tb", lid, ids, None)
    st4.save_checkpoint(path2)
    with pytest.raises(ValueError, match="host-partitioned"):
        st2.restore_checkpoint(path2)
    for s in (st, st2, st3, st4):
        s.close()


def test_partial_failure_releases_sibling_pins():
    """One partition exhausting capacity mid-batch must release the pins
    the other (successful) partitions took — their results never reach
    the caller, so nothing else could unpin them."""
    import numpy as np
    import pytest

    from ratelimiter_tpu.engine.partitioned import (
        PartitionedSlotIndex,
        _part_of_int_keys,
    )

    ix = PartitionedSlotIndex(4, n_parts=2)  # 2 slots per partition
    keys = np.arange(64, dtype=np.int64)
    part = _part_of_int_keys(keys, 2)
    p0 = keys[part == 0]
    p1 = keys[part == 1]
    # Fill partition 0 and pin both its slots (as in-flight windows).
    for k in p0[:2]:
        ix.assign((0, int(k)), hold_pin=True)
    # Mixed batch: a fresh partition-0 key must fail (-2, all pinned),
    # while partition-1 keys succeed and get pinned.
    batch = np.asarray([int(p0[2]), int(p1[0]), int(p1[1])], dtype=np.int64)
    with pytest.raises(RuntimeError):
        ix.assign_batch_ints(batch, lid=0, hold_pins=True)
    # Partition 1's pins must be gone: both its slots evictable again.
    s1, ev1 = ix.assign((0, int(p1[2])))
    s2, ev2 = ix.assign((0, int(p1[3])))
    assert {s1, s2} == {2, 3}  # both partition-1 slots reachable
    ix.close()
