"""Sidecar ingress hardening under network fault injection.

The fast drill (`storage/chaos.py:ingress_drill`) is the acceptance
surface: under malformed-frame, slowloris, garbage, and kill-mid-pipeline
faults the server stays up, healthy clients' decisions stay bit-identical
to ``semantics/oracle.py``, shed frames carry the typed retry-after
status, and handler threads / batcher futures / queue depth return to
baseline.  The slow soak drives 8 pipelining clients against sustained
faults for ~30 s (RUN_SLOW=1 via verify.sh).
"""

import threading
import time

import pytest

from ratelimiter_tpu import RateLimitConfig
from ratelimiter_tpu.metrics import MeterRegistry
from ratelimiter_tpu.service import sidecar as sc
from ratelimiter_tpu.storage import FaultInjectingProxy, TpuBatchedStorage
from ratelimiter_tpu.storage.chaos import ingress_drill

T0 = 1_753_000_000_000


def test_ingress_drill_fast():
    registry = MeterRegistry()
    report = ingress_drill(registry=registry)
    assert report["mismatches"] == 0
    assert set(report["faults"]) == {
        "malformed", "malformed_v5_columns", "slowloris", "garbage",
        "kill_mid_pipeline"}
    assert report["shed"] >= 1
    # 5 classic malformed frames + 4 malformed v5 columnar frames, every
    # one answered in-protocol with the stream staying in sync.
    assert report["malformed_answered"] == 9
    scrape = registry.scrape()
    assert scrape["ratelimiter.sidecar.malformed"] >= 9
    assert scrape["ratelimiter.sidecar.idle_closed"] >= 1
    assert scrape["ratelimiter.sidecar.pipeline_shed"] >= 1
    assert scrape["ratelimiter.sidecar.connections"] == 0


def test_fault_proxy_passthrough_is_transparent():
    clock = {"t": T0}
    storage = TpuBatchedStorage(num_slots=256, max_delay_ms=0.2,
                                clock_ms=lambda: clock["t"])
    server = sc.SidecarServer(storage, host="127.0.0.1").start()
    proxy = FaultInjectingProxy(server.port).start()
    try:
        lid = server.register("tb", RateLimitConfig(
            max_permits=50, window_ms=60_000, refill_rate=25.0))
        client = sc.SidecarClient("127.0.0.1", proxy.port)
        assert client.server_version >= 3  # handshake survives the hop
        got = client.acquire_batch(lid, [f"p{i}" for i in range(16)])
        assert all(s == sc.ST_OK and a for s, a, _ in got)
        client.close()
        assert proxy.connections == 1
        assert proxy.faults_injected == 0
    finally:
        proxy.stop()
        server.stop()
        storage.close()


def test_batcher_forget_withdraws_queued_requests():
    """`MicroBatcher.forget` removes still-queued futures (cancelled, out
    of the waiter set, slots unpinned) and leaves dispatched ones alone."""
    from ratelimiter_tpu.engine.batcher import MicroBatcher

    gate = threading.Event()

    def dispatch(slots, lids, permits):
        gate.wait(timeout=5.0)
        return {"allowed": [True] * len(slots)}

    # Huge delay: nothing dispatches until flush is forced.
    batcher = MicroBatcher(dispatch={"sw": dispatch},
                           clear={"sw": lambda s: None},
                           max_batch=1024, max_delay_ms=10_000.0)
    try:
        futs = [batcher.submit("sw", i, 0, 1) for i in range(8)]
        assert batcher.queue_depth() == 8
        dropped = futs[:5]
        assert batcher.forget(dropped) == 5
        assert batcher.abandoned_total == 5
        assert batcher.queue_depth() == 3
        assert batcher.pending_slots("sw") == {5, 6, 7}
        assert all(f.cancelled() for f in dropped)
        gate.set()
        batcher.flush()
        for f in futs[5:]:
            assert f.result(timeout=5.0)["allowed"] is True
        # Nothing left in the stranding-watch set.
        with batcher._cv:
            assert not batcher._waiters
        # Forgetting already-resolved futures is a no-op.
        assert batcher.forget(futs[5:]) == 0
    finally:
        batcher.close()


def test_health_state_machine_includes_sidecar_sheds():
    """The TCP front door participates in the PR 2 health state machine:
    a pipeline shed flips /actuator/health to SHEDDING within the window
    and decays back to UP after it."""
    from ratelimiter_tpu.service.app import health_payload
    from ratelimiter_tpu.service.props import AppProperties
    from ratelimiter_tpu.service.wiring import build_app

    clock = {"t": T0}
    storage = TpuBatchedStorage(num_slots=256, max_delay_ms=0.2,
                                clock_ms=lambda: clock["t"])
    props = AppProperties({
        "ratelimiter.overload.shed_health_window_ms": "400"})
    ctx = build_app(props, storage=storage)
    server = sc.SidecarServer(storage, host="127.0.0.1",
                              max_pipeline=4).start()
    ctx.sidecar = server
    try:
        lid = server.register("tb", RateLimitConfig(
            max_permits=100, window_ms=60_000, refill_rate=50.0))
        assert health_payload(ctx)["status"] == "UP"
        client = sc.SidecarClient("127.0.0.1", server.port)
        got = client.acquire_batch(lid, [f"h{i}" for i in range(16)])
        assert any(s == sc.ST_SHED for s, _, _ in got)
        payload = health_payload(ctx)
        assert payload["status"] == "SHEDDING"
        assert payload["sidecar"]["pipeline_shed_total"] >= 1
        time.sleep(0.6)  # outlive the 400 ms shed window
        assert health_payload(ctx)["status"] == "UP"
        client.close()
    finally:
        server.stop()
        ctx.close()


def test_wiring_starts_sidecar_from_props():
    from ratelimiter_tpu.service.app import health_payload
    from ratelimiter_tpu.service.props import AppProperties
    from ratelimiter_tpu.service.wiring import build_app

    ctx = build_app(AppProperties({
        "storage.num_slots": "256",
        "warmup.enabled": "false",
        "link.probe.enabled": "false",
        "ratelimiter.sidecar.enabled": "true",
        "ratelimiter.sidecar.port": "0",   # ephemeral
    }))
    try:
        assert ctx.sidecar is not None
        client = sc.SidecarClient("127.0.0.1", ctx.sidecar.port)
        assert client.server_version >= 3
        assert client.ping()
        client.close()
        assert "sidecar" in health_payload(ctx)
    finally:
        ctx.close()


@pytest.mark.slow
def test_ingress_soak_slow():
    """30 s soak: 8 pipelining clients sustain decisions while chaos
    clients hammer the proxy with cycling faults.  Healthy traffic never
    sees a non-OK status; everything drains to baseline at the end."""
    duration_s = 30.0
    n_clients = 8
    pipeline = 32
    storage = TpuBatchedStorage(num_slots=1 << 12, max_delay_ms=0.3,
                                max_inflight=1)
    server = sc.SidecarServer(
        storage, host="127.0.0.1",
        max_frame_bytes=512, max_key_bytes=64, max_pipeline=256,
        idle_timeout_ms=5_000.0, read_timeout_ms=500.0).start()
    proxy = FaultInjectingProxy(server.port, seed=3).start()
    stop = threading.Event()
    errors: list = []
    decisions = [0] * n_clients
    try:
        lid = server.register("tb", RateLimitConfig(
            max_permits=1_000_000, window_ms=60_000, refill_rate=1e6))
        lid_atk = server.register("tb", RateLimitConfig(
            max_permits=1000, window_ms=60_000, refill_rate=100.0))

        def healthy_loop(i: int) -> None:
            try:
                client = sc.SidecarClient("127.0.0.1", server.port)
                r = 0
                while not stop.is_set():
                    keys = [f"c{i}-k{(r * pipeline + j) % 512}"
                            for j in range(pipeline)]
                    got = client.acquire_batch(lid, keys)
                    for s, _, _ in got:
                        assert s == sc.ST_OK, f"healthy client saw {s}"
                    decisions[i] += len(got)
                    r += 1
                client.close()
            except Exception as exc:  # noqa: BLE001 — collected below
                errors.append((i, repr(exc)))

        def chaos_loop() -> None:
            faults = ["kill", "garbage", "truncate", None]
            k = 0
            while not stop.is_set():
                mode = faults[k % len(faults)]
                if mode == "kill":
                    proxy.set_fault("kill", after=100 + 40 * (k % 5))
                elif mode == "garbage":
                    proxy.set_fault("garbage", after=13 + 7 * (k % 9),
                                    n=32)
                elif mode == "truncate":
                    proxy.set_fault("truncate", after=9 + 5 * (k % 7))
                else:
                    proxy.set_fault(None)
                k += 1
                try:
                    atk = sc.SidecarClient("127.0.0.1", proxy.port,
                                           timeout=2.0, protocol=1)
                    atk.acquire_batch(lid_atk,
                                      [f"a{j}" for j in range(24)])
                    atk.close()
                except Exception:  # noqa: BLE001 — faults SHOULD break it
                    pass
                time.sleep(0.02)

        threads = [threading.Thread(target=healthy_loop, args=(i,),
                                    daemon=True)
                   for i in range(n_clients)]
        threads += [threading.Thread(target=chaos_loop, daemon=True)
                    for _ in range(2)]
        for t in threads:
            t.start()
        time.sleep(duration_s)
        stop.set()
        for t in threads:
            t.join(timeout=15.0)

        assert not errors, f"healthy clients failed: {errors[:5]}"
        assert sum(decisions) > 0
        # Everything returns to baseline: no wedged handlers, no leaked
        # futures, queue drained, server still answering.
        batcher = storage._batcher
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            with batcher._cv:
                waiters = len(batcher._waiters)
            if waiters == 0 and batcher.queue_depth() == 0 \
                    and server.inflight() == 0:
                break
            time.sleep(0.1)
        with batcher._cv:
            assert not batcher._waiters, "batcher futures leaked"
        assert batcher.queue_depth() == 0
        assert server.inflight() == 0
        probe = sc.SidecarClient("127.0.0.1", server.port)
        assert probe.ping()
        probe.close()
    finally:
        stop.set()
        proxy.stop()
        server.stop()
        storage.close()


# ---------------------------------------------------------------------------
# Partition / flap primitives (the orchestrator drills build on these)
# ---------------------------------------------------------------------------

def _echo_server():
    import socketserver

    class Echo(socketserver.BaseRequestHandler):
        def handle(self):
            while True:
                try:
                    data = self.request.recv(64)
                except OSError:
                    return
                if not data:
                    return
                try:
                    self.request.sendall(data)
                except OSError:
                    return

    class Server(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    server = Server(("127.0.0.1", 0), Echo)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server


def test_proxy_partition_drops_both_directions_without_rst():
    """partition(): bytes vanish in BOTH directions, but neither socket
    is closed — the peer looks silently gone (recv blocks to timeout,
    send succeeds into the void), exactly the no-RST network-partition
    shape.  heal() restores the SAME connection."""
    import socket

    echo = _echo_server()
    proxy = FaultInjectingProxy(echo.server_address[1]).start()
    try:
        conn = socket.create_connection(("127.0.0.1", proxy.port),
                                        timeout=2.0)
        conn.sendall(b"ping")
        assert conn.recv(16) == b"ping"

        proxy.partition()
        conn.settimeout(0.3)
        conn.sendall(b"lost")            # send succeeds: no RST came back
        with pytest.raises(socket.timeout):
            conn.recv(16)                # ...but nothing ever returns
        assert proxy.faults_injected >= 1

        proxy.heal()                     # same connection, live again
        conn.settimeout(2.0)
        conn.sendall(b"back")
        assert conn.recv(16) == b"back"
        conn.close()
    finally:
        proxy.stop()
        echo.shutdown()
        echo.server_close()


def test_proxy_flap_alternates_partition_and_passthrough():
    """flap(period_s): the link alternates healthy/partitioned every
    half period — the flaky-link shape the orchestrator's hysteresis
    must damp.  Sampled across several periods, both phases must be
    observed on one connection."""
    import socket

    echo = _echo_server()
    proxy = FaultInjectingProxy(echo.server_address[1]).start()
    try:
        period = 0.4
        proxy.flap(period)
        conn = socket.create_connection(("127.0.0.1", proxy.port),
                                        timeout=2.0)
        conn.settimeout(0.15)
        ok = cut = 0
        deadline = time.monotonic() + 4 * period
        while time.monotonic() < deadline and not (ok and cut):
            try:
                conn.sendall(b"x")
                if conn.recv(16):
                    ok += 1
                else:
                    break
            except socket.timeout:
                cut += 1
            time.sleep(period / 8)
        assert ok >= 1, "flap never let a byte through"
        assert cut >= 1, "flap never cut the link"
        proxy.heal()
        conn.close()
    finally:
        proxy.stop()
        echo.shutdown()
        echo.server_close()
