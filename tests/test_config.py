"""RateLimitConfig validation + factories (core/RateLimitConfig.java:44-80)."""

from datetime import timedelta

import pytest

from ratelimiter_tpu import RateLimitConfig
from ratelimiter_tpu.core.config import TOKEN_FP_ONE, TOKEN_FP_SHIFT


def test_factories():
    assert RateLimitConfig.per_second(5).window_ms == 1_000
    assert RateLimitConfig.per_minute(100).window_ms == 60_000
    assert RateLimitConfig.per_hour(1000).window_ms == 3_600_000
    assert RateLimitConfig.per_minute(100).max_permits == 100


def test_defaults():
    cfg = RateLimitConfig.per_minute(100)
    assert cfg.refill_rate == 0.0
    assert cfg.enable_local_cache is True
    assert cfg.local_cache_ttl_ms == 100


def test_timedelta_windows():
    cfg = RateLimitConfig(max_permits=10, window_ms=timedelta(seconds=30))
    assert cfg.window_ms == 30_000
    cfg = RateLimitConfig(max_permits=10, window_ms=60_000,
                          local_cache_ttl_ms=timedelta(milliseconds=250))
    assert cfg.local_cache_ttl_ms == 250


@pytest.mark.parametrize(
    "kwargs",
    [
        dict(max_permits=0, window_ms=1000),
        dict(max_permits=-1, window_ms=1000),
        dict(max_permits=10, window_ms=0),
        dict(max_permits=10, window_ms=-5),
        dict(max_permits=10, window_ms=1000, refill_rate=-1.0),
    ],
)
def test_validate_rejects(kwargs):
    with pytest.raises(ValueError):
        RateLimitConfig(**kwargs).validate()


def test_fixed_point_rate():
    cfg = RateLimitConfig(max_permits=50, window_ms=60_000, refill_rate=10.0)
    # Rate in fp units per ms: exact for integral rates since TOKEN_FP_ONE
    # carries the ms factor 1000.
    assert cfg.refill_rate_fp == 10 << TOKEN_FP_SHIFT
    assert cfg.max_permits_fp == 50 * TOKEN_FP_ONE
    # Consistency: refilling for exactly 1 second yields exactly the rate.
    assert 1000 * cfg.refill_rate_fp == 10 * TOKEN_FP_ONE
