"""Sidecar protocol: pipelined TCP decisions against the device engine."""

import threading

import numpy as np
import pytest

from ratelimiter_tpu import RateLimitConfig
from ratelimiter_tpu.semantics import SlidingWindowOracle, TokenBucketOracle
from ratelimiter_tpu.service.sidecar import SidecarClient, SidecarServer
from ratelimiter_tpu.storage import TpuBatchedStorage

T0 = 1_753_000_000_000


class FakeClock:
    def __init__(self, t=T0):
        self.t = t

    def __call__(self):
        return self.t


@pytest.fixture()
def sidecar():
    clock = FakeClock()
    storage = TpuBatchedStorage(num_slots=512, max_delay_ms=0.2, clock_ms=clock)
    server = SidecarServer(storage, host="127.0.0.1").start()
    yield server, clock
    server.stop()
    storage.close()


def test_ping_and_basic_acquire(sidecar):
    server, clock = sidecar
    lid = server.register("sw", RateLimitConfig(
        max_permits=3, window_ms=60_000, enable_local_cache=False))
    client = SidecarClient("127.0.0.1", server.port)
    assert client.ping()
    clock.t = (T0 // 60_000) * 60_000
    results = [client.try_acquire(lid, "alice") for _ in range(5)]
    assert results == [True, True, True, False, False]
    assert client.available(lid, "alice") == 0
    client.reset(lid, "alice")
    assert client.try_acquire(lid, "alice")
    client.close()


def test_pipelined_batch_matches_oracle(sidecar):
    server, clock = sidecar
    cfg = RateLimitConfig(max_permits=20, window_ms=2000, refill_rate=30.0)
    lid = server.register("tb", cfg)
    oracle = TokenBucketOracle(cfg)
    client = SidecarClient("127.0.0.1", server.port)
    rng = np.random.default_rng(8)
    for step in range(10):
        clock.t += int(rng.integers(0, 500))
        n = int(rng.integers(1, 24))
        keys = [f"u{rng.integers(0, 5)}" for _ in range(n)]
        perms = [int(rng.integers(1, 8)) for _ in range(n)]
        got = client.acquire_batch(lid, keys, perms)
        for j, (status, allowed, _rem) in enumerate(got):
            assert status == 0
            want = oracle.try_acquire(keys[j], perms[j], clock.t).allowed
            assert allowed == want, (step, j)
    client.close()


def test_concurrent_clients_share_one_authority(sidecar):
    server, clock = sidecar
    lid = server.register("sw", RateLimitConfig(
        max_permits=10, window_ms=60_000, enable_local_cache=False))
    clock.t = (T0 // 60_000) * 60_000
    n_clients, per_client = 8, 10
    allowed = np.zeros(n_clients, dtype=np.int64)
    barrier = threading.Barrier(n_clients)

    def worker(i):
        client = SidecarClient("127.0.0.1", server.port)
        barrier.wait()
        for _ in range(per_client):
            if client.try_acquire(lid, "shared"):
                allowed[i] += 1
        client.close()

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # 80 requests across 8 connections -> exactly 10 allowed: all clients
    # funnel into the same device batches.
    assert allowed.sum() == 10


def test_error_paths(sidecar):
    server, _ = sidecar
    client = SidecarClient("127.0.0.1", server.port)
    with pytest.raises(RuntimeError):
        client.try_acquire(9999, "nobody")  # unknown limiter id
    client.close()
