"""Sidecar protocol: pipelined TCP decisions against the device engine."""

import threading

import numpy as np
import pytest

from ratelimiter_tpu import RateLimitConfig
from ratelimiter_tpu.semantics import SlidingWindowOracle, TokenBucketOracle
from ratelimiter_tpu.service.sidecar import SidecarClient, SidecarServer
from ratelimiter_tpu.storage import TpuBatchedStorage

T0 = 1_753_000_000_000


class FakeClock:
    def __init__(self, t=T0):
        self.t = t

    def __call__(self):
        return self.t


@pytest.fixture()
def sidecar():
    clock = FakeClock()
    storage = TpuBatchedStorage(num_slots=512, max_delay_ms=0.2, clock_ms=clock)
    server = SidecarServer(storage, host="127.0.0.1").start()
    yield server, clock
    server.stop()
    storage.close()


def test_ping_and_basic_acquire(sidecar):
    server, clock = sidecar
    lid = server.register("sw", RateLimitConfig(
        max_permits=3, window_ms=60_000, enable_local_cache=False))
    client = SidecarClient("127.0.0.1", server.port)
    assert client.ping()
    clock.t = (T0 // 60_000) * 60_000
    results = [client.try_acquire(lid, "alice") for _ in range(5)]
    assert results == [True, True, True, False, False]
    assert client.available(lid, "alice") == 0
    client.reset(lid, "alice")
    assert client.try_acquire(lid, "alice")
    client.close()


def test_pipelined_batch_matches_oracle(sidecar):
    server, clock = sidecar
    cfg = RateLimitConfig(max_permits=20, window_ms=2000, refill_rate=30.0)
    lid = server.register("tb", cfg)
    oracle = TokenBucketOracle(cfg)
    client = SidecarClient("127.0.0.1", server.port)
    rng = np.random.default_rng(8)
    for step in range(10):
        clock.t += int(rng.integers(0, 500))
        n = int(rng.integers(1, 24))
        keys = [f"u{rng.integers(0, 5)}" for _ in range(n)]
        perms = [int(rng.integers(1, 8)) for _ in range(n)]
        got = client.acquire_batch(lid, keys, perms)
        for j, (status, allowed, _rem) in enumerate(got):
            assert status == 0
            want = oracle.try_acquire(keys[j], perms[j], clock.t).allowed
            assert allowed == want, (step, j)
    client.close()


def test_concurrent_clients_share_one_authority(sidecar):
    server, clock = sidecar
    lid = server.register("sw", RateLimitConfig(
        max_permits=10, window_ms=60_000, enable_local_cache=False))
    clock.t = (T0 // 60_000) * 60_000
    n_clients, per_client = 8, 10
    allowed = np.zeros(n_clients, dtype=np.int64)
    barrier = threading.Barrier(n_clients)

    def worker(i):
        client = SidecarClient("127.0.0.1", server.port)
        barrier.wait()
        for _ in range(per_client):
            if client.try_acquire(lid, "shared"):
                allowed[i] += 1
        client.close()

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # 80 requests across 8 connections -> exactly 10 allowed: all clients
    # funnel into the same device batches.
    assert allowed.sum() == 10


def test_error_paths(sidecar):
    server, _ = sidecar
    client = SidecarClient("127.0.0.1", server.port)
    with pytest.raises(RuntimeError):
        client.try_acquire(9999, "nobody")  # unknown limiter id
    client.close()


# ---------------------------------------------------------------------------
# Protocol v2/v3: handshake, downgrade, edge frames (answered in-protocol)
# ---------------------------------------------------------------------------

def test_v3_handshake_negotiates(sidecar):
    from ratelimiter_tpu.service import sidecar as sc

    server, _ = sidecar
    client = SidecarClient("127.0.0.1", server.port)
    assert client.server_version == sc.PROTOCOL_VERSION
    # A v3-pinned client negotiates exactly v3 (no v4 frame extension).
    pinned = SidecarClient("127.0.0.1", server.port, protocol=3)
    assert pinned.server_version == 3
    pinned.close()
    assert client.server_max_frame == server.max_frame_bytes
    client.close()


def test_v2_client_negotiates_down_and_never_sees_lease_ops(sidecar):
    """min(client, server): a v2 HELLO stays on v2, and the v3 lease ops
    are unknown ops on that connection — answered BAD_FRAME, never a
    lease status — even with a lease manager attached."""
    from ratelimiter_tpu.leases import LeaseManager
    from ratelimiter_tpu.service import sidecar as sc

    server, _ = sidecar
    lid = server.register("tb", RateLimitConfig(
        max_permits=100, window_ms=60_000, refill_rate=50.0))
    server.attach_leases(LeaseManager(server.storage))
    client = SidecarClient("127.0.0.1", server.port, protocol=2)
    assert client.server_version == 2
    for op in (sc.OP_LEASE, sc.OP_RENEW, sc.OP_RELEASE):
        client._send(client._frame(op, lid, 8, "k"))
        status, _, errno = client._read_raw()
        assert (status, errno) == (sc.ST_BAD_FRAME, sc.ERR_UNKNOWN_OP), op
    # ... and the ordinary v2 decision path still serves afterwards.
    assert client.try_acquire(lid, "v2-still-works") is True
    client.close()


def test_unknown_op_on_v3_connection_is_bad_frame(sidecar):
    from ratelimiter_tpu.service import sidecar as sc

    server, _ = sidecar
    lid = server.register("tb", RateLimitConfig(
        max_permits=100, window_ms=60_000, refill_rate=50.0))
    client = SidecarClient("127.0.0.1", server.port, protocol=3)
    assert client.server_version == 3
    client._send(client._frame(42, lid, 0, "k"))
    status, _, errno = client._read_raw()
    assert (status, errno) == (sc.ST_BAD_FRAME, sc.ERR_UNKNOWN_OP)
    assert client.try_acquire(lid, "after-unknown-op") is True
    client.close()


def test_lease_ops_without_manager_answer_disabled(sidecar):
    from ratelimiter_tpu.service import sidecar as sc

    server, _ = sidecar
    lid = server.register("tb", RateLimitConfig(
        max_permits=100, window_ms=60_000, refill_rate=50.0))
    client = SidecarClient("127.0.0.1", server.port)
    client._send(client._frame(sc.OP_LEASE, lid, 8, "k", ext=0))
    status, _, errno = client._read_raw()
    assert (status, errno) == (sc.ST_ERROR, sc.ERR_LEASE_DISABLED)
    client.close()


def test_lease_wire_round_trip_and_local_burn(sidecar):
    """Full v3 lease cycle over TCP: grant -> local burns -> renew ->
    release, with the decision stream matching a per-decision oracle
    replay of the charges."""
    from ratelimiter_tpu.leases import LeaseClient, LeaseManager

    server, clock = sidecar
    cfg = RateLimitConfig(max_permits=500, window_ms=60_000,
                          refill_rate=100.0)
    lid = server.register("tb", cfg)
    mgr = LeaseManager(server.storage, default_budget=16, ttl_ms=10_000.0,
                       clock_ms=lambda: clock.t)
    server.attach_leases(mgr)
    wire = SidecarClient("127.0.0.1", server.port)
    cli = LeaseClient(wire, lid, budget=16, clock_ms=lambda: clock.t,
                      direct_fallback=False)
    allowed = sum(1 for _ in range(100) if cli.try_acquire("leased-key"))
    assert allowed == 100
    assert cli.wire_ops <= 100 // 10  # >= 10x frame reduction
    cli.release_all()
    assert mgr.table.outstanding() == 0
    # Everything the client burned was pre-charged on the device.
    st = mgr.status()
    assert st["local_decisions"] == 100
    assert st["over_admission"] == 0
    avail = int(server.storage.available_many("tb", lid,
                                              ["leased-key"])[0])
    assert avail == cfg.max_permits - 100
    wire.close()


def test_v1_client_interoperates_unchanged(sidecar):
    """A v1 client (no HELLO) runs the full op set against the v2 server —
    the handshake-downgrade contract."""
    server, clock = sidecar
    lid = server.register("sw", RateLimitConfig(
        max_permits=3, window_ms=60_000, enable_local_cache=False))
    client = SidecarClient("127.0.0.1", server.port, protocol=1)
    assert client.server_version == 1  # never handshook
    assert client.ping()
    clock.t = (T0 // 60_000) * 60_000
    assert [client.try_acquire(lid, "v1user") for _ in range(5)] == \
        [True, True, True, False, False]
    assert client.available(lid, "v1user") == 0
    client.reset(lid, "v1user")
    assert client.try_acquire(lid, "v1user")
    with pytest.raises(RuntimeError):
        client.try_acquire(9999, "nobody")
    client.close()


def test_edge_frames_answered_in_protocol(sidecar):
    """Zero-length key, max-length key, permits=0, unknown limiter, and a
    malformed frame — all answered in-protocol on ONE connection, which
    keeps working afterwards (no teardown, no handler exception)."""
    import struct

    from ratelimiter_tpu.service import sidecar as sc

    server, _ = sidecar
    lid = server.register("tb", RateLimitConfig(
        max_permits=100, window_ms=60_000, refill_rate=50.0))
    client = SidecarClient("127.0.0.1", server.port)

    # zero-length key: a legal key (one shared bucket).
    assert client.try_acquire(lid, "") is True
    # max-length key: exactly at the bound.
    big = "k" * server.max_key_bytes
    assert client.try_acquire(lid, big) is True
    # one byte over: BAD_FRAME, in-protocol.
    with pytest.raises(RuntimeError):
        client.try_acquire(lid, big + "k")
    # permits=0 clamps to 1 (documented v1 behavior, kept).
    assert client.try_acquire(lid, "zero", permits=0) is True
    # unknown limiter id: typed error, connection lives.
    with pytest.raises(RuntimeError):
        client.try_acquire(9999, "nobody")
    # short frame (length < body header): BAD_FRAME with errno.
    client._send(struct.pack("<I", 3) + b"abc")
    status, _, errno = client._read_raw()
    assert (status, errno) == (sc.ST_BAD_FRAME, sc.ERR_SHORT_FRAME)
    # ... and the connection still decides afterwards.
    assert client.try_acquire(lid, "after-the-storm") is True
    client.close()


def test_oversized_declared_frame_stays_in_sync(sidecar):
    """A frame declaring more than max_frame_bytes is answered BAD_FRAME
    and its payload discarded as it streams — the next frame decides."""
    import struct

    from ratelimiter_tpu.service import sidecar as sc

    server, _ = sidecar
    lid = server.register("tb", RateLimitConfig(
        max_permits=100, window_ms=60_000, refill_rate=50.0))
    client = SidecarClient("127.0.0.1", server.port)
    declared = server.max_frame_bytes + 1000
    client._send(struct.pack("<I", declared) + b"\x00" * declared)
    status, _, errno = client._read_raw()
    assert (status, errno) == (sc.ST_BAD_FRAME, sc.ERR_FRAME_TOO_LONG)
    assert client.try_acquire(lid, "still-alive") is True
    assert server.malformed_total >= 1
    client.close()


def test_graceful_drain_on_stop():
    """stop() drains: new decision frames answer SHUTTING_DOWN (typed for
    v2 clients) instead of a dead socket."""
    from ratelimiter_tpu.service import sidecar as sc

    clock = FakeClock()
    storage = TpuBatchedStorage(num_slots=256, max_delay_ms=0.2,
                                clock_ms=clock)
    server = SidecarServer(storage, host="127.0.0.1",
                           drain_timeout_ms=200.0).start()
    try:
        lid = server.register("tb", RateLimitConfig(
            max_permits=10, window_ms=60_000, refill_rate=5.0))
        client = SidecarClient("127.0.0.1", server.port)
        assert client.try_acquire(lid, "pre-drain") is True
        server._draining = True  # what stop() sets first
        got = client.acquire_batch(lid, ["a", "b"])
        assert all(s == sc.ST_SHUTTING_DOWN for s, _, _ in got)
        assert server.drained_total == 2
        client.close()
    finally:
        server.stop()
        storage.close()


def test_global_connection_limit():
    clock = FakeClock()
    storage = TpuBatchedStorage(num_slots=256, max_delay_ms=0.2,
                                clock_ms=clock)
    server = SidecarServer(storage, host="127.0.0.1",
                           max_connections=2).start()
    try:
        a = SidecarClient("127.0.0.1", server.port)
        b = SidecarClient("127.0.0.1", server.port)
        # The third connection is refused: handshake gets EOF.
        with pytest.raises(ConnectionError):
            SidecarClient("127.0.0.1", server.port, timeout=2.0)
        assert server.refused_total == 1
        assert a.ping() and b.ping()  # accepted conns unaffected
        a.close()
        b.close()
    finally:
        server.stop()
        storage.close()


# ---------------------------------------------------------------------------
# Protocol v5: columnar batch frames (op 10)
# ---------------------------------------------------------------------------

def test_v5_negotiation_and_v4_batch_rejected(sidecar):
    """The ceiling is v5; a v4-pinned connection negotiates v4 and the
    batch op does not exist there — same unknown-op answer a v4 server
    gives, with the per-request path untouched afterwards."""
    from ratelimiter_tpu.service import sidecar as sc

    server, _ = sidecar
    lid = server.register("tb", RateLimitConfig(
        max_permits=100, window_ms=60_000, refill_rate=50.0))
    cli = SidecarClient("127.0.0.1", server.port)
    assert cli.server_version == sc.PROTOCOL_VERSION
    pinned = SidecarClient("127.0.0.1", server.port, protocol=4)
    assert pinned.server_version == 4
    pinned._send(pinned._frame(sc.OP_BATCH, lid, 2, "xx"))
    status, _, errno = pinned._read_raw()
    assert (status, errno) == (sc.ST_BAD_FRAME, sc.ERR_UNKNOWN_OP)
    assert pinned.try_acquire(lid, "v4-after-batch") is True
    pinned.close()
    cli.close()


def test_acquire_block_matches_per_request_decisions(sidecar):
    """One columnar frame must decide exactly like N per-request frames
    on the same traffic (mirrored limiter = mirrored keyspace), permits
    column included, across deny/allow interleavings."""
    server, _ = sidecar
    cfg = RateLimitConfig(max_permits=7, window_ms=60_000, refill_rate=0.0)
    lid_blk = server.register("tb", cfg)
    lid_ref = server.register("tb", cfg)
    cli = SidecarClient("127.0.0.1", server.port)
    keys = [f"k{i % 5}" for i in range(40)]
    perms = [(i % 3) + 1 for i in range(40)]
    got = cli.acquire_block(lid_blk, keys, permits=perms)
    ref = [a for _, a, _ in cli.acquire_batch(lid_ref, keys, permits=perms)]
    assert got == ref
    assert True in got and False in got  # both outcomes exercised
    # Unweighted, chunked (>16 rows forces multiple columnar frames).
    got = cli.acquire_block(lid_blk, keys)
    ref = [a for _, a, _ in cli.acquire_batch(lid_ref, keys)]
    assert got == ref
    cli.close()


def test_v5_malformed_columns_answered_in_protocol(sidecar):
    """Column lies (length mismatch, offsets out of bounds, rows over
    the cap) answer BAD_FRAME with typed errnos; the stream stays in
    sync and a valid batch directly behind still decides."""
    import struct

    from ratelimiter_tpu.service import sidecar as sc

    server, _ = sidecar
    lid = server.register("tb", RateLimitConfig(
        max_permits=100, window_ms=60_000, refill_rate=50.0))
    cli = SidecarClient("127.0.0.1", server.port)

    def raw(rows, klen, key_col, offs, flags, permits=b""):
        payload = (struct.pack("<I", klen) + key_col
                   + np.asarray(offs, dtype=np.uint32).tobytes()
                   + bytes([flags]) + permits)
        body = struct.pack("<BIIQ", sc.OP_BATCH, lid, rows, 0) + payload
        return struct.pack("<I", len(body)) + body

    cap = server.max_pipeline
    bad = [
        raw(2, 4, b"abcd", [0, 2, 4], 1),        # permits col missing
        raw(2, 4, b"abcd", [0, 2, 9], 0),        # offsets past the column
        raw(2, 4, b"abcd", [4, 2, 4], 0),        # offs[0] != 0
        raw(cap + 1, 4, b"abcd", [0] * (cap + 2), 0),  # rows over cap
        raw(2, 2, b"\xff\xfe", [0, 1, 2], 0),    # invalid UTF-8 column
    ]
    cli._send(b"".join(bad))
    got = cli._read_responses(len(bad))
    assert [s for s, _, _ in got] == [sc.ST_BAD_FRAME] * 5
    assert [e for _, _, e in got] == [
        sc.ERR_SHORT_FRAME, sc.ERR_BAD_COLUMN, sc.ERR_BAD_COLUMN,
        sc.ERR_FRAME_TOO_LONG, sc.ERR_BAD_KEY]
    assert cli.acquire_block(lid, ["ok-a", "ok-b"]) == [True, True]
    cli.close()


def test_v5_block_unknown_limiter_and_shed_raise(sidecar):
    from ratelimiter_tpu.service import sidecar as sc

    server, _ = sidecar
    cli = SidecarClient("127.0.0.1", server.port)
    with pytest.raises(RuntimeError):
        cli.acquire_block(9999, ["a", "b"])
    lid = server.register("tb", RateLimitConfig(
        max_permits=100, window_ms=60_000, refill_rate=50.0))
    assert cli.acquire_block(lid, ["after-error"]) == [True]
    del sc
    cli.close()


def test_lease_client_batched_submit(sidecar):
    """LeaseClient.try_acquire_many burns locally where leases cover and
    coalesces fallback decisions into columnar frames — decisions equal
    the per-key surface, with strictly fewer wire frames."""
    from ratelimiter_tpu.leases import LeaseClient, LeaseManager

    server, _ = sidecar
    cfg = RateLimitConfig(max_permits=1 << 16, window_ms=60_000,
                          refill_rate=1e5)
    lid_a = server.register("tb", cfg)
    lid_b = server.register("tb", cfg)
    server.attach_leases(LeaseManager(server.storage, default_budget=32,
                                      max_budget=32, ttl_ms=60_000.0))
    wire_a = SidecarClient("127.0.0.1", server.port)
    wire_b = SidecarClient("127.0.0.1", server.port)
    batched = LeaseClient(wire_a, lid_a, budget=32, telemetry=False)
    serial = LeaseClient(wire_b, lid_b, budget=32, telemetry=False)
    keys = [f"u{i % 6}" for i in range(192)]
    got = batched.try_acquire_many(keys)
    ref = [serial.try_acquire(k) for k in keys]
    assert got == ref
    assert batched.local_decisions > 0
    assert batched.wire_ops <= serial.wire_ops
    batched.release_all()
    serial.release_all()
    wire_a.close()
    wire_b.close()
