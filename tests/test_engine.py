"""Device engine vs oracle: the central differential test.

Random multi-step streams — duplicate-heavy batches, multi-tenant mixes,
resets, peeks, window rollovers, bucket expiry — applied both to the batched
device engine and, request by request (in batch order, at the batch's shared
timestamp), to the pure-Python oracle.  Every decision and observable must
match exactly.
"""

import random

import numpy as np
import pytest

from ratelimiter_tpu import RateLimitConfig
from ratelimiter_tpu.engine.engine import DeviceEngine
from ratelimiter_tpu.engine.state import LimiterTable
from ratelimiter_tpu.semantics import SlidingWindowOracle, TokenBucketOracle

T0 = 1_753_000_000_000


class SlotMap:
    """Test-side key -> slot assignment."""

    def __init__(self):
        self.slots = {}

    def get(self, key):
        if key not in self.slots:
            self.slots[key] = len(self.slots)
        return self.slots[key]


def run_sw_differential(configs, key_space, steps, batch_range, seed, permit_hi=3):
    rng = random.Random(seed)
    table = LimiterTable()
    lids = [table.register(c) for c in configs]
    oracles = [SlidingWindowOracle(c) for c in configs]
    engine = DeviceEngine(num_slots=4096, table=table)
    smap = SlotMap()
    now = T0
    for step in range(steps):
        now += rng.randrange(0, 800)
        if rng.random() < 0.05:
            # Reset a random key across all tenants.
            key = f"k{rng.randrange(key_space)}"
            for li, oracle in zip(lids, oracles):
                oracle.reset(key, now)
                engine.sw_clear([smap.get((li, key))])
            continue
        n = rng.randrange(*batch_range)
        keys = [f"k{rng.randrange(key_space)}" for _ in range(n)]
        which = [rng.randrange(len(lids)) for _ in range(n)]
        permits = [rng.randrange(1, permit_hi) for _ in range(n)]
        slots = [smap.get((lids[w], k)) for w, k in zip(which, keys)]
        out = engine.sw_acquire(slots, [lids[w] for w in which], permits, now)
        for j in range(n):
            d = oracles[which[j]].try_acquire(keys[j], permits[j], now)
            assert out["allowed"][j] == d.allowed, (step, j, keys[j], now - T0)
            assert out["mutated"][j] == d.mutated, (step, j)
            assert out["observed"][j] == d.observed, (step, j, out["observed"][j], d.observed)
        # Spot-check availability (read-only) for a few keys.
        for _ in range(3):
            w = rng.randrange(len(lids))
            key = f"k{rng.randrange(key_space)}"
            got = engine.sw_available([smap.get((lids[w], key))], [lids[w]], now)[0]
            assert got == oracles[w].get_available_permits(key, now)


def run_tb_differential(configs, key_space, steps, batch_range, seed):
    rng = random.Random(seed)
    table = LimiterTable()
    lids = [table.register(c) for c in configs]
    oracles = [TokenBucketOracle(c) for c in configs]
    engine = DeviceEngine(num_slots=4096, table=table)
    smap = SlotMap()
    now = T0
    for step in range(steps):
        now += rng.randrange(0, 800)
        if rng.random() < 0.05:
            key = f"k{rng.randrange(key_space)}"
            for li, oracle in zip(lids, oracles):
                oracle.reset(key, now)
                engine.tb_clear([smap.get((li, key))])
            continue
        n = rng.randrange(*batch_range)
        keys = [f"k{rng.randrange(key_space)}" for _ in range(n)]
        which = [rng.randrange(len(lids)) for _ in range(n)]
        permits = [rng.randrange(1, configs[w].max_permits + 3)
                   for w in which]  # sometimes above capacity
        slots = [smap.get((lids[w], k)) for w, k in zip(which, keys)]
        out = engine.tb_acquire(slots, [lids[w] for w in which], permits, now)
        for j in range(n):
            d = oracles[which[j]].try_acquire(keys[j], permits[j], now)
            assert out["allowed"][j] == d.allowed, (step, j, keys[j], permits[j], now - T0)
            assert out["observed"][j] == d.observed, (step, j)
            assert out["remaining"][j] == d.remaining_hint, (step, j)
        for _ in range(3):
            w = rng.randrange(len(lids))
            key = f"k{rng.randrange(key_space)}"
            got = engine.tb_available([smap.get((lids[w], key))], [lids[w]], now)[0]
            assert got == oracles[w].get_available_permits(key, now)


@pytest.mark.parametrize("seed", [0, 1])
def test_sw_differential_small_windows(seed):
    configs = [
        RateLimitConfig(max_permits=8, window_ms=1000, enable_local_cache=False),
        RateLimitConfig(max_permits=30, window_ms=2500, enable_local_cache=False),
    ]
    run_sw_differential(configs, key_space=12, steps=60, batch_range=(1, 48), seed=seed)


def test_sw_differential_duplicate_heavy():
    # Few keys, big batches: most segments are long (the single-hot-key shape).
    configs = [RateLimitConfig(max_permits=50, window_ms=5000, enable_local_cache=False)]
    run_sw_differential(configs, key_space=2, steps=30, batch_range=(32, 120), seed=7)


def test_tb_differential_multi_tenant():
    configs = [
        RateLimitConfig(max_permits=10, window_ms=1000, refill_rate=5.0),
        RateLimitConfig(max_permits=50, window_ms=60_000, refill_rate=10.0),
        RateLimitConfig(max_permits=25, window_ms=3000, refill_rate=97.5),
    ]
    run_tb_differential(configs, key_space=10, steps=60, batch_range=(1, 48), seed=3)


def test_tb_differential_duplicate_heavy():
    configs = [RateLimitConfig(max_permits=20, window_ms=2000, refill_rate=50.0)]
    run_tb_differential(configs, key_space=2, steps=30, batch_range=(32, 120), seed=11)


def test_sw_multi_permit_batch_exact():
    # Deterministic scenario: one slot, batch of mixed permits; expected
    # sequence computed by hand against the quirk semantics.
    cfg = RateLimitConfig(max_permits=5, window_ms=60_000, enable_local_cache=False)
    table = LimiterTable()
    lid = table.register(cfg)
    engine = DeviceEngine(num_slots=16, table=table)
    now = (T0 // 60_000) * 60_000
    # permits: 1,1,1,1,1,1,1 -> increments while est+1 <= 5, i.e. first 5.
    out = engine.sw_acquire([0] * 7, [lid] * 7, [1] * 7, now)
    assert list(out["allowed"]) == [True] * 5 + [False] * 2
    # permits=3 next: est=5, 5+3>5 -> reject without increment.
    out = engine.sw_acquire([0], [lid], [3], now + 1)
    assert not out["allowed"][0] and not out["mutated"][0]


def test_tb_burst_batch_exact():
    cfg = RateLimitConfig(max_permits=10, window_ms=60_000, refill_rate=1.0)
    table = LimiterTable()
    lid = table.register(cfg)
    engine = DeviceEngine(num_slots=16, table=table)
    # One batch: 4+4 allowed (8 consumed), 4 denied (2 left), 2 allowed, 11 pre-rejected.
    out = engine.tb_acquire([0, 0, 0, 0, 0], [lid] * 5, [4, 4, 4, 2, 11], T0)
    assert list(out["allowed"]) == [True, True, False, True, False]
    assert list(out["remaining"]) == [6, 2, 2, 0, 0]


def test_tenant_registration_during_traffic():
    """Registering new limiters while acquire traffic is in flight must
    neither corrupt decisions for existing tenants nor lose the new
    tenant's policy (VERDICT r1 weak #7: tenant churn)."""
    import threading

    import numpy as np

    from ratelimiter_tpu.storage import TpuBatchedStorage

    clock = lambda: 30_000  # noqa: E731
    st = TpuBatchedStorage(num_slots=4096, clock_ms=clock, max_delay_ms=0.1)
    base_cfg = RateLimitConfig(max_permits=10, window_ms=60_000,
                               refill_rate=0.001)
    lid0 = st.register_limiter("tb", base_cfg)

    stop = threading.Event()
    errors = []
    new_lids = []

    def churner():
        # Register 80 tenants (forcing at least one capacity grow) while
        # traffic runs, and verify each new tenant's policy immediately.
        try:
            for i in range(80):
                cap = 3 + (i % 5)
                lid = st.register_limiter("tb", RateLimitConfig(
                    max_permits=cap, window_ms=60_000, refill_rate=0.001))
                got = st.acquire_many_ids(
                    "tb", lid, np.full(cap + 2, 1000 + i, dtype=np.int64),
                    np.ones(cap + 2, dtype=np.int64))["allowed"]
                assert got.tolist() == [True] * cap + [False, False], (i, got)
                new_lids.append(lid)
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)
        finally:
            stop.set()

    def traffic():
        # Existing tenant hammers its own keys; per-key cap must hold.
        try:
            rng = np.random.default_rng(3)
            while not stop.is_set():
                ids = rng.integers(0, 64, 256)
                st.acquire_stream_ids("tb", lid0, ids, None,
                                      batch=128, subbatches=2)
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=traffic) for _ in range(2)]
    churn = threading.Thread(target=churner)
    for t in threads:
        t.start()
    churn.start()
    churn.join(timeout=120)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    assert not errors, errors
    assert len(new_lids) == 80 and len(set(new_lids)) == 80
    # Existing tenant's buckets enforced their cap throughout.
    got = st.acquire_many_ids("tb", lid0, np.arange(64, dtype=np.int64),
                              np.full(64, 10, dtype=np.int64))["allowed"]
    st.close()
    assert not got.any()  # every key already at/over cap => 10 more denied
