"""Fleet telemetry plane (ARCHITECTURE §13e): per-tenant usage ring
exactness, client burn telemetry over the wire (drop-don't-block),
fleet-counter reconciliation, and end-to-end trace lineage with lease
ops interleaved."""

import threading
import time

import pytest

from ratelimiter_tpu.core.config import RateLimitConfig
from ratelimiter_tpu.metrics import MeterRegistry
from ratelimiter_tpu.observability.telemetry import (
    ClientTelemetry,
    TelemetryPlane,
    TraceLineage,
    decode_report,
    default_key_class,
    mint_trace_id,
)
from ratelimiter_tpu.observability.usage import FIELDS, UsageRing

T0 = 1_700_000_000_000


class FakeClock:
    def __init__(self, t=T0):
        self.t = t

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# Usage ring
# ---------------------------------------------------------------------------

def test_usage_ring_window_rotation_exact_vs_brute_force():
    """Window sums must equal a brute-force recount of the raw event
    log across bucket rotations, ring wrap-arounds, and a clock jump
    far past the ring span."""
    import random

    rnd = random.Random(1234)
    clock = FakeClock()
    ring = UsageRing(clock_ms=clock, max_tenants=8,
                     resolutions=((100, 8), (1000, 8)))
    events = []  # (t_ms, tenant, field, n)
    for step in range(3000):
        # Mixed cadence: mostly small steps, occasional jumps including
        # one far past the whole ring span.
        clock.t += rnd.choice([0, 1, 7, 40, 140, 900, 5000]
                              if step != 1500 else [50_000])
        tenant = rnd.randrange(3)
        field = rnd.choice(FIELDS)
        n = rnd.randrange(1, 5)
        ring.record(tenant, **{field: n})
        events.append((clock.t, tenant, field, n))

        if step % 157 == 0:
            for window_ms in (100, 250, 800, 3000, 8000):
                got, covered = ring.window_counts(tenant, window_ms)
                # Brute force with the SAME bucket-epoch definition:
                # pick the resolution the ring picks, count events whose
                # epoch is within the last k epochs incl. current.
                r = ring._pick_res(window_ms)
                bucket_ms, slots = ring._res[r]
                k = min(max(-(-window_ms // bucket_ms), 1), slots)
                e_now = clock.t // bucket_ms
                expect = dict.fromkeys(FIELDS, 0)
                for t_ms, ten, f, m in events:
                    if ten != tenant:
                        continue
                    e = t_ms // bucket_ms
                    # Events older than the ring span were overwritten —
                    # only epochs inside the last `slots` epochs can
                    # still be represented, and the window keeps k.
                    if e_now - k < e <= e_now:
                        expect[f] += m
                assert got == expect, (step, window_ms, got, expect)
                assert covered == k * bucket_ms


def test_usage_ring_tenant_cap_counts_drops():
    ring = UsageRing(clock_ms=FakeClock(), max_tenants=2)
    assert ring.record(1, admitted=1)
    assert ring.record(2, admitted=1)
    assert not ring.record(3, admitted=1)   # over the cap: refused
    assert ring.dropped_tenants == 1
    assert ring.tenants() == [1, 2]


def test_usage_signals_contract():
    clock = FakeClock()
    ring = UsageRing(clock_ms=clock, resolutions=((1000, 64),))
    ring.record(7, admitted=30, denied=10)
    ring.record(7, shed=5, lease_local=20)
    sig = ring.signals(7, window_ms=10_000)
    assert sig.tenant == 7 and sig.window_s == 10.0
    assert (sig.admitted, sig.denied, sig.shed) == (30, 10, 5)
    assert sig.lease_local == 20
    assert sig.goodput == pytest.approx(3.0)
    assert sig.observed_load == pytest.approx(4.5)
    assert sig.lease_local_rate == pytest.approx(2.0)


# ---------------------------------------------------------------------------
# Client telemetry codec + plane folding
# ---------------------------------------------------------------------------

def test_client_telemetry_roundtrip_and_classes():
    telem = ClientTelemetry(client_id=42, max_classes=2)
    telem.record_burn(1, "acme:u1", 2, 3.0)
    telem.record_burn(1, "acme:u2", 1, 900.0)
    telem.record_deny(1, "globex:u9", 10.0)
    telem.record_burn(1, 'evil"class\n:x', 1, 1.0)   # 3rd class: overflow
    blob = telem.encode_and_reset()
    assert not telem.pending()

    report = decode_report(blob)
    assert report.client_id == 42
    assert (report.allowed, report.denied) == (3, 1)
    recs = {cls: (a, d, p) for _lid, cls, a, d, p in report.records}
    assert recs["acme"] == (2, 0, 3)
    assert recs["globex"] == (0, 1, 0)
    assert recs["~other"] == (1, 0, 1)
    assert sum(c for _i, c in report.hist) == 4


def test_client_telemetry_sampled_latency_stamping():
    """stamp_pending: the caller pays the perf_counter pair only for
    the FIRST record of each flush interval — counts always land,
    the histogram gets one sample per interval, and encode_and_reset
    re-arms the stamp."""
    telem = ClientTelemetry(client_id=7)
    assert telem.stamp_pending
    telem.record_burn(1, "t:a", 1, 4.0)
    assert not telem.stamp_pending          # first sample taken
    telem.record_burn(1, "t:a", 1)          # latency-free fast path
    telem.record_deny(1, "t:b")
    report = decode_report(telem.encode_and_reset())
    assert (report.allowed, report.denied) == (2, 1)  # counts complete
    assert sum(c for _i, c in report.hist) == 1       # one sample
    assert telem.stamp_pending               # re-armed by the flush
    # A latency passed while unarmed still lands (the caller decides).
    telem.record_deny(1, "t:b", 9.0)
    assert not telem.stamp_pending
    report = decode_report(telem.encode_and_reset())
    assert sum(c for _i, c in report.hist) == 1


def test_default_key_class_bounds_cardinality():
    assert default_key_class("tenant:user123") == "tenant"
    assert default_key_class("plainkey") == "*"
    assert default_key_class(":leading") == "*"


def test_plane_fold_counters_staleness_and_rejects():
    clock = FakeClock()
    reg = MeterRegistry()
    plane = TelemetryPlane(reg, clock_ms=clock)
    telem = ClientTelemetry(client_id=9)
    telem.record_burn(3, "t:one", 1, 5.0)
    telem.record_burn(3, "t:one", 1, 5.0)
    telem.record_deny(3, "u:two", 5.0)
    assert plane.fold(telem.encode_and_reset()) == 2  # classes t and u
    scrape = reg.scrape()
    assert scrape["ratelimiter.decisions.allowed"] == 2
    assert scrape["ratelimiter.decisions.denied"] == 1
    assert scrape["ratelimiter.decisions.lease_local"] == 3
    assert scrape["ratelimiter.telemetry.reports"] == 1
    assert scrape["ratelimiter.telemetry.local_latency"]["count"] == 3
    counts, _ = plane.usage.window_counts(3, 10_000)
    assert counts["admitted"] == 2 and counts["lease_local"] == 2

    clock.t += 750
    assert plane.staleness_ms() == 750.0
    # Malformed input is counted, never raised.
    assert plane.fold(b"\x01garbage") == -1
    assert plane.reports_rejected == 1
    assert scrape is not None

    # note_server + shed + degraded feed the same fleet counters.
    plane.note_server(3, 10, 7)
    plane.note_shed(3, 2)
    plane.note_degraded(3, True)
    assert plane.allowed_total == 2 + 7 + 1
    assert plane.shed_total == 2
    counts, _ = plane.usage.window_counts(3, 10_000)
    assert counts["shed"] == 2


def test_plane_prometheus_labeled_series_escaped():
    from ratelimiter_tpu.observability import prometheus

    reg = MeterRegistry()
    plane = TelemetryPlane(reg, clock_ms=FakeClock())
    telem = ClientTelemetry(client_id=1,
                            key_class=lambda k: k.split("|")[0])
    telem.record_burn(5, 'bad\\cls"x\n|y', 1, 2.0)
    plane.fold(telem.encode_and_reset())
    text = prometheus.render(reg, collectors=(plane,))
    # Tenant series present...
    assert 'ratelimiter_tenant_admitted_total{tenant="5"} 1' in text
    # ...and the hostile key-class label is escaped per the exposition
    # format (backslash, quote, newline).
    assert ('key_class="bad\\\\cls\\"x\\n"' in text), text
    # Exposition stays line-parseable: no raw newline inside a sample.
    for line in text.splitlines():
        assert line.startswith("#") or " " in line


# ---------------------------------------------------------------------------
# Trace lineage
# ---------------------------------------------------------------------------

def test_lineage_sampling_forced_and_bounds():
    lin = TraceLineage(capacity=4, sample_n=0, max_hops=3)
    tid = mint_trace_id()
    assert not lin.sampled(tid)          # sample_n=0: only forced ids
    assert not lin.record(tid, "sidecar")
    lin.force(tid)
    assert lin.sampled(tid)
    assert lin.record(tid, "sidecar")
    assert lin.record(tid, "batcher")
    assert lin.record(tid, "resolve")
    assert not lin.record(tid, "overflow")   # max_hops bound
    assert lin.hops(tid) == ["sidecar", "batcher", "resolve"]
    assert lin.dropped_hops == 1

    # Capacity LRU: old traces fall off.
    tids = []
    for _ in range(6):
        t = mint_trace_id()
        lin.force(t)
        lin.record(t, "hop")
        tids.append(t)
    assert lin.lineage(tids[-1])
    assert not lin.lineage(tid)


# ---------------------------------------------------------------------------
# End-to-end: leases + telemetry + lineage through sidecar v4
# ---------------------------------------------------------------------------

@pytest.fixture
def lease_stack():
    from ratelimiter_tpu.leases import LeaseManager
    from ratelimiter_tpu.service.sidecar import SidecarServer
    from ratelimiter_tpu.storage import TpuBatchedStorage

    storage = TpuBatchedStorage(num_slots=1 << 10, max_delay_ms=0.2)
    server = SidecarServer(storage, host="127.0.0.1").start()
    lid = server.register("tb", RateLimitConfig(
        max_permits=1 << 18, window_ms=60_000, refill_rate=1e6))
    manager = LeaseManager(storage, default_budget=8, max_budget=8,
                           ttl_ms=60_000.0)
    server.attach_leases(manager)
    yield storage, server, manager, lid
    server.stop()
    storage.close()


def test_trace_propagation_sidecar_with_lease_ops_interleaved(lease_stack):
    """grant -> local burns -> renew must read back under ONE trace
    lineage, and a plain traced TRY_ACQUIRE shows its own
    sidecar -> batcher -> shard -> resolve path."""
    from ratelimiter_tpu.leases import LeaseClient
    from ratelimiter_tpu.service.sidecar import SidecarClient

    storage, server, manager, lid = lease_stack
    wire = SidecarClient("127.0.0.1", server.port)
    assert wire.server_version >= 4
    cli = LeaseClient(wire, lid, budget=8, trace_lineage=True,
                      telemetry_flush_ms=0.0)
    try:
        # Burn through one budget so a renew happens, with ordinary
        # traced decisions interleaved between the lease ops.
        for i in range(12):
            assert cli.try_acquire("trace:leased")
            if i == 5:
                assert wire.try_acquire(lid, f"plain{i}",
                                        trace_id=mint_trace_id())
        tid = cli.trace_of("trace:leased")
        assert tid
        hops = storage.lineage.hops(tid)
        # One lineage spans the lease lifecycle: the grant, then the
        # renew carrying the locally-burned decisions.
        gi = hops.index("lease.grant")
        ci = hops.index("client")
        ri = hops.index("lease.renew")
        assert gi < ci < ri
        assert {"sidecar", "batcher", "shard", "resolve"} <= set(hops)
        burns = [h for h in storage.lineage.lineage(tid)
                 if h["hop"] == "client"]
        assert burns[0]["local_burns"] == 8   # the exhausted budget

        # And the explicitly-traced plain decision got its own path.
        plain_tid = None
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and plain_tid is None:
            snap = storage.lineage.snapshot(last=64)["traces"]
            for th, hop_list in snap.items():
                names = [h["hop"] for h in hop_list]
                if names[:1] == ["sidecar"] and "lease.grant" not in names \
                        and "batcher" in names:
                    plain_tid = th
                    assert {"shard", "resolve"} <= set(names)
            time.sleep(0.01)
        assert plain_tid is not None, "traced TRY_ACQUIRE left no lineage"
    finally:
        cli.release_all()
        wire.close()


def test_fleet_counters_reconcile_over_wire(lease_stack):
    """After release_all's final flush, ratelimiter.decisions.* equals
    the client's ground-truth decision count exactly."""
    from ratelimiter_tpu.leases import LeaseClient
    from ratelimiter_tpu.service.sidecar import SidecarClient

    storage, server, manager, lid = lease_stack
    plane = storage.telemetry
    base = plane.allowed_total + plane.denied_total
    wire = SidecarClient("127.0.0.1", server.port)
    cli = LeaseClient(wire, lid, budget=8)
    try:
        n = 50
        for i in range(n):
            assert cli.try_acquire(f"acct:k{i % 3}")
        cli.release_all()
        # The release frames (request/response) serialize BEHIND the
        # final telemetry frame, so the fold has landed by now.
        assert plane.allowed_total + plane.denied_total - base == n
        assert plane.lease_local_total >= cli.local_decisions
        assert server.telemetry_frames_total > 0
        assert plane.reports_total > 0
    finally:
        wire.close()


def test_v3_client_sees_no_telemetry_and_old_framing(lease_stack):
    """A v3-pinned client is served byte-identically to a v3 server:
    TELEMETRY answers BAD_FRAME/unknown-op, lease ops still work."""
    from ratelimiter_tpu.service import sidecar as sc
    from ratelimiter_tpu.service.sidecar import SidecarClient

    storage, server, manager, lid = lease_stack
    client = SidecarClient("127.0.0.1", server.port, protocol=3)
    assert client.server_version == 3
    assert not client.telemetry_supported()
    assert client.telemetry_report(b"anything") is False
    g = client.lease_grant(lid, "v3:key", 8)
    assert g is not None and g.granted == 8
    client.lease_release(lid, "v3:key", 0)
    # Hand-built TELEMETRY frame on the v3 connection: unknown op.
    client._send(client._frame(sc.OP_TELEMETRY, 0, 0, "x"))
    status, _, errno = client._read_raw()
    assert (status, errno) == (sc.ST_BAD_FRAME, sc.ERR_UNKNOWN_OP)
    assert client.try_acquire(lid, "v3-still-works") is True
    client.close()


def test_telemetry_drop_dont_block_under_partition(lease_stack):
    """FaultInjectingProxy.partition(): reports are lost in flight but
    local lease decisions keep answering at memory speed — the decision
    path is never pinned behind a telemetry send; a fully-dead socket
    then exercises the dropped-flush counter + the telemetry-down
    latch (one bounded failure, never retried inline)."""
    from ratelimiter_tpu.leases import LeaseClient, LeaseManager
    from ratelimiter_tpu.service.sidecar import SidecarClient
    from ratelimiter_tpu.storage.chaos import FaultInjectingProxy

    storage, server, manager, lid = lease_stack
    # A budget big enough that NO renew happens during the partition —
    # the only wire traffic after the grant is telemetry flushes.
    server.attach_leases(LeaseManager(storage, default_budget=1 << 15,
                                      max_budget=1 << 15,
                                      ttl_ms=600_000.0))
    plane = storage.telemetry
    proxy = FaultInjectingProxy(server.port).start()
    try:
        wire = SidecarClient("127.0.0.1", proxy.port, timeout=5.0,
                             telemetry_send_timeout=0.2)
        cli = LeaseClient(wire, lid, budget=1 << 15,
                          telemetry_flush_ms=0.0)
        # Grant once while the link is healthy; the huge budget means
        # no renew (no wire op on the decision path) afterwards.
        assert cli.try_acquire("part:key")
        time.sleep(0.05)
        reports_before = plane.reports_total
        proxy.partition()
        t0 = time.perf_counter()
        for _ in range(4000):
            assert cli.try_acquire("part:key")
        wall = time.perf_counter() - t0
        assert cli.local_decisions >= 4000
        # Drop-don't-block: the partitioned link never stalls the
        # decision path (response-less frames, bounded send timeout).
        assert wall < 3.0, f"decision path stalled {wall:.1f}s"
        # The partitioned proxy swallowed every in-flight report: the
        # server folded nothing new (the staleness gauge is what makes
        # this visible operationally).
        time.sleep(0.05)
        assert plane.reports_total == reports_before

        # Link fully dead: the flush attempt FAILS (not just vanishes),
        # is counted as dropped, latches telemetry down — and the local
        # decision still answers.
        wire._sock.close()
        dropped_before = cli.telemetry_dropped
        assert cli.try_acquire("part:key")
        assert cli.telemetry_dropped == dropped_before + 1
        assert wire._telemetry_down
        # Latched: later flushes fail fast without touching the socket.
        assert cli.try_acquire("part:key")
        assert cli.telemetry_dropped == dropped_before + 2
    finally:
        proxy.stop()


# ---------------------------------------------------------------------------
# Flight recorder: lease lifecycle events + filters
# ---------------------------------------------------------------------------

def test_lease_lifecycle_flight_events_and_revocation_storm():
    from ratelimiter_tpu.leases import LeaseManager
    from ratelimiter_tpu.observability import FlightRecorder
    from ratelimiter_tpu.storage import TpuBatchedStorage

    clock = FakeClock()
    rec = FlightRecorder(capacity=256)
    storage = TpuBatchedStorage(num_slots=1 << 10, clock_ms=clock)
    try:
        lid = storage.register_limiter("tb", RateLimitConfig(
            max_permits=1 << 16, window_ms=60_000, refill_rate=1e6))
        mgr = LeaseManager(storage, default_budget=4, max_budget=4,
                           ttl_ms=1000.0, clock_ms=clock, recorder=rec,
                           storm_threshold=3, storm_window_ms=5000.0)
        keys = [f"storm:k{i}" for i in range(5)]
        for k in keys:
            assert mgr.grant(lid, k, 4).granted == 4
        assert rec.events(kind="lease.granted")

        # Release one (event), expire one (TTL), then bump the fence
        # epoch and renew the rest: a coalesced revocation storm.
        mgr.release(lid, keys[0], 1)
        assert rec.events(kind="lease.released")
        clock.t += 2000   # TTL passed for everyone still outstanding
        assert mgr.renew(lid, keys[1], 1) is None   # expired
        assert rec.events(kind="lease.expired")
        # Re-grant three, then fence: their renewals revoke.
        for k in keys[2:]:
            assert mgr.grant(lid, k, 4).granted == 4
        storage.fence(1)
        storage.lift_fence(1)   # lift so only the epoch delta remains
        for k in keys[2:]:
            assert mgr.renew(lid, k, 2) is None
        assert rec.events(kind="lease.revoked")
        storms = rec.events(kind="lease.revocation_storm")
        assert storms and storms[0]["n_revocations"] >= 3
        assert mgr.revocation_storms >= 1
    finally:
        storage.close()


def test_flightrecorder_kind_and_since_ms_filters():
    from ratelimiter_tpu.observability import FlightRecorder

    rec = FlightRecorder(capacity=64)
    rec.record("lease.granted", key="a")
    rec.record("overload.shed", reason="x")
    cut_ms = time.time_ns() // 1_000_000
    time.sleep(0.002)
    rec.record("lease.revoked", key="b")
    rec.record("lease.granted", key="c")

    snap = rec.snapshot(kind="lease")
    kinds = [e["kind"] for e in snap["events"]]
    assert kinds == ["lease.granted", "lease.revoked", "lease.granted"]
    assert snap["filtered"]["matched"] == 3

    snap = rec.snapshot(since_ms=cut_ms + 1)
    assert [e["kind"] for e in snap["events"]] == [
        "lease.revoked", "lease.granted"]

    snap = rec.snapshot(kind="lease.granted", since_ms=cut_ms + 1)
    assert [e["key"] for e in snap["events"]] == ["c"]
    # Unfiltered snapshots keep their original shape (no filter block).
    assert "filtered" not in rec.snapshot()


def test_flightrecorder_http_filters_and_tenants_endpoint():
    """?kind=/&since_ms= on /actuator/flightrecorder + the new
    /actuator/tenants payload through the full wiring."""
    import http.client
    import json

    from ratelimiter_tpu.service.app import make_server
    from ratelimiter_tpu.service.props import AppProperties
    from ratelimiter_tpu.service.wiring import build_app

    props = AppProperties({
        "storage.backend": "tpu",
        "storage.num_slots": "4096",
        "batcher.max_delay_ms": "0.2",
        "parallel.shard": "off",
        "warmup.enabled": "false",
        "link.probe.enabled": "false",
        "ratelimiter.lease.enabled": "true",
    })
    ctx = build_app(props)
    srv = make_server(ctx, port=0)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    try:
        conn = http.client.HTTPConnection(
            "127.0.0.1", srv.server_address[1], timeout=10)
        conn.request("GET", "/api/data", headers={"X-User-ID": "ten1"})
        conn.getresponse().read()
        conn.request("GET", "/actuator/health")
        conn.getresponse().read()

        conn.request("GET", "/actuator/flightrecorder?kind=health")
        fr = json.loads(conn.getresponse().read())
        assert fr["events"] and all(
            e["kind"] == "health" for e in fr["events"])
        conn.request("GET",
                     "/actuator/flightrecorder?kind=health&since_ms="
                     f"{time.time_ns() // 1_000_000 + 60_000}")
        fr = json.loads(conn.getresponse().read())
        assert fr["events"] == []
        conn.request("GET", "/actuator/flightrecorder?since_ms=oops")
        assert conn.getresponse().status == 400

        conn.request("GET", "/actuator/tenants")
        resp = conn.getresponse()
        assert resp.status == 200
        tenants = json.loads(resp.read())
        assert tenants["enabled"]
        assert tenants["tenants"], "no tenant usage recorded"
        assert "telemetry" in tenants
        assert "leases" in tenants
        some = next(iter(tenants["tenants"].values()))
        assert some["totals"]["admitted"] >= 1
        conn.close()
    finally:
        srv.shutdown()
        ctx.close()
