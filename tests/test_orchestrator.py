"""Self-healing failover orchestrator (PR 9).

Layers under test, bottom-up:

- the fencing epoch on TpuBatchedStorage: monotonic install, typed
  FencedError on every decision surface, shard-scoped fences that let
  survivor traffic through, lift_fence restoration;
- the orchestrator state machine driven tick-by-tick on a simulated
  clock: SUSPECT needs consecutive failures, a heal inside the
  hysteresis window is a counted false alarm (flap damping), promotion
  falls back to a spare standby, exhausted candidates fail the shard
  closed;
- the full drills: orchestrated_failover_drill (kill one shard of N
  mid-Zipf-stream, ZERO manual actuator calls, oracle-bit-identical,
  re-seeded back to N+1) and orchestrator_flap_drill (transient fault
  never promotes; fenced zombie dispatch refused);
- wiring: ratelimiter.orchestrator.* props build the in-process N+1
  topology, /actuator/orchestrator and the health payload expose it.
"""

import numpy as np
import pytest

from ratelimiter_tpu import RateLimitConfig
from ratelimiter_tpu.engine.state import LimiterTable
from ratelimiter_tpu.metrics import MeterRegistry
from ratelimiter_tpu.parallel import ShardedDeviceEngine, make_mesh
from ratelimiter_tpu.replication import (
    FailoverOrchestrator,
    OrchestratorConfig,
    ShardFailoverRouter,
    ShardStandbySet,
    ShardedReplicationLog,
    ShardedReplicator,
)
from ratelimiter_tpu.storage import TpuBatchedStorage
from ratelimiter_tpu.storage.errors import FencedError

T0 = 1_753_000_000_000


# ---------------------------------------------------------------------------
# Fencing epoch (storage layer)
# ---------------------------------------------------------------------------

def test_fence_is_monotonic_and_refuses_all_surfaces():
    clock = {"t": T0}
    storage = TpuBatchedStorage(num_slots=128, clock_ms=lambda: clock["t"])
    lid = storage.register_limiter("tb", RateLimitConfig(
        max_permits=10, window_ms=1000, refill_rate=5.0))
    storage.acquire("tb", lid, "a", 1)
    storage.fence(3)
    for call in (
        lambda: storage.acquire("tb", lid, "a", 1),
        lambda: storage.acquire_many("tb", [lid], ["a"], [1]),
        lambda: storage.acquire_many_ids("tb", lid, np.array([1]),
                                         np.array([1])),
        lambda: storage.acquire_stream_ids("tb", lid, np.array([1])),
        lambda: storage.acquire_stream_strs("tb", lid, ["a"]),
    ):
        with pytest.raises(FencedError):
            call()
    assert storage.fence_rejected == 5
    assert storage.fence_info()["epoch"] == 3
    # Monotonic: a stale orchestrator replaying an old epoch is refused.
    with pytest.raises(ValueError, match="monotonic"):
        storage.fence(3)
    with pytest.raises(ValueError, match="monotonic"):
        storage.fence(2)
    # A stale lift is refused too; a current one restores service.
    with pytest.raises(ValueError, match="behind"):
        storage.lift_fence(2)
    storage.lift_fence(3)
    out = storage.acquire_many("tb", [lid], ["a"], [1])
    assert len(out["allowed"]) == 1
    storage.close()


def test_shard_scoped_fence_lets_survivors_through():
    from ratelimiter_tpu.parallel.sharded import shard_of_int_keys

    n_sh = 4
    engine = ShardedDeviceEngine(
        slots_per_shard=128, table=LimiterTable(),
        mesh=make_mesh(n_devices=n_sh))
    clock = {"t": T0}
    storage = TpuBatchedStorage(engine=engine, clock_ms=lambda: clock["t"])
    lid = storage.register_limiter("tb", RateLimitConfig(
        max_permits=10, window_ms=1000, refill_rate=5.0))
    keys = np.arange(64, dtype=np.int64)
    shard = shard_of_int_keys(keys, n_sh)
    victim = int(np.bincount(shard, minlength=n_sh).argmax())
    victim_keys = keys[shard == victim]
    other_keys = keys[shard != victim]
    storage.fence(1, shards=(victim,))
    with pytest.raises(FencedError):
        storage.acquire_stream_ids("tb", lid, victim_keys)
    with pytest.raises(FencedError):
        storage.acquire_many_ids("tb", lid, victim_keys[:2],
                                 np.array([1, 1]))
    # Survivor-only dispatches pass the fence.
    got = storage.acquire_stream_ids("tb", lid, other_keys)
    assert len(got) == len(other_keys)
    # A MIXED dispatch touching the fenced shard is refused whole.
    with pytest.raises(FencedError):
        storage.acquire_stream_ids("tb", lid, keys)
    storage.lift_fence(1, shards=(victim,))
    got = storage.acquire_stream_ids("tb", lid, victim_keys)
    assert len(got) == len(victim_keys)
    storage.close()


# ---------------------------------------------------------------------------
# State machine (tick-driven, simulated clock)
# ---------------------------------------------------------------------------

def make_topology(n_shards=2, slots_per_shard=128, probe=None, spares=None,
                  registry=None, reseed=True, **cfg_kw):
    clock = {"t": T0}
    engine = ShardedDeviceEngine(
        slots_per_shard=slots_per_shard, table=LimiterTable(),
        mesh=make_mesh(n_devices=n_shards))
    primary = TpuBatchedStorage(engine=engine, clock_ms=lambda: clock["t"])
    router = ShardFailoverRouter(primary)

    def factory():
        return TpuBatchedStorage(num_slots=slots_per_shard,
                                 clock_ms=lambda: clock["t"])

    mesh_set = ShardStandbySet(n_shards, factory, registry=registry)
    repl = ShardedReplicator(ShardedReplicationLog(primary),
                             mesh_set.in_process_sinks())
    sim = {"s": 0.0}
    cfg = OrchestratorConfig(probe_interval_ms=50.0, suspect_threshold=2,
                             hysteresis_ms=150.0, promote_backoff_ms=1.0,
                             reseed=reseed, **cfg_kw)
    orch = FailoverOrchestrator(
        router, mesh_set, repl, standby_factory=factory, config=cfg,
        probe=probe, spares=spares, registry=registry,
        clock=lambda: sim["s"], sleep=lambda s: None)

    def tick(n=1):
        for _ in range(n):
            sim["s"] += cfg.probe_interval_ms / 1000.0
            orch.tick()

    return clock, primary, router, mesh_set, repl, orch, tick


def test_transient_fault_is_flap_damped():
    """Fail for exactly the suspect threshold, heal inside the
    hysteresis window: one false alarm, no fence, no promotion."""
    bad = {"on": False}
    clock, primary, router, mesh_set, repl, orch, tick = make_topology(
        probe=lambda q: not (bad["on"] and q == 0))
    try:
        tick(3)
        assert orch.status()["shards"][0]["state"] == "MONITORING"
        bad["on"] = True
        tick(2)  # consecutive threshold reached
        assert orch.status()["shards"][0]["state"] == "SUSPECT"
        bad["on"] = False
        tick()
        st = orch.status()
        assert st["shards"][0]["state"] == "MONITORING"
        assert st["false_alarms"] == 1
        assert st["promotions"] == 0
        assert orch.fence_epoch == 0
        assert primary.fence_info()["epoch"] == 0
    finally:
        orch.close()
        router.close()
        mesh_set.close()


def test_single_blip_never_reaches_suspect():
    """One failed probe (below the consecutive threshold) is absorbed in
    MONITORING — not even a SUSPECT transition, no false alarm."""
    bad = {"on": False}
    clock, primary, router, mesh_set, repl, orch, tick = make_topology(
        probe=lambda q: not (bad["on"] and q == 0))
    try:
        bad["on"] = True
        tick()          # one failure: threshold is 2
        bad["on"] = False
        tick(3)
        st = orch.status()
        assert st["shards"][0]["state"] == "MONITORING"
        assert st["false_alarms"] == 0
    finally:
        orch.close()
        router.close()
        mesh_set.close()


def test_promotion_falls_back_to_spare_standby():
    """The primary standby's promote fails (stale stream) — the spare
    candidate wins instead of the shard failing closed."""
    from ratelimiter_tpu.replication import InProcessSink, StandbyReceiver
    from ratelimiter_tpu.replication.log import ReplicationLog

    bad = {"on": False}
    registry = MeterRegistry()
    clock, primary, router, mesh_set, repl, orch, tick = make_topology(
        probe=lambda q: not (bad["on"] and q == victim
                             and orch.promotions == 0),
        registry=registry)
    lid = primary.register_limiter("tb", RateLimitConfig(
        max_permits=10, window_ms=1000, refill_rate=5.0))
    try:
        from ratelimiter_tpu.parallel.sharded import shard_of_int_keys

        keys = np.arange(32, dtype=np.int64)
        shard = shard_of_int_keys(keys, 2)
        victim = int(np.bincount(shard, minlength=2).argmax())
        clock["t"] += 5
        primary.acquire_stream_ids("tb", lid, keys)
        repl.ship_now()
        # A consistent SPARE standby fed by its own full stream.
        spare_storage = TpuBatchedStorage(num_slots=128,
                                          clock_ms=lambda: clock["t"])
        spare_rx = StandbyReceiver(spare_storage)
        # The spare receives the victim shard's stream (an ordinary flat
        # stream) via a second sink teed for this test.
        frames = repl.log.cut_shard(victim)
        from ratelimiter_tpu.replication.wire import encode_frame

        for f in frames:
            spare_rx.apply_bytes(encode_frame(f))
        if not spare_rx.consistent:
            repl.log.request_full(victim)
            for f in repl.log.cut_shard(victim):
                spare_rx.apply_bytes(encode_frame(f))
        assert spare_rx.consistent
        orch._spares = {victim: [spare_rx]}
        # Poison the primary standby: mark its stream inconsistent so
        # standby_ok refuses it (stale replica must not be promoted).
        mesh_set.receivers[victim].consistent = False
        bad["on"] = True
        tick(8)
        st = orch.status()["shards"][victim]
        assert st["state"] in ("RESTORED", "MONITORING"), st
        assert router.shard_health()[victim] == "promoted"
        assert router.replacements[victim] is spare_storage
        assert orch.promotions == 1
        spare_storage.flush()
    finally:
        orch.close()
        router.close()
        mesh_set.close()


def test_exhausted_candidates_fail_the_shard_closed():
    bad = {"on": False}
    registry = MeterRegistry()
    clock, primary, router, mesh_set, repl, orch, tick = make_topology(
        probe=lambda q: not (bad["on"] and q == 0), registry=registry)
    try:
        # No traffic ever replicated: the standby is unbootstrapped, so
        # standby_ok refuses it and there are no spares.
        bad["on"] = True
        tick(12)
        st = orch.status()
        assert st["shards"][0]["state"] == "FAILED"
        assert st["promotions"] == 0
        assert router.shard_health()[0] == "failed"
        # Fail-closed: the router denies the dead shard's keys.
        assert registry.scrape()[
            "ratelimiter.orchestrator.state"] == 5.0
        # The terminal state sticks (no auto-unfence flapping).
        tick(3)
        assert orch.status()["shards"][0]["state"] == "FAILED"
    finally:
        orch.close()
        router.close()
        mesh_set.close()


def test_unfence_recovers_a_terminal_failed_shard():
    """Operator exit from terminal FAILED: fence lifted, router repaired
    back to the primary, fresh standby re-seeded — shard serves again."""
    bad = {"on": False}
    registry = MeterRegistry()
    clock, primary, router, mesh_set, repl, orch, tick = make_topology(
        probe=lambda q: not (bad["on"] and q == 0), registry=registry)
    lid = primary.register_limiter("tb", RateLimitConfig(
        max_permits=10, window_ms=1000, refill_rate=5.0))
    try:
        bad["on"] = True
        tick(12)
        assert orch.status()["shards"][0]["state"] == "FAILED"
        assert primary.fence_info()["shards"] == [0]
        # unfence is the FAILED-only exit: live shards are refused.
        with pytest.raises(ValueError, match="not FAILED"):
            orch.unfence(1)
        bad["on"] = False  # the operator repaired/verified the shard
        out = orch.unfence(0)
        assert out["state"] == "MONITORING"
        assert orch.status()["shards"][0]["state"] == "MONITORING"
        assert primary.fence_info()["shards"] == []
        assert router.shard_health()[0] == "active"
        # Shard-0 keys serve through the router again (fence lifted,
        # routing back on the primary).
        clock["t"] += 5
        got = router.acquire_stream_ids(
            "tb", lid, np.arange(64, dtype=np.int64))
        assert len(got) == 64
        # Standby coverage resumed: the replaced standby re-baselines
        # from a FULL frame on the next cut.
        repl.ship_now()
        assert mesh_set.receivers[0].consistent
        assert not mesh_set.receivers[0].promoted
        tick(3)
        assert orch.status()["shards"][0]["state"] == "MONITORING"
    finally:
        orch.close()
        router.close()
        mesh_set.close()


def test_unfence_actuator_endpoint():
    """POST /actuator/orchestrator/unfence: plumbing + typed refusals
    (the full unfence path is covered by the direct test above)."""
    import http.client
    import json
    import threading

    from ratelimiter_tpu.service.app import make_server
    from ratelimiter_tpu.service.props import AppProperties
    from ratelimiter_tpu.service.wiring import build_app

    props = AppProperties({
        "storage.backend": "tpu",
        "storage.num_slots": "4096",
        "parallel.shard": "auto",
        "warmup.enabled": "false",
        "link.probe.enabled": "false",
        "ratelimiter.orchestrator.enabled": "true",
        "ratelimiter.orchestrator.probe_interval_ms": "60000",
        "replication.interval_ms": "60000",
    })
    ctx = build_app(props)
    if ctx.orchestrator is None:
        ctx.close()
        pytest.skip("container exposes a single device; no shards")
    srv = make_server(ctx, port=0)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    try:
        conn = http.client.HTTPConnection(
            "127.0.0.1", srv.server_address[1], timeout=10)

        def post(body):
            conn.request("POST", "/actuator/orchestrator/unfence",
                         body=json.dumps(body),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            return resp.status, json.loads(resp.read() or b"{}")

        status, payload = post({})
        assert status == 400 and "shard" in payload["error"]
        status, payload = post({"shard": 0})  # MONITORING, not FAILED
        assert status == 409 and "not FAILED" in payload["error"]
        conn.close()
    finally:
        srv.shutdown()
        ctx.close()


def test_router_shard_status_reports_time_in_state():
    clock, primary, router, mesh_set, repl, orch, tick = make_topology()
    try:
        st = router.shard_status()
        assert st[0]["state"] == "active"
        assert st[0]["in_state_ms"] >= 0
        router.fail_shard(1)
        st = router.shard_status()
        assert st[1]["state"] == "failed"
        assert st[1]["since_ms"] >= T0 // 2  # a real wall timestamp
        import time as time_mod

        time_mod.sleep(0.02)
        assert router.shard_status()[1]["in_state_ms"] >= 15
    finally:
        orch.close()
        router.close()
        mesh_set.close()


# ---------------------------------------------------------------------------
# The drills (fast variants; verify.sh runs these)
# ---------------------------------------------------------------------------

def test_orchestrated_failover_drill_fast():
    from ratelimiter_tpu.storage.chaos import orchestrated_failover_drill

    registry = MeterRegistry()
    report = orchestrated_failover_drill(
        n_shards=4, slots_per_shard=256, n_keys=64, waves=2,
        stream_n=512, batch=16, registry=registry)
    assert report["mismatches"] == 0
    assert report["decisions"] > 1000
    assert report["promotions"] == 1
    assert report["reseeds"] == 1           # back to N+1
    assert report["false_alarms"] == 0
    assert report["fence_rejected"] >= 1    # the zombie was refused
    assert report["cycles"][0]["detection_ms"] <= 450.0
    meters = registry.scrape()
    assert meters["ratelimiter.orchestrator.promotions"] == 1.0
    assert meters["ratelimiter.orchestrator.false_alarms"] == 0.0
    assert meters["ratelimiter.orchestrator.state"] == 0.0  # settled
    assert meters["ratelimiter.replication.failovers"] == 1.0


def test_orchestrator_flap_drill_fast():
    from ratelimiter_tpu.storage.chaos import orchestrator_flap_drill

    registry = MeterRegistry()
    report = orchestrator_flap_drill(registry=registry)
    assert report["mismatches"] == 0
    assert report["false_alarms"] == 3
    assert report["fence_rejected"] >= 1
    meters = registry.scrape()
    assert meters["ratelimiter.orchestrator.promotions"] == 0.0
    assert meters["ratelimiter.orchestrator.false_alarms"] == 3.0


@pytest.mark.slow
def test_orchestrator_soak_slow():
    """Multi-cycle kill -> promote -> re-seed -> kill-again: the
    re-seeded standby must carry the SECOND failover."""
    from ratelimiter_tpu.storage.chaos import orchestrated_failover_drill

    registry = MeterRegistry()
    report = orchestrated_failover_drill(
        n_shards=4, slots_per_shard=512, n_keys=96, waves=3,
        stream_n=1536, batch=32, cycles=3, registry=registry)
    assert report["mismatches"] == 0
    assert report["promotions"] == 3
    assert report["reseeds"] == 3
    assert len({c["fence_epoch"] for c in report["cycles"]}) == 3


# ---------------------------------------------------------------------------
# Wiring + actuator surface
# ---------------------------------------------------------------------------

def test_wiring_orchestrator_disabled_by_default():
    from ratelimiter_tpu.service.props import AppProperties
    from ratelimiter_tpu.service.wiring import _maybe_orchestrator

    clock = {"t": T0}
    storage = TpuBatchedStorage(num_slots=256, clock_ms=lambda: clock["t"])
    handle, serving = _maybe_orchestrator(storage, AppProperties({}),
                                          MeterRegistry())
    assert handle is None and serving is storage
    storage.close()


def test_wiring_orchestrator_requires_sharded_engine():
    from ratelimiter_tpu.service.props import AppProperties
    from ratelimiter_tpu.service.wiring import _maybe_orchestrator

    clock = {"t": T0}
    storage = TpuBatchedStorage(num_slots=256, clock_ms=lambda: clock["t"])
    handle, serving = _maybe_orchestrator(
        storage, AppProperties({"ratelimiter.orchestrator.enabled": "true"}),
        MeterRegistry())
    assert handle is None and serving is storage  # warned, disabled
    storage.close()


def test_wiring_orchestrator_builds_n_plus_one_topology():
    from ratelimiter_tpu.service.app import health_payload
    from ratelimiter_tpu.service.props import AppProperties
    from ratelimiter_tpu.service.wiring import AppContext, _maybe_orchestrator

    engine = ShardedDeviceEngine(
        slots_per_shard=128, table=LimiterTable(),
        mesh=make_mesh(n_devices=2))
    clock = {"t": T0}
    storage = TpuBatchedStorage(engine=engine, clock_ms=lambda: clock["t"])
    registry = MeterRegistry()
    props = AppProperties({
        "ratelimiter.orchestrator.enabled": "true",
        "ratelimiter.orchestrator.probe_interval_ms": "60000",
        "replication.interval_ms": "60000",
    })
    handle, serving = _maybe_orchestrator(storage, props, registry)
    assert handle is not None
    try:
        assert serving is handle.router
        assert handle.standby_set.n_shards == 2
        status = handle.status()
        assert status["enabled"] is True
        assert status["shards"][0]["state"] == "MONITORING"
        assert status["config"]["suspect_threshold"] == 3
        # Health payload folds the orchestrator + per-shard detail in.
        ctx = AppContext(props=props, storage=serving, registry=registry,
                         limiters={}, fail_open=True, orchestrator=handle)
        payload = health_payload(ctx)
        assert payload["status"] == "UP"
        assert payload["orchestrator"]["promotions"] == 0
        assert payload["shards_detail"]["0"]["state"] == "active"
        assert "in_state_ms" in payload["shards_detail"]["0"]
    finally:
        handle.close()
        serving.close()


def test_build_app_serves_through_router(monkeypatch):
    """Full wiring with the orchestrator on: the limiter trio serves
    through retry(breaker(router)) and the actuator surface answers."""
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    from ratelimiter_tpu.service.app import health_payload
    from ratelimiter_tpu.service.props import AppProperties
    from ratelimiter_tpu.service.wiring import build_app

    props = AppProperties({
        "storage.backend": "tpu",
        "storage.num_slots": "4096",
        "parallel.shard": "auto",
        "warmup.enabled": "false",
        "link.probe.enabled": "false",
        "ratelimiter.orchestrator.enabled": "true",
        # Park the cadences: this test drives nothing periodic.
        "ratelimiter.orchestrator.probe_interval_ms": "60000",
        "replication.interval_ms": "60000",
    })
    ctx = build_app(props)
    try:
        if ctx.orchestrator is None:
            pytest.skip("container exposes a single device; no shards")
        assert ctx.limiters["api"].try_acquire("user-1") is True
        assert ctx.limiters["burst"].try_acquire("user-1", 2) is True
        payload = health_payload(ctx)
        assert payload["status"] == "UP"
        assert payload["orchestrator"]["promotions"] == 0
        assert all(v == "active" for v in payload["shards"].values())
        status = ctx.orchestrator.status()
        assert status["enabled"] is True
        assert all(s["state"] == "MONITORING"
                   for s in status["shards"].values())
    finally:
        ctx.close()
