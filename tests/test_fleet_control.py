"""Fleet-true control plane (control/fleet.py + replication/control.py,
ARCHITECTURE §15).

- ControllerSeat: the fence-epoch acceptor — higher epoch wins, lower
  is refused in-protocol, a stale-epoch policy write is counted and
  never applied.
- controller_handlers over a real loopback ControlServer: claim /
  set_policy / policy_info / signals, epoch + generation fencing.
- FleetControlPlane: majority election, monotone-generation broadcast,
  anti-entropy convergence, self-demotion (superseded AND own-clock
  lease expiry), NotLeader actuation refusals.
- ControllerElection: leader-death failover on the manager tick,
  note_join anti-entropy, ratelimiter.control.* metrics.
- The partitioned-controller drill (fast shape): two real hostproc
  cells, the leader partitioned mid-storm — zero stale policy writes,
  successor at epoch+1, one generation fleet-wide, goodput holds.
"""

import pytest

from ratelimiter_tpu.control import (
    AdaptivePolicyController,
    ControlConfig,
    ControllerElection,
    FleetControlPlane,
    NotLeader,
)
from ratelimiter_tpu.control.fleet import STALE_UNREACHABLE_MS
from ratelimiter_tpu.core.config import RateLimitConfig
from ratelimiter_tpu.metrics import MeterRegistry
from ratelimiter_tpu.observability.flightrecorder import FlightRecorder
from ratelimiter_tpu.replication.control import (
    ControllerSeat,
    controller_handlers,
)
from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

T0 = 1_700_000_000_000


def make_storage(clock, **kw):
    kw.setdefault("num_slots", 256)
    kw.setdefault("max_delay_ms", 0.2)
    return TpuBatchedStorage(clock_ms=lambda: clock["t"], **kw)


class TableBackend:
    """In-process member: the RemoteBackend duck over a node's
    controller_handlers table — no sockets, injected clocks."""

    def __init__(self, table):
        self.table = table
        self.unreachable = False

    def _call(self, op, **kw):
        if self.unreachable:
            raise OSError("partitioned")
        return self.table[op](**kw)

    def controller_claim(self, node, epoch, ttl_ms=3000.0):
        return self._call("controller_claim", node=node, epoch=epoch,
                          ttl_ms=ttl_ms)

    def set_policy_rows(self, rows, epoch, node=""):
        return self._call("set_policy", rows=rows, epoch=epoch, node=node)

    def policy_info(self):
        return self._call("policy_info")

    def signals(self, window_ms=2000):
        return self._call("signals", window_ms=window_ms)


def make_cell(clock, n=2, limiter=None):
    """n member storages + their handler tables, same registrations."""
    limiter = limiter or RateLimitConfig(max_permits=40, window_ms=1000)
    storages, members = [], {}
    for i in range(n):
        st = make_storage(clock)
        lid = st.register_limiter("sw", limiter)
        assert lid == 1
        storages.append(st)
        members[f"n{i}"] = TableBackend(controller_handlers(st))
    return storages, members, 1, limiter


def make_plane(members, limiter, node="ctrl-a", mono=None, **kw):
    ceilings = {1: ("sw", limiter)}
    if mono is not None:
        kw["clock_ms"] = lambda: mono["t"]
    return FleetControlPlane(node, members, limiters=ceilings, **kw)


# ---------------------------------------------------------------------------
# ControllerSeat: the node-side fence
# ---------------------------------------------------------------------------

def test_seat_higher_epoch_wins_lower_refused():
    clock = {"t": 0.0}
    seat = ControllerSeat(clock=lambda: clock["t"])
    assert seat.claim("a", 1)["granted"]
    # The holder renews at its own epoch (TTL refresh).
    assert seat.claim("a", 1)["granted"]
    # A rival at the SAME epoch is refused: one winner per epoch.
    refused = seat.claim("b", 1)
    assert not refused["granted"] and refused["epoch"] == 1
    # A strictly higher epoch supersedes even an unexpired grant.
    assert seat.claim("b", 2)["granted"]
    out = seat.claim("a", 1)
    assert not out["granted"] and out["epoch"] == 2
    info = seat.info()
    assert info["node"] == "b" and info["epoch"] == 2


def test_seat_stale_epoch_write_counted_never_applied():
    clock = {"t": T0}
    st = make_storage(clock)
    lid = st.register_limiter("sw", RateLimitConfig(max_permits=40,
                                                    window_ms=1000))
    seat = ControllerSeat()
    table = controller_handlers(st, seat)
    assert table["controller_claim"](node="a", epoch=3)["granted"]
    row = {str(lid): {"algo": "sw", "max_permits": 10, "window_ms": 1000,
                      "refill_rate": 0.0, "gen": 1}}
    resp = table["set_policy"](rows=row, epoch=2, node="zombie")
    assert resp == {"applied": False, "stale_epoch": True, "epoch": 3,
                    "generation": 0}
    assert seat.stale_rejected == 1
    assert st.policy_info()["lids"][lid]["max_permits"] == 40
    # The current epoch applies; a duplicate is idempotent; an OLDER
    # generation at a current epoch is refused in-protocol.
    assert table["set_policy"](rows=row, epoch=3)["applied"]
    assert st.policy_info()["lids"][lid]["max_permits"] == 10
    dup = table["set_policy"](rows=row, epoch=3)
    assert dup["applied"] and dup["generation"] == 1
    older = {str(lid): {"algo": "sw", "max_permits": 20,
                        "window_ms": 1000, "refill_rate": 0.0, "gen": 1}}
    resp = table["set_policy"](rows=older, epoch=3)
    assert resp["stale_generation"] and not resp["applied"]
    info = table["policy_info"]()
    assert info["controller"]["node"] == "a"
    assert info["controller"]["epoch"] == 3
    st.close()


def test_seat_expiry_is_reported_not_self_cleared():
    clock = {"t": 0.0}
    seat = ControllerSeat(clock=lambda: clock["t"])
    seat.claim("a", 1, ttl_ms=100.0)
    clock["t"] += 10.0  # seconds: far past the 100ms TTL
    refused = seat.claim("b", 1)
    # Same-epoch rivals stay refused even expired — only a HIGHER epoch
    # (a real election round) takes an expired seat, so a network blip
    # can never yield two same-epoch holders.
    assert not refused["granted"] and refused["expired"]
    assert seat.info()["expired"]
    assert seat.claim("b", 2)["granted"]


# ---------------------------------------------------------------------------
# FleetControlPlane: election, broadcast, demotion
# ---------------------------------------------------------------------------

def test_plane_elects_with_majority_and_broadcasts_one_generation():
    clock = {"t": T0}
    storages, members, lid, limiter = make_cell(clock)
    plane = make_plane(members, limiter)
    assert not plane.is_leader
    with pytest.raises(NotLeader):
        plane.set_policy(lid, RateLimitConfig(max_permits=10,
                                              window_ms=1000))
    assert plane.elect()
    assert plane.is_leader and plane.epoch == 1
    gen = plane.set_policy(lid, RateLimitConfig(max_permits=10,
                                                window_ms=1000))
    assert gen == 1 and plane.last_broadcast_generation == 1
    for st in storages:
        info = st.policy_info()
        assert info["generation"] == 1
        assert info["lids"][lid]["max_permits"] == 10
    assert plane.node_generations == {"n0": 1, "n1": 1}
    with pytest.raises(KeyError):
        plane.set_policy(99, RateLimitConfig(max_permits=5,
                                             window_ms=1000))
    for st in storages:
        st.close()


def test_plane_without_majority_does_not_lead():
    clock = {"t": T0}
    storages, members, _, limiter = make_cell(clock, n=3)
    members["n1"].unreachable = True
    members["n2"].unreachable = True
    plane = make_plane(members, limiter)
    assert not plane.elect()  # 1 of 3 seats is no quorum
    assert not plane.is_leader
    for st in storages:
        st.close()


def test_plane_superseded_demotes_and_refuses_to_actuate():
    clock = {"t": T0}
    storages, members, lid, limiter = make_cell(clock)
    old = make_plane(members, limiter, node="ctrl-old")
    new = make_plane(members, limiter, node="ctrl-new")
    assert old.elect() and old.epoch == 1
    assert new.elect() and new.epoch == 2  # observed 1, claims 2
    # The old leader learns it was superseded at its next heartbeat
    # and self-demotes; its actuations refuse BEFORE touching a seat.
    assert not old.maintain()
    assert not old.is_leader and old.demote_reason == "superseded"
    with pytest.raises(NotLeader):
        old.set_policy(lid, RateLimitConfig(max_permits=5,
                                            window_ms=1000))
    # Its zombie frame (stale epoch, forced past the self-fence) dies
    # at every seat without moving a row.
    row = {str(lid): {"algo": "sw", "max_permits": 5, "window_ms": 1000,
                      "refill_rate": 0.0, "gen": 9}}
    for name, member in members.items():
        resp = member.set_policy_rows(row, old.epoch, "ctrl-old")
        assert resp["stale_epoch"] and not resp["applied"], name
    for st in storages:
        assert st.policy_info()["lids"][lid]["max_permits"] == 40
    # The rightful leader still actuates.
    assert new.set_policy(lid, RateLimitConfig(max_permits=20,
                                               window_ms=1000)) >= 1
    for st in storages:
        st.close()


def test_plane_own_clock_lease_expiry_self_demotes():
    clock = {"t": T0}
    mono = {"t": 0.0}
    storages, members, lid, limiter = make_cell(clock)
    plane = make_plane(members, limiter, mono=mono, ttl_ms=500.0)
    assert plane.elect()
    mono["t"] += 499.0
    assert plane.self_check()
    # Sever BOTH seats: renewals stop landing a majority, and once the
    # plane's OWN clock passes the TTL it must assume a rival won.
    for member in members.values():
        member.unreachable = True
    mono["t"] += 2.0
    assert not plane.renew()
    assert plane.is_leader  # not yet expired on its own clock... barely
    mono["t"] += 500.0
    assert not plane.self_check()
    assert not plane.is_leader
    assert plane.demote_reason == "lease_expired"
    with pytest.raises(NotLeader):
        plane.set_policy(lid, RateLimitConfig(max_permits=5,
                                              window_ms=1000))
    for st in storages:
        st.close()


def test_plane_converge_anti_entropies_a_stale_member():
    clock = {"t": T0}
    storages, members, lid, limiter = make_cell(clock)
    plane = make_plane(members, limiter)
    assert plane.elect()
    plane.set_policy(lid, RateLimitConfig(max_permits=10,
                                          window_ms=1000))
    # A re-seeded member joins at generation 0 with the same
    # registrations: converge pushes the leader's newest rows to it.
    fresh = make_storage(clock)
    assert fresh.register_limiter("sw", limiter) == lid
    plane.add_member("n2", TableBackend(controller_handlers(fresh)))
    # The new seat has never granted the leader's epoch: a broadcast
    # would be refused (stale epoch 0 < ... no: seat epoch is 0, the
    # leader's 1 wins) — converge claims nothing, so re-elect first.
    assert plane.elect()  # re-claims every seat (epoch 2), converges
    assert fresh.policy_info()["generation"] == 1
    assert fresh.policy_info()["lids"][lid]["max_permits"] == 10
    assert plane.converged()
    for st in storages:
        st.close()
    fresh.close()


# ---------------------------------------------------------------------------
# ControllerElection: the repair loop
# ---------------------------------------------------------------------------

def test_election_fails_over_to_the_standby_candidate():
    clock = {"t": T0}
    mono = {"t": 0.0}
    storages, members, lid, limiter = make_cell(clock)
    registry = MeterRegistry()
    a = make_plane(members, limiter, node="ctrl-a", mono=mono,
                   ttl_ms=500.0)
    # ctrl-b gets its OWN links to the same seats — the partition cuts
    # one controller's world, not the seats themselves.
    members_b = {name: TableBackend(m.table)
                 for name, m in members.items()}
    b = make_plane(members_b, limiter, node="ctrl-b")
    election = ControllerElection([a, b], registry=registry)
    election.tick()
    assert election.leader() is a and a.epoch == 1
    # Healthy ticks keep the lease renewed.
    mono["t"] += 400.0
    election.tick()
    mono["t"] += 400.0
    election.tick()
    assert election.leader() is a
    # Kill ctrl-a's links: the tick demotes it (own-clock lease) and
    # seats ctrl-b at the NEXT epoch in the same repair pass.
    for member in members.values():
        member.unreachable = True
    mono["t"] += 600.0
    election.tick()
    assert not a.is_leader and a.demote_reason == "lease_expired"
    assert election.leader() is b and b.epoch == 2
    assert election.elections == 2
    meters = registry.scrape()
    assert meters["ratelimiter.control.leader"] == 1.0
    assert meters["ratelimiter.control.elections"] == 2
    assert meters["ratelimiter.control.converge_ms"] >= 0.0
    # The healed zombie's writes die at the seats and are EXPORTED:
    # its next broadcast attempt self-fences, and a forced frame bumps
    # stale_rejected on every seat (scraped via the election tick).
    for member in members.values():
        member.unreachable = False
    row = {str(lid): {"algo": "sw", "max_permits": 5, "window_ms": 1000,
                      "refill_rate": 0.0, "gen": 9}}
    for member in members.values():
        assert member.set_policy_rows(row, a.epoch, "ctrl-a")["stale_epoch"]
    election.tick()
    assert registry.scrape()["ratelimiter.control.stale_rejected"] == 0
    # (stale_rejected meters the CANDIDATES' own refusals-at-claim;
    # node-side seat counts surface via /actuator/controller instead.)
    assert all(st.policy_info()["lids"][lid]["max_permits"] == 40
               for st in storages)
    election.close()
    for st in storages:
        st.close()


def test_election_note_join_converges_the_newcomer():
    clock = {"t": T0}
    storages, members, lid, limiter = make_cell(clock)
    plane = make_plane(members, limiter)
    election = ControllerElection([plane])
    election.tick()
    plane.set_policy(lid, RateLimitConfig(max_permits=10,
                                          window_ms=1000))
    fresh = make_storage(clock)
    assert fresh.register_limiter("sw", limiter) == lid
    seat = ControllerSeat()
    backend = TableBackend(controller_handlers(fresh, seat))
    # A promoted/re-seeded standby joins: it must not serve gen 0
    # while its peers serve gen 1.
    seat.claim(plane.node, plane.epoch)  # promotion handshake grants
    election.note_join("n2", backend)
    assert fresh.policy_info()["generation"] == 1
    assert fresh.policy_info()["lids"][lid]["max_permits"] == 10
    assert plane.node_generations["n2"] == 1
    election.close()
    for st in storages:
        st.close()
    fresh.close()


# ---------------------------------------------------------------------------
# The AIMD controller over the fleet plane
# ---------------------------------------------------------------------------

def _storm(st, lid, demand, now):
    st.acquire_many("sw", [lid] * demand, ["hot"] * demand, [1] * demand)


def test_controller_over_plane_cuts_fleet_wide():
    clock = {"t": T0}
    storages, members, lid, limiter = make_cell(clock)
    plane = make_plane(members, limiter)
    assert plane.elect()
    ctl = AdaptivePolicyController(
        plane, ControlConfig(interval_ms=1000.0, window_ms=2000,
                             target_excess=0.5, decrease_factor=0.5,
                             min_load_per_s=1.0),
        clock_ms=lambda: clock["t"])
    for _ in range(2):
        clock["t"] += 1000
        for st in storages:
            _storm(st, lid, 300, clock["t"])  # 40 admitted, 260 denied
        ctl.tick()
    assert ctl.adjustments_total >= 1
    # The cut is ONE broadcast landing on EVERY node at one generation.
    gens = {st.policy_info()["generation"] for st in storages}
    assert len(gens) == 1 and gens.pop() >= 1
    cuts = [st.policy_info()["lids"][lid]["max_permits"]
            for st in storages]
    assert all(c < limiter.max_permits for c in cuts)
    assert len(set(cuts)) == 1
    ctl.close()
    for st in storages:
        st.close()


def test_stale_fleet_signals_freeze_raises_allow_cuts():
    """An unreachable member makes the plane's staleness infinite:
    raises freeze (a partitioned reporter's silence must not justify
    relaxing), cuts stay allowed, and the episode is one coalesced
    control.signals_stale flight event."""
    clock = {"t": T0}
    storages, members, lid, limiter = make_cell(clock)
    recorder = FlightRecorder(64)
    plane = make_plane(members, limiter)
    assert plane.elect()
    ctl = AdaptivePolicyController(
        plane, ControlConfig(interval_ms=1000.0, window_ms=2000,
                             target_excess=0.5, decrease_factor=0.5,
                             staleness_bound_ms=10_000.0,
                             event_coalesce_ms=10_000.0,
                             min_load_per_s=1.0),
        clock_ms=lambda: clock["t"], recorder=recorder)
    # Storm -> cut while healthy.
    clock["t"] += 1000
    for st in storages:
        _storm(st, lid, 300, clock["t"])
    ctl.tick()
    cut = storages[0].policy_info()["lids"][lid]["max_permits"]
    assert cut < limiter.max_permits
    clock["t"] += 5000  # the storm ages out of the signals window
    # Partition one member: staleness goes to the unreachable sentinel.
    members["n1"].unreachable = True
    assert plane.telemetry.all_signals(2000) is not None
    assert plane.telemetry.staleness_ms() == STALE_UNREACHABLE_MS
    # Healthy-looking signals from the remaining member would RAISE —
    # stale signals must hold the cut instead.
    for _ in range(3):
        clock["t"] += 1000
        _storm(storages[0], lid, 5, clock["t"])  # light, healthy load
        ctl.tick()
    assert ctl.signals_stale_ticks >= 3
    held = storages[0].policy_info()["lids"][lid]["max_permits"]
    assert held == cut, "a stale plane RAISED a limit"
    # A storm during the partition still cuts (observed evidence of
    # overload is safe to act on even if old).
    clock["t"] += 1000
    _storm(storages[0], lid, 300, clock["t"])
    ctl.tick()
    assert storages[0].policy_info()["lids"][lid]["max_permits"] < cut
    kinds = [e["kind"] for e in recorder.snapshot(last=64)["events"]]
    assert kinds.count("control.signals_stale") == 1  # coalesced
    assert ctl.status()["signals_stale_ticks"] == ctl.signals_stale_ticks
    ctl.close()
    for st in storages:
        st.close()


# ---------------------------------------------------------------------------
# The drill (fast shape)
# ---------------------------------------------------------------------------

def test_partitioned_controller_drill_fast():
    from ratelimiter_tpu.storage.chaos import partitioned_controller_drill

    report = partitioned_controller_drill(pre_waves=2, storm_waves=2)
    assert report["mismatches"] == 0 and report["decisions"] > 0
    assert report["epochs"]["ctrl-b"] == report["epochs"]["ctrl-a"] + 1
    assert report["demote_reason"] == "lease_expired"
    assert report["stale_refused"] == 2
    assert report["goodput_ratio"] >= 0.8
    assert report["elections"] == 2
