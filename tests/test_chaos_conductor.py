"""Chaos conductor (ARCHITECTURE §17): seeded schedules, the fleet
harness + invariant monitor, failure minimization, and the satellites
that ride along — the forward clock-jump clamp and the seeded-random
BulkPool conservation property.

Layout mirrors the package:

- plan: determinism (same inputs → byte-identical schedule), JSON
  roundtrip, generator hygiene (heals scheduled, defects gated);
- clamp: LeaseTable.clamp_forward unit behavior plus the manager-level
  claim that a poisoned forward jump does NOT mass-expire live leases
  while normal TTL expiry still works;
- sublease: conservation holds under a seeded-random interleaving of
  every BulkPool verb (the property the invariant monitor checks
  fleet-wide every step);
- fleet: a fault-free plan and a faulted plan both run to completion
  with ZERO violations; a known-bad schedule (deliberate defect)
  produces a violation the minimizer shrinks to the planted action and
  the artifact replays deterministically;
- procs (slow): a real hostproc under ProcActor honors SIGTERM (drain,
  exit 0) and survives SIGSTOP/SIGCONT.
"""

import json
import random

import pytest

from ratelimiter_tpu.chaos.plan import (
    DEFAULT_TOPOLOGY,
    DEFECT_OPS,
    FAULT_OPS,
    FaultAction,
    FaultPlan,
)
from ratelimiter_tpu.leases.manager import LeaseManager
from ratelimiter_tpu.leases.sublease import BulkPool
from ratelimiter_tpu.leases.table import LeaseTable
from ratelimiter_tpu.storage.tpu import TpuBatchedStorage
from ratelimiter_tpu.core.config import RateLimitConfig


# ---------------------------------------------------------------------------
# FaultPlan: determinism, serialization, generator hygiene
# ---------------------------------------------------------------------------

def test_plan_generation_is_deterministic():
    a = FaultPlan.generate(42, steps=32, fault_rate=0.6)
    b = FaultPlan.generate(42, steps=32, fault_rate=0.6)
    assert a.dumps() == b.dumps()
    c = FaultPlan.generate(43, steps=32, fault_rate=0.6)
    assert a.dumps() != c.dumps()


def test_plan_json_roundtrip():
    plan = FaultPlan.generate(7, steps=24, include_defects=True)
    back = FaultPlan.from_json(json.loads(json.dumps(plan.to_json())))
    assert back.dumps() == plan.dumps()
    assert back.topology == plan.topology
    assert all(isinstance(a, FaultAction) for a in back.actions)


def test_generator_emits_only_known_ops_and_gates_defects():
    clean = FaultPlan.generate(3, steps=64, fault_rate=0.9)
    assert clean.actions, "a 0.9 fault rate over 64 steps emits faults"
    assert all(a.op in FAULT_OPS for a in clean.actions)
    dirty = FaultPlan.generate(3, steps=64, fault_rate=0.9,
                               include_defects=True)
    planted = [a for a in dirty.actions if a.op in DEFECT_OPS]
    assert len(planted) == 1


def test_generator_schedules_heals_and_resumes():
    plan = FaultPlan.generate(11, steps=64, fault_rate=0.9)
    ops = [a.op for a in plan.actions]
    if any(o.startswith("edge_") and o != "edge_heal" for o in ops):
        assert "edge_heal" in ops
    pauses = [a for a in plan.actions if a.op == "pause_shard"]
    resumes = {(a.params["cell"], a.params["shard"]): a.step
               for a in plan.actions if a.op == "resume_shard"}
    for p in pauses:
        key = (p.params["cell"], p.params["shard"])
        assert key in resumes and resumes[key] > p.step, (
            "every pause must schedule its resume (the zombie probe "
            "runs at resume time)")


def test_with_actions_preserves_the_traffic_frame():
    plan = FaultPlan.generate(5, steps=16)
    cut = plan.with_actions(plan.actions[:1])
    assert (cut.seed, cut.steps, cut.fault_rate) == (
        plan.seed, plan.steps, plan.fault_rate)
    assert cut.topology == plan.topology
    assert len(cut.actions) == min(1, len(plan.actions))


def test_lazy_package_exports_resolve_to_callables():
    # `minimize` and `replay` collide with submodule names: importing
    # the submodule sets the MODULE as a package attribute, which must
    # not shadow the exported callable on a from-import.
    from ratelimiter_tpu.chaos import (
        dump_artifact, load_artifact, minimize, replay, run_plan)
    for fn in (dump_artifact, load_artifact, minimize, replay, run_plan):
        assert callable(fn), fn


# ---------------------------------------------------------------------------
# Forward clock-jump clamp (leases/table.py) — satellite
# ---------------------------------------------------------------------------

def test_clamp_forward_passes_normal_steps():
    t = LeaseTable(max_forward_jump_ms=10_000)
    assert t.clamp_forward(1_000) == 1_000
    assert t.clamp_forward(5_000) == 5_000       # within threshold
    assert t.clamp_forward(15_000) == 15_000     # exactly 10_000 ahead
    assert t.forward_clamps == 0


def test_clamp_forward_absorbs_a_jump():
    t = LeaseTable(max_forward_jump_ms=10_000, forward_step_ms=1_000)
    assert t.clamp_forward(0) == 0
    got = t.clamp_forward(1_000_000)             # a poisoned jump
    assert got == 1_000                          # one bounded step
    assert t.forward_clamps == 1
    # The jump is absorbed as an offset: the same wall reading maps to
    # the SAME rebased now (a sweep over many keys can't creep the
    # expiry clock), and wall progress resumes at 1x from there.
    assert t.clamp_forward(1_000_000) == 1_000
    assert t.clamp_forward(1_000_500) == 1_500
    assert t.forward_clamps == 1


def test_clamp_forward_backward_and_disabled():
    t = LeaseTable(max_forward_jump_ms=10_000)
    t.clamp_forward(50_000)
    assert t.clamp_forward(40_000) == 40_000     # backward: untouched
    off = LeaseTable()                           # clamp disabled
    off.clamp_forward(0)
    assert off.clamp_forward(10 ** 12) == 10 ** 12
    assert off.forward_clamps == 0


def test_forward_jump_does_not_mass_expire_leases():
    """The manager-level claim: a huge injected forward jump degrades
    into clamped ticks — live clients keep renewing through it instead
    of every lease expiring in one poisoned sweep."""
    clock = {"t": 1_000_000}
    storage = TpuBatchedStorage(num_slots=256,
                                clock_ms=lambda: clock["t"])
    cfg = RateLimitConfig(max_permits=1 << 12, window_ms=60_000,
                          refill_rate=500.0)
    lid = storage.register_limiter("tb", cfg)
    mgr = LeaseManager(storage, default_budget=8, ttl_ms=2_000.0,
                       clock_ms=lambda: clock["t"])
    try:
        keys = [f"k{i}" for i in range(8)]
        for k in keys:
            assert mgr.grant(lid, k).granted > 0
        clock["t"] += 10 ** 9                    # the poisoned jump
        for k in keys:
            resp = mgr.renew(lid, k, used=1)
            assert resp is not None and resp.granted > 0, (
                f"lease {k} mass-expired through a clamped forward "
                f"jump")
        assert mgr.table.forward_clamps >= 1
        assert mgr.expired_total == 0
    finally:
        storage.close()


def test_normal_ttl_expiry_still_works_with_the_clamp():
    clock = {"t": 1_000_000}
    storage = TpuBatchedStorage(num_slots=256,
                                clock_ms=lambda: clock["t"])
    cfg = RateLimitConfig(max_permits=1 << 12, window_ms=60_000,
                          refill_rate=500.0)
    lid = storage.register_limiter("tb", cfg)
    mgr = LeaseManager(storage, default_budget=8, ttl_ms=2_000.0,
                       clock_ms=lambda: clock["t"])
    try:
        assert mgr.grant(lid, "k").granted > 0
        clock["t"] += 2_001                      # one ordinary TTL lapse
        assert mgr.renew(lid, "k", used=0) is None
        assert mgr.expired_total == 1
        assert mgr.table.forward_clamps == 0, (
            "an ordinary TTL-sized step must pass the clamp untouched")
    finally:
        storage.close()


# ---------------------------------------------------------------------------
# BulkPool conservation under seeded-random interleaving — satellite
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_bulk_pool_conserves_under_random_interleaving(seed):
    """Every interleaving of slice/fold/return/top_up/renewal/lost/
    over-report conserves ``remaining + sliced_out + used_pending ==
    budget + deficit`` — checked after EVERY verb, exactly what the
    fleet monitor asserts per step."""
    rng = random.Random(seed)
    pool = BulkPool(lid=1, key="k", budget=256, remaining=256,
                    epoch=0, deadline_ms=10_000)
    sessions = list(range(6))
    for step in range(400):
        sid = rng.choice(sessions)
        sub = pool.subs.get(sid)
        op = rng.randrange(7)
        if op == 0:
            pool.slice(sid, rng.randint(1, 64))
        elif op == 1 and sub is not None:
            pool.fold_used(sub, rng.randint(0, sub.amount + 8))
        elif op == 2 and sub is not None:
            pool.return_unused(sub)
        elif op == 3 and sub is not None:
            pool.top_up(sub, rng.randint(1, 64))
        elif op == 4 and sub is not None:
            pool.fold_lost(pool.drop_sub(sid))
        elif op == 5:
            pool.fold_over_report(rng.randint(0, 16))
        elif op == 6:
            # Renewal, sometimes shrinking below what's sliced out —
            # the deficit path.
            pool.apply_renewal(rng.randint(32, 256), 5_000, 0, 0,
                               rng.randint(0, pool.used_pending))
        pool.check_conservation()
        assert pool.remaining >= 0 and pool.sliced_out >= 0
        assert pool.used_pending >= 0 and pool.deficit >= 0


# ---------------------------------------------------------------------------
# FleetHarness: clean runs, known-bad fixtures, minimize + replay
# ---------------------------------------------------------------------------

def _small_topology(**over):
    topo = {"n_direct_keys": 12, "n_lease_keys": 4, "n_edge_keys": 3}
    topo.update(over)
    return topo


def test_fault_free_plan_runs_clean():
    from ratelimiter_tpu.chaos.harness import run_plan

    plan = FaultPlan.generate(0, steps=6, fault_rate=0.0,
                              topology=_small_topology())
    report = run_plan(plan)
    assert report["violation"] is None
    assert report["steps_completed"] == 6
    assert report["decisions"] > 0
    assert report["invariant_checks"] == 6


def test_faulted_plan_runs_clean():
    """The acceptance shape: a seeded multi-fault schedule (kills,
    pauses, clock jumps, edge faults, storage faults) completes with
    zero violations AND the final reserve/credit replay reconciles
    against the oracle bit-for-bit."""
    from ratelimiter_tpu.chaos.harness import run_plan

    plan = FaultPlan.generate(0, steps=14, fault_rate=0.5,
                              topology=_small_topology())
    assert plan.actions, "seed 0 must exercise real faults"
    report = run_plan(plan)
    assert report["violation"] is None
    assert report["steps_completed"] == 14


def test_planted_epoch_rollback_is_caught_minimized_and_replayed(tmp_path):
    """The known-bad fixture end to end: a deliberately corrupted
    schedule fails, the minimizer strips it to the planted defect, the
    artifact round-trips, and the replay reproduces the SAME invariant
    deterministically."""
    from ratelimiter_tpu.chaos.harness import run_plan
    from ratelimiter_tpu.chaos.minimize import minimize
    from ratelimiter_tpu.chaos.replay import (
        dump_artifact,
        load_artifact,
        replay,
    )

    base = FaultPlan.generate(5, steps=10, fault_rate=0.4,
                              topology=_small_topology())
    bad = base.with_actions(
        list(base.actions)
        + [FaultAction(5, "epoch_rollback", {"cell": 1})])

    res = minimize(bad, max_runs=16)
    assert res["reproduced"]
    v = res["violation"]
    assert v["invariant"] == "epoch-monotonicity"
    kept = res["plan"].actions
    assert [a.op for a in kept] == ["epoch_rollback"], (
        f"minimizer kept noise: {[a.to_dict() for a in kept]}")

    path = str(tmp_path / "artifact.json")
    dump_artifact(path, res["plan"], v, minimized=True,
                  original_actions=len(bad.actions))
    art = load_artifact(path)
    assert art["minimized"] and art["original_actions"] == len(bad.actions)

    rep = replay(art)
    assert rep["reproduced"], (
        f"replay diverged: expected [{v['invariant']}], "
        f"got {rep.get('violation')}")
    assert rep["violation"]["step"] == v["step"]
    # Determinism: replaying twice is byte-for-byte the same verdict.
    rep2 = replay(art)
    assert rep2["violation"] == rep["violation"]

    # And the clean base still passes (the defect WAS the failure).
    assert run_plan(base)["violation"] is None


def test_planted_pool_leak_is_caught():
    from ratelimiter_tpu.chaos.harness import run_plan

    topo = dict(DEFAULT_TOPOLOGY)
    topo.update(_small_topology())
    plan = FaultPlan(seed=3, steps=8, topology=topo,
                     actions=[FaultAction(4, "pool_leak", {"cell": 0})])
    report = run_plan(plan)
    v = report["violation"]
    assert v is not None and v["invariant"] == "conservation"


@pytest.mark.slow
def test_tcp_edge_topology_runs_clean():
    """The same schedule shape over a REAL wire: sidecar server behind
    a FaultInjectingProxy, garbage/partition faults included."""
    from ratelimiter_tpu.chaos.harness import run_plan

    plan = FaultPlan.generate(1, steps=12, fault_rate=0.5,
                              topology=_small_topology(edge="tcp"))
    report = run_plan(plan)
    assert report["violation"] is None
    assert report["steps_completed"] == 12


# ---------------------------------------------------------------------------
# ProcActor: real processes under signal control — slow
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_procactor_sigterm_is_a_graceful_stop():
    from ratelimiter_tpu.chaos.actors import ProcActor

    actor = ProcActor(["ratelimiter_tpu.replication.hostproc",
                       "--role", "standby", "--shards", "1",
                       "--num-slots", "128"])
    try:
        ready = actor.spawn(timeout_s=180.0)
        assert ready.get("ready") and ready.get("role") == "standby"
        rc = actor.stop_graceful(timeout_s=30.0)
        assert rc == 0, (
            f"SIGTERM must drain and exit 0 (the graceful path), "
            f"got rc={rc}")
    finally:
        actor.close()


@pytest.mark.slow
def test_procactor_sigstop_pause_preserves_the_process():
    """The zombie shape at the process level: SIGSTOP freezes the node
    (state intact, sockets open), SIGCONT revives it, and the revived
    process still honors the graceful stop."""
    import time

    from ratelimiter_tpu.chaos.actors import ProcActor

    actor = ProcActor(["ratelimiter_tpu.replication.hostproc",
                       "--role", "standby", "--shards", "1",
                       "--num-slots", "128"])
    try:
        actor.spawn(timeout_s=180.0)
        actor.pause()
        time.sleep(0.2)
        assert actor.proc.poll() is None, "SIGSTOP killed the process"
        actor.resume()
        time.sleep(0.2)
        rc = actor.stop_graceful(timeout_s=30.0)
        assert rc == 0, f"revived process lost the drain path: rc={rc}"
    finally:
        actor.close()
