"""Segmented primitives vs brute-force sequential reference."""

import numpy as np
import pytest

import jax.numpy as jnp

from ratelimiter_tpu.ops.segments import (
    first_occurrence,
    last_occurrence,
    segment_totals,
    segmented_cumsum_exclusive,
    solve_threshold_recurrence,
)


def brute_force(u, w, first):
    """Sequential semantics: inc[j] = (sum of w[i]*inc[i] for prior i in the
    same segment) <= u[j]."""
    inc = np.zeros(len(u), dtype=np.int64)
    s = 0
    for j in range(len(u)):
        if first[j]:
            s = 0
        inc[j] = 1 if s <= u[j] else 0
        s += w[j] * inc[j]
    return inc


def test_first_last_occurrence():
    slots = jnp.array([-1, -1, 0, 0, 0, 3, 7, 7], dtype=jnp.int32)
    assert list(np.asarray(first_occurrence(slots))) == [1, 0, 1, 0, 0, 1, 1, 0]
    assert list(np.asarray(last_occurrence(slots))) == [0, 1, 0, 0, 1, 1, 0, 1]


def test_segmented_cumsum():
    slots = jnp.array([0, 0, 0, 2, 2, 5], dtype=jnp.int32)
    x = jnp.array([3, 1, 4, 1, 5, 9], dtype=jnp.int64)
    first = first_occurrence(slots)
    out = segmented_cumsum_exclusive(x, first)
    assert list(np.asarray(out)) == [0, 3, 4, 0, 1, 0]
    tot = segment_totals(x, first)
    assert list(np.asarray(tot)) == [3, 4, 8, 1, 6, 9]


@pytest.mark.parametrize("seed", range(8))
def test_solver_matches_sequential(seed):
    rng = np.random.default_rng(seed)
    n = 512
    # Random segment structure, including long segments (duplicate-heavy).
    slots = np.sort(rng.integers(0, rng.integers(2, 40), size=n)).astype(np.int32)
    w = rng.integers(1, 10, size=n).astype(np.int64)
    u = rng.integers(-5, 30, size=n).astype(np.int64)
    first = np.asarray(first_occurrence(jnp.asarray(slots)))
    got = np.asarray(
        solve_threshold_recurrence(jnp.asarray(u), jnp.asarray(w), jnp.asarray(first)))
    want = brute_force(u, w, first)
    np.testing.assert_array_equal(got, want)


def test_solver_single_hot_segment():
    # Entire batch is one segment with uniform weights — the single-key
    # benchmark shape; must converge fast and exactly.
    n = 4096
    u = jnp.full((n,), 100, dtype=jnp.int64)
    w = jnp.ones((n,), dtype=jnp.int64)
    first = jnp.zeros((n,), dtype=bool).at[0].set(True)
    inc = np.asarray(solve_threshold_recurrence(u, w, first))
    # First 101 pass (S=0..100 <= 100), rest fail.
    assert inc.sum() == 101
    assert inc[:101].all() and not inc[101:].any()


def test_solver_padding_never_passes():
    u = jnp.array([-1, -1, 5], dtype=jnp.int64)
    w = jnp.ones((3,), dtype=jnp.int64)
    first = jnp.array([True, False, True])
    inc = np.asarray(solve_threshold_recurrence(u, w, first))
    assert list(inc) == [0, 0, 1]
