"""Sharded engine on the 8-device CPU mesh.

- differential vs oracle through the full limiter stack (TpuBatchedStorage
  wired to a ShardedDeviceEngine),
- exact equivalence sharded-vs-single-device on an identical stream,
- shard routing invariants and the psum metrics totals.
"""

import random

import numpy as np

import jax

from ratelimiter_tpu import RateLimitConfig
from ratelimiter_tpu.algorithms import SlidingWindowRateLimiter, TokenBucketRateLimiter
from ratelimiter_tpu.engine.engine import DeviceEngine
from ratelimiter_tpu.engine.state import LimiterTable
from ratelimiter_tpu.metrics import MeterRegistry
from ratelimiter_tpu.parallel import ShardedDeviceEngine, shard_of_key
from ratelimiter_tpu.semantics import SlidingWindowOracle, TokenBucketOracle
from ratelimiter_tpu.storage import TpuBatchedStorage

T0 = 1_753_000_000_000


class FakeClock:
    def __init__(self, t=T0):
        self.t = t

    def __call__(self):
        return self.t


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8


def test_sharded_slot_index_routing():
    eng_table = LimiterTable()
    engine = ShardedDeviceEngine(slots_per_shard=32, table=eng_table)
    idx = engine.make_slot_index()
    for i in range(100):
        key = (1, f"user{i}")
        slot, _ = idx.assign(key)
        assert slot // 32 == shard_of_key(key, engine.n_shards)
        assert idx.get(key) == slot


def test_sharded_equivalent_to_single_device():
    rng = random.Random(9)
    cfg_sw = RateLimitConfig(max_permits=12, window_ms=1500, enable_local_cache=False)
    cfg_tb = RateLimitConfig(max_permits=20, window_ms=2000, refill_rate=25.0)

    t1 = LimiterTable()
    single = DeviceEngine(num_slots=256, table=t1)
    lid_sw1, lid_tb1 = t1.register(cfg_sw), t1.register(cfg_tb)

    t2 = LimiterTable()
    sharded = ShardedDeviceEngine(slots_per_shard=32, table=t2)
    lid_sw2, lid_tb2 = t2.register(cfg_sw), t2.register(cfg_tb)
    assert (lid_sw1, lid_tb1) == (lid_sw2, lid_tb2)

    # Identical slot usage on both engines: map key i -> slot i (single) and
    # key i -> (shard_of i, local i) (sharded). Decisions must agree exactly.
    keys = list(range(40))
    sh_index = sharded.make_slot_index()
    sh_slot = {k: sh_index.assign(("k", k))[0] for k in keys}

    now = T0
    for step in range(25):
        now += rng.randrange(0, 900)
        n = rng.randrange(1, 64)
        ks = [rng.choice(keys) for _ in range(n)]
        perms = [rng.randrange(1, 4) for _ in range(n)]
        a = single.sw_acquire(ks, [lid_sw1] * n, perms, now)
        b = sharded.sw_acquire([sh_slot[k] for k in ks], [lid_sw2] * n, perms, now)
        np.testing.assert_array_equal(a["allowed"], b["allowed"])
        np.testing.assert_array_equal(a["observed"], b["observed"])
        a = single.tb_acquire(ks, [lid_tb1] * n, perms, now)
        b = sharded.tb_acquire([sh_slot[k] for k in ks], [lid_tb2] * n, perms, now)
        np.testing.assert_array_equal(a["allowed"], b["allowed"])
        np.testing.assert_array_equal(a["remaining"], b["remaining"])
        # psum totals: allowed count across all shards == batch-wide truth.
        assert sharded.last_step_totals[1] == n


def test_full_stack_on_sharded_engine_vs_oracle():
    clock = FakeClock()
    table = LimiterTable()
    engine = ShardedDeviceEngine(slots_per_shard=64, table=table)
    storage = TpuBatchedStorage(engine=engine, max_delay_ms=0.2, clock_ms=clock)
    cfg_sw = RateLimitConfig(max_permits=10, window_ms=1000, enable_local_cache=False)
    cfg_tb = RateLimitConfig(max_permits=30, window_ms=2000, refill_rate=40.0)
    sw = SlidingWindowRateLimiter(storage, cfg_sw, MeterRegistry(), clock_ms=clock)
    tb = TokenBucketRateLimiter(storage, cfg_tb, MeterRegistry(), clock_ms=clock)
    osw, otb = SlidingWindowOracle(cfg_sw), TokenBucketOracle(cfg_tb)

    rng = random.Random(13)
    keys = [f"u{i}" for i in range(24)]
    for step in range(40):
        clock.t += rng.randrange(0, 500)
        n = rng.randrange(1, 48)
        ks = [rng.choice(keys) for _ in range(n)]
        perms = [rng.randrange(1, 5) for _ in range(n)]
        got = sw.try_acquire_many(ks, perms)
        for j in range(n):
            assert got[j] == osw.try_acquire(ks[j], perms[j], clock.t).allowed, (step, j)
        got = tb.try_acquire_many(ks, perms)
        for j in range(n):
            assert got[j] == otb.try_acquire(ks[j], perms[j], clock.t).allowed, (step, j)
        if rng.random() < 0.15:
            k = rng.choice(keys)
            sw.reset(k)
            osw.reset(k, clock.t)
            tb.reset(k)
            otb.reset(k, clock.t)
        k = rng.choice(keys)
        assert sw.get_available_permits(k) == osw.get_available_permits(k, clock.t)
        assert tb.get_available_permits(k) == otb.get_available_permits(k, clock.t)
    storage.close()


def test_native_shard_route_matches_numpy():
    """The C routing pass must be bit-identical to shard_of_int_keys +
    stable argsort (scalar and stream paths must agree on shards)."""
    import numpy as np
    import pytest

    from ratelimiter_tpu.engine.native_index import shard_route
    from ratelimiter_tpu.parallel.sharded import shard_of_int_keys

    if shard_route(np.asarray([1], dtype=np.int64), 2) is None:
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(3)
    for n_sh in (1, 2, 3, 8):
        keys = rng.integers(-(1 << 62), 1 << 62, 5000)
        shard, order, counts = shard_route(keys, n_sh)
        want = shard_of_int_keys(keys, n_sh)
        np.testing.assert_array_equal(shard, want)
        np.testing.assert_array_equal(order,
                                      np.argsort(want, kind="stable"))
        np.testing.assert_array_equal(counts,
                                      np.bincount(want, minlength=n_sh))


def test_sharded_str_stream_matches_single_device():
    """The r6 sharded STRING stream (hash once -> fingerprint routing ->
    per-shard fps assigns, pipelined) must decide bit-identically to the
    single-device string stream AND stay consistent with interleaved
    scalar calls on the same keys."""
    clock = FakeClock()
    cfg = RateLimitConfig(max_permits=5, window_ms=60_000, refill_rate=1.0)

    eng = ShardedDeviceEngine(slots_per_shard=256, table=LimiterTable())
    st_sharded = TpuBatchedStorage(engine=eng, clock_ms=clock)
    st_single = TpuBatchedStorage(num_slots=2048, clock_ms=clock)
    lid_s = st_sharded.register_limiter("tb", cfg)
    lid_f = st_single.register_limiter("tb", cfg)
    assert st_sharded._index["tb"].supports_batch_strs

    rng = np.random.default_rng(5)
    ids = rng.zipf(1.3, size=8000).astype(np.int64) % 300
    keys = [f"user-{i}" for i in ids]
    for _ in range(2):  # second pass exercises staging-buffer reuse
        a = st_sharded.acquire_stream_strs("tb", lid_s, keys)
        b = st_single.acquire_stream_strs("tb", lid_f, keys)
        np.testing.assert_array_equal(a, b)
        clock.t += 700
    # Scalar interleave: both storages agree afterward too.
    ra = st_sharded.acquire("tb", lid_s, "user-7", 1)
    rb = st_single.acquire("tb", lid_f, "user-7", 1)
    assert ra["allowed"] == rb["allowed"]
    a = st_sharded.acquire_stream_strs("tb", lid_s, keys[:1000])
    b = st_single.acquire_stream_strs("tb", lid_f, keys[:1000])
    np.testing.assert_array_equal(a, b)
    st_sharded.close()
    st_single.close()


def test_device_route_count_matches_host_router():
    """The on-mesh route-and-count pass (build_route_count, r8) must bin
    bit-identically to the host router — (shard, order, counts) — for
    int keys (splitmix64) and string fingerprints (h1), including the
    empty-shard, all-one-shard and empty-chunk edge cases."""
    import numpy as np

    from ratelimiter_tpu.engine.native_index import route_hashes_gather
    from ratelimiter_tpu.parallel.sharded import shard_of_int_keys
    from ratelimiter_tpu.storage.tpu import _route_chunk

    engine = ShardedDeviceEngine(slots_per_shard=32, table=LimiterTable())
    n_sh = engine.n_shards
    rng = np.random.default_rng(11)

    # Int keys (negative ids wrap through uint64 exactly like the host).
    keys = rng.integers(-(1 << 62), 1 << 62, 4096).astype(np.int64)
    h_shard, h_order, h_counts = _route_chunk(keys, n_sh)
    d_shard, d_order, d_counts = engine.route_on_device(key_ids=keys)
    np.testing.assert_array_equal(h_shard, d_shard)
    np.testing.assert_array_equal(h_order, d_order)
    np.testing.assert_array_equal(h_counts, d_counts)

    # String fingerprints: route by the h1 stream, exactly as
    # shard_of_key's string branch does.
    h1 = rng.integers(0, 1 << 63, 2048).astype(np.uint64) * np.uint64(3)
    h2 = rng.integers(0, 1 << 63, 2048).astype(np.uint64)
    hs, ho, hc, h1s, h2s = route_hashes_gather(h1, h2, n_sh)
    ds, do, dc = engine.route_on_device(hashes=h1)
    np.testing.assert_array_equal(hs, ds)
    np.testing.assert_array_equal(ho, do)
    np.testing.assert_array_equal(hc, dc)
    np.testing.assert_array_equal(h1s, h1[do])
    np.testing.assert_array_equal(h2s, h2[do])

    # All-one-shard: every key identical -> one full row, rest empty.
    k1 = np.full(300, 424242, dtype=np.int64)
    tgt = int(shard_of_int_keys(k1[:1], n_sh)[0])
    s1, o1, c1 = engine.route_on_device(key_ids=k1)
    assert c1[tgt] == 300 and c1.sum() == 300
    np.testing.assert_array_equal(o1, np.arange(300))
    np.testing.assert_array_equal(s1, np.full(300, tgt))

    # Empty shards exist in a tiny chunk (n < n_shards).
    k2 = np.asarray([7], dtype=np.int64)
    s2, o2, c2 = engine.route_on_device(key_ids=k2)
    assert c2.sum() == 1 and (c2 == 0).sum() == n_sh - 1

    # Empty chunk.
    s0, o0, c0 = engine.route_on_device(
        key_ids=np.asarray([], dtype=np.int64))
    assert len(s0) == 0 and len(o0) == 0 and c0.sum() == 0


def test_sharded_stream_pipelining_invariant_under_concurrency(monkeypatch):
    """Per-shard pipelines (r8): decisions must be IDENTICAL whether the
    lanes run deeply pipelined (lookahead + concurrent bounded drains)
    or fully serialized chunk-by-chunk — on a many-chunk Zipf stream
    with EVICTION pressure, so the per-shard stream-order clear path
    (evictions cleared in a shard's own device stream ahead of the
    dispatch reusing the slots) is what keeps them equal."""
    from ratelimiter_tpu.storage import tpu as tpu_mod

    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK", 2048)
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK_MAX", 2048)

    rng = np.random.default_rng(17)
    ids = rng.zipf(1.15, size=40_000).astype(np.int64) % 16_000
    cfg = RateLimitConfig(max_permits=8, window_ms=60_000, refill_rate=2.0)

    def run(lookahead, inflight):
        monkeypatch.setattr(tpu_mod, "_SHARD_LOOKAHEAD", lookahead)
        monkeypatch.setattr(tpu_mod, "_SHARD_DRAIN_INFLIGHT", inflight)
        clock = FakeClock()
        engine = ShardedDeviceEngine(slots_per_shard=512,
                                     table=LimiterTable())
        st = TpuBatchedStorage(engine=engine, clock_ms=clock)
        lid = st.register_limiter("tb", cfg)
        outs = []
        for _ in range(2):  # uniques (16K) >> slots (4K): constant churn
            outs.append(st.acquire_stream_ids("tb", lid, ids, None))
            clock.t += 1500
        st.close()
        return outs

    pipelined = run(2, 2)
    serial = run(0, 1)
    for a, b in zip(pipelined, serial):
        np.testing.assert_array_equal(a, b)


def test_sharded_pipelined_stream_matches_single_device_multichunk(
        monkeypatch):
    """Multi-chunk sharded int stream (per-shard single-device
    dispatches, concurrent drains) must decide bit-identically to the
    flat single-device stream on an eviction-free workload."""
    from ratelimiter_tpu.storage import tpu as tpu_mod

    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK", 4096)
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK_MAX", 4096)

    rng = np.random.default_rng(23)
    ids = rng.zipf(1.2, size=32_000).astype(np.int64) % 3000
    cfg = RateLimitConfig(max_permits=12, window_ms=10_000,
                          refill_rate=20.0)

    clock_a, clock_b = FakeClock(), FakeClock()
    engine = ShardedDeviceEngine(slots_per_shard=2048,
                                 table=LimiterTable())
    st_sharded = TpuBatchedStorage(engine=engine, clock_ms=clock_a)
    st_single = TpuBatchedStorage(num_slots=1 << 14, clock_ms=clock_b)
    lid_a = st_sharded.register_limiter("tb", cfg)
    lid_b = st_single.register_limiter("tb", cfg)
    for _ in range(2):
        a = st_sharded.acquire_stream_ids("tb", lid_a, ids, None)
        b = st_single.acquire_stream_ids("tb", lid_b, ids, None)
        np.testing.assert_array_equal(a, b)
        clock_a.t += 900
        clock_b.t += 900
    # Per-shard dispatch routes are in the decision trace.
    paths = {r.get("path") for r in st_sharded.trace.snapshot()["recent"]}
    assert any(p and p.startswith("sharded|") for p in paths), paths
    st_sharded.close()
    st_single.close()


def test_sharded_route_election_records_verdict(monkeypatch):
    """RATELIMITER_DEVICE_ROUTE=auto must A/B the host router against
    the on-mesh pass once, serve the winner, and report the verdict to
    the flight recorder; forcing either side must produce identical
    decisions."""
    import os

    from ratelimiter_tpu.storage import tpu as tpu_mod

    monkeypatch.delenv("RATELIMITER_DEVICE_ROUTE", raising=False)
    rng = np.random.default_rng(29)
    ids = rng.zipf(1.2, size=70_000).astype(np.int64) % 10_000
    cfg = RateLimitConfig(max_permits=50, window_ms=60_000,
                          refill_rate=10.0)

    def run(route_env):
        if route_env is None:
            monkeypatch.delenv("RATELIMITER_DEVICE_ROUTE", raising=False)
        else:
            monkeypatch.setenv("RATELIMITER_DEVICE_ROUTE", route_env)
        clock = FakeClock()
        engine = ShardedDeviceEngine(slots_per_shard=4096,
                                     table=LimiterTable())
        st = TpuBatchedStorage(engine=engine, clock_ms=clock)
        lid = st.register_limiter("tb", cfg)
        out = st.acquire_stream_ids("tb", lid, ids, None)
        mode = st._route_mode
        events = [e for e in st._recorder.events()
                  if e.get("kind") == "sharded.route_elect"]
        st.close()
        return out, mode, events

    auto, auto_mode, auto_events = run(None)
    assert auto_mode in ("host", "device")
    assert auto_events, "election verdict missing from flight recorder"
    assert auto_events[-1]["elected"] == auto_mode
    assert auto_events[-1]["host_s"] > 0 and auto_events[-1]["device_s"] > 0

    forced_host, host_mode, _ = run("off")
    forced_dev, dev_mode, _ = run("on")
    assert host_mode == "host" and dev_mode == "device"
    np.testing.assert_array_equal(auto, forced_host)
    np.testing.assert_array_equal(auto, forced_dev)
