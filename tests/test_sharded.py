"""Sharded engine on the 8-device CPU mesh.

- differential vs oracle through the full limiter stack (TpuBatchedStorage
  wired to a ShardedDeviceEngine),
- exact equivalence sharded-vs-single-device on an identical stream,
- shard routing invariants and the psum metrics totals.
"""

import random

import numpy as np

import jax

from ratelimiter_tpu import RateLimitConfig
from ratelimiter_tpu.algorithms import SlidingWindowRateLimiter, TokenBucketRateLimiter
from ratelimiter_tpu.engine.engine import DeviceEngine
from ratelimiter_tpu.engine.state import LimiterTable
from ratelimiter_tpu.metrics import MeterRegistry
from ratelimiter_tpu.parallel import ShardedDeviceEngine, shard_of_key
from ratelimiter_tpu.semantics import SlidingWindowOracle, TokenBucketOracle
from ratelimiter_tpu.storage import TpuBatchedStorage

T0 = 1_753_000_000_000


class FakeClock:
    def __init__(self, t=T0):
        self.t = t

    def __call__(self):
        return self.t


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8


def test_sharded_slot_index_routing():
    eng_table = LimiterTable()
    engine = ShardedDeviceEngine(slots_per_shard=32, table=eng_table)
    idx = engine.make_slot_index()
    for i in range(100):
        key = (1, f"user{i}")
        slot, _ = idx.assign(key)
        assert slot // 32 == shard_of_key(key, engine.n_shards)
        assert idx.get(key) == slot


def test_sharded_equivalent_to_single_device():
    rng = random.Random(9)
    cfg_sw = RateLimitConfig(max_permits=12, window_ms=1500, enable_local_cache=False)
    cfg_tb = RateLimitConfig(max_permits=20, window_ms=2000, refill_rate=25.0)

    t1 = LimiterTable()
    single = DeviceEngine(num_slots=256, table=t1)
    lid_sw1, lid_tb1 = t1.register(cfg_sw), t1.register(cfg_tb)

    t2 = LimiterTable()
    sharded = ShardedDeviceEngine(slots_per_shard=32, table=t2)
    lid_sw2, lid_tb2 = t2.register(cfg_sw), t2.register(cfg_tb)
    assert (lid_sw1, lid_tb1) == (lid_sw2, lid_tb2)

    # Identical slot usage on both engines: map key i -> slot i (single) and
    # key i -> (shard_of i, local i) (sharded). Decisions must agree exactly.
    keys = list(range(40))
    sh_index = sharded.make_slot_index()
    sh_slot = {k: sh_index.assign(("k", k))[0] for k in keys}

    now = T0
    for step in range(25):
        now += rng.randrange(0, 900)
        n = rng.randrange(1, 64)
        ks = [rng.choice(keys) for _ in range(n)]
        perms = [rng.randrange(1, 4) for _ in range(n)]
        a = single.sw_acquire(ks, [lid_sw1] * n, perms, now)
        b = sharded.sw_acquire([sh_slot[k] for k in ks], [lid_sw2] * n, perms, now)
        np.testing.assert_array_equal(a["allowed"], b["allowed"])
        np.testing.assert_array_equal(a["observed"], b["observed"])
        a = single.tb_acquire(ks, [lid_tb1] * n, perms, now)
        b = sharded.tb_acquire([sh_slot[k] for k in ks], [lid_tb2] * n, perms, now)
        np.testing.assert_array_equal(a["allowed"], b["allowed"])
        np.testing.assert_array_equal(a["remaining"], b["remaining"])
        # psum totals: allowed count across all shards == batch-wide truth.
        assert sharded.last_step_totals[1] == n


def test_full_stack_on_sharded_engine_vs_oracle():
    clock = FakeClock()
    table = LimiterTable()
    engine = ShardedDeviceEngine(slots_per_shard=64, table=table)
    storage = TpuBatchedStorage(engine=engine, max_delay_ms=0.2, clock_ms=clock)
    cfg_sw = RateLimitConfig(max_permits=10, window_ms=1000, enable_local_cache=False)
    cfg_tb = RateLimitConfig(max_permits=30, window_ms=2000, refill_rate=40.0)
    sw = SlidingWindowRateLimiter(storage, cfg_sw, MeterRegistry(), clock_ms=clock)
    tb = TokenBucketRateLimiter(storage, cfg_tb, MeterRegistry(), clock_ms=clock)
    osw, otb = SlidingWindowOracle(cfg_sw), TokenBucketOracle(cfg_tb)

    rng = random.Random(13)
    keys = [f"u{i}" for i in range(24)]
    for step in range(40):
        clock.t += rng.randrange(0, 500)
        n = rng.randrange(1, 48)
        ks = [rng.choice(keys) for _ in range(n)]
        perms = [rng.randrange(1, 5) for _ in range(n)]
        got = sw.try_acquire_many(ks, perms)
        for j in range(n):
            assert got[j] == osw.try_acquire(ks[j], perms[j], clock.t).allowed, (step, j)
        got = tb.try_acquire_many(ks, perms)
        for j in range(n):
            assert got[j] == otb.try_acquire(ks[j], perms[j], clock.t).allowed, (step, j)
        if rng.random() < 0.15:
            k = rng.choice(keys)
            sw.reset(k)
            osw.reset(k, clock.t)
            tb.reset(k)
            otb.reset(k, clock.t)
        k = rng.choice(keys)
        assert sw.get_available_permits(k) == osw.get_available_permits(k, clock.t)
        assert tb.get_available_permits(k) == otb.get_available_permits(k, clock.t)
    storage.close()


def test_native_shard_route_matches_numpy():
    """The C routing pass must be bit-identical to shard_of_int_keys +
    stable argsort (scalar and stream paths must agree on shards)."""
    import numpy as np
    import pytest

    from ratelimiter_tpu.engine.native_index import shard_route
    from ratelimiter_tpu.parallel.sharded import shard_of_int_keys

    if shard_route(np.asarray([1], dtype=np.int64), 2) is None:
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(3)
    for n_sh in (1, 2, 3, 8):
        keys = rng.integers(-(1 << 62), 1 << 62, 5000)
        shard, order, counts = shard_route(keys, n_sh)
        want = shard_of_int_keys(keys, n_sh)
        np.testing.assert_array_equal(shard, want)
        np.testing.assert_array_equal(order,
                                      np.argsort(want, kind="stable"))
        np.testing.assert_array_equal(counts,
                                      np.bincount(want, minlength=n_sh))


def test_sharded_str_stream_matches_single_device():
    """The r6 sharded STRING stream (hash once -> fingerprint routing ->
    per-shard fps assigns, pipelined) must decide bit-identically to the
    single-device string stream AND stay consistent with interleaved
    scalar calls on the same keys."""
    clock = FakeClock()
    cfg = RateLimitConfig(max_permits=5, window_ms=60_000, refill_rate=1.0)

    eng = ShardedDeviceEngine(slots_per_shard=256, table=LimiterTable())
    st_sharded = TpuBatchedStorage(engine=eng, clock_ms=clock)
    st_single = TpuBatchedStorage(num_slots=2048, clock_ms=clock)
    lid_s = st_sharded.register_limiter("tb", cfg)
    lid_f = st_single.register_limiter("tb", cfg)
    assert st_sharded._index["tb"].supports_batch_strs

    rng = np.random.default_rng(5)
    ids = rng.zipf(1.3, size=8000).astype(np.int64) % 300
    keys = [f"user-{i}" for i in ids]
    for _ in range(2):  # second pass exercises staging-buffer reuse
        a = st_sharded.acquire_stream_strs("tb", lid_s, keys)
        b = st_single.acquire_stream_strs("tb", lid_f, keys)
        np.testing.assert_array_equal(a, b)
        clock.t += 700
    # Scalar interleave: both storages agree afterward too.
    ra = st_sharded.acquire("tb", lid_s, "user-7", 1)
    rb = st_single.acquire("tb", lid_f, "user-7", 1)
    assert ra["allowed"] == rb["allowed"]
    a = st_sharded.acquire_stream_strs("tb", lid_s, keys[:1000])
    b = st_single.acquire_stream_strs("tb", lid_f, keys[:1000])
    np.testing.assert_array_equal(a, b)
    st_sharded.close()
    st_single.close()
