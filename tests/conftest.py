"""Test harness config.

Tests run on CPU with 8 virtual devices so the sharded (multi-chip) engine
paths are exercised without TPU hardware — the key-space sharding is
device-count agnostic (SURVEY.md §4 "multi-device tests runnable on CPU").

jax may already be imported by the time this conftest runs (pytest's import
graph pulls it in), so the platform override must go through jax.config —
the JAX_PLATFORMS env var is latched at import.  XLA_FLAGS is read at first
backend initialization, which has not happened yet, so the env var works for
the virtual device count.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")
