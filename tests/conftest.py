"""Test harness config.

Tests run on CPU with 8 virtual devices so the sharded (multi-chip) engine
paths are exercised without TPU hardware — the key-space sharding is
device-count agnostic (SURVEY.md §4 "multi-device tests runnable on CPU").

jax may already be imported by the time this conftest runs (pytest's import
graph pulls it in), so the platform override must go through jax.config —
the JAX_PLATFORMS env var is latched at import.  XLA_FLAGS is read at first
backend initialization, which has not happened yet, so the env var works for
the virtual device count.
"""

import os

# Device-rate probing (engine/device_rates.py) would spend seconds
# compiling probe chains on the CPU backend and make election inputs
# vary with the host — tests pin the v5e fallback rates instead; the
# probe logic itself is unit-tested via its cache/fallback paths.
os.environ.setdefault("RATELIMITER_RATE_PROBE", "0")

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running soak/drill tests (excluded from tier-1 "
        "'-m \"not slow\"' runs; verify.sh runs them with RUN_SLOW=1)")
