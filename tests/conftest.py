"""Test harness config.

Tests run on CPU with 8 virtual devices so the sharded (multi-chip) engine
paths are exercised without TPU hardware — the key-space sharding is
device-count agnostic (SURVEY.md §4 "multi-device tests runnable on CPU").
Must be set before JAX is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
