"""Edge aggregator tier (edge/ + leases/sublease.py + protocol v6,
ARCHITECTURE §14b).

Layers under test, bottom-up:

- BulkPool sublease accounting: the conservation invariant
  ``remaining + sliced_out + used_pending == budget + deficit`` over
  randomized slice/burn/return/lost/renewal schedules, so the
  aggregator can never admit more than its bulk budgets between
  flushes;
- the nested over-admission bound: burns folded on revoked bulk
  leases reconcile EXACTLY between the aggregator's fold counter and
  the core's ``lease.over_admission``, and stay within the revoked
  bulk budgets;
- the v6 wire surface: bulk grants straddling the old u16 budget
  ceiling, the OP_BULK_RENEW epochs column, and stale lease-instance
  reports landing in over_admission instead of a successor's books;
- scoped fence epochs: ``lease_scope_epoch`` on the unsharded engine;
- the edgeproc standalone process: ready line, front-door serving,
  EOF shutdown;
- the chaos drill (the fast variant verify.sh runs).
"""

import json
import os
import random
import subprocess
import sys
import threading

import pytest

from ratelimiter_tpu import RateLimitConfig
from ratelimiter_tpu.edge import EdgeAggregator
from ratelimiter_tpu.leases import DirectTransport, LeaseClient, LeaseManager
from ratelimiter_tpu.leases.sublease import BulkPool
from ratelimiter_tpu.metrics import MeterRegistry
from ratelimiter_tpu.storage import TpuBatchedStorage

T0 = 1_753_000_000_000


def make_storage(clock, **kw):
    return TpuBatchedStorage(num_slots=256, clock_ms=lambda: clock["t"],
                             **kw)


def make_stack(clock, *, bulk_budget=96, slice_budget=12, flush_ms=50.0,
               max_permits=100_000, registry=None):
    """Storage + manager + one aggregator over a DirectTransport."""
    st = make_storage(clock)
    cfg = RateLimitConfig(max_permits=max_permits, window_ms=60_000,
                          refill_rate=float(max_permits) / 10.0)
    lid = st.register_limiter("tb", cfg)
    mgr = LeaseManager(st, default_budget=slice_budget,
                       max_budget=slice_budget,
                       max_bulk_budget=bulk_budget, ttl_ms=10_000.0,
                       clock_ms=lambda: clock["t"], registry=registry)
    agg = EdgeAggregator(DirectTransport(mgr), bulk_budget=bulk_budget,
                         slice_budget=slice_budget, flush_ms=flush_ms,
                         clock_ms=lambda: clock["t"], registry=registry)
    return st, cfg, lid, mgr, agg


# ---------------------------------------------------------------------------
# BulkPool conservation (the nesting invariant, property-tested)
# ---------------------------------------------------------------------------

def _fresh_pool(budget):
    return BulkPool(lid=1, key="k", budget=budget, remaining=budget,
                    epoch=0, deadline_ms=10_000, granted_total=budget)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_bulk_pool_conservation_random_schedule(seed):
    """Any interleaving of slice / burn-report / return / lost-holder /
    over-report / renewal keeps every permit in exactly one bucket, and
    the pool's outstanding admission never exceeds budget + deficit."""
    rng = random.Random(seed)
    budget = 200
    pool = _fresh_pool(budget)
    sessions = list(range(6))
    for step in range(400):
        op = rng.choice(["slice", "burn", "ret", "lost", "over",
                         "renew", "topup"])
        sid = rng.choice(sessions)
        sub = pool.subs.get(sid)
        if op == "slice":
            pool.slice(sid, rng.randrange(1, 40))
        elif op == "burn" and sub is not None:
            # Occasionally over-report past the slice (a client whose
            # local count drifted): folds conservatively.
            pool.fold_used(sub, rng.randrange(0, sub.amount + 3))
        elif op == "ret" and sub is not None:
            pool.return_unused(sub)
        elif op == "lost" and sub is not None:
            pool.fold_lost(sub)
            pool.drop_sub(sid)
        elif op == "over":
            pool.fold_over_report(rng.randrange(0, 10))
        elif op == "topup" and sub is not None and sub.amount == 0:
            # top_up's contract: only a folded/emptied slice refills
            # (the renewal path always folds+returns first).
            pool.top_up(sub, rng.randrange(1, 40))
        elif op == "renew":
            # Renewals may shrink (the core re-granted less than what
            # is sliced out) — the gap becomes deficit, never free
            # permits.
            granted = rng.randrange(0, budget + 1)
            pool.apply_renewal(granted, 1000, pool.epoch,
                               rng.randrange(0, 5000), pool.used_pending)
        pool.check_conservation()
        assert pool.outstanding() <= pool.budget + pool.deficit
        assert pool.remaining >= 0 and pool.sliced_out >= 0
        assert pool.used_pending >= 0 and pool.deficit >= 0
    # Fold every straggler and drain: the pool must still conserve.
    for sid in list(pool.subs):
        pool.fold_lost(pool.subs[sid])
        pool.drop_sub(sid)
    pool.check_conservation()
    assert pool.sliced_out == 0


def test_bulk_pool_shrinking_renewal_builds_then_pays_deficit():
    pool = _fresh_pool(100)
    sub = pool.slice(1, 60)
    assert sub.amount == 60
    # The core re-grants only 20 while 60 are in the client's hands.
    pool.apply_renewal(20, 1000, 0, 0, 0)
    assert pool.deficit == 40 and pool.remaining == 0
    pool.check_conservation()
    # Returns pay the deficit down before anything re-enters remaining.
    pool.return_unused(sub)
    assert pool.deficit == 0 and pool.remaining == 20
    pool.check_conservation()


# ---------------------------------------------------------------------------
# Aggregator semantics over a live core (DirectTransport)
# ---------------------------------------------------------------------------

def test_aggregator_collapses_frames_and_reconciles():
    clock = {"t": T0}
    st, cfg, lid, mgr, agg = make_stack(clock)
    clients = [LeaseClient(agg.session(), lid, budget=12,
                           clock_ms=lambda: clock["t"],
                           direct_fallback=False, telemetry=False)
               for _ in range(4)]
    try:
        decisions = 0
        for i in range(600):
            clock["t"] += 1
            assert clients[i % 4].try_acquire(f"k{i % 3}")
            decisions += 1
        for lc in clients:
            lc.release_all()
        agg.release_all()
        st.flush()
        # Multiplicative collapse: 4 clients x 3 keys through one
        # aggregator spend <= decisions/5 upstream frames.
        assert agg.upstream_frames * 5 <= decisions
        # Everything settled: no outstanding lease, exact availability.
        assert mgr.table.outstanding() == 0
        avail = int(st.available_many("tb", lid, ["k0"])[0])
        assert 0 <= avail <= cfg.max_permits
    finally:
        st.close()


def test_aggregator_nested_over_admission_bound():
    """Randomized revocation schedule: fence-epoch advances revoke the
    bulk pools; every burn clients land on revoked slices must fold
    into over_admission at BOTH tiers, with the aggregator's fold delta
    equal to the core's, bounded by the revoked bulk budgets."""
    clock = {"t": T0}
    st, cfg, lid, mgr, agg = make_stack(clock, bulk_budget=48,
                                        slice_budget=8)
    rng = random.Random(7)
    keys = [f"k{i}" for i in range(4)]
    clients = [LeaseClient(agg.session(), lid, budget=8,
                           clock_ms=lambda: clock["t"],
                           direct_fallback=False, telemetry=False)
               for _ in range(3)]
    try:
        epoch = 0
        revoked_budget_sum = 0
        for _ in range(5):
            # Burn a while through the aggregator.
            for _ in range(150):
                clock["t"] += 1
                assert clients[rng.randrange(3)].try_acquire(
                    rng.choice(keys))
            # Settle the pending burn reports, then advance the fence
            # epoch: EVERY live bulk lease is now stale (unsharded
            # scope covers all keys).
            agg.flush()
            revoked_budget_sum += sum(p.budget
                                      for p in agg._pools.values())
            epoch += 1
            st.fence(epoch)
            st.lift_fence(epoch)
            over_core0 = mgr.over_admission_total
            over_agg0 = agg.over_admission_total
            revoked0 = agg.scoped_revocations_total
            # One flush tells the aggregator its pools were revoked
            # (settled above, so the revocation rows report zero burns
            # and the core folds nothing yet).
            agg.flush()
            assert mgr.over_admission_total == over_core0
            assert agg.scoped_revocations_total > revoked0
            # Clients drain their stranded slices (served locally —
            # this IS the bounded over-admission), then re-grant.
            burned = 0
            for lc in clients:
                for k in list(lc._leases):
                    lease = lc._leases[k]
                    while lease.remaining > 0:
                        clock["t"] += 1
                        assert lc.try_acquire(k)
                        burned += 1
                    clock["t"] += 1
                    assert lc.try_acquire(k)  # re-grant at new epoch
            agg.flush()
            assert agg.over_admission_total - over_agg0 >= burned
            assert mgr.over_admission_total - over_core0 \
                == agg.over_admission_total - over_agg0, (
                "core and aggregator over-admission folds diverged")
        assert mgr.over_admission_total <= revoked_budget_sum, (
            "fleet over-admission escaped the revoked bulk budgets")
        for lc in clients:
            lc.release_all()
        agg.release_all()
        assert mgr.table.outstanding() == 0
    finally:
        st.close()


def test_aggregator_session_isolation_one_slice_each():
    """Two sessions on the same key get independent slices from ONE
    pool; a session re-granting folds only its own slice."""
    clock = {"t": T0}
    st, cfg, lid, mgr, agg = make_stack(clock, bulk_budget=64,
                                        slice_budget=8)
    try:
        s1, s2 = agg.session(), agg.session()
        g1 = s1.grant(lid, "k", 8)
        g2 = s2.grant(lid, "k", 8)
        assert g1.granted == 8 and g2.granted == 8
        assert len(agg._pools) == 1
        pool = next(iter(agg._pools.values()))
        assert len(pool.subs) == 2 and pool.sliced_out == 16
        # The CORE sees one bulk lease, not two client leases.
        assert mgr.table.outstanding() == 1
        s1.release(lid, "k", used=3)
        assert len(pool.subs) == 1 and pool.used_pending == 3
        pool.check_conservation()
        agg.release_all()
        assert mgr.table.outstanding() == 0
    finally:
        st.close()


# ---------------------------------------------------------------------------
# v6 wire surface: wide budgets + the lease-instance epoch column
# ---------------------------------------------------------------------------

def test_v6_bulk_budget_straddles_u16():
    """Bulk budgets past the old u16 wire ceiling survive the LEASE /
    BULK_RENEW round trip full-width (the v6 granted64 trailer)."""
    from ratelimiter_tpu.service.sidecar import SidecarClient, SidecarServer

    clock = {"t": T0}
    st = TpuBatchedStorage(num_slots=1024, clock_ms=lambda: clock["t"])
    big = 200_000
    server = SidecarServer(st, host="127.0.0.1").start()
    try:
        lid = server.register("tb", RateLimitConfig(
            max_permits=1 << 20, window_ms=60_000, refill_rate=1e6))
        server.attach_leases(LeaseManager(
            st, default_budget=64, max_budget=64, max_bulk_budget=big,
            ttl_ms=60_000.0, clock_ms=lambda: clock["t"]))
        cli = SidecarClient("127.0.0.1", server.port)
        try:
            assert cli.server_version >= 6
            granted, ttl, epoch = cli.lease_grant(lid, "wide", big,
                                                  bulk=True)
            assert granted == big > 0xFFFF
            rows = cli.lease_bulk_renew(lid, ["wide"], [70_000], [big],
                                        epochs=[epoch])
            assert len(rows) == 1
            g2, _ttl2, _ep2, revoked = rows[0]
            assert not revoked and g2 == big > 0xFFFF
            cli.lease_release(lid, "wide", 0)
        finally:
            cli.close()
    finally:
        server.stop()
        st.close()


def test_bulk_renew_stale_epoch_row_folds_to_over_admission():
    """A dead bulk lease's burn report must land in over_admission even
    when a successor lease already lives on the same key — the epochs
    column names the lease INSTANCE, so the successor's books stay
    untouched."""
    clock = {"t": T0}
    st = make_storage(clock)
    cfg = RateLimitConfig(max_permits=100_000, window_ms=60_000,
                          refill_rate=10_000.0)
    lid = st.register_limiter("tb", cfg)
    mgr = LeaseManager(st, default_budget=16, max_budget=16,
                       max_bulk_budget=64, ttl_ms=10_000.0,
                       clock_ms=lambda: clock["t"])
    t = DirectTransport(mgr)
    try:
        g = t.lease_grant(lid, "k", 64, bulk=True)
        assert g.granted == 64
        dead_epoch = g.epoch
        # The fence advances (the holder's lease is now a dead
        # instance); a successor re-grants at the NEW epoch.
        st.fence(3)
        st.lift_fence(3)
        g2 = t.lease_grant(lid, "k", 64, bulk=True)
        assert g2.granted == 64 and g2.epoch != dead_epoch
        successor = mgr.table.get("tb", lid, "k")
        used0 = successor.used_total
        over0 = mgr.over_admission_total
        rev0 = mgr.revoked_total
        # The dead instance's burns arrive late, stamped with ITS
        # epoch: over_admission only — not a revocation event, and not
        # the successor's problem.
        rows = t.lease_bulk_renew(lid, ["k"], [40], [0],
                                  epochs=[dead_epoch])
        assert rows[0] == (0, 0, 0, True)
        assert mgr.over_admission_total - over0 == 40
        assert mgr.revoked_total == rev0
        assert successor.used_total == used0, (
            "stale-instance burns leaked into the successor's books")
        # The successor still renews normally with its own epoch.
        g3 = mgr.renew(lid, "k", used=5, requested=64,
                       epoch=successor.epoch)
        assert g3 is not None and g3.granted == 64
    finally:
        st.close()


def test_bulk_renew_wire_epoch_column_matches_direct():
    """The OP_BULK_RENEW epochs column decodes row-for-row: a stale
    epoch in one row folds that row to over_admission while its
    neighbors renew normally."""
    from ratelimiter_tpu.service.sidecar import SidecarClient, SidecarServer

    clock = {"t": T0}
    st = TpuBatchedStorage(num_slots=1024, clock_ms=lambda: clock["t"])
    server = SidecarServer(st, host="127.0.0.1").start()
    try:
        lid = server.register("tb", RateLimitConfig(
            max_permits=1 << 20, window_ms=60_000, refill_rate=1e6))
        mgr = LeaseManager(st, default_budget=64, max_budget=64,
                           max_bulk_budget=256, ttl_ms=60_000.0,
                           clock_ms=lambda: clock["t"])
        server.attach_leases(mgr)
        cli = SidecarClient("127.0.0.1", server.port)
        try:
            eps = {}
            for k in ("a", "b", "c"):
                granted, _ttl, epoch = cli.lease_grant(lid, k, 256,
                                                       bulk=True)
                assert granted == 256
                eps[k] = epoch
            over0 = mgr.over_admission_total
            rows = cli.lease_bulk_renew(
                lid, ["a", "b", "c"], [10, 20, 30], [256, 256, 256],
                epochs=[eps["a"], eps["b"] + 7, eps["c"]])
            # Row b was a stale instance: granted 0 is how the wire
            # spells "fold and go away"; its neighbors renew normally.
            assert rows[0][0] == 256 and rows[2][0] == 256
            assert rows[1][0] == 0
            assert mgr.over_admission_total - over0 == 20
            # a and c still live and renewable; b's lease untouched.
            assert mgr.table.get("tb", lid, "b").used_total == 0
            for k in ("a", "b", "c"):
                cli.lease_release(lid, k, 0)
        finally:
            cli.close()
    finally:
        server.stop()
        st.close()


# ---------------------------------------------------------------------------
# Scoped fence epochs (unsharded surface; the drill covers sharded)
# ---------------------------------------------------------------------------

def test_lease_scope_epoch_unsharded_tracks_full_fence():
    clock = {"t": T0}
    st = make_storage(clock)
    lid = st.register_limiter("tb", RateLimitConfig(
        max_permits=100, window_ms=60_000, refill_rate=50.0))
    try:
        e0 = st.lease_scope_epoch(lid, "k")
        st.fence(5)
        st.lift_fence(5)
        assert st.lease_scope_epoch(lid, "k") >= max(e0, 5)
        # Every key shares the scope on an unsharded engine.
        assert st.lease_scope_epoch(lid, "other") \
            == st.lease_scope_epoch(lid, "k")
    finally:
        st.close()


# ---------------------------------------------------------------------------
# edgeproc: the standalone aggregator process
# ---------------------------------------------------------------------------

def _core_server(clock=None):
    from ratelimiter_tpu.service.sidecar import SidecarServer

    st = TpuBatchedStorage(num_slots=1024)
    server = SidecarServer(st, host="127.0.0.1").start()
    lid = server.register("tb", RateLimitConfig(
        max_permits=1 << 20, window_ms=60_000, refill_rate=1e6))
    server.attach_leases(LeaseManager(
        st, default_budget=64, max_budget=64, max_bulk_budget=8192,
        ttl_ms=60_000.0))
    return st, server, lid


def test_edgeproc_in_process_front_door():
    """build_edge fronts a real core: clients on the edge's OWN wire
    port burn subleases locally; the edge's upstream traffic collapses
    multiplicatively; plain ops proxy through."""
    from ratelimiter_tpu.edge.edgeproc import build_edge
    from ratelimiter_tpu.service.sidecar import SidecarClient

    st, core, lid = _core_server()
    edge_server = agg = upstream = None
    try:
        edge_server, agg, upstream = build_edge(
            "127.0.0.1", core.port, [lid], bulk_budget=2048,
            slice_budget=64)
        wire = SidecarClient("127.0.0.1", edge_server.port)
        try:
            cli = LeaseClient(wire, lid, budget=64, telemetry=False,
                              direct_fallback=False)
            n = 1500
            for i in range(n):
                assert cli.try_acquire(f"hot{i % 2}")
            cli.release_all()
            # The edge spent <= n/5 frames upstream for n decisions.
            assert agg.upstream_frames * 5 <= n
            # Plain per-decision ops proxy to the core unchanged.
            assert wire.try_acquire(lid, "proxy-key") is True
            assert wire.available(lid, "proxy-key") >= 0
        finally:
            wire.close()
        agg.release_all()
        assert core._leases.table.outstanding() == 0
    finally:
        if upstream is not None:
            upstream.close()
        if edge_server is not None:
            edge_server.stop()
        core.stop()
        st.close()


@pytest.mark.slow
def test_edgeproc_subprocess_ready_and_eof_shutdown():
    """The process contract hostproc also honors: one JSON ready line
    on stdout, serve until stdin EOF, exit 0."""
    st, core, lid = _core_server()
    try:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-m", "ratelimiter_tpu.edge.edgeproc",
             "--upstream-host", "127.0.0.1",
             "--upstream-port", str(core.port),
             "--lids", str(lid)],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(
                __file__))))
        try:
            ready = json.loads(proc.stdout.readline())
            assert ready["ready"] and ready["role"] == "edge"
            assert ready["version"] >= 6
            from ratelimiter_tpu.service.sidecar import SidecarClient

            wire = SidecarClient("127.0.0.1", int(ready["port"]))
            try:
                cli = LeaseClient(wire, lid, budget=64, telemetry=False,
                                  direct_fallback=False)
                for _ in range(200):
                    assert cli.try_acquire("sub")
                cli.release_all()
            finally:
                wire.close()
            proc.stdin.close()  # EOF => graceful shutdown
            assert proc.wait(timeout=30) == 0
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)
    finally:
        core.stop()
        st.close()


# ---------------------------------------------------------------------------
# Service wiring: /actuator/edge + config gating
# ---------------------------------------------------------------------------

def test_wiring_edge_disabled_without_leases():
    from ratelimiter_tpu.service.props import AppProperties
    from ratelimiter_tpu.service.wiring import build_app

    ctx = build_app(AppProperties({
        "storage.backend": "tpu", "storage.num_slots": "1024",
        "parallel.shard": "off", "warmup.enabled": "false",
        "link.probe.enabled": "false",
        "ratelimiter.edge.enabled": "true",  # but leases are off
    }))
    try:
        assert ctx.edge is None
    finally:
        ctx.close()


def test_wiring_edge_sessions_and_actuator():
    import http.client

    from ratelimiter_tpu.service.app import make_server
    from ratelimiter_tpu.service.props import AppProperties
    from ratelimiter_tpu.service.wiring import build_app

    ctx = build_app(AppProperties({
        "storage.backend": "tpu", "storage.num_slots": "1024",
        "parallel.shard": "off", "warmup.enabled": "false",
        "link.probe.enabled": "false",
        "ratelimiter.lease.enabled": "true",
        "ratelimiter.lease.max_bulk_budget": "4096",
        "ratelimiter.edge.enabled": "true",
        "ratelimiter.edge.bulk_budget": "512",
        "ratelimiter.edge.slice_budget": "32",
    }))
    srv = make_server(ctx, port=0)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    try:
        assert ctx.edge is not None
        lid = ctx.limiters["burst"]._lid
        cli = LeaseClient(ctx.edge.session(), lid, budget=32,
                          telemetry=False, direct_fallback=False)
        for _ in range(40):
            cli.try_acquire("edge-wired")
        conn = http.client.HTTPConnection(
            "127.0.0.1", srv.server_address[1], timeout=10)
        conn.request("GET", "/actuator/edge")
        body = json.loads(conn.getresponse().read())
        conn.close()
        assert body["enabled"] is True
        assert body["pools"] >= 1 and body["subleases"] >= 1
        cli.release_all()
    finally:
        srv.shutdown()
        ctx.close()


# ---------------------------------------------------------------------------
# The drill (fast variant; verify.sh runs this)
# ---------------------------------------------------------------------------

def test_aggregator_failover_drill_fast():
    from ratelimiter_tpu.storage.chaos import aggregator_failover_drill

    registry = MeterRegistry()
    report = aggregator_failover_drill(registry=registry)
    assert report["promotions"] == 1
    assert report["decisions"] > 500
    # Multiplicative collapse while healthy.
    assert report["wire_frames_healthy"] * 5 <= report["decisions"]
    # Death bounded by the dropped bulk budgets (nesting invariant).
    assert report["burned_after_death"] \
        <= report["exposure"]["sliced_out"] \
        <= report["exposure"]["bulk_budget"]
    # Scoped revocation: some pools died, but strictly fewer than the
    # key population — only the victim shard's routes were revoked.
    assert 0 < report["scoped_revocations"] < 12
    meters = registry.scrape()
    assert meters["ratelimiter.edge.bulk_renewals"] >= 1.0
    assert meters["ratelimiter.edge.scoped_revocations"] \
        == float(report["scoped_revocations"])
    assert meters["ratelimiter.lease.outstanding"] == 0.0
