"""Circuit breaker + degraded-mode host limiter (storage/breaker.py,
storage/degraded.py) and the sustained-outage chaos drill.

The contract under test: consecutive backend faults open the breaker;
while open, decisions short-circuit to the degraded host limiter (zero
backend traffic, bounded over-admission); a half-open probe closes it and
resyncs every key the degraded limiter mutated, after which decisions are
bit-identical to ``semantics/oracle.py`` again.
"""

import pytest

from ratelimiter_tpu.core.config import RateLimitConfig
from ratelimiter_tpu.storage import (
    CircuitBreakerStorage,
    CircuitOpenError,
    DegradedHostLimiter,
    FaultInjectingStorage,
)
from ratelimiter_tpu.storage.errors import RetryPolicy, StorageException
from ratelimiter_tpu.storage.tpu import TpuBatchedStorage


@pytest.fixture()
def stack():
    """retry-less breaker stack over a real device storage, manual clock."""
    clock = {"t": 1_753_000_000_000}
    inner = TpuBatchedStorage(num_slots=128, clock_ms=lambda: clock["t"])
    chaos = FaultInjectingStorage(inner)
    fallback = DegradedHostLimiter(clock_ms=lambda: clock["t"])
    breaker = CircuitBreakerStorage(
        chaos, failure_threshold=3, open_ms=1000.0, half_open_probes=1,
        clock_ms=lambda: clock["t"], fallback=fallback)
    yield clock, chaos, fallback, breaker
    inner.close()


def _trip(breaker, chaos, lid, n=3):
    chaos.fail_next(n)
    for _ in range(n):
        with pytest.raises(StorageException):
            breaker.acquire("sw", lid, "trip-key", 1)


def test_breaker_opens_after_consecutive_failures(stack):
    clock, chaos, fallback, breaker = stack
    lid = breaker.register_limiter("sw", RateLimitConfig(
        max_permits=5, window_ms=1000))
    chaos.fail_next(2)  # below threshold: a success resets the streak
    for _ in range(2):
        with pytest.raises(StorageException):
            breaker.acquire("sw", lid, "k", 1)
    assert breaker.state == "closed"
    assert breaker.acquire("sw", lid, "k", 1)["allowed"]
    assert breaker.status()["consecutive_failures"] == 0

    _trip(breaker, chaos, lid)
    assert breaker.state == "open"
    assert breaker.opened_total == 1


def test_open_breaker_short_circuits_without_backend_calls(stack):
    clock, chaos, fallback, breaker = stack
    lid = breaker.register_limiter("sw", RateLimitConfig(
        max_permits=5, window_ms=1000))
    _trip(breaker, chaos, lid)
    calls_at_open = len(chaos.calls)
    for _ in range(5):
        out = breaker.acquire("sw", lid, "k", 1)
        assert out["degraded"]
    with pytest.raises(CircuitOpenError):  # no fallback for this surface
        breaker.increment_and_expire("legacy-key", 1000)
    assert len(chaos.calls) == calls_at_open  # backend never touched


def test_half_open_probe_failure_reopens(stack):
    clock, chaos, fallback, breaker = stack
    lid = breaker.register_limiter("sw", RateLimitConfig(
        max_permits=5, window_ms=1000))
    _trip(breaker, chaos, lid)
    clock["t"] += 1001
    chaos.fail_next(1)  # the probe itself fails
    with pytest.raises(StorageException):
        breaker.acquire("sw", lid, "k", 1)
    assert breaker.state == "open"
    assert breaker.opened_total == 2
    # ...and while re-opened, degraded service continues.
    assert breaker.acquire("sw", lid, "k", 1)["degraded"]


def test_half_open_probe_success_closes_and_resyncs(stack):
    # window > open_ms so the pre-outage device count is still live when
    # the breaker closes — the resync reset is what restores the budget.
    clock, chaos, fallback, breaker = stack
    cfg = RateLimitConfig(max_permits=5, window_ms=5000)
    lid = breaker.register_limiter("sw", cfg)
    assert breaker.acquire("sw", lid, "k", 1)["allowed"]  # device count: 1
    _trip(breaker, chaos, lid)
    assert breaker.acquire("sw", lid, "k", 1)["degraded"]  # mutates "k"
    assert ("sw", lid, "k") in fallback.touched()
    clock["t"] += 1001
    out = breaker.acquire("sw", lid, "probe", 1)
    assert breaker.state == "closed" and not out.get("degraded")
    assert breaker.resyncs_total == 1
    assert fallback.touched() == []  # episode state dropped
    # "k" was reset on the device: full budget again, bit-identical to a
    # fresh oracle key.
    assert int(breaker.available_many("sw", lid, ["k"])[0]) == 5


def test_failed_resync_reopens_and_keeps_touched_set():
    clock = {"t": 1_753_000_000_000}
    inner = TpuBatchedStorage(num_slots=128, clock_ms=lambda: clock["t"])
    # Chaos that can ONLY fail reset_key — the resync op.
    chaos = FaultInjectingStorage(inner, ops=("reset_key",))
    fallback = DegradedHostLimiter(clock_ms=lambda: clock["t"])
    breaker = CircuitBreakerStorage(
        chaos, failure_threshold=1, open_ms=1000.0,
        clock_ms=lambda: clock["t"], fallback=fallback)
    try:
        lid = breaker.register_limiter("sw", RateLimitConfig(
            max_permits=5, window_ms=1000))
        breaker.trip()
        assert breaker.acquire("sw", lid, "k", 1)["degraded"]
        clock["t"] += 1001
        chaos.fail_next(1)  # probe acquire succeeds; resync reset fails
        breaker.acquire("sw", lid, "probe", 1)
        assert breaker.state == "open"  # reopened by the failed resync
        assert fallback.touched() != []  # kept for the next recovery
        clock["t"] += 1001
        breaker.acquire("sw", lid, "probe", 1)  # clean recovery this time
        assert breaker.state == "closed"
        assert breaker.resyncs_total == 1
        assert fallback.touched() == []
    finally:
        inner.close()


def test_validation_errors_do_not_count_or_convert():
    class _BadInputBackend:
        supports_device_batching = True

        def acquire(self, *args, **kwargs):
            raise ValueError("caller bug")

    breaker = CircuitBreakerStorage(_BadInputBackend(), failure_threshold=2)
    for _ in range(5):  # > threshold: caller bugs must not open the breaker
        with pytest.raises(ValueError):
            breaker.acquire("sw", 0, "k", 1)
    assert breaker.state == "closed"


def test_healthy_path_seeds_degraded_budget(stack):
    """A key near its limit before the outage stays near its limit in
    degraded mode: the last device-reported counter seeds the host
    approximation (fail-approximate, not a blank-slate fail-open)."""
    clock, chaos, fallback, breaker = stack
    lid = breaker.register_limiter("sw", RateLimitConfig(
        max_permits=5, window_ms=1000))
    for _ in range(3):  # burn 3 of 5 on the device
        assert breaker.acquire("sw", lid, "hot", 1)["allowed"]
    breaker.trip()
    allowed = sum(
        bool(breaker.acquire("sw", lid, "hot", 1)["allowed"])
        for _ in range(5))
    assert allowed == 2  # only the remaining budget, not a fresh 5


def test_degraded_limiter_unknown_lid_raises_circuit_open():
    fb = DegradedHostLimiter(clock_ms=lambda: 1000)
    with pytest.raises(CircuitOpenError):
        fb.acquire("sw", 99, "k", 1)


def test_degraded_limiter_shapes_and_reset():
    fb = DegradedHostLimiter(clock_ms=lambda: 10_000)
    fb.register(0, "sw", RateLimitConfig(max_permits=3, window_ms=1000))
    fb.register(1, "tb", RateLimitConfig(max_permits=4, window_ms=1000,
                                         refill_rate=1.0))
    sw = fb.acquire("sw", 0, "k", 1)
    assert sw["degraded"] and {"allowed", "mutated", "observed",
                               "cache_value"} <= set(sw)
    tb = fb.acquire("tb", 1, "k", 1)
    assert tb["degraded"] and {"allowed", "observed", "remaining"} <= set(tb)
    assert fb.available("sw", 0, ["k", "fresh"]) == [2, 3]
    fb.reset("sw", 0, "k")
    assert fb.available("sw", 0, ["k"]) == [3]
    assert ("sw", 0, "k") in fb.touched()  # admin reset must reach resync
    fb.clear_state()
    assert fb.touched() == []


def test_outage_drill_fast():
    """Chaos drill: sustained outage -> breaker opens -> degraded serving
    (bounded, zero backend traffic) -> heal -> resync -> bit-identical."""
    from ratelimiter_tpu.storage.chaos import outage_drill

    report = outage_drill()
    assert report["mismatches"] == 0
    assert report["degraded_decisions"] > 0
    assert report["shorted_backend_calls"] == 0
    assert report["over_admissions"] == 0


@pytest.mark.slow
def test_outage_soak_slow():
    from ratelimiter_tpu.storage.chaos import outage_drill

    report = outage_drill(num_slots=2048, n_keys=96, healthy_waves=10,
                          outage_waves=12, post_waves=10, batch=64, seed=7)
    assert report["mismatches"] == 0
    assert report["over_admissions"] == 0
