"""Differential tests for the flat mega-batch steps (ops/flat.py) and the
Pallas dense block-scatter (ops/pallas/block_scatter.py).

The flat step must decide exactly like K sequential scan sub-batches at the
same timestamp — that equivalence is what lets the stream path trade the
lax.scan for one big sorted batch.  The block-scatter must write exactly
like the XLA drop-mode scatter it replaces.
"""

import numpy as np
import pytest

from ratelimiter_tpu.core.config import RateLimitConfig
from ratelimiter_tpu.engine.engine import DeviceEngine
from ratelimiter_tpu.engine.state import LimiterTable


@pytest.fixture()
def table():
    t = LimiterTable()
    t.register(RateLimitConfig(max_permits=5, window_ms=1000))          # 1 sw
    t.register(RateLimitConfig(max_permits=10, window_ms=1000,
                               refill_rate=5.0))                        # 2 tb
    t.register(RateLimitConfig(max_permits=3, window_ms=500,
                               refill_rate=2.0))                        # 3 tb
    return t


def _flat_bits(engine, algo, slots, lids, permits, now):
    fn = (engine.sw_flat_dispatch if algo == "sw"
          else engine.tb_flat_dispatch)
    bits = np.asarray(fn(slots, lids, permits, now))
    return np.unpackbits(bits)[: len(slots)].astype(bool)


def _sequential_truth(table, algo, lid_per_req, slots, permits, now, k):
    """K successive plain acquires over fresh state — the scan semantics."""
    eng = DeviceEngine(num_slots=64, table=table)
    fn = eng.sw_acquire if algo == "sw" else eng.tb_acquire
    b = len(slots) // k
    out = []
    for i in range(k):
        sl = slots[i * b:(i + 1) * b]
        ld = lid_per_req[i * b:(i + 1) * b]
        pm = (np.ones(b, np.int64) if permits is None
              else permits[i * b:(i + 1) * b].astype(np.int64))
        out.append(fn(sl, ld, pm, now)["allowed"])
    return np.concatenate(out), eng


@pytest.mark.parametrize("algo,lid", [("sw", 1), ("tb", 2)])
@pytest.mark.parametrize("unit_permits", [True, False])
def test_flat_matches_sequential_subbatches(table, algo, lid, unit_permits):
    """Hot duplicate segments spanning 'sub-batch' boundaries: the flat
    batch must reproduce the sequential decisions bit-for-bit, and leave
    identical state."""
    rng = np.random.default_rng(10)
    k, b = 4, 24
    n = k * b
    slots = rng.integers(0, 6, n).astype(np.int32)  # heavy duplication
    permits = None if unit_permits else rng.integers(1, 3, n).astype(np.int32)
    now = 7_000

    expect, seq_eng = _sequential_truth(
        table, algo, [lid] * n, slots, permits, now, k)

    flat_eng = DeviceEngine(num_slots=64, table=table)
    got = _flat_bits(flat_eng, algo, slots, lid, permits, now)
    np.testing.assert_array_equal(got, expect)
    # State convergence: both engines hold the same rows afterwards.
    np.testing.assert_array_equal(
        flat_eng.read_rows(algo, np.arange(64)),
        seq_eng.read_rows(algo, np.arange(64)))


def test_flat_multi_lid_and_padding(table):
    """Per-request limiter ids + padding lanes (-1) in one flat batch."""
    rng = np.random.default_rng(11)
    n = 64
    slots = rng.integers(0, 8, n).astype(np.int32)
    slots[::9] = -1  # padding / force-deny lanes
    lids = np.where(slots % 2 == 0, 2, 3).astype(np.int32)
    permits = rng.integers(1, 3, n).astype(np.int32)
    now = 9_000

    # Truth: single plain batched acquire (same semantics as flat n=k*b, k=1).
    eng = DeviceEngine(num_slots=64, table=table)
    expect = eng.tb_acquire(slots, lids, permits.astype(np.int64),
                            now)["allowed"]

    flat_eng = DeviceEngine(num_slots=64, table=table)
    got = _flat_bits(flat_eng, "tb", slots, lids, permits, now)
    np.testing.assert_array_equal(got, expect)
    assert not got[slots == -1].any()


def test_flat_unit_permits_closed_form_segment_caps(table):
    """A single hot key with more requests than capacity: exactly cap
    requests pass, in arrival order (closed-form rank solve)."""
    flat_eng = DeviceEngine(num_slots=64, table=table)
    n = 32
    slots = np.zeros(n, dtype=np.int32)
    got = _flat_bits(flat_eng, "tb", slots, 2, None, 5_000)
    assert got[:10].all() and not got[10:].any()  # lid 2: cap 10

    got = _flat_bits(flat_eng, "sw", slots, 1, None, 5_000)
    assert got[:5].all() and not got[5:].any()    # lid 1: max 5


# ---------------------------------------------------------------------------
# Pallas block-scatter (interpret mode on CPU)
# ---------------------------------------------------------------------------

def _xla_truth(state, slots, mask, rows):
    out = state.copy()
    out[slots[mask]] = rows[mask]
    return out


@pytest.mark.parametrize("lanes", [4, 6])
def test_block_scatter_matches_xla(lanes):
    from ratelimiter_tpu.ops.pallas import block_scatter as bs

    rng = np.random.default_rng(12)
    S, B = 4 * bs.T, 4 * bs.T
    state = rng.integers(-(1 << 30), 1 << 30, (S, lanes)).astype(np.int32)
    # Sorted batch with duplicates + padding; mask = last-of-segment & valid.
    slots = np.sort(rng.choice(S, size=B - 7, replace=True)).astype(np.int32)
    slots = np.concatenate([np.full(7, -1, np.int32), slots])
    valid = slots >= 0
    last = np.r_[slots[:-1] != slots[1:], True]
    mask = valid & last
    rows = rng.integers(-(1 << 30), 1 << 30, (B, lanes)).astype(np.int32)

    import jax.numpy as jnp

    got = np.asarray(bs.scatter_rows(
        jnp.asarray(state), jnp.asarray(slots), jnp.asarray(mask),
        jnp.asarray(rows), interpret=True))
    np.testing.assert_array_equal(got, _xla_truth(state, slots, mask, rows))


def test_block_scatter_dense_and_empty_edges():
    """Every slot written (update count == block size everywhere), and the
    zero-updates case (all masked out)."""
    from ratelimiter_tpu.ops.pallas import block_scatter as bs

    import jax.numpy as jnp

    S = 2 * bs.T
    state = np.arange(S * 4, dtype=np.int32).reshape(S, 4)
    slots = np.arange(S, dtype=np.int32)
    rows = -np.arange(S * 4, dtype=np.int32).reshape(S, 4)
    got = np.asarray(bs.scatter_rows(
        jnp.asarray(state), jnp.asarray(slots),
        jnp.asarray(np.ones(S, bool)), jnp.asarray(rows), interpret=True))
    np.testing.assert_array_equal(got, rows)

    got = np.asarray(bs.scatter_rows(
        jnp.asarray(state), jnp.asarray(slots),
        jnp.asarray(np.zeros(S, bool)), jnp.asarray(rows), interpret=True))
    np.testing.assert_array_equal(got, state)


def test_flat_step_through_block_scatter_interpret(table, monkeypatch):
    """The full flat TB step with the Pallas scatter enabled (interpret):
    decisions and state identical to the XLA-scatter flat step."""
    from ratelimiter_tpu.ops.pallas import block_scatter as bs

    rng = np.random.default_rng(13)
    n = 2 * bs.T
    S = 4 * bs.T
    big = LimiterTable()
    big.register(RateLimitConfig(max_permits=5, window_ms=1000))
    lid = big.register(RateLimitConfig(max_permits=4, window_ms=1000,
                                       refill_rate=2.0))
    slots = rng.integers(0, 40, n).astype(np.int32)

    ref_eng = DeviceEngine(num_slots=S, table=big)
    expect = _flat_bits(ref_eng, "tb", slots, lid, None, 6_000)

    monkeypatch.setattr(bs, "_FLAG", True)
    monkeypatch.setattr(bs, "_INTERPRET", True)
    monkeypatch.setattr(bs, "_probe_ok", None)
    pal_eng = DeviceEngine(num_slots=S, table=big)
    assert bs.enabled((S, 4), n)  # geometry passes; probe runs interpreted
    got = _flat_bits(pal_eng, "tb", slots, lid, None, 6_000)
    np.testing.assert_array_equal(got, expect)
    np.testing.assert_array_equal(
        pal_eng.read_rows("tb", np.arange(S)),
        ref_eng.read_rows("tb", np.arange(S)))
