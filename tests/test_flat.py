"""Differential tests for the flat mega-batch steps (ops/flat.py) and the
Pallas dense block-scatter (ops/pallas/block_scatter.py).

The flat step must decide exactly like K sequential scan sub-batches at the
same timestamp — that equivalence is what lets the stream path trade the
lax.scan for one big sorted batch.  The block-scatter must write exactly
like the XLA drop-mode scatter it replaces.
"""

import numpy as np
import pytest

from ratelimiter_tpu.core.config import RateLimitConfig
from ratelimiter_tpu.engine.engine import DeviceEngine
from ratelimiter_tpu.engine.state import LimiterTable


@pytest.fixture()
def table():
    t = LimiterTable()
    t.register(RateLimitConfig(max_permits=5, window_ms=1000))          # 1 sw
    t.register(RateLimitConfig(max_permits=10, window_ms=1000,
                               refill_rate=5.0))                        # 2 tb
    t.register(RateLimitConfig(max_permits=3, window_ms=500,
                               refill_rate=2.0))                        # 3 tb
    return t


def _flat_bits(engine, algo, slots, lids, permits, now):
    fn = (engine.sw_flat_dispatch if algo == "sw"
          else engine.tb_flat_dispatch)
    bits = np.asarray(fn(slots, lids, permits, now))
    return np.unpackbits(bits)[: len(slots)].astype(bool)


def _sequential_truth(table, algo, lid_per_req, slots, permits, now, k):
    """K successive plain acquires over fresh state — the scan semantics."""
    eng = DeviceEngine(num_slots=64, table=table)
    fn = eng.sw_acquire if algo == "sw" else eng.tb_acquire
    b = len(slots) // k
    out = []
    for i in range(k):
        sl = slots[i * b:(i + 1) * b]
        ld = lid_per_req[i * b:(i + 1) * b]
        pm = (np.ones(b, np.int64) if permits is None
              else permits[i * b:(i + 1) * b].astype(np.int64))
        out.append(fn(sl, ld, pm, now)["allowed"])
    return np.concatenate(out), eng


@pytest.mark.parametrize("algo,lid", [("sw", 1), ("tb", 2)])
@pytest.mark.parametrize("unit_permits", [True, False])
def test_flat_matches_sequential_subbatches(table, algo, lid, unit_permits):
    """Hot duplicate segments spanning 'sub-batch' boundaries: the flat
    batch must reproduce the sequential decisions bit-for-bit, and leave
    identical state."""
    rng = np.random.default_rng(10)
    k, b = 4, 24
    n = k * b
    slots = rng.integers(0, 6, n).astype(np.int32)  # heavy duplication
    permits = None if unit_permits else rng.integers(1, 3, n).astype(np.int32)
    now = 7_000

    expect, seq_eng = _sequential_truth(
        table, algo, [lid] * n, slots, permits, now, k)

    flat_eng = DeviceEngine(num_slots=64, table=table)
    got = _flat_bits(flat_eng, algo, slots, lid, permits, now)
    np.testing.assert_array_equal(got, expect)
    # State convergence: both engines hold the same rows afterwards.
    np.testing.assert_array_equal(
        flat_eng.read_rows(algo, np.arange(64)),
        seq_eng.read_rows(algo, np.arange(64)))


def test_flat_multi_lid_and_padding(table):
    """Per-request limiter ids + padding lanes (-1) in one flat batch."""
    rng = np.random.default_rng(11)
    n = 64
    slots = rng.integers(0, 8, n).astype(np.int32)
    slots[::9] = -1  # padding / force-deny lanes
    lids = np.where(slots % 2 == 0, 2, 3).astype(np.int32)
    permits = rng.integers(1, 3, n).astype(np.int32)
    now = 9_000

    # Truth: single plain batched acquire (same semantics as flat n=k*b, k=1).
    eng = DeviceEngine(num_slots=64, table=table)
    expect = eng.tb_acquire(slots, lids, permits.astype(np.int64),
                            now)["allowed"]

    flat_eng = DeviceEngine(num_slots=64, table=table)
    got = _flat_bits(flat_eng, "tb", slots, lids, permits, now)
    np.testing.assert_array_equal(got, expect)
    assert not got[slots == -1].any()


def test_flat_unit_permits_closed_form_segment_caps(table):
    """A single hot key with more requests than capacity: exactly cap
    requests pass, in arrival order (closed-form rank solve)."""
    flat_eng = DeviceEngine(num_slots=64, table=table)
    n = 32
    slots = np.zeros(n, dtype=np.int32)
    got = _flat_bits(flat_eng, "tb", slots, 2, None, 5_000)
    assert got[:10].all() and not got[10:].any()  # lid 2: cap 10

    got = _flat_bits(flat_eng, "sw", slots, 1, None, 5_000)
    assert got[:5].all() and not got[5:].any()    # lid 1: max 5


# ---------------------------------------------------------------------------
# Pallas block-scatter (interpret mode on CPU)
# ---------------------------------------------------------------------------

def _xla_truth(state, slots, mask, rows):
    out = state.copy()
    out[slots[mask]] = rows[mask]
    return out


@pytest.mark.parametrize("lanes", [4, 6])
def test_block_scatter_matches_xla(lanes):
    from ratelimiter_tpu.ops.pallas import block_scatter as bs

    rng = np.random.default_rng(12)
    S, B = 4 * bs.T, 4 * bs.T
    state = rng.integers(-(1 << 30), 1 << 30, (S, lanes)).astype(np.int32)
    # Sorted batch with duplicates + padding; mask = last-of-segment & valid.
    slots = np.sort(rng.choice(S, size=B - 7, replace=True)).astype(np.int32)
    slots = np.concatenate([np.full(7, -1, np.int32), slots])
    valid = slots >= 0
    last = np.r_[slots[:-1] != slots[1:], True]
    mask = valid & last
    rows = rng.integers(-(1 << 30), 1 << 30, (B, lanes)).astype(np.int32)

    import jax.numpy as jnp

    got = np.asarray(bs.scatter_rows(
        jnp.asarray(state), jnp.asarray(slots), jnp.asarray(mask),
        jnp.asarray(rows), interpret=True))
    np.testing.assert_array_equal(got, _xla_truth(state, slots, mask, rows))


def test_block_scatter_dense_and_empty_edges():
    """Every slot written (update count == block size everywhere), and the
    zero-updates case (all masked out)."""
    from ratelimiter_tpu.ops.pallas import block_scatter as bs

    import jax.numpy as jnp

    S = 2 * bs.T
    state = np.arange(S * 4, dtype=np.int32).reshape(S, 4)
    slots = np.arange(S, dtype=np.int32)
    rows = -np.arange(S * 4, dtype=np.int32).reshape(S, 4)
    got = np.asarray(bs.scatter_rows(
        jnp.asarray(state), jnp.asarray(slots),
        jnp.asarray(np.ones(S, bool)), jnp.asarray(rows), interpret=True))
    np.testing.assert_array_equal(got, rows)

    got = np.asarray(bs.scatter_rows(
        jnp.asarray(state), jnp.asarray(slots),
        jnp.asarray(np.zeros(S, bool)), jnp.asarray(rows), interpret=True))
    np.testing.assert_array_equal(got, state)


def test_flat_step_through_block_scatter_interpret(table, monkeypatch):
    """The full flat TB step with the Pallas scatter enabled (interpret):
    decisions and state identical to the XLA-scatter flat step."""
    from ratelimiter_tpu.ops.pallas import block_scatter as bs

    rng = np.random.default_rng(13)
    n = 2 * bs.T
    S = 4 * bs.T
    big = LimiterTable()
    big.register(RateLimitConfig(max_permits=5, window_ms=1000))
    lid = big.register(RateLimitConfig(max_permits=4, window_ms=1000,
                                       refill_rate=2.0))
    slots = rng.integers(0, 40, n).astype(np.int32)

    ref_eng = DeviceEngine(num_slots=S, table=big)
    expect = _flat_bits(ref_eng, "tb", slots, lid, None, 6_000)

    monkeypatch.setattr(bs, "_FLAG", True)
    monkeypatch.setattr(bs, "_INTERPRET", True)
    monkeypatch.setattr(bs, "_probe_ok", None)
    pal_eng = DeviceEngine(num_slots=S, table=big)
    assert bs.enabled((S, 4), n)  # geometry passes; probe runs interpreted
    got = _flat_bits(pal_eng, "tb", slots, lid, None, 6_000)
    np.testing.assert_array_equal(got, expect)
    np.testing.assert_array_equal(
        pal_eng.read_rows("tb", np.arange(S)),
        ref_eng.read_rows("tb", np.arange(S)))


def test_stream_strs_matches_acquire_many():
    """String-key streaming == chunked acquire_many on the same stream
    (same index namespace, same kernels, pipelining must not change
    decisions)."""
    from ratelimiter_tpu.storage import TpuBatchedStorage

    cfg = RateLimitConfig(max_permits=6, window_ms=1000, refill_rate=4.0)
    rng = np.random.default_rng(14)
    n = 600
    keys = [f"user-{k}" for k in rng.integers(0, 35, n)]
    permits = rng.integers(1, 3, n).astype(np.int64)
    clock = lambda: 88_000  # noqa: E731

    s1 = TpuBatchedStorage(num_slots=256, clock_ms=clock)
    lid1 = s1.register_limiter("tb", cfg)
    expect = np.empty(n, dtype=bool)
    for i in range(0, n, 64):
        chunk = keys[i:i + 64]
        expect[i:i + len(chunk)] = s1.acquire_many(
            "tb", [lid1] * len(chunk), chunk,
            list(permits[i:i + len(chunk)]))["allowed"]
    s1.close()

    s2 = TpuBatchedStorage(num_slots=256, clock_ms=clock)
    lid2 = s2.register_limiter("tb", cfg)
    got = s2.acquire_stream_strs("tb", lid2, keys, permits,
                                 batch=64, subbatches=2)
    s2.close()
    np.testing.assert_array_equal(got, expect)


def test_stream_strs_shares_namespace_with_scalar_path():
    """Stream-consumed string keys are the same buckets the scalar path
    sees."""
    from ratelimiter_tpu.storage import TpuBatchedStorage

    clock = lambda: 44_000  # noqa: E731
    s = TpuBatchedStorage(num_slots=64, clock_ms=clock)
    lid = s.register_limiter("tb", RateLimitConfig(
        max_permits=3, window_ms=1000, refill_rate=0.001))
    got = s.acquire_stream_strs("tb", lid, ["alice"] * 5, None,
                                batch=8, subbatches=1)
    assert got.tolist() == [True, True, True, False, False]
    out = s.acquire("tb", lid, "alice", 1)
    s.close()
    assert not out["allowed"]


def test_try_acquire_many_routes_large_calls_to_stream(monkeypatch):
    """Above the size threshold the limiters stream; decisions must be the
    same either way (cache-less SW and TB)."""
    from ratelimiter_tpu.algorithms import (
        SlidingWindowRateLimiter,
        TokenBucketRateLimiter,
    )
    from ratelimiter_tpu.algorithms import sliding_window as swmod
    from ratelimiter_tpu.algorithms import token_bucket as tbmod
    from ratelimiter_tpu.metrics import MeterRegistry
    from ratelimiter_tpu.storage import TpuBatchedStorage

    monkeypatch.setattr(swmod, "_STREAM_MIN", 64)
    monkeypatch.setattr(tbmod, "_STREAM_MIN", 64)
    rng = np.random.default_rng(15)
    n = 300
    keys = [f"u{k}" for k in rng.integers(0, 20, n)]
    clock = lambda: 66_000  # noqa: E731

    results = {}
    for threshold_hit in (False, True):
        st = TpuBatchedStorage(num_slots=256, clock_ms=clock)
        sw = SlidingWindowRateLimiter(
            st, RateLimitConfig(max_permits=8, window_ms=1000,
                                enable_local_cache=False),
            MeterRegistry(), clock_ms=clock)
        tb = TokenBucketRateLimiter(
            st, RateLimitConfig(max_permits=5, window_ms=1000,
                                refill_rate=1.0),
            MeterRegistry(), clock_ms=clock)
        if threshold_hit:
            got_sw = sw.try_acquire_many(keys)           # n >= 64: streams
            got_tb = tb.try_acquire_many(keys)
        else:
            got_sw = np.concatenate(
                [sw.try_acquire_many(keys[i:i + 50]) for i in range(0, n, 50)])
            got_tb = np.concatenate(
                [tb.try_acquire_many(keys[i:i + 50]) for i in range(0, n, 50)])
        results[threshold_hit] = (got_sw, got_tb)
        st.close()
    np.testing.assert_array_equal(results[False][0], results[True][0])
    np.testing.assert_array_equal(results[False][1], results[True][1])


@pytest.mark.parametrize("lanes", [4, 6])
def test_block_scatter_presorted_matches_xla(lanes):
    """The presorted entry (no compaction sort: caller-sorted unique
    slots, padding at the tail — the host-sorted digest layout) against
    XLA drop-scatter truth, in interpret mode."""
    from ratelimiter_tpu.ops.pallas import block_scatter as bs

    import jax.numpy as jnp

    rng = np.random.default_rng(21)
    S, B = 4 * bs.T, 4 * bs.T
    for trial in range(4):
        state = rng.integers(-(1 << 30), 1 << 30, (S, lanes)).astype(
            np.int32)
        u = int(rng.integers(1, B - 1))
        live = np.sort(rng.choice(S, size=u, replace=False)).astype(
            np.int32)
        # Digest padding decodes to slot >= S, at the tail.
        slots = np.concatenate([live, np.full(B - u, S + 5, np.int32)])
        mask = np.r_[np.ones(u, bool), np.zeros(B - u, bool)]
        rows = rng.integers(-(1 << 30), 1 << 30, (B, lanes)).astype(
            np.int32)
        got = np.asarray(bs.scatter_rows_presorted(
            jnp.asarray(state), jnp.asarray(slots), jnp.asarray(mask),
            jnp.asarray(rows), interpret=True))
        np.testing.assert_array_equal(
            got, _xla_truth(state, slots, mask, rows), err_msg=str(trial))
    # Edges: everything written; nothing written.
    state = np.arange(S * lanes, dtype=np.int32).reshape(S, lanes)
    slots = np.arange(S, dtype=np.int32)
    rows = -state
    got = np.asarray(bs.scatter_rows_presorted(
        jnp.asarray(state), jnp.asarray(slots),
        jnp.asarray(np.ones(S, bool)), jnp.asarray(rows), interpret=True))
    np.testing.assert_array_equal(got, rows)
    got = np.asarray(bs.scatter_rows_presorted(
        jnp.asarray(state), jnp.asarray(slots),
        jnp.asarray(np.zeros(S, bool)), jnp.asarray(rows), interpret=True))
    np.testing.assert_array_equal(got, state)
