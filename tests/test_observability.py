"""Observability subsystem (ARCHITECTURE §13): Prometheus exposition,
request-lifecycle trace propagation, flight recorder, latency stage
histograms."""

import re
import threading

from ratelimiter_tpu.metrics import MeterRegistry
from ratelimiter_tpu.observability import (
    FlightRecorder,
    render_prometheus,
)


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

def test_prometheus_golden():
    """Exact output for two counters, one gauge, one histogram — pins
    the format (name sanitization, HELP escaping + the description-table
    fallback for meters registered without one, bucket ladder,
    sum/count)."""
    reg = MeterRegistry()
    reg.counter("ratelimiter.requests.allowed", "Allowed requests").add(42)
    # Registered WITHOUT a description: HELP comes from the
    # METRIC_HELP description table.
    reg.counter("ratelimiter.cache.hits").add(7)
    reg.gauge("ratelimiter.replication.lag_ms", "Replication lag").set(1.5)
    t = reg.timer("ratelimiter.storage.latency",
                  "Dispatch latency\nsecond line \\ backslash")
    for v in (1.0, 3.0, 100.0):
        t.record_us(v)
    got = render_prometheus(reg)
    expected = "\n".join([
        "# HELP ratelimiter_cache_hits_total Local TTL-cache hits",
        "# TYPE ratelimiter_cache_hits_total counter",
        "ratelimiter_cache_hits_total 7",
        "# HELP ratelimiter_replication_lag_ms Replication lag",
        "# TYPE ratelimiter_replication_lag_ms gauge",
        "ratelimiter_replication_lag_ms 1.5",
        "# HELP ratelimiter_requests_allowed_total Allowed requests",
        "# TYPE ratelimiter_requests_allowed_total counter",
        "ratelimiter_requests_allowed_total 42",
        "# HELP ratelimiter_storage_latency_seconds "
        "Dispatch latency\\nsecond line \\\\ backslash",
        "# TYPE ratelimiter_storage_latency_seconds histogram",
        'ratelimiter_storage_latency_seconds_bucket{le="1e-06"} 1',
        'ratelimiter_storage_latency_seconds_bucket{le="2e-06"} 1',
        'ratelimiter_storage_latency_seconds_bucket{le="4e-06"} 2',
        'ratelimiter_storage_latency_seconds_bucket{le="8e-06"} 2',
        'ratelimiter_storage_latency_seconds_bucket{le="1.6e-05"} 2',
        'ratelimiter_storage_latency_seconds_bucket{le="3.2e-05"} 2',
        'ratelimiter_storage_latency_seconds_bucket{le="6.4e-05"} 2',
        'ratelimiter_storage_latency_seconds_bucket{le="0.000128"} 3',
        'ratelimiter_storage_latency_seconds_bucket{le="+Inf"} 3',
        "ratelimiter_storage_latency_seconds_sum 0.000104",
        "ratelimiter_storage_latency_seconds_count 3",
    ]) + "\n"
    assert got == expected


def _parse_histograms(text):
    """name -> {"buckets": [(le, cum)], "sum": float, "count": int}"""
    hists = {}
    for line in text.splitlines():
        m = re.match(r'^(\w+)_bucket\{le="([^"]+)"\} (\d+)$', line)
        if m:
            le = float("inf") if m.group(2) == "+Inf" else float(m.group(2))
            hists.setdefault(m.group(1), {"buckets": []})[
                "buckets"].append((le, int(m.group(3))))
            continue
        m = re.match(r"^(\w+)_(sum|count) (\S+)$", line)
        if m and m.group(1) in hists:
            hists[m.group(1)][m.group(2)] = float(m.group(3))
    return hists


def test_prometheus_histogram_invariants():
    """Bucket bounds and cumulative counts strictly monotonic; +Inf
    equals _count; _sum consistent with the recorded values."""
    reg = MeterRegistry()
    t = reg.timer("ratelimiter.latency.total", "total")
    import random

    rnd = random.Random(7)
    values = [rnd.uniform(0.1, 1e7) for _ in range(500)]
    for v in values:
        t.record_us(v)
    hists = _parse_histograms(render_prometheus(reg))
    h = hists["ratelimiter_latency_total_seconds"]
    les = [b[0] for b in h["buckets"]]
    cums = [b[1] for b in h["buckets"]]
    assert les == sorted(les) and len(set(les)) == len(les)
    assert cums == sorted(cums), "cumulative counts must be monotonic"
    assert les[-1] == float("inf")
    assert cums[-1] == h["count"] == len(values)
    assert abs(h["sum"] - sum(values) / 1e6) < 1e-6


def test_prometheus_name_sanitization():
    reg = MeterRegistry()
    reg.counter("ratelimiter.weird-name.v2", "d").add(1)
    out = render_prometheus(reg)
    assert "ratelimiter_weird_name_v2_total 1" in out


def test_prometheus_labeled_collector_golden():
    """Collector-provided labeled families render after the registry's
    meters, with label keys sorted and values escaped."""

    class FakeCollector:
        @staticmethod
        def prometheus_samples():
            return [(
                "ratelimiter.tenant.admitted", "counter", "Per-tenant",
                [({"tenant": "3"}, 10),
                 ({"tenant": "7", "key_class": 'a"b\\c\nd'}, 2)],
            )]

    reg = MeterRegistry()
    reg.counter("ratelimiter.requests.allowed", "Allowed").add(1)
    out = render_prometheus(reg, collectors=(FakeCollector(),))
    assert out.endswith("\n".join([
        "# HELP ratelimiter_tenant_admitted_total Per-tenant",
        "# TYPE ratelimiter_tenant_admitted_total counter",
        'ratelimiter_tenant_admitted_total{tenant="3"} 10',
        'ratelimiter_tenant_admitted_total'
        '{key_class="a\\"b\\\\c\\nd",tenant="7"} 2',
    ]) + "\n"), out


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------

def test_flight_recorder_ring_wrap():
    rec = FlightRecorder(capacity=8)
    for i in range(20):
        rec.record("k", i=i)
    snap = rec.snapshot()
    assert snap["total_events"] == 20
    assert len(snap["events"]) == 8
    assert [e["i"] for e in snap["events"]] == list(range(12, 20))
    assert [e["seq"] for e in snap["events"]] == list(range(12, 20))


def test_flight_recorder_thread_safety():
    rec = FlightRecorder(capacity=64)
    n_threads, per = 8, 500

    def work(t):
        for i in range(per):
            rec.record(f"t{t}", i=i)
            if i % 100 == 0:
                rec.snapshot(last=16)

    threads = [threading.Thread(target=work, args=(t,))
               for t in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    snap = rec.snapshot()
    assert snap["total_events"] == n_threads * per
    assert len(snap["events"]) == 64
    # Sequence numbers of surviving events are unique and ordered.
    seqs = [e["seq"] for e in snap["events"]]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)


def test_flight_recorder_coalescing():
    rec = FlightRecorder(capacity=16)
    for _ in range(10):
        rec.record("overload.shed", coalesce_ms=60_000.0, reason="x")
    events = rec.events(kind="overload.shed")
    assert len(events) == 1
    assert events[0]["n"] == 10


def test_flight_recorder_transitions_and_anomalies():
    rec = FlightRecorder(capacity=16, slo_ms=1.0, context_events=4)
    assert rec.record_transition("health", "UP")
    assert not rec.record_transition("health", "UP")  # no repeat
    assert rec.record_transition("health", "SHEDDING")
    assert [e["state"] for e in rec.events(kind="health")] == [
        "UP", "SHEDDING"]

    rec.note_dispatch(500.0)          # under the 1 ms SLO: no anomaly
    rec.note_dispatch(2_000.0, {"device": 1_800.0}, algo="tb")
    snap = rec.snapshot()
    assert snap["anomaly_total"] == 1
    anom = snap["anomalies"][0]
    assert anom["total_us"] == 2000.0
    assert anom["stages_us"] == {"device": 1800.0}
    assert anom["algo"] == "tb"
    assert len(anom["context"]) <= 4  # the last ring events ride along


def test_flight_recorder_mark_and_since():
    rec = FlightRecorder(capacity=16)
    rec.record("a")
    mark = rec.mark()
    rec.record("b")
    rec.record("a")
    kinds = [e["kind"] for e in rec.events(since=mark)]
    assert kinds == ["b", "a"]


# ---------------------------------------------------------------------------
# Request-lifecycle tracing (batcher -> histograms + sampled traces)
# ---------------------------------------------------------------------------

def _stage_sum_close_to_total(entry):
    stages = entry["stages_us"]
    assert set(stages) == {"queue_wait", "assembly", "device", "resolve"}
    for v in stages.values():
        assert v >= 0.0
    total = entry["latency_us"]
    assert abs(sum(stages.values()) - total) <= 1.0  # rounding slack


def test_trace_propagation_single_acquire():
    """One tryAcquire through the micro-batcher yields one sampled trace
    whose four stages are non-negative and telescope to ≈ total."""
    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.storage import TpuBatchedStorage

    storage = TpuBatchedStorage(num_slots=256, max_delay_ms=0.1,
                                trace_sample=1,
                                recorder=FlightRecorder())
    try:
        lid = storage.register_limiter("sw", RateLimitConfig.per_minute(10))
        out = storage.acquire("sw", lid, "trace-user", 1)
        assert out["allowed"]
        storage.flush()
        # The sampled trace lands on the drain thread right after the
        # future resolves; give it a moment.
        import time

        entry = None
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and entry is None:
            recent = storage.trace.snapshot()["recent"]
            entry = next((e for e in recent
                          if e.get("path") == "micro"
                          and "stages_us" in e), None)
            if entry is None:
                time.sleep(0.01)
        assert entry is not None, "no sampled micro trace recorded"
        _stage_sum_close_to_total(entry)
        assert entry["batch"] >= 1

        # The stage histograms aggregated the same lifecycle.
        scrape = storage.registry.scrape()
        for stage in ("queue_wait", "assembly", "device", "resolve",
                      "total"):
            snap = scrape[f"ratelimiter.latency.{stage}"]
            assert snap["count"] >= 1, stage
    finally:
        storage.close()


def test_trace_propagation_through_sidecar():
    """The same lifecycle trace survives the TCP front door: one
    pipelined sidecar acquire produces a sampled micro trace."""
    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.service import sidecar as sc
    from ratelimiter_tpu.storage import TpuBatchedStorage

    storage = TpuBatchedStorage(num_slots=256, max_delay_ms=0.1,
                                trace_sample=1,
                                recorder=FlightRecorder())
    server = sc.SidecarServer(storage, host="127.0.0.1").start()
    try:
        lid = server.register("tb", RateLimitConfig(
            max_permits=50, window_ms=60_000, refill_rate=10.0))
        client = sc.SidecarClient("127.0.0.1", server.port)
        assert client.try_acquire(lid, "sidecar-trace-user") is True
        client.close()
        import time

        entry = None
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and entry is None:
            recent = storage.trace.snapshot()["recent"]
            entry = next((e for e in recent
                          if e.get("path") == "micro"
                          and "stages_us" in e), None)
            if entry is None:
                time.sleep(0.01)
        assert entry is not None, "no sampled trace through the sidecar"
        _stage_sum_close_to_total(entry)
    finally:
        server.stop()
        storage.close()


def test_slow_dispatch_anomaly_capture():
    """A dispatch over the SLO threshold snapshots its stage breakdown
    into the flight recorder."""
    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.storage import TpuBatchedStorage

    rec = FlightRecorder(slo_ms=0.000001)  # everything is an anomaly
    storage = TpuBatchedStorage(num_slots=256, max_delay_ms=0.1,
                                recorder=rec)
    try:
        lid = storage.register_limiter("sw", RateLimitConfig.per_minute(10))
        storage.acquire("sw", lid, "slow-user", 1)
        storage.flush()
        import time

        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if rec.snapshot()["anomaly_total"] > 0:
                break
            time.sleep(0.01)
        snap = rec.snapshot()
        assert snap["anomaly_total"] > 0
        assert snap["anomalies"][0]["kind"] == "slow_dispatch"
    finally:
        storage.close()


def test_stream_dispatch_path_enrichment():
    """Stream dispatches record their dispatch route (relay/flat/...)
    in the decision trace."""
    import numpy as np

    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.storage import TpuBatchedStorage

    storage = TpuBatchedStorage(num_slots=4096,
                                recorder=FlightRecorder())
    try:
        lid = storage.register_limiter("tb", RateLimitConfig(
            max_permits=1000, window_ms=1000, refill_rate=500.0))
        keys = np.arange(5000, dtype=np.int64) % 64
        storage.acquire_stream_ids("tb", lid, keys)
        recent = storage.trace.snapshot()["recent"]
        paths = {e.get("path") for e in recent}
        assert any(p and p != "micro" for p in paths), paths
    finally:
        storage.close()


def test_actuator_prometheus_and_flightrecorder_endpoints():
    """The HTTP tier serves both new actuator surfaces."""
    import http.client
    import json
    import threading

    from ratelimiter_tpu.service.app import make_server
    from ratelimiter_tpu.service.props import AppProperties
    from ratelimiter_tpu.service.wiring import build_app

    props = AppProperties({
        "storage.backend": "tpu",
        "storage.num_slots": "4096",
        "batcher.max_delay_ms": "0.2",
        "parallel.shard": "off",
        "warmup.enabled": "false",
        "link.probe.enabled": "false",
    })
    ctx = build_app(props)
    srv = make_server(ctx, port=0)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    port = srv.server_address[1]
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", "/api/data", headers={"X-User-ID": "u1"})
        assert conn.getresponse().read()

        conn.request("GET", "/actuator/prometheus")
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.getheader("Content-Type", "").startswith("text/plain")
        text = resp.read().decode()
        assert "ratelimiter_storage_latency_seconds_bucket" in text
        assert "ratelimiter_requests_allowed_total" in text
        hists = _parse_histograms(text)
        for name, h in hists.items():
            cums = [b[1] for b in h["buckets"]]
            assert cums == sorted(cums), name
            assert cums[-1] == h["count"], name

        conn.request("GET", "/actuator/health")
        assert conn.getresponse().read()
        conn.request("GET", "/actuator/flightrecorder")
        resp = conn.getresponse()
        assert resp.status == 200
        fr = json.loads(resp.read())
        # The health poll above recorded the UP transition.
        assert any(e["kind"] == "health" for e in fr["events"])
        conn.close()
    finally:
        srv.shutdown()
        ctx.close()
