"""Double-buffered micro-batch assembly + adaptive flush control (r11).

Covers the staged submit-time packing path (engine/batcher.py:_Pending),
the combined-upload engine dispatch (engine/engine.py:
micro_staged_dispatch), the assembly sub-stage timers, and the
AdaptiveFlushController's bounds/hysteresis (engine/flush_control.py).
"""

import threading
import time

import numpy as np
import pytest

from ratelimiter_tpu.core.config import RateLimitConfig
from ratelimiter_tpu.engine.flush_control import AdaptiveFlushController
from ratelimiter_tpu.semantics.oracle import SlidingWindowOracle


# ---------------------------------------------------------------------------
# Adaptive flush controller
# ---------------------------------------------------------------------------

def test_controller_bounds_under_step_time_ramp():
    """Simulated-clock ramp: however the measured step time moves, the
    applied deadline stays within [floor, cap] and the size trigger
    within [size_floor, size_cap]."""
    c = AdaptiveFlushController(
        base_delay_ms=0.5, floor_ms=0.05, cap_ms=0.5,
        size_floor=32, size_cap=4096, hysteresis_steps=2)
    # Ramp device step 10 us -> 10 ms and back, batches 1 -> 10_000.
    steps = [1e-5 * (1.2 ** i) for i in range(40)]
    steps += list(reversed(steps))
    for i, s in enumerate(steps):
        c.observe(s, min(1 + i * 137, 10_000))
        assert 0.05e-3 <= c.delay_s() <= 0.5e-3
        assert 32 <= c.size_trigger() <= 4096
    # After the ramp settled low, both applied values converged back to
    # their floors (within the hysteresis band — the EWMAs need ~25
    # observations to decay from the ramp peak).
    for _ in range(30):
        c.observe(1e-5, 4)
    assert c.delay_s() <= 0.05e-3 * (1 + c.hysteresis_pct)
    assert c.size_trigger() == 32


def test_controller_clamps_pathological_reading():
    """One 90 s reading (a first-compile stall) must not pin the
    deadline at the cap for thousands of batches: the sample is clamped
    before the EWMA, and recovery is fast."""
    c = AdaptiveFlushController(
        base_delay_ms=1.0, floor_ms=0.05, cap_ms=1.0,
        size_floor=32, size_cap=4096, hysteresis_steps=2)
    for _ in range(20):
        c.observe(1e-4, 8)  # steady 100 us steps -> near floor
    settled = c.delay_s()
    assert settled < 0.3e-3
    c.observe(90.0, 8)      # pathological
    assert c.delay_s() <= 1.0e-3  # hard cap regardless
    assert c.clamped_samples == 1
    recovery = 0
    while c.delay_s() > settled * 1.5 and recovery < 50:
        c.observe(1e-4, 8)
        recovery += 1
    assert recovery < 50, "controller never recovered from one outlier"


def test_controller_hysteresis_damps_oscillation():
    """Alternating readings (noise) never move the applied values: the
    direction streak resets every flip, so adjustments stay at zero —
    the 'never oscillates unbounded' bound, by construction."""
    c = AdaptiveFlushController(
        base_delay_ms=0.2, floor_ms=0.05, cap_ms=0.5,
        size_floor=32, size_cap=4096, hysteresis_steps=3)
    for i in range(20):  # settle the EWMAs and the size trigger
        c.observe(3e-4 if i % 2 else 1e-4, 8)
    settled_adj = c.adjustments
    before = c.delay_s()
    for i in range(500):
        # +-50% noise around the settled mean: the EWMA's residual
        # swing stays inside the hysteresis band, so nothing moves.
        c.observe(3e-4 if i % 2 else 1e-4, 8)
    assert c.adjustments == settled_adj
    assert c.delay_s() == before


# ---------------------------------------------------------------------------
# Staged batcher path (storage-level)
# ---------------------------------------------------------------------------

@pytest.fixture
def storage():
    from ratelimiter_tpu.storage import TpuBatchedStorage

    st = TpuBatchedStorage(num_slots=1 << 10, max_delay_ms=0.2)
    yield st
    st.close()


def test_staged_micro_path_matches_oracle(storage):
    cfg = RateLimitConfig(max_permits=3, window_ms=60_000)
    lid = storage.register_limiter("sw", cfg)
    oracle = SlidingWindowOracle(cfg)
    storage.warm_micro_shapes()
    for i in range(40):
        key = f"k{i % 5}"
        out = storage.acquire("sw", lid, key, 1)
        # The staged dispatch stamps its own clock; replay the oracle at
        # the same stamp the device used.
        d = oracle.try_acquire(key, 1, int(storage._last_stamp))
        assert bool(out["allowed"]) == d.allowed
        assert int(out["observed"]) == d.observed
        assert int(out["cache_value"]) == d.remaining_hint


def test_staged_buffers_recycle_and_grow(storage):
    """A burst larger than the initial staging cap grows the buffer; the
    double-buffer pool recycles without cross-batch contamination."""
    cfg = RateLimitConfig(max_permits=10_000, window_ms=60_000)
    lid = storage.register_limiter("sw", cfg)
    futs = [storage.acquire_async("sw", lid, f"g{i}", 1)
            for i in range(300)]  # > _STAGE_CAP(32), forces growth
    storage.flush()
    assert all(bool(f.result(timeout=30)["allowed"]) for f in futs)
    # Several more flush cycles through the recycled buffers.
    for r in range(3):
        futs = [storage.acquire_async("sw", lid, f"g{i}", 1)
                for i in range(10)]
        storage.flush()
        for f in futs:
            assert bool(f.result(timeout=30)["allowed"])


def test_assembly_substage_timers_populate(storage):
    cfg = RateLimitConfig(max_permits=100, window_ms=60_000)
    lid = storage.register_limiter("sw", cfg)
    for i in range(20):
        storage.acquire("sw", lid, f"t{i}", 1)
    scrape = storage.registry.scrape()
    for sub in ("pack", "index", "layout"):
        snap = scrape.get(f"ratelimiter.latency.assembly.{sub}")
        assert snap is not None, f"missing sub-stage timer {sub}"
        assert snap["count"] > 0, f"sub-stage timer {sub} never recorded"
    # Sub-stages live inside the assembly stage: their p50 sum can't
    # wildly exceed assembly's (sanity, not an exact telescope — index
    # is recorded per request on the submit side).
    assert scrape["ratelimiter.latency.assembly"]["count"] > 0


def test_shed_compaction_keeps_staged_lanes_aligned():
    """Deadline-shedding from the middle of a staged queue must keep the
    buffer rows and the future list in lockstep."""
    from ratelimiter_tpu.engine.batcher import MicroBatcher
    from ratelimiter_tpu.engine.errors import OverloadedError

    seen = []

    def dispatch(slots, lids, permits):
        seen.append((list(slots), list(lids), list(permits)))
        return {"allowed": [True] * len(slots)}

    b = MicroBatcher(dispatch={"sw": dispatch},
                     clear={"sw": lambda s: None},
                     max_delay_ms=10_000.0)
    try:
        f1 = b.submit("sw", 1, 0, 11, deadline_ms=1.0)   # will expire
        f2 = b.submit("sw", 2, 5, 22, deadline_ms=0.0)   # no deadline
        f3 = b.submit("sw", 3, 0, 33, deadline_ms=1.0)   # will expire
        f4 = b.submit("sw", 4, 7, 44, deadline_ms=0.0)
        deadline = time.monotonic() + 5.0
        while (b.deadline_total < 2 and time.monotonic() < deadline):
            time.sleep(0.005)  # watchdog sheds the expired pair
        b.flush()
        assert f2.result(timeout=5)["allowed"]
        assert f4.result(timeout=5)["allowed"]
        with pytest.raises(OverloadedError):
            f1.result(timeout=5)
        with pytest.raises(OverloadedError):
            f3.result(timeout=5)
        assert seen == [([2, 4], [5, 7], [22, 44])]
    finally:
        b.close()


def test_submit_many_bulk_path():
    from ratelimiter_tpu.engine.batcher import MicroBatcher

    seen = []

    def dispatch(slots, lids, permits):
        seen.append((list(slots), list(lids), list(permits)))
        return {"allowed": [True] * len(slots)}

    b = MicroBatcher(dispatch={"sw": dispatch},
                     clear={"sw": lambda s: None},
                     max_delay_ms=10_000.0)
    try:
        futs = b.submit_many(
            "sw", np.arange(5), np.zeros(5, dtype=np.int64),
            np.full(5, 2, dtype=np.int64))
        b.flush()
        assert all(f.result(timeout=5)["allowed"] for f in futs)
        assert seen == [(list(range(5)), [0] * 5, [2] * 5)]
    finally:
        b.close()


def test_acquire_async_many_matches_scalar_path(storage):
    """The bulk C-hash submit path decides exactly like per-key
    acquire_async over the same traffic."""
    cfg = RateLimitConfig(max_permits=2, window_ms=60_000)
    lid = storage.register_limiter("sw", cfg)
    keys = [f"bulk{i % 4}" for i in range(16)]  # 4 keys x 4 repeats
    futs = storage.acquire_async_many("sw", lid, keys)
    storage.flush()
    got = [bool(f.result(timeout=30)["allowed"]) for f in futs]
    oracle = SlidingWindowOracle(cfg)
    stamp = int(storage._last_stamp)
    want = [oracle.try_acquire(k, 1, stamp).allowed for k in keys]
    assert got == want


def test_adaptive_flush_controller_attached_and_fed(storage):
    cfg = RateLimitConfig(max_permits=10_000, window_ms=60_000)
    lid = storage.register_limiter("sw", cfg)
    assert storage._flush_controller is not None
    for i in range(30):
        storage.acquire("sw", lid, f"c{i}", 1)
    snap = storage._flush_controller.snapshot()
    assert snap["step_ewma_ms"] > 0  # fed by the drain
    assert 0 < snap["delay_ms"] <= 0.2  # clamped to the configured cap


def test_adaptive_flush_can_be_disabled():
    from ratelimiter_tpu.storage import TpuBatchedStorage

    st = TpuBatchedStorage(num_slots=1 << 9, max_delay_ms=0.2,
                           adaptive_flush=False)
    try:
        assert st._flush_controller is None
        lid = st.register_limiter(
            "sw", RateLimitConfig(max_permits=5, window_ms=60_000))
        assert bool(st.acquire("sw", lid, "x", 1)["allowed"])
    finally:
        st.close()


def test_concurrent_submitters_staged_correctness(storage):
    """16 threads of distinct keys through the staged path: every
    decision allowed (far under limit), nothing lost or cross-wired."""
    cfg = RateLimitConfig(max_permits=1_000_000, window_ms=60_000)
    lid = storage.register_limiter("sw", cfg)
    storage.warm_micro_shapes()
    errors = []

    def worker(t):
        try:
            for i in range(50):
                out = storage.acquire("sw", lid, f"w{t}-{i}", 1)
                assert bool(out["allowed"])
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors


def test_warm_micro_shapes_rounds_to_buckets_no_recompile(storage):
    """The PR 11 footgun, guarded: warming with NON-bucket sizes must
    round up to the real dispatch buckets (a warm dispatch whose n is
    below its buffer width would slice down and compile a lane count
    the batcher never produces).  After a public-API warm with odd
    sizes, steady-state micro traffic compiles NOTHING new."""
    from ratelimiter_tpu.engine.engine import DeviceEngine, _bucket_size

    cfg = RateLimitConfig(max_permits=1_000_000, window_ms=60_000)
    lid = storage.register_limiter("sw", cfg)
    # Odd sizes: each must round UP to its pow2 bucket (48 -> 64,
    # 100 -> 128, 1 -> 32) instead of warming phantom executables.
    assert isinstance(storage.engine, DeviceEngine)
    storage.engine.warm_micro_shapes(sizes=(1, 48, 100))
    assert {_bucket_size(n) for n in (1, 48, 100)} == {32, 64, 128}
    compiles = DeviceEngine.micro_compile_count()
    # Steady micro traffic across every warmed bucket: zero recompiles.
    for n in (1, 20, 33, 48, 64, 100, 128):
        futs = [storage.acquire_async("sw", lid, f"warm{n}-{i}", 1)
                for i in range(n)]
        storage.flush()
        for f in futs:
            assert bool(f.result(timeout=30)["allowed"])
    assert DeviceEngine.micro_compile_count() == compiles, (
        "micro traffic recompiled after a public-API warm — the "
        "bucket-rounding guard regressed")
