"""Differential tests for the relay steps (ops/relay.py) and the native
index's duplicate-structure outputs
(native/slot_index.cpp:assign_batch_uniques).

The relay paths must decide exactly like the sorted flat step on the
same batch and leave identical device state — that equivalence is what
lets the stream path delete the device-side sort/scan.  The C++ words
must match a straightforward Python reconstruction of ranks and last
flags, including the clamp sentinel.
"""

import numpy as np
import pytest

from ratelimiter_tpu.core.config import RateLimitConfig
from ratelimiter_tpu.engine.engine import DeviceEngine
from ratelimiter_tpu.engine.state import LimiterTable


@pytest.fixture()
def table():
    t = LimiterTable()
    t.register(RateLimitConfig(max_permits=5, window_ms=1000))          # 1 sw
    t.register(RateLimitConfig(max_permits=10, window_ms=1000,
                               refill_rate=5.0))                        # 2 tb
    t.register(RateLimitConfig(max_permits=3, window_ms=500,
                               refill_rate=2.0))                        # 3 tb
    return t


def _truth_structure(slots):
    """(rank, uidx, unique slots in first-appearance order, counts)."""
    seen, order, cnt = {}, [], {}
    rank = np.empty(len(slots), dtype=np.int32)
    uidx = np.empty(len(slots), dtype=np.int32)
    for i, s in enumerate(slots):
        if s not in seen:
            seen[s] = len(order)
            order.append(s)
        r = cnt.get(s, 0)
        cnt[s] = r + 1
        rank[i] = r
        uidx[i] = seen[s]
    return rank, uidx, np.asarray(order), np.asarray(
        [cnt[s] for s in order])


def _make_words(slots, rank_bits):
    rank, uidx, _, counts = _truth_structure(slots)
    clamp = (1 << rank_bits) - 1
    # True last occurrence (the C++ words path flags the actual last
    # position regardless of clamping).
    last = rank + 1 == counts[uidx]
    return (np.asarray(slots, np.uint32) << np.uint32(rank_bits + 1)
            | (np.minimum(rank, clamp).astype(np.uint32) << np.uint32(1))
            | last.astype(np.uint32))


def _make_uwords(slots, rank_bits):
    _, _, order, counts = _truth_structure(slots)
    clamp = (1 << rank_bits) - 1
    return (order.astype(np.uint32) << np.uint32(rank_bits + 1)
            | np.minimum(counts, clamp).astype(np.uint32) << np.uint32(1))


def _flat(engine, algo, slots, lid, now):
    fn = (engine.sw_flat_dispatch if algo == "sw"
          else engine.tb_flat_dispatch)
    return np.unpackbits(np.asarray(
        fn(slots, np.int32(lid), None, now)))[: len(slots)].astype(bool)


def _relay(engine, algo, slots, lid, now):
    words = _make_words(slots, engine.rank_bits)
    fn = (engine.sw_relay_dispatch if algo == "sw"
          else engine.tb_relay_dispatch)
    return np.unpackbits(np.asarray(
        fn(words, np.int32(lid), now)))[: len(slots)].astype(bool)


def _digest(engine, algo, slots, lid, now, out_dtype=np.uint8):
    rank, uidx, order, _ = _truth_structure(slots)
    uwords = _make_uwords(slots, engine.rank_bits)
    fn = (engine.sw_relay_counts_dispatch if algo == "sw"
          else engine.tb_relay_counts_dispatch)
    counts = np.asarray(fn(uwords, np.int32(lid), now, out_dtype))
    return rank < counts[: len(order)].astype(np.int32)[uidx]


def _state(engine, algo):
    return np.asarray(engine.sw_packed if algo == "sw"
                      else engine.tb_packed)


@pytest.mark.parametrize("algo,lid", [("sw", 1), ("tb", 2), ("tb", 3)])
def test_relay_matches_flat(table, algo, lid):
    """Duplicate-heavy random batches across window/refill boundaries:
    relay bits and digest counts must reproduce the sorted flat step's
    decisions bit-for-bit and leave identical state."""
    rng = np.random.default_rng(11)
    engines = [DeviceEngine(num_slots=64, table=table) for _ in range(3)]
    for now in (1_000_000, 1_000_123, 1_000_750, 1_004_000):
        slots = rng.integers(0, 9, 240).astype(np.int32)
        a = _flat(engines[0], algo, slots, lid, now)
        b = _relay(engines[1], algo, slots, lid, now)
        c = _digest(engines[2], algo, slots, lid, now)
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(a, c)
        np.testing.assert_array_equal(
            _state(engines[0], algo), _state(engines[1], algo))
        np.testing.assert_array_equal(
            _state(engines[0], algo), _state(engines[2], algo))


@pytest.mark.parametrize("algo,lid", [("sw", 1), ("tb", 3)])
def test_relay_clamped_ranks(table, algo, lid):
    """One segment longer than the rank clamp: decisions and state must
    still match the flat step.  The sentinel is deny-only ONLY when the
    clamp exceeds max_permits (here clamp 7 > max_permits 5 and 3 —
    exactly the precondition relay_usable() enforces)."""
    import functools

    import jax
    from ratelimiter_tpu.ops import relay

    rb = 3  # forced small clamp; engines would derive 24 at 64 slots
    eng = DeviceEngine(num_slots=64, table=table)
    slots = np.zeros(32, dtype=np.int32)  # one 32-long segment
    now = 1_000_000
    a = _flat(eng, algo, slots, lid, now)

    bits_fn = jax.jit(functools.partial(
        relay.sw_relay_bits if algo == "sw" else relay.tb_relay_bits,
        rank_bits=rb))
    counts_fn = jax.jit(functools.partial(
        relay.sw_relay_counts if algo == "sw" else relay.tb_relay_counts,
        rank_bits=rb))
    state0 = (eng.sw_packed if algo == "sw" else eng.tb_packed) * 0
    arrays = table.device_arrays

    st_b, bits = bits_fn(state0, arrays, _make_words(slots, rb),
                         np.int32(lid), now)
    b = np.unpackbits(np.asarray(bits))[:32].astype(bool)
    rank, uidx, order, _ = _truth_structure(slots)
    st_c, counts = counts_fn(state0, arrays, _make_uwords(slots, rb),
                             np.int32(lid), now)
    c = rank < np.asarray(counts)[: len(order)].astype(np.int32)[uidx]
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(a, c)
    truth_state = _state(eng, algo)
    np.testing.assert_array_equal(truth_state[:1], np.asarray(st_b)[:1])
    np.testing.assert_array_equal(truth_state[:1], np.asarray(st_c)[:1])


@pytest.mark.parametrize("algo,lid", [("sw", 1), ("tb", 2), ("tb", 3)])
def test_relay_digest_both_backends_match_flat(table, algo, lid,
                                               monkeypatch):
    """The digest parity of test_relay_matches_flat, run on BOTH digest
    backends: the composed-XLA step and the fused Pallas relay kernel
    (interpret mode, elected through the real engine dispatch).  Both
    must reproduce the sorted flat step bit-for-bit and leave identical
    state."""
    from ratelimiter_tpu.ops.pallas import election
    from ratelimiter_tpu.ops.pallas import relay_step as rs

    monkeypatch.setattr(rs, "_INTERPRET", True)
    monkeypatch.setattr(rs, "_probe_ok", None)
    election.reset_for_tests()
    try:
        rng = np.random.default_rng(11)
        num_slots = 512  # fused floor: >= 2 Pallas blocks
        e_flat = DeviceEngine(num_slots=num_slots, table=table)
        e_xla = DeviceEngine(num_slots=num_slots, table=table)
        e_fused = DeviceEngine(num_slots=num_slots, table=table)
        e_xla._relay_fused_ok = lambda algo, u: False  # force composed
        assert e_fused._relay_fused_ok(algo, num_slots)
        rb = e_fused.rank_bits
        dispatch_of = {
            e_xla: (e_xla.sw_relay_counts_dispatch if algo == "sw"
                    else e_xla.tb_relay_counts_dispatch),
            e_fused: (e_fused.sw_relay_counts_dispatch if algo == "sw"
                      else e_fused.tb_relay_counts_dispatch),
        }

        def digest_sorted(engine, slots, now):
            rank, uidx, order, counts = _truth_structure(slots)
            perm = np.argsort(order)
            inv = np.empty_like(perm)
            inv[perm] = np.arange(len(perm))
            clamp = (1 << rb) - 1
            uw = np.full(num_slots, 0xFFFFFFFF, dtype=np.uint32)
            uw[:len(order)] = (
                (order[perm].astype(np.uint32) << np.uint32(rb + 1))
                | (np.minimum(counts[perm], clamp).astype(np.uint32)
                   << np.uint32(1)))
            out = np.asarray(dispatch_of[engine](
                uw, np.int32(lid), now, np.uint8, slots_sorted=True))
            return rank < out[:len(order)].astype(np.int32)[inv[uidx]]

        for now in (1_000_000, 1_000_123, 1_000_750, 1_004_000):
            slots = rng.integers(0, 9, 240).astype(np.int32)
            a = _flat(e_flat, algo, slots, lid, now)
            b = digest_sorted(e_xla, slots, now)
            c = digest_sorted(e_fused, slots, now)
            np.testing.assert_array_equal(a, b)
            np.testing.assert_array_equal(a, c)
            np.testing.assert_array_equal(
                _state(e_flat, algo), _state(e_xla, algo))
            np.testing.assert_array_equal(
                _state(e_flat, algo), _state(e_fused, algo))
        assert any(len(k) > 2 and k[2] == "fused"
                   for k in e_fused._relay_counts)
    finally:
        election.reset_for_tests()


def test_relay_usable_gate():
    """A policy whose max_permits exceeds the clamp must disable relay."""
    t = LimiterTable()
    t.register(RateLimitConfig(max_permits=5, window_ms=1000))
    eng = DeviceEngine(num_slots=1 << 20, table=t)  # rank_bits 10, clamp 1023
    assert eng.relay_usable()
    t.register(RateLimitConfig(max_permits=2000, window_ms=1000))
    assert not eng.relay_usable()


def test_native_uniques_match_truth():
    """C++ duplicate structure == Python reconstruction, including count
    clamping, for all three key flavors."""
    from ratelimiter_tpu.engine.native_index import (
        NativeSlotIndex, native_available)

    if not native_available():
        pytest.skip("native index unavailable")
    rng = np.random.default_rng(5)
    rb = 3
    for flavor in ("int", "str", "multi"):
        ix_u = NativeSlotIndex(256)
        ix_ref = NativeSlotIndex(256)
        keys = rng.integers(0, 17, 400)
        if flavor == "int":
            uwords, uidx, rank, _ = ix_u.assign_batch_ints_uniques(keys, 1, rb)
            slots, _ = ix_ref.assign_batch_ints(keys, 1)
        elif flavor == "str":
            skeys = [f"k{v}" for v in keys]
            uwords, uidx, rank, _ = ix_u.assign_batch_strs_uniques(
                skeys, 1, rb)
            slots, _ = ix_ref.assign_batch_strs(skeys, 1)
        else:
            lids = rng.integers(1, 4, 400)
            uwords, uidx, rank, _ = ix_u.assign_batch_ints_multi_uniques(
                keys, lids, rb)
            slots, _ = ix_ref.assign_batch_ints_multi(keys, lids)
        np.testing.assert_array_equal(uwords, _make_uwords(slots, rb),
                                      err_msg=flavor)
        t_rank, t_uidx, _, _ = _truth_structure(slots)
        np.testing.assert_array_equal(rank, t_rank, err_msg=flavor)
        np.testing.assert_array_equal(uidx, t_uidx, err_msg=flavor)


@pytest.mark.parametrize("force_mode", ["digest", "bits"])
def test_stream_relay_modes_match_batch_path(monkeypatch, force_mode):
    """Storage-level: the relay stream (either mode) must decide exactly
    like acquire_many_ids over the same chunks at the same timestamps."""
    import ratelimiter_tpu.storage.tpu as tpu_mod
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    if force_mode == "bits":
        # Disable digest election so the per-request reconstruction runs.
        monkeypatch.setattr(
            TpuBatchedStorage, "_stream_relay",
            _forced_bits_stream(TpuBatchedStorage._stream_relay))
    rng = np.random.default_rng(21)
    now = [5_000_000]
    st_a = TpuBatchedStorage(num_slots=1 << 12, clock_ms=lambda: now[0])
    st_b = TpuBatchedStorage(num_slots=1 << 12, clock_ms=lambda: now[0])
    cfg = RateLimitConfig(max_permits=6, window_ms=1000, refill_rate=4.0)
    lid_a = st_a.register_limiter("tb", cfg)
    lid_b = st_b.register_limiter("tb", cfg)
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK", 256)
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK_MAX", 256)
    for rep in range(4):
        ids = rng.integers(0, 40, 700)
        a = st_a.acquire_stream_ids("tb", lid_a, ids, None, batch=256,
                                    subbatches=1)
        res = np.empty(700, dtype=bool)
        for i in range(0, 700, 256):
            res[i:i + 256] = st_b.acquire_many_ids(
                "tb", lid_b, ids[i:i + 256],
                np.ones(len(ids[i:i + 256]), np.int64))["allowed"]
        np.testing.assert_array_equal(a, res, err_msg=f"rep {rep}")
        now[0] += 237
    st_a.close()
    st_b.close()


@pytest.mark.parametrize("algo", ["sw", "tb"])
def test_stream_relay_soak_vs_oracle(algo):
    """Randomized multi-pass soak: the relay stream (mode elected per
    chunk) against the executable oracle, with duplicate-heavy traffic,
    window rolls, refills, and resets between passes."""
    import random

    from ratelimiter_tpu.semantics import (
        SlidingWindowOracle,
        TokenBucketOracle,
    )
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    now = [3_000_000]
    st = TpuBatchedStorage(num_slots=1 << 12, clock_ms=lambda: now[0])
    if algo == "sw":
        cfg = RateLimitConfig(max_permits=6, window_ms=1000,
                              enable_local_cache=False)
        oracle = SlidingWindowOracle(cfg)
    else:
        cfg = RateLimitConfig(max_permits=8, window_ms=1500,
                              refill_rate=5.0)
        oracle = TokenBucketOracle(cfg)
    lid = st.register_limiter(algo, cfg)
    rng = np.random.default_rng(77)
    pyrng = random.Random(77)
    for step in range(12):
        now[0] += pyrng.randrange(0, 900)
        ids = rng.integers(0, 30, 400)
        got = st.acquire_stream_ids(algo, lid, ids, None)
        for j, k in enumerate(ids):
            want = oracle.try_acquire(f"id:{k}", 1, now[0]).allowed
            assert got[j] == want, (algo, step, j)
        if pyrng.random() < 0.3:
            k = int(pyrng.choice(list(ids)))
            st.reset_key(algo, lid, k)  # int user key, same namespace
            oracle.reset(f"id:{k}", now[0])
    st.close()


@pytest.mark.parametrize("algo", ["sw", "tb"])
def test_resident_lid_map_survives_eviction_churn(monkeypatch, algo):
    """Multi-tenant digest with device-resident lids: a slot evicted and
    reassigned to a key of a DIFFERENT tenant must get its new lid
    re-uploaded (tracked by _lid_known, invalidated via _clear_slots) —
    decisions must match the chunked batch path exactly throughout."""
    import ratelimiter_tpu.storage.tpu as tpu_mod
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    now = [2_000_000]
    # Tiny slot table so the stream constantly evicts and reassigns.
    st_a = TpuBatchedStorage(num_slots=32, clock_ms=lambda: now[0])
    st_b = TpuBatchedStorage(num_slots=32, clock_ms=lambda: now[0])
    if algo == "sw":
        cfgs = [RateLimitConfig(max_permits=3 + i, window_ms=1000,
                                enable_local_cache=False) for i in range(3)]
    else:
        cfgs = [RateLimitConfig(max_permits=3 + i, window_ms=1000,
                                refill_rate=2.0 + i) for i in range(3)]
    lids_a = np.asarray([st_a.register_limiter(algo, c) for c in cfgs])
    lids_b = np.asarray([st_b.register_limiter(algo, c) for c in cfgs])
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK", 64)
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK_MAX", 64)
    rng = np.random.default_rng(5)
    for rep in range(6):
        # 24 live (lid,key) pairs per rep, window sliding by 8 each rep:
        # old pairs evict (32-slot table) and their slots get reassigned
        # to pairs of OTHER tenants across reps — the lid re-upload path.
        pairs = rng.integers(rep * 8, rep * 8 + 24, 256)
        ids = pairs
        tl = pairs % 3
        a = st_a.acquire_stream_ids(algo, lids_a[tl], ids, None)
        res = np.empty(256, dtype=bool)
        for i in range(0, 256, 64):
            chunk_lids = lids_b[tl[i:i + 64]]
            got = st_b.acquire_stream_ids(
                algo, chunk_lids, ids[i:i + 64], np.ones(64, np.int64))
            res[i:i + 64] = got
        np.testing.assert_array_equal(a, res, err_msg=f"rep {rep}")
        now[0] += 173
    st_a.close()
    st_b.close()


@pytest.mark.parametrize("force_mode", ["digest", "bits"])
@pytest.mark.parametrize("multi_lid", [False, True])
def test_sharded_relay_matches_single_device(monkeypatch, force_mode,
                                             multi_lid):
    """The sharded relay stream (8-device CPU mesh, either wire mode,
    single- and multi-tenant) must decide exactly like the single-device
    relay on the same stream at the same timestamps."""
    import ratelimiter_tpu.storage.tpu as tpu_mod
    from ratelimiter_tpu.parallel import ShardedDeviceEngine
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    now = [7_000_000]
    table_s, table_f = LimiterTable(), LimiterTable()
    cfgs = [RateLimitConfig(max_permits=4 + i, window_ms=1000,
                            refill_rate=3.0 + i) for i in range(3)]
    lids_s = [table_s.register(c) for c in cfgs]
    lids_f = [table_f.register(c) for c in cfgs]
    eng = ShardedDeviceEngine(slots_per_shard=64, table=table_s)
    st_s = TpuBatchedStorage(engine=eng, clock_ms=lambda: now[0])
    st_f = TpuBatchedStorage(num_slots=1 << 12, table=table_f,
                             clock_ms=lambda: now[0])
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK", 128)
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK_MAX", 128)
    if force_mode == "bits":
        for e in (eng, st_f.engine):
            monkeypatch.setattr(type(e), "counts_dtype",
                                lambda self: None, raising=True)
    rng = np.random.default_rng(33)
    for rep in range(3):
        ids = rng.integers(0, 60, 500)
        if multi_lid:
            larr_s = np.asarray(lids_s)[rng.integers(0, 3, 500)]
            larr_f = np.asarray(lids_f)[(larr_s - lids_s[0])]
            a = st_s.acquire_stream_ids("tb", larr_s, ids, None)
            b = st_f.acquire_stream_ids("tb", larr_f, ids, None)
        else:
            a = st_s.acquire_stream_ids("tb", lids_s[1], ids, None)
            b = st_f.acquire_stream_ids("tb", lids_f[1], ids, None)
        np.testing.assert_array_equal(a, b, err_msg=f"rep {rep}")
        now[0] += 321
    st_s.close()
    st_f.close()


def _forced_bits_stream(orig):
    def wrapper(self, algo, lid, assign_uniques, n, lid_arr=None):
        eng = self.engine
        real = eng.counts_dtype

        eng.counts_dtype = lambda: None  # digest never elected
        try:
            return orig(self, algo, lid, assign_uniques, n, lid_arr)
        finally:
            eng.counts_dtype = real
    return wrapper


def test_held_pins_block_concurrent_eviction():
    """The assign->dispatch window contract: pinned slots must survive a
    concurrent assign's eviction pressure (the concurrent assign either
    finds other victims or refuses), for the native and Python indexes."""
    from ratelimiter_tpu.engine.native_index import (
        NativeSlotIndex, native_available)
    from ratelimiter_tpu.engine.slots import SlotIndex

    indexes = [SlotIndex(4)]
    if native_available():
        indexes.append(NativeSlotIndex(4))
    for ix in indexes:
        slots = [ix.assign((1, k))[0] for k in range(4)]  # full table
        ix.pin_batch(np.asarray(slots[:3], dtype=np.int32))
        # Only the unpinned slot may be evicted.
        s, ev = ix.assign((1, 99))
        assert ev == slots[3] and s == slots[3], (type(ix).__name__, s, ev)
        ix.pin_batch(np.asarray([s], dtype=np.int32))
        with pytest.raises(RuntimeError):
            ix.assign((1, 100))  # everything pinned now
        ix.unpin_batch(np.asarray(slots[:3] + [s], dtype=np.int32))
        s2, ev2 = ix.assign((1, 100))  # unpinned again: eviction works
        assert ev2 is not None


# ---------------------------------------------------------------------------
# Weighted-permit relay (ops/relay.py:*_relay_weighted)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("algo", ["sw", "tb"])
def test_stream_weighted_matches_batch_path(monkeypatch, algo):
    """The weighted relay stream must decide exactly like acquire_many_ids
    over the same chunks at the same timestamps — including mixed
    single/multi segments and the skip recurrence."""
    import ratelimiter_tpu.storage.tpu as tpu_mod
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    rng = np.random.default_rng(31)
    now = [5_000_000]
    st_a = TpuBatchedStorage(num_slots=1 << 12, clock_ms=lambda: now[0])
    st_b = TpuBatchedStorage(num_slots=1 << 12, clock_ms=lambda: now[0])
    if algo == "sw":
        cfg = RateLimitConfig(max_permits=6, window_ms=1000,
                              enable_local_cache=False)
    else:
        cfg = RateLimitConfig(max_permits=9, window_ms=1000,
                              refill_rate=4.0)
    lid_a = st_a.register_limiter(algo, cfg)
    lid_b = st_b.register_limiter(algo, cfg)
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK", 256)
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK_MAX", 256)
    for rep in range(4):
        ids = rng.integers(0, 40, 768)
        perms = rng.integers(1, 11, 768).astype(np.int64)
        a = st_a.acquire_stream_ids(algo, lid_a, ids, perms)
        res = np.empty(768, dtype=bool)
        for i in range(0, 768, 256):
            res[i:i + 256] = st_b.acquire_many_ids(
                algo, lid_b, ids[i:i + 256],
                perms[i:i + 256])["allowed"]
        np.testing.assert_array_equal(a, res, err_msg=f"rep {rep}")
        now[0] += 431
    st_a.close()
    st_b.close()


def test_stream_weighted_skip_semantics():
    """A denied large request consumes nothing — a later smaller request
    of the SAME key in the SAME chunk can still pass (the reference's
    Lua semantics; a prefix-sum closed form would get this wrong)."""
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    now = [9_000_000]
    st = TpuBatchedStorage(num_slots=1 << 12, clock_ms=lambda: now[0])
    cfg = RateLimitConfig(max_permits=10, window_ms=1000, refill_rate=1.0)
    lid = st.register_limiter("tb", cfg)
    ids = np.asarray([7, 7, 7], dtype=np.int64)
    perms = np.asarray([8, 5, 2], dtype=np.int64)
    got = st.acquire_stream_ids("tb", lid, ids, perms)
    np.testing.assert_array_equal(got, [True, False, True])
    st.close()


@pytest.mark.parametrize("algo", ["sw", "tb"])
def test_stream_weighted_fallback_deep_segments(monkeypatch, algo):
    """A chunk whose deepest segment exceeds _WREL_MAX_R must take the
    sorted-flat fallback and still match the batch path exactly."""
    import ratelimiter_tpu.storage.tpu as tpu_mod
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    monkeypatch.setattr(tpu_mod, "_WREL_MAX_R", 4)
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK", 128)
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK_MAX", 128)
    rng = np.random.default_rng(41)
    now = [6_000_000]
    st_a = TpuBatchedStorage(num_slots=1 << 12, clock_ms=lambda: now[0])
    st_b = TpuBatchedStorage(num_slots=1 << 12, clock_ms=lambda: now[0])
    if algo == "sw":
        cfg = RateLimitConfig(max_permits=7, window_ms=1000,
                              enable_local_cache=False)
    else:
        cfg = RateLimitConfig(max_permits=12, window_ms=1000,
                              refill_rate=6.0)
    lid_a = st_a.register_limiter(algo, cfg)
    lid_b = st_b.register_limiter(algo, cfg)
    # Hot key: ~1/3 of traffic -> segments far deeper than the forced cap.
    ids = np.where(rng.random(384) < 0.34, 3,
                   rng.integers(0, 30, 384)).astype(np.int64)
    perms = rng.integers(1, 9, 384).astype(np.int64)
    a = st_a.acquire_stream_ids(algo, lid_a, ids, perms)
    res = np.empty(384, dtype=bool)
    for i in range(0, 384, 128):
        res[i:i + 128] = st_b.acquire_many_ids(
            algo, lid_b, ids[i:i + 128], perms[i:i + 128])["allowed"]
    np.testing.assert_array_equal(a, res)
    st_a.close()
    st_b.close()


@pytest.mark.parametrize("algo", ["sw", "tb"])
def test_stream_weighted_soak_vs_oracle(algo):
    """Randomized weighted soak against the executable oracle: mixed
    permits, duplicate-heavy traffic, rolls/refills, resets."""
    import random

    from ratelimiter_tpu.semantics import (
        SlidingWindowOracle,
        TokenBucketOracle,
    )
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    now = [3_000_000]
    st = TpuBatchedStorage(num_slots=1 << 12, clock_ms=lambda: now[0])
    if algo == "sw":
        cfg = RateLimitConfig(max_permits=6, window_ms=1000,
                              enable_local_cache=False)
        oracle = SlidingWindowOracle(cfg)
    else:
        cfg = RateLimitConfig(max_permits=8, window_ms=1500,
                              refill_rate=5.0)
        oracle = TokenBucketOracle(cfg)
    lid = st.register_limiter(algo, cfg)
    rng = np.random.default_rng(87)
    pyrng = random.Random(87)
    for step in range(12):
        now[0] += pyrng.randrange(0, 900)
        ids = rng.integers(0, 30, 400)
        perms = rng.integers(1, 7, 400).astype(np.int64)
        got = st.acquire_stream_ids(algo, lid, ids, perms)
        for j, k in enumerate(ids):
            want = oracle.try_acquire(f"id:{k}", int(perms[j]),
                                      now[0]).allowed
            assert got[j] == want, (algo, step, j)
        if pyrng.random() < 0.3:
            k = int(pyrng.choice(list(ids)))
            st.reset_key(algo, lid, k)
            oracle.reset(f"id:{k}", now[0])
    st.close()


@pytest.mark.parametrize("algo", ["sw", "tb"])
def test_stream_weighted_strs_matches_batch_path(monkeypatch, algo):
    """String-key weighted streams run the same weighted relay loop; the
    decisions must match acquire_many on identical chunks."""
    import ratelimiter_tpu.storage.tpu as tpu_mod
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    rng = np.random.default_rng(53)
    now = [4_000_000]
    st_a = TpuBatchedStorage(num_slots=1 << 12, clock_ms=lambda: now[0])
    st_b = TpuBatchedStorage(num_slots=1 << 12, clock_ms=lambda: now[0])
    if algo == "sw":
        cfg = RateLimitConfig(max_permits=6, window_ms=1000,
                              enable_local_cache=False)
    else:
        cfg = RateLimitConfig(max_permits=9, window_ms=1000,
                              refill_rate=4.0)
    lid_a = st_a.register_limiter(algo, cfg)
    lid_b = st_b.register_limiter(algo, cfg)
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK", 256)
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK_MAX", 256)
    for rep in range(3):
        keys = [f"u{int(k)}" for k in rng.integers(0, 35, 512)]
        perms = rng.integers(1, 11, 512).astype(np.int64)
        a = st_a.acquire_stream_strs(algo, lid_a, keys, perms)
        res = np.empty(512, dtype=bool)
        for i in range(0, 512, 256):
            got = st_b.acquire_many(
                algo, [lid_b] * 256, keys[i:i + 256],
                list(perms[i:i + 256]))
            res[i:i + 256] = got["allowed"]
        np.testing.assert_array_equal(a, res, err_msg=f"rep {rep}")
        now[0] += 433
    st_a.close()
    st_b.close()


def test_sorted_digest_stream_matches_unsorted(monkeypatch):
    """Slot-sorted digest dispatches (u >= _SORT_UNIQUES_MIN triggers the
    C radix sort + uidx remap + presorted scatter path) decide exactly
    like the unsorted path on the same stream."""
    import ratelimiter_tpu.storage.tpu as tpu_mod
    from ratelimiter_tpu.engine.native_index import native_available
    from ratelimiter_tpu.storage import TpuBatchedStorage

    if not native_available():
        pytest.skip("needs the native library")
    now = [1_000_000]
    rng = np.random.default_rng(8)
    n = 1 << 15
    # Zipf-ish duplication with > 4096 uniques per chunk.
    ids = rng.integers(0, 12_000, n).astype(np.int64)

    # Force the sorted path on CPU (the device sweep itself is gated to
    # TPU; the XLA fallback scatter is order-blind, so this exercises
    # sort + uidx remap + dispatch + reconstruction end to end).
    monkeypatch.setattr(tpu_mod, "_presorted_scatter_usable",
                        lambda eng, algo, padded: True)

    def run(sort_min):
        monkeypatch.setattr(tpu_mod, "_SORT_UNIQUES_MIN", sort_min)
        st = TpuBatchedStorage(num_slots=1 << 15, clock_ms=lambda: now[0])
        lid = st.register_limiter("tb", RateLimitConfig(
            max_permits=5, window_ms=60_000, refill_rate=1.0))
        outs = [st.acquire_stream_ids("tb", lid, ids, None)
                for _ in range(2)]
        st.close()
        return outs

    sorted_outs = run(1 << 12)   # sorting active
    unsorted_outs = run(1 << 62)  # threshold unreachable: never sorts
    for a, b in zip(sorted_outs, unsorted_outs):
        np.testing.assert_array_equal(a, b)


def test_sort_uniques_parity():
    """rl_sort_uniques: words end up slot-ascending, the multiset of
    words is preserved, and the remapped uidx points every request at
    its original word."""
    from ratelimiter_tpu.engine.native_index import (
        native_available,
        sort_uniques,
    )

    if not native_available():
        pytest.skip("needs the native library")
    rng = np.random.default_rng(4)
    rb = 9
    for _ in range(10):
        u = int(rng.integers(2, 5000))
        n = u * 3
        slots = rng.choice(1 << 20, size=u, replace=False).astype(np.uint32)
        counts = rng.integers(1, 7, u).astype(np.uint32)
        uwords = (slots << np.uint32(rb + 1)) | (counts << np.uint32(1))
        uidx = rng.integers(0, u, n).astype(np.int32)
        orig_words = uwords.copy()
        orig_word_of_req = orig_words[uidx]
        uw = uwords.copy()
        ui = uidx.copy()
        assert sort_uniques(uw, rb, ui)
        # Cast BEFORE diff: uint32 diff wraps modulo 2^32, which made
        # this assertion pass for any permutation.
        sorted_slots = (uw >> np.uint32(rb + 1)).astype(np.int64)
        assert (np.diff(sorted_slots) > 0).all()
        np.testing.assert_array_equal(np.sort(uw), np.sort(orig_words))
        np.testing.assert_array_equal(uw[ui], orig_word_of_req)


def test_split_digest_mode_parity_and_engagement():
    """r5 split-digest: singleton uniques ride a 3-byte slot plane with
    BIT decisions back; multis keep uwords+counts.  Decisions must be
    identical to a profile-less storage (words/digest paths) on the
    same stream, and the mode must actually engage (the stream_stats
    record proves it, not the test's intent)."""
    import numpy as np

    from ratelimiter_tpu import RateLimitConfig
    from ratelimiter_tpu.storage import TpuBatchedStorage

    now = [1_000_000]
    rng = np.random.default_rng(11)
    n = 40_000
    # ~0.85 u/n with a few hot keys: both singles and multis present.
    ids = np.concatenate([
        rng.integers(0, 30_000, n - 2_000),
        rng.integers(0, 50, 2_000),
    ]).astype(np.int64)
    rng.shuffle(ids)

    def make(profiled):
        st = TpuBatchedStorage(num_slots=1 << 16, clock_ms=lambda: now[0])
        lid = st.register_limiter("tb", RateLimitConfig(
            max_permits=20, window_ms=60_000, refill_rate=5.0))
        if profiled:
            # Slow both directions: per-unique wire dominates and the
            # split's 3 B + bits-back wins every election.
            st.set_link_profile(2e6, 0.05, 2e6)
        return st, lid

    sa, la = make(True)
    sb, lb = make(False)
    engaged = 0
    for p in range(3):
        sa.stream_stats = stats = []
        ga = sa.acquire_stream_ids("tb", la, ids)
        sa.stream_stats = None
        gb = sb.acquire_stream_ids("tb", lb, ids)
        np.testing.assert_array_equal(ga, gb)
        engaged += sum(1 for r in stats if r.get("mode") == "split")
        now[0] += 10_000
    assert engaged > 0, "split mode never engaged"
    # Sanity: singletons were the majority and recorded.
    rec = next(r for r in stats if r.get("mode") == "split")
    assert rec["singles"] > rec["u"] * 0.3, rec
    sa.close()
    sb.close()
