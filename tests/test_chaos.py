"""Fault injection (storage/chaos.py) driving the documented failure
machinery: retry-with-backoff, fail-open, and metric accounting."""

import numpy as np
import pytest

from ratelimiter_tpu import RateLimitConfig
from ratelimiter_tpu.algorithms import SlidingWindowRateLimiter
from ratelimiter_tpu.metrics import MeterRegistry
from ratelimiter_tpu.storage import (
    FaultInjectingStorage,
    InMemoryStorage,
    StorageException,
    TpuBatchedStorage,
)
from ratelimiter_tpu.storage.errors import RetryPolicy


def test_forced_failures_then_recovery():
    chaos = FaultInjectingStorage(InMemoryStorage())
    chaos.fail_next(2)
    with pytest.raises(StorageException):
        chaos.increment_and_expire("k", 1000)
    with pytest.raises(StorageException):
        chaos.increment_and_expire("k", 1000)
    # Third call succeeds and state is consistent (failures left no trace).
    assert chaos.increment_and_expire("k", 1000) == 1
    assert chaos.injected_failures == 2


def test_retry_policy_survives_transient_faults():
    """RetryPolicy (the reference's 3-attempt linear-backoff analog) rides
    over injected transients."""
    chaos = FaultInjectingStorage(InMemoryStorage())
    retry = RetryPolicy(max_retries=3, retry_delay_ms=0.1)
    chaos.fail_next(2)  # two transients, third attempt lands
    value = retry.execute(lambda: chaos.increment_and_expire("k", 1000))
    assert value == 1
    # Exhaustion: more faults than attempts -> StorageException escapes.
    chaos.fail_next(3)
    with pytest.raises(StorageException):
        retry.execute(lambda: chaos.increment_and_expire("k", 1000))


def test_probabilistic_faults_are_deterministic_by_seed():
    a = FaultInjectingStorage(InMemoryStorage(), failure_rate=0.5, seed=7)
    b = FaultInjectingStorage(InMemoryStorage(), failure_rate=0.5, seed=7)

    def drive(s):
        outcomes = []
        for i in range(50):
            try:
                s.increment_and_expire(f"k{i}", 1000)
                outcomes.append(True)
            except StorageException:
                outcomes.append(False)
        return outcomes

    assert drive(a) == drive(b)
    assert 0 < a.injected_failures < 50


def test_limiter_fail_open_over_chaos_storage():
    """The service-documented fail-open policy: storage outage => allow.
    (The reference documents this and actually 500s; SURVEY §5.3.)
    StorageException surfaces from the limiter, which is exactly what
    service/app.py's _try_acquire converts into allow-and-count."""
    chaos = FaultInjectingStorage(InMemoryStorage())
    limiter = SlidingWindowRateLimiter(
        chaos,
        RateLimitConfig(max_permits=2, window_ms=1000,
                        enable_local_cache=False),
        MeterRegistry())
    assert limiter.try_acquire("u")
    chaos.fail_next(10)
    with pytest.raises(StorageException):
        limiter.try_acquire("u")


def test_chaos_wraps_device_storage_stream():
    """The wrapper composes with the TPU-batched backend: injected faults
    surface from the stream path, clean calls pass through unchanged."""
    clock = lambda: 12_000  # noqa: E731
    inner = TpuBatchedStorage(num_slots=64, clock_ms=clock)
    chaos = FaultInjectingStorage(inner)
    lid = chaos.register_limiter("tb", RateLimitConfig(
        max_permits=3, window_ms=1000, refill_rate=1.0))
    ids = np.zeros(5, dtype=np.int64)
    got = chaos.acquire_stream_ids("tb", lid, ids, None, batch=4, subbatches=1)
    assert got.tolist() == [True, True, True, False, False]
    chaos.fail_next(1)
    with pytest.raises(StorageException):
        chaos.acquire_stream_ids("tb", lid, ids, None, batch=4, subbatches=1)
    chaos.close()


def test_default_wiring_composes_retry_over_breaker_over_chaos():
    """build_app wires retry(breaker(chaos(storage))): transient faults are
    absorbed by the retry layer (the RedisRateLimitStorage.java:155-178
    analog) and never reach the caller; only exhaustion escalates.  The
    breaker sits INSIDE retry so every attempt counts toward its
    threshold."""
    from ratelimiter_tpu.service.props import AppProperties
    from ratelimiter_tpu.service.wiring import build_app
    from ratelimiter_tpu.storage.breaker import CircuitBreakerStorage
    from ratelimiter_tpu.storage.retry import RetryingStorage

    props = AppProperties({
        "storage.backend": "memory",
        "chaos.failure_rate": "0.3",   # any nonzero rate arms the injector
        "storage.retry.max_retries": "3",
        "storage.retry.delay_ms": "0.1",
        "warmup.enabled": "false",
    })
    ctx = build_app(props)
    try:
        assert isinstance(ctx.storage, RetryingStorage)
        breaker = ctx.storage._inner
        assert isinstance(breaker, CircuitBreakerStorage)
        assert ctx.breaker is breaker
        chaos = breaker._inner
        assert isinstance(chaos, FaultInjectingStorage)
        chaos.failure_rate = 0.0  # deterministic: forced faults only

        # Two transients: absorbed (3 attempts) — the decision still lands.
        chaos.fail_next(2)
        assert ctx.limiters["auth"].try_acquire("bob")
        assert chaos.injected_failures >= 2

        # Exhaustion: three forced faults beat 3 attempts on ONE op.
        chaos.fail_next(3)
        with pytest.raises(StorageException):
            ctx.limiters["auth"].try_acquire("bob")
    finally:
        ctx.close()


def test_retry_exhaustion_reaches_fail_open_counter():
    """Service-level accounting: only retry exhaustion lands in the
    fail-open counter; absorbed transients don't."""
    from ratelimiter_tpu.service.app import make_server
    from ratelimiter_tpu.service.props import AppProperties
    from ratelimiter_tpu.service.wiring import build_app
    import json
    import urllib.request

    props = AppProperties({
        "storage.backend": "memory",
        "chaos.failure_rate": "0.0001",  # armed but ~quiet
        "storage.retry.max_retries": "2",
        "storage.retry.delay_ms": "0.1",
        "warmup.enabled": "false",
        "server.port": "0",
    })
    ctx = build_app(props)
    server = make_server(ctx)
    import threading

    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    port = server.server_address[1]
    chaos = ctx.storage._inner._inner  # retry -> breaker -> chaos
    assert isinstance(chaos, FaultInjectingStorage)
    chaos.failure_rate = 0.0  # deterministic: forced faults only

    def hit():
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/data",
            headers={"X-User-ID": "carol"})
        with urllib.request.urlopen(req) as resp:
            return resp.status

    def metric():
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/actuator/metrics") as resp:
            data = json.loads(resp.read())
        return data["meters"].get("ratelimiter.failopen.allowed", 0)

    try:
        assert hit() == 200
        # One transient: retry absorbs it, no fail-open.
        chaos.fail_next(1)
        assert hit() == 200
        assert metric() == 0
        # Exhaustion (2 attempts, 2 faults): fail-open allows and counts.
        chaos.fail_next(2)
        assert hit() == 200
        assert metric() == 1
    finally:
        server.shutdown()
        ctx.close()


def test_retry_policy_skips_validation_errors():
    """Programming/validation errors are not transport faults: no retry, no
    StorageException conversion — they must never reach fail-open."""
    calls = []

    def op():
        calls.append(1)
        raise ValueError("bad arg")

    with pytest.raises(ValueError):
        RetryPolicy(max_retries=3, retry_delay_ms=0.1).execute(op)
    assert len(calls) == 1


def test_stream_ops_pass_through_retry_unreplayed():
    """Batch/stream decision ops mutate state per super-batch: a replay
    would re-charge already-committed requests, so the retry wrapper must
    NOT replay them — while single acquire (replay-safe, reference parity)
    is retried."""
    from ratelimiter_tpu.storage.retry import RetryingStorage

    clock = lambda: 20_000  # noqa: E731
    inner = TpuBatchedStorage(num_slots=64, clock_ms=clock)
    chaos = FaultInjectingStorage(inner)
    st = RetryingStorage(chaos, RetryPolicy(max_retries=3,
                                            retry_delay_ms=0.1))
    lid = st.register_limiter("tb", RateLimitConfig(
        max_permits=5, window_ms=1000, refill_rate=1.0))

    chaos.fail_next(1)
    with pytest.raises(StorageException):
        st.acquire_stream_ids("tb", lid, np.zeros(4, np.int64), None,
                              batch=4, subbatches=1)
    assert chaos.injected_failures == 1  # exactly one attempt — no replay

    chaos.fail_next(1)  # transient on the single-acquire path: absorbed
    out = st.acquire("tb", lid, "k", 1)
    assert out["allowed"]
    st.close()


# ---------------------------------------------------------------------------
# Mid-stream fault injection (VERDICT r2 #8): a dispatch or fetch dying
# inside a stream must release held pins, keep the lid bookkeeping
# conservative, and leave the storage fully usable.  The contract on
# partial results is RAISE — callers never see a partial `out`.
# ---------------------------------------------------------------------------

def _fail_after(fn, n, exc=RuntimeError("injected dispatch failure")):
    """Wrap an engine dispatch: exactly the (n+1)-th call raises; all
    later calls pass through (so post-failure recovery can be driven)."""
    calls = {"n": 0}

    def wrapped(*a, **kw):
        calls["n"] += 1
        if calls["n"] == n + 1:
            raise exc
        return fn(*a, **kw)

    return wrapped


class _PoisonFetch:
    """A dispatch handle whose fetch (np.asarray) raises."""

    def __array__(self, *a, **kw):
        raise RuntimeError("injected fetch failure")


def _assert_no_pin_leak(storage, algo, n_slots):
    """Every slot must be evictable again: assigning a full table's worth
    of fresh keys raises iff a pin leaked (pinned slots are skipped by
    eviction, so one leak leaves the last fresh key victimless)."""
    index = storage._index[algo]
    fresh = np.arange(10_000_000, 10_000_000 + n_slots, dtype=np.int64)
    slots, _ = index.assign_batch_ints(fresh, 0)
    assert len(set(slots.tolist())) == n_slots


@pytest.mark.parametrize("mode", ["unit", "weighted"])
def test_stream_dispatch_failure_releases_pins(monkeypatch, mode):
    import ratelimiter_tpu.storage.tpu as tpu_mod
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK", 128)
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK_MAX", 128)
    n_slots = 64
    st = TpuBatchedStorage(num_slots=n_slots)
    lid = st.register_limiter("tb", RateLimitConfig(
        max_permits=50, window_ms=60_000, refill_rate=5.0))
    eng = st.engine
    if mode == "unit":
        monkeypatch.setattr(
            eng, "tb_relay_counts_dispatch",
            _fail_after(eng.tb_relay_counts_dispatch, 1))
        monkeypatch.setattr(
            eng, "tb_relay_dispatch",
            _fail_after(eng.tb_relay_dispatch, 1))
        perms = None
    else:
        monkeypatch.setattr(
            eng, "tb_weighted_dispatch",
            _fail_after(eng.tb_weighted_dispatch, 1))
        perms = np.random.default_rng(1).integers(1, 9, 512).astype(np.int64)
    ids = np.random.default_rng(0).integers(0, 48, 512)
    with pytest.raises(RuntimeError, match="injected"):
        st.acquire_stream_ids("tb", lid, ids, perms)
    _assert_no_pin_leak(st, "tb", n_slots)
    st.close()


def test_stream_drain_failure_releases_pins(monkeypatch):
    """A fetch (drain) dying mid-pipeline: pins were already released at
    dispatch-enqueue, the exception propagates, storage stays usable."""
    import ratelimiter_tpu.storage.tpu as tpu_mod
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK", 128)
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK_MAX", 128)
    n_slots = 64
    st = TpuBatchedStorage(num_slots=n_slots)
    lid = st.register_limiter("tb", RateLimitConfig(
        max_permits=50, window_ms=60_000, refill_rate=5.0))
    eng = st.engine
    real = eng.tb_relay_counts_dispatch
    calls = {"n": 0}

    def poison_second(*a, **kw):
        calls["n"] += 1
        h = real(*a, **kw)
        return _PoisonFetch() if calls["n"] == 2 else h

    monkeypatch.setattr(eng, "tb_relay_counts_dispatch", poison_second)
    monkeypatch.setattr(eng, "tb_relay_dispatch", poison_second)
    ids = np.random.default_rng(0).integers(0, 48, 512)
    with pytest.raises(RuntimeError, match="injected fetch"):
        st.acquire_stream_ids("tb", lid, ids, None)
    _assert_no_pin_leak(st, "tb", n_slots)
    # Fully usable afterward: a clean stream pass decides everything.
    out = st.acquire_stream_ids("tb", lid, ids, None)
    assert out.shape == (512,)
    st.close()


def test_multi_lid_stream_failure_keeps_state_consistent(monkeypatch):
    """Multi-tenant digest stream dying on chunk 2: the chunks that DID
    dispatch persist (like the reference crashing after a Redis write),
    the failed chunk leaves no partial marks, and a rerun produces
    exactly the decisions a fresh storage makes after the same prefix."""
    import ratelimiter_tpu.storage.tpu as tpu_mod
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage
    from ratelimiter_tpu.engine.engine import DeviceEngine
    from ratelimiter_tpu.engine.state import LimiterTable

    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK", 128)
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK_MAX", 128)
    now = [7_000_000]

    def build():
        table = LimiterTable()
        lids = [table.register(RateLimitConfig(
            max_permits=5 + i, window_ms=60_000, refill_rate=2.0 + i))
            for i in range(4)]
        st = TpuBatchedStorage(
            engine=DeviceEngine(num_slots=256, table=table),
            clock_ms=lambda: now[0])
        return st, np.asarray(lids, dtype=np.int64)

    rng = np.random.default_rng(5)
    ids = rng.integers(0, 60, 384)
    lid_arr = rng.integers(0, 4, 384)

    st_a, lids_a = build()
    eng = st_a.engine
    for name in ("tb_relay_counts_resident_dispatch", "tb_relay_dispatch"):
        monkeypatch.setattr(eng, name, _fail_after(getattr(eng, name), 1))
    with pytest.raises(RuntimeError, match="injected"):
        st_a.acquire_stream_ids("tb", lids_a[lid_arr], ids, None)
    # Rerun the whole stream on the survivor.
    got = st_a.acquire_stream_ids("tb", lids_a[lid_arr], ids, None)

    # Fresh storage: apply the prefix that succeeded in A, then the rerun.
    st_b, lids_b = build()
    st_b.acquire_stream_ids("tb", lids_b[lid_arr[:128]], ids[:128], None)
    want = st_b.acquire_stream_ids("tb", lids_b[lid_arr], ids, None)
    np.testing.assert_array_equal(got, want)
    st_a.close()
    st_b.close()


def test_interleaved_scalar_and_stream_traffic():
    """Concurrent try_acquire traffic while stream calls run on the SAME
    storage (VERDICT r2 #9): no deadlock, and the per-key allow total
    across BOTH paths never exceeds the policy budget."""
    import threading

    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    now = [9_000_000]  # frozen clock: no refill during the test
    st = TpuBatchedStorage(num_slots=1 << 10, clock_ms=lambda: now[0])
    results = {}
    budgets = {}
    for algo, cfg in (
        ("tb", RateLimitConfig(max_permits=7, window_ms=600_000,
                               refill_rate=0.001)),
        ("sw", RateLimitConfig(max_permits=7, window_ms=600_000,
                               enable_local_cache=False)),
    ):
        lid = st.register_limiter(algo, cfg)
        budgets[algo] = cfg.max_permits
        rng = np.random.default_rng(11)
        scalar_allowed = []
        errs = []

        def scalar_worker(algo=algo, lid=lid):
            r = np.random.default_rng(threading.get_ident() % 1000)
            try:
                for i in range(60):
                    key = f"user-{int(r.integers(0, 40))}"
                    res = st.acquire(algo, lid, key, 1)
                    scalar_allowed.append((key, bool(res["allowed"])))
            except Exception as exc:  # noqa: BLE001
                errs.append(exc)

        threads = [threading.Thread(target=scalar_worker) for _ in range(4)]
        for t in threads:
            t.start()
        stream_out = []
        ids = rng.integers(0, 40, 2000)
        for _ in range(3):
            stream_out.append(
                (ids.copy(),
                 st.acquire_stream_ids(algo, lid, ids, None)))
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive(), "deadlock: scalar worker stuck"
        assert not errs, errs
        results[algo] = (scalar_allowed, stream_out)

    st.flush()
    for algo, (scalar_allowed, stream_out) in results.items():
        per_key: dict = {}
        for key, ok in scalar_allowed:
            per_key[key] = per_key.get(key, 0) + int(ok)
        for ids, out in stream_out:
            for k, ok in zip(ids, out):
                # int stream keys share the scalar string namespace only
                # if spelled identically; scalar used 'user-N', stream
                # used raw ints -> distinct keys, tracked separately.
                per_key[int(k)] = per_key.get(int(k), 0) + int(ok)
        over = {k: v for k, v in per_key.items() if v > budgets[algo]}
        assert not over, (algo, over)
    st.close()


def test_stream_failure_with_prefetched_assign_clears_evictions(monkeypatch):
    """An exception escaping while a PREFETCHED next-chunk assignment is
    outstanding must still clear that assignment's evicted slots (their
    index entries already point at new keys) and release its pins."""
    import ratelimiter_tpu.storage.tpu as tpu_mod
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK", 128)
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK_MAX", 128)
    n_slots = 64
    now = [8_000_000]
    st = TpuBatchedStorage(num_slots=n_slots, clock_ms=lambda: now[0])
    lid = st.register_limiter("tb", RateLimitConfig(
        max_permits=3, window_ms=60_000, refill_rate=0.001))
    eng = st.engine
    # Chunk 1 dispatch fails AFTER chunk 2's assignment was prefetched
    # (the prefetch is submitted before the drains run, and dispatch of
    # chunk 1 precedes it — so fail the SECOND dispatch: chunk 2's).
    # Only the digest dispatch is wrapped: the failing stream's chunks
    # (40 uniques / 128 requests) deterministically elect digest mode,
    # while the recovery stream below (uniform uniques) elects words.
    monkeypatch.setattr(eng, "tb_relay_counts_dispatch",
                        _fail_after(eng.tb_relay_counts_dispatch, 1))
    rng = np.random.default_rng(9)
    # 4 chunks of 128; each chunk's 40 uniques fit the 64-slot table but
    # later chunks evict earlier chunks' keys — so the PREFETCHED
    # assignment that is outstanding when chunk 2's dispatch dies has
    # performed evictions that only the abort path can clear.
    ids = np.concatenate([rng.integers(c * 40, c * 40 + 40, 128)
                          for c in range(4)]).astype(np.int64)
    with pytest.raises(RuntimeError, match="injected"):
        st.acquire_stream_ids("tb", lid, ids, None)
    _assert_no_pin_leak(st, "tb", n_slots)
    # Every key must see a clean budget for its slot: burn each key once
    # under a frozen clock; a slot with stale (unclear) state would have
    # less than the full budget.
    fresh = np.arange(20_000_000, 20_000_000 + n_slots, dtype=np.int64)
    for _ in range(3):
        out = st.acquire_stream_ids("tb", lid, fresh, None)
        assert bool(out.all()), "stale device state survived the abort"
    st.close()


# ---------------------------------------------------------------------------
# Capacity-exhaustion partial failure (ADVICE r3): the lanes that DID
# assign before the failing one applied evictions — those slots are
# remapped in the index, so their device state must be zeroed before the
# error propagates, or a later acquire of the newly mapped key reads the
# evicted key's stale counters.
# ---------------------------------------------------------------------------

def test_capacity_failure_clears_applied_evictions():
    from ratelimiter_tpu.engine.native_index import native_available

    if not native_available():
        pytest.skip("needs the native slot index")
    now = [1_000_000]
    st = TpuBatchedStorage(num_slots=8, clock_ms=lambda: now[0])
    lid = st.register_limiter("sw", RateLimitConfig(
        max_permits=12, window_ms=60_000))
    # Fill the table; key 7's slot accumulates count 10.
    st.acquire_many_ids("sw", lid, np.arange(8, dtype=np.int64),
                        np.ones(8, dtype=np.int64))
    st.acquire_many_ids("sw", lid, np.full(9, 7, dtype=np.int64),
                        np.ones(9, dtype=np.int64))
    index = st._index["sw"]
    pins = np.asarray([index.get((lid, k)) for k in range(7)],
                      dtype=np.int32)
    index.pin_batch(pins)
    try:
        # Lane 0 (key 100) evicts key 7's slot — the only unpinned one;
        # lane 1 (key 101) then finds no victim: capacity error.
        with pytest.raises(RuntimeError, match="capacity"):
            st.acquire_many_ids("sw", lid,
                                np.asarray([100, 101], dtype=np.int64),
                                np.ones(2, dtype=np.int64))
    finally:
        index.unpin_batch(pins)
    # Key 100 now maps to key 7's old slot.  Its device state must have
    # been CLEARED by the failure path: count 0 + 12 <= 12 allows; stale
    # count 10 would deny.
    out = st.acquire_many_ids("sw", lid, np.asarray([100], dtype=np.int64),
                              np.asarray([12], dtype=np.int64))
    assert bool(out["allowed"][0]), \
        "evicted slot kept stale state through a capacity failure"
    st.close()


def test_partitioned_partial_failure_surfaces_evictions():
    """One partition fails (-2), the sibling succeeded and evicted: the
    raised error must carry the sibling's eviction as a GLOBAL slot id in
    ``pending_clears``, and the sibling's held pins must be released."""
    from ratelimiter_tpu.engine.native_index import native_available

    if not native_available():
        pytest.skip("needs the native slot index")
    from ratelimiter_tpu.engine.partitioned import PartitionedSlotIndex
    from ratelimiter_tpu.parallel.sharded import shard_of_int_keys

    idx = PartitionedSlotIndex(8, 2)  # 4 slots per partition
    keys = np.arange(10_000, dtype=np.int64)
    parts = shard_of_int_keys(keys, 2)
    p0, p1 = keys[parts == 0], keys[parts == 1]
    fill = np.concatenate([p0[:4], p1[:4]])
    slots, ev = idx.assign_batch_ints(fill, 0)
    assert len(ev) == 0
    s_of = dict(zip(fill.tolist(), slots.tolist()))
    pin = np.asarray([s_of[int(k)] for k in p0[:4]]
                     + [s_of[int(k)] for k in p1[:3]], dtype=np.int32)
    idx.pin_batch(pin)
    victim = s_of[int(p1[3])]  # the one unpinned slot
    try:
        batch = np.asarray([int(p1[4]), int(p0[4])], dtype=np.int64)
        with pytest.raises(RuntimeError) as ei:
            idx.assign_batch_ints(batch, 0, hold_pins=True)
        pc = getattr(ei.value, "pending_clears", None)
        assert pc is not None and victim in [int(x) for x in pc], \
            "successful partition's eviction lost on partial failure"
    finally:
        idx.unpin_batch(pin)
    # No leaked pins: a full table of fresh keys assigns cleanly.
    fresh = np.concatenate([p0[10:14], p1[10:14]]).astype(np.int64)
    slots2, _ = idx.assign_batch_ints(fresh, 0)
    assert len(set(slots2.tolist())) == 8
    idx.close()


# ---------------------------------------------------------------------------
# Sharded stream fault injection (VERDICT r3 #5): a shard's assign or the
# shard_map'd dispatch dying mid-stream must release every shard's pins,
# surface applied evictions, leave no partial `out`, and keep the storage
# fully usable.
# ---------------------------------------------------------------------------

def _make_sharded_storage(slots_per_shard=32):
    from ratelimiter_tpu.engine.state import LimiterTable
    from ratelimiter_tpu.parallel import ShardedDeviceEngine

    table = LimiterTable()
    eng = ShardedDeviceEngine(slots_per_shard=slots_per_shard, table=table)
    st = TpuBatchedStorage(engine=eng)
    lid = st.register_limiter("tb", RateLimitConfig(
        max_permits=50, window_ms=60_000, refill_rate=5.0))
    return st, lid, eng


def _assert_no_sharded_pin_leak(storage, algo):
    """Every sub-index slot must be evictable again: filling each shard's
    sub-index with fresh keys raises iff a pin leaked there."""
    index = storage._index[algo]
    for s, sub in enumerate(index._sub):
        n = sub.num_slots
        fresh = np.arange(50_000_000 + s * n, 50_000_000 + (s + 1) * n,
                          dtype=np.int64)
        slots, _ = sub.assign_batch_ints(fresh, 0)
        assert len(set(slots.tolist())) == n, f"shard {s} leaked a pin"


def test_sharded_flat_stream_shard_assign_failure(monkeypatch):
    """One shard's C assign dying mid-super-batch (flat sharded path,
    weighted permits): raise, all shards' pins released, the successful
    shards' evictions cleared, storage decides cleanly afterward."""
    st, lid, eng = _make_sharded_storage()
    index = st._index["tb"]
    sub = index._sub[2]
    monkeypatch.setattr(sub, "assign_batch_ints",
                        _fail_after(sub.assign_batch_ints, 1,
                                    RuntimeError("injected shard assign")))
    rng = np.random.default_rng(0)
    # Keyspace sized so no super-batch can exhaust a 32-slot shard with
    # same-generation (eviction-protected) keys.
    ids = rng.integers(0, 150, 1024).astype(np.int64)
    perms = rng.integers(1, 9, 1024).astype(np.int64)
    with pytest.raises(RuntimeError, match="injected shard assign"):
        st.acquire_stream_ids("tb", lid, ids, perms, batch=128, subbatches=2)
    _assert_no_sharded_pin_leak(st, "tb")
    monkeypatch.undo()
    out = st.acquire_stream_ids("tb", lid, ids, perms, batch=128,
                                subbatches=2)
    assert out.shape == (1024,)
    st.close()


def test_sharded_relay_stream_dispatch_failure(monkeypatch):
    """A per-shard relay dispatch (r8 lanes) dying after its first call
    (unit permits): the stream raises, sibling lanes stop cleanly, pins
    are released on every shard, and the storage is usable afterward
    with a clean full-budget pass per key."""
    import ratelimiter_tpu.storage.tpu as tpu_mod

    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK", 128)
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK_MAX", 128)
    st, lid, eng = _make_sharded_storage()
    monkeypatch.setattr(
        eng, "relay_shard_dispatch",
        _fail_after(eng.relay_shard_dispatch, 1,
                    RuntimeError("injected sharded dispatch")))
    ids = np.random.default_rng(1).integers(0, 150, 512).astype(np.int64)
    with pytest.raises(RuntimeError, match="injected sharded dispatch"):
        st.acquire_stream_ids("tb", lid, ids, None)
    _assert_no_sharded_pin_leak(st, "tb")
    out = st.acquire_stream_ids("tb", lid, ids, None)
    assert out.shape == (512,)
    st.close()


def test_sharded_relay_shard_assign_failure_clears_and_releases(monkeypatch):
    """One shard's uniques assign dying mid-chunk in the sharded RELAY
    loop: the sibling shards' evictions (their slots are already
    remapped) must be cleared even though no dispatch happens, and every
    pin released."""
    import ratelimiter_tpu.storage.tpu as tpu_mod

    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK", 128)
    monkeypatch.setattr(tpu_mod, "_RELAY_CHUNK_MAX", 128)
    st, lid, eng = _make_sharded_storage(slots_per_shard=32)
    index = st._index["tb"]
    # Fill the whole table so the failing chunk's assigns must evict.
    fill = np.arange(9_000_000, 9_000_000 + 32 * eng.n_shards,
                     dtype=np.int64)
    st.acquire_stream_ids("tb", lid, fill, None)
    cleared: list = []
    real_clear = st._clear_slots
    monkeypatch.setattr(
        st, "_clear_slots",
        lambda algo, slots: (cleared.extend(slots),
                             real_clear(algo, slots))[1])
    # r8: sharded streams clear evictions per shard, in the lane's own
    # stream order — observe that choke point too (global slot ids).
    real_clear_shard = st._clear_shard
    monkeypatch.setattr(
        st, "_clear_shard",
        lambda algo, s, local: (cleared.extend(
            int(x) + s * eng.slots_per_shard for x in local),
            real_clear_shard(algo, s, local))[1])
    sub = index._sub[3]
    monkeypatch.setattr(sub, "assign_batch_ints_uniques",
                        _fail_after(sub.assign_batch_ints_uniques, 0,
                                    RuntimeError("injected uniques assign")))
    ids = np.random.default_rng(2).integers(20_000, 20_100, 256).astype(
        np.int64)
    with pytest.raises(RuntimeError, match="injected uniques assign"):
        st.acquire_stream_ids("tb", lid, ids, None)
    # Sibling shards assigned fresh keys over a full table: evictions
    # happened and must have been routed through the clear choke point.
    assert len(cleared) > 0, "successful shards' evictions were dropped"
    _assert_no_sharded_pin_leak(st, "tb")
    st.close()


# ---------------------------------------------------------------------------
# Retry passthrough contract (satellite): multi-dispatch batch/stream ops
# must NOT be retried — a replay re-charges already-committed requests.
# ---------------------------------------------------------------------------

class _CountingBackend:
    """Duck-typed backend that always fails, counting attempts per op."""

    supports_device_batching = True

    def __init__(self):
        self.attempts = {}

    def __getattr__(self, name):
        def op(*args, **kwargs):
            self.attempts[name] = self.attempts.get(name, 0) + 1
            raise StorageException(f"down ({name})")

        return op


def test_retry_covers_exactly_the_replay_safe_surface():
    from ratelimiter_tpu.storage.retry import (
        _PASSTHROUGH_OPS,
        REPLAY_SAFE_OPS,
        RetryingStorage,
    )

    inner = _CountingBackend()
    st = RetryingStorage(inner, RetryPolicy(max_retries=3,
                                            retry_delay_ms=0.01))
    for op in ("acquire_many", "acquire_many_ids", "acquire_stream_ids",
               "acquire_stream_strs"):
        assert op in _PASSTHROUGH_OPS
        with pytest.raises(StorageException):
            getattr(st, op)("sw", 0, [], [])
        assert inner.attempts[op] == 1, (
            f"{op} was replayed {inner.attempts[op]}x — it mutates state "
            "per super-batch and must pass through un-retried")
    for op in ("acquire", "available_many", "reset_key"):
        assert op in REPLAY_SAFE_OPS
        with pytest.raises(StorageException):
            getattr(st, op)("sw", 0, "k")
        assert inner.attempts[op] == 3, f"{op} should be retried to exhaustion"


def test_retry_policy_skips_overload_and_lifecycle_errors():
    """Shed/shutdown/breaker-open signals are deterministic local
    decisions: replaying them amplifies the condition they report."""
    from ratelimiter_tpu.engine.errors import OverloadedError, ShutdownError
    from ratelimiter_tpu.storage.errors import CircuitOpenError

    for exc in (OverloadedError("shed", reason="queue_full"),
                ShutdownError("closed"),
                CircuitOpenError("open")):
        calls = []

        def op():
            calls.append(1)
            raise exc

        with pytest.raises(type(exc)):
            RetryPolicy(max_retries=3, retry_delay_ms=0.01).execute(op)
        assert len(calls) == 1, f"{type(exc).__name__} must not be retried"


# ---------------------------------------------------------------------------
# consume_pending_clears double-clear protection (satellite): an eviction
# failure's pending_clears must be consumed exactly once even when the
# same exception propagates through nested handlers.
# ---------------------------------------------------------------------------

def test_consume_pending_clears_once_through_nested_handlers():
    from ratelimiter_tpu.engine.errors import (
        SlotCapacityError,
        consume_pending_clears,
    )

    pooled = []
    try:
        try:  # inner handler: consumes (with a shard offset) and re-raises
            raise SlotCapacityError("full", pending_clears=[2, 5])
        except SlotCapacityError as exc:
            pooled.extend(consume_pending_clears(exc, base=100))
            raise
    except SlotCapacityError as exc:  # outer handler: same raise, no clears
        pooled.extend(consume_pending_clears(exc, base=100))
        assert exc.pending_clears is None
    assert pooled == [102, 105]  # offset applied, exactly once


def test_consume_pending_clears_handles_absent_and_empty():
    from ratelimiter_tpu.engine.errors import (
        SlotCapacityError,
        consume_pending_clears,
    )

    assert consume_pending_clears(RuntimeError("no attr")) == []
    assert consume_pending_clears(
        SlotCapacityError("full", pending_clears=[])) == []
