"""Fault injection (storage/chaos.py) driving the documented failure
machinery: retry-with-backoff, fail-open, and metric accounting."""

import numpy as np
import pytest

from ratelimiter_tpu import RateLimitConfig
from ratelimiter_tpu.algorithms import SlidingWindowRateLimiter
from ratelimiter_tpu.metrics import MeterRegistry
from ratelimiter_tpu.storage import (
    FaultInjectingStorage,
    InMemoryStorage,
    StorageException,
    TpuBatchedStorage,
)
from ratelimiter_tpu.storage.errors import RetryPolicy


def test_forced_failures_then_recovery():
    chaos = FaultInjectingStorage(InMemoryStorage())
    chaos.fail_next(2)
    with pytest.raises(StorageException):
        chaos.increment_and_expire("k", 1000)
    with pytest.raises(StorageException):
        chaos.increment_and_expire("k", 1000)
    # Third call succeeds and state is consistent (failures left no trace).
    assert chaos.increment_and_expire("k", 1000) == 1
    assert chaos.injected_failures == 2


def test_retry_policy_survives_transient_faults():
    """RetryPolicy (the reference's 3-attempt linear-backoff analog) rides
    over injected transients."""
    chaos = FaultInjectingStorage(InMemoryStorage())
    retry = RetryPolicy(max_retries=3, retry_delay_ms=0.1)
    chaos.fail_next(2)  # two transients, third attempt lands
    value = retry.execute(lambda: chaos.increment_and_expire("k", 1000))
    assert value == 1
    # Exhaustion: more faults than attempts -> StorageException escapes.
    chaos.fail_next(3)
    with pytest.raises(StorageException):
        retry.execute(lambda: chaos.increment_and_expire("k", 1000))


def test_probabilistic_faults_are_deterministic_by_seed():
    a = FaultInjectingStorage(InMemoryStorage(), failure_rate=0.5, seed=7)
    b = FaultInjectingStorage(InMemoryStorage(), failure_rate=0.5, seed=7)

    def drive(s):
        outcomes = []
        for i in range(50):
            try:
                s.increment_and_expire(f"k{i}", 1000)
                outcomes.append(True)
            except StorageException:
                outcomes.append(False)
        return outcomes

    assert drive(a) == drive(b)
    assert 0 < a.injected_failures < 50


def test_limiter_fail_open_over_chaos_storage():
    """The service-documented fail-open policy: storage outage => allow.
    (The reference documents this and actually 500s; SURVEY §5.3.)
    StorageException surfaces from the limiter, which is exactly what
    service/app.py's _try_acquire converts into allow-and-count."""
    chaos = FaultInjectingStorage(InMemoryStorage())
    limiter = SlidingWindowRateLimiter(
        chaos,
        RateLimitConfig(max_permits=2, window_ms=1000,
                        enable_local_cache=False),
        MeterRegistry())
    assert limiter.try_acquire("u")
    chaos.fail_next(10)
    with pytest.raises(StorageException):
        limiter.try_acquire("u")


def test_chaos_wraps_device_storage_stream():
    """The wrapper composes with the TPU-batched backend: injected faults
    surface from the stream path, clean calls pass through unchanged."""
    clock = lambda: 12_000  # noqa: E731
    inner = TpuBatchedStorage(num_slots=64, clock_ms=clock)
    chaos = FaultInjectingStorage(inner)
    lid = chaos.register_limiter("tb", RateLimitConfig(
        max_permits=3, window_ms=1000, refill_rate=1.0))
    ids = np.zeros(5, dtype=np.int64)
    got = chaos.acquire_stream_ids("tb", lid, ids, None, batch=4, subbatches=1)
    assert got.tolist() == [True, True, True, False, False]
    chaos.fail_next(1)
    with pytest.raises(StorageException):
        chaos.acquire_stream_ids("tb", lid, ids, None, batch=4, subbatches=1)
    chaos.close()


def test_default_wiring_composes_retry_over_chaos():
    """build_app wires retry(chaos(storage)): transient faults are absorbed
    by the retry layer (the RedisRateLimitStorage.java:155-178 analog) and
    never reach the caller; only exhaustion escalates."""
    from ratelimiter_tpu.service.props import AppProperties
    from ratelimiter_tpu.service.wiring import build_app
    from ratelimiter_tpu.storage.retry import RetryingStorage

    props = AppProperties({
        "storage.backend": "memory",
        "chaos.failure_rate": "0.3",   # any nonzero rate arms the injector
        "storage.retry.max_retries": "3",
        "storage.retry.delay_ms": "0.1",
        "warmup.enabled": "false",
    })
    ctx = build_app(props)
    try:
        assert isinstance(ctx.storage, RetryingStorage)
        chaos = ctx.storage._inner
        assert isinstance(chaos, FaultInjectingStorage)
        chaos.failure_rate = 0.0  # deterministic: forced faults only

        # Two transients: absorbed (3 attempts) — the decision still lands.
        chaos.fail_next(2)
        assert ctx.limiters["auth"].try_acquire("bob")
        assert chaos.injected_failures >= 2

        # Exhaustion: three forced faults beat 3 attempts on ONE op.
        chaos.fail_next(3)
        with pytest.raises(StorageException):
            ctx.limiters["auth"].try_acquire("bob")
    finally:
        ctx.close()


def test_retry_exhaustion_reaches_fail_open_counter():
    """Service-level accounting: only retry exhaustion lands in the
    fail-open counter; absorbed transients don't."""
    from ratelimiter_tpu.service.app import make_server
    from ratelimiter_tpu.service.props import AppProperties
    from ratelimiter_tpu.service.wiring import build_app
    import json
    import urllib.request

    props = AppProperties({
        "storage.backend": "memory",
        "chaos.failure_rate": "0.0001",  # armed but ~quiet
        "storage.retry.max_retries": "2",
        "storage.retry.delay_ms": "0.1",
        "warmup.enabled": "false",
        "server.port": "0",
    })
    ctx = build_app(props)
    server = make_server(ctx)
    import threading

    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    port = server.server_address[1]
    chaos = ctx.storage._inner
    chaos.failure_rate = 0.0  # deterministic: forced faults only

    def hit():
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/data",
            headers={"X-User-ID": "carol"})
        with urllib.request.urlopen(req) as resp:
            return resp.status

    def metric():
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/actuator/metrics") as resp:
            data = json.loads(resp.read())
        return data["meters"].get("ratelimiter.failopen.allowed", 0)

    try:
        assert hit() == 200
        # One transient: retry absorbs it, no fail-open.
        chaos.fail_next(1)
        assert hit() == 200
        assert metric() == 0
        # Exhaustion (2 attempts, 2 faults): fail-open allows and counts.
        chaos.fail_next(2)
        assert hit() == 200
        assert metric() == 1
    finally:
        server.shutdown()
        ctx.close()


def test_retry_policy_skips_validation_errors():
    """Programming/validation errors are not transport faults: no retry, no
    StorageException conversion — they must never reach fail-open."""
    calls = []

    def op():
        calls.append(1)
        raise ValueError("bad arg")

    with pytest.raises(ValueError):
        RetryPolicy(max_retries=3, retry_delay_ms=0.1).execute(op)
    assert len(calls) == 1


def test_stream_ops_pass_through_retry_unreplayed():
    """Batch/stream decision ops mutate state per super-batch: a replay
    would re-charge already-committed requests, so the retry wrapper must
    NOT replay them — while single acquire (replay-safe, reference parity)
    is retried."""
    from ratelimiter_tpu.storage.retry import RetryingStorage

    clock = lambda: 20_000  # noqa: E731
    inner = TpuBatchedStorage(num_slots=64, clock_ms=clock)
    chaos = FaultInjectingStorage(inner)
    st = RetryingStorage(chaos, RetryPolicy(max_retries=3,
                                            retry_delay_ms=0.1))
    lid = st.register_limiter("tb", RateLimitConfig(
        max_permits=5, window_ms=1000, refill_rate=1.0))

    chaos.fail_next(1)
    with pytest.raises(StorageException):
        st.acquire_stream_ids("tb", lid, np.zeros(4, np.int64), None,
                              batch=4, subbatches=1)
    assert chaos.injected_failures == 1  # exactly one attempt — no replay

    chaos.fail_next(1)  # transient on the single-acquire path: absorbed
    out = st.acquire("tb", lid, "k", 1)
    assert out["allowed"]
    st.close()
