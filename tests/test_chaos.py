"""Fault injection (storage/chaos.py) driving the documented failure
machinery: retry-with-backoff, fail-open, and metric accounting."""

import numpy as np
import pytest

from ratelimiter_tpu import RateLimitConfig
from ratelimiter_tpu.algorithms import SlidingWindowRateLimiter
from ratelimiter_tpu.metrics import MeterRegistry
from ratelimiter_tpu.storage import (
    FaultInjectingStorage,
    InMemoryStorage,
    StorageException,
    TpuBatchedStorage,
)
from ratelimiter_tpu.storage.errors import RetryPolicy


def test_forced_failures_then_recovery():
    chaos = FaultInjectingStorage(InMemoryStorage())
    chaos.fail_next(2)
    with pytest.raises(StorageException):
        chaos.increment_and_expire("k", 1000)
    with pytest.raises(StorageException):
        chaos.increment_and_expire("k", 1000)
    # Third call succeeds and state is consistent (failures left no trace).
    assert chaos.increment_and_expire("k", 1000) == 1
    assert chaos.injected_failures == 2


def test_retry_policy_survives_transient_faults():
    """RetryPolicy (the reference's 3-attempt linear-backoff analog) rides
    over injected transients."""
    chaos = FaultInjectingStorage(InMemoryStorage())
    retry = RetryPolicy(max_retries=3, retry_delay_ms=0.1)
    chaos.fail_next(2)  # two transients, third attempt lands
    value = retry.execute(lambda: chaos.increment_and_expire("k", 1000))
    assert value == 1
    # Exhaustion: more faults than attempts -> StorageException escapes.
    chaos.fail_next(3)
    with pytest.raises(StorageException):
        retry.execute(lambda: chaos.increment_and_expire("k", 1000))


def test_probabilistic_faults_are_deterministic_by_seed():
    a = FaultInjectingStorage(InMemoryStorage(), failure_rate=0.5, seed=7)
    b = FaultInjectingStorage(InMemoryStorage(), failure_rate=0.5, seed=7)

    def drive(s):
        outcomes = []
        for i in range(50):
            try:
                s.increment_and_expire(f"k{i}", 1000)
                outcomes.append(True)
            except StorageException:
                outcomes.append(False)
        return outcomes

    assert drive(a) == drive(b)
    assert 0 < a.injected_failures < 50


def test_limiter_fail_open_over_chaos_storage():
    """The service-documented fail-open policy: storage outage => allow.
    (The reference documents this and actually 500s; SURVEY §5.3.)
    StorageException surfaces from the limiter, which is exactly what
    service/app.py's _try_acquire converts into allow-and-count."""
    chaos = FaultInjectingStorage(InMemoryStorage())
    limiter = SlidingWindowRateLimiter(
        chaos,
        RateLimitConfig(max_permits=2, window_ms=1000,
                        enable_local_cache=False),
        MeterRegistry())
    assert limiter.try_acquire("u")
    chaos.fail_next(10)
    with pytest.raises(StorageException):
        limiter.try_acquire("u")


def test_chaos_wraps_device_storage_stream():
    """The wrapper composes with the TPU-batched backend: injected faults
    surface from the stream path, clean calls pass through unchanged."""
    clock = lambda: 12_000  # noqa: E731
    inner = TpuBatchedStorage(num_slots=64, clock_ms=clock)
    chaos = FaultInjectingStorage(inner)
    lid = chaos.register_limiter("tb", RateLimitConfig(
        max_permits=3, window_ms=1000, refill_rate=1.0))
    ids = np.zeros(5, dtype=np.int64)
    got = chaos.acquire_stream_ids("tb", lid, ids, None, batch=4, subbatches=1)
    assert got.tolist() == [True, True, True, False, False]
    chaos.fail_next(1)
    with pytest.raises(StorageException):
        chaos.acquire_stream_ids("tb", lid, ids, None, batch=4, subbatches=1)
    chaos.close()
