"""Shard-aware replication & device-side delta extraction (PR 6).

Layers under test, bottom-up:

- the device-resident dirty-slot journal (engine/state.py:
  DeviceSlotJournal) marks every engine path and drains identically to
  the host journal;
- the journal election (replication/log.py) honors forcing overrides;
- standby hardening: stale/reordered delta frames are refused, never
  applied (rows must not regress), promotion refuses or serializes
  against racing dispatches;
- replicator backpressure: a stalled standby link bounds host memory
  and coalesces cuts (the ``ratelimiter.replication.coalesced`` metric);
- per-shard replication: each shard's stream converges its own flat
  standby bit for bit; a ship failure on one shard never stalls the
  others; one-shard-of-N failover is bit-identical to the oracle while
  survivors keep serving (the chaos drill);
- health surface: DEGRADED-shard state + fused-relay fallback info.
"""

import threading
import time

import numpy as np
import pytest

from ratelimiter_tpu import RateLimitConfig
from ratelimiter_tpu.engine.state import (
    DeviceSlotJournal,
    LimiterTable,
    SlotJournal,
)
from ratelimiter_tpu.metrics import MeterRegistry
from ratelimiter_tpu.parallel import ShardedDeviceEngine, make_mesh
from ratelimiter_tpu.replication import (
    InProcessSink,
    ReplicationLog,
    ReplicationStateError,
    Replicator,
    ShardFailoverRouter,
    ShardStandbySet,
    ShardedReplicationLog,
    ShardedReplicator,
    StandbyReceiver,
    engine_state_fingerprint,
)
from ratelimiter_tpu.storage import TpuBatchedStorage

T0 = 1_753_000_000_000


def make_sharded_primary(n_shards=4, slots_per_shard=128, clock=None):
    clock = clock if clock is not None else {"t": T0}
    engine = ShardedDeviceEngine(
        slots_per_shard=slots_per_shard, table=LimiterTable(),
        mesh=make_mesh(n_devices=n_shards))
    storage = TpuBatchedStorage(engine=engine, clock_ms=lambda: clock["t"])
    return clock, storage


# ---------------------------------------------------------------------------
# Device journal
# ---------------------------------------------------------------------------

def test_device_journal_parity_with_host():
    """Same marks in, same drain out — the two journals are drop-in."""
    host, dev = SlotJournal(64), DeviceSlotJournal(64)
    for j in (host, dev):
        j.mark("sw", [3, 5, 5, -1, 999])       # padding/out-of-range dropped
        j.mark("tb", np.array([7], dtype=np.int32))
        # relay words: slot in the high bits (rank_bits=10)
        words = (np.array([9, 12], dtype=np.uint64) << np.uint64(11))
        j.mark_words("tb", words.astype(np.uint32), 10)
        # sharded matrices: 2 shards x 32 local slots
        j.mark_matrix("sw", np.array([[1, -1], [4, 2]]), 32)
        j.mark_words_matrix(
            "sw", (np.array([[6], [0xFFFFFFFF >> 11]], dtype=np.uint64)
                   << np.uint64(11)).astype(np.uint32), 10, 32)
    assert host.pending() == dev.pending() > 0
    d_host, _, _ = host.drain()
    d_dev, oldest, was_all = dev.drain()
    assert oldest is not None and not was_all
    for algo in ("sw", "tb"):
        np.testing.assert_array_equal(sorted(d_host[algo].tolist()),
                                      sorted(d_dev[algo].tolist()))
    # drained: empty until new marks
    d2, oldest2, _ = dev.drain()
    assert d2 == {} and oldest2 is None
    dev.mark_all("tb")
    d3, _, was_all = dev.drain()
    assert was_all and len(d3["tb"]) == 64 and "sw" not in d3


def test_device_journal_accepts_device_arrays():
    import jax.numpy as jnp

    j = DeviceSlotJournal(32)
    j.mark("sw", jnp.asarray(np.array([1, 2, 31], dtype=np.int32)))
    d, _, _ = j.drain()
    assert sorted(d["sw"].tolist()) == [1, 2, 31]


def test_engine_paths_mark_device_journal():
    """Every storage decision path leaves its slots dirty in the DEVICE
    journal (mirror of the host-journal coverage test)."""
    clock = {"t": T0}
    storage = TpuBatchedStorage(num_slots=256, clock_ms=lambda: clock["t"])
    log = ReplicationLog(storage, journal_kind="device")
    assert log.journal_kind == "device"
    j = log.journal
    lid = storage.register_limiter("tb", RateLimitConfig(
        max_permits=50, window_ms=2000, refill_rate=10.0))
    lid_sw = storage.register_limiter("sw", RateLimitConfig(
        max_permits=20, window_ms=2000, enable_local_cache=False))
    storage.acquire_many("tb", [lid] * 4, ["a", "b", "c", "d"], [1] * 4)
    storage.acquire("sw", lid_sw, "z", 1)
    storage.flush()
    deltas, _, _ = j.drain()
    assert len(deltas["tb"]) >= 4 and len(deltas["sw"]) >= 1
    keys = np.asarray([1, 2, 3, 1, 2, 9, 9, 9], dtype=np.int64)
    storage.acquire_stream_ids("tb", lid, keys)                      # relay
    storage.acquire_stream_ids("tb", lid, keys,
                               permits=np.full(8, 2))                # weighted
    storage.flush()
    deltas, _, _ = j.drain()
    assert len(deltas["tb"]) >= 4
    storage.reset_key("tb", lid, "a")
    deltas, _, _ = j.drain()
    assert len(deltas["tb"]) >= 1
    storage.close()


def test_flat_replication_device_journal_converges():
    clock = {"t": T0}
    primary = TpuBatchedStorage(num_slots=512, clock_ms=lambda: clock["t"])
    standby = TpuBatchedStorage(num_slots=512, clock_ms=lambda: clock["t"])
    lid = primary.register_limiter("tb", RateLimitConfig(
        max_permits=100, window_ms=1000, refill_rate=50.0))
    log = ReplicationLog(primary, journal_kind="device")
    repl = Replicator(log, InProcessSink(StandbyReceiver(standby)))
    rng = np.random.default_rng(3)
    for _ in range(3):
        clock["t"] += 137
        primary.acquire_stream_ids("tb", lid,
                                   rng.integers(0, 300, size=2048))
        repl.ship_now()
    fp_p = engine_state_fingerprint(primary.engine)
    fp_s = engine_state_fingerprint(standby.engine)
    np.testing.assert_array_equal(fp_p["tb"], fp_s["tb"])
    primary.close()
    standby.close()


def test_journal_election_env_override(monkeypatch):
    from ratelimiter_tpu.replication.log import device_journal_elected

    monkeypatch.setenv("RATELIMITER_DEVICE_JOURNAL", "on")
    assert device_journal_elected() is True
    monkeypatch.setenv("RATELIMITER_DEVICE_JOURNAL", "off")
    assert device_journal_elected() is False


def test_log_engine_kind_guards():
    clock, sharded = make_sharded_primary()
    with pytest.raises(ValueError, match="sharded"):
        ReplicationLog(sharded)
    flat = TpuBatchedStorage(num_slots=128, clock_ms=lambda: clock["t"])
    with pytest.raises(ValueError, match="sharded engine"):
        ShardedReplicationLog(flat)
    sharded.close()
    flat.close()


# ---------------------------------------------------------------------------
# Standby hardening: reordering + promotion races
# ---------------------------------------------------------------------------

def test_standby_refuses_reordered_and_stale_frames():
    registry = MeterRegistry()
    clock = {"t": T0}
    primary = TpuBatchedStorage(num_slots=256, clock_ms=lambda: clock["t"])
    standby = TpuBatchedStorage(num_slots=256, clock_ms=lambda: clock["t"])
    lid = primary.register_limiter("tb", RateLimitConfig(
        max_permits=40, window_ms=1000, refill_rate=10.0))
    log = ReplicationLog(primary)
    receiver = StandbyReceiver(standby, registry=registry)

    def traffic():
        clock["t"] += 77
        primary.acquire_many("tb", [lid] * 8,
                             [f"g{i}" for i in range(8)], [1] * 8)

    traffic()
    epoch1 = log.cut()                      # full bootstrap
    traffic()
    epoch2 = log.cut()
    traffic()
    epoch3 = log.cut()
    for f in epoch1:
        receiver.apply(f)
    assert receiver.consistent
    for f in epoch3:                        # delivered ahead of epoch 2
        receiver.apply(f)
    assert not receiver.consistent          # gap observed
    fp_before = engine_state_fingerprint(standby.engine)
    for f in epoch2:                        # late/reordered: must be refused
        receiver.apply(f)
    fp_after = engine_state_fingerprint(standby.engine)
    # The stale frame's rows were NOT applied: epoch 3's newer rows
    # survive untouched.
    np.testing.assert_array_equal(fp_before["tb"], fp_after["tb"])
    assert receiver.reordered >= 1
    assert registry.scrape()["ratelimiter.replication.reordered"] >= 1.0
    assert not receiver.consistent
    with pytest.raises(ReplicationStateError):
        receiver.promote()

    # A full frame heals the stream; state converges; promotion serves.
    log.request_full()
    for f in log.cut():
        receiver.apply(f)
    assert receiver.consistent
    fp_p = engine_state_fingerprint(primary.engine)
    fp_s = engine_state_fingerprint(standby.engine)
    np.testing.assert_array_equal(fp_p["tb"], fp_s["tb"])
    receiver.promote()
    primary.close()
    standby.close()


def test_promotion_refuses_racing_dispatch(monkeypatch):
    """A decision racing promote_from_replica gets the typed retryable
    refusal, never a half-applied index."""
    from ratelimiter_tpu.engine import checkpoint as ckpt
    from ratelimiter_tpu.storage.errors import PromotionInProgressError

    clock = {"t": T0}
    primary = TpuBatchedStorage(num_slots=256, clock_ms=lambda: clock["t"])
    standby = TpuBatchedStorage(num_slots=256, clock_ms=lambda: clock["t"])
    lid = primary.register_limiter("tb", RateLimitConfig(
        max_permits=40, window_ms=1000, refill_rate=10.0))
    clock["t"] += 5
    primary.acquire_many("tb", [lid] * 4, list("abcd"), [1] * 4)
    log = ReplicationLog(primary)
    receiver = StandbyReceiver(standby)
    for f in log.cut():
        receiver.apply(f)

    in_restore = threading.Event()
    release = threading.Event()
    real_restore = ckpt.restore_slot_indexes

    def slow_restore(storage, dump):
        in_restore.set()
        assert release.wait(5.0)
        return real_restore(storage, dump)

    monkeypatch.setattr(ckpt, "restore_slot_indexes", slow_restore)
    promoted_box = {}
    t = threading.Thread(
        target=lambda: promoted_box.update(p=receiver.promote()),
        daemon=True)
    t.start()
    assert in_restore.wait(5.0)
    # Mid-promotion: every decision surface refuses with the typed error.
    with pytest.raises(PromotionInProgressError):
        standby.acquire("tb", lid, "x", 1)
    with pytest.raises(PromotionInProgressError):
        standby.acquire_many("tb", [lid], ["x"], [1])
    with pytest.raises(PromotionInProgressError):
        standby.acquire_many_ids("tb", lid, np.array([1]), np.array([1]))
    with pytest.raises(PromotionInProgressError):
        standby.acquire_stream_ids("tb", lid, np.array([1]))
    release.set()
    t.join(timeout=5.0)
    assert promoted_box["p"] is standby
    # After the window the promoted storage serves normally.
    out = standby.acquire_many("tb", [lid] * 2, ["a", "new"], [1, 1])
    assert len(out["allowed"]) == 2
    primary.close()
    standby.close()


def test_promotion_race_exactly_one_wins(monkeypatch):
    """Orchestrator auto-promotion vs a concurrent manual
    POST /actuator/replication/promote (both land on the same
    ``StandbyReceiver.promote``): exactly one wins, the loser gets the
    typed retryable ``PromotionInProgressError``, and the fencing state
    ends up consistent (one promotion recorded, storage serving)."""
    import concurrent.futures as cf

    from ratelimiter_tpu.engine import checkpoint as ckpt
    from ratelimiter_tpu.storage.errors import PromotionInProgressError

    clock = {"t": T0}
    primary = TpuBatchedStorage(num_slots=256, clock_ms=lambda: clock["t"])
    standby = TpuBatchedStorage(num_slots=256, clock_ms=lambda: clock["t"])
    lid = primary.register_limiter("tb", RateLimitConfig(
        max_permits=40, window_ms=1000, refill_rate=10.0))
    clock["t"] += 5
    primary.acquire_many("tb", [lid] * 4, list("abcd"), [1] * 4)
    registry = MeterRegistry()
    log = ReplicationLog(primary)
    receiver = StandbyReceiver(standby, registry=registry)
    for f in log.cut():
        receiver.apply(f)

    entered = threading.Event()
    release = threading.Event()
    real_restore = ckpt.restore_slot_indexes

    def slow_restore(storage, dump):
        entered.set()
        assert release.wait(5.0)
        return real_restore(storage, dump)

    monkeypatch.setattr(ckpt, "restore_slot_indexes", slow_restore)

    def promote():
        return receiver.promote()

    with cf.ThreadPoolExecutor(2) as pool:
        first = pool.submit(promote)
        assert entered.wait(5.0)
        # The second (the "manual" POST) races the in-flight one and
        # must lose with the typed error, NOT deadlock or double-run.
        second = pool.submit(promote)
        with pytest.raises(PromotionInProgressError):
            second.result(timeout=5.0)
        release.set()
        assert first.result(timeout=5.0) is standby
    # Exactly one promotion ran.
    assert registry.scrape()["ratelimiter.replication.failovers"] == 1.0
    assert receiver.promoted
    # A latecomer after the window is told the storage already serves.
    with pytest.raises(ReplicationStateError):
        receiver.promote()
    # The promoted storage serves normally (fencing state consistent:
    # nothing fenced IT — only the replaced primary gets fenced).
    out = standby.acquire_many("tb", [lid] * 2, ["a", "x"], [1, 1])
    assert len(out["allowed"]) == 2
    assert standby.fence_info()["epoch"] == 0
    primary.close()
    standby.close()


def test_promoted_standby_refuses_late_frames():
    """A zombie primary still shipping frames into a PROMOTED (now
    serving) standby must be refused — the replication-side twin of the
    dispatch fence."""
    clock = {"t": T0}
    primary = TpuBatchedStorage(num_slots=256, clock_ms=lambda: clock["t"])
    standby = TpuBatchedStorage(num_slots=256, clock_ms=lambda: clock["t"])
    lid = primary.register_limiter("tb", RateLimitConfig(
        max_permits=40, window_ms=1000, refill_rate=10.0))
    clock["t"] += 5
    primary.acquire_many("tb", [lid] * 4, list("abcd"), [1] * 4)
    log = ReplicationLog(primary)
    receiver = StandbyReceiver(standby)
    for f in log.cut():
        receiver.apply(f)
    receiver.promote()
    fp_before = engine_state_fingerprint(standby.engine)
    clock["t"] += 5
    primary.acquire_many("tb", [lid] * 4, list("abcd"), [1] * 4)
    late = log.cut()
    assert late
    with pytest.raises(ReplicationStateError, match="zombie"):
        receiver.apply(late[0])
    assert receiver.refused_after_promote == 1
    # The serving state was NOT overwritten by the zombie's rows.
    fp_after = engine_state_fingerprint(standby.engine)
    np.testing.assert_array_equal(fp_before["tb"], fp_after["tb"])
    primary.close()
    standby.close()


# ---------------------------------------------------------------------------
# Replicator backpressure
# ---------------------------------------------------------------------------

class GatedSink:
    """Blocks sends until released; then feeds an InProcessSink."""

    def __init__(self, receiver):
        self.inner = InProcessSink(receiver)
        self.gate = threading.Event()

    def send(self, data):
        assert self.gate.wait(30.0), "test gate never released"
        self.inner.send(data)


def test_replicator_backpressure_bounds_memory_and_coalesces():
    registry = MeterRegistry()
    clock = {"t": T0}
    primary = TpuBatchedStorage(num_slots=512, clock_ms=lambda: clock["t"])
    standby = TpuBatchedStorage(num_slots=512, clock_ms=lambda: clock["t"])
    lid = primary.register_limiter("tb", RateLimitConfig(
        max_permits=100, window_ms=1000, refill_rate=50.0))
    log = ReplicationLog(primary)
    sink = GatedSink(StandbyReceiver(standby))
    # Tiny byte bound: the FIRST queued epoch saturates it, so every
    # later cut must coalesce instead of growing the queue.
    repl = Replicator(log, sink, interval_ms=5.0, registry=registry,
                      max_queue_bytes=1024).start()
    rng = np.random.default_rng(5)
    deadline = time.monotonic() + 20.0
    while repl.coalesced < 3 and time.monotonic() < deadline:
        clock["t"] += 50
        primary.acquire_stream_ids("tb", lid, rng.integers(0, 400, 512))
        time.sleep(0.01)
    assert repl.coalesced >= 3, "stalled link never coalesced cuts"
    # Bounded: at most ONE epoch is in flight past the byte bound.
    assert repl.queue_bytes() <= 1024 + 8 * (1 << 20)
    assert registry.scrape()["ratelimiter.replication.coalesced"] >= 3.0
    # Heal the link: the stream drains and the standby converges.
    sink.gate.set()
    clock["t"] += 50
    primary.acquire_many("tb", [lid] * 4, list("wxyz"), [1] * 4)
    repl.stop(final_ship=True)
    fp_p = engine_state_fingerprint(primary.engine)
    fp_s = engine_state_fingerprint(standby.engine)
    np.testing.assert_array_equal(fp_p["tb"], fp_s["tb"])
    primary.close()
    standby.close()


# ---------------------------------------------------------------------------
# Per-shard replication
# ---------------------------------------------------------------------------

def test_sharded_replication_converges_each_shard():
    clock, primary = make_sharded_primary(n_shards=4, slots_per_shard=128)
    lid = primary.register_limiter("tb", RateLimitConfig(
        max_permits=100, window_ms=1000, refill_rate=50.0))
    log = ShardedReplicationLog(primary)
    mesh_set = ShardStandbySet(
        4, lambda: TpuBatchedStorage(num_slots=128,
                                     clock_ms=lambda: clock["t"]))
    repl = ShardedReplicator(log, mesh_set.in_process_sinks())
    rng = np.random.default_rng(7)
    for _ in range(3):
        clock["t"] += 137
        primary.acquire_stream_ids("tb", lid, rng.integers(0, 300, 2048))
        repl.ship_now()
    host_tb = np.asarray(primary.engine.tb_packed)  # [n_sh, sps, lanes]
    for q in range(4):
        fp_q = engine_state_fingerprint(mesh_set.storages[q].engine)
        np.testing.assert_array_equal(host_tb[q], fp_q["tb"])
    assert all(e >= 1 for e in log.epochs)
    primary.close()
    mesh_set.close()


def test_sharded_ship_failure_isolated_to_one_shard():
    clock, primary = make_sharded_primary(n_shards=4, slots_per_shard=128)
    lid = primary.register_limiter("tb", RateLimitConfig(
        max_permits=100, window_ms=1000, refill_rate=50.0))
    log = ShardedReplicationLog(primary)
    mesh_set = ShardStandbySet(
        4, lambda: TpuBatchedStorage(num_slots=128,
                                     clock_ms=lambda: clock["t"]))
    sinks = mesh_set.in_process_sinks()

    class FlakySink:
        def __init__(self, inner):
            self.inner = inner
            self.fail = False

        def send(self, data):
            if self.fail:
                raise ConnectionError("standby 1 unreachable")
            self.inner.send(data)

    sinks[1] = FlakySink(sinks[1])
    repl = ShardedReplicator(log, sinks)
    clock["t"] += 9
    primary.acquire_stream_ids(
        "tb", lid, np.arange(400, dtype=np.int64))
    sinks[1].fail = True
    repl.ship_now()  # shard 1 fails, the others ship
    assert repl.shard_errors[1] >= 1
    assert sum(repl.shard_errors) == repl.shard_errors[1]
    host_tb = np.asarray(primary.engine.tb_packed)
    for q in (0, 2, 3):
        fp_q = engine_state_fingerprint(mesh_set.storages[q].engine)
        np.testing.assert_array_equal(host_tb[q], fp_q["tb"])
    # Shard 1's standby is behind and inconsistent-on-gap; healing the
    # link re-baselines it with a full frame on the next cycle.
    sinks[1].fail = False
    clock["t"] += 9
    primary.acquire_stream_ids("tb", lid, np.arange(50, dtype=np.int64))
    repl.ship_now()
    host_tb = np.asarray(primary.engine.tb_packed)
    fp1 = engine_state_fingerprint(mesh_set.storages[1].engine)
    np.testing.assert_array_equal(host_tb[1], fp1["tb"])
    assert mesh_set.receivers[1].consistent
    primary.close()
    mesh_set.close()


def test_shard_failover_drill_fast():
    from ratelimiter_tpu.storage.chaos import shard_failover_drill

    registry = MeterRegistry()
    report = shard_failover_drill(
        n_shards=4, slots_per_shard=256, n_keys=64, waves=4,
        kill_after_wave=2, post_waves=2, stream_n=768, batch=24,
        registry=registry)
    assert report["mismatches"] == 0
    assert report["decisions"] > 1000
    assert report["loss_wave_decisions"] > 0    # the kill WAS mid-stream
    assert report["window_decisions"] > 0       # survivors kept serving
    assert report["window_denied"] > 0          # victim failed closed
    meters = registry.scrape()
    assert meters["ratelimiter.replication.failovers"] == 1.0
    assert meters["ratelimiter.replication.epoch_gap"] == 0.0


@pytest.mark.slow
def test_shard_failover_soak_slow():
    """Bigger drill with the ASYNC per-shard replicator running mid-soak
    (the production shape)."""
    from ratelimiter_tpu.storage.chaos import shard_failover_drill

    registry = MeterRegistry()
    report = shard_failover_drill(
        n_shards=8, slots_per_shard=512, n_keys=192, waves=8,
        kill_after_wave=6, post_waves=4, stream_n=4096, batch=64,
        registry=registry, background_interval_ms=20.0)
    assert report["mismatches"] == 0
    assert report["decisions"] > 10000
    assert registry.scrape()["ratelimiter.replication.failovers"] == 1.0


def test_wiring_sharded_primary_targets_over_tcp():
    """`replication.targets` wires one SocketSink per shard; status
    exposes per-shard epochs; each flat standby converges its shard."""
    from ratelimiter_tpu.replication import ReplicationServer
    from ratelimiter_tpu.service.props import AppProperties
    from ratelimiter_tpu.service.wiring import _maybe_replication

    clock, primary = make_sharded_primary(n_shards=2, slots_per_shard=128)
    registry = MeterRegistry()
    standbys = [TpuBatchedStorage(num_slots=128,
                                  clock_ms=lambda: clock["t"])
                for _ in range(2)]
    receivers = [StandbyReceiver(s) for s in standbys]
    servers = [ReplicationServer(r, host="127.0.0.1").start()
               for r in receivers]
    handle = _maybe_replication(primary, AppProperties({
        "replication.enabled": "true", "replication.role": "primary",
        "replication.targets": ",".join(
            f"127.0.0.1:{s.port}" for s in servers),
        "replication.interval_ms": "10000"}), registry)
    assert handle is not None and handle.role == "primary"
    try:
        lid = primary.register_limiter("tb", RateLimitConfig(
            max_permits=25, window_ms=1000, refill_rate=10.0))
        clock["t"] += 9
        primary.acquire_stream_ids("tb", lid,
                                   np.arange(100, dtype=np.int64))
        handle.replicator.ship_now()
        status = handle.status()
        assert status["epochs"] == [1, 1]
        assert set(status["shards"]) == {0, 1}
        host_tb = np.asarray(primary.engine.tb_packed)
        for q in (0, 1):
            fp = engine_state_fingerprint(standbys[q].engine)
            np.testing.assert_array_equal(host_tb[q], fp["tb"])
    finally:
        handle.close()
        for s in servers:
            s.stop()
        primary.close()
        for st in standbys:
            st.close()


# ---------------------------------------------------------------------------
# Health surface: DEGRADED-shard state + fused-relay fallback info
# ---------------------------------------------------------------------------

def test_router_health_degraded_not_down():
    from ratelimiter_tpu.service.app import health_payload
    from ratelimiter_tpu.service.props import AppProperties
    from ratelimiter_tpu.service.wiring import AppContext

    clock, primary = make_sharded_primary(n_shards=4, slots_per_shard=128)
    router = ShardFailoverRouter(primary)
    registry = MeterRegistry()
    ctx = AppContext(props=AppProperties({}), storage=router,
                     registry=registry, limiters={}, fail_open=True)
    payload = health_payload(ctx)
    assert payload["status"] == "UP"
    assert payload["shards"] == {str(q): "active" for q in range(4)}
    assert "relay_fused_live" in payload["pallas"]  # CPU: not live, stated
    assert payload["pallas"]["relay_fused_live"] is False

    router.fail_shard(2)
    payload = health_payload(ctx)
    assert payload["status"] == "DEGRADED"         # NOT DOWN
    assert payload["shards"]["2"] == "failed"
    # The fused-fallback gauge is exported on scrape.
    assert "ratelimiter.pallas.fused_fallback" in registry.scrape()
    router.close()


def test_breaker_status_surfaces_shard_health():
    from ratelimiter_tpu.storage.breaker import CircuitBreakerStorage

    clock, primary = make_sharded_primary(n_shards=4, slots_per_shard=128)
    router = ShardFailoverRouter(primary)
    breaker = CircuitBreakerStorage(router)
    router.fail_shard(1)
    status = breaker.status()
    assert status["degraded_shards"] == ["1"]
    assert status["shards"]["1"] == "failed"
    router.close()


def test_relay_fused_fallback_info():
    from ratelimiter_tpu.ops.pallas import relay_step

    info = relay_step.fallback_info()
    assert info["relay_fused_live"] is False       # CPU backend
    assert info["probe_failed"] in (False, True)
    assert info["reason"]
    # Simulate the real-hardware trap: a failed probe must be loudly
    # attributable (module state is restored after).
    saved = (relay_step._probe_ok, relay_step._fallback_reason,
             relay_step._warned)
    try:
        relay_step._probe_ok = False
        relay_step._note_fallback("probe mismatch (tb): test")
        info = relay_step.fallback_info()
        assert info["probe_failed"] is True
        assert "probe mismatch" in info["reason"]
    finally:
        (relay_step._probe_ok, relay_step._fallback_reason,
         relay_step._warned) = saved
