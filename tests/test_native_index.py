"""Native C++ slot index: semantic equivalence with the Python SlotIndex and
end-to-end use through the TPU storage (incl. the int-key fast path)."""

import random

import numpy as np
import pytest

from ratelimiter_tpu.engine.native_index import native_available

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native slot index unavailable (no g++?)")


def make_native(n):
    from ratelimiter_tpu.engine.native_index import NativeSlotIndex

    return NativeSlotIndex(n)


def test_scalar_parity_with_python_index():
    from ratelimiter_tpu.engine.slots import SlotIndex

    rng = random.Random(3)
    py, nat = SlotIndex(32), make_native(32)
    key_to_slot_py, key_to_slot_nat = {}, {}
    keys = [(rng.randrange(3), f"user{rng.randrange(60)}") for _ in range(500)]
    for i, key in enumerate(keys):
        op = rng.random()
        if op < 0.8:
            sp, _ = py.assign(key)
            sn, _ = nat.assign(key)
            key_to_slot_py[key], key_to_slot_nat[key] = sp, sn
        elif op < 0.9:
            assert (py.get(key) is None) == (nat.get(key) is None)
        else:
            rp, rn = py.remove(key), nat.remove(key)
            assert (rp is None) == (rn is None)
        assert len(py) == len(nat), f"step {i}"
    # Same keys resident (slot numbering may differ; membership must not).
    for key in set(keys):
        assert (py.get(key) is None) == (nat.get(key) is None), key


def test_batch_ints_identity_and_eviction():
    nat = make_native(16)
    slots, ev = nat.assign_batch_ints(np.arange(16), lid=0)
    assert len(set(slots.tolist())) == 16 and len(ev) == 0
    # Same keys again: identical slots, no evictions.
    slots2, ev2 = nat.assign_batch_ints(np.arange(16), lid=0)
    np.testing.assert_array_equal(slots, slots2)
    assert len(ev2) == 0
    # 8 new keys evict the 8 least-recent.
    slots3, ev3 = nat.assign_batch_ints(np.arange(100, 108), lid=0)
    assert len(ev3) == 8
    assert len(nat) == 16


def test_lid_isolation():
    nat = make_native(8)
    s1, _ = nat.assign((1, 42))
    s2, _ = nat.assign((2, 42))
    assert s1 != s2
    assert nat.get((1, 42)) == s1 and nat.get((2, 42)) == s2


def test_same_batch_oversubscription_raises():
    nat = make_native(4)
    with pytest.raises(RuntimeError):
        nat.assign_batch_ints(np.arange(10), lid=0)


def test_tpu_storage_int_key_fast_path_matches_oracle():
    from ratelimiter_tpu import RateLimitConfig
    from ratelimiter_tpu.algorithms import TokenBucketRateLimiter
    from ratelimiter_tpu.metrics import MeterRegistry
    from ratelimiter_tpu.semantics import TokenBucketOracle
    from ratelimiter_tpu.storage import TpuBatchedStorage

    T0 = 1_753_000_000_000

    class FakeClock:
        def __init__(self):
            self.t = T0

        def __call__(self):
            return self.t

    clock = FakeClock()
    storage = TpuBatchedStorage(num_slots=1024, max_delay_ms=0.1, clock_ms=clock)
    cfg = RateLimitConfig(max_permits=12, window_ms=1500, refill_rate=20.0)
    limiter = TokenBucketRateLimiter(storage, cfg, MeterRegistry(), clock_ms=clock)
    oracle = TokenBucketOracle(cfg)
    rng = np.random.default_rng(4)
    for step in range(25):
        clock.t += int(rng.integers(0, 500))
        n = int(rng.integers(1, 40))
        ids = rng.integers(0, 30, size=n)
        perms = rng.integers(1, 14, size=n)
        got = limiter.try_acquire_ids(ids, perms)
        for j in range(n):
            want = oracle.try_acquire(int(ids[j]), int(perms[j]), clock.t).allowed
            assert got[j] == want, (step, j)
    storage.close()


def test_fp_dump_restore_preserves_lru_and_survives_bad_input():
    """Fingerprint restore rebuilds the exact LRU recency order; an invalid
    or oversized dump refuses but leaves the index empty-and-usable."""
    import numpy as np
    import pytest

    from ratelimiter_tpu.engine.native_index import (
        NativeSlotIndex,
        native_available,
    )

    if not native_available():
        pytest.skip("no native index")
    ix = NativeSlotIndex(8)
    for k in range(8):
        ix.assign((1, k))
    ix.assign((1, 2))  # key 2 -> MRU; LRU victim is key 0
    h1, h2, slots = ix.dump_fp()

    ix2 = NativeSlotIndex(8)
    ix2.restore_fp(h1, h2, slots)
    # Same eviction order: assigning a NEW key must evict the dump's LRU
    # tail (last dump entry).  No get() here — get touches the LRU.
    ix2_lru_before = len(ix2)
    _, evicted = ix2.assign((1, 99))
    assert evicted == slots[-1] and len(ix2) == ix2_lru_before

    # Oversized dump refused; index stays usable.
    big = NativeSlotIndex(4)
    with pytest.raises(ValueError):
        big.restore_fp(h1, h2, slots)
    s, ev = big.assign((1, 7))
    assert s >= 0 and ev is None

    # Duplicate-slot dump refused; index stays usable.
    bad = NativeSlotIndex(8)
    dup = slots.copy()
    dup[1] = dup[0]
    with pytest.raises(ValueError):
        bad.restore_fp(h1, h2, dup)
    s, ev = bad.assign((1, 7))
    assert s >= 0 and ev is None


def test_fp_rebalance_import_preserves_recency_order():
    """import_keys of an fp export keeps the source's eviction order in the
    target (MRU-first dump is assigned in reverse)."""
    import numpy as np
    import pytest

    from ratelimiter_tpu.engine import checkpoint as ck
    from ratelimiter_tpu.engine.native_index import native_available
    from ratelimiter_tpu.storage import TpuBatchedStorage
    from ratelimiter_tpu import RateLimitConfig

    if not native_available():
        pytest.skip("no native index")
    clock = lambda: 95_000  # noqa: E731
    cfg = RateLimitConfig(max_permits=4, window_ms=60_000, refill_rate=0.001)
    src = TpuBatchedStorage(num_slots=8, clock_ms=clock)
    lid = src.register_limiter("tb", cfg)
    src.acquire_stream_ids("tb", lid, np.arange(8, dtype=np.int64), None,
                           batch=8, subbatches=1)
    src.acquire_stream_ids("tb", lid, np.asarray([0], dtype=np.int64), None,
                           batch=8, subbatches=1)  # key 0 -> MRU; LRU = key 1
    dump = ck.export_keys(src)
    src.close()

    dst = TpuBatchedStorage(num_slots=8, clock_ms=clock)
    dst.register_limiter("tb", cfg)
    ck.import_keys(dst, dump)
    index = dst._index["tb"]
    # Source LRU tail = last fp in the MRU-first dump; lookup_fps does not
    # touch the LRU (get would).
    fp = dump["algos"]["tb"]
    lru_victim_slot = int(index.lookup_fps(fp["h1"][-1:], fp["h2"][-1:])[0])
    _, evicted = index.assign((lid, 99))
    dst.close()
    assert evicted == lru_victim_slot


def test_batch_recency_is_first_occurrence_granular():
    """Documented contract: within ONE batch call, repeat hits of a key do
    not re-touch the LRU — recency among same-batch keys follows first
    occurrence.  Batch [A, B, A] therefore leaves B most-recent; a later
    eviction takes A's slot (not B's, as per-occurrence touching would)."""
    import numpy as np
    import pytest

    from ratelimiter_tpu.engine.native_index import (
        NativeSlotIndex,
        native_available,
    )

    if not native_available():
        pytest.skip("no native index")
    ix = NativeSlotIndex(2)
    slots, ev = ix.assign_batch_ints(np.asarray([7, 8, 7], dtype=np.int64), 1)
    assert slots[0] == slots[2] and len(ev) == 0
    # Table full; next NEW key evicts the batch's first-touched key (7).
    _, evicted = ix.assign((1, 9))
    assert evicted == slots[0]
    assert ix.get((1, 8)) is not None


@pytest.mark.parametrize("kind", ["native", "python"])
def test_remove_while_pinned_defers_free(kind):
    """ADVICE r2: an admin remove() racing a stream's assign->dispatch pin
    window must NOT hand the slot to a new key until the pin drops — and
    the reassignment must report the slot as its own eviction so the
    (possibly stale) device state is cleared before reuse."""
    if kind == "native":
        ix = make_native(2)
    else:
        from ratelimiter_tpu.engine.slots import SlotIndex

        ix = SlotIndex(2)
    s_a, _ = ix.assign((0, 1), hold_pin=True)  # stream holds the pin
    s_b, _ = ix.assign((0, 2))
    assert ix.remove((0, 1)) == s_a  # admin reset while pinned
    # Capacity is 2: key 3 must NOT receive the pinned slot s_a.
    s_c, ev_c = ix.assign((0, 3))
    assert s_c != s_a
    assert ev_c == s_b  # LRU eviction of the only unpinned entry
    # Pin drops (dispatch enqueued): the slot becomes reusable, but its
    # next assignment reports it as its own eviction (clear before use).
    ix.unpin_batch(np.asarray([s_a], dtype=np.int32))
    s_d, ev_d = ix.assign((0, 4))
    assert s_d == s_a and ev_d == s_a


@pytest.mark.parametrize("kind", ["native", "python"])
def test_remove_while_pinned_all_pinned_raises(kind):
    """With every slot pinned (one via remove-deferral), a new key's
    assignment must fail loudly, not hand out a pinned slot."""
    if kind == "native":
        ix = make_native(1)
    else:
        from ratelimiter_tpu.engine.slots import SlotIndex

        ix = SlotIndex(1)
    s_a, _ = ix.assign((0, 1), hold_pin=True)
    ix.remove((0, 1))
    with pytest.raises(RuntimeError):
        ix.assign((0, 2))
    ix.unpin_batch(np.asarray([s_a], dtype=np.int32))
    s_b, ev_b = ix.assign((0, 2))
    assert s_b == s_a and ev_b == s_a


def test_dirty_slot_repinned_is_skipped():
    """A dirty slot that was RE-pinned after listing (queued micro-batch
    request) must not be handed out until that pin also drops."""
    ix = make_native(2)
    s_a, _ = ix.assign((0, 1), hold_pin=True)
    s_b, _ = ix.assign((0, 2))
    ix.remove((0, 1))
    ix.unpin_batch(np.asarray([s_a], dtype=np.int32))  # s_a now dirty
    ix.pin_batch(np.asarray([s_a], dtype=np.int32))    # re-pinned
    s_c, ev_c = ix.assign((0, 3))
    assert s_c == s_b and ev_c == s_b  # LRU eviction, not the dirty slot
    ix.unpin_batch(np.asarray([s_a], dtype=np.int32))
    s_d, ev_d = ix.assign((0, 4))
    assert s_d == s_a and ev_d == s_a  # dirty handout clears first


def test_restore_defers_pinned_unmapped_slot():
    """restore_fp with a live pin on a slot absent from the dump: the slot
    must not reach the clean free list — it surfaces dirty at last unpin."""
    ix = make_native(2)
    s_a, _ = ix.assign((0, 1), hold_pin=True)  # pinned by an in-flight window
    s_b, _ = ix.assign((0, 2))
    h1, h2, slots = ix.dump_fp()
    keep = slots != s_a  # dump without the pinned slot's entry
    ix.restore_fp(h1[keep], h2[keep], slots[keep])
    # Only key 2 is mapped; the pinned slot must not be assigned clean.
    s_c, ev_c = ix.assign((0, 3))
    assert s_c != s_a
    ix.unpin_batch(np.asarray([s_a], dtype=np.int32))
    s_d, ev_d = ix.assign((0, 4))
    assert s_d == s_a and ev_d == s_a  # dirty: cleared before reuse


def test_restore_remaps_pinned_slot_cleanly():
    """restore_fp where the pinned slot IS in the dump: the mapping wins —
    the slot must never surface on the dirty list at unpin (two keys would
    share it)."""
    ix = make_native(2)
    s_a, _ = ix.assign((0, 1), hold_pin=True)
    ix.assign((0, 2))
    h1, h2, slots = ix.dump_fp()
    ix.restore_fp(h1, h2, slots)  # s_a re-mapped to key 1
    ix.unpin_batch(np.asarray([s_a], dtype=np.int32))
    assert ix.get((0, 1)) == s_a
    # Capacity full: a new key's assignment must EVICT (clearing state),
    # never receive s_a as a "free" slot while key 1 still maps to it.
    s_c, ev_c = ix.assign((0, 9))
    assert ev_c is not None and ev_c == s_c
    assert len(ix) == 2


def test_strpack_native_matches_numpy_packer():
    """The optional CPython-API string packer must produce byte-identical
    (buffer, offsets) to the numpy join packer — including empty keys,
    unicode, 300-char keys, and embedded NULs (where the join path takes
    its slow per-key fallback)."""
    import ratelimiter_tpu.engine.native_index as ni

    if ni._load_strpack() is None:
        pytest.skip("strpack unavailable (no Python headers/libpython)")
    cases = [
        ["hello", "", "wörld", "a" * 300, "nul\x00byte", "k123"],
        [f"user-{i}" for i in range(257)],
        [""],
    ]
    sp = ni._strpack
    for keys in cases:
        b1, o1 = ni._pack_str_keys(keys)
        ni._strpack, ni._strpack_failed = None, True
        try:
            b2, o2 = ni._pack_str_keys(keys)
        finally:
            ni._strpack, ni._strpack_failed = sp, False
        np.testing.assert_array_equal(o1, o2)
        np.testing.assert_array_equal(b1, b2)
    # Non-str items: the native packer declines and the fallback handles.
    b, o = ni._pack_str_keys(["a", b"raw-bytes", "c"])
    assert bytes(b) == b"araw-bytesc" and list(o) == [0, 1, 10, 11]


def test_strpack_rejects_size_drift():
    """rl_strlist_pack re-checks the list size and total bytes the
    buffers were allocated for (the GIL can drop between the sizing pass
    and the pack, so drift must be an error, never a heap overflow)."""
    import numpy as np

    from ratelimiter_tpu.engine import native_index as ni

    sp = ni._load_strpack()
    if sp is None:
        pytest.skip("strpack unavailable")
    keys = ["abc", "defg"]
    total = sp.rl_strlist_total(keys)
    assert total == 7
    buf = np.empty(total, dtype=np.uint8)
    offs = np.empty(3, dtype=np.int64)
    assert sp.rl_strlist_pack2(keys, buf.ctypes.data, offs.ctypes.data,
                              2, total) == 0
    assert bytes(buf) == b"abcdefg" and offs.tolist() == [0, 3, 7]
    # List "grew" after sizing -> error.
    assert sp.rl_strlist_pack2(keys, buf.ctypes.data, offs.ctypes.data,
                              1, total) == -1
    # Content outgrew the buffer -> error before any overflow.
    assert sp.rl_strlist_pack2(keys, buf.ctypes.data, offs.ctypes.data,
                              2, total - 1) == -1


def test_weighted_layout_matches_numpy_reference():
    """rl_weighted_layout/rl_weighted_decide vs the numpy layout they
    replace (storage/tpu.py fallback): identical sorted words, offsets,
    permit scatter, and decisions on random duplicate structures."""
    from ratelimiter_tpu.engine.native_index import (
        weighted_decide,
        weighted_layout,
    )

    if not native_available():
        pytest.skip("needs the native library")
    rng = np.random.default_rng(11)
    rb = 12
    for trial in range(20):
        n = int(rng.integers(1, 2000))
        keys = rng.integers(0, max(n // 3, 1), n)
        # Build uwords/uidx/rank the way the walk does: first-appearance
        # order, count field = segment size.
        uniq, uidx = np.unique(keys, return_inverse=True)
        first = np.sort(np.unique(uidx, return_index=True)[1])
        remap = np.empty(len(uniq), dtype=np.int64)
        remap[uidx[first]] = np.arange(len(uniq))
        uidx = remap[uidx].astype(np.int32)
        counts = np.bincount(uidx).astype(np.int64)
        rank = np.zeros(n, dtype=np.int32)
        seen: dict = {}
        for i, ui in enumerate(uidx):
            rank[i] = seen.get(ui, 0)
            seen[ui] = rank[i] + 1
        u = len(uniq)
        slots = rng.permutation(u).astype(np.uint32)
        uwords = ((slots << np.uint32(rb + 1))
                  | (counts.astype(np.uint32) << np.uint32(1)))
        perms = rng.integers(1, 200, n).astype(np.int64)
        r_max = int(counts.max())
        r_b = 2
        while r_b < r_max:
            r_b *= 2
        # numpy reference (the fallback path)
        order = np.argsort(-counts, kind="stable")
        spos_ref = np.empty(u, dtype=np.int64)
        spos_ref[order] = np.arange(u)
        hist = np.bincount(counts, minlength=r_b + 1)
        k_r = u - np.cumsum(hist[:r_b])
        roff_ref = np.zeros(r_b, dtype=np.int64)
        np.cumsum(k_r[:-1], out=roff_ref[1:])
        pos_ref = roff_ref[rank] + spos_ref[uidx]
        plen = n + u + 16
        pr_ref = np.zeros(plen, dtype=np.uint8)
        pr_ref[pos_ref] = perms
        uw_ref = uwords[order]
        # native
        uw_nat = np.full(u, 0xFFFFFFFF, dtype=np.uint32)
        spos_nat = np.empty(u, dtype=np.int32)
        roff_nat = np.empty(r_b, dtype=np.int64)
        pr_nat = np.zeros(plen, dtype=np.uint8)
        assert weighted_layout(np.ascontiguousarray(uwords), rb, uidx,
                               rank, perms, r_b, uw_nat, spos_nat,
                               roff_nat, pr_nat)
        np.testing.assert_array_equal(uw_nat, uw_ref, err_msg=str(trial))
        np.testing.assert_array_equal(spos_nat, spos_ref.astype(np.int32))
        np.testing.assert_array_equal(roff_nat, roff_ref)
        np.testing.assert_array_equal(pr_nat, pr_ref)
        # decide: random bitmask, both reconstructions agree
        bits = rng.integers(0, 256, (plen + 7) // 8).astype(np.uint8)
        flat = np.unpackbits(bits)
        want = flat[pos_ref].astype(bool)
        got = weighted_decide(bits, roff_nat, spos_nat, uidx, rank)
        np.testing.assert_array_equal(got, want)


def test_rebuild_words_into_matches_numpy():
    """rl_rebuild_words vs ops/relay.rebuild_words on random duplicate
    structures, including over-clamp segments."""
    from ratelimiter_tpu.engine.native_index import rebuild_words_into
    from ratelimiter_tpu.ops.relay import rebuild_words

    if not native_available():
        pytest.skip("needs the native library")
    rng = np.random.default_rng(13)
    for rb in (3, 7, 12):
        n = 5000
        keys = rng.integers(0, 600, n)
        uniq, uidx = np.unique(keys, return_inverse=True)
        first = np.sort(np.unique(uidx, return_index=True)[1])
        remap = np.empty(len(uniq), dtype=np.int64)
        remap[uidx[first]] = np.arange(len(uniq))
        uidx = remap[uidx].astype(np.int32)
        counts = np.bincount(uidx)
        rank = np.zeros(n, dtype=np.int32)
        seen: dict = {}
        for i, ui in enumerate(uidx):
            rank[i] = seen.get(ui, 0)
            seen[ui] = rank[i] + 1
        rmask = (1 << rb) - 1
        slots = rng.permutation(len(uniq)).astype(np.uint32)
        uwords = ((slots << np.uint32(rb + 1))
                  | (np.minimum(counts, rmask).astype(np.uint32)
                     << np.uint32(1)))
        want = rebuild_words(uwords, uidx, rank, rb)
        out = np.empty(n, dtype=np.uint32)
        assert rebuild_words_into(np.ascontiguousarray(uwords), uidx,
                                  rank, rb, out)
        np.testing.assert_array_equal(out, want, err_msg=f"rb={rb}")


def test_shard_route_matches_numpy_reference():
    """rl_shard_route / rl_shard_route2 vs the numpy reference
    (splitmix hash + stable argsort): identical shard ids, order,
    counts — and the fused gather emits exactly keys[order]."""
    import ratelimiter_tpu.engine.native_index as ni
    from ratelimiter_tpu.parallel.sharded import shard_of_int_keys

    rng = np.random.default_rng(21)
    for n_shards in (1, 2, 8):
        keys = rng.integers(-(1 << 40), 1 << 40, size=4096)
        want_shard = shard_of_int_keys(keys, n_shards)
        want_order = np.argsort(want_shard, kind="stable")
        want_counts = np.bincount(want_shard, minlength=n_shards)
        r = ni.shard_route(keys, n_shards)
        assert r is not None
        np.testing.assert_array_equal(r[0], want_shard)
        np.testing.assert_array_equal(r[1], want_order)
        np.testing.assert_array_equal(r[2], want_counts)
        r2 = ni.shard_route_gather(keys, n_shards)
        assert r2 is not None
        np.testing.assert_array_equal(r2[0], want_shard)
        np.testing.assert_array_equal(r2[1], want_order)
        np.testing.assert_array_equal(r2[2], want_counts)
        np.testing.assert_array_equal(r2[3], keys[want_order])


def test_route_hashes_gather_matches_numpy():
    import ratelimiter_tpu.engine.native_index as ni

    rng = np.random.default_rng(22)
    h1 = rng.integers(0, 1 << 63, size=4096).astype(np.uint64)
    h2 = rng.integers(0, 1 << 63, size=4096).astype(np.uint64)
    for n_shards in (2, 5):
        want_shard = (h1 % np.uint64(n_shards)).astype(np.int32)
        want_order = np.argsort(want_shard, kind="stable")
        s, o, c = ni.route_hashes(h1, n_shards)
        np.testing.assert_array_equal(s, want_shard)
        np.testing.assert_array_equal(o, want_order)
        s2, o2, c2, h1s, h2s = ni.route_hashes_gather(h1, h2, n_shards)
        np.testing.assert_array_equal(o2, want_order)
        np.testing.assert_array_equal(h1s, h1[want_order])
        np.testing.assert_array_equal(h2s, h2[want_order])


def test_str_fingerprint_python_mirror_and_shard_agreement():
    """fnv_fingerprint_h1 (the Python mirror shard_of_key routes
    strings with) must equal the native hashers' h1 — and therefore
    scalar and batched string traffic agree on every key's shard."""
    import ratelimiter_tpu.engine.native_index as ni
    from ratelimiter_tpu.parallel.sharded import shard_of_key

    keys = ["alice", "", "wörld", "x" * 300, "k42"]
    lid = 7
    fp = ni.hash_str_keys(keys, lid)
    assert fp is not None
    for i, k in enumerate(keys):
        assert ni.fnv_fingerprint_h1(k.encode(), lid) == int(fp[0][i])
        assert shard_of_key((lid, k), 8) == int(fp[0][i]) % 8


def test_fps_uniques_matches_bytes_uniques():
    """The fingerprint uniques walk (string fast path) must produce the
    exact structure the packed-bytes walk does, and interoperate with
    scalar lookups on the same keys."""
    import ratelimiter_tpu.engine.native_index as ni

    keys = ["a", "b", "a", "c", "b", "a"]
    lid, rb = 5, 8
    ix_fp, ix_by = make_native(16), make_native(16)
    fp = ni.hash_str_keys(keys, lid)
    uw1, ui1, rk1, ev1 = ix_fp.assign_batch_fps_uniques(
        fp[0].copy(), fp[1].copy(), rb)
    packed, offs = ni._pack_str_keys(keys)
    uw2 = np.empty(len(keys), dtype=np.uint32)
    ui2 = np.empty(len(keys), dtype=np.int32)
    rk2 = np.empty(len(keys), dtype=np.int32)
    ev2 = np.empty(len(keys), dtype=np.int32)
    u = ix_by._lib.rl_index_assign_bytes_uniques(
        ix_by._h, packed.ctypes.data, offs.ctypes.data, len(keys),
        lid, rb, uw2.ctypes.data, ui2.ctypes.data, rk2.ctypes.data,
        ev2.ctypes.data)
    np.testing.assert_array_equal(uw1, uw2[:u])
    np.testing.assert_array_equal(ui1, ui2)
    np.testing.assert_array_equal(rk1, rk2)
    # Interop: scalar gets resolve the fp-assigned keys.
    for k in set(keys):
        assert ix_fp.get((lid, k)) is not None


def test_relay_decide_pos_matches_two_pass():
    import ratelimiter_tpu.engine.native_index as ni

    rng = np.random.default_rng(23)
    for dt in (np.uint8, np.uint16):
        u, n = 300, 2000
        counts = rng.integers(0, 200, u).astype(dt)
        uidx = rng.integers(0, u, n).astype(np.int32)
        rank = rng.integers(0, 250, n).astype(np.int32)
        pos = rng.permutation(n).astype(np.int64)
        want = np.zeros(n, dtype=bool)
        got_dense = ni.relay_decide(counts, uidx, rank)
        want[pos] = got_dense
        out = np.zeros(n, dtype=bool)
        alw = ni.relay_decide_pos(counts, uidx, rank, pos, out)
        np.testing.assert_array_equal(out, want)
        assert alw == int(got_dense.sum())


def test_sharded_index_remove_while_pinned_defers_globally():
    """ShardedSlotIndex (satellite r6 #4): the global pin_batch /
    unpin_batch used by the stream's assign->dispatch window must defer
    a removed-while-pinned slot per SHARD — the slot is never handed to
    a new key until the global unpin, and its reassignment reports it
    as its own eviction."""
    from ratelimiter_tpu.parallel.sharded import ShardedSlotIndex

    ix = ShardedSlotIndex(slots_per_shard=2, n_shards=2)
    # Find two keys on the same shard so capacity pressure is local.
    shard_keys: dict = {}
    i = 0
    while len(shard_keys.get(0, [])) < 3:
        from ratelimiter_tpu.parallel.sharded import shard_of_key

        k = (0, f"key-{i}")
        if shard_of_key(k, 2) == 0:
            shard_keys.setdefault(0, []).append(k)
        i += 1
    k_a, k_b, k_c = shard_keys[0][:3]
    s_a, _ = ix.assign(k_a)
    ix.pin_batch(np.asarray([s_a], dtype=np.int32))  # stream window pin
    s_b, _ = ix.assign(k_b)
    assert ix.remove(k_a) == s_a  # admin remove while pinned
    s_c, ev_c = ix.assign(k_c)  # shard 0 full: must NOT take s_a
    assert s_c != s_a and ev_c == s_b
    ix.unpin_batch(np.asarray([s_a], dtype=np.int32))
    s_d, ev_d = ix.assign(k_b)  # next assignment reuses the dirty slot
    assert s_d == s_a and ev_d == s_a


def test_sharded_index_pins_under_concurrent_batched_assign_remove():
    """Concurrency soak (satellite r6 #4): global pins held across
    per-shard batched assigns must keep their slots stable while other
    threads churn the same shards with batched assigns and removes.
    Asserts the pinned keys' mappings never move while pinned and that
    all pins drain (everything evictable afterward)."""
    import threading

    from ratelimiter_tpu.parallel.sharded import ShardedSlotIndex

    ix = ShardedSlotIndex(slots_per_shard=64, n_shards=2)
    # Pin a handful of keys through the same path the streams use:
    # per-shard batched assign with hold_pins, then global bookkeeping.
    pinned_keys = np.arange(8, dtype=np.int64)
    held = []
    for s in range(2):
        from ratelimiter_tpu.parallel.sharded import shard_of_int_keys

        mine = pinned_keys[shard_of_int_keys(pinned_keys, 2) == s]
        if not len(mine):
            continue
        slots, _ = ix._sub[s].assign_batch_ints(mine, 3, hold_pins=True)
        held.append((s, mine, slots + np.int32(s * 64)))
    stop = threading.Event()
    errs: list = []

    def churn(seed):
        rng = np.random.default_rng(seed)
        try:
            while not stop.is_set():
                batch = rng.integers(100, 100_000, size=64)
                for s in range(2):
                    from ratelimiter_tpu.parallel.sharded import (
                        shard_of_int_keys,
                    )

                    mine = batch[shard_of_int_keys(batch, 2) == s]
                    if len(mine):
                        ix._sub[s].assign_batch_ints(mine, 3)
                for k in rng.integers(100, 100_000, size=8):
                    ix.remove((3, int(k)))
        except Exception as exc:  # noqa: BLE001 — surfaced below
            errs.append(exc)

    threads = [threading.Thread(target=churn, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    try:
        import time as _t

        deadline = _t.monotonic() + 1.5
        while _t.monotonic() < deadline:
            for s, mine, gslots in held:
                for k, g in zip(mine, gslots):
                    assert ix.get((3, int(k))) == int(g), \
                        "pinned slot moved under concurrent churn"
            _t.sleep(0.01)
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert not errs, errs
    # Release the pins through the sharded index's global unpin.
    for s, mine, gslots in held:
        ix.unpin_batch(np.ascontiguousarray(gslots, dtype=np.int32))
    # Everything is now evictable: a flood of fresh keys fully turns
    # over both shards without raising (no leaked pin refcounts).
    for k in range(200_000, 200_000 + 256):
        ix.assign((3, k))
    for s, mine, gslots in held:
        for k in mine:
            assert ix.get((3, int(k))) is None


def test_split_layout_c_numpy_parity():
    """rl_split_layout (C) must emit byte-identical planes, words, and
    remapped uidx to the numpy fallback on mixed singleton/multi
    chunks."""
    import unittest.mock as mock

    import numpy as np

    import ratelimiter_tpu.engine.native_index as ni

    lib = ni._load_library()
    if lib is None or not hasattr(lib, "rl_split_layout"):
        import pytest

        pytest.skip("rl_split_layout unavailable (stale .so?) — the "
                    "parity check would compare numpy against numpy")
    rng = np.random.default_rng(9)
    u, n, rb = 50_000, 140_000, 8
    counts = rng.integers(1, 5, u).astype(np.uint32)
    slots = rng.permutation(1 << 22)[:u].astype(np.uint32)
    uwords = (slots << np.uint32(rb + 1)) | (counts << np.uint32(1))
    uidx = rng.integers(0, u, n).astype(np.int32)
    s3c, mwc, u2c, nsc = ni.split_layout(uwords.copy(), rb, uidx.copy())
    with mock.patch.object(ni, "_load_library", lambda: None):
        s3n, mwn, u2n, nsn = ni.split_layout(uwords.copy(), rb,
                                             uidx.copy())
    assert nsc == nsn == int((counts == 1).sum())
    np.testing.assert_array_equal(s3c, s3n)
    np.testing.assert_array_equal(mwc, mwn)
    np.testing.assert_array_equal(u2c, u2n)
