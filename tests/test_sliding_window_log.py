"""Sliding-window-log limiter — the exact algorithm the reference declared
storage for but never built (quirk Q5); here the zset surface is load-bearing."""

import pytest

from ratelimiter_tpu import RateLimitConfig
from ratelimiter_tpu.algorithms import SlidingWindowLogRateLimiter
from ratelimiter_tpu.metrics import MeterRegistry
from ratelimiter_tpu.storage import InMemoryStorage, TpuBatchedStorage

T0 = 1_753_000_000_000


class FakeClock:
    def __init__(self, t=T0):
        self.t = t

    def __call__(self):
        return self.t


def make(max_permits=5, window_ms=1000, storage=None):
    clock = FakeClock()
    storage = storage or InMemoryStorage(clock_ms=clock)
    limiter = SlidingWindowLogRateLimiter(
        storage,
        RateLimitConfig(max_permits=max_permits, window_ms=window_ms,
                        enable_local_cache=False),
        MeterRegistry(), clock_ms=clock)
    return limiter, clock


def test_exact_window_boundary():
    limiter, clock = make(max_permits=3, window_ms=1000)
    for _ in range(3):
        assert limiter.try_acquire("u")
    assert not limiter.try_acquire("u")
    # Exactly window_ms later the oldest events age out — exact, no
    # two-bucket approximation.
    clock.t += 1000
    assert limiter.try_acquire("u")


def test_multi_permits_exact():
    limiter, clock = make(max_permits=5, window_ms=1000)
    assert limiter.try_acquire("u", 3)
    assert not limiter.try_acquire("u", 3)  # 3 + 3 > 5
    assert limiter.try_acquire("u", 2)
    assert limiter.get_available_permits("u") == 0
    clock.t += 1000
    assert limiter.get_available_permits("u") == 5


def test_gradual_expiry():
    limiter, clock = make(max_permits=4, window_ms=1000)
    for i in range(4):
        assert limiter.try_acquire("u")
        clock.t += 100
    # t=400: all 4 still live.
    assert not limiter.try_acquire("u")
    clock.t = T0 + 1000  # first event (at T0) ages out exactly now
    assert limiter.get_available_permits("u") == 1
    assert limiter.try_acquire("u")
    assert not limiter.try_acquire("u")


def test_reset_and_validation():
    limiter, clock = make(max_permits=2, window_ms=60_000)
    limiter.try_acquire("u")
    limiter.try_acquire("u")
    assert not limiter.try_acquire("u")
    limiter.reset("u")
    assert limiter.try_acquire("u")
    with pytest.raises(ValueError):
        limiter.try_acquire("u", 0)


def test_runs_on_tpu_storage_legacy_surface():
    # The log algorithm uses the generic zset contract, which the TPU
    # backend serves host-side — proving the full 10-method boundary works
    # there too.
    clock = FakeClock()
    storage = TpuBatchedStorage(num_slots=64, clock_ms=clock)
    limiter = SlidingWindowLogRateLimiter(
        storage, RateLimitConfig(max_permits=2, window_ms=1000),
        MeterRegistry(), clock_ms=clock)
    assert limiter.try_acquire("u")
    assert limiter.try_acquire("u")
    assert not limiter.try_acquire("u")
    clock.t += 1000
    assert limiter.try_acquire("u")
    storage.close()
