"""Oracle semantics tests.

Ports the scenarios of the reference's (disabled) unit test class
``SlidingWindowRateLimiterTest.java:27-199`` against the pure-Python oracle,
plus: quirk Q1/Q2 behaviors, PEXPIRE-accurate previous-window expiry, token
bucket refill/burst/TTL, and float-emulation differential property tests
backing the integer-arithmetic claims in ``semantics/oracle.py``.
"""

import random

import pytest

from ratelimiter_tpu import RateLimitConfig
from ratelimiter_tpu.semantics import SlidingWindowOracle, TokenBucketOracle

T0 = 1_753_000_000_000  # fixed epoch for determinism (aligned tests offset it)


def sw(max_permits=10, window_ms=60_000):
    return SlidingWindowOracle(
        RateLimitConfig(max_permits=max_permits, window_ms=window_ms,
                        enable_local_cache=False))


def tb(max_permits=50, window_ms=60_000, refill_rate=10.0):
    return TokenBucketOracle(
        RateLimitConfig(max_permits=max_permits, window_ms=window_ms,
                        refill_rate=refill_rate))


# ---------------------------------------------------------------------------
# Sliding window: reference test scenarios
# ---------------------------------------------------------------------------

def test_allows_requests_under_limit():
    # SlidingWindowRateLimiterTest.java:50-64
    o = sw(max_permits=10)
    now = (T0 // 60_000) * 60_000  # window-aligned: no prev-window bleed
    for i in range(10):
        assert o.try_acquire("user1", 1, now + i).allowed, f"request {i}"


def test_rejects_when_limit_reached_without_increment():
    # SlidingWindowRateLimiterTest.java:67-78 — at the limit, the request is
    # rejected pre-increment (no storage mutation).
    o = sw(max_permits=10)
    now = (T0 // 60_000) * 60_000
    for i in range(10):
        o.try_acquire("user1", 1, now + i)
    d = o.try_acquire("user1", 1, now + 50)
    assert not d.allowed and not d.mutated
    assert d.observed == 10


def test_multi_permit_acquire():
    # SlidingWindowRateLimiterTest.java:81-100
    o = sw(max_permits=10)
    now = (T0 // 60_000) * 60_000
    d = o.try_acquire("user1", 5, now)
    assert d.allowed
    # Quirk Q1: the counter rose by 1, not 5 — estimate is now 1.
    assert o.current_count("user1", now) == 1
    # permits=10 still passes the pre-check (1 + 10 > 10 -> reject).
    assert not o.try_acquire("user1", 10, now + 1).allowed


def test_available_permits():
    # SlidingWindowRateLimiterTest.java:103-111
    o = sw(max_permits=10)
    now = (T0 // 60_000) * 60_000
    assert o.get_available_permits("user1", now) == 10
    for i in range(3):
        o.try_acquire("user1", 1, now + i)
    assert o.get_available_permits("user1", now + 3) == 7


def test_reset_clears_both_windows():
    # SlidingWindowRateLimiterTest.java:114-122
    o = sw(max_permits=10, window_ms=1000)
    now = (T0 // 1000) * 1000 + 500
    # Populate previous window and current window.
    for i in range(4):
        o.try_acquire("user1", 1, now - 1000 + i)
    for i in range(4):
        o.try_acquire("user1", 1, now + i)
    assert o.current_count("user1", now + 10) > 0
    o.reset("user1", now + 10)
    assert o.current_count("user1", now + 10) == 0
    assert o.get_available_permits("user1", now + 10) == 10


def test_invalid_permits_raise():
    # SlidingWindowRateLimiterTest.java:125-132
    o = sw()
    with pytest.raises(ValueError):
        o.try_acquire("user1", 0, T0)
    with pytest.raises(ValueError):
        o.try_acquire("user1", -1, T0)


# ---------------------------------------------------------------------------
# Sliding window: weighting, rollover, expiry
# ---------------------------------------------------------------------------

def test_weighted_estimate_mid_window():
    # 100 req in window W; at 30s into W+1 the prev weight is 0.5.
    o = sw(max_permits=1000, window_ms=60_000)
    w0 = (T0 // 60_000) * 60_000
    # Increment late in the window so the bucket's TTL (last incr + window)
    # survives the reads below (PEXPIRE semantics).
    for i in range(100):
        assert o.try_acquire("u", 1, w0 + 59_000 + i).allowed
    mid = w0 + 60_000 + 30_000
    assert o.current_count("u", mid) == 50  # 100 * 0.5
    q3 = w0 + 60_000 + 45_000
    assert o.current_count("u", q3) == 25  # 100 * 0.25


def test_quirk_q2_count_then_reject():
    # Q2: the post-increment check uses the RAW current-bucket counter; a
    # request passing the pre-check can be counted then rejected when the raw
    # bucket alone exceeds max.  Construct: prev bleed keeps estimate low is
    # impossible (prev only adds); instead use multi-permits pre-check slack:
    # raw bucket == max via increments, then estimate < raw impossible...
    # The real Q2 trigger is concurrent interleaving in the reference; in
    # sequential semantics it triggers when est < raw count cannot happen, so
    # verify the guard equivalence instead: after max increments, the
    # pre-check always fires first.
    o = sw(max_permits=3, window_ms=60_000)
    w0 = (T0 // 60_000) * 60_000
    for i in range(3):
        assert o.try_acquire("u", 1, w0 + i).allowed
    d = o.try_acquire("u", 1, w0 + 10)
    assert not d.allowed and not d.mutated


def test_prev_window_pexpire_semantics():
    # The previous bucket vanishes `window` ms after its LAST increment —
    # not at the 2x-window boundary (RedisRateLimitStorage.java:38-49).
    o = sw(max_permits=1000, window_ms=1000)
    w0 = (T0 // 1000) * 1000
    # Last increment at w0+100 -> bucket expires at w0+1100.
    for i in range(10):
        o.try_acquire("u", 1, w0 + 91 + i)
    # At w0+1050 (in next window), prev bucket still alive: weight=0.95
    assert o.current_count("u", w0 + 1050) == int(10 * 0.95)
    # At w0+1100 the prev bucket is expired even though window math would
    # still weight it until w0+2000.
    assert o.current_count("u", w0 + 1100) == 0


def test_rollover_two_windows_clears_all():
    o = sw(max_permits=1000, window_ms=1000)
    w0 = (T0 // 1000) * 1000
    for i in range(5):
        o.try_acquire("u", 1, w0 + i)
    assert o.current_count("u", w0 + 2000) == 0


# ---------------------------------------------------------------------------
# Token bucket
# ---------------------------------------------------------------------------

def test_tb_initial_burst_and_deny():
    o = tb(max_permits=50, refill_rate=10.0)
    d = o.try_acquire("u", 50, T0)  # full burst allowed from a fresh bucket
    assert d.allowed and d.remaining_hint == 0
    assert not o.try_acquire("u", 1, T0).allowed  # drained


def test_tb_refill_rate():
    o = tb(max_permits=50, refill_rate=10.0)
    o.try_acquire("u", 50, T0)
    # 10 tokens/sec -> after 500 ms, 5 tokens.
    assert o.get_available_permits("u", T0 + 500) == 5
    assert o.try_acquire("u", 5, T0 + 500).allowed
    assert not o.try_acquire("u", 1, T0 + 500).allowed


def test_tb_cap_clipping():
    o = tb(max_permits=50, refill_rate=10.0)
    o.try_acquire("u", 10, T0)
    # After a long idle, tokens cap at capacity.
    assert o.get_available_permits("u", T0 + 3_600_000) == 50


def test_tb_permits_above_capacity_rejected_without_storage():
    o = tb(max_permits=50, refill_rate=10.0)
    d = o.try_acquire("u", 51, T0)
    assert not d.allowed and not d.mutated
    # Bucket untouched: still full.
    assert o.try_acquire("u", 50, T0 + 1).allowed


def test_tb_deny_does_not_refresh_ttl():
    # TTL (2x window) is refreshed only by the allow branch; a denied request
    # leaves the old deadline, after which the bucket re-inits to capacity.
    o = tb(max_permits=10, window_ms=1000, refill_rate=1.0)
    o.try_acquire("u", 10, T0)  # allow: deadline = T0 + 2000
    d = o.try_acquire("u", 5, T0 + 1000)  # deny (only 1 token): no refresh
    assert not d.allowed
    # At T0+2000 the bucket expired -> fresh full bucket.
    assert o.try_acquire("u", 10, T0 + 2000).allowed


def test_tb_deny_leaves_refill_idempotent():
    # Denies don't write back, but refill recomputation is observationally
    # identical (associativity in exact fp arithmetic).
    o1 = tb(max_permits=50, refill_rate=7.3)
    o2 = tb(max_permits=50, refill_rate=7.3)
    o1.try_acquire("u", 50, T0)
    o2.try_acquire("u", 50, T0)
    # o1 issues intermediate denied probes; o2 doesn't.
    for dt in (100, 250, 333):
        o1.try_acquire("u", 50, T0 + dt)
    for dt in (1000, 2000, 5000):
        a1 = o1.try_acquire("u", 9, T0 + dt)
        a2 = o2.try_acquire("u", 9, T0 + dt)
        assert (a1.allowed, a1.remaining_hint) == (a2.allowed, a2.remaining_hint)


def test_tb_reset():
    o = tb(max_permits=50, refill_rate=10.0)
    o.try_acquire("u", 50, T0)
    o.reset("u", T0)
    assert o.try_acquire("u", 50, T0 + 1).allowed


def test_tb_invalid_permits():
    o = tb()
    with pytest.raises(ValueError):
        o.try_acquire("u", 0, T0)


def test_tb_requires_refill_rate():
    with pytest.raises(ValueError):
        TokenBucketOracle(RateLimitConfig(max_permits=10, window_ms=1000))


# ---------------------------------------------------------------------------
# Float-emulation differential property tests
# ---------------------------------------------------------------------------

def _java_estimate(prev: int, curr: int, now: int, win: int) -> int:
    """(long)(prev * (1.0 - (now % win)/win) + curr) — the Java double math
    (SlidingWindowRateLimiter.java:170-174)."""
    pct = float(now % win) / float(win)
    return int(prev * (1.0 - pct) + curr)


def test_sw_integer_estimate_matches_java_double_math():
    rng = random.Random(42)
    mismatch = 0
    for _ in range(200_000):
        win = rng.choice([1000, 60_000, 3_600_000])
        prev = rng.randrange(0, 100_000)
        curr = rng.randrange(0, 100_000)
        now = T0 + rng.randrange(0, 10 * win)
        rem = now % win
        ours = curr + (prev * (win - rem)) // win
        theirs = _java_estimate(prev, curr, now, win)
        if ours != theirs:
            mismatch += 1
            # Every divergence must be the documented boundary: the exact
            # weighted product is an integer and the double rounds just
            # below it, so Java truncates one lower than the exact floor.
            assert (prev * (win - rem)) % win == 0, (prev, rem, win)
            assert ours == theirs + 1, (ours, theirs)
    assert mismatch / 200_000 < 1e-4


class _LuaTokenBucket:
    """Double-arithmetic emulation of the Lua script
    (TokenBucketRateLimiter.java:38-68)."""

    def __init__(self, capacity: float, refill_per_sec: float, window_ms: int):
        self.capacity = float(capacity)
        self.rate_ms = refill_per_sec / 1000.0
        self.window_ms = window_ms
        self.state = None  # (tokens: float, last_refill: int, deadline: int)

    def try_acquire(self, permits: int, now: int) -> bool:
        if permits > self.capacity:
            return False
        if self.state is None or now >= self.state[2]:
            tokens, last = self.capacity, now
        else:
            tokens, last, _ = self.state
        tokens = min(self.capacity, tokens + (now - last) * self.rate_ms)
        if tokens >= permits:
            tokens -= permits
            self.state = (tokens, now, now + 2 * self.window_ms)
            return True
        return False


class _ExactTokenBucket:
    """Exact rational-arithmetic token bucket (the mathematical semantics)."""

    def __init__(self, capacity: int, refill_per_sec, window_ms: int):
        from fractions import Fraction

        self.capacity = Fraction(capacity)
        self.rate_ms = Fraction(refill_per_sec) / 1000
        self.window_ms = window_ms
        self.state = None

    def try_acquire(self, permits: int, now: int) -> bool:
        if permits > self.capacity:
            return False
        if self.state is None or now >= self.state[2]:
            tokens, last = self.capacity, now
        else:
            tokens, last, _ = self.state
        tokens = min(self.capacity, tokens + (now - last) * self.rate_ms)
        if tokens >= permits:
            self.state = (tokens - permits, now, now + 2 * self.window_ms)
            return True
        return False


def test_tb_fixed_point_is_exact_rational_semantics():
    """For rates of the form k/2**20 (all integral and most practical rates)
    the fixed-point arithmetic is EXACTLY the rational semantics — zero
    divergence over long adversarial histories."""
    rng = random.Random(7)
    total = agree = 0
    for trial in range(200):
        cap = rng.choice([10, 50, 1000])
        rate = rng.choice([1.0, 10.0, 97.5, 1000.0])
        win = 60_000
        ours = tb(max_permits=cap, window_ms=win, refill_rate=rate)
        exact = _ExactTokenBucket(cap, rate, win)
        now = T0
        for _ in range(300):
            now += rng.randrange(0, 500)
            p = rng.randrange(1, cap + 1)
            total += 1
            agree += ours.try_acquire("k", p, now).allowed == exact.try_acquire(p, now)
    assert agree == total, f"{agree}/{total}"


def test_tb_fixed_point_matches_lua_double_math():
    """Against the Lua double emulation, disagreements are the double's OWN
    rounding error at knife-edge boundaries (e.g. 0.01 tokens/ms is not
    binary-representable) and compound within a history once reached; demand
    near-total statistical agreement."""
    rng = random.Random(7)
    total = agree = 0
    for trial in range(300):
        cap = rng.choice([10, 50, 1000])
        rate = rng.choice([1.0, 10.0, 97.5, 1000.0])
        win = 60_000
        ours = tb(max_permits=cap, window_ms=win, refill_rate=rate)
        lua = _LuaTokenBucket(cap, rate, win)
        now = T0
        for _ in range(300):
            now += rng.randrange(0, 500)
            p = rng.randrange(1, cap + 1)
            total += 1
            agree += ours.try_acquire("k", p, now).allowed == lua.try_acquire(p, now)
    assert agree / total > 0.998, f"{agree}/{total}"
