"""Per-key export/import (engine/checkpoint.py): geometry-free rebalance.

Checkpoints restore 1:1 into the same geometry; a rebalance exports live
(key, state) pairs and imports them into a target of ANY geometry — more
slots, different shard count, flat <-> sharded. Decisions must continue
exactly where the source left off.
"""

import numpy as np

from ratelimiter_tpu import RateLimitConfig
from ratelimiter_tpu.engine import checkpoint as ck
from ratelimiter_tpu.storage import TpuBatchedStorage


def _consume(storage, lid, key_ids, permits):
    return storage.acquire_stream_ids(
        "tb", lid, np.asarray(key_ids, dtype=np.int64),
        np.asarray(permits, dtype=np.int64), batch=16, subbatches=1)


def test_rebalance_flat_to_larger_flat():
    clock = lambda: 21_000  # noqa: E731
    cfg = RateLimitConfig(max_permits=5, window_ms=60_000, refill_rate=0.001)

    src = TpuBatchedStorage(num_slots=64, clock_ms=clock, checkpointable=True)
    lid = src.register_limiter("tb", cfg)
    # Drain keys 0..9 fully, key 10 partially.
    _consume(src, lid, list(range(10)) * 5 + [10], [1] * 51)
    dump = ck.export_keys(src)
    src.close()

    dst = TpuBatchedStorage(num_slots=1024, clock_ms=clock,
                            checkpointable=True)
    lid2 = dst.register_limiter("tb", cfg)
    assert lid2 == lid
    ck.import_keys(dst, dump)
    # Drained keys stay drained; the partial key has exactly 4 left.
    got = _consume(dst, lid2, list(range(10)), [1] * 10)
    assert not got.any()
    got = _consume(dst, lid2, [10] * 5, [1] * 5)
    assert got.tolist() == [True, True, True, True, False]
    dst.close()


def test_rebalance_flat_to_sharded():
    import jax

    if jax.device_count() < 2:
        import pytest

        pytest.skip("needs a multi-device mesh")
    from ratelimiter_tpu.engine.state import LimiterTable
    from ratelimiter_tpu.parallel import ShardedDeviceEngine, make_mesh

    clock = lambda: 31_000  # noqa: E731
    cfg = RateLimitConfig(max_permits=2, window_ms=60_000, refill_rate=0.001)

    src = TpuBatchedStorage(num_slots=64, clock_ms=clock, checkpointable=True)
    lid = src.register_limiter("tb", cfg)
    _consume(src, lid, [7, 7, 8], [1, 1, 1])  # key 7 drained, key 8 at 1/2
    dump = ck.export_keys(src)
    src.close()

    engine = ShardedDeviceEngine(slots_per_shard=32, table=LimiterTable(),
                                 mesh=make_mesh())
    dst = TpuBatchedStorage(engine=engine, clock_ms=clock,
                            checkpointable=True)
    lid2 = dst.register_limiter("tb", cfg)
    assert lid2 == lid
    ck.import_keys(dst, dump)
    got = _consume(dst, lid2, [7, 8, 8], [1, 1, 1])
    assert got.tolist() == [False, True, False]
    dst.close()


def test_rebalance_refuses_limiter_mismatch():
    import pytest

    clock = lambda: 51_000  # noqa: E731
    src = TpuBatchedStorage(num_slots=64, clock_ms=clock, checkpointable=True)
    lid = src.register_limiter("tb", RateLimitConfig(
        max_permits=5, window_ms=60_000, refill_rate=1.0))
    _consume(src, lid, [1], [1])
    dump = ck.export_keys(src)
    src.close()

    dst = TpuBatchedStorage(num_slots=64, clock_ms=clock, checkpointable=True)
    dst.register_limiter("tb", RateLimitConfig(
        max_permits=99, window_ms=60_000, refill_rate=1.0))  # different policy
    with pytest.raises(ValueError, match="mismatch"):
        ck.import_keys(dst, dump)
    dst.close()


def test_rebalance_refuses_undersized_target():
    import pytest

    clock = lambda: 41_000  # noqa: E731
    cfg = RateLimitConfig(max_permits=2, window_ms=60_000, refill_rate=0.001)
    src = TpuBatchedStorage(num_slots=64, clock_ms=clock, checkpointable=True)
    lid = src.register_limiter("tb", cfg)
    _consume(src, lid, list(range(40)), [1] * 40)
    dump = ck.export_keys(src)
    src.close()

    dst = TpuBatchedStorage(num_slots=8, clock_ms=clock, checkpointable=True)
    dst.register_limiter("tb", cfg)
    with pytest.raises(ValueError, match="too small"):
        ck.import_keys(dst, dump)
    dst.close()


def test_rebalance_refuses_overfull_shard():
    """Capacity is per shard, not fungible: a target whose GLOBAL free count
    covers the export must still refuse when one shard overflows."""
    import jax

    if jax.device_count() < 2:
        pytest.skip("needs a multi-device mesh")
    import pytest

    from ratelimiter_tpu.engine.state import LimiterTable
    from ratelimiter_tpu.parallel import ShardedDeviceEngine, make_mesh
    from ratelimiter_tpu.parallel.sharded import shard_of_key

    clock = lambda: 61_000  # noqa: E731
    cfg = RateLimitConfig(max_permits=9, window_ms=60_000, refill_rate=0.001)
    engine = ShardedDeviceEngine(slots_per_shard=4, table=LimiterTable(),
                                 mesh=make_mesh())
    n_shards = engine.n_shards

    src = TpuBatchedStorage(num_slots=64, clock_ms=clock, checkpointable=True)
    lid = src.register_limiter("tb", cfg)
    # More keys on ONE target shard than its 4 local slots, while total
    # stays far under the target's global capacity.
    hot = [k for k in range(1000)
           if shard_of_key((lid, k), n_shards) == 0][:6]
    assert len(hot) == 6
    _consume(src, lid, hot, [1] * len(hot))
    dump = ck.export_keys(src)
    src.close()

    dst = TpuBatchedStorage(engine=engine, clock_ms=clock, checkpointable=True)
    dst.register_limiter("tb", cfg)
    with pytest.raises(ValueError, match="shard 0 is too small"):
        ck.import_keys(dst, dump)
    # The refusal must be up-front: nothing was assigned in the target.
    assert len(dst._index["tb"]) == 0
    dst.close()
