import jax
jax.config.update("jax_enable_x64", True)
import time, numpy as np, jax.numpy as jnp

B = 1 << 20
N = 1 << 21
R = 20
rng = np.random.default_rng(0)
slots = jnp.asarray(rng.integers(0, N, B).astype(np.int32))
state64 = jnp.zeros((N,), jnp.int64)
staterow = jnp.zeros((N, 4), jnp.int64)

def timed(name, fn, *args):
    out = fn(*args); np.asarray(jax.tree_util.tree_leaves(out)[0])
    t0 = time.perf_counter()
    out = fn(*args); np.asarray(jax.tree_util.tree_leaves(out)[0])
    dt = time.perf_counter() - t0
    print(f"{name:46s} {(dt-0.11)/R*1e3:8.1f} ms/iter", flush=True)

@jax.jit
def g3s3(st, idx):
    # 3 separate gathers + 3 scatters (current TB layout, i64)
    def body(i, st):
        a, b, c = st
        va, vb, vc = a[idx] + 1, b[idx] + 1, c[idx] + 1
        return (a.at[idx].set(va), b.at[idx].set(vb), c.at[idx].set(vc))
    return jax.lax.fori_loop(0, R, body, (st, st + 1, st + 2))

@jax.jit
def g1s1_rows(st, idx):
    # 1 row gather + 1 row scatter (packed [N,4] layout, i64)
    def body(i, st):
        rows = st[idx] + 1
        return st.at[idx].set(rows)
    return jax.lax.fori_loop(0, R, body, st)

@jax.jit
def sort_take_unsort(x):
    def body(i, x):
        order = jnp.argsort(x, stable=True)
        s = x[order]
        back = jnp.zeros_like(s).at[order].set(s)
        return back
    return jax.lax.fori_loop(0, R, body, x)

timed("3x gather + 3x scatter i64[2M] @1M", g3s3, state64, slots)
timed("1x row-gather + row-scatter i64[2M,4] @1M", g1s1_rows, staterow, slots)
timed("argsort+take+unsort i32[1M]", sort_take_unsort, slots)
