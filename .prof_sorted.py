import jax
jax.config.update("jax_enable_x64", True)
import time, numpy as np, jax.numpy as jnp

B = 1 << 20
N = 1 << 21
R = 20
rng = np.random.default_rng(0)
idx_rand = rng.integers(0, N, B).astype(np.int32)
idx_sorted = np.sort(idx_rand).astype(np.int32)
d_rand = jnp.asarray(idx_rand); d_sorted = jnp.asarray(idx_sorted)
st64 = jnp.zeros((N,), jnp.int64)
row64 = jnp.zeros((N, 4), jnp.int64)

def timed(name, fn, *args):
    out = fn(*args); np.asarray(jax.tree_util.tree_leaves(out)[0])
    t0 = time.perf_counter()
    out = fn(*args); np.asarray(jax.tree_util.tree_leaves(out)[0])
    dt = time.perf_counter() - t0
    print(f"{name:56s} {(dt-0.11)/R*1e3:8.1f} ms/iter", flush=True)

def mk_gather(sorted_flag):
    @jax.jit
    def f(st, idx):
        def body(i, carry):
            acc, st = carry
            v = st.take(idx, indices_are_sorted=sorted_flag) if False else \
                jax.lax.gather(st[:, None], idx[:, None],
                    jax.lax.GatherDimensionNumbers(
                        offset_dims=(1,), collapsed_slice_dims=(0,),
                        start_index_map=(0,)),
                    (1, 1), indices_are_sorted=sorted_flag).squeeze(-1)
            return (acc + v[0], st)
        return jax.lax.fori_loop(0, R, body, (jnp.int64(0), st))[0]
    return f

# simpler: use jnp.take with mode + at[].get with flags
def mk_take(sorted_flag, idx):
    @jax.jit
    def f(st):
        def body(i, acc):
            v = st.at[idx].get(indices_are_sorted=sorted_flag, mode="promise_in_bounds")
            return acc + v[0] + i
        return jax.lax.fori_loop(0, R, body, jnp.int64(0))
    return f

def mk_rowtake(sorted_flag, idx):
    @jax.jit
    def f(st):
        def body(i, acc):
            v = st.at[idx].get(indices_are_sorted=sorted_flag, mode="promise_in_bounds")
            return acc + v[0, 0] + i
        return jax.lax.fori_loop(0, R, body, jnp.int64(0))
    return f

def mk_rowscatter(sorted_flag, unique, idx):
    @jax.jit
    def f(st):
        def body(i, st):
            rows = st.at[idx].get(indices_are_sorted=sorted_flag, mode="promise_in_bounds")
            return st.at[idx].set(rows + 1, indices_are_sorted=sorted_flag,
                                  unique_indices=unique, mode="promise_in_bounds")
        return jax.lax.fori_loop(0, R, body, st)
    return f

timed("flat i64 take, random", mk_take(False, d_rand), st64)
timed("flat i64 take, sorted+flag", mk_take(True, d_sorted), st64)
timed("row i64[*,4] take, random", mk_rowtake(False, d_rand), row64)
timed("row i64[*,4] take, sorted+flag", mk_rowtake(True, d_sorted), row64)
timed("row g+s, random noflags", mk_rowscatter(False, False, d_rand), row64)
timed("row g+s, sorted+unique flags", mk_rowscatter(True, True, d_sorted), row64)
