import numpy as np, jax.numpy as jnp
from ratelimiter_tpu import RateLimitConfig
from ratelimiter_tpu.engine.state import LimiterTable
from ratelimiter_tpu.engine.engine import DeviceEngine

table = LimiterTable()
lid = table.register(RateLimitConfig(max_permits=50, window_ms=60_000, refill_rate=10.0))
e = DeviceEngine(num_slots=64, table=table)
now = 1_753_000_000_000
out = e.tb_acquire([7], [lid], [45], now)
print("first 45:", out["allowed"][0], "remaining", out["remaining"][0])
print("raw packed row:", np.asarray(e.tb_packed)[7])
st = e.tb_state
print("decoded tokens_fp:", int(np.asarray(st.tokens_fp)[7]), "last:", int(np.asarray(st.last_refill)[7]))
out = e.tb_acquire([7], [lid], [45], now + 100)
print("second 45 (+100ms):", out["allowed"][0], "remaining", out["remaining"][0])
