import time, numpy as np
from ratelimiter_tpu import RateLimitConfig
from ratelimiter_tpu.algorithms import TokenBucketRateLimiter
from ratelimiter_tpu.metrics import MeterRegistry
from ratelimiter_tpu.storage import TpuBatchedStorage

cfg = RateLimitConfig(max_permits=100, window_ms=60_000, refill_rate=50.0)
storage = TpuBatchedStorage(num_slots=1 << 21)
lim = TokenBucketRateLimiter(storage, cfg, MeterRegistry())
rng = np.random.default_rng(7)

# isolate: native index batch assign throughput
keys = rng.integers(0, 1_000_000, 1 << 20)
idx = storage._index["tb"]
t0 = time.perf_counter()
slots, clears = idx.assign_batch_ints(keys, 1)
print(f"index assign 1M keys: {(time.perf_counter()-t0)*1e3:.0f} ms", flush=True)
t0 = time.perf_counter()
slots, clears = idx.assign_batch_ints(keys, 1)
print(f"index assign 1M keys (warm): {(time.perf_counter()-t0)*1e3:.0f} ms", flush=True)

for B, K in [(1 << 17, 8), (1 << 19, 8), (1 << 20, 8)]:
    n = B * K * 4
    key_ids = rng.integers(0, 1_000_000, n)
    t0 = time.perf_counter()
    lim.try_acquire_stream_ids(key_ids[:B * K], batch=B, subbatches=K)
    print(f"B={B} K={K}: compile+first {time.perf_counter()-t0:.1f}s", flush=True)
    t0 = time.perf_counter()
    lim.try_acquire_stream_ids(key_ids, batch=B, subbatches=K)
    dt = time.perf_counter() - t0
    print(f"B={B} K={K}: {n} decisions {dt:.2f}s -> {n/dt/1e6:.2f}M/s", flush=True)
storage.close()
