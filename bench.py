"""Driver benchmark: prints ONE JSON line with the headline metric.

Headline: end-to-end rate-limit decisions/sec on a 1M-key token-bucket
Zipf(1.1) stream (BASELINE.json config #2) — integer keys in, allow/deny
out, through the native slot index + the pipelined scan-bits device path on
one chip.  vs_baseline compares against the reference's published 80,192
req/s (README single-key sliding-window, local cache on, M1 + Redis —
BASELINE.md).

Detailed results for all scenarios land in BENCH_DETAIL.json:
  1. single-key sliding window, 10 threads, through the micro-batcher
     (latency percentiles — the reference's headline scenario; per-request
     latency here is dominated by the host<->device tunnel RTT of this
     environment, ~110 ms per fetch — see the "tunnel" note in the detail)
  2. 1M-key token bucket, Zipf(1.1)      [headline, streaming path]
  3. 10M-key sliding window, uniform     (streaming path)
  4. 100K-tenant multi-config mix        (fused engine path, mixed lids)
  5. burst batch-acquire tryAcquire(key, n in [1,100]) over 1M keys
     (streaming path with per-request permits)

Scale knobs: BENCH_SCALE=small|full (default full on TPU, small elsewhere).
A persistent XLA compilation cache (.jax_cache) makes repeat runs cheap.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    import jax

    from ratelimiter_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache(
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"))

    platform = jax.devices()[0].platform
    scale = os.environ.get("BENCH_SCALE") or ("full" if platform == "tpu" else "small")
    small = scale == "small"
    log(f"bench: platform={platform} scale={scale}")

    def link_probe():
        """Upload bandwidth + round-trip floor of the host<->device link,
        recorded with every run: the dev tunnel's throughput swings 4-60
        MB/s hour to hour, and stream scenarios are wire-bound — a run's
        numbers are only comparable alongside its link health."""
        import jax.numpy as jnp

        csum = jax.jit(lambda v: v.sum())
        probe = np.zeros(1024, dtype=np.int32)
        np.asarray(csum(jnp.asarray(probe)))  # compile + settle
        t0 = time.perf_counter()
        for _ in range(3):
            np.asarray(csum(jnp.asarray(probe)))
        rtt_s = (time.perf_counter() - t0) / 3
        buf = np.random.default_rng(7).integers(
            0, 1 << 20, 1 << 20).astype(np.int32)  # 4 MB
        np.asarray(csum(jnp.asarray(buf)))  # compile this shape untimed
        t0 = time.perf_counter()
        for _ in range(2):
            np.asarray(csum(jnp.asarray(buf)))
        up_s = max((time.perf_counter() - t0) / 2 - rtt_s, 1e-6)
        return {"round_trip_ms": round(rtt_s * 1000, 1),
                "upload_4mb_mbps": round(4.0 / up_s, 1)}

    detail_link = link_probe() if platform == "tpu" else None
    if detail_link:
        log(f"link: rtt {detail_link['round_trip_ms']} ms, "
            f"upload {detail_link['upload_4mb_mbps']} MB/s")

    from ratelimiter_tpu import RateLimitConfig
    from ratelimiter_tpu.algorithms import (
        SlidingWindowRateLimiter,
        TokenBucketRateLimiter,
    )
    from ratelimiter_tpu.bench.harness import (
        bench_end_to_end,
        bench_end_to_end_stream,
        bench_threaded,
        uniform_stream,
        zipf_stream,
    )
    from ratelimiter_tpu.engine.engine import DeviceEngine
    from ratelimiter_tpu.engine.state import LimiterTable
    from ratelimiter_tpu.metrics import MeterRegistry
    from ratelimiter_tpu.storage import TpuBatchedStorage

    from ratelimiter_tpu.utils.tracing import device_profile

    profile_dir = os.environ.get("BENCH_PROFILE")
    rng = np.random.default_rng(42)
    detail = {"platform": platform, "scale": scale}
    if detail_link:
        detail["link"] = detail_link
    t_start = time.time()

    # Streaming shape: K sub-batches of B per device dispatch.
    B = (1 << 12) if small else (1 << 19)
    K = 4 if small else 8
    super_n = B * K

    def run_stream(lim, key_ids, permits, reps):
        """Compile once on the first super-batch, then time `reps` passes."""
        lim.try_acquire_stream_ids(key_ids[:super_n], permits if permits is None
                                   else permits[:super_n], batch=B, subbatches=K)
        n = len(key_ids)
        t0 = time.perf_counter()
        for _ in range(reps):
            allowed = lim.try_acquire_stream_ids(key_ids, permits,
                                                 batch=B, subbatches=K)
        wall = time.perf_counter() - t0
        return {
            "mode": "stream_ids", "decisions": n * reps, "wall_s": wall,
            "decisions_per_sec": n * reps / wall, "batch": B, "subbatches": K,
            "allowed_last_pass": int(allowed.sum()),
        }

    # -- scenario 2 (headline): 1M-key token bucket, Zipf(1.1) ---------------
    num_keys = 20_000 if small else 1_000_000
    n_requests = super_n * (2 if small else 4)
    log(f"scenario 2: TB Zipf over {num_keys} keys, {n_requests} reqs/pass...")

    tb_cfg = RateLimitConfig(max_permits=100, window_ms=60_000, refill_rate=50.0)
    storage = TpuBatchedStorage(num_slots=max(num_keys * 2, 1 << 16))
    tb_limiter = TokenBucketRateLimiter(storage, tb_cfg, MeterRegistry())

    key_ids = zipf_stream(rng, num_keys, n_requests)
    with device_profile(profile_dir):
        res = run_stream(tb_limiter, key_ids, None, reps=2 if small else 3)
    detail["tb_1m_zipf_stream_ids"] = res
    headline = res["decisions_per_sec"]
    log(f"  stream (int keys): {headline:,.0f} decisions/s")

    # String-key end-to-end (Python key handling included; streamed).
    n_str = min(n_requests, 50_000 if small else 2_000_000)
    keys = [f"k{i}" for i in key_ids[:n_str]]
    res = bench_end_to_end_stream(tb_limiter, keys, None)
    detail["tb_1m_zipf_end_to_end_strs"] = res
    log(f"  end-to-end (str keys): {res['decisions_per_sec']:,.0f} decisions/s")
    storage.close()

    # -- scenario 1: single-key SW, 10 threads through the batcher -----------
    log("scenario 1: single-key sliding window, 10 threads...")
    sw_cfg = RateLimitConfig(max_permits=100, window_ms=60_000,
                             enable_local_cache=True, local_cache_ttl_ms=100)
    storage = TpuBatchedStorage(num_slots=1 << 12, max_delay_ms=0.3)
    sw_limiter = SlidingWindowRateLimiter(storage, sw_cfg, MeterRegistry())
    res = bench_threaded(
        sw_limiter,
        keys_per_thread=lambda t: ["hot-key"],
        n_threads=10,
        requests_per_thread=200 if small else 2000,
    )
    # Context figure: one synchronous decision round trip on this link.
    # When it exceeds the 100 ms local-cache TTL (always true on the dev
    # tunnel, never true on a local-attached TPU), every cache expiry
    # chains a full round trip and the scenario measures the LINK, not
    # the engine — the reference's regime (0.8 ms Redis RTT << TTL)
    # reproduces only with local attachment.
    t0 = time.perf_counter()
    for _ in range(3):
        sw_limiter.try_acquire("rtt-probe-key")
    res["device_round_trip_ms"] = round(
        (time.perf_counter() - t0) / 3 * 1000, 1)
    res["note"] = ("per-request latency includes the host<->device tunnel "
                   "RTT of this environment on cache misses; see "
                   "device_round_trip_ms — when it exceeds the cache TTL "
                   "the throughput number measures the link, not the "
                   "engine")
    detail["sw_single_key_threaded"] = res
    log(f"  {res['decisions_per_sec']:,.0f} req/s; "
        f"p99 {res['request_latency']['p99_us']:.0f} us")
    storage.close()

    # -- scenario 3: 10M-key sliding window, uniform (streaming) -------------
    num_keys3 = 50_000 if small else 10_000_000
    n3 = super_n * (2 if small else 4)
    log(f"scenario 3: SW uniform over {num_keys3} keys (stream)...")
    storage3 = TpuBatchedStorage(num_slots=max(int(num_keys3 * 1.25), 1 << 16))
    sw3 = SlidingWindowRateLimiter(
        storage3,
        RateLimitConfig(max_permits=100, window_ms=60_000,
                        enable_local_cache=False),
        MeterRegistry())
    res = run_stream(sw3, uniform_stream(rng, num_keys3, n3), None,
                     reps=2 if small else 3)
    detail["sw_10m_uniform_stream"] = res
    log(f"  stream: {res['decisions_per_sec']:,.0f} decisions/s")
    storage3.close()

    # -- scenario 4: 100K-tenant multi-config mix (multi-lid stream) ---------
    n_tenants = 1000 if small else 100_000
    n4 = super_n * (2 if small else 3)
    log(f"scenario 4: {n_tenants}-tenant mix (stream)...")
    table = LimiterTable(capacity=n_tenants + 2)
    lids = np.asarray(
        [table.register(RateLimitConfig(
            max_permits=50 + (i % 100), window_ms=60_000,
            refill_rate=float(5 + i % 20)))
         for i in range(n_tenants)], dtype=np.int64)
    storage4 = TpuBatchedStorage(
        engine=DeviceEngine(num_slots=max(n_tenants * 8, 1 << 16), table=table))
    tenant_of_req = rng.integers(0, n_tenants, size=n4)
    # ~8 user keys per tenant, per-request tenant policy.
    keys4 = (tenant_of_req * 8 + rng.integers(0, 8, size=n4)).astype(np.int64)
    lids4 = lids[tenant_of_req]
    storage4.acquire_stream_ids("tb", lids4[:super_n], keys4[:super_n],
                                batch=B, subbatches=K)
    t0_all = time.perf_counter()
    allowed4 = storage4.acquire_stream_ids("tb", lids4, keys4,
                                           batch=B, subbatches=K)
    wall = time.perf_counter() - t0_all
    detail["multi_tenant_100k_stream"] = {
        "mode": "stream_ids_multi", "decisions": n4, "wall_s": wall,
        "decisions_per_sec": n4 / wall, "tenants": n_tenants,
        "allowed": int(allowed4.sum()),
    }
    log(f"  stream: {n4 / wall:,.0f} decisions/s")
    storage4.close()

    # -- scenario 5: burst batch-acquire over 1M keys (streaming) ------------
    num_keys5 = 20_000 if small else 1_000_000
    n5 = super_n * (2 if small else 3)
    log(f"scenario 5: burst batch-acquire over {num_keys5} keys...")
    storage5 = TpuBatchedStorage(num_slots=max(num_keys5 * 2, 1 << 16))
    tb5 = TokenBucketRateLimiter(
        storage5,
        RateLimitConfig(max_permits=100, window_ms=60_000, refill_rate=100.0),
        MeterRegistry())
    key5 = uniform_stream(rng, num_keys5, n5)
    perms5 = rng.integers(1, 101, size=n5).astype(np.int64)
    res = run_stream(tb5, key5, perms5, reps=2)
    detail["tb_burst_batch_stream"] = res
    log(f"  stream: {res['decisions_per_sec']:,.0f} decisions/s")
    storage5.close()

    # -- sharded scaling (virtual CPU mesh, subprocess) ----------------------
    # The multi-chip sharding machinery measured 1 -> 8 shards; a separate
    # process because the CPU backend must be selected before any device
    # work (this process owns the TPU).
    log("sharded scaling (8-device virtual CPU mesh, subprocess)...")
    try:
        import subprocess

        proc = subprocess.run(
            [sys.executable, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "bench", "sharded_scaling.py")],
            capture_output=True, timeout=600, text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        if proc.returncode != 0 or not proc.stdout.strip():
            raise RuntimeError(
                f"rc={proc.returncode} stderr={proc.stderr[-500:]!r}")
        detail["sharded_scaling"] = json.loads(
            proc.stdout.strip().splitlines()[-1])
        for p in detail["sharded_scaling"]["points"]:
            log(f"  {p['n_shards']} shard(s): "
                f"{p['decisions_per_sec']:,.0f} decisions/s")
    except Exception as exc:  # noqa: BLE001 — aux section must not kill bench
        detail["sharded_scaling"] = {"error": str(exc)}
        log(f"  sharded scaling failed: {exc}")

    detail["total_bench_seconds"] = time.time() - t_start

    with open(os.path.join(os.path.dirname(__file__) or ".", "BENCH_DETAIL.json"), "w") as fh:
        json.dump(detail, fh, indent=2)

    baseline = 80_192.0  # reference README throughput (BASELINE.md)
    # Honest labeling: the headline is the int-key STREAM rate; the
    # string-key end-to-end number lives in BENCH_DETAIL.json under
    # tb_1m_zipf_end_to_end_strs.
    print(json.dumps({
        "metric": "tb_1m_keys_zipf_stream_decisions_per_sec",
        "value": round(float(headline), 1),
        "unit": "decisions/s",
        "vs_baseline": round(float(headline) / baseline, 2),
    }))


if __name__ == "__main__":
    main()
