"""Driver benchmark: prints ONE JSON line with the headline metric.

Headline: end-to-end rate-limit decisions/sec on a 1M-key token-bucket
Zipf(1.1) stream (BASELINE.json config #2) — integer keys in, allow/deny
out, through the native slot index + the pipelined relay/digest device path
on one chip.  vs_baseline compares against the reference's published 80,192
req/s (README single-key sliding-window, local cache on, M1 + Redis —
BASELINE.md).

Robustness discipline (VERDICT r2 #1 — the driver's recorded number must
match the code's ability):

- Every stream scenario runs a FULL untimed warmup pass first.  The relay
  chunk-growth schedule is deterministic in the key stream, so the warmup
  visits every chunk shape the timed passes will visit — no mid-timing
  XLA compiles (r2's prime suspect for the 5x driver/builder swing).
- Timed passes record a per-pass phase breakdown (assign_s / host_s /
  fetch_s / wire_bytes / chunks) from the storage's stream instrumentation
  plus the number and seconds of backend compiles that fired inside the
  timed region — so BENCH_DETAIL explains where the seconds went.
- If the pass walls spread wider than 1.6x, the link is re-probed and ONE
  extra pass runs; everything (both probes, all passes) is recorded.

Detailed results for all scenarios land in BENCH_DETAIL.json:
  1. single-key sliding window, 10 threads, through the micro-batcher
     (tunnel-RTT-bound here; a CPU-device in-process run of the same code
     is recorded as sw_single_key_threaded_local — the RTT<<TTL regime
     the reference actually operates in)
  2. 1M-key token bucket, Zipf(1.1)      [headline, streaming path]
  3. 10M-key sliding window, uniform     (streaming path)
  4. 100K-tenant multi-config mix        (churn pass and resident-lid
     steady-state passes, reported separately)
  5. burst batch-acquire tryAcquire(key, n in [1,100]) over 1M keys
  plus: a latency-SLO section (per-request percentiles + RTT
  decomposition against the <=1 ms target) and a Pallas A/B subprocess
  pair recording what the kernels buy on this link.

Scale knobs: BENCH_SCALE=small|full (default full on TPU, small elsewhere).
A persistent XLA compilation cache (.jax_cache) makes repeat runs cheap.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.abspath(__file__))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    import jax

    from ratelimiter_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache(os.path.join(_REPO, ".jax_cache"))

    platform = jax.devices()[0].platform
    scale = os.environ.get("BENCH_SCALE") or ("full" if platform == "tpu" else "small")
    small = scale == "small"
    log(f"bench: platform={platform} scale={scale}")

    # -- compile accounting: every backend compile that fires inside a timed
    # region is a measurement hazard; count them so the detail can prove a
    # pass was (or was not) compile-contaminated.
    compile_events: list = []

    def _on_event(name, secs, **kw):
        if name == "/jax/core/compile/backend_compile_duration":
            compile_events.append(secs)

    jax.monitoring.register_event_duration_secs_listener(_on_event)

    class _compiles:
        def __enter__(self):
            self._n0 = len(compile_events)
            return self

        def __exit__(self, *a):
            evs = compile_events[self._n0:]
            self.n = len(evs)
            self.secs = round(float(sum(evs)), 3)

    def link_probe():
        """Upload bandwidth + round-trip floor of the host<->device link,
        recorded with every run: the dev tunnel's throughput swings 4-60
        MB/s hour to hour, and stream scenarios are wire-bound — a run's
        numbers are only comparable alongside its link health.  Same
        probe the storages' chunk-plan election consumes (utils/link.py),
        so the logged link and the elected plans cannot disagree."""
        from ratelimiter_tpu.utils.link import measure_link

        up_bps, rtt_s, down_bps = measure_link()
        return {"round_trip_ms": round(rtt_s * 1000, 1),
                "upload_4mb_mbps": round(up_bps / (1 << 20), 1),
                "download_4mb_mbps": round(down_bps / (1 << 20), 1)}

    detail_link = link_probe() if platform == "tpu" else None
    if detail_link:
        log(f"link: rtt {detail_link['round_trip_ms']} ms, "
            f"upload {detail_link['upload_4mb_mbps']} MB/s, "
            f"download {detail_link['download_4mb_mbps']} MB/s")

    # Device step rates the elections will run on: probed per (platform,
    # device kind), disk-cached (engine/device_rates.py, VERDICT r4 #5) —
    # recorded so the plan/mode decisions in this run are reproducible.
    from ratelimiter_tpu.engine.device_rates import get_device_rates

    device_rates = get_device_rates()
    log(f"device rates: {device_rates}")

    from ratelimiter_tpu import RateLimitConfig
    from ratelimiter_tpu.algorithms import (
        SlidingWindowRateLimiter,
        TokenBucketRateLimiter,
    )
    from ratelimiter_tpu.bench.harness import (
        bench_end_to_end_stream,
        bench_threaded,
        uniform_stream,
        zipf_stream,
    )
    from ratelimiter_tpu.engine.engine import DeviceEngine
    from ratelimiter_tpu.engine.state import LimiterTable
    from ratelimiter_tpu.metrics import MeterRegistry
    from ratelimiter_tpu.storage import TpuBatchedStorage

    from ratelimiter_tpu.utils.tracing import device_profile

    profile_dir = os.environ.get("BENCH_PROFILE")
    rng = np.random.default_rng(42)
    detail = {"platform": platform, "scale": scale,
              "device_rates": device_rates}
    if detail_link:
        detail["link"] = detail_link
    t_start = time.time()

    # Which Pallas kernels are LIVE vs silently fallen back (VERDICT r2 #6:
    # the axis must be falsifiable from the artifacts).  settle() is the
    # same cached probe the engines consult, so this records exactly what
    # the scenario dispatches will use.
    from ratelimiter_tpu.ops.pallas import (
        block_scatter,
        election_report,
        relay_step,
        solver,
    )

    detail["pallas"] = {
        "flag": os.environ.get("RATELIMITER_PALLAS", "1"),
        "solver_live": bool(solver.settle()),
        "block_scatter_live": bool(block_scatter.settle()),
        "relay_fused_live": bool(relay_step.settle()),
        # Per-path measured elections (ops/pallas/election.py): which
        # backend serves each Pallas-capable path on THIS device, with
        # the A/B timings the verdicts came from — so a path can never
        # silently run a measured-slower kernel (perf_smoke.py asserts
        # record/verdict consistency in CI).
        "elections": election_report(),
    }
    log(f"pallas: solver_live={detail['pallas']['solver_live']} "
        f"block_scatter_live={detail['pallas']['block_scatter_live']} "
        f"relay_fused_live={detail['pallas']['relay_fused_live']}")

    # Streaming shape: K sub-batches of B per device dispatch.
    B = (1 << 12) if small else (1 << 19)
    K = 4 if small else 8
    super_n = B * K

    def _agg_stats(stats):
        """Collapse per-chunk records into one phase breakdown."""
        if not stats:
            return None
        agg = {
            "chunks": len(stats),
            "assign_s": round(sum(r.get("assign_s", 0) for r in stats), 4),
            # walk_s records are cumulative within a pass: take the max.
            # assign_s is the walk time EXPOSED on the main thread (a
            # prefetched walk that hid under a fetch shows ~0); walk_s is
            # the true walk seconds wherever they ran.
            "walk_s": round(max((r.get("walk_s", 0) for r in stats),
                                default=0.0), 4),
            "host_s": round(sum(r.get("host_s", 0) for r in stats), 4),
            "fetch_s": round(sum(r.get("fetch_s", 0) for r in stats), 4),
            "max_fetch_s": round(max((r.get("fetch_s", 0) for r in stats),
                                     default=0.0), 4),
            "wire_bytes": int(sum(r.get("wire_bytes", 0) for r in stats)),
        }
        # r5: drains run CONCURRENTLY, so the honest fetch wall-clock
        # figure is the SPAN of fetch activity, not the sum of per-chunk
        # blocking times (which can exceed the wall under overlap).
        ats = [r["fetch_at"] for r in stats if r.get("fetch_at")]
        if ats:
            agg["fetch_span_s"] = round(
                max(a[1] for a in ats) - min(a[0] for a in ats), 4)
        for extra in ("rebuild_s", "dispatch_s", "pack_s"):
            tot = sum(r.get(extra, 0) for r in stats)
            if tot:
                agg[extra] = round(tot, 4)
        modes: dict = {}
        for r in stats:
            m = r.get("mode", "?")
            modes[m] = modes.get(m, 0) + 1
        agg["modes"] = modes
        return agg

    def plan_sig(storage):
        """Only (kind, chunk) decide dispatch shapes; the pass/best
        counters mutate every pass and must not defeat stability
        checks."""
        return {k: (v["kind"], v["chunk"])
                for k, v in storage._chunk_plans.items()}

    def plans_settled(storage):
        """True when no plan can change shape on a later pass: pipelined
        and locked plans are sticky, giant plans stop re-electing at
        passes >= 3.  Warmup must not stop before this, or a measured
        pass could elect new chunk shapes and pay their compiles."""
        return all(v["kind"] == "pipelined" or v.get("locked")
                   or v.get("passes", 0) >= 3
                   for v in storage._chunk_plans.values())

    scenario_links: dict = {}

    def set_link(storage, scenario=None):
        """Feed a FRESH link probe into the storage so its streaming
        loops elect chunk plans for the link as it is NOW — the tunnel
        swings hour to hour and a start-of-run probe is stale by the
        third scenario (r5: 77 MB/s at boot, 28 MB/s ninety minutes
        in).  Each scenario's probe is recorded for the link curve."""
        if not detail_link:
            return
        probe = link_probe()
        if scenario:
            scenario_links[scenario] = probe
            log(f"  link now: up {probe['upload_4mb_mbps']} MB/s, "
                f"down {probe['download_4mb_mbps']} MB/s")
        storage.set_link_profile(
            probe["upload_4mb_mbps"] * (1 << 20),
            probe["round_trip_ms"] / 1000.0,
            probe["download_4mb_mbps"] * (1 << 20))

    def run_stream(go, key_ids, permits, reps, storage, warmed=False):
        """Full untimed warmup pass (visits every chunk shape the growth
        schedule reaches), then ``reps`` timed passes with per-pass phase
        breakdowns; re-probes the link and retries once if the pass walls
        spread wider than 1.6x.  A chunk-plan election during the warmup
        changes the later passes' shapes, so the warmup reruns until the
        plan map is stable — timed passes never meet a fresh shape."""
        n = len(key_ids)
        res = {"mode": "stream_ids", "batch": B, "subbatches": K,
               "decisions_per_pass": n}
        if not warmed:
            warmups = []
            for _ in range(4):  # provisional-giant + elect + new shapes
                sig_before = plan_sig(storage)
                with _compiles() as cw:
                    go(key_ids, permits)
                warmups.append({"n_compiles": cw.n, "compile_s": cw.secs})
                if plan_sig(storage) == sig_before and plans_settled(storage):
                    break
            res["warmup"] = warmups[0]
            if len(warmups) > 1:
                res["warmup_extra"] = warmups[1:]
            res["chunk_plans"] = {
                "/".join(map(str, k)): dict(v)
                for k, v in storage._chunk_plans.items()}
        passes = []

        def timed_pass():
            storage.stream_stats = stats = []
            with _compiles() as c:
                t0 = time.perf_counter()
                allowed = go(key_ids, permits)
                wall = time.perf_counter() - t0
            storage.stream_stats = None
            rec = {"wall_s": round(wall, 4),
                   "decisions_per_sec": round(n / wall, 1),
                   "n_compiles": c.n, "compile_s": c.secs,
                   "phase": _agg_stats(stats)}
            passes.append(rec)
            return allowed

        for _ in range(reps):
            allowed = timed_pass()
        walls = [p["wall_s"] for p in passes]
        if platform == "tpu" and max(walls) > 1.6 * min(walls):
            # A pass was degraded by something outside the code (link
            # hiccup / noisy neighbor): record a fresh probe + one retry.
            res["relink"] = link_probe()
            allowed = timed_pass()
        total_wall = sum(p["wall_s"] for p in passes)
        rates = sorted(p["decisions_per_sec"] for p in passes)
        res.update({
            "decisions": n * len(passes), "wall_s": round(total_wall, 4),
            "decisions_per_sec": n * len(passes) / total_wall,
            # The median pass is robust to single multi-second link
            # stalls (observed: a 65 s zero-compile fetch on an
            # otherwise-normal run); the aggregate and every pass stay
            # recorded alongside.
            "median_pass_decisions_per_sec": rates[len(rates) // 2],
            "best_pass_decisions_per_sec": rates[-1],
            "passes": passes,
            "allowed_last_pass": int(allowed.sum()),
        })
        return res

    # -- scenario 2 (headline): 1M-key token bucket, Zipf(1.1) ---------------
    num_keys = 20_000 if small else 1_000_000
    n_requests = super_n * (2 if small else 4)
    log(f"scenario 2: TB Zipf over {num_keys} keys, {n_requests} reqs/pass...")

    tb_cfg = RateLimitConfig(max_permits=100, window_ms=60_000, refill_rate=50.0)
    from ratelimiter_tpu.ops.pallas.block_scatter import align_slots

    storage = TpuBatchedStorage(num_slots=align_slots(
        max(num_keys * 2, 1 << 16)))
    set_link(storage, 'tb_1m_zipf_stream_ids')
    # Auto-elected host-parallel partitioned index (r7): the storage
    # constructions pick it up by default; record what the headline ran
    # with so the walk-term split in the phase lanes is attributable.
    detail["host_parallel"] = {
        "elected": storage._host_parallel,
        "note": ("0 = single-LRU native index; T>1 = T-way partitioned "
                 "walk (engine/partitioned.py), auto-elected from cores "
                 "and table size, explicit kwarg wins")}
    log(f"host_parallel: {storage._host_parallel}")
    tb_limiter = TokenBucketRateLimiter(storage, tb_cfg, MeterRegistry())

    key_ids = zipf_stream(rng, num_keys, n_requests)
    with device_profile(profile_dir):
        res = run_stream(
            lambda ids, p: tb_limiter.try_acquire_stream_ids(
                ids, p, batch=B, subbatches=K),
            key_ids, None, 2 if small else 3, storage)
    detail["tb_1m_zipf_stream_ids"] = res
    # Median pass: robust to single link stalls; every pass + the
    # aggregate are in BENCH_DETAIL with their phase breakdowns.
    headline = res["median_pass_decisions_per_sec"]
    log(f"  stream (int keys): {headline:,.0f} decisions/s median pass "
        f"(aggregate {res['decisions_per_sec']:,.0f}, best "
        f"{res['best_pass_decisions_per_sec']:,.0f})")

    # String-key end-to-end (Python key handling included; streamed).
    # 8M requests (r5, was 2M): the string walk runs ~70 ns/request
    # (pack + hash + probe), so short streams were dominated by the
    # fixed final-fetch round trip and measured the link, not the
    # path.  Per-batch round-trip latency is reported separately
    # (batch_latency) — this figure is sustained throughput.
    n_str = min(n_requests, 50_000 if small else 8_000_000)
    keys = [f"k{i}" for i in key_ids[:n_str]]
    res = bench_end_to_end_stream(tb_limiter, keys, None, storage=storage)
    for p in res["passes"]:  # collapse raw chunk records to phase lanes
        p["phase"] = _agg_stats(p.pop("stats"))
    detail["tb_1m_zipf_end_to_end_strs"] = res
    log(f"  end-to-end (str keys): {res['decisions_per_sec']:,.0f} decisions/s"
        f" (median pass {res['median_pass_decisions_per_sec']:,.0f})")
    storage.close()

    # -- scenario 1: single-key SW, 10 threads through the batcher -----------
    log("scenario 1: single-key sliding window, 10 threads...")
    sw_cfg = RateLimitConfig(max_permits=100, window_ms=60_000,
                             enable_local_cache=True, local_cache_ttl_ms=100)
    storage = TpuBatchedStorage(num_slots=1 << 12, max_delay_ms=0.3)
    sw_limiter = SlidingWindowRateLimiter(storage, sw_cfg, MeterRegistry())
    res = bench_threaded(
        sw_limiter,
        keys_per_thread=lambda t: ["hot-key"],
        n_threads=10,
        requests_per_thread=200 if small else 2000,
    )
    # Context figure: one synchronous decision round trip on this link.
    # When it exceeds the 100 ms local-cache TTL (always true on the dev
    # tunnel, never true on a local-attached TPU), every cache expiry
    # chains a full round trip and the scenario measures the LINK, not
    # the engine — the reference's regime (0.8 ms Redis RTT << TTL)
    # reproduces only with local attachment (see
    # sw_single_key_threaded_local for that regime measured in-process).
    t0 = time.perf_counter()
    for _ in range(3):
        sw_limiter.try_acquire("rtt-probe-key")
    res["device_round_trip_ms"] = round(
        (time.perf_counter() - t0) / 3 * 1000, 1)
    res["note"] = ("per-request latency includes the host<->device tunnel "
                   "RTT of this environment on cache misses; see "
                   "device_round_trip_ms and sw_single_key_threaded_local")
    detail["sw_single_key_threaded"] = res
    log(f"  {res['decisions_per_sec']:,.0f} req/s; "
        f"p99 {res['request_latency']['p99_us']:.0f} us")

    # -- latency-SLO section: per-request percentiles + decomposition --------
    # The <=1 ms p99 target (BASELINE.md) is a LOCAL-attachment claim; this
    # section records the tunnel numbers alongside the pieces that compose
    # them (batcher flush delay, device RTT) so the production claim is
    # checkable: p99_local ~= max_delay_ms + device step + PCIe RTT.
    log("latency SLO: 16 threads, distinct keys, percentiles + decomposition...")
    res = bench_threaded(
        sw_limiter,
        keys_per_thread=lambda t: [f"slo-user-{t}-{i}" for i in range(64)],
        n_threads=16,
        requests_per_thread=100 if small else 400,
    )
    res["decomposition"] = {
        "batcher_max_delay_ms": 0.3,
        "device_round_trip_ms": detail["sw_single_key_threaded"][
            "device_round_trip_ms"],
        "target_p99_ms_local": 1.0,
        "note": ("tunnel RTT dominates every percentile here; on local "
                 "attachment the same path's bound is max_delay + one "
                 "device step + PCIe round trip — see "
                 "sw_single_key_threaded_local for the measured "
                 "zero-RTT regime"),
    }
    detail["latency_slo_threaded"] = res
    log(f"  p50 {res['request_latency']['p50_us']:.0f} us, "
        f"p99 {res['request_latency']['p99_us']:.0f} us over "
        f"{res['request_latency']['n_samples']} requests")
    storage.close()

    # -- scenario 1-local: same code, CPU device in-process (RTT ~ 0) --------
    # The reference's operating regime is RTT << cache TTL; the tunnel
    # inverts that.  A subprocess pins jax to the in-process CPU device and
    # reruns scenario 1 — same limiter, same batcher, zero tunnel.
    log("scenario 1-local: single-key SW, CPU device in-process...")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO, "bench",
                                          "local_single_key.py")],
            capture_output=True, timeout=600, text=True, cwd=_REPO)
        if proc.returncode != 0 or not proc.stdout.strip():
            raise RuntimeError(
                f"rc={proc.returncode} stderr={proc.stderr[-500:]!r}")
        detail["sw_single_key_threaded_local"] = json.loads(
            proc.stdout.strip().splitlines()[-1])
        r = detail["sw_single_key_threaded_local"]
        log(f"  local: {r['decisions_per_sec']:,.0f} req/s; "
            f"p99 {r['request_latency']['p99_us']:.0f} us")
    except Exception as exc:  # noqa: BLE001 — aux section must not kill bench
        detail["sw_single_key_threaded_local"] = {"error": str(exc)}
        log(f"  local single-key failed: {exc}")

    # -- latency SLO, local attachment, realistic load (VERDICT r3 #6) -------
    # 16 threads x 4096 distinct keys, cache OFF: every request crosses
    # the device boundary through the micro-batcher, against the <=1 ms
    # p99 target — with a measured decomposition (flush deadline, single
    # device step) when the backend's floor makes the target unreachable.
    log("latency SLO local: 16 threads, multi-key, cache off (subprocess)...")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO, "bench",
                                          "local_latency_slo.py")],
            capture_output=True, timeout=900, text=True, cwd=_REPO)
        if proc.returncode != 0 or not proc.stdout.strip():
            raise RuntimeError(
                f"rc={proc.returncode} stderr={proc.stderr[-500:]!r}")
        detail["latency_slo_local"] = json.loads(
            proc.stdout.strip().splitlines()[-1])
        r = detail["latency_slo_local"]
        log(f"  local SLO: p50 {r['request_latency']['p50_us']:.0f} us, "
            f"p99 {r['request_latency']['p99_us']:.0f} us "
            f"(target 1000 us, meets={r['meets_target']}; device step "
            f"{r['decomposition']['device_step_16_lanes_ms']} ms)")
    except Exception as exc:  # noqa: BLE001 — aux section must not kill bench
        detail["latency_slo_local"] = {"error": str(exc)}
        log(f"  local SLO failed: {exc}")

    # -- sidecar loopback: production ingress under pipelining load ----------
    # N pipelining clients -> TCP sidecar -> shared micro-batcher
    # (VERDICT #6: the ingress had correctness tests only).  CPU device
    # in its own subprocess — it measures the ingress machinery, and
    # this process owns the TPU.
    log("sidecar loopback: 8 pipelining clients (subprocess)...")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO, "bench",
                                          "sidecar_loopback.py")],
            capture_output=True, timeout=600, text=True, cwd=_REPO)
        if proc.returncode != 0 or not proc.stdout.strip():
            raise RuntimeError(
                f"rc={proc.returncode} stderr={proc.stderr[-500:]!r}")
        detail["sidecar_loopback"] = json.loads(
            proc.stdout.strip().splitlines()[-1])
        r = detail["sidecar_loopback"]
        log(f"  sidecar: {r['decisions_per_sec']:,.0f} decisions/s; "
            f"batch p99 {r['batch_latency']['p99_us']:.0f} us")
    except Exception as exc:  # noqa: BLE001 — aux section must not kill bench
        detail["sidecar_loopback"] = {"error": str(exc)}
        log(f"  sidecar loopback failed: {exc}")

    # -- coalesce smoke: Zipf key coalescing A/B (v5 ingest digest) ----------
    # The wire-speed ingestion claim: repeat-heavy Zipf traffic coalesces
    # to one weighted decision per unique key, bit-identical to the
    # sequential oracle.  Subprocess (CPU in-process device).
    log("coalesce smoke: Zipf digest vs rank-major scan (subprocess)...")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO, "bench",
                                          "coalesce_smoke.py")],
            capture_output=True, timeout=600, text=True, cwd=_REPO)
        if proc.returncode != 0 or not proc.stdout.strip():
            raise RuntimeError(
                f"rc={proc.returncode} stderr={proc.stderr[-500:]!r}")
        detail["coalesce_smoke"] = json.loads(
            proc.stdout.strip().splitlines()[-1])
        r = detail["coalesce_smoke"]
        log(f"  coalesce: {r['coalesce_ratio']}x vs uncoalesced scan "
            f"({r['coalesced_decisions_per_sec']:,.0f}/s; "
            f"{r['oracle_mismatches']} oracle mismatches)")
    except Exception as exc:  # noqa: BLE001 — aux section must not kill bench
        detail["coalesce_smoke"] = {"error": str(exc)}
        log(f"  coalesce smoke failed: {exc}")

    # -- scenario 3: 10M-key sliding window, uniform (streaming) -------------
    num_keys3 = 50_000 if small else 10_000_000
    n3 = super_n * (2 if small else 4)
    log(f"scenario 3: SW uniform over {num_keys3} keys (stream)...")
    storage3 = TpuBatchedStorage(
        num_slots=align_slots(max(int(num_keys3 * 1.25), 1 << 16)))
    set_link(storage3, 'sw_10m_uniform_stream')
    sw3 = SlidingWindowRateLimiter(
        storage3,
        RateLimitConfig(max_permits=100, window_ms=60_000,
                        enable_local_cache=False),
        MeterRegistry())
    res = run_stream(
        lambda ids, p: sw3.try_acquire_stream_ids(ids, p, batch=B,
                                                  subbatches=K),
        uniform_stream(rng, num_keys3, n3), None, 2 if small else 3,
        storage3)
    detail["sw_10m_uniform_stream"] = res
    log(f"  stream: {res['decisions_per_sec']:,.0f} decisions/s")
    storage3.close()

    # -- scenario 4: 100K-tenant multi-config mix (multi-lid stream) ---------
    # Measured in TWO phases (VERDICT r2 #4): a CHURN pass where every lid
    # is a first touch (the warmup fills the slot space with a disjoint
    # key population, so the timed churn pass pays full eviction + lid
    # delta-upload cost at warm compile shapes), then STEADY-STATE passes
    # where the lids are device-resident and the digest wire cost drops to
    # ~5-6 B/unique.
    n_tenants = 1000 if small else 100_000
    n4 = super_n * (2 if small else 3)
    log(f"scenario 4: {n_tenants}-tenant mix (churn + steady stream)...")
    table = LimiterTable(capacity=n_tenants + 2)
    lids = np.asarray(
        [table.register(RateLimitConfig(
            max_permits=50 + (i % 100), window_ms=60_000,
            refill_rate=float(5 + i % 20)))
         for i in range(n_tenants)], dtype=np.int64)
    storage4 = TpuBatchedStorage(
        engine=DeviceEngine(num_slots=align_slots(max(n_tenants * 8, 1 << 16)),
                            table=table))
    tenant_of_req = rng.integers(0, n_tenants, size=n4)
    # ~8 user keys per tenant, per-request tenant policy.
    keys4 = (tenant_of_req * 8 + rng.integers(0, 8, size=n4)).astype(np.int64)
    lids4 = lids[tenant_of_req]
    set_link(storage4, 'multi_tenant_100k_stream')
    # Warmup on a DISJOINT key population: compiles every chunk shape and
    # fills the slot space so the churn pass below is 100% first-touch.
    # A chunk-plan election during the first warmup changes later passes'
    # shapes, so re-warm (on yet another disjoint population) until the
    # plan map is stable.
    with _compiles() as cw:
        pop = 1
        for _ in range(4):
            plans_before = plan_sig(storage4)
            storage4.acquire_stream_ids(
                "tb", lids4, keys4 + pop * (n_tenants * 8),
                batch=B, subbatches=K)
            pop += 1
            if plan_sig(storage4) == plans_before and plans_settled(storage4):
                break
    storage4.stream_stats = churn_stats = []
    with _compiles() as cc:
        t0 = time.perf_counter()
        allowed_churn = storage4.acquire_stream_ids("tb", lids4, keys4,
                                                    batch=B, subbatches=K)
        churn_wall = time.perf_counter() - t0
    storage4.stream_stats = None
    detail["multi_tenant_100k_churn"] = {
        "mode": "stream_ids_multi_first_touch", "decisions": n4,
        "wall_s": round(churn_wall, 4),
        "decisions_per_sec": round(n4 / churn_wall, 1),
        "tenants": n_tenants, "allowed": int(allowed_churn.sum()),
        "n_compiles": cc.n, "compile_s": cc.secs,
        "warmup": {"n_compiles": cw.n, "compile_s": cw.secs},
        "phase": _agg_stats(churn_stats),
    }
    log(f"  churn (first touch): {n4 / churn_wall:,.0f} decisions/s")
    # run_stream's own untimed warmup doubles as the first steady pass:
    # the zero-delta resident-lid dispatch is a NEW compile shape after a
    # churn pass (delta lanes shrink to the floor bucket), and it must
    # settle before the timed steady passes.
    res = run_stream(
        lambda ids, p: storage4.acquire_stream_ids("tb", lids4, ids,
                                                   batch=B, subbatches=K),
        keys4, None, 2 if small else 3, storage4)
    res["mode"] = "stream_ids_multi_steady"
    res["tenants"] = n_tenants
    detail["multi_tenant_100k_stream"] = res
    log(f"  steady state: {res['decisions_per_sec']:,.0f} decisions/s")
    storage4.close()

    # -- scenario 5: burst batch-acquire over 1M keys (streaming) ------------
    num_keys5 = 20_000 if small else 1_000_000
    n5 = super_n * (2 if small else 3)
    log(f"scenario 5: burst batch-acquire over {num_keys5} keys...")
    storage5 = TpuBatchedStorage(num_slots=align_slots(
        max(num_keys5 * 2, 1 << 16)))
    set_link(storage5, 'tb_burst_batch_stream')
    tb5 = TokenBucketRateLimiter(
        storage5,
        RateLimitConfig(max_permits=100, window_ms=60_000, refill_rate=100.0),
        MeterRegistry())
    key5 = uniform_stream(rng, num_keys5, n5)
    perms5 = rng.integers(1, 101, size=n5).astype(np.int64)
    res = run_stream(
        lambda ids, p: tb5.try_acquire_stream_ids(ids, p, batch=B,
                                                  subbatches=K),
        key5, perms5, 2, storage5)
    detail["tb_burst_batch_stream"] = res
    log(f"  stream: {res['decisions_per_sec']:,.0f} decisions/s")
    storage5.close()

    # -- Pallas A/B (subprocess pair): what the kernels buy on this link -----
    # The solver serves micro-batcher-sized dispatches (<= 16K lanes); the
    # A/B drives that path with the flag on/off.  RATELIMITER_PALLAS is
    # read at import, hence subprocesses.
    if platform == "tpu" and not small:
        log("pallas A/B (micro-batch path, subprocess pair)...")
        ab = {}
        for flag in ("1", "0"):
            try:
                env = dict(os.environ, RATELIMITER_PALLAS=flag,
                           RATELIMITER_BLOCK_SCATTER=flag,
                           RATELIMITER_RELAY_FUSED=flag)
                proc = subprocess.run(
                    [sys.executable, os.path.join(_REPO, "bench",
                                                  "pallas_ab.py")],
                    capture_output=True, timeout=600, text=True, cwd=_REPO,
                    env=env)
                if proc.returncode != 0 or not proc.stdout.strip():
                    raise RuntimeError(
                        f"rc={proc.returncode} stderr={proc.stderr[-400:]!r}")
                ab["pallas_on" if flag == "1" else "pallas_off"] = (
                    json.loads(proc.stdout.strip().splitlines()[-1]))
            except Exception as exc:  # noqa: BLE001
                ab["pallas_on" if flag == "1" else "pallas_off"] = {
                    "error": str(exc)}
        detail["pallas_ab"] = ab
        on = ab.get("pallas_on", {}).get("decisions_per_sec")
        off = ab.get("pallas_off", {}).get("decisions_per_sec")
        if on and off:
            log(f"  pallas on: {on:,.0f}/s, off: {off:,.0f}/s "
                f"(x{on / off:.2f})")

    # -- device-only chained-step measurement + on-device Pallas A/B --------
    # K decision steps inside one jit over donated state, one fetched
    # checksum (VERDICT r3 #4): measures the device step itself with no
    # per-step wire, and settles the Pallas kernels' value on-device
    # (subprocess pair — the kernels bind at import).
    if platform == "tpu" and not small:
        log("device-only chained steps (subprocess pair)...")
        dev = {}
        for flag in ("1", "0"):
            try:
                env = dict(os.environ, RATELIMITER_PALLAS=flag,
                           RATELIMITER_BLOCK_SCATTER=flag,
                           RATELIMITER_RELAY_FUSED=flag)
                proc = subprocess.run(
                    [sys.executable, os.path.join(_REPO, "bench",
                                                  "device_only.py")],
                    capture_output=True, timeout=900, text=True, cwd=_REPO,
                    env=env)
                if proc.returncode != 0 or not proc.stdout.strip():
                    raise RuntimeError(
                        f"rc={proc.returncode} stderr={proc.stderr[-400:]!r}")
                dev["pallas_on" if flag == "1" else "pallas_off"] = (
                    json.loads(proc.stdout.strip().splitlines()[-1]))
            except Exception as exc:  # noqa: BLE001
                dev["pallas_on" if flag == "1" else "pallas_off"] = {
                    "error": str(exc)}
        detail["device_only"] = dev
        on = dev.get("pallas_on", {})
        off = dev.get("pallas_off", {})
        if "relay" in on:
            log(f"  relay step: {on['relay']['decisions_per_sec']:,.0f} "
                f"lanes/s ({on['relay']['ns_per_decision']} ns)")
        if "flat_weighted" in on and "flat_weighted" in off:
            fon = on["flat_weighted"]["decisions_per_sec"]
            foff = off["flat_weighted"]["decisions_per_sec"]
            log(f"  flat weighted: pallas on {fon:,.0f}/s, "
                f"off {foff:,.0f}/s (x{fon / foff:.2f})")

    # -- sharded scaling (virtual CPU mesh, subprocess) ----------------------
    # The multi-chip sharding machinery measured 1 -> 8 shards; a separate
    # process because the CPU backend must be selected before any device
    # work (this process owns the TPU).
    log("sharded scaling (8-device virtual CPU mesh, subprocess)...")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO, "bench",
                                          "sharded_scaling.py")],
            capture_output=True, timeout=600, text=True, cwd=_REPO)
        if proc.returncode != 0 or not proc.stdout.strip():
            raise RuntimeError(
                f"rc={proc.returncode} stderr={proc.stderr[-500:]!r}")
        detail["sharded_scaling"] = json.loads(
            proc.stdout.strip().splitlines()[-1])
        for p in detail["sharded_scaling"]["points"]:
            s = p.get("str_end_to_end")
            extra = (f"; strs {s['decisions_per_sec']:,.0f}/s"
                     if s else "")
            log(f"  {p['n_shards']} shard(s): "
                f"{p['decisions_per_sec']:,.0f} decisions/s{extra}")
    except Exception as exc:  # noqa: BLE001 — aux section must not kill bench
        detail["sharded_scaling"] = {"error": str(exc)}
        log(f"  sharded scaling failed: {exc}")

    # Elections resolved lazily during the run (device_rates probes,
    # engine dispatches) land in the final record too.
    detail["pallas"]["elections"] = election_report()
    detail["total_bench_seconds"] = time.time() - t_start

    # Link-dependence record (VERDICT r4 #8): every stream scenario's
    # median throughput alongside the link it ran on, so the headline's
    # swing across rounds is attributable to the tunnel, not guessed.
    # The link of record is the run's probe (plus any mid-scenario
    # re-probe stored by run_stream as "relink").
    if detail_link:
        curve = []
        for scen in ("tb_1m_zipf_stream_ids", "tb_1m_zipf_end_to_end_strs",
                     "sw_10m_uniform_stream", "multi_tenant_100k_stream",
                     "tb_burst_batch_stream"):
            res = detail.get(scen)
            if not isinstance(res, dict) or "error" in res:
                continue
            med = res.get("median_pass_decisions_per_sec",
                          res.get("decisions_per_sec"))
            # The string scenario runs on the headline's storage (and
            # its elected plans): its link of record is that probe, not
            # the boot probe.
            probe_key = ("tb_1m_zipf_stream_ids"
                         if scen == "tb_1m_zipf_end_to_end_strs" else scen)
            probe = scenario_links.get(probe_key, detail_link)
            curve.append({
                "scenario": scen,
                "upload_mbps": probe["upload_4mb_mbps"],
                "download_mbps": probe["download_4mb_mbps"],
                "rtt_ms": probe["round_trip_ms"],
                "relink": res.get("relink"),
                "median_dps": round(float(med), 1),
            })
        detail["link_curve"] = curve

    with open(os.path.join(_REPO, "BENCH_DETAIL.json"), "w") as fh:
        json.dump(detail, fh, indent=2)

    baseline = 80_192.0  # reference README throughput (BASELINE.md)
    # Honest labeling: the headline is the MEDIAN timed pass of the
    # int-key stream (robust to single tunnel stalls; aggregate + every
    # pass recorded in BENCH_DETAIL); the string-key end-to-end number
    # lives under tb_1m_zipf_end_to_end_strs.
    print(json.dumps({
        "metric": "tb_1m_keys_zipf_stream_decisions_per_sec_median_pass",
        "value": round(float(headline), 1),
        "unit": "decisions/s",
        "vs_baseline": round(float(headline) / baseline, 2),
    }))


if __name__ == "__main__":
    main()
