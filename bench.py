"""Driver benchmark: prints ONE JSON line with the headline metric.

Headline: end-to-end rate-limit decisions/sec on a 1M-key token-bucket
Zipf(1.1) stream (BASELINE.json config #2) — string keys in, allow/deny out,
through the slot index + batched device engine on one chip.
vs_baseline compares against the reference's published 80,192 req/s
(README single-key sliding-window, local cache on, M1 + Redis —
BASELINE.md).

Detailed results for all scenarios land in BENCH_DETAIL.json:
  1. single-key sliding window, 10 threads, through the micro-batcher
     (latency percentiles — the reference's headline scenario)
  2. 1M-key token bucket, Zipf(1.1)      [headline]
  3. 10M-key sliding window, uniform     (engine-level; 10M host index
     warmup is excluded by design)
  4. 100K-tenant multi-config mix
  5. burst batch-acquire tryAcquire(key, n in [1,100]) over 1M keys

Scale knobs: BENCH_SCALE=small|full (default full on TPU, small elsewhere).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    import jax

    platform = jax.devices()[0].platform
    scale = os.environ.get("BENCH_SCALE") or ("full" if platform == "tpu" else "small")
    small = scale == "small"
    log(f"bench: platform={platform} scale={scale}")

    from ratelimiter_tpu import RateLimitConfig
    from ratelimiter_tpu.algorithms import (
        SlidingWindowRateLimiter,
        TokenBucketRateLimiter,
    )
    from ratelimiter_tpu.bench.harness import (
        bench_end_to_end,
        bench_engine,
        bench_threaded,
        make_engine,
        uniform_stream,
        zipf_stream,
    )
    from ratelimiter_tpu.engine.state import LimiterTable
    from ratelimiter_tpu.metrics import MeterRegistry
    from ratelimiter_tpu.storage import TpuBatchedStorage

    from ratelimiter_tpu.utils.tracing import device_profile

    profile_dir = os.environ.get("BENCH_PROFILE")
    rng = np.random.default_rng(42)
    detail = {"platform": platform, "scale": scale}
    t_start = time.time()

    # -- scenario 2 (headline): 1M-key token bucket, Zipf(1.1) ---------------
    num_keys = 20_000 if small else 1_000_000
    n_requests = 200_000 if small else 4_000_000
    batch = 4096 if small else 65_536
    log(f"scenario 2: TB Zipf over {num_keys} keys, {n_requests} requests...")

    tb_cfg = RateLimitConfig(max_permits=100, window_ms=60_000, refill_rate=50.0)
    storage = TpuBatchedStorage(num_slots=max(num_keys * 2, 1 << 16))
    tb_limiter = TokenBucketRateLimiter(storage, tb_cfg, MeterRegistry())
    lid_tb = tb_limiter._lid

    key_ids = zipf_stream(rng, num_keys, n_requests)
    permits = np.ones(n_requests, dtype=np.int64)

    # Headline: integer-key end-to-end (slot index + device dispatch) —
    # the hyperscale interface (services pass integer user/tenant ids).
    # Warm with the exact batch size: padding buckets are per-shape, a
    # different size would leave the timed loop to compile.
    for w in range(2):
        tb_limiter.try_acquire_ids(key_ids[w * batch:(w + 1) * batch],
                                   permits[w * batch:(w + 1) * batch])
    t0 = time.perf_counter()
    with device_profile(profile_dir):
        for i in range(0, (n_requests // batch) * batch, batch):
            tb_limiter.try_acquire_ids(key_ids[i:i + batch], permits[i:i + batch])
    wall = time.perf_counter() - t0
    headline = ((n_requests // batch) * batch) / wall
    detail["tb_1m_zipf_end_to_end_ids"] = {
        "mode": "end_to_end_ids", "decisions": (n_requests // batch) * batch,
        "wall_s": wall, "decisions_per_sec": headline, "batch": batch,
    }
    log(f"  end-to-end (int keys): {headline:,.0f} decisions/s")

    # String-key end-to-end (Python key handling included).
    n_str = min(n_requests, 1_000_000)
    keys = [f"k{i}" for i in key_ids[:n_str]]
    res = bench_end_to_end(tb_limiter, keys, permits[:n_str], batch)
    detail["tb_1m_zipf_end_to_end_strs"] = res
    log(f"  end-to-end (str keys): {res['decisions_per_sec']:,.0f} decisions/s")

    # Engine-level on the same stream (device decision throughput).
    slot_stream = (key_ids % storage.engine.num_slots).astype(np.int64)
    res = bench_engine(storage.engine, "tb", lid_tb, slot_stream, permits, batch)
    detail["tb_1m_zipf_engine"] = res
    log(f"  engine:                {res['decisions_per_sec']:,.0f} decisions/s")
    storage.close()

    # -- scenario 1: single-key SW, 10 threads through the batcher -----------
    log("scenario 1: single-key sliding window, 10 threads...")
    sw_cfg = RateLimitConfig(max_permits=100, window_ms=60_000,
                             enable_local_cache=True, local_cache_ttl_ms=100)
    storage = TpuBatchedStorage(num_slots=1 << 12, max_delay_ms=0.3)
    sw_limiter = SlidingWindowRateLimiter(storage, sw_cfg, MeterRegistry())
    res = bench_threaded(
        sw_limiter,
        keys_per_thread=lambda t: ["hot-key"],
        n_threads=10,
        requests_per_thread=200 if small else 2000,
    )
    detail["sw_single_key_threaded"] = res
    log(f"  {res['decisions_per_sec']:,.0f} req/s; "
        f"p99 {res['request_latency']['p99_us']:.0f} us")
    storage.close()

    # -- scenario 3: 10M-key sliding window, uniform (engine-level) ----------
    num_keys3 = 50_000 if small else 10_000_000
    n3 = 200_000 if small else 4_000_000
    log(f"scenario 3: SW uniform over {num_keys3} slots (engine)...")
    engine, (lid_sw,) = make_engine(
        num_slots=num_keys3,
        configs=[RateLimitConfig(max_permits=100, window_ms=60_000,
                                 enable_local_cache=False)])
    slots3 = uniform_stream(rng, num_keys3, n3)
    res = bench_engine(engine, "sw", lid_sw, slots3, np.ones(n3, dtype=np.int64), batch)
    detail["sw_10m_uniform_engine"] = res
    log(f"  engine: {res['decisions_per_sec']:,.0f} decisions/s")

    # -- scenario 4: 100K-tenant multi-config mix (engine-level) -------------
    n_tenants = 1000 if small else 100_000
    n4 = 200_000 if small else 2_000_000
    log(f"scenario 4: {n_tenants}-tenant mix...")
    table = LimiterTable(capacity=n_tenants + 2)
    lids = np.asarray(
        [table.register(RateLimitConfig(
            max_permits=50 + (i % 100), window_ms=60_000,
            refill_rate=float(5 + i % 20)))
         for i in range(n_tenants)], dtype=np.int32)
    from ratelimiter_tpu.engine.engine import DeviceEngine

    engine4 = DeviceEngine(num_slots=max(n_tenants * 8, 1 << 16), table=table)
    tenant_of_req = rng.integers(0, n_tenants, size=n4)
    slots4 = (tenant_of_req * 8 + rng.integers(0, 8, size=n4)).astype(np.int64)
    # Mixed-tenant TB batches: every request carries its own tenant's policy.
    fn_lids = lids[tenant_of_req]
    n4b = (n4 // batch) * batch
    # Warm the jit cache (compile excluded from timing).
    engine4.tb_acquire(slots4[:batch], fn_lids[:batch],
                       np.ones(batch, dtype=np.int64), 1_752_999_999_000)
    engine4.block_until_ready()
    t0_all = time.perf_counter()
    for i in range(0, n4b, batch):
        engine4.tb_acquire(slots4[i:i + batch], fn_lids[i:i + batch],
                           np.ones(batch, dtype=np.int64), 1_753_000_000_000 + i)
    wall = time.perf_counter() - t0_all
    detail["multi_tenant_100k_engine"] = {
        "mode": "engine", "decisions": n4b, "wall_s": wall,
        "decisions_per_sec": n4b / wall, "tenants": n_tenants,
    }
    log(f"  engine: {n4b / wall:,.0f} decisions/s")

    # -- scenario 5: burst batch-acquire over 1M keys ------------------------
    num_keys5 = 20_000 if small else 1_000_000
    n5 = 200_000 if small else 2_000_000
    log(f"scenario 5: burst batch-acquire over {num_keys5} keys...")
    engine5, (lid5,) = make_engine(
        num_slots=num_keys5,
        configs=[RateLimitConfig(max_permits=100, window_ms=60_000,
                                 refill_rate=100.0)])
    slots5 = uniform_stream(rng, num_keys5, n5)
    perms5 = rng.integers(1, 101, size=n5).astype(np.int64)
    res = bench_engine(engine5, "tb", lid5, slots5, perms5, batch)
    detail["tb_burst_batch_engine"] = res
    log(f"  engine: {res['decisions_per_sec']:,.0f} decisions/s")

    detail["total_bench_seconds"] = time.time() - t_start

    with open(os.path.join(os.path.dirname(__file__) or ".", "BENCH_DETAIL.json"), "w") as fh:
        json.dump(detail, fh, indent=2)

    baseline = 80_192.0  # reference README throughput (BASELINE.md)
    print(json.dumps({
        "metric": "tb_1m_keys_zipf_end_to_end_decisions_per_sec",
        "value": round(float(headline), 1),
        "unit": "decisions/s",
        "vs_baseline": round(float(headline) / baseline, 2),
    }))


if __name__ == "__main__":
    main()
